"""Fleet-serving certification (docs/DESIGN.md §23).

Three layers, cheapest first:

1. **Keying parity** — the router's pageless ``PrefixIndex`` and the
   real ``RadixPrefixCache`` share the walk code, and the parity test
   pins predicted match == actual match across randomized prompt
   families (shared prefixes, partial tails, interleaved inserts), so
   the router's warm predictions CANNOT drift from the cache they
   predict.
2. **Router semantics** — in-process stub transports pin the routing
   policy itself: session pinning, warm-prefix affinity, load
   fallback, round-robin, clean ``WorkerCrashedError`` + cold
   re-route on replica death, state-file restart recovery, rid
   propagation, ``zk_fleet_*`` / ``/statusz`` / ``/healthz``
   exposition, and the FaultPlan chaos knobs.
3. **The real thing** (``slow``) — a router over REAL worker
   processes (each a paged-KV ``LMServingConfig`` behind HTTP):
   fleet output certified token-identical to an in-process
   single-replica oracle, turn-2 warm prefill proved by the worker's
   own ``shared_tokens``, one rid traced router → worker, and the
   replica-kill chaos leg (mid-request SIGKILL → clean failure →
   survivor finishes the session cold, still token-identical).
"""

import json
import os
import urllib.request

import numpy as np
import pytest

from zookeeper_tpu.observability import trace
from zookeeper_tpu.resilience import FaultPlan, faults
from zookeeper_tpu.serving import (
    FleetRouter,
    FleetUnavailableError,
    ReplicaHandle,
    WorkerCrashedError,
)
from zookeeper_tpu.serving.decode.pages import RadixPrefixCache
from zookeeper_tpu.serving.decode.prefix_key import (
    PrefixIndex,
    common_prefix,
)

pytestmark = pytest.mark.serving


# -- layer 1: keying parity -------------------------------------------------


def make_cache(page_size):
    """A real RadixPrefixCache with inert page plumbing (parity tests
    exercise the WALKS, not the pool)."""
    return RadixPrefixCache(
        page_size, ref=lambda p: None, unref=lambda p: None,
        evictable=lambda p: True,
    )


def pages_for(n, ps):
    return (n + ps - 1) // ps


def test_common_prefix():
    assert common_prefix([1, 2, 3], [1, 2, 4]) == 2
    assert common_prefix([], [1]) == 0
    assert common_prefix([1, 2], [1, 2]) == 2


@pytest.mark.parametrize("page_size", [1, 3, 4])
def test_prefix_index_matches_radix_cache_exactly(page_size):
    """THE parity certification: after any interleaved sequence of
    inserts, the index's predicted match length equals the real
    cache's actual match length for every probe — full-chunk hits,
    partial tails, misses, and prompts diverging mid-chunk."""
    rng = np.random.default_rng(7)
    cache = make_cache(page_size)
    index = PrefixIndex(page_size)
    bases = [rng.integers(0, 13, size=n).tolist() for n in (24, 17, 9)]
    inserted = []
    next_page = [0]

    def insert_both(tokens):
        n_pages = pages_for(len(tokens), page_size)
        pages = list(range(next_page[0], next_page[0] + n_pages))
        next_page[0] += n_pages
        cache.insert(tokens, pages)
        index.observe(tokens)
        inserted.append(tokens)

    def probe(tokens):
        t_cache, _ = cache.lookup(tokens)
        assert index.match(tokens) == t_cache, (
            f"parity broke: index predicted {index.match(tokens)}, "
            f"cache matched {t_cache} for {tokens}"
        )

    for base in bases:
        # Grow the same conversation: each turn extends the last.
        for cut in (len(base) // 2, len(base)):
            insert_both(base[:cut])
        # Diverge mid-chunk off the shared prefix.
        insert_both(base[: len(base) // 2] + [50, 51, 52])
    probes = list(inserted)
    for base in bases:
        probes.append(base + [7, 8, 9])           # past the cached end
        probes.append(base[: max(1, len(base) - 2)])  # shorter
        probes.append([60] + base)                # cold miss
        probes.append(base[: page_size + 1])      # partial-tail probe
    for p in probes:
        probe(p)
    # And random probes for good measure.
    for _ in range(50):
        probe(rng.integers(0, 14, size=int(rng.integers(1, 30))).tolist())


def test_prefix_index_predict_caps_like_assign_prompt():
    """``predict`` mirrors ``PagePool.assign_prompt``: the final
    prompt token is never served warm (its logits must be computed),
    so a fully-cached prompt predicts len - 1 shared tokens."""
    idx = PrefixIndex(4)
    p = list(range(12))
    idx.observe(p)
    assert idx.match(p) == 12
    assert idx.predict(p) == 11
    assert idx.predict([]) == 0
    assert idx.predict([99]) == 0


def test_prefix_index_caps_nodes_and_resets():
    idx = PrefixIndex(2, max_nodes=4)
    idx.observe([1, 2, 3, 4])  # 2 nodes
    assert idx.nodes == 2 and idx.resets == 0
    idx.observe([5, 6, 7, 8, 9, 10])  # 3 more -> over cap -> reset
    assert idx.resets == 1
    assert idx.nodes == 0
    assert idx.match([1, 2, 3, 4]) == 0  # cold after reset


def test_prefix_index_rejects_bad_config():
    with pytest.raises(ValueError, match="page_size"):
        PrefixIndex(0)
    with pytest.raises(ValueError, match="max_nodes"):
        PrefixIndex(4, max_nodes=0)


# -- layer 2: router semantics over stub transports -------------------------


class StubFleet:
    """In-process stand-in for N workers: echoes tokens + [7], records
    every payload, and fails like a dead socket when killed."""

    def __init__(self, n):
        self.calls = []
        self.dead = set()
        self.replicas = [
            ReplicaHandle(f"w{i}", f"stub://w{i}/generate")
            for i in range(n)
        ]

    def transport(self, replica, payload, timeout_s):
        if replica.worker_id in self.dead:
            raise ConnectionError(f"{replica.worker_id} is dead")
        self.calls.append((replica.worker_id, payload))
        return {
            "rid": payload["rid"],
            "worker_id": replica.worker_id,
            "tokens": list(payload["tokens"]) + [7],
            "ttft_ms": 1.0,
            "shared_tokens": 0,
            "finish_reason": "length",
        }

    def health(self, replica, timeout_s):
        return replica.worker_id not in self.dead

    def kill(self, replica):
        self.dead.add(replica.worker_id)


def make_router(n=2, **kw):
    stub = StubFleet(n)
    router = FleetRouter(
        stub.replicas,
        page_size=4,
        transport=stub.transport,
        health_probe=stub.health,
        kill_replica=stub.kill,
        **kw,
    )
    return router, stub


def test_router_rejects_bad_config():
    stub = StubFleet(1)
    with pytest.raises(ValueError, match="policy"):
        FleetRouter(stub.replicas, page_size=4, policy="random")
    with pytest.raises(ValueError, match="at least one"):
        FleetRouter([], page_size=4)
    with pytest.raises(ValueError, match="duplicate"):
        FleetRouter(
            [ReplicaHandle("w0", "u"), ReplicaHandle("w0", "u")],
            page_size=4,
        )


def test_session_pins_and_turn2_is_affinity_hit():
    router, stub = make_router(2)
    p1 = list(range(16))
    r1 = router.submit(p1, session="s1")
    assert not r1.affinity_hit  # cold first turn routes by load
    assert router.session_pin("s1") == r1.worker_id
    # Turn 2 (history grew) rides the pin — the warm replica.
    r2 = router.submit(p1 + [40, 41], session="s1")
    assert r2.worker_id == r1.worker_id
    assert r2.affinity_hit
    assert r2.predicted_shared == 16  # the whole cached turn-1 prompt
    np.testing.assert_array_equal(r2.tokens, p1 + [40, 41, 7])


def test_unpinned_warm_prompt_routes_by_prefix_affinity():
    router, stub = make_router(2)
    base = list(range(16))
    first = router.submit(base)
    warm = router.submit(base[:8] + [55])  # shares 2 full chunks
    assert warm.worker_id == first.worker_id
    assert warm.affinity_hit
    assert warm.predicted_shared == 8


def test_cold_prompts_fall_back_by_load():
    router, stub = make_router(2)
    router.replicas[0].outstanding = 3  # w0 busy
    cold = router.submit([90, 91, 92])
    assert cold.worker_id == "w1"
    assert not cold.affinity_hit


def test_round_robin_policy_rotates():
    router, stub = make_router(2, policy="round_robin")
    seen = [router.submit([i, i + 1, i + 2]).worker_id for i in range(4)]
    assert seen == ["w0", "w1", "w0", "w1"]


def test_dead_replica_fails_clean_then_session_reroutes_cold():
    router, stub = make_router(2)
    p1 = list(range(16))
    r1 = router.submit(p1, session="s1")
    stub.kill(router._by_id[r1.worker_id])
    # In-flight against a dead worker: clean typed failure, replica
    # marked unhealthy, crash counted, rid in the router's RequestLog.
    with pytest.raises(WorkerCrashedError, match=r1.worker_id):
        router.submit(p1 + [40], session="s1", rid=4242)
    assert not router._by_id[r1.worker_id].healthy
    rec = router.request_log.find(4242)
    assert rec is not None and rec["outcome"] == "crashed"
    assert "WorkerCrashedError" in rec["detail"]
    # The resubmit re-routes COLD to the survivor and re-pins there.
    survivor = [r for r in router.replicas if r.healthy][0]
    r3 = router.submit(p1 + [40], session="s1")
    assert r3.worker_id == survivor.worker_id
    assert r3.rerouted
    assert router.session_pin("s1") == survivor.worker_id
    snap = router.metrics.snapshot()
    assert snap["fleet_worker_crashes_total"] == 1.0
    assert snap["fleet_rerouted_total"] == 1.0


def test_all_replicas_dead_raises_fleet_unavailable():
    router, stub = make_router(2)
    for r in router.replicas:
        stub.kill(r)
    router.check_health()
    with pytest.raises(FleetUnavailableError, match="no healthy"):
        router.submit([1, 2, 3])


def test_health_probe_marks_dead_and_cold_revival():
    router, stub = make_router(2)
    base = list(range(8))
    first = router.submit(base)
    warm_replica = router._by_id[first.worker_id]
    assert warm_replica.index.nodes > 0
    stub.kill(warm_replica)
    assert router.check_health() == {
        first.worker_id: False,
        ({"w0", "w1"} - {first.worker_id}).pop(): True,
    }
    assert not warm_replica.healthy
    assert warm_replica.index.nodes == 0  # its pages died with it
    # Revival (worker restarted): healthy again but COLD.
    stub.dead.clear()
    router.check_health()
    assert warm_replica.healthy
    assert warm_replica.index.nodes == 0


def test_state_path_restores_session_pins(tmp_path):
    state = str(tmp_path / "fleet_state.json")
    router, stub = make_router(2, state_path=state)
    r1 = router.submit(list(range(12)), session="s1")
    router.submit(list(range(6)), session="other")
    # A restarted router (same replicas, same state file) keeps the
    # pins — turn-2 of every session still lands on its warm replica.
    router2 = FleetRouter(
        stub.replicas,
        page_size=4,
        state_path=state,
        transport=stub.transport,
        health_probe=stub.health,
    )
    assert router2.session_pin("s1") == r1.worker_id
    r2 = router2.submit(list(range(12)) + [40], session="s1")
    assert r2.worker_id == r1.worker_id and r2.affinity_hit
    # Pins for replicas that no longer exist are dropped, not adopted.
    with open(state, "w") as f:
        json.dump({"sessions": {"ghost": "w9", "s1": r1.worker_id}}, f)
    router3 = FleetRouter(
        stub.replicas, page_size=4, state_path=state,
        transport=stub.transport,
    )
    assert router3.session_pin("ghost") is None
    assert router3.session_pin("s1") == r1.worker_id


def test_rid_propagates_and_router_logs_ok():
    router, stub = make_router(1)
    resp = router.submit([1, 2, 3], rid=991)
    assert resp.rid == 991
    assert stub.calls[-1][1]["rid"] == 991  # the worker ADOPTS it
    rec = router.request_log.find(991)
    assert rec is not None and rec["outcome"] == "ok"
    assert rec["role"] == "router"
    assert "replica=w0" in rec["detail"]


def test_fleet_route_emits_flow_traceable_event():
    prior = trace._TRACER
    trace.install(trace.Tracer(1024))
    try:
        router, stub = make_router(1)
        router.submit([1, 2, 3, 4], rid=5005)
        doc = trace.to_chrome_trace()
        routes = [
            e for e in doc["traceEvents"]
            if e.get("name") == "fleet_route"
        ]
        assert routes, "no fleet_route event in the trace"
        assert routes[0]["args"]["rid"] == 5005
        assert routes[0]["args"]["replica"] == "w0"
    finally:
        trace.install(prior)


def test_worker_error_body_raises_with_type():
    router, stub = make_router(1)

    def bad_transport(replica, payload, timeout_s):
        return {"error": "prompt too long", "type": "ValueError"}

    router._transport = bad_transport
    with pytest.raises(RuntimeError, match="ValueError: prompt too long"):
        router.submit([1, 2, 3], rid=17)
    rec = router.request_log.find(17)
    assert rec["outcome"] == "error"


def test_router_observability_endpoint(tmp_path):
    router, stub = make_router(2)
    router.submit(list(range(8)), session="s1")
    server = router.start_observability(port=0)
    try:
        base = f"http://127.0.0.1:{server.port}"
        with urllib.request.urlopen(base + "/healthz", timeout=5) as r:
            assert r.read() == b"ok\n"
        with urllib.request.urlopen(base + "/metrics", timeout=5) as r:
            body = r.read().decode()
        for series in (
            "zk_fleet_routed_total",
            "zk_fleet_rerouted_total",
            "zk_fleet_worker_crashes_total",
            "zk_fleet_replica_healthy",
            "zk_fleet_replicas",
            "zk_fleet_sessions",
            "zk_fleet_route_ms",
        ):
            assert series in body, f"missing {series} in /metrics"
        assert 'replica="w0"' in body
        with urllib.request.urlopen(base + "/statusz", timeout=5) as r:
            doc = json.loads(r.read().decode())
        fleet = doc["fleet"]
        assert fleet["policy"] == "affinity"
        assert fleet["sessions"] == 1
        assert {x["worker_id"] for x in fleet["replicas"]} == {"w0", "w1"}
        assert doc["requests"]["service"] == "fleet"
    finally:
        router.close()
    assert router.obs_server is None


# -- FaultPlan chaos knobs --------------------------------------------------


@pytest.mark.chaos
def test_fault_plan_fleet_replica_kill_fires_once_at_nth_route():
    router, stub = make_router(2)
    with faults.injected(FaultPlan(fleet_replica_kill_at=2)):
        first = router.submit(list(range(8)), session="s1")  # 1st: safe
        with pytest.raises(WorkerCrashedError):
            router.submit(list(range(8)) + [40], session="s1")  # 2nd: kill
        # One-shot: the next submit routes to the survivor and works.
        r3 = router.submit(list(range(8)) + [40], session="s1")
    assert r3.worker_id != first.worker_id
    assert r3.rerouted
    assert stub.dead == {first.worker_id}


@pytest.mark.chaos
def test_fault_plan_router_restart_knob_is_one_shot():
    plan = FaultPlan(fleet_router_restart_at=2)
    with faults.injected(plan):
        assert not plan.take_fleet_router_restart()
        assert plan.take_fleet_router_restart()  # fires at the 2nd
        assert not plan.take_fleet_router_restart()  # one-shot


# -- layer 3: real multi-process certification (slow) -----------------------

# Tiny but REAL geometry shared by the workers (spawned processes) and
# the in-process oracle: same seed => identical fresh-init weights =>
# greedy decode is token-identical wherever a request lands.
FLEET_CONF = {
    "model.num_layers": 1,
    "model.d_model": 32,
    "model.num_heads": 4,
    "model.max_seq_len": 64,
    "model.attention": "dense",
    "seq_len": 64,
    "vocab_size": 61,
    "seed": 0,
    "engine.kv_layout": "paged",
    "engine.page_size": 8,
    "engine.slots": 2,
    "engine.seq_buckets": (16, 64),
    "engine.prefill_buckets": (1,),
    "requests": 0,
    "verbose": False,
}

NEW_TOKENS = 6


def fleet_prompts():
    """Deterministic 2-session, 2-turn conversation set: turn 2
    extends turn 1's prompt (the history-grows shape whose warm
    prefill the router's affinity protects)."""
    rng = np.random.default_rng(3)
    sessions = {}
    for sid in ("sA", "sB"):
        t1 = rng.integers(1, 60, size=20).tolist()
        t2 = t1 + rng.integers(1, 60, size=9).tolist()
        sessions[sid] = [t1, t2]
    return sessions


def oracle_outputs(sessions):
    """Single-replica in-process oracle: the same prompts through one
    paged-KV service (certified against the greedy oracle by
    test_paged_kv) — what every fleet routing MUST reproduce."""
    from zookeeper_tpu.core import configure
    from zookeeper_tpu.serving import LMServingConfig

    svc = LMServingConfig()
    conf = dict(FLEET_CONF)
    conf["metrics_port"] = -1
    configure(svc, conf, name="fleet_oracle")
    _, scheduler = svc.build_service()
    try:
        out = {}
        for sid, turns in sessions.items():
            out[sid] = [
                scheduler.submit(
                    np.asarray(t, np.int32), max_new_tokens=NEW_TOKENS
                ).result(timeout=300.0).tolist()
                for t in turns
            ]
        return out
    finally:
        svc._teardown_service(suppress=True)


def spawn_fleet(tmp_path, n=2):
    from zookeeper_tpu.testing import spawn_fleet_workers

    return spawn_fleet_workers(str(tmp_path), num_workers=n,
                               config=FLEET_CONF)


@pytest.mark.slow
def test_fleet_token_identity_warm_turn2_and_rid_trace(tmp_path):
    """The §23 headline over REAL processes: (1) every fleet output is
    token-identical to the single-replica oracle; (2) turn 2 of every
    session lands on its pinned replica and the WORKER reports warm
    shared prompt tokens (the radix cache actually hit — TTFT rides
    the §20 warm path); (3) one router-minted rid is traceable in the
    router's RequestLog AND the worker's own /statusz request tail."""
    from zookeeper_tpu.testing import stop_fleet_workers

    sessions = fleet_prompts()
    want = oracle_outputs(sessions)
    workers = spawn_fleet(tmp_path)
    router = None
    try:
        router = FleetRouter(
            [ReplicaHandle.from_worker(w) for w in workers],
            page_size=FLEET_CONF["engine.page_size"],
        )
        got = {sid: [] for sid in sessions}
        turn2 = {}
        traced_rid = 314159
        for turn in range(2):
            for sid, turns in sessions.items():
                rid = (
                    traced_rid
                    if (turn, sid) == (0, "sA")
                    else None
                )
                resp = router.submit(
                    turns[turn], session=sid,
                    max_new_tokens=NEW_TOKENS, rid=rid,
                )
                got[sid].append(resp.tokens.tolist())
                if turn == 1:
                    turn2[sid] = resp
        assert got == want, "fleet output diverged from the oracle"
        for sid, resp in turn2.items():
            assert resp.worker_id == router.session_pin(sid)
            assert resp.affinity_hit
            # The WORKER's cache served turn-1's prompt warm: the
            # prediction was real, not just a routing bias.
            assert resp.shared_tokens >= len(sessions[sid][0]) - 1
            assert resp.predicted_shared <= resp.shared_tokens + \
                FLEET_CONF["engine.page_size"]
        # rid end-to-end: router log ...
        rec = router.request_log.find(traced_rid)
        assert rec is not None and rec["outcome"] == "ok"
        # ... and the worker the request landed on logged the SAME rid.
        first_a = router.request_log.find(traced_rid)["detail"]
        wid = first_a.split("replica=")[1].split()[0]
        w = next(x for x in workers if x["worker_id"] == wid)
        with urllib.request.urlopen(
            "http://127.0.0.1:%d/statusz" % w["metrics_port"], timeout=10
        ) as r:
            doc = json.loads(r.read().decode())
        worker_rids = [
            e["rid"] for e in doc["requests"]["tail"]
        ]
        assert traced_rid in worker_rids
    finally:
        if router is not None:
            router.close()
        stop_fleet_workers(workers)


@pytest.mark.slow
@pytest.mark.chaos
def test_fleet_replica_kill_reroutes_and_router_restart_recovers(
    tmp_path,
):
    """Replica-kill chaos over REAL processes: the FaultPlan knob
    SIGKILLs the chosen replica mid-route, the in-flight request fails
    with WorkerCrashedError, the session finishes COLD on the survivor
    with token-identical output, and a restarted router (fresh object,
    same state file) still holds the session's pin."""
    from zookeeper_tpu.testing import stop_fleet_workers

    sessions = fleet_prompts()
    want = oracle_outputs(sessions)
    workers = spawn_fleet(tmp_path)
    state = str(tmp_path / "fleet_state.json")
    router = None
    try:
        replicas = [ReplicaHandle.from_worker(w) for w in workers]
        router = FleetRouter(
            replicas,
            page_size=FLEET_CONF["engine.page_size"],
            state_path=state,
        )
        t1, t2 = sessions["sA"]
        r1 = router.submit(t1, session="sA", max_new_tokens=NEW_TOKENS)
        assert r1.tokens.tolist() == want["sA"][0]
        with faults.injected(FaultPlan(fleet_replica_kill_at=1)):
            with pytest.raises(WorkerCrashedError):
                router.submit(
                    t2, session="sA", max_new_tokens=NEW_TOKENS
                )
        dead = router._by_id[r1.worker_id]
        assert not dead.healthy
        # The resubmit re-routes cold to the survivor — and the cold
        # path is still token-identical (affinity is a LATENCY
        # optimization, never a correctness dependency).
        r2 = router.submit(t2, session="sA", max_new_tokens=NEW_TOKENS)
        assert r2.rerouted
        assert r2.worker_id != r1.worker_id
        assert r2.shared_tokens == 0  # genuinely cold on the survivor
        assert r2.tokens.tolist() == want["sA"][1]
        snap = router.metrics.snapshot()
        assert snap["fleet_worker_crashes_total"] == 1.0
        assert snap["fleet_rerouted_total"] == 1.0
        # Router restart (the fleet_router_restart_at coordinate is
        # harness-consumed: the "restart" IS building the new router):
        plan = FaultPlan(fleet_router_restart_at=1)
        with faults.injected(plan):
            assert plan.take_fleet_router_restart()
            router.close()
            survivors = [r for r in replicas if r.healthy]
            router = FleetRouter(
                [
                    ReplicaHandle(
                        s.worker_id, s.generate_url, obs_url=s.obs_url,
                        pid=s.pid,
                    )
                    for s in survivors
                ],
                page_size=FLEET_CONF["engine.page_size"],
                state_path=state,
            )
        # The restarted router kept the pin and the session rides the
        # (now-warm again) survivor.
        assert router.session_pin("sA") == r2.worker_id
        r3 = router.submit(
            t2 + [5, 6], session="sA", max_new_tokens=NEW_TOKENS
        )
        assert r3.worker_id == r2.worker_id
        assert r3.shared_tokens > 0  # turn-2's prompt is cached now
    finally:
        if router is not None:
            router.close()
        stop_fleet_workers(workers)
