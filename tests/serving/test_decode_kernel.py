"""Engine-level paged-decode-kernel certification (docs/DESIGN.md §17):
the ``decode_attention="pallas"`` decode_step program must be
TOKEN-EXACT against the reference flavor through the real
continuous-batching path (mid-stream slot refill included), degrade to
the reference on unsupported geometry, publish the HBM-accounting
gauges, and survive the donated-cache crash-recovery leg with the
kernel selected.

The reference engine IS the oracle here: its own token parity against
the full-context ``greedy_decode`` is pinned by
tests/serving/test_decode_engine.py, so kernel == reference composes
into kernel == full-context oracle without paying a second
greedy-recompute sweep. All CPU (interpret-mode kernel), synchronous
scheduler.
"""

import logging

import numpy as np
import pytest

from zookeeper_tpu.core import configure
from zookeeper_tpu.resilience import FaultPlan, faults
from zookeeper_tpu.serving import WorkerCrashedError
from zookeeper_tpu.serving.decode import DecodeEngine

from tests.serving.test_decode_engine import VOCAB, build_lm, make_scheduler

pytestmark = pytest.mark.serving


def kernel_engine(module, params, state, *, flavor, slots=2,
                  kv_capacity=64, **conf):
    engine = DecodeEngine()
    configure(
        engine,
        {
            "slots": slots,
            "seq_buckets": (8, 16),
            "kv_capacity": kv_capacity,
            "decode_attention": flavor,
            **conf,
        },
        name=f"kengine_{flavor}",
    )
    engine.bind(module, params, state)
    return engine


@pytest.fixture(scope="module")
def lm():
    return build_lm()


@pytest.fixture(scope="module")
def prompts():
    rng = np.random.default_rng(7)
    # > slots so later admissions REFILL freed slots mid-traffic: the
    # kernel then decodes over caches whose rows past ``lengths`` hold
    # the previous occupant's K/V — the garbage-masking leg, live.
    return [
        rng.integers(1, VOCAB, size=int(rng.integers(1, 16))).astype(
            np.int32
        )
        for _ in range(7)
    ]


def serve(engine, prompts, new_tokens=8):
    sched = make_scheduler(engine, max_new_tokens=new_tokens)
    streams = [sched.submit(p) for p in prompts]
    sched.drain()
    return [s.result() for s in streams]


def test_kernel_engine_token_exact_vs_reference_with_refill(lm, prompts):
    module, params, state, _ = lm
    ref_engine = kernel_engine(module, params, state, flavor="reference")
    pal_engine = kernel_engine(module, params, state, flavor="pallas")
    assert pal_engine.decode_attention_flavor == "pallas"
    ref_warm = ref_engine.warmup()
    pal_warm = pal_engine.warmup()
    ref_out = serve(ref_engine, prompts)
    pal_out = serve(pal_engine, prompts)
    for a, b in zip(ref_out, pal_out):
        np.testing.assert_array_equal(a, b)
    # Slot refill happened (7 requests, 2 slots) with zero recompiles
    # on either flavor — the compile-free steady state holds with the
    # kernel program in the cache.
    assert ref_engine.compile_count == ref_warm
    assert pal_engine.compile_count == pal_warm


def test_unsupported_geometry_degrades_to_reference(caplog):
    """head_dim 60/3 = 20 is off the kernel's lane quantum: the engine
    must WARN, resolve the reference flavor, and still serve
    token-identically to an explicit reference engine."""
    module, params, state, _ = build_lm(d_model=60, num_heads=3)
    with caplog.at_level(logging.WARNING):
        engine = kernel_engine(module, params, state, flavor="pallas")
    assert engine.decode_attention_flavor == "reference"
    assert any(
        "decode_attention='pallas'" in r.message for r in caplog.records
    )
    engine.warmup()
    ref = kernel_engine(module, params, state, flavor="reference")
    ref.warmup()
    p = np.arange(1, 9, dtype=np.int32)
    np.testing.assert_array_equal(
        make_scheduler(engine, max_new_tokens=6).generate(p),
        make_scheduler(ref, max_new_tokens=6).generate(p),
    )


def test_module_level_override_logits_pinned(lm):
    """decode_step's ``attention_override`` seam at the module level:
    kernel logits within documented-ULP of the reference trace and
    argmax token-exact (the tolerance contract of
    tests/ops/test_paged_decode_attention.py, composed through the
    whole block stack)."""
    import jax.numpy as jnp

    from zookeeper_tpu.ops import cached_attention, paged_decode_attention

    module, params, state, variables = lm
    slots, cap = 2, 64
    cache = tuple(
        {
            "k": jnp.zeros((slots, cap, 4, 8), jnp.float32),
            "v": jnp.zeros((slots, cap, 4, 8), jnp.float32),
        }
        for _ in range(module.num_layers)
    )
    tokens = jnp.asarray([3, 41], jnp.int32)
    lengths = jnp.asarray([0, 17], jnp.int32)
    ref_logits, ref_cache = module.apply(
        variables, tokens, lengths, cache, method="decode_step",
        attention_override=cached_attention,
    )
    pal_logits, pal_cache = module.apply(
        variables, tokens, lengths, cache, method="decode_step",
        attention_override=paged_decode_attention,
    )
    np.testing.assert_allclose(
        np.asarray(pal_logits), np.asarray(ref_logits), atol=1e-4, rtol=1e-5
    )
    np.testing.assert_array_equal(
        np.argmax(np.asarray(pal_logits), -1),
        np.argmax(np.asarray(ref_logits), -1),
    )
    # The cache WRITE path is shared (outside the attention flavor):
    # layer 0's written rows are bit-identical (its input residual
    # stream precedes any attention); deeper layers inherit the
    # previous layer's attention ULPs and agree to the same tolerance.
    np.testing.assert_array_equal(
        np.asarray(ref_cache[0]["k"]), np.asarray(pal_cache[0]["k"])
    )
    for r, p in zip(ref_cache, pal_cache):
        np.testing.assert_allclose(
            np.asarray(r["k"]), np.asarray(p["k"]), atol=1e-5, rtol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(r["v"]), np.asarray(p["v"]), atol=1e-5, rtol=1e-5
        )


def test_decode_attention_field_validated(lm):
    module, params, state, _ = lm
    with pytest.raises(ValueError, match="decode_attention"):
        kernel_engine(module, params, state, flavor="typo")


def test_kernel_engine_publishes_hbm_gauges(lm, prompts):
    from zookeeper_tpu.observability.registry import default_registry

    module, params, state, _ = lm
    engine = kernel_engine(module, params, state, flavor="pallas")
    engine.warmup()
    reg = default_registry()
    # Bind-time: provisioned KV bytes exported (the PR-9 accounting
    # gap); the PER-ENGINE mbu is exactly the -1-unknown sentinel
    # before this engine's first dispatch (the process-global gauge may
    # hold another engine's value — that's the export path, not this
    # engine's number).
    assert reg.gauge("zk_decode_kv_bytes").value == float(
        engine.kv_cache_nbytes
    )
    assert engine.decode_mbu == -1.0
    serve(engine, prompts[:3], new_tokens=4)
    mbu = engine.decode_mbu
    assert mbu == -1.0 or mbu >= 0.0
    sched = make_scheduler(engine)
    status = sched.status()
    assert status["kv_cache_bytes"] == engine.kv_cache_nbytes
    assert status["kv_bytes_per_slot"] == engine.kv_cache_nbytes // 2
    assert status["decode_attention"] == "pallas"
    assert "decode_mbu" in status


@pytest.mark.chaos
def test_crash_recovery_with_kernel_selected(lm, prompts):
    """The donated-cache ``_reset_cache`` leg with the kernel program
    live: an injected scheduler crash fails streams cleanly, and a
    resubmit on the restarted scheduler serves from the reallocated
    cache — token-identical to the reference flavor, zero recompiles."""
    module, params, state, _ = lm
    engine = kernel_engine(module, params, state, flavor="pallas")
    warm = engine.warmup()
    sched = make_scheduler(engine, max_new_tokens=6)
    p = np.arange(1, 8, dtype=np.int32)
    with faults.injected(FaultPlan(decode_worker_crash=1)):
        stream = sched.submit(p)
        with pytest.raises(WorkerCrashedError):
            stream.result()
    got = sched.generate(p)  # restarted scheduler, fresh zeroed cache
    ref = kernel_engine(module, params, state, flavor="reference")
    ref.warmup()
    want = make_scheduler(ref, max_new_tokens=6).generate(p)
    np.testing.assert_array_equal(got, want)
    assert engine.compile_count == warm
