"""Chunked-prefill certification (docs/DESIGN.md §25): the paged
engine's ``prefill_chunk_tokens`` splits every admitted prompt into
bounded chunk dispatches the scheduler's token-budget planner
interleaves with decode steps — and the whole mode is pinned
TOKEN-IDENTICAL to monolithic prefill (which test_paged_kv.py pins
against the slot layout and the full-context greedy oracle, so
chunked == monolithic composes into chunked == oracle; the headline
test re-pins the oracle directly anyway) through real mid-prefill slot
refill, prefix-cache warm partial-chunk hits, chunk == page boundary
alignment, int8 KV, and the speculative schedule at both acceptance
extremes — with zero post-warmup compiles on every leg (chunk
dispatches ride the warmed ``prefill_extend`` grid).

The chaos leg pins crash-mid-chunk custody: pages released,
``leak_check() == 0``, the mid-prefill stream fails clean with
``WorkerCrashedError``. The guard leg regression-tests the §25
tokens-owed fix: remaining prefill chunks count toward predicted
completion. All CPU, synchronous scheduler.
"""

import numpy as np
import pytest

from zookeeper_tpu.core import configure
from zookeeper_tpu.resilience import FaultPlan, faults
from zookeeper_tpu.serving import WorkerCrashedError
from zookeeper_tpu.serving.decode import (
    DecodeEngine,
    DecodeMetrics,
    DecodeScheduler,
    SpeculativeDecoding,
)
from zookeeper_tpu.serving.guardrails import OverloadGuard, PredictedMissError

from tests.serving.test_decode_engine import (
    VOCAB,
    build_lm,
    make_scheduler,
    oracle,
)
from tests.serving.test_paged_kv import paged_engine, serve, slots_engine

pytestmark = pytest.mark.serving

# Tier-1 keeps the tentpole certification (chunked == monolithic ==
# oracle through mid-prefill refill, compile-pinned) plus the instant
# config-seam rejections; the heavier legs (chunk-size sweep, page
# alignment, int8, both speculative extremes, warm-prefix skip, guard
# accounting, planner floor, statusz, crash-mid-chunk) are slow-marked
# and run UNFILTERED in the dedicated CI step — the same split as the
# disagg suite.


def chunked_engine(module, params, state, *, chunk=4, name="chunked",
                   **conf):
    return paged_engine(
        module, params, state, name=name,
        prefill_chunk_tokens=chunk, **conf,
    )


@pytest.fixture(scope="module")
def lm():
    return build_lm()


@pytest.fixture(scope="module")
def prompts():
    rng = np.random.default_rng(23)
    # > slots so admissions REFILL freed slots while OTHER prompts are
    # still mid-prefill — the planner must juggle decode, partial
    # cursors, and fresh admissions in the same iterations.
    return [
        rng.integers(1, VOCAB, size=int(rng.integers(1, 16))).astype(
            np.int32
        )
        for _ in range(7)
    ]


# -- the parity certification ---------------------------------------------


@pytest.mark.slow
def test_chunked_token_identical_with_midprefill_refill(lm, prompts):
    module, params, state, variables = lm
    mono = paged_engine(module, params, state, name="chunkmono")
    chk = chunked_engine(module, params, state, chunk=4, name="chunkhead")
    mono_warm, chk_warm = mono.warmup(), chk.warmup()
    want = serve(mono, prompts)
    got = serve(chk, prompts)
    for a, b in zip(want, got):
        np.testing.assert_array_equal(a, b)
    # And directly against the full-context greedy oracle.
    for p, out in zip(prompts[:3], got[:3]):
        np.testing.assert_array_equal(
            out, oracle(module, variables, p, out.shape[0])
        )
    # Refill happened (7 requests, 2 slots) and every chunk dispatch
    # rode the warmed extend grid: zero post-warmup compiles.
    assert mono.compile_count == mono_warm
    assert chk.compile_count == chk_warm
    assert chk.recompiles_detected == 0
    assert chk.page_pool.leak_check() == 0


@pytest.mark.slow
def test_chunk_size_sweep_token_identical(lm):
    """chunk=1 (every token its own dispatch) through chunk > prompt
    (a single chunk, the degenerate monolithic case) all agree."""
    module, params, state, _ = lm
    rng = np.random.default_rng(3)
    ps = [
        rng.integers(1, VOCAB, size=n).astype(np.int32)
        for n in (1, 7, 13)
    ]
    mono = paged_engine(module, params, state, name="sweepmono")
    mono.warmup()
    want = serve(mono, ps, new_tokens=6)
    for chunk in (1, 5, 16):
        chk = chunked_engine(
            module, params, state, chunk=chunk, name=f"sweep{chunk}"
        )
        warm = chk.warmup()
        got = serve(chk, ps, new_tokens=6)
        for a, b in zip(want, got):
            np.testing.assert_array_equal(a, b)
        assert chk.compile_count == warm, f"chunk={chunk} recompiled"
        assert chk.page_pool.leak_check() == 0


@pytest.mark.slow
def test_chunk_boundary_equals_page_boundary(lm):
    """chunk_tokens == page_size: every chunk fills exactly one page,
    so each dispatch's first row starts a fresh page (the alignment
    edge where an off-by-one would write across a page seam)."""
    module, params, state, _ = lm
    rng = np.random.default_rng(5)
    # 8 and 12 tokens land EXACTLY on 4-row page boundaries; 7 leaves
    # a partial final chunk.
    ps = [
        rng.integers(1, VOCAB, size=n).astype(np.int32)
        for n in (8, 12, 7)
    ]
    mono = paged_engine(
        module, params, state, name="pagemono", page_size=4
    )
    mono.warmup()
    chk = chunked_engine(
        module, params, state, chunk=4, name="pagechunk", page_size=4
    )
    warm = chk.warmup()
    want = serve(mono, ps, new_tokens=6)
    got = serve(chk, ps, new_tokens=6)
    for a, b in zip(want, got):
        np.testing.assert_array_equal(a, b)
    assert chk.compile_count == warm
    assert chk.page_pool.leak_check() == 0


@pytest.mark.slow
def test_chunked_int8_token_identical(lm):
    module, params, state, _ = lm
    mono = paged_engine(
        module, params, state, name="i8mono", kv_quant="int8"
    )
    mono.warmup()
    chk = chunked_engine(
        module, params, state, chunk=4, name="i8chunk", kv_quant="int8"
    )
    warm = chk.warmup()
    for seed in (0, 6):
        rng = np.random.default_rng(seed)
        ps = [
            rng.integers(1, VOCAB, size=int(rng.integers(1, 16))).astype(
                np.int32
            )
            for _ in range(5)
        ]
        a = serve(mono, ps)
        b = serve(chk, ps)
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)
    assert chk.compile_count == warm


# -- prefix cache: warm partial-chunk hits ---------------------------------


@pytest.mark.slow
def test_warm_prefix_hit_skips_cached_chunks(lm):
    """A warm admission starts its chunk cursor PAST the cached prefix
    (shared pages are never re-prefilled), CoW fires exactly at the
    divergence, and streams stay identical to the slot layout. The
    12-token shared prefix with chunk=5 puts the cursor mid-chunk —
    the partial-chunk resume case."""
    module, params, state, _ = lm
    rng = np.random.default_rng(11)
    shared = rng.integers(1, VOCAB, size=12).astype(np.int32)
    ps = [
        np.concatenate(
            [shared, rng.integers(1, VOCAB, size=3).astype(np.int32)]
        )
        for _ in range(4)
    ] + [shared.copy()]  # an exact repeat of the shared prefix
    ref = slots_engine(module, params, state, name="warmchunkref")
    ref.warmup()
    want = serve(ref, ps, new_tokens=6)

    chk = chunked_engine(module, params, state, chunk=5, name="warmchunk")
    warm = chk.warmup()
    got = serve(chk, ps, new_tokens=6)
    for a, b in zip(want, got):
        np.testing.assert_array_equal(a, b)
    pool = chk.page_pool
    assert pool.prefix.hits >= 3  # every admission after the first
    assert pool.cow_pages >= 3  # 12 % 16 != 0: divergence mid-page
    assert chk.compile_count == warm
    assert pool.leak_check() == 0


# -- speculative at both acceptance extremes -------------------------------


@pytest.mark.slow
def test_chunked_speculative_full_acceptance(lm, prompts):
    """Draft IS the teacher (acceptance ~1.0): the draft cache seeds
    on each FINAL chunk, then every window commits k+1 tokens —
    token-identical to the unchunked speculative run and to the slot
    layout."""
    module, params, state, _ = lm
    ref = slots_engine(module, params, state, name="chunkspecref")
    ref.warmup()
    want = serve(ref, prompts)

    teacher = chunked_engine(
        module, params, state, chunk=4, name="chunkspec"
    )
    teacher.warmup()
    spec = SpeculativeDecoding()
    configure(spec, {"enabled": True, "k": 3}, name="chunk_spec")
    spec.bind(teacher, module, params, state)
    sched = DecodeScheduler()
    configure(sched, {"max_new_tokens": 8}, name="chunk_spec_sched")
    sched.bind(teacher, speculative=spec)
    streams = [sched.submit(p) for p in prompts]
    sched.drain()
    got = [s.result() for s in streams]
    for a, b in zip(want, got):
        np.testing.assert_array_equal(a, b)
    assert spec.acceptance_rate > 0.9  # draft IS the teacher
    assert teacher.page_pool.leak_check() == 0


@pytest.mark.slow
def test_chunked_speculative_low_acceptance(lm, prompts):
    """The rejection extreme: an independently-initialized draft
    disagrees almost always, so chunked admissions feed windows that
    roll back constantly — still token-identical."""
    module, params, state, _ = lm
    d_module, d_params, d_state, _ = build_lm(
        num_layers=1, d_model=32, num_heads=4, seed=99
    )
    ref = slots_engine(module, params, state, name="chunkrndref")
    ref.warmup()
    want = serve(ref, prompts)
    teacher = chunked_engine(
        module, params, state, chunk=4, name="chunkrnd"
    )
    teacher.warmup()
    spec = SpeculativeDecoding()
    configure(spec, {"enabled": True, "k": 3}, name="chunk_spec_rnd")
    spec.bind(teacher, d_module, d_params, d_state)
    sched = DecodeScheduler()
    configure(sched, {"max_new_tokens": 8}, name="chunk_spec_rnd_sched")
    sched.bind(teacher, speculative=spec)
    streams = [sched.submit(p) for p in prompts]
    sched.drain()
    got = [s.result() for s in streams]
    for a, b in zip(want, got):
        np.testing.assert_array_equal(a, b)


# -- the token-budget planner ----------------------------------------------


@pytest.mark.slow
def test_explicit_token_budget_floor_still_completes(lm):
    """token_budget=1 squeezes every iteration to the 1-token progress
    floor — prefill crawls one token per iteration but never
    livelocks, and the streams stay token-identical."""
    module, params, state, _ = lm
    rng = np.random.default_rng(9)
    ps = [rng.integers(1, VOCAB, size=10).astype(np.int32)
          for _ in range(3)]
    mono = paged_engine(module, params, state, name="floormono")
    mono.warmup()
    want = serve(mono, ps, new_tokens=4)
    chk = chunked_engine(module, params, state, chunk=4, name="floor")
    chk.warmup()
    got = serve(chk, ps, new_tokens=4, token_budget=1)
    for a, b in zip(want, got):
        np.testing.assert_array_equal(a, b)
    assert chk.page_pool.leak_check() == 0


@pytest.mark.slow
def test_decode_never_stalls_behind_long_prompt(lm):
    """The tentpole's scheduling claim, pinned structurally: while a
    long prompt is mid-prefill, already-active streams KEEP receiving
    tokens in the same iterations (the monolithic path would freeze
    them for the whole prefill)."""
    module, params, state, _ = lm
    chk = chunked_engine(
        module, params, state, chunk=2, name="nostall", slots=2,
        seq_buckets=(8, 16), kv_capacity=64,
    )
    chk.warmup()
    sched = make_scheduler(chk, max_new_tokens=12)
    short = sched.submit(np.arange(1, 4, dtype=np.int32))
    # Admit + finish the short prompt's prefill first.
    sched._pump()
    tokens_before = len(short.tokens_so_far)
    assert tokens_before >= 1
    long = sched.submit(np.arange(1, 15, dtype=np.int32))  # 7 chunks
    progressed = []
    while long.ttft_ms is None and sched._has_work():
        sched._pump()
        progressed.append(len(short.tokens_so_far))
    # The short stream advanced DURING the long prompt's chunked
    # prefill — at least one token before the long TTFT landed.
    assert progressed and progressed[-1] > tokens_before
    sched.drain()
    assert long.result().shape[0] == 12
    st = sched.status()["chunked_prefill"]
    assert st["enabled"] and st["pending_prefills"] == 0


# -- config seam -----------------------------------------------------------


def test_chunking_requires_paged_layout(lm):
    module, params, state, _ = lm
    engine = DecodeEngine()
    configure(
        engine,
        {"slots": 2, "seq_buckets": (8,), "prefill_chunk_tokens": 4},
        name="chunk_slots_seam",
    )
    with pytest.raises(ValueError, match="kv_layout='paged'"):
        engine.bind(module, params, state)


def test_chunking_rejects_bad_sizes(lm):
    module, params, state, _ = lm
    engine = DecodeEngine()
    configure(
        engine,
        {
            "slots": 2, "seq_buckets": (8,), "kv_layout": "paged",
            "kv_capacity": 64, "prefill_chunk_tokens": -1,
        },
        name="chunk_neg_seam",
    )
    with pytest.raises(ValueError, match="prefill_chunk_tokens"):
        engine.bind(module, params, state)
    wide = DecodeEngine()
    configure(
        wide,
        {
            "slots": 2, "seq_buckets": (8, 16), "kv_layout": "paged",
            "kv_capacity": 64, "prefill_chunk_tokens": 32,
        },
        name="chunk_wide_seam",
    )
    with pytest.raises(ValueError, match="seq bucket"):
        wide.bind(module, params, state)


def test_scheduler_rejects_negative_token_budget(lm):
    module, params, state, _ = lm
    engine = chunked_engine(module, params, state, name="budget_seam")
    sched = DecodeScheduler()
    configure(sched, {"token_budget": -1}, name="budget_seam_sched")
    with pytest.raises(ValueError, match="token_budget"):
        sched.bind(engine)


def test_disagg_config_warn_degrades_chunking(caplog):
    """DisaggServingConfig: chunking on either role engine is LOUDLY
    degraded to monolithic prefill BEFORE bind (disagg already
    isolates the roles on separate slices — §25's problem does not
    exist there)."""
    import logging

    from zookeeper_tpu.serving import DisaggServingConfig

    svc = DisaggServingConfig()
    configure(
        svc,
        {
            "model.num_layers": 1, "model.d_model": 32,
            "model.num_heads": 4, "model.attention": "dense",
            "seq_len": 64, "vocab_size": 61,
            "engine.slots": 2, "engine.seq_buckets": (8,),
            "engine.prefill_buckets": (1,),
            "engine.kv_layout": "paged",
            "engine.prefill_chunk_tokens": 4,
            "prefill_engine.slots": 2,
            "prefill_engine.seq_buckets": (8,),
            "prefill_engine.prefill_buckets": (1, 2),
            "prefill_engine.kv_layout": "paged",
            "prefill_engine.prefill_chunk_tokens": 4,
            "requests": 0, "max_prompt": 6, "new_tokens": 2,
            "warmup": False, "verbose": False,
        },
        name="svc_disagg_chunk",
    )
    with caplog.at_level(logging.WARNING):
        engine, sched = svc.build_service()
    try:
        assert int(svc.engine.prefill_chunk_tokens) == 0
        assert int(svc.prefill_engine.prefill_chunk_tokens) == 0
        warned = [
            r for r in caplog.records
            if "prefill_chunk_tokens" in r.getMessage()
        ]
        assert len(warned) == 2  # one per role, loud
    finally:
        svc._teardown_service(suppress=True)


# -- guardrails: tokens-owed counts remaining chunks -----------------------


def _warmed_guard():
    guard = OverloadGuard()
    configure(guard, {"enabled": True}, name="chunk_guard")
    guard.bind()
    for _ in range(guard.min_samples):
        guard.observe_service(10.0, 1)  # 10 ms per unit
        guard.observe_wait(0.0)
    return guard


@pytest.mark.slow
def test_guard_admission_counts_remaining_prefill_chunks(lm):
    """The §25 estimator fix, as a regression on the predicted-miss
    math: queued 16-token prompts owe 4 chunk units each at chunk=4,
    so a deadline that clears the tokens-only estimate (monolithic
    posture) is predicted to MISS once prefill work is counted.

    queued = A's 8 tokens (+4 chunks chunked) ; request = 8 (+4).
    At 10 ms/unit: monolithic predicts 80 + 80 = 160 ms < 200 ms
    deadline (admit); chunked predicts 120 + 120 = 240 ms > 200 ms
    (shed)."""
    module, params, state, _ = lm
    prompt = np.arange(1, 17, dtype=np.int32)  # 16 tokens = 4 chunks

    mono = paged_engine(
        module, params, state, name="guardmono", seq_buckets=(8, 16, 32),
        kv_capacity=64,
    )
    mono.warmup()
    msched = make_scheduler(mono, max_new_tokens=8)
    object.__setattr__(msched, "_guard", _warmed_guard())
    msched.submit(prompt)  # queued ahead; scheduler not yet pumped
    msched.submit(prompt, deadline_ms=200.0)  # admits: 160 < 200
    msched.close()

    chk = chunked_engine(
        module, params, state, chunk=4, name="guardchunk",
        seq_buckets=(8, 16, 32), kv_capacity=64,
    )
    chk.warmup()
    csched = make_scheduler(chk, max_new_tokens=8)
    object.__setattr__(csched, "_guard", _warmed_guard())
    csched.submit(prompt)
    with pytest.raises(PredictedMissError):
        csched.submit(prompt, deadline_ms=200.0)  # sheds: 240 > 200
    csched.close()


# -- observability ---------------------------------------------------------


@pytest.mark.slow
def test_chunk_metrics_and_statusz(lm, prompts):
    module, params, state, _ = lm
    chk = chunked_engine(module, params, state, chunk=4, name="obs")
    chk.warmup()
    metrics = DecodeMetrics()
    configure(metrics, {}, name="chunk_obs_metrics")
    sched = DecodeScheduler()
    configure(sched, {"max_new_tokens": 6}, name="chunk_obs_sched")
    sched.bind(chk, metrics=metrics)
    streams = [sched.submit(p) for p in prompts]
    sched.drain()
    for s in streams:
        s.result()
    totals = metrics.totals
    assert totals["prefill_chunks_total"] > len(prompts) / 2
    assert totals["requests_total"] == len(prompts)
    snap = metrics.snapshot()
    for key in (
        "itl_p50_ms", "itl_p99_ms", "prefill_stall_p50_ms",
        "prefill_stall_p99_ms",
    ):
        assert key in snap, key
    # The new series render as exposition text through the registry.
    names = {inst.name for inst in metrics.registry.collect()}
    assert "zk_decode_itl_ms" in names
    assert "zk_prefill_chunks_total" in names
    assert "zk_prefill_stall_ms" in names
    st = sched.status()["chunked_prefill"]
    assert st["enabled"] is True
    assert st["chunk_tokens"] == 4
    assert st["token_budget"] > 0
    assert st["pending_prefills"] == 0
    assert st["pending_prefill_tokens"] == 0


@pytest.mark.slow
def test_monolithic_statusz_reports_chunking_off(lm):
    module, params, state, _ = lm
    mono = paged_engine(module, params, state, name="obsmono")
    mono.warmup()
    sched = make_scheduler(mono, max_new_tokens=2)
    sched.generate(np.arange(1, 5, dtype=np.int32))
    st = sched.status()["chunked_prefill"]
    assert st["enabled"] is False
    assert st["chunk_tokens"] == 0
    assert st["token_budget"] == 0


# -- chaos -----------------------------------------------------------------


@pytest.mark.chaos
@pytest.mark.slow
def test_crash_mid_chunk_releases_pages(lm):
    """A crash while a prompt's chunk cursor is mid-prefill: its pages
    release, ``leak_check() == 0``, the stream fails clean with
    ``WorkerCrashedError``, and a resubmit on the restarted scheduler
    serves token-identically with zero new compiles."""
    module, params, state, _ = lm
    chk = chunked_engine(module, params, state, chunk=2, name="crash")
    warm = chk.warmup()
    sched = make_scheduler(chk, max_new_tokens=6)
    p = np.arange(1, 14, dtype=np.int32)  # 13 tokens = 7 chunks
    stream = sched.submit(p)
    sched._pump()  # admit + first chunk(s): cursor now mid-prompt
    st = sched.status()["chunked_prefill"]
    assert st["pending_prefills"] == 1
    assert 0 < st["pending_prefill_tokens"] < 13
    with faults.injected(FaultPlan(decode_worker_crash=1)):
        with pytest.raises(WorkerCrashedError):
            sched._pump()
    with pytest.raises(WorkerCrashedError):
        stream.result()
    pool = chk.page_pool
    assert pool.leak_check() == 0
    assert sched.status()["chunked_prefill"]["pending_prefills"] == 0
    got = sched.generate(p)  # restarted scheduler
    ref = slots_engine(module, params, state, name="crashchunkref")
    ref.warmup()
    np.testing.assert_array_equal(
        got, make_scheduler(ref, max_new_tokens=6).generate(p)
    )
    assert chk.compile_count == warm
    assert pool.leak_check() == 0
