"""Speculative-decode chaos certification (FaultPlan-driven,
deterministic — docs/DESIGN.md §18): a scheduler crash mid-speculation
fails every in-flight stream cleanly and the restarted scheduler serves
token-exact with BOTH caches (teacher + draft) consistent across
recovery; a draft dispatch failure after donation exercises the draft
engine's ``_reset_cache`` path in isolation from the teacher's; and a
staged TEACHER hot-swap mid-speculation upholds the
one-weight-version-per-sequence contract (the draft is never swapped —
staleness only lowers acceptance, never correctness)."""

import numpy as np
import pytest

from zookeeper_tpu.core import configure
from zookeeper_tpu.resilience import FaultPlan, faults
from zookeeper_tpu.serving import WorkerCrashedError
from zookeeper_tpu.serving.decode import DecodeMetrics, DecodeScheduler

from tests.serving.test_decode_engine import (
    VOCAB,
    build_lm,
    make_engine,
    oracle,
)
from tests.serving.test_speculative import make_spec, zero_tail_pair

pytestmark = [pytest.mark.serving, pytest.mark.chaos]


def make_sched(engine, spec, **conf):
    m = DecodeMetrics()
    configure(m, {}, name="spec_chaos_metrics")
    s = DecodeScheduler()
    configure(s, dict(conf), name="spec_chaos_sched")
    s.bind(engine, metrics=m, speculative=spec)
    return s, m


def test_crash_mid_speculation_fails_streams_clean_and_restarts():
    """Injected loop crash with speculation bound: in-flight AND
    queued streams fail with WorkerCrashedError (partial tokens
    readable and oracle-exact), draft bookkeeping is cleared, and the
    restarted scheduler serves token-exact through the speculative
    schedule with zero new compiles on either engine."""
    teacher, draft = zero_tail_pair()
    module, params, state, variables = teacher
    engine = make_engine(module, params, state, slots=2)
    engine.warmup()
    spec = make_spec(engine, draft, k=3)
    warm = engine.compile_count
    dwarm = spec.draft_engine.compile_count
    sched, m = make_sched(engine, spec)
    p1 = np.arange(1, 8, dtype=np.int32)
    p2 = np.arange(2, 7, dtype=np.int32)
    in_flight = sched.submit(p1, max_new_tokens=12)
    sched._pump()  # prefill + first speculative window landed
    assert in_flight.tokens_so_far.shape[0] >= 1
    queued = sched.submit(p2, max_new_tokens=4)
    with faults.injected(FaultPlan(decode_worker_crash=1)):
        with pytest.raises(WorkerCrashedError):
            sched.drain()
    for stream in (in_flight, queued):
        assert stream.done
        with pytest.raises(WorkerCrashedError):
            stream.result()
    partial = in_flight.tokens_so_far
    assert partial.shape[0] >= 1
    np.testing.assert_array_equal(
        partial, oracle(module, variables, p1, partial.shape[0])
    )
    assert m.totals["worker_restarts_total"] == 1
    assert sched.active_slots == 0 and sched.queue_depth == 0
    # Restarted: speculative, token-exact, compile-free — the dead
    # streams' rows in BOTH caches are invisible to the new occupants.
    out = sched.generate(p1, max_new_tokens=6)
    np.testing.assert_array_equal(out, oracle(module, variables, p1, 6))
    assert engine.compile_count == warm
    assert spec.draft_engine.compile_count == dwarm


def test_draft_dispatch_failure_resets_draft_cache_and_serves_resubmits():
    """A failure of the DRAFT's compiled call itself (after donation
    consumed the draft KV buffers): streams fail clean like any crash,
    the draft engine restores a usable zeroed cache via its own
    ``_reset_cache`` — teacher-cache state is untouched machinery-wise
    (its rows die with the failed streams per the validity invariant) —
    and resubmits serve token-exact with zero new compiles."""
    teacher, draft = zero_tail_pair()
    module, params, state, variables = teacher
    engine = make_engine(module, params, state, slots=2)
    engine.warmup()
    spec = make_spec(engine, draft, k=2)
    warm = engine.compile_count
    dwarm = spec.draft_engine.compile_count
    sched, _ = make_sched(engine, spec)
    draft_engine = spec.draft_engine
    key = ("verify", 2, draft_engine._partitioner.mesh)
    real = draft_engine._compiled_cache[key]

    def dying(variables_, cache, tokens, lengths):
        real(variables_, cache, tokens, lengths)  # donation happens
        raise RuntimeError("injected draft dispatch-time failure")

    draft_engine._compiled_cache[key] = dying
    p = np.arange(1, 6, dtype=np.int32)
    doomed = sched.submit(p, max_new_tokens=6)
    with pytest.raises(RuntimeError, match="injected draft"):
        sched.drain()
    with pytest.raises(WorkerCrashedError):
        doomed.result()
    draft_engine._compiled_cache[key] = real
    revived = sched.submit(p, max_new_tokens=6)
    sched.drain()
    np.testing.assert_array_equal(
        revived.result(), oracle(module, variables, p, 6)
    )
    assert engine.compile_count == warm
    assert draft_engine.compile_count == dwarm


def test_teacher_hot_swap_mid_speculation_one_weight_version_per_stream():
    """request_swap staged while streams are mid-SPECULATION: the swap
    applies only at the drain boundary, in-flight streams finish
    bit-exact on their ORIGINAL teacher weights, and post-swap streams
    run bit-exact on the NEW teacher — with the DRAFT deliberately
    unswapped (it now disagrees with the new teacher, so acceptance
    drops, but every emitted token is still the live teacher's argmax:
    losslessness is independent of draft quality)."""
    teacher, draft = zero_tail_pair()
    module, params, state, variables = teacher
    _, params_b, state_b, variables_b = build_lm(num_layers=3, seed=29)
    engine = make_engine(module, params, state, slots=2)
    engine.warmup()
    spec = make_spec(engine, draft, k=3)
    warm = engine.compile_count
    sched, m = make_sched(engine, spec)
    rng = np.random.default_rng(9)
    p1 = rng.integers(1, VOCAB, size=6).astype(np.int32)
    p2 = rng.integers(1, VOCAB, size=9).astype(np.int32)
    # Budgets span many k+1 windows so both streams are genuinely
    # mid-speculation at the swap request (a full-accept window
    # delivers up to 4 tokens per pump at k=3).
    s1 = sched.submit(p1, max_new_tokens=30)
    s2 = sched.submit(p2, max_new_tokens=24)
    sched._pump()
    sched._pump()  # both streams mid-speculation
    sched.request_swap(params_b, state_b, step=31)
    sched._pump()  # must NOT apply: slots occupied
    assert sched.swap_pending
    post = sched.submit(p1, max_new_tokens=5)  # admitted only post-swap
    sched.drain()
    assert not sched.swap_pending
    np.testing.assert_array_equal(
        s1.result(), oracle(module, variables, p1, 30)
    )
    np.testing.assert_array_equal(
        s2.result(), oracle(module, variables, p2, 24)
    )
    np.testing.assert_array_equal(
        post.result(), oracle(module, variables_b, p1, 5)
    )
    assert engine.compile_count == warm  # swap never recompiles
    assert m.totals["weight_swaps_total"] == 1


def test_crash_with_swap_pending_survives_into_speculative_restart():
    """Crash while a teacher swap is staged: streams fail clean, the
    staged swap survives and applies before the next admission — the
    post-crash stream speculates against the NEW teacher weights."""
    teacher, draft = zero_tail_pair()
    module, params, state, variables = teacher
    _, params_b, state_b, variables_b = build_lm(num_layers=3, seed=29)
    engine = make_engine(module, params, state, slots=2)
    engine.warmup()
    spec = make_spec(engine, draft, k=2)
    sched, _ = make_sched(engine, spec)
    p = np.arange(1, 7, dtype=np.int32)
    victim = sched.submit(p, max_new_tokens=8)
    sched._pump()
    sched.request_swap(params_b, state_b)
    with faults.injected(FaultPlan(decode_worker_crash=1)):
        with pytest.raises(WorkerCrashedError):
            sched.drain()
    assert victim.done and sched.swap_pending
    out = sched.generate(p, max_new_tokens=4)
    np.testing.assert_array_equal(
        out, oracle(module, variables_b, p, 4)
    )
    assert not sched.swap_pending
