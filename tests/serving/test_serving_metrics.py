"""ServingMetrics: concurrent recording exactness, empty-reservoir
percentile edge cases, window bounding, and the typed-registry backing
(docs/DESIGN.md §13) — the aggregator is recorded into from the async
batcher worker, the checkpoint watcher daemon, and submitter threads
at once, so its counters must be exact under contention."""

import threading

import numpy as np
import pytest

from zookeeper_tpu.core import configure
from zookeeper_tpu.observability.export import render_prometheus
from zookeeper_tpu.serving import ServingMetrics

pytestmark = pytest.mark.serving


def make_metrics(extra=None):
    m = ServingMetrics()
    configure(m, dict(extra or {}), name="metrics_test")
    return m


# -- concurrency ---------------------------------------------------------


def test_concurrent_recording_counters_are_exact():
    m = make_metrics()
    n_threads, per_thread = 8, 500
    barrier = threading.Barrier(n_threads)

    def record(tid):
        barrier.wait()  # maximize interleaving
        for i in range(per_thread):
            m.record_request(float(i % 37), rows=2)
            m.record_dispatch(real_rows=3, bucket_rows=4)
            m.record_queue_depth(i % 11)
            if i % 5 == 0:
                m.record_rejected()
            if i % 7 == 0:
                m.record_deadline_expired()

    threads = [
        threading.Thread(target=record, args=(t,))
        for t in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    totals = m.totals
    assert totals["requests"] == n_threads * per_thread
    assert totals["rows"] == n_threads * per_thread * 2
    assert totals["dispatches"] == n_threads * per_thread
    assert totals["rejected"] == n_threads * len(range(0, per_thread, 5))
    assert totals["deadline_expired"] == n_threads * len(
        range(0, per_thread, 7)
    )
    # Histograms saw every sample too (the /metrics view can't
    # silently undercount relative to the totals).
    hist = m._obs()["hist"]["latency_ms"]
    assert hist.count == n_threads * per_thread


def test_concurrent_first_touch_initialization_shares_one_registry():
    """The racing-threads-at-first-record path: every thread's samples
    must land in ONE registry (a dropped half-initialized registry
    would silently eat samples)."""
    m = make_metrics()
    n_threads = 16
    barrier = threading.Barrier(n_threads)

    def record():
        barrier.wait()
        m.record_request(1.0, rows=1)

    threads = [threading.Thread(target=record) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert m.totals["requests"] == n_threads
    assert len(m._series("latency_ms")) == n_threads


def test_concurrent_percentile_snapshot_during_recording():
    """snapshot() races record_* without crashing or returning
    out-of-range percentiles (the scrape thread reads while the worker
    records)."""
    m = make_metrics()
    stop = threading.Event()
    errors = []

    def record():
        i = 0
        while not stop.is_set():
            m.record_request(float(i % 100), rows=1)
            i += 1

    def snapshot():
        try:
            while not stop.is_set():
                snap = m.snapshot()
                if "latency_p99_ms" in snap:
                    assert 0.0 <= snap["latency_p50_ms"] <= 99.0
                    assert snap["latency_p50_ms"] <= snap["latency_p99_ms"]
        except Exception as e:  # pragma: no cover - the failure leg
            errors.append(e)

    recorder = threading.Thread(target=record)
    reader = threading.Thread(target=snapshot)
    recorder.start()
    reader.start()
    # Let them contend briefly but deterministically-bounded.
    recorder.join(timeout=0.25)
    stop.set()
    recorder.join()
    reader.join()
    assert not errors


# -- empty-reservoir percentile edge cases -------------------------------


def test_snapshot_with_no_samples_has_counters_only():
    m = make_metrics()
    snap = m.snapshot()
    assert snap["requests"] == 0.0
    assert "latency_p50_ms" not in snap
    assert "latency_p95_ms" not in snap
    assert "latency_p99_ms" not in snap
    assert "latency_mean_ms" not in snap
    assert "queue_depth_mean" not in snap
    assert "bucket_fill_mean" not in snap


def test_snapshot_after_counter_only_recording_omits_percentiles():
    """Counter recorders (rejected/deadline/watcher) must not conjure
    an empty latency series into the percentile math."""
    m = make_metrics()
    m.record_rejected()
    m.record_deadline_expired()
    m.record_watcher_stopped()
    m.record_weights_step(12)
    snap = m.snapshot()
    assert snap["rejected"] == 1.0
    assert snap["serving_weights_step"] == 12.0
    assert "latency_p50_ms" not in snap
    assert "weight_swap_ms_mean" not in snap


def test_single_sample_percentiles_degenerate_to_that_sample():
    m = make_metrics()
    m.record_request(8.25, rows=1)
    snap = m.snapshot()
    for key in (
        "latency_p50_ms", "latency_p95_ms", "latency_p99_ms",
        "latency_mean_ms",
    ):
        assert snap[key] == 8.25


def test_window_bounds_percentile_reservoir():
    m = make_metrics({"window": 8})
    for v in range(100):
        m.record_request(float(v), rows=1)
    # Only the last 8 samples survive; totals still count everything.
    assert m.totals["requests"] == 100
    arr = np.asarray(m._series("latency_ms"))
    assert arr.tolist() == [float(v) for v in range(92, 100)]
    snap = m.snapshot()
    assert snap["latency_p50_ms"] == pytest.approx(
        float(np.percentile(arr, 50))
    )


def test_reset_clears_counters_windows_in_place():
    m = make_metrics()
    m.record_request(1.0, rows=1)
    m.record_weight_swap(5.0, step=7)
    old_registry = m.registry
    m.reset()
    assert m.totals["requests"] == 0
    assert m.totals["serving_weights_step"] == -1  # back to initial
    assert "latency_p50_ms" not in m.snapshot()
    # The registry and instruments survive reset: a live
    # ObservabilityServer that captured m.registry at startup must keep
    # rendering this aggregator (and see post-reset samples).
    assert m.registry is old_registry
    m.record_request(2.0, rows=3)
    text = render_prometheus([old_registry])
    assert "zk_serving_requests 1" in text
    assert "zk_serving_rows 3" in text


# -- registry backing ----------------------------------------------------


def test_registry_renders_every_serving_series():
    m = make_metrics()
    m.record_request(3.0, rows=2)
    m.record_dispatch(3, 4)
    m.record_queue_depth(5)
    m.record_weight_swap(20.0, step=42)
    text = render_prometheus([m.registry])
    assert "zk_serving_requests 1" in text
    assert "zk_serving_rows 2" in text
    assert "zk_serving_dispatches 1" in text
    assert "zk_serving_queue_depth 5" in text
    assert "zk_serving_serving_weights_step 42" in text
    assert "zk_serving_weight_swaps 1" in text
    assert "zk_serving_latency_ms_count 1" in text
    assert 'zk_serving_bucket_fill_bucket{le="+Inf"} 1' in text


def test_two_instances_have_independent_registries():
    a, b = make_metrics(), make_metrics()
    a.record_request(1.0, rows=1)
    assert a.totals["requests"] == 1
    assert b.totals["requests"] == 0
    assert a.registry is not b.registry


def test_totals_key_order_is_stable():
    # Downstream JSON consumers (finish_report lines, dashboards) see
    # the historical key order.
    assert list(make_metrics().totals) == [
        "requests", "rows", "dispatches", "rejected",
        "deadline_expired", "worker_restarts", "weight_swaps",
        "serving_weights_step", "watcher_stopped",
    ]
