"""Request-scoped flow tracing through both serving stacks
(docs/DESIGN.md §16): rids minted at submit link submit -> dispatch ->
complete records across threads, every terminal outcome lands one
RequestLog summary, and a chaos-triggered flight-recorder bundle
carries one request's rid in all three places (RequestLog, flow
events, manifest) — the end-to-end correlation acceptance pin."""

import json
import os

import numpy as np
import pytest

from zookeeper_tpu.core import configure
from zookeeper_tpu.observability import trace
from zookeeper_tpu.observability import recorder as recorder_mod
from zookeeper_tpu.observability.recorder import FlightRecorder
from zookeeper_tpu.resilience import faults
from zookeeper_tpu.serving import (
    DeadlineExpiredError,
    InferenceEngine,
    MicroBatcher,
    RejectedError,
    ServingMetrics,
    WorkerCrashedError,
)

pytestmark = pytest.mark.serving

FEATURES = 6
CLASSES = 4


@pytest.fixture(scope="module")
def engine():
    from zookeeper_tpu.models.simple import Mlp

    model = Mlp()
    configure(model, {"hidden_units": (16,)}, name="model")
    module = model.build((FEATURES,), CLASSES)
    params, model_state = model.initialize(module, (FEATURES,))
    eng = InferenceEngine()
    configure(eng, {"batch_buckets": (1, 4, 8)}, name="engine")
    eng.bind(module.apply, params, model_state, (FEATURES,))
    eng.warmup()
    return eng


@pytest.fixture
def fresh_tracer():
    prior = trace.get_tracer()
    trace.install(trace.Tracer(4096))
    yield trace.get_tracer()
    trace.install(prior)


@pytest.fixture
def no_global_recorder():
    prior = recorder_mod.get_recorder()
    recorder_mod.uninstall()
    yield
    (
        recorder_mod.install(prior)
        if prior is not None
        else recorder_mod.uninstall()
    )


def make_batcher(engine, **conf):
    metrics = ServingMetrics()
    configure(metrics, {}, name="metrics")
    batcher = MicroBatcher()
    configure(batcher, dict(conf), name="batcher")
    batcher.bind(engine, metrics=metrics)
    return batcher, metrics


def wait_for_bundle(rec, kind, timeout=15.0):
    """Poll for a COMPLETE bundle of trigger ``kind`` (manifest last =
    complete, the recorder's finalize protocol): synchronous bundles
    for crash triggers are written by the crashing worker thread,
    which keeps running briefly after result() has already raised."""
    import time

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        for path in rec.bundles():
            manifest = os.path.join(path, "manifest.json")
            if os.path.exists(manifest):
                trigger = json.load(open(manifest))["trigger"]
                if trigger["kind"] == kind:
                    return path, trigger
        time.sleep(0.01)
    raise AssertionError(
        f"no complete {kind!r} bundle within {timeout}s: {rec.bundles()}"
    )


def flow_chain(rid):
    doc = trace.to_chrome_trace()
    chain = sorted(
        (
            e
            for e in doc["traceEvents"]
            if e.get("cat") == "rid" and e["id"] == rid
        ),
        key=lambda e: e["ts"],
    )
    names_by_rid = [
        e["name"]
        for e in doc["traceEvents"]
        if e.get("args", {}).get("rid") == rid
    ]
    threads = {}
    for e in doc["traceEvents"]:
        if e.get("ph") == "M" and e["name"] == "thread_name":
            threads[e["tid"]] = e["args"]["name"]
    return chain, names_by_rid, threads


def test_sync_rid_links_submit_dispatch_complete(engine, fresh_tracer):
    batcher, _ = make_batcher(engine)
    x = np.ones((3, FEATURES), np.float32)
    handle = batcher.submit(x)
    rid = handle.rid
    assert rid is not None
    out = handle.result()
    assert out.shape == (3, CLASSES)
    chain, names, _ = flow_chain(rid)
    assert [e["ph"] for e in chain] == ["s", "t", "f"]
    assert names == [
        "request_enqueue", "request_dispatch", "request_complete",
    ]
    # The RequestLog summary correlates on the same rid.
    rec = batcher.request_log.find(rid)
    assert rec["outcome"] == "ok"
    assert rec["rows"] == 3
    assert rec["bucket"] == 4
    assert rec["enqueue_ns"] <= rec["dispatch_ns"] <= rec["complete_ns"]
    assert rec["weights_step"] == -1  # bind-time weights


def test_async_rid_flow_crosses_into_microbatcher_thread(
    engine, fresh_tracer
):
    """The cross-thread pin: submit records on the caller thread,
    dispatch/complete on zk-microbatcher, one flow id across both."""
    batcher, _ = make_batcher(engine, synchronous=False, max_delay_ms=1.0)
    try:
        handles = [
            batcher.submit(np.ones((2, FEATURES), np.float32))
            for _ in range(3)
        ]
        for handle in handles:
            assert handle.result(timeout=30).shape == (2, CLASSES)
        for handle in handles:
            chain, names, threads = flow_chain(handle.rid)
            assert [e["ph"] for e in chain] == ["s", "t", "f"]
            assert threads[chain[0]["tid"]] != "zk-microbatcher"
            assert threads[chain[-1]["tid"]] == "zk-microbatcher"
            assert batcher.request_log.find(handle.rid)["outcome"] == "ok"
    finally:
        batcher.close()


def test_shed_and_deadline_outcomes_recorded(engine, fresh_tracer):
    batcher, metrics = make_batcher(engine, shed_above_rows=2)
    # Fill the queue past the shed threshold, then submit one more.
    first = batcher.submit(np.ones((2, FEATURES), np.float32))
    with pytest.raises(RejectedError):
        batcher.submit(np.ones((4, FEATURES), np.float32))
    shed = [
        r
        for r in batcher.request_log.tail()
        if r["outcome"] == "shed"
    ]
    assert len(shed) == 1 and shed[0]["rows"] == 4
    # Drain the queue (an empty queue always admits), then the
    # deadline leg: deadline_ms=0 is expiry-by-construction (the
    # clock-free chaos idiom).
    assert first.result().shape == (2, CLASSES)
    assert batcher.request_log.find(first.rid)["outcome"] == "ok"
    expired = batcher.submit(
        np.ones((1, FEATURES), np.float32), deadline_ms=0
    )
    with pytest.raises(DeadlineExpiredError):
        expired.result()
    rec = batcher.request_log.find(expired.rid)
    assert rec["outcome"] == "deadline_expired"
    assert rec["dispatch_ns"] is None  # never dispatched


@pytest.mark.chaos
def test_worker_crash_outcome_and_flow(engine, fresh_tracer):
    """FaultPlan.serving_worker_crash: the crashed requests' summaries
    say crashed, and their flow still links submit -> complete."""
    batcher, _ = make_batcher(engine, synchronous=False, max_delay_ms=1.0)
    try:
        with faults.injected(faults.FaultPlan(serving_worker_crash=1)):
            handle = batcher.submit(np.ones((2, FEATURES), np.float32))
            with pytest.raises(WorkerCrashedError):
                handle.result(timeout=30)
        rec = batcher.request_log.find(handle.rid)
        assert rec["outcome"] == "crashed"
        assert rec["detail"] == "WorkerCrashedError"
        chain, names, _ = flow_chain(handle.rid)
        assert [e["ph"] for e in chain] == ["s", "f"]
        assert names == ["request_enqueue", "request_complete"]
        # Crash cleanup restarts on the next submit: the follow-up is ok.
        retry = batcher.submit(np.ones((2, FEATURES), np.float32))
        assert retry.result(timeout=30).shape == (2, CLASSES)
        assert batcher.request_log.find(retry.rid)["outcome"] == "ok"
    finally:
        batcher.close()


@pytest.mark.chaos
def test_chaos_bundle_correlates_rid_in_all_three_places(
    engine, tmp_path, fresh_tracer, no_global_recorder
):
    """THE end-to-end correlation acceptance pin (ISSUE 10): a
    chaos-triggered bundle contains one request's rid in (1) the
    RequestLog summary with outcome=crashed, (2) the Chrome flow
    events linking its submit/dispatch records, and (3) sits beside
    the manifest's trigger record naming the crash."""
    batcher, metrics = make_batcher(
        engine, synchronous=False, max_delay_ms=1.0
    )
    rec = FlightRecorder(
        str(tmp_path / "bundles"),
        registries=[metrics.registry],
        request_logs={"serving": batcher.request_log},
        min_interval_s=0.0,
        synchronous=True,
    )
    recorder_mod.install(rec)
    try:
        with faults.injected(faults.FaultPlan(serving_worker_crash=1)):
            handle = batcher.submit(np.ones((3, FEATURES), np.float32))
            with pytest.raises(WorkerCrashedError):
                handle.result(timeout=30)
        rid = handle.rid
        # The crash produced (at least) the worker_crash bundle, fired
        # AFTER the requests were failed; the fault_injected bundle
        # rides alongside. Written by the crashing worker thread, so
        # poll for manifest-complete.
        bundle, _ = wait_for_bundle(rec, "worker_crash")
        # (1) RequestLog tail: outcome=crashed under this rid.
        requestlog = json.load(
            open(os.path.join(bundle, "requestlog.json"))
        )
        summary = [
            r
            for r in requestlog["serving"]["tail"]
            if r["rid"] == rid
        ]
        assert summary and summary[0]["outcome"] == "crashed"
        # (2) Chrome flow events linking the request's records.
        doc = json.load(open(os.path.join(bundle, "trace.json")))
        flow = sorted(
            (
                e
                for e in doc["traceEvents"]
                if e.get("cat") == "rid" and e["id"] == rid
            ),
            key=lambda e: e["ts"],
        )
        assert [e["ph"] for e in flow] == ["s", "f"]
        # (3) The manifest's trigger record names the crash.
        manifest = json.load(
            open(os.path.join(bundle, "manifest.json"))
        )
        assert manifest["trigger"]["kind"] == "worker_crash"
        assert manifest["trigger"]["attrs"]["error"] == "WorkerCrashedError"
    finally:
        recorder_mod.uninstall(rec)
        batcher.close()


# -- decode stack ---------------------------------------------------------


@pytest.fixture(scope="module")
def decode_pair():
    from zookeeper_tpu.serving.decode.metrics import DecodeMetrics

    from tests.serving.test_decode_engine import build_lm, make_engine

    module, params, state, _ = build_lm()
    eng = make_engine(module, params, state, slots=2, seq_buckets=(8,))
    eng.warmup()
    metrics = DecodeMetrics()
    configure(metrics, {}, name="metrics")
    return eng, metrics


def make_scheduler(decode_pair, **conf):
    from zookeeper_tpu.serving.decode import DecodeScheduler

    eng, metrics = decode_pair
    sched = DecodeScheduler()
    configure(sched, dict(conf), name="scheduler")
    sched.bind(eng, metrics=metrics)
    return sched


def test_decode_sync_rid_flow_and_summary(decode_pair, fresh_tracer):
    sched = make_scheduler(decode_pair)
    stream = sched.submit(
        np.arange(1, 5, dtype=np.int32), max_new_tokens=3
    )
    rid = stream.rid
    assert rid is not None
    tokens = stream.result()
    assert tokens.shape[0] == 3
    chain, names, _ = flow_chain(rid)
    assert [e["ph"] for e in chain] == ["s", "t", "f"]
    assert names == [
        "decode_request_enqueue",
        "decode_request_dispatch",
        "decode_stream_finish",
    ]
    rec = sched.request_log.find(rid)
    assert rec["outcome"] == "ok"
    assert rec["detail"] == "length"  # max_new_tokens finish reason
    assert rec["tokens"] == 3
    assert rec["slot"] is not None


def test_decode_async_rid_flow_crosses_into_worker(
    decode_pair, fresh_tracer
):
    sched = make_scheduler(decode_pair, synchronous=False)
    try:
        stream = sched.submit(
            np.arange(1, 4, dtype=np.int32), max_new_tokens=2
        )
        assert stream.result(timeout=30).shape[0] == 2
        chain, _, threads = flow_chain(stream.rid)
        assert [e["ph"] for e in chain] == ["s", "t", "f"]
        assert threads[chain[0]["tid"]] != "zk-decode-scheduler"
        assert threads[chain[-1]["tid"]] == "zk-decode-scheduler"
        assert sched.request_log.find(stream.rid)["outcome"] == "ok"
    finally:
        sched.close()


def test_decode_shed_and_deadline_summaries(decode_pair, fresh_tracer):
    sched = make_scheduler(decode_pair, shed_above=2)
    first = sched.submit(np.arange(1, 3, dtype=np.int32))
    second = sched.submit(np.arange(1, 3, dtype=np.int32))
    with pytest.raises(RejectedError):
        sched.submit(np.arange(1, 3, dtype=np.int32))
    shed = [
        r for r in sched.request_log.tail() if r["outcome"] == "shed"
    ]
    assert len(shed) == 1
    sched.drain()  # empty the queue: an empty queue always admits
    for stream in (first, second):
        stream.result()
        assert sched.request_log.find(stream.rid)["outcome"] == "ok"
    expired = sched.submit(
        np.arange(1, 3, dtype=np.int32), deadline_ms=0
    )
    with pytest.raises(DeadlineExpiredError):
        expired.result()
    assert (
        sched.request_log.find(expired.rid)["outcome"]
        == "deadline_expired"
    )


@pytest.mark.chaos
def test_decode_crash_bundle_correlates_rid(
    decode_pair, tmp_path, fresh_tracer, no_global_recorder
):
    """Decode half of the correlation pin: FaultPlan.decode_worker_crash
    -> bundle with the stream's rid in RequestLog (crashed), flow
    events, and the decode_worker_crash manifest."""
    eng, metrics = decode_pair
    sched = make_scheduler(decode_pair)
    rec = FlightRecorder(
        str(tmp_path / "bundles"),
        registries=[metrics.registry],
        request_logs={"decode": sched.request_log},
        min_interval_s=0.0,
        synchronous=True,
    )
    recorder_mod.install(rec)
    try:
        with faults.injected(faults.FaultPlan(decode_worker_crash=1)):
            stream = sched.submit(
                np.arange(1, 4, dtype=np.int32), max_new_tokens=2
            )
            with pytest.raises(WorkerCrashedError):
                stream.result()
        rid = stream.rid
        bundle, _ = wait_for_bundle(rec, "decode_worker_crash")
        requestlog = json.load(
            open(os.path.join(bundle, "requestlog.json"))
        )
        summary = [
            r
            for r in requestlog["decode"]["tail"]
            if r["rid"] == rid
        ]
        assert summary and summary[0]["outcome"] == "crashed"
        doc = json.load(open(os.path.join(bundle, "trace.json")))
        flow = [
            e
            for e in doc["traceEvents"]
            if e.get("cat") == "rid" and e["id"] == rid
        ]
        assert {e["ph"] for e in flow} == {"s", "f"}
    finally:
        recorder_mod.uninstall(rec)
        sched.close()


def test_statusz_requests_section_renders(engine, no_global_recorder):
    """ServingConfig exposes the RequestLog as a /statusz section and
    arms the flight recorder from config (flight_recorder_dir=)."""
    import tempfile
    import urllib.request

    from zookeeper_tpu.serving import ServingConfig

    with tempfile.TemporaryDirectory() as tmp:
        svc = ServingConfig()
        configure(
            svc,
            {
                "model": "Mlp",
                "model.hidden_units": (8,),
                "height": 4,
                "width": 4,
                "channels": 1,
                "num_classes": 3,
                "engine.batch_buckets": (1, 4),
                "verbose": False,
                "metrics_port": 0,
                "flight_recorder_dir": os.path.join(tmp, "bundles"),
            },
            name="svc_requests_statusz",
        )
        engine2, batcher = svc.build_service()
        try:
            batcher.submit(np.zeros((2, 4, 4, 1), np.float32)).result()
            with urllib.request.urlopen(
                f"http://127.0.0.1:{svc.obs_server.port}/statusz",
                timeout=10,
            ) as resp:
                statusz = json.loads(resp.read().decode())
            requests_section = statusz["requests"]
            assert requests_section["recorded_total"] == 1
            assert requests_section["tail"][0]["outcome"] == "ok"
            assert statusz["flight_recorder"]["installed"] is True
            # Manual POST /debugz writes a bundle via the config-armed
            # recorder.
            req = urllib.request.Request(
                f"http://127.0.0.1:{svc.obs_server.port}/debugz",
                data=b"",
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=10) as resp:
                body = json.loads(resp.read().decode())
            assert os.path.isdir(body["bundle"])
        finally:
            svc._teardown_service(suppress=True)
        # Teardown disarms the global recorder.
        assert recorder_mod.get_recorder() is None
