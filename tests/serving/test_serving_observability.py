"""Serving observability: request-lifecycle host spans (enqueue →
coalesce/dispatch → complete) and the ServingConfig /metrics +
/statusz endpoint over a real socket."""

import json
import re
import urllib.request

import numpy as np
import pytest

from zookeeper_tpu.core import configure
from zookeeper_tpu.observability import trace
from zookeeper_tpu.serving import ServingConfig

pytestmark = pytest.mark.serving

PROM_SAMPLE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? [^ ]+$"
)


@pytest.fixture(autouse=True)
def _clean_tracer():
    trace.disable()
    yield
    trace.disable()


def make_service(extra=None):
    svc = ServingConfig()
    conf = {
        "model": "Mlp",
        "model.hidden_units": (8,),
        "height": 4,
        "width": 4,
        "channels": 1,
        "num_classes": 3,
        "engine.batch_buckets": (1, 4),
        "requests": 6,
        "max_request": 4,
        "verbose": False,
        **(extra or {}),
    }
    configure(svc, conf, name="serve_obs")
    return svc


def test_request_lifecycle_spans(tmp_path):
    """One serving request's full lifecycle lands on the host
    timeline: enqueue event → serve_dispatch span (with coalescing
    attribution) → engine_infer span → per-request complete event."""
    tracer = trace.enable(4096)
    svc = make_service()
    engine, batcher = svc.build_service()
    h1 = batcher.submit(np.zeros((3, 4, 4, 1), np.float32))
    h2 = batcher.submit(np.ones((2, 4, 4, 1), np.float32))
    h1.result()
    h2.result()
    records = tracer.snapshot()
    by_name = {}
    for r in records:
        by_name.setdefault(r["name"], []).append(r)
    assert len(by_name["request_enqueue"]) == 2
    assert by_name["request_enqueue"][0]["attrs"]["rows"] == 3
    # Both requests coalesced: dispatches cover 5 rows over 2 requests
    # (row-granular FIFO packing into the size-4 bucket).
    dispatches = by_name["serve_dispatch"]
    assert sum(d["attrs"]["rows"] for d in dispatches) == 5
    assert any(d["attrs"]["requests"] == 2 for d in dispatches)
    infers = by_name["engine_infer"]
    assert all(i["attrs"]["bucket"] in (1, 4) for i in infers)
    completes = by_name["request_complete"]
    assert len(completes) == 2
    assert all(c["attrs"]["error"] is None for c in completes)
    assert all(c["attrs"]["latency_ms"] >= 0 for c in completes)
    batcher.close()


def test_shed_and_deadline_events():
    trace.enable(1024)
    svc = make_service({"batcher.shed_above_rows": 2})
    engine, batcher = svc.build_service()
    from zookeeper_tpu.serving import DeadlineExpiredError, RejectedError

    # Deadline leg first (an empty queue always admits): deadline_ms=0
    # is expired-by-construction; result() drains and fails it.
    expired = batcher.submit(
        np.zeros((1, 4, 4, 1), np.float32), deadline_ms=0
    )
    with pytest.raises(DeadlineExpiredError):
        expired.result()
    # Shed leg: fill the queue past the threshold, next submit sheds.
    batcher.submit(np.zeros((2, 4, 4, 1), np.float32))
    with pytest.raises(RejectedError):
        batcher.submit(np.zeros((2, 4, 4, 1), np.float32))
    names = [r["name"] for r in trace.get_tracer().snapshot()]
    assert "request_shed" in names
    assert "request_deadline_expired" in names
    batcher.close()


def test_serving_metrics_endpoint_end_to_end():
    """The CI smoke, as a tier-1 pin: metrics_port=0 serves every
    registered ServingMetrics series in valid Prometheus text, and
    /statusz reports the serving vitals."""
    svc = make_service({"metrics_port": 0})
    engine, batcher = svc.build_service()
    batcher.submit(np.zeros((3, 4, 4, 1), np.float32)).result()
    port = svc.obs_server.port
    body = (
        urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics")
        .read()
        .decode()
    )
    samples = [
        line
        for line in body.splitlines()
        if line and not line.startswith("#")
    ]
    assert samples and all(PROM_SAMPLE.match(s) for s in samples), samples
    for inst in svc.metrics.registry.collect():
        assert inst.name in body
    assert "zk_serving_requests 1" in body
    assert "zk_serving_rows 3" in body
    assert 'zk_serving_latency_ms_bucket{le="+Inf"} 1' in body
    status = json.loads(
        urllib.request.urlopen(f"http://127.0.0.1:{port}/statusz").read()
    )
    assert status["serving"]["model"] == "Mlp"
    assert status["serving"]["batch_buckets"] == [1, 4]
    assert status["serving"]["serving_weights_step"] == -1
    # finish_report tears the endpoint down.
    svc.finish_report(
        warm_compiles=engine.compile_count, n_requests=1, dt=0.1
    )
    assert getattr(svc, "obs_server", None) is None
    with pytest.raises(Exception):
        urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=2
        )


def test_run_with_metrics_port_smokes():
    """ServingConfig.run() (the demo/bench driver) with the endpoint on:
    the whole loop works and tears down clean."""
    svc = make_service({"metrics_port": 0})
    result = svc.run()
    assert result["requests"] == 6
    assert getattr(svc, "obs_server", None) is None
