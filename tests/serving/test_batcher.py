"""MicroBatcher correctness: the acceptance pin — results for N
concurrent heterogeneous requests are BIT-identical to unbatched
single-request forwards, padding sliced away, including the
oversized-split and partial-bucket paths, in deterministic synchronous
mode (no threads, no clocks). Async/threaded behavior is exercised in
the slow-marked tests at the bottom."""

import numpy as np
import pytest

from zookeeper_tpu.core import configure
from zookeeper_tpu.serving import InferenceEngine, MicroBatcher, ServingMetrics

pytestmark = pytest.mark.serving

FEATURES = 6
CLASSES = 4


@pytest.fixture(scope="module")
def engine():
    from zookeeper_tpu.models.simple import Mlp

    model = Mlp()
    configure(model, {"hidden_units": (16,)}, name="model")
    module = model.build((FEATURES,), CLASSES)
    params, model_state = model.initialize(module, (FEATURES,))
    eng = InferenceEngine()
    configure(eng, {"batch_buckets": (1, 4, 8)}, name="engine")
    eng.bind(module.apply, params, model_state, (FEATURES,))
    eng.warmup()
    return eng


def make_batcher(engine, metrics=False, **conf):
    m = None
    if metrics:
        m = ServingMetrics()
        configure(m, {}, name="metrics")
    b = MicroBatcher()
    configure(b, dict(conf), name="batcher")
    b.bind(engine, metrics=m)
    return b, m


def reference(engine, x):
    """Unbatched single-request forward (chunked only when the request
    itself exceeds the largest bucket)."""
    step = engine.max_batch
    return np.concatenate(
        [
            np.asarray(engine.infer(x[i : i + step]))
            for i in range(0, x.shape[0], step)
        ]
    )


def test_concurrent_heterogeneous_requests_bit_identical(engine):
    """The headline acceptance test: heterogeneous sizes, including an
    OVERSIZED request (> max bucket, split over dispatches) and a final
    PARTIAL bucket, all bit-identical to single-request forwards."""
    rng = np.random.default_rng(0)
    sizes = [3, 1, 11, 4, 2, 7, 1, 5]  # 11 > max_batch=8: oversized
    requests = [
        rng.normal(size=(n, FEATURES)).astype(np.float32) for n in sizes
    ]
    batcher, metrics = make_batcher(engine, metrics=True)
    before = engine.compile_count
    handles = [batcher.submit(x) for x in requests]
    batcher.flush()
    for x, handle in zip(requests, handles):
        got = handle.result()
        assert got.shape == (x.shape[0], CLASSES)
        assert np.array_equal(got, reference(engine, x))
    assert engine.compile_count == before  # warmed buckets: no compiles
    totals = metrics.totals
    assert totals["requests"] == len(sizes)
    assert totals["rows"] == sum(sizes)
    # 34 rows coalesce into ceil(34/8)=5 dispatches (FIFO row packing).
    assert totals["dispatches"] == 5


def test_partial_bucket_path(engine):
    """A queue draining below the largest bucket pads into the smallest
    covering bucket — and the result is still exact."""
    rng = np.random.default_rng(1)
    x = rng.normal(size=(3, FEATURES)).astype(np.float32)
    batcher, metrics = make_batcher(engine, metrics=True)
    handle = batcher.submit(x)
    batcher.flush()
    assert np.array_equal(handle.result(), reference(engine, x))
    snap = metrics.snapshot()
    # 3 real rows rode the 4-bucket: fill 0.75, waste 0.25.
    assert snap["bucket_fill_mean"] == pytest.approx(0.75)
    assert snap["padding_waste_mean"] == pytest.approx(0.25)


def test_oversized_request_split_exact(engine):
    """A single request far above the largest bucket splits across
    dispatches and reassembles in row order."""
    rng = np.random.default_rng(2)
    x = rng.normal(size=(29, FEATURES)).astype(np.float32)  # 8+8+8+5
    batcher, metrics = make_batcher(engine, metrics=True)
    handle = batcher.submit(x)
    batcher.flush()
    got = handle.result()
    assert got.shape == (29, CLASSES)
    assert np.array_equal(got, reference(engine, x))
    assert metrics.totals["dispatches"] == 4


def test_result_triggers_flush_synchronously(engine):
    """Deterministic sync mode needs no explicit flush: result() IS the
    trigger (thread- and clock-free)."""
    rng = np.random.default_rng(3)
    a = rng.normal(size=(2, FEATURES)).astype(np.float32)
    b = rng.normal(size=(5, FEATURES)).astype(np.float32)
    batcher, _ = make_batcher(engine)
    ha, hb = batcher.submit(a), batcher.submit(b)
    assert not ha.done and not hb.done
    got_a = ha.result()  # flushes the whole queue
    assert hb.done
    assert np.array_equal(got_a, reference(engine, a))
    assert np.array_equal(hb.result(), reference(engine, b))


def test_fifo_coalescing_fills_largest_bucket(engine):
    """Pending rows >= the largest bucket coalesce into FULL largest-
    bucket dispatches (the throughput contract)."""
    rng = np.random.default_rng(4)
    requests = [
        rng.normal(size=(4, FEATURES)).astype(np.float32) for _ in range(4)
    ]
    batcher, metrics = make_batcher(engine, metrics=True)
    handles = [batcher.submit(x) for x in requests]
    batcher.flush()
    for x, h in zip(requests, handles):
        assert np.array_equal(h.result(), reference(engine, x))
    snap = metrics.snapshot()
    assert metrics.totals["dispatches"] == 2  # 16 rows = 2 full 8-buckets
    assert snap["bucket_fill_mean"] == pytest.approx(1.0)
    assert snap["padding_waste_mean"] == pytest.approx(0.0)


def test_queue_full_backpressure_drains_inline(engine):
    """Sync-mode backpressure: a submit that would exceed max_queue_rows
    drains the backlog inline instead of growing it — the queue never
    holds more than max_queue_rows + one request."""
    rng = np.random.default_rng(5)
    batcher, _ = make_batcher(engine, max_queue_rows=8)
    handles = []
    max_seen = 0
    for _ in range(10):
        x = rng.normal(size=(3, FEATURES)).astype(np.float32)
        handles.append((x, batcher.submit(x)))
        max_seen = max(max_seen, batcher.queue_rows)
    assert max_seen <= 8 + 3
    # Earlier requests were already served by the inline drains.
    assert sum(1 for _, h in handles if h.done) >= 7
    batcher.flush()
    for x, h in handles:
        assert np.array_equal(h.result(), reference(engine, x))


def test_bad_request_shapes_rejected(engine):
    batcher, _ = make_batcher(engine)
    with pytest.raises(ValueError, match="at least one row"):
        batcher.submit(np.zeros((0, FEATURES), np.float32))
    with pytest.raises(RuntimeError, match="not bound"):
        MicroBatcher().submit(np.zeros((1, FEATURES), np.float32))


def test_failed_dispatch_propagates_to_requests(engine):
    """An engine failure surfaces through every affected handle instead
    of hanging it."""
    batcher, _ = make_batcher(engine)
    bad = np.zeros((2, FEATURES + 1), np.float32)  # wrong feature width
    handle = batcher.submit(bad)
    with pytest.raises(Exception):
        batcher.flush()
    with pytest.raises(Exception):
        handle.result()


def test_bind_validates_config(engine):
    b = MicroBatcher()
    configure(b, {"max_queue_rows": 0}, name="batcher")
    with pytest.raises(ValueError, match="max_queue_rows"):
        b.bind(engine)
    b2 = MicroBatcher()
    configure(b2, {"max_delay_ms": -1.0}, name="batcher2")
    with pytest.raises(ValueError, match="max_delay_ms"):
        b2.bind(engine)


# -- threaded paths (excluded from tier-1: markers below) ----------------


@pytest.mark.slow
def test_async_mode_serves_and_matches(engine):
    """Async worker: results match sync references; close() is clean."""
    rng = np.random.default_rng(6)
    batcher, _ = make_batcher(
        engine, synchronous=False, max_delay_ms=5.0
    )
    try:
        requests = [
            rng.normal(
                size=(int(rng.integers(1, 10)), FEATURES)
            ).astype(np.float32)
            for _ in range(16)
        ]
        handles = [batcher.submit(x) for x in requests]
        for x, h in zip(requests, handles):
            assert np.array_equal(
                h.result(timeout=30), reference(engine, x)
            )
    finally:
        batcher.close()


@pytest.mark.slow
def test_qps_soak_async(engine):
    """QPS soak: sustained concurrent submitters against the async
    worker — every result exact, queue bounded by backpressure."""
    import threading

    rng = np.random.default_rng(7)
    metrics = ServingMetrics()
    configure(metrics, {}, name="metrics")
    batcher = MicroBatcher()
    configure(
        batcher,
        {"synchronous": False, "max_delay_ms": 1.0, "max_queue_rows": 64},
        name="batcher",
    )
    batcher.bind(engine, metrics=metrics)
    failures = []

    def client(seed):
        r = np.random.default_rng(seed)
        for _ in range(25):
            x = r.normal(
                size=(int(r.integers(1, 12)), FEATURES)
            ).astype(np.float32)
            got = batcher.submit(x).result(timeout=60)
            if not np.array_equal(got, reference(engine, x)):
                failures.append(seed)

    threads = [
        threading.Thread(target=client, args=(s,)) for s in range(4)
    ]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
    finally:
        batcher.close()
    assert not failures
    totals = metrics.totals
    assert totals["requests"] == 100
    snap = metrics.snapshot()
    assert snap["latency_p99_ms"] >= snap["latency_p50_ms"] >= 0.0
