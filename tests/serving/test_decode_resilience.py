"""Decode-path chaos certification (FaultPlan-driven, deterministic):
scheduler crash fails every in-flight STREAM and queued request cleanly
(`WorkerCrashedError`, no result() ever hangs) and restarts on the next
submit; weight hot-swap mid-decode keeps each in-flight sequence on one
weight version (the drain-boundary contract under fault pressure)."""

import numpy as np
import pytest

from zookeeper_tpu.core import configure
from zookeeper_tpu.resilience import FaultPlan, faults
from zookeeper_tpu.serving import WorkerCrashedError
from zookeeper_tpu.serving.decode import DecodeMetrics, DecodeScheduler

from tests.serving.test_decode_engine import (
    VOCAB,
    build_lm,
    make_engine,
    oracle,
)

pytestmark = [pytest.mark.serving, pytest.mark.chaos]


@pytest.fixture(scope="module")
def lm():
    return build_lm()


@pytest.fixture(scope="module")
def warm_engine(lm):
    module, params, state, _ = lm
    engine = make_engine(module, params, state, slots=2)
    engine.warmup()
    return engine


def make_sched(engine, **conf):
    m = DecodeMetrics()
    configure(m, {}, name="metrics")
    s = DecodeScheduler()
    configure(s, dict(conf), name="sched")
    s.bind(engine, metrics=m)
    return s, m


def test_injected_crash_fails_streams_clean_and_restarts(lm, warm_engine):
    """Sync mode: an injected loop crash fails the in-flight stream AND
    the queued one with WorkerCrashedError (partial tokens readable),
    then the scheduler serves normally again — the continuous-batching
    analogue of the MicroBatcher worker-death leg."""
    module, _, _, variables = lm
    sched, m = make_sched(warm_engine)
    p1 = np.arange(1, 6, dtype=np.int32)
    p2 = np.arange(2, 7, dtype=np.int32)
    in_flight = sched.submit(p1, max_new_tokens=6)
    sched._pump()  # prefill landed: one token already streamed
    assert in_flight.tokens_so_far.shape[0] >= 1
    queued1 = sched.submit(p2, max_new_tokens=4)
    queued2 = sched.submit(p2, max_new_tokens=4)
    with faults.injected(FaultPlan(decode_worker_crash=1)):
        with pytest.raises(WorkerCrashedError):
            sched.drain()
    for stream in (in_flight, queued1, queued2):
        assert stream.done
        with pytest.raises(WorkerCrashedError):
            stream.result()
    # Partial output of the in-flight stream is real output.
    partial = in_flight.tokens_so_far
    assert partial.shape[0] >= 1
    np.testing.assert_array_equal(
        partial, oracle(module, variables, p1, partial.shape[0])
    )
    assert m.totals["worker_restarts_total"] == 1
    assert sched.active_slots == 0 and sched.queue_depth == 0
    # The restarted scheduler serves token-exact, zero new compiles.
    warm = warm_engine.compile_count
    out = sched.generate(p1, max_new_tokens=5)
    np.testing.assert_array_equal(out, oracle(module, variables, p1, 5))
    assert warm_engine.compile_count == warm


def test_async_worker_crash_restarts_on_next_submit(lm, warm_engine):
    """Async mode: the worker THREAD dies on the injected crash; every
    pending stream fails (never hangs), and the next submit starts a
    fresh worker that serves normally."""
    module, _, _, variables = lm
    sched, m = make_sched(warm_engine, synchronous=False)
    try:
        p = np.arange(1, 5, dtype=np.int32)
        with faults.injected(FaultPlan(decode_worker_crash=1)):
            doomed = sched.submit(p, max_new_tokens=8)
            with pytest.raises(WorkerCrashedError):
                doomed.result(timeout=120)
        assert m.totals["worker_restarts_total"] == 1
        revived = sched.submit(p, max_new_tokens=4)
        np.testing.assert_array_equal(
            revived.result(timeout=120), oracle(module, variables, p, 4)
        )
    finally:
        sched.close()


def test_crash_keeps_kv_isolation_across_restart(lm, warm_engine):
    """After a crash mid-stream, the next occupant of the same slot is
    unaffected by the dead stream's cache rows (the validity invariant:
    prefill + masking make stale rows invisible)."""
    module, _, _, variables = lm
    sched, _ = make_sched(warm_engine)
    long_prompt = np.arange(1, 16, dtype=np.int32)
    victim = sched.submit(long_prompt, max_new_tokens=16)
    sched._pump()
    sched._pump()  # several KV rows written beyond any short prompt
    with faults.injected(FaultPlan(decode_worker_crash=1)):
        with pytest.raises(WorkerCrashedError):
            sched.drain()
    assert victim.done
    short = np.array([7, 3], np.int32)
    np.testing.assert_array_equal(
        sched.generate(short, max_new_tokens=6),
        oracle(module, variables, short, 6),
    )


def test_hot_swap_mid_decode_one_weight_version_per_stream(lm):
    """The chaos-leg restatement of the swap contract: a swap staged
    while streams are mid-decode applies only at the drain boundary —
    in-flight sequences finish bit-exact on their ORIGINAL weights even
    though the swap request landed between their dispatches."""
    module, params, state, variables = lm
    _, params_b, state_b, variables_b = build_lm(seed=23)
    engine = make_engine(module, params, state, slots=2)
    warm = engine.warmup()
    sched, m = make_sched(engine)
    rng = np.random.default_rng(9)
    p1 = rng.integers(1, VOCAB, size=6).astype(np.int32)
    p2 = rng.integers(1, VOCAB, size=9).astype(np.int32)
    s1 = sched.submit(p1, max_new_tokens=8)
    s2 = sched.submit(p2, max_new_tokens=5)
    sched._pump()
    sched._pump()  # both streams mid-decode
    sched.request_swap(params_b, state_b, step=7)
    sched._pump()  # swap must NOT apply: slots are occupied
    assert sched.swap_pending
    post = sched.submit(p1, max_new_tokens=5)  # admitted only post-swap
    sched.drain()
    assert not sched.swap_pending
    np.testing.assert_array_equal(s1.result(), oracle(module, variables, p1, 8))
    np.testing.assert_array_equal(s2.result(), oracle(module, variables, p2, 5))
    np.testing.assert_array_equal(
        post.result(), oracle(module, variables_b, p1, 5)
    )
    assert engine.compile_count == warm  # swap never recompiles
    assert m.totals["weight_swaps_total"] == 1


def test_crash_with_swap_pending_preserves_staged_swap(lm):
    """A crash while a swap is staged: streams fail clean, the staged
    swap survives and applies before the next admission, so post-crash
    streams run on the NEW weights."""
    module, params, state, variables = lm
    _, params_b, state_b, variables_b = build_lm(seed=23)
    engine = make_engine(module, params, state, slots=2)
    engine.warmup()
    sched, _ = make_sched(engine)
    p = np.arange(1, 7, dtype=np.int32)
    victim = sched.submit(p, max_new_tokens=8)
    sched._pump()
    sched.request_swap(params_b, state_b)
    with faults.injected(FaultPlan(decode_worker_crash=1)):
        with pytest.raises(WorkerCrashedError):
            sched.drain()
    assert victim.done and sched.swap_pending
    out = sched.generate(p, max_new_tokens=4)
    np.testing.assert_array_equal(out, oracle(module, variables_b, p, 4))
    assert not sched.swap_pending


def test_dispatch_failure_resets_cache_and_serves_resubmits(lm):
    """A failure of the compiled call ITSELF (transient device/runtime
    error at execute time, after donation consumed the KV buffers):
    streams fail clean like any crash, and the engine restores a usable
    cache — resubmits on the restarted scheduler serve token-exact with
    zero new compiles instead of dying on deleted arrays."""
    module, params, state, variables = lm
    engine = make_engine(module, params, state, slots=2)
    engine.warmup()
    warm = engine.compile_count
    sched, _ = make_sched(engine)
    key = ("decode_step", engine._partitioner.mesh)
    real = engine._compiled_cache[key]

    def dying(variables_, cache, tokens, lengths):
        real(variables_, cache, tokens, lengths)  # donation happens
        raise RuntimeError("injected dispatch-time device failure")

    engine._compiled_cache[key] = dying
    p = np.arange(1, 6, dtype=np.int32)
    doomed = sched.submit(p, max_new_tokens=4)
    # Sync drain re-raises the ORIGINAL dispatch error (the streams
    # carry the WorkerCrashedError wrapper).
    with pytest.raises(RuntimeError, match="injected dispatch-time"):
        sched.drain()
    with pytest.raises(WorkerCrashedError):
        doomed.result()
    engine._compiled_cache[key] = real
    revived = sched.submit(p, max_new_tokens=4)
    sched.drain()
    np.testing.assert_array_equal(
        revived.result(), oracle(module, variables, p, 4)
    )
    assert engine.compile_count == warm
