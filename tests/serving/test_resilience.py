"""Serving resilience: deadlines, load shedding, worker-death cleanup +
restart, and drain-or-fail close — every leg driven deterministically
(sync-mode tests are clock-free; async tests inject the crash via
FaultPlan and assert on completion events, not timing)."""

import time

import numpy as np
import pytest

from zookeeper_tpu.core import configure
from zookeeper_tpu.resilience import FaultPlan, faults
from zookeeper_tpu.serving import (
    DeadlineExpiredError,
    InferenceEngine,
    MicroBatcher,
    RejectedError,
    ServingMetrics,
    WorkerCrashedError,
)

pytestmark = [pytest.mark.serving, pytest.mark.chaos]

FEATURES = 6
CLASSES = 4


@pytest.fixture(scope="module")
def engine():
    from zookeeper_tpu.models.simple import Mlp

    model = Mlp()
    configure(model, {"hidden_units": (16,)}, name="model")
    module = model.build((FEATURES,), CLASSES)
    params, model_state = model.initialize(module, (FEATURES,))
    eng = InferenceEngine()
    configure(eng, {"batch_buckets": (1, 4, 8)}, name="engine")
    eng.bind(module.apply, params, model_state, (FEATURES,))
    eng.warmup()
    return eng


def make_batcher(engine, **conf):
    m = ServingMetrics()
    configure(m, {}, name="metrics")
    b = MicroBatcher()
    configure(b, dict(conf), name="batcher")
    b.bind(engine, metrics=m)
    return b, m


def req(rng, n=2):
    return rng.normal(size=(n, FEATURES)).astype(np.float32)


def reference(engine, x):
    step = engine.max_batch
    return np.concatenate(
        [
            np.asarray(engine.infer(x[i : i + step]))
            for i in range(0, x.shape[0], step)
        ]
    )


# -- load shedding --------------------------------------------------------


def test_shed_rejects_over_threshold_sync(engine):
    rng = np.random.default_rng(0)
    batcher, metrics = make_batcher(engine, shed_above_rows=4)
    kept = batcher.submit(req(rng, 3))
    with pytest.raises(RejectedError, match="shed"):
        batcher.submit(req(rng, 3))
    # The shed submit was never enqueued; the admitted one still serves.
    assert batcher.queue_rows == 3
    batcher.flush()
    assert kept.result().shape == (3, CLASSES)
    assert metrics.totals["rejected"] == 1
    assert metrics.totals["requests"] == 1


def test_shed_always_admits_into_empty_queue(engine):
    """An oversized single request must stay servable: shedding never
    rejects into an empty queue."""
    rng = np.random.default_rng(1)
    batcher, metrics = make_batcher(engine, shed_above_rows=4)
    h = batcher.submit(req(rng, 11))  # > threshold AND > max bucket
    batcher.flush()
    assert h.result().shape == (11, CLASSES)
    assert metrics.totals["rejected"] == 0


def test_shed_async_rejects_without_blocking(engine):
    rng = np.random.default_rng(2)
    batcher, metrics = make_batcher(
        engine, synchronous=False, shed_above_rows=4, max_delay_ms=60000.0
    )
    try:
        kept = batcher.submit(req(rng, 3))
        t0 = time.perf_counter()
        with pytest.raises(RejectedError):
            batcher.submit(req(rng, 3))
        assert time.perf_counter() - t0 < 1.0  # shed, not backpressured
        assert metrics.totals["rejected"] == 1
        batcher.flush()
        assert kept.result(timeout=30).shape == (3, CLASSES)
    finally:
        batcher.close()


def test_shed_validates_config(engine):
    b = MicroBatcher()
    configure(b, {"shed_above_rows": -1}, name="b")
    with pytest.raises(ValueError, match="shed_above_rows"):
        b.bind(engine)


# -- deadlines ------------------------------------------------------------


def test_deadline_expired_request_never_served_sync(engine):
    """deadline_ms=0 is expiry-by-construction (clock-free determinism):
    the request fails at dispatch planning, neighbors still serve."""
    rng = np.random.default_rng(3)
    batcher, metrics = make_batcher(engine)
    doomed = batcher.submit(req(rng, 2), deadline_ms=0)
    x_alive = req(rng, 2)
    alive = batcher.submit(x_alive)
    batcher.flush()
    with pytest.raises(DeadlineExpiredError):
        doomed.result()
    assert np.array_equal(alive.result(), reference(engine, x_alive))
    assert metrics.totals["deadline_expired"] == 1
    assert metrics.totals["requests"] == 1  # only the served one counts


def test_default_deadline_field_applies(engine):
    rng = np.random.default_rng(4)
    batcher, metrics = make_batcher(engine, default_deadline_ms=0.0)
    # Field value 0 = disabled: requests serve normally.
    h = batcher.submit(req(rng, 2))
    batcher.flush()
    assert h.result().shape == (2, CLASSES)

    batcher2, metrics2 = make_batcher(engine, default_deadline_ms=0.001)
    doomed = batcher2.submit(req(rng, 2))
    time.sleep(0.002)  # let the (tiny) default deadline lapse
    batcher2.flush()
    with pytest.raises(DeadlineExpiredError):
        doomed.result()
    assert metrics2.totals["deadline_expired"] == 1


def test_result_never_blocks_past_deadline_async(engine):
    """The acceptance pin: a stalled worker (coalescing window held open
    for 60s) cannot make result() wait past the request deadline."""
    rng = np.random.default_rng(5)
    batcher, metrics = make_batcher(
        engine, synchronous=False, max_delay_ms=60000.0
    )
    try:
        t0 = time.perf_counter()
        h = batcher.submit(req(rng, 2), deadline_ms=50)
        with pytest.raises(DeadlineExpiredError):
            h.result()  # timeout=None: bounded by the deadline alone
        assert time.perf_counter() - t0 < 10.0
        assert metrics.totals["deadline_expired"] == 1
    finally:
        batcher.close()


def test_deadline_with_explicit_timeout_uses_sooner(engine):
    rng = np.random.default_rng(6)
    batcher, _ = make_batcher(
        engine, synchronous=False, max_delay_ms=60000.0
    )
    try:
        h = batcher.submit(req(rng, 2), deadline_ms=50)
        with pytest.raises(DeadlineExpiredError):
            h.result(timeout=30)  # deadline (50ms) < timeout (30s)
    finally:
        batcher.close()


def test_negative_deadline_rejected(engine):
    batcher, _ = make_batcher(engine)
    with pytest.raises(ValueError, match="deadline_ms"):
        batcher.submit(np.zeros((1, FEATURES), np.float32), deadline_ms=-1)


# -- worker death ---------------------------------------------------------


def test_worker_crash_fails_pending_and_restarts(engine):
    """The PendingResult-hang fix + restart leg: an injected worker
    crash fails every queued request promptly (result(timeout=None)
    raises instead of hanging forever), counts a restart, and the next
    submit serves on a fresh worker."""
    rng = np.random.default_rng(7)
    batcher, metrics = make_batcher(
        engine, synchronous=False, max_delay_ms=1.0
    )
    try:
        with faults.injected(FaultPlan(serving_worker_crash=1)):
            x = req(rng, 2)
            h = batcher.submit(x)
            # Wait on COMPLETION (event), not timing: the crash handler
            # must have failed the request.
            for _ in range(1000):
                if h.done:
                    break
                time.sleep(0.005)
            assert h.done
            with pytest.raises(WorkerCrashedError):
                h.result()  # timeout=None — hung forever before the fix
            assert metrics.totals["worker_restarts"] == 1
            # Fresh worker serves the retry bit-identically.
            x2 = req(rng, 3)
            h2 = batcher.submit(x2)
            assert np.array_equal(
                h2.result(timeout=30), reference(engine, x2)
            )
            assert metrics.totals["worker_restarts"] == 1  # no re-crash
    finally:
        batcher.close()


def test_worker_crash_fails_many_queued_requests(engine):
    """Deterministic many-queued crash: the worker is held un-started
    (a stand-in thread object) while 5 requests queue, then the real
    worker starts, crashes on its first iteration, and ALL 5 fail."""
    import types

    rng = np.random.default_rng(8)
    batcher, metrics = make_batcher(
        engine, synchronous=False, max_delay_ms=1.0
    )
    try:
        with faults.injected(FaultPlan(serving_worker_crash=1)):
            object.__setattr__(
                batcher,
                "_worker",
                types.SimpleNamespace(is_alive=lambda: True),
            )
            handles = [batcher.submit(req(rng, 1)) for _ in range(5)]
            assert batcher.queue_rows == 5  # nothing dispatched yet
            object.__setattr__(batcher, "_worker", None)
            batcher._ensure_worker()  # real worker: crashes immediately
            for h in handles:
                with pytest.raises(WorkerCrashedError):
                    h.result(timeout=30)
        assert metrics.totals["worker_restarts"] == 1
        assert batcher.queue_rows == 0
    finally:
        batcher.close()


def test_engine_error_does_not_kill_worker(engine):
    """An engine failure is a per-request error, not a worker death:
    the SAME worker keeps serving (no restart counted)."""
    rng = np.random.default_rng(9)
    batcher, metrics = make_batcher(
        engine, synchronous=False, max_delay_ms=1.0
    )
    try:
        bad = batcher.submit(np.zeros((2, FEATURES + 1), np.float32))
        with pytest.raises(Exception):
            bad.result(timeout=30)
        x = req(rng, 2)
        good = batcher.submit(x)
        assert np.array_equal(
            good.result(timeout=30), reference(engine, x)
        )
        assert metrics.totals["worker_restarts"] == 0
    finally:
        batcher.close()


# -- close: drain or fail -------------------------------------------------


def test_close_without_drain_fails_pending(engine):
    rng = np.random.default_rng(10)
    batcher, _ = make_batcher(engine)
    h = batcher.submit(req(rng, 2))
    batcher.close()
    with pytest.raises(RuntimeError, match="closed with requests pending"):
        h.result()


def test_close_drain_serves_pending_sync_and_async(engine):
    rng = np.random.default_rng(11)
    for conf in ({}, {"synchronous": False, "max_delay_ms": 1.0}):
        batcher, _ = make_batcher(engine, **conf)
        x = req(rng, 3)
        h = batcher.submit(x)
        batcher.close(drain=True)
        assert np.array_equal(h.result(timeout=30), reference(engine, x))


def test_close_idempotent_and_unbound_safe(engine):
    MicroBatcher().close()  # unbound: no-op
    batcher, _ = make_batcher(engine)
    batcher.close()
    batcher.close(drain=True)


# -- metrics surface ------------------------------------------------------


def test_resilience_counters_in_snapshot(engine):
    rng = np.random.default_rng(12)
    batcher, metrics = make_batcher(engine, shed_above_rows=2)
    batcher.submit(req(rng, 2), deadline_ms=0)
    with pytest.raises(RejectedError):
        batcher.submit(req(rng, 2))
    batcher.flush()
    snap = metrics.snapshot()
    assert snap["rejected"] == 1.0
    assert snap["deadline_expired"] == 1.0
    assert snap["worker_restarts"] == 0.0
