"""Decode-engine certification: token parity against the full-context
oracle, slot-refill compile discipline, KV-capacity truncation, and the
cached-attention numerics contract (docs/DESIGN.md §15).

The parity pin is the subsystem's load-bearing claim: every token the
incremental cached-attention path emits must equal the token
``greedy_decode`` (full-context recompute, the oracle) emits from the
same weights — including mid-stream slot refill (a new occupant's
prefill overwrites a retired stream's rows) and the capacity boundary.
All CPU, thread-free (synchronous scheduler).
"""

import numpy as np
import pytest

from zookeeper_tpu.core import configure
from zookeeper_tpu.models.transformer import TransformerLM, greedy_decode
from zookeeper_tpu.serving.decode import (
    DecodeEngine,
    DecodeScheduler,
    allocate_kv_cache,
    kv_cache_bytes,
    pages_in_use,
)

pytestmark = pytest.mark.serving

VOCAB = 53
SEQ_LEN = 64


def build_lm(num_layers=2, d_model=32, num_heads=4, max_seq_len=SEQ_LEN,
             seed=0):
    model = TransformerLM()
    configure(
        model,
        {
            "num_layers": num_layers,
            "d_model": d_model,
            "num_heads": num_heads,
            "max_seq_len": max_seq_len,
            "attention": "dense",
        },
        name="lm",
    )
    module = model.build((max_seq_len,), VOCAB)
    params, state = model.initialize(module, (max_seq_len,), seed=seed)
    variables = {"params": params, **dict(state or {})}
    return module, params, state, variables


def make_engine(module, params, state, *, slots=3, seq_buckets=(8, 16),
                kv_capacity=SEQ_LEN, partitioner=None, **conf):
    engine = DecodeEngine()
    configure(
        engine,
        {
            "slots": slots,
            "seq_buckets": tuple(seq_buckets),
            "kv_capacity": kv_capacity,
            **conf,
        },
        name="engine",
    )
    engine.bind(module, params, state, partitioner=partitioner)
    return engine


def make_scheduler(engine, **conf):
    sched = DecodeScheduler()
    configure(sched, dict(conf), name="sched")
    sched.bind(engine)
    return sched


def oracle(module, variables, prompt, steps):
    """Full-context greedy continuation (generated tokens only)."""
    out = np.asarray(greedy_decode(module, variables, prompt[None], steps))
    return out[0, prompt.shape[0]:]


@pytest.fixture(scope="module")
def lm():
    return build_lm()


# -- the parity certification ---------------------------------------------


def test_incremental_decode_matches_full_context_oracle(lm):
    """Every generated token equals the full-context oracle's, for
    prompts of varying length across both seq buckets."""
    module, params, state, variables = lm
    engine = make_engine(module, params, state)
    engine.warmup()
    sched = make_scheduler(engine, max_new_tokens=12)
    rng = np.random.default_rng(0)
    prompts = [
        rng.integers(1, VOCAB, size=n).astype(np.int32)
        for n in (1, 2, 7, 8, 9, 16)
    ]
    streams = [sched.submit(p, max_new_tokens=10) for p in prompts]
    sched.drain()
    for p, s in zip(prompts, streams):
        got = s.result()
        want = oracle(module, variables, p, 10)
        np.testing.assert_array_equal(got, want)
        assert s.finish_reason == "length"


def test_slot_refill_parity_and_zero_post_warmup_compiles(lm):
    """The acceptance pin: many more requests than slots — finished
    slots are REFILLED mid-stream (new prefills overwrite retired
    streams' KV rows) — and every stream stays token-exact with ZERO
    compiles after warmup."""
    module, params, state, variables = lm
    engine = make_engine(module, params, state, slots=3)
    warm = engine.warmup()
    assert warm == engine.compile_count
    sched = make_scheduler(engine)
    rng = np.random.default_rng(1)
    prompts = [
        rng.integers(1, VOCAB, size=int(rng.integers(1, 17))).astype(np.int32)
        for _ in range(11)
    ]
    # Varying budgets => staggered finishes => real mid-flight refills.
    budgets = [int(rng.integers(1, 9)) for _ in prompts]
    streams = [
        sched.submit(p, max_new_tokens=b) for p, b in zip(prompts, budgets)
    ]
    sched.drain()
    for p, b, s in zip(prompts, budgets, streams):
        np.testing.assert_array_equal(s.result(), oracle(module, variables, p, b))
    assert engine.compile_count == warm  # the zero-recompile pin
    assert engine.recompiles_detected == 0


def test_capacity_boundary_truncates_with_parity(lm):
    """A stream that reaches the per-slot KV capacity (the ring
    boundary) truncates cleanly with reason "capacity" — and every
    token UP TO the boundary is still oracle-exact."""
    module, params, state, variables = lm
    engine = make_engine(
        module, params, state, slots=2, seq_buckets=(8,), kv_capacity=16
    )
    engine.warmup()
    assert engine.capacity == 16
    sched = make_scheduler(engine)
    prompt = np.arange(1, 7, dtype=np.int32)  # 6 tokens, 10 fit after
    stream = sched.submit(prompt, max_new_tokens=64)
    sched.drain()
    got = stream.result()
    assert stream.finish_reason == "capacity"
    assert got.shape[0] == engine.token_limit - prompt.shape[0]
    np.testing.assert_array_equal(
        got, oracle(module, variables, prompt, got.shape[0])
    )


def test_positional_table_bounds_generation():
    """token_limit is min(capacity, positional table): a module built
    with a short table truncates there even with KV headroom."""
    module, params, state, variables = build_lm(max_seq_len=16)
    engine = make_engine(
        module, params, state, slots=1, seq_buckets=(8,), kv_capacity=64
    )
    engine.warmup()
    assert engine.position_cap == 16
    assert engine.token_limit == 16
    sched = make_scheduler(engine)
    prompt = np.arange(1, 5, dtype=np.int32)
    stream = sched.submit(prompt, max_new_tokens=64)
    sched.drain()
    got = stream.result()
    assert stream.finish_reason == "capacity"
    assert prompt.shape[0] + got.shape[0] == 16
    np.testing.assert_array_equal(
        got, oracle(module, variables, prompt, got.shape[0])
    )


def test_grouped_prefill_parity(lm):
    """prefill_buckets > 1: several queued prompts ride ONE bucketed
    prefill dispatch (incl. a partial group padded with dropped rows)
    and stay oracle-exact."""
    module, params, state, variables = lm
    engine = make_engine(
        module, params, state, slots=4, prefill_buckets=(2, 4)
    )
    warm = engine.warmup()
    assert warm == 2 * 2 + 1  # (prefill buckets x seq buckets) + decode
    sched = make_scheduler(engine)
    rng = np.random.default_rng(2)
    prompts = [
        rng.integers(1, VOCAB, size=int(rng.integers(1, 9))).astype(np.int32)
        for _ in range(3)  # 3 => one full pair + one padded partial
    ]
    streams = [sched.submit(p, max_new_tokens=6) for p in prompts]
    sched.drain()
    for p, s in zip(prompts, streams):
        np.testing.assert_array_equal(s.result(), oracle(module, variables, p, 6))
    assert engine.compile_count == warm


# -- cached attention numerics --------------------------------------------


def test_cached_attention_matches_reference_row():
    """ops.cached_attention over a padded cache equals the full
    attention_reference row at the same position (the op-for-op
    numerics mirror the docstring commits to)."""
    import jax.numpy as jnp

    from zookeeper_tpu.ops import attention_reference, cached_attention

    rng = np.random.default_rng(3)
    b, s, h, d, cap = 2, 9, 4, 8, 16
    q = rng.normal(size=(b, s, h, d)).astype(np.float32)
    k = rng.normal(size=(b, s, h, d)).astype(np.float32)
    v = rng.normal(size=(b, s, h, d)).astype(np.float32)
    full = np.asarray(attention_reference(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=True
    ))
    # Cache rows past the live region hold garbage that MUST be masked.
    k_cache = rng.normal(size=(b, cap, h, d)).astype(np.float32)
    v_cache = rng.normal(size=(b, cap, h, d)).astype(np.float32)
    pos = s - 1
    k_cache[:, : pos + 1] = k[:, : pos + 1]
    v_cache[:, : pos + 1] = v[:, : pos + 1]
    got = np.asarray(cached_attention(
        jnp.asarray(q[:, pos : pos + 1]),
        jnp.asarray(k_cache),
        jnp.asarray(v_cache),
        jnp.full((b,), pos, np.int32),
    ))
    np.testing.assert_allclose(got[:, 0], full[:, pos], rtol=0, atol=2e-6)


# -- cache state ----------------------------------------------------------


def test_cache_allocation_and_accounting():
    cache = allocate_kv_cache(2, 3, 16, 4, 8, np.float32)
    assert len(cache) == 2
    assert cache[0]["k"].shape == (3, 16, 4, 8)
    assert kv_cache_bytes(2, 3, 16, 4, 8, 4) == 2 * 2 * 3 * 16 * 4 * 8 * 4
    # ceil(5/4) + ceil(8/4) + (0 skipped)
    assert pages_in_use([5, 8, 0], 4) == 2 + 2
    with pytest.raises(ValueError, match="slots >= 1"):
        allocate_kv_cache(2, 0, 16, 4, 8, np.float32)
    with pytest.raises(ValueError, match="page_size"):
        pages_in_use([1], 0)


def test_capacity_page_alignment(lm):
    module, params, state, _ = lm
    engine = make_engine(
        module, params, state, kv_capacity=33, page_size=16,
        seq_buckets=(8,),
    )
    assert engine.capacity == 48  # 33 rounded up to the page boundary


# -- config validation ----------------------------------------------------


def test_bind_validation(lm):
    module, params, state, _ = lm

    def expect(match, **conf):
        engine = DecodeEngine()
        configure(engine, dict(conf), name="engine")
        with pytest.raises(ValueError, match=match):
            engine.bind(module, params, state)

    expect("seq_buckets", seq_buckets=())
    expect("seq_buckets", seq_buckets=(16, 8))
    expect("seq_buckets", seq_buckets=(0, 8))
    expect("prefill_buckets", prefill_buckets=(4, 2))
    expect("slots", slots=0)
    expect("exceeds", slots=2, prefill_buckets=(4,))
    expect("page_size", page_size=0)
    expect("kv_capacity", kv_capacity=0)
    expect("exceeds the KV capacity", seq_buckets=(32,), kv_capacity=16)
    expect("positional table", seq_buckets=(128,), kv_capacity=256)

    class NotALM:
        pass

    engine = DecodeEngine()
    configure(engine, {}, name="engine")
    with pytest.raises(ValueError, match="prefill"):
        engine.bind(NotALM(), params, state)


def test_unbound_engine_raises():
    engine = DecodeEngine()
    configure(engine, {}, name="engine")
    with pytest.raises(RuntimeError, match="not bound"):
        engine.warmup()


def test_prompt_dispatch_validation(lm):
    module, params, state, _ = lm
    engine = make_engine(module, params, state)
    engine.warmup()
    with pytest.raises(ValueError, match="exceeds the largest seq bucket"):
        engine.seq_bucket_for(17)
    with pytest.raises(ValueError, match="unique"):
        engine.prefill(
            [np.array([1], np.int32), np.array([2], np.int32)], [0, 0]
        )
    with pytest.raises(ValueError, match="empty prompt"):
        engine.prefill([np.zeros((0,), np.int32)], [0])
    with pytest.raises(ValueError, match="slots"):
        engine.decode(np.zeros((5,), np.int32), np.zeros((5,), np.int32))


# -- weight swap (engine level) -------------------------------------------


def test_check_swap_rejects_mismatched_weights(lm):
    module, params, state, _ = lm
    engine = make_engine(module, params, state)
    other_module, other_params, other_state, _ = build_lm(d_model=64)
    with pytest.raises(ValueError, match="shape/dtype mismatch"):
        engine.check_swap(other_params, other_state)


def test_swap_weights_changes_tokens_without_recompiling(lm):
    module, params, state, variables = lm
    engine = make_engine(module, params, state, slots=1, seq_buckets=(8,))
    warm = engine.warmup()
    _, params_b, state_b, variables_b = build_lm(seed=7)
    sched = make_scheduler(engine)
    prompt = np.arange(1, 6, dtype=np.int32)
    a = sched.generate(prompt, max_new_tokens=6)
    np.testing.assert_array_equal(a, oracle(module, variables, prompt, 6))
    engine.swap_weights(params_b, state_b)
    b = sched.generate(prompt, max_new_tokens=6)
    np.testing.assert_array_equal(
        b, oracle(module, variables_b, prompt, 6)
    )
    assert engine.compile_count == warm


# -- mesh legs (slow: multi-device compiles) ------------------------------


@pytest.mark.slow
def test_decode_parity_on_dp_tp_mesh():
    """KV cache sharded (slots on data, heads on model) on a 2x2 mesh:
    token-exact vs the single-device oracle, zero post-warmup
    compiles. The dryrun_multichip leg re-certifies this under the
    clean-SPMD harness."""
    from zookeeper_tpu.parallel.partitioner import MeshPartitioner

    module, params, state, variables = build_lm()
    part = MeshPartitioner()
    configure(
        part,
        {
            "mesh_shape": (2, 4),
            "mesh_axes": ("data", "model"),
            "data_axes": ("data",),
        },
        name="part",
    )
    part.setup()
    engine = make_engine(
        module, params, state, slots=4, partitioner=part
    )
    warm = engine.warmup()
    sched = make_scheduler(engine)
    rng = np.random.default_rng(4)
    prompts = [
        rng.integers(1, VOCAB, size=int(rng.integers(2, 15))).astype(np.int32)
        for _ in range(6)
    ]
    streams = [sched.submit(p, max_new_tokens=8) for p in prompts]
    sched.drain()
    for p, s in zip(prompts, streams):
        np.testing.assert_array_equal(s.result(), oracle(module, variables, p, 8))
    assert engine.compile_count == warm


@pytest.mark.slow
def test_decode_kernel_parity_on_dp_tp_mesh():
    """The PALLAS paged decode kernel under the sharded path: slots on
    'data', heads on 'model' via the shard_map-composed
    ``sharded_paged_decode_attention`` (docs/DESIGN.md §17) — still
    token-exact vs the full-context oracle, zero post-warmup compiles.
    The dryrun_multichip decode leg re-certifies this with the SPMD log
    asserted clean."""
    from zookeeper_tpu.parallel.partitioner import MeshPartitioner

    module, params, state, variables = build_lm()
    part = MeshPartitioner()
    configure(
        part,
        {
            "mesh_shape": (2, 4),
            "mesh_axes": ("data", "model"),
            "data_axes": ("data",),
        },
        name="part",
    )
    part.setup()
    engine = make_engine(
        module, params, state, slots=4, partitioner=part,
        decode_attention="pallas",
    )
    assert engine.decode_attention_flavor == "pallas"
    warm = engine.warmup()
    sched = make_scheduler(engine)
    rng = np.random.default_rng(11)
    prompts = [
        rng.integers(1, VOCAB, size=int(rng.integers(2, 15))).astype(np.int32)
        for _ in range(6)
    ]
    streams = [sched.submit(p, max_new_tokens=8) for p in prompts]
    sched.drain()
    for p, s in zip(prompts, streams):
        np.testing.assert_array_equal(
            s.result(), oracle(module, variables, p, 8)
        )
    assert engine.compile_count == warm
    assert not engine._cache[0]["k"].sharding.is_fully_replicated

    # The indivisible-geometry posture with the kernel selected:
    # slots=3 cannot shard over the 2-way data axis, the cache goes
    # REPLICATED, and the kernel runs under fully-replicated shard_map
    # specs — still token-exact.
    engine3 = make_engine(
        module, params, state, slots=3, partitioner=part,
        decode_attention="pallas",
    )
    assert engine3.decode_attention_flavor == "pallas"
    assert engine3._cache_replicated
    engine3.warmup()
    p = np.arange(1, 8, dtype=np.int32)
    np.testing.assert_array_equal(
        make_scheduler(engine3).generate(p, max_new_tokens=6),
        oracle(module, variables, p, 6),
    )


@pytest.mark.slow
def test_indivisible_cache_falls_back_replicated(caplog):
    """slots=3 on a 2-way data mesh cannot shard — the engine warns and
    decodes with a REPLICATED cache, still token-exact."""
    import logging

    from zookeeper_tpu.parallel.partitioner import MeshPartitioner

    module, params, state, variables = build_lm()
    part = MeshPartitioner()
    configure(
        part,
        {
            "mesh_shape": (2, 4),
            "mesh_axes": ("data", "model"),
            "data_axes": ("data",),
        },
        name="part",
    )
    part.setup()
    with caplog.at_level(logging.WARNING):
        engine = make_engine(
            module, params, state, slots=3, partitioner=part
        )
    assert any("REPLICATED" in r.message for r in caplog.records)
    engine.warmup()
    sched = make_scheduler(engine)
    prompt = np.arange(1, 8, dtype=np.int32)
    np.testing.assert_array_equal(
        sched.generate(prompt, max_new_tokens=6),
        oracle(module, variables, prompt, 6),
    )
