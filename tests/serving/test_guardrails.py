"""Overload guardrails certification (docs/DESIGN.md §24).

Four layers, cheapest first:

1. **CircuitBreaker state machine** — threshold trips, the single
   half-open probe claim, jitter bounds + determinism, latency
   (gray-failure) trips, concurrent failure races.
2. **OverloadGuard estimator** — EWMA math, warmup admits-all, the
   empty-queue invariant (PR 4), headroom, brown-out hysteresis.
3. **Service integration** — MicroBatcher + DecodeScheduler shed with
   :class:`PredictedMissError` at submit, RequestLog records the
   predictive shed, brown-out applies only at the drain boundary and
   caps newly admitted streams.
4. **Router integration** (stub transports) — rid-preserving retry
   before first token, breaker open→half-open→closed over live
   routing, the scrape-cache invalidation regression, and the
   ``delay_forward_ms`` FaultPlan knob's one-shot contract.
"""

import threading

import numpy as np
import pytest

from zookeeper_tpu.core import configure
from zookeeper_tpu.observability.export import render_prometheus
from zookeeper_tpu.resilience import FaultPlan
from zookeeper_tpu.serving import (
    BrownOut,
    CircuitBreaker,
    MicroBatcher,
    OverloadGuard,
    PredictedMissError,
    RejectedError,
)
from zookeeper_tpu.serving.decode import DecodeScheduler

from tests.serving.test_decode_engine import build_lm, make_engine
from tests.serving.test_fleet import make_router

pytestmark = pytest.mark.serving


# -- layer 1: the breaker state machine -------------------------------------


class FakeClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t


def make_breaker(**kw):
    clock = FakeClock()
    kw.setdefault("key", "w0")
    kw.setdefault("failure_threshold", 3)
    kw.setdefault("cooldown_s", 5.0)
    kw.setdefault("jitter_frac", 0.5)
    return CircuitBreaker(clock=clock, **kw), clock


def test_breaker_rejects_bad_config():
    with pytest.raises(ValueError, match="failure_threshold"):
        CircuitBreaker(failure_threshold=-1)
    with pytest.raises(ValueError, match="latency_window"):
        CircuitBreaker(latency_window=0)
    with pytest.raises(ValueError, match="cooldown_s"):
        CircuitBreaker(cooldown_s=0)


def test_breaker_opens_at_failure_threshold_only():
    b, _ = make_breaker()
    b.record_failure()
    b.record_failure()
    assert b.state == CircuitBreaker.CLOSED
    b.record_failure()
    assert b.state == CircuitBreaker.OPEN
    assert b.opened_total == 1


def test_breaker_success_resets_failure_streak():
    b, _ = make_breaker()
    b.record_failure()
    b.record_failure()
    b.record_success()
    b.record_failure()
    b.record_failure()
    # 2+2 failures with a success between: streak never reached 3.
    assert b.state == CircuitBreaker.CLOSED


def test_breaker_zero_threshold_never_trips_on_failures():
    b, _ = make_breaker(failure_threshold=0)
    for _ in range(20):
        b.record_failure()
    assert b.state == CircuitBreaker.CLOSED


def test_open_breaker_is_unroutable_until_cooldown():
    b, clock = make_breaker()
    for _ in range(3):
        b.record_failure()
    assert not b.routable()
    assert not b.try_probe()  # not due yet
    clock.t = b.open_until + 0.001
    assert b.routable()


def test_half_open_single_probe_claim():
    """Exactly ONE caller wins the probe; everyone else keeps waiting
    until the probe resolves."""
    b, clock = make_breaker()
    for _ in range(3):
        b.record_failure()
    clock.t = b.open_until + 0.001
    assert b.try_probe()
    assert b.state == CircuitBreaker.HALF_OPEN
    assert not b.try_probe()  # the probe is already in flight
    assert not b.routable()
    assert b.probes_total == 1


def test_probe_success_closes_probe_failure_reopens():
    b, clock = make_breaker()
    for _ in range(3):
        b.record_failure()
    clock.t = b.open_until + 0.001
    assert b.try_probe()
    b.record_failure()  # probe failed
    assert b.state == CircuitBreaker.OPEN
    assert b.opened_total == 2
    clock.t = b.open_until + 0.001
    assert b.try_probe()
    b.record_success()
    assert b.state == CircuitBreaker.CLOSED


def test_jitter_bounds_and_determinism():
    """Cooldown delay lands in [cooldown, cooldown*(1+jitter)] and is a
    pure function of (seed, key, open count) — two breakers with the
    same coordinates open to identical offsets; different keys differ."""
    delays_a, delays_b, delays_c = [], [], []
    for delays, key, seed in (
        (delays_a, "w0", 3),
        (delays_b, "w0", 3),
        (delays_c, "w1", 3),
    ):
        b, clock = make_breaker(key=key, seed=seed)
        for _ in range(4):  # four opens: threshold then probe failures
            if b.state == CircuitBreaker.CLOSED:
                for _ in range(3):
                    b.record_failure()
            else:
                clock.t = b.open_until + 0.001
                assert b.try_probe()
                b.record_failure()
            delays.append(b.open_until - clock.t)
    for d in delays_a:
        assert 5.0 <= d <= 5.0 * 1.5
    assert delays_a == delays_b  # same coordinates, same jitter
    assert delays_a != delays_c  # per-replica decorrelation
    assert len(set(delays_a)) == len(delays_a)  # fresh draw per open


def test_zero_jitter_is_exact_cooldown():
    b, clock = make_breaker(jitter_frac=0.0, cooldown_s=2.0)
    for _ in range(3):
        b.record_failure()
    assert b.open_until - clock.t == pytest.approx(2.0)


def test_latency_trip_is_the_gray_failure_path():
    """A replica answering successfully but slowly trips after
    latency_window consecutive slow responses — the case a liveness
    probe cannot see. A fast response resets the slow streak."""
    b, _ = make_breaker(latency_threshold_ms=50.0, latency_window=3)
    b.record_success(200.0)
    b.record_success(200.0)
    b.record_success(1.0)  # fast: streak resets
    b.record_success(200.0)
    b.record_success(200.0)
    assert b.state == CircuitBreaker.CLOSED
    b.record_success(200.0)
    assert b.state == CircuitBreaker.OPEN


def test_latency_disabled_by_default():
    b, _ = make_breaker()
    for _ in range(10):
        b.record_success(10_000.0)
    assert b.state == CircuitBreaker.CLOSED


def test_concurrent_failures_trip_exactly_once():
    """A thundering herd of failures must produce ONE open (one jitter
    draw, one log line), not one per racing thread."""
    b, _ = make_breaker(failure_threshold=1)
    barrier = threading.Barrier(8)

    def slam():
        barrier.wait()
        for _ in range(50):
            b.record_failure()

    threads = [threading.Thread(target=slam) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert b.state == CircuitBreaker.OPEN
    assert b.opened_total == 1


def test_concurrent_probe_claim_single_winner():
    b, clock = make_breaker()
    for _ in range(3):
        b.record_failure()
    clock.t = b.open_until + 0.001
    wins = []
    barrier = threading.Barrier(8)

    def race():
        barrier.wait()
        if b.try_probe():
            wins.append(1)

    threads = [threading.Thread(target=race) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(wins) == 1
    assert b.probes_total == 1


def test_reset_restores_closed_with_clean_streaks():
    b, _ = make_breaker()
    for _ in range(3):
        b.record_failure()
    b.reset()
    assert b.state == CircuitBreaker.CLOSED
    b.record_failure()
    b.record_failure()
    assert b.state == CircuitBreaker.CLOSED  # streak restarted from 0


# -- layer 2: the OverloadGuard estimator -----------------------------------


def make_guard(**conf):
    conf.setdefault("enabled", True)
    g = OverloadGuard()
    configure(g, conf, name="guard")
    return g.bind()


def test_guard_rejects_bad_config():
    with pytest.raises(ValueError, match="alpha"):
        make_guard(alpha=0.0)
    with pytest.raises(ValueError, match="min_samples"):
        make_guard(min_samples=0)
    with pytest.raises(ValueError, match="headroom"):
        make_guard(headroom=0.0)


def test_guard_warmup_admits_everything():
    """Below min_samples the estimator has no opinion — even an absurd
    queue with a 0.1ms deadline admits."""
    g = make_guard(min_samples=4)
    for _ in range(3):
        g.observe_service(1000.0, 1)
        ok, predicted = g.admit(
            queued_units=10_000, request_units=100, deadline_ms=0.1
        )
        assert ok and predicted is None
    g.observe_service(1000.0, 1)  # 4th sample: warmed up
    ok, predicted = g.admit(
        queued_units=10_000, request_units=100, deadline_ms=0.1
    )
    assert not ok and predicted is not None


def test_guard_never_sheds_into_empty_queue():
    """The PR 4 invariant verbatim: an empty queue always admits one
    request, however hopeless the estimate says it is."""
    g = make_guard(min_samples=1)
    g.observe_service(10_000.0, 1)
    ok, _ = g.admit(queued_units=0, request_units=50, deadline_ms=0.1)
    assert ok


def test_guard_no_deadline_nothing_to_miss():
    g = make_guard(min_samples=1)
    g.observe_service(10_000.0, 1)
    ok, _ = g.admit(queued_units=100, request_units=50, deadline_ms=None)
    assert ok


def test_guard_ewma_and_prediction_math():
    """predicted = max(queued*service, wait) + request*service, with
    both estimators following the standard EWMA recurrence."""
    g = make_guard(alpha=0.5, min_samples=1)
    g.observe_service(40.0, 4)  # 10 ms/unit seeds the EWMA
    g.observe_service(40.0, 2)  # 20 ms/unit -> ewma 15
    assert g.predicted_ms(4, 2) == pytest.approx(4 * 15 + 2 * 15)
    # The observed-wait floor catches what queue*service misses.
    g.observe_wait(500.0)
    assert g.predicted_ms(4, 2) == pytest.approx(500.0 + 2 * 15)
    # Shed decision honors headroom.
    ok, _ = g.admit(queued_units=4, request_units=2, deadline_ms=520.0)
    assert not ok  # 530 > 520
    g2 = make_guard(alpha=0.5, min_samples=1, headroom=1.5)
    g2.observe_service(10.0, 1)
    g2.observe_wait(500.0)
    ok, _ = g2.admit(queued_units=4, request_units=2, deadline_ms=520.0)
    assert ok  # 520 * 1.5 tolerance


def test_guard_counters_and_status():
    g = make_guard(min_samples=1)
    g.observe_service(100.0, 1)
    g.admit(queued_units=5, request_units=1, deadline_ms=10.0)   # shed
    g.admit(queued_units=0, request_units=1, deadline_ms=10.0)   # admit
    st = g.status()
    assert st["predicted_miss_total"] == 1
    assert st["admitted_total"] == 1
    assert st["warmed_up"]
    snap = g.snapshot()
    assert snap["guard_predicted_miss_total"] == 1.0
    text = render_prometheus([g.registry])
    assert "zk_guard_predicted_miss_total 1" in text
    assert "zk_guard_service_ewma_ms" in text


def test_brownout_hysteresis():
    bo = BrownOut(engage_after=3, release_after=2)
    for _ in range(2):
        bo.note(shed=True)
    assert not bo.engaged
    bo.note(shed=False)  # streak broken
    for _ in range(3):
        bo.note(shed=True)
    assert bo.engaged
    bo.note(shed=False)
    assert bo.engaged  # needs release_after in a row
    bo.note(shed=False)
    assert not bo.engaged
    assert bo.engaged_total == 1
    with pytest.raises(ValueError, match="engage_after"):
        BrownOut(engage_after=0, release_after=1)


def test_guard_brownout_pressure_wiring():
    g = make_guard(min_samples=1, brownout_after=2, brownout_release=1)
    g.observe_service(10_000.0, 1)
    assert not g.brownout_engaged
    for _ in range(2):
        g.admit(queued_units=50, request_units=8, deadline_ms=1.0)
    assert g.brownout_engaged
    g.admit(queued_units=0, request_units=8, deadline_ms=1.0)
    assert not g.brownout_engaged


# -- layer 3: service integration -------------------------------------------


class TinyEngine:
    """The minimal surface MicroBatcher needs: doubles its input."""

    max_batch = 8

    def bucket_for(self, rows):
        return self.max_batch

    def infer(self, x):
        return np.asarray(x) * 2


def test_batcher_predicted_miss_shed():
    """A warmed guard sheds a doomed submit with PredictedMissError
    (a RejectedError subclass) and records the predictive shed in the
    RequestLog detail — while an empty queue still admits."""
    guard = make_guard(min_samples=1)
    guard.observe_service(5_000.0, 1)  # 5s per row: everything misses
    b = MicroBatcher()
    configure(b, dict(synchronous=True), name="batcher")
    b.bind(TinyEngine(), guard=guard)
    first = b.submit(np.ones((2, 3)), deadline_ms=50.0)  # empty queue
    with pytest.raises(PredictedMissError):
        b.submit(np.ones((2, 3)), deadline_ms=50.0)
    with pytest.raises(RejectedError):  # the subclass contract
        b.submit(np.ones((2, 3)), deadline_ms=50.0)
    rec = b.request_log.tail(1)[0]
    assert rec["outcome"] == "shed"
    assert "PredictedMissError" in rec["detail"]
    assert "predicted_ms=" in rec["detail"]
    # No deadline: nothing to miss, rides the queue normally.
    ok = b.submit(np.ones((2, 3)))
    b.flush()
    np.testing.assert_array_equal(first.result(), np.ones((2, 3)) * 2)
    np.testing.assert_array_equal(ok.result(), np.ones((2, 3)) * 2)


def test_batcher_feeds_guard_from_completions():
    guard = make_guard(min_samples=1)
    b = MicroBatcher()
    configure(b, dict(synchronous=True), name="batcher")
    b.bind(TinyEngine(), guard=guard)
    r = b.submit(np.ones((2, 3)))
    b.flush()
    r.result()
    assert guard.samples >= 1
    assert guard.status()["service_ewma_ms"] is not None


@pytest.fixture(scope="module")
def lm():
    return build_lm()


@pytest.fixture(scope="module")
def warm_engine(lm):
    module, params, state, _ = lm
    engine = make_engine(module, params, state, slots=3)
    engine.warmup()
    return engine


def make_guarded_sched(engine, guard, **conf):
    s = DecodeScheduler()
    configure(s, dict(conf), name="sched")
    s.bind(engine, guard=guard)
    return s


def test_scheduler_predicted_miss_shed(warm_engine):
    guard = make_guard(min_samples=1)
    guard.observe_service(5_000.0, 1)  # 5s per token
    sched = make_guarded_sched(warm_engine, guard)
    p = np.arange(1, 5, dtype=np.int32)
    first = sched.submit(p, max_new_tokens=2, deadline_ms=50.0)
    with pytest.raises(PredictedMissError):
        sched.submit(p, max_new_tokens=2, deadline_ms=50.0)
    rec = sched.request_log.tail(1)[0]
    assert rec["outcome"] == "shed"
    assert "PredictedMissError" in rec["detail"]
    assert first.result().shape[0] == 2  # the admitted one still runs
    st = sched.status()
    assert st["guardrails"]["guard"]["predicted_miss_total"] == 1


def test_scheduler_feeds_guard_and_reports_status(warm_engine):
    guard = make_guard(min_samples=1)
    sched = make_guarded_sched(warm_engine, guard)
    sched.generate(np.arange(1, 6, dtype=np.int32), max_new_tokens=3)
    assert guard.samples >= 1
    st = sched.status()["guardrails"]
    assert st["guard"]["warmed_up"]
    assert st["brownout_active"] is False


def test_brownout_caps_new_admissions_at_drain_boundary(warm_engine):
    """Engage brown-out under pressure, verify (a) the transition
    applies only when the slot array is empty, (b) newly admitted
    streams get the capped budget, (c) release restores full budgets."""
    guard = make_guard(
        min_samples=1,
        brownout_after=1,
        brownout_release=2,
        brownout_max_new_tokens=2,
    )
    guard.observe_service(5_000.0, 1)
    sched = make_guarded_sched(warm_engine, guard)
    p = np.arange(1, 5, dtype=np.int32)
    # One stream admitted into a slot, one riding the queue.
    inflight = sched.submit(p, max_new_tokens=8)
    sched._step_once()  # admits inflight into a slot
    queued = sched.submit(p, max_new_tokens=8)
    # The predicted-miss shed engages the CONTROLLER (not yet applied).
    with pytest.raises(PredictedMissError):
        sched.submit(p, max_new_tokens=8, deadline_ms=1.0)
    assert guard.brownout_engaged
    # Slots are occupied: the boundary must NOT flip mid-flight, and
    # the queued stream is still admitted with its FULL budget.
    sched._step_once()
    assert not sched.status()["guardrails"]["brownout_active"]
    assert inflight.result().shape[0] == 8
    assert queued.result().shape[0] == 8
    sched._step_once()  # an idle step observes the drained slot array
    assert sched.status()["guardrails"]["brownout_active"]
    # New admissions are capped.
    capped = sched.submit(p, max_new_tokens=8)
    assert capped.result().shape[0] == 2
    assert "zk_guard_brownout_active 1" in render_prometheus(
        [guard.registry]
    )
    # Recovery: sustained non-shed admissions release the controller
    # (the capped submit above was the first of the release streak);
    # the boundary follows at the next drained step.
    guard.admit(queued_units=0, request_units=1, deadline_ms=None)
    assert not guard.brownout_engaged
    sched._step_once()
    assert not sched.status()["guardrails"]["brownout_active"]
    full = sched.submit(p, max_new_tokens=8)
    assert full.result().shape[0] == 8


# -- layer 4: router integration --------------------------------------------


def test_router_retry_reroutes_rid_preserving():
    """A transport failure before first token retries onto the
    survivor under the SAME rid, records retried=N in the RequestLog,
    and counts zk_fleet_retries_total."""
    router, stub = make_router(2, max_retries=2, retry_backoff_s=0.0)
    try:
        r_ok = router.submit([1, 2, 3])
        stub.dead.add(r_ok.worker_id)  # the load-preferred replica dies
        r = router.submit([1, 2, 3], rid=777)
        assert r.rid == 777
        assert r.worker_id != r_ok.worker_id
        np.testing.assert_array_equal(r.tokens, [1, 2, 3, 7])
        rec = router.request_log.find(777)
        assert rec["outcome"] == "ok"
        assert "retried=1" in rec["detail"]
        assert router.retries_total == 1
        assert router.metrics.snapshot()["fleet_retries_total"] == 1.0
        assert "zk_fleet_retries_total 1" in render_prometheus(
            [router.metrics.registry]
        )
    finally:
        router.close()


def test_router_retry_exhaustion_still_fails_clean():
    from zookeeper_tpu.serving import WorkerCrashedError

    router, stub = make_router(2, max_retries=1, retry_backoff_s=0.0)
    try:
        stub.dead.update({"w0", "w1"})
        with pytest.raises(WorkerCrashedError, match="retried=1"):
            router.submit([1, 2, 3], rid=42)
        rec = router.request_log.find(42)
        assert rec["outcome"] == "crashed"
        assert "retried=1" in rec["detail"]
    finally:
        router.close()


def test_router_no_retries_by_default():
    from zookeeper_tpu.serving import WorkerCrashedError

    router, stub = make_router(2)
    try:
        stub.dead.update({"w0", "w1"})
        with pytest.raises(WorkerCrashedError):
            router.submit([1, 2, 3])
        assert router.retries_total == 0
    finally:
        router.close()


def test_router_breaker_gray_failure_cycle():
    """A slow-but-alive replica trips its breaker via the latency
    threshold, is excluded from routing while open, serves exactly one
    half-open probe after the cooldown, and closes on the probe's
    success — the full open→half-open→closed cycle over live routing,
    with the state gauge tracking every transition."""
    clock = FakeClock()
    router, stub = make_router(
        2,
        policy="round_robin",
        breaker_latency_ms=0.000001,  # every real call counts as slow
        breaker_latency_window=1,
        breaker_cooldown_s=5.0,
        breaker_jitter_frac=0.0,
        breaker_clock=clock,
    )
    try:
        # Only w0 is "gray": w1's latency trip is disabled so the slow
        # stub transport (every real call exceeds the 1ns threshold)
        # trips exactly one replica.
        router.replicas[1].breaker.latency_threshold_ms = 0.0
        r = router.submit([1, 2, 3])  # w0: slow success -> breaker opens
        assert r.worker_id == "w0"
        b0 = router.replicas[0].breaker
        assert b0.state == CircuitBreaker.OPEN
        # While open, round-robin skips w0 entirely — though w0 is
        # perfectly "healthy" by the liveness probe's lights.
        assert {router.submit([4, 5, 6]).worker_id for _ in range(3)} == {
            "w1"
        }
        render = render_prometheus([router.metrics.registry])
        assert 'zk_fleet_breaker_state{replica="w0"} 1' in render
        # Cooldown elapses: the next submit claims THE half-open probe
        # on w0, and the probe's success closes the breaker (a probe
        # resolves on success/failure alone — its latency seeds the
        # next closed-state window instead of instantly re-tripping).
        clock.t = b0.open_until + 0.001
        probe = router.submit([7, 8, 9])
        assert probe.worker_id == "w0"
        assert b0.state == CircuitBreaker.CLOSED
        assert 'zk_fleet_breaker_state{replica="w0"} 0' in (
            render_prometheus([router.metrics.registry])
        )
        status = router.status()["replicas"][0]["breaker"]
        assert status["state"] == "closed"
        assert status["opened_total"] == 1
        assert status["probes_total"] == 1
        # The gray condition persists: the very next w0 response trips
        # the breaker again.
        while router.submit([1, 2, 3]).worker_id != "w0":
            pass
        assert b0.state == CircuitBreaker.OPEN
        assert b0.opened_total == 2
    finally:
        router.close()


def test_router_open_breaker_reroutes_pinned_session():
    clock = FakeClock()
    router, stub = make_router(
        2,
        breaker_failures=1,
        breaker_cooldown_s=5.0,
        breaker_jitter_frac=0.0,
        breaker_clock=clock,
        max_retries=1,
        retry_backoff_s=0.0,
    )
    try:
        r1 = router.submit([1, 2, 3, 4], session="sA")
        pinned = r1.worker_id
        stub.dead.add(pinned)
        # Transport fails -> breaker opens + replica marked dead; the
        # retry re-pins the session on the survivor.
        r2 = router.submit([1, 2, 3, 4, 5], session="sA")
        assert r2.worker_id != pinned
        assert router.session_pin("sA") == r2.worker_id
        assert (
            router._by_id[pinned].breaker.state == CircuitBreaker.OPEN
        )
    finally:
        router.close()


def test_router_all_breakers_open_is_unavailable():
    from zookeeper_tpu.serving import FleetUnavailableError

    clock = FakeClock()
    router, stub = make_router(
        2,
        breaker_latency_ms=0.000001,
        breaker_latency_window=1,
        breaker_cooldown_s=1000.0,
        breaker_jitter_frac=0.0,
        breaker_clock=clock,
        policy="round_robin",
    )
    try:
        router.submit([1, 2, 3])  # opens w0
        router.submit([1, 2, 3])  # opens w1
        with pytest.raises(FleetUnavailableError, match="open circuit"):
            router.submit([1, 2, 3])
    finally:
        router.close()


def test_scrape_cache_invalidated_on_health_transitions():
    """The satellite regression: a dead replica's cached load scrape
    must not survive the health transition (stale flattering numbers
    would rank the corpse), and a revived replica starts cold."""
    import time as _time

    router, stub = make_router(2)
    try:
        r0 = router.replicas[0]
        r0._scrape = (_time.monotonic(), 0.0, 99.0)  # flattering cache
        with router._lock:
            router._mark_dead(r0)
        assert r0._scrape is None  # death invalidates
        r0._scrape = (_time.monotonic(), 0.0, 99.0)  # pre-revival junk
        router.check_health()  # stub says w0 is alive again
        assert r0.healthy
        assert r0._scrape is None  # revival invalidates too
        assert r0.breaker.state == CircuitBreaker.CLOSED
    finally:
        router.close()


def test_fault_plan_delay_forward_one_shot():
    plan = FaultPlan(delay_forward_ms={"w0": 25})
    assert plan.take_delay_forward("w1") == 0  # not targeted
    assert plan.take_delay_forward("w0") == 25
    assert plan.take_delay_forward("w0") == 0  # one-shot: fired
    assert FaultPlan().take_delay_forward("w0") == 0  # default never fires
