"""Disaggregated-serving certification (docs/DESIGN.md §22): prefill
on one role engine, decode on another, KV pages streamed between the
pools. The headline pin is the repo's strongest kind — disagg greedy
output is TOKEN-IDENTICAL to the single-mesh ``DecodeScheduler`` (and
re-pinned against the full-context greedy oracle directly) through
real slot refill, on fp paged KV, int8 KV on both sides, and the
speculative schedule at both ends of the acceptance spectrum; with
zero post-warmup compiles on either role.

The chaos legs pin the refcount-custody contract: an injected
page-transfer failure or a prefill-role crash mid-handoff must leave
``leak_check() == 0`` on BOTH pools, fail only its victims (partial
tokens readable), and leave every survivor token-identical.

All CPU, thread-free (synchronous scheduler); the two roles overlap on
the single CPU device (``DisaggPartitioner``'s portable fallback), so
every protocol step — export, place, import, refcount handoff — runs
for real.
"""

import numpy as np
import pytest

from zookeeper_tpu.core import configure
from zookeeper_tpu.observability import trace
from zookeeper_tpu.resilience import FaultPlan, faults
from zookeeper_tpu.serving import (
    DeadlineExpiredError,
    DisaggPartitioner,
    DisaggScheduler,
    PageTransfer,
    PageTransferError,
    WorkerCrashedError,
)
from zookeeper_tpu.serving.decode import DecodeEngine, DecodeMetrics

from tests.serving.test_decode_engine import (
    VOCAB,
    build_lm,
    make_scheduler,
    oracle,
)
from tests.serving.test_speculative import make_spec, zero_tail_pair

pytestmark = pytest.mark.serving


def role_engine(module, params, state, *, name, slots=2,
                seq_buckets=(8, 16), kv_capacity=64, **conf):
    engine = DecodeEngine()
    configure(
        engine,
        {
            "slots": slots,
            "seq_buckets": tuple(seq_buckets),
            "kv_capacity": kv_capacity,
            "kv_layout": "paged",
            **conf,
        },
        name=f"dg_{name}",
    )
    engine.bind(module, params, state)
    return engine


def make_disagg(lm, *, lanes=2, slots=2, host_bounce=False, draft=None,
                k=3, metrics=False, warm=False, engine_conf=None,
                **sched_conf):
    """A full disagg stack on one device: (sched, prefill, decode,
    transfer, metrics)."""
    module, params, state, _ = lm
    engine_conf = dict(engine_conf or {})
    pre = role_engine(
        module, params, state, name="prefill", slots=lanes,
        prefill_buckets=(1, 2), **engine_conf,
    )
    dec = role_engine(
        module, params, state, name="decode", slots=slots,
        prefill_buckets=(1,), prefix_cache=False, **engine_conf,
    )
    if warm:
        pre.warmup()
        dec.warmup()
        pre.warmup_transfer()
        dec.warmup_transfer()
    m = None
    if metrics:
        m = DecodeMetrics()
        configure(m, {}, name="dg_metrics")
    transfer = PageTransfer()
    configure(transfer, {"host_bounce": host_bounce}, name="dg_transfer")
    transfer.bind(pre, dec, metrics=m)
    spec = make_spec(dec, draft, k=k) if draft is not None else None
    sched = DisaggScheduler()
    configure(sched, dict(sched_conf), name="dg_sched")
    sched.bind(pre, dec, transfer, metrics=m, speculative=spec)
    return sched, pre, dec, transfer, m


def leak_free(*engines):
    return all(e.page_pool.leak_check() == 0 for e in engines)


@pytest.fixture(scope="module")
def lm():
    return build_lm()


@pytest.fixture(scope="module")
def prompts():
    rng = np.random.default_rng(11)
    # > slots AND > lanes: later admissions refill freed prefill lanes
    # and freed decode slots mid-traffic, and transferred pages land in
    # recycled destination pages.
    return [
        rng.integers(1, VOCAB, size=int(rng.integers(1, 16))).astype(
            np.int32
        )
        for _ in range(7)
    ]


# -- THE parity certification ----------------------------------------------


@pytest.mark.slow
def test_disagg_token_identical_to_single_mesh_and_oracle(lm, prompts):
    """Every token the disaggregated service emits equals the
    single-mesh paged DecodeScheduler's AND the full-context greedy
    oracle's, through prefill-lane refill, the page handoff, and
    decode-slot refill."""
    module, params, state, variables = lm
    sched, pre, dec, _, _ = make_disagg(lm)
    got = [sched.submit(p, max_new_tokens=8) for p in prompts]
    sched.drain()
    single = role_engine(module, params, state, name="single")
    base = make_scheduler(single, max_new_tokens=8)
    want = [base.submit(p) for p in prompts]
    base.drain()
    for p, g, w in zip(prompts, got, want):
        np.testing.assert_array_equal(g.result(), w.result())
        np.testing.assert_array_equal(
            g.result(), oracle(module, variables, p, 8)
        )
    assert leak_free(pre, dec, single)


def test_disagg_int8_token_identical_to_single_mesh_int8(lm, prompts):
    """int8 KV on BOTH roles: quantized rows transfer verbatim, so the
    disagg stream equals the single-mesh int8 stream token for token
    (int8-vs-fp parity is the paged suite's contract, not this one's)."""
    module, params, state, _ = lm
    sched, pre, dec, _, _ = make_disagg(
        lm, engine_conf={"kv_quant": "int8"}
    )
    got = [sched.submit(p, max_new_tokens=8) for p in prompts]
    sched.drain()
    single = role_engine(
        module, params, state, name="single_i8", kv_quant="int8"
    )
    base = make_scheduler(single, max_new_tokens=8)
    want = [base.submit(p) for p in prompts]
    base.drain()
    for g, w in zip(got, want):
        np.testing.assert_array_equal(g.result(), w.result())
    assert leak_free(pre, dec)


@pytest.mark.slow
@pytest.mark.parametrize("draft_kind", ["random", "zero_tail"])
def test_disagg_speculative_token_identical(draft_kind, prompts):
    """Speculative decoding rides the disaggregated decode loop
    unchanged: token-identical to the full-context oracle at BOTH ends
    of the acceptance spectrum (random draft = every window rejects;
    zero-tail draft = windows fully accept)."""
    if draft_kind == "zero_tail":
        teacher, draft = zero_tail_pair()
    else:
        teacher = build_lm(num_layers=2)
        draft = build_lm(num_layers=1, seed=17)
    module, params, state, variables = teacher
    sched, pre, dec, _, _ = make_disagg(teacher, draft=draft, k=3)
    got = [sched.submit(p, max_new_tokens=8) for p in prompts]
    sched.drain()
    for p, g in zip(prompts, got):
        np.testing.assert_array_equal(
            g.result(), oracle(module, variables, p, 8)
        )
    if draft_kind == "zero_tail":
        assert sched._speculative.acceptance_rate > 0.9
    assert leak_free(pre, dec)


@pytest.mark.slow
def test_host_bounce_path_token_identical_and_counted(lm, prompts):
    """``transfer.host_bounce=True`` forces the portable host path:
    same tokens, every handoff counted as a bounce."""
    module, params, state, variables = lm
    sched, pre, dec, transfer, _ = make_disagg(lm, host_bounce=True)
    got = [sched.submit(p, max_new_tokens=6) for p in prompts[:4]]
    sched.drain()
    for p, g in zip(prompts, got):
        np.testing.assert_array_equal(
            g.result(), oracle(module, variables, p, 6)
        )
    status = transfer.status()
    assert status["host_bounce_forced"] is True
    assert status["host_bounces"] == status["handoffs_total"] > 0
    assert leak_free(pre, dec)


@pytest.mark.slow
def test_compile_free_steady_state_on_both_roles(lm, prompts):
    """After warmup (role programs + both transfer halves), serving
    never compiles again on EITHER engine — the §22 twin of the
    single-mesh AOT discipline."""
    sched, pre, dec, transfer, _ = make_disagg(lm, warm=True)
    pre_c, dec_c = pre.compile_count, dec.compile_count
    streams = [sched.submit(p, max_new_tokens=8) for p in prompts]
    sched.drain()
    assert all(s.result().shape[0] == 8 or s.done for s in streams)
    assert transfer.handoffs >= len(prompts) - 1
    assert pre.compile_count == pre_c
    assert dec.compile_count == dec_c
    assert pre.recompiles_detected == 0
    assert dec.recompiles_detected == 0


# -- accounting / observability seams --------------------------------------


def test_transfer_metrics_and_status(lm, prompts):
    sched, pre, dec, transfer, m = make_disagg(lm, metrics=True)
    streams = [sched.submit(p, max_new_tokens=4) for p in prompts[:5]]
    sched.drain()
    [s.result() for s in streams]
    assert m.totals["transfer_handoffs_total"] == 5
    assert m.totals["transfer_pages_total"] >= 5
    assert m.totals["transfer_bytes"] > 0
    snap = m.snapshot()
    assert snap["transfer_p50_ms"] >= 0
    assert snap["transfer_p99_ms"] >= snap["transfer_p50_ms"]
    ts = transfer.status()
    assert ts["handoffs_total"] == 5
    assert ts["pages_total"] == m.totals["transfer_pages_total"]
    assert ts["bytes_total"] == m.totals["transfer_bytes"]
    assert ts["transfer_ms_p50"] > 0
    st = sched.status()
    assert st["role_topology"] == "disagg"
    assert st["prefill"]["lanes"] == 2
    assert st["prefill"]["busy_lanes"] == 0
    assert st["prefill"]["kv_pool"]["num_pages"] > 0
    assert st["transfer"]["handoffs_total"] == 5


def test_request_log_records_completing_role(lm):
    """Terminal summaries carry the role that completed dispatch:
    "decode" for a stream that crossed the seam, "prefill" for one
    finished by its first token (never transferred)."""
    sched, pre, dec, _, _ = make_disagg(lm)
    crossed = sched.submit(np.array([1, 2, 3], np.int32), max_new_tokens=4)
    first_only = sched.submit(np.array([4, 5], np.int32), max_new_tokens=1)
    sched.drain()
    crossed.result(), first_only.result()
    by_rid = {r["rid"]: r for r in sched.request_log.tail()}
    assert by_rid[crossed.rid]["role"] == "decode"
    assert by_rid[first_only.rid]["role"] == "prefill"
    assert by_rid[crossed.rid]["outcome"] == "ok"


def test_rid_flow_spans_prefill_transfer_decode(lm):
    """One request's rid links the whole §22 chain in the Chrome
    trace: prefill dispatch -> park -> page_transfer -> decode admit
    -> finish, with flow start/finish present."""
    prior = trace.get_tracer()
    trace.install(trace.Tracer(4096))
    try:
        sched, _, _, _, _ = make_disagg(lm)
        stream = sched.submit(
            np.array([1, 2, 3, 4], np.int32), max_new_tokens=4
        )
        sched.drain()
        stream.result()
        doc = trace.to_chrome_trace()
        names = [
            e["name"]
            for e in doc["traceEvents"]
            if e.get("args", {}).get("rid") == stream.rid
        ]
        for name in ("disagg_prefill_dispatch", "disagg_prefill_park",
                     "page_transfer", "disagg_decode_admit",
                     "decode_stream_finish"):
            assert name in names, (name, names)
        phases = {
            e["ph"]
            for e in doc["traceEvents"]
            if e.get("cat") == "rid" and e["id"] == stream.rid
        }
        assert phases >= {"s", "f"}
    finally:
        trace.install(prior)


def test_queued_deadline_semantics_inherit(lm):
    """deadline_ms=0 = expired-by-construction: the inherited queue
    sweep fails it before any prefill; live traffic unaffected."""
    sched, pre, dec, _, m = make_disagg(lm, metrics=True)
    p = np.array([1, 2, 3], np.int32)
    doomed = sched.submit(p, max_new_tokens=4, deadline_ms=0)
    alive = sched.submit(p, max_new_tokens=4)
    sched.drain()
    with pytest.raises(DeadlineExpiredError):
        doomed.result()
    assert doomed.tokens_so_far.shape[0] == 0
    assert alive.result().shape[0] == 4
    assert m.totals["deadline_expired_total"] == 1
    assert leak_free(pre, dec)


def test_close_fails_parked_and_lane_streams_without_leaks(lm):
    """close() with handoffs still parked: pending streams fail
    cleanly, both pools leak-free."""
    sched, pre, dec, _, _ = make_disagg(lm, slots=1)
    streams = [
        sched.submit(np.array([1, 2, 3], np.int32), max_new_tokens=32)
        for _ in range(3)
    ]
    # One synchronous iteration: prefill admits, parks, one handoff
    # lands; the rest stay parked/queued.
    sched._step_once()
    sched.close()
    assert any(s.done and s._error is not None for s in streams)
    for s in streams:
        assert s.done
    assert leak_free(pre, dec)


# -- construction validation ----------------------------------------------


def test_transfer_bind_rejects_bad_geometry(lm):
    module, params, state, _ = lm
    paged = role_engine(module, params, state, name="v_paged")
    ring = DecodeEngine()
    configure(
        ring,
        {"slots": 2, "seq_buckets": (8, 16), "kv_capacity": 64},
        name="dg_v_ring",
    )
    ring.bind(module, params, state)
    t = PageTransfer()
    configure(t, {}, name="dg_v_t")
    with pytest.raises(ValueError, match="paged"):
        t.bind(ring, paged)
    other = role_engine(
        module, params, state, name="v_ps", page_size=8
    )
    with pytest.raises(ValueError, match="page_size|transfer_width"):
        t.bind(paged, other)
    unbound = PageTransfer()
    configure(unbound, {}, name="dg_v_unbound")
    with pytest.raises(RuntimeError, match="not bound"):
        unbound.move([0], [0])


def test_scheduler_bind_rejects_mismatched_pair(lm):
    module, params, state, _ = lm
    pre = role_engine(module, params, state, name="v_pre")
    dec = role_engine(module, params, state, name="v_dec")
    other = role_engine(module, params, state, name="v_other")
    t = PageTransfer()
    configure(t, {}, name="dg_v_pair")
    t.bind(other, dec)
    sched = DisaggScheduler()
    configure(sched, {}, name="dg_v_sched")
    with pytest.raises(ValueError, match="different engine pair"):
        sched.bind(pre, dec, t)
    narrow = role_engine(
        module, params, state, name="v_narrow", seq_buckets=(8, 48)
    )
    t2 = PageTransfer()
    configure(t2, {}, name="dg_v_pair2")
    with pytest.raises(ValueError, match="transfer_width"):
        t2.bind(narrow, dec)


def test_partitioner_validates_and_falls_back_overlapping():
    bad = DisaggPartitioner()
    configure(bad, {"prefill_devices": 0}, name="dg_part_bad")
    with pytest.raises(ValueError, match="must be"):
        bad.setup()
    import jax

    huge = DisaggPartitioner()
    configure(
        huge,
        {"prefill_devices": len(jax.devices()) + 1},
        name="dg_part_huge",
    )
    with pytest.raises(ValueError, match="exceed"):
        huge.setup()
    part = DisaggPartitioner()
    configure(part, {}, name="dg_part_auto")
    part.setup()
    desc = part.describe()
    assert part.prefill.mesh is not None
    assert part.decode.mesh is not None
    if len(jax.devices()) == 1:
        # The portable fallback: both roles on device 0, flagged.
        assert not part.disjoint and not desc["disjoint"]
        assert desc["prefill_devices"] == desc["decode_devices"]
    else:
        assert part.disjoint == desc["disjoint"]
    # The ABC delegation surface answers with the DECODE role's mesh.
    assert part.mesh is part.decode.mesh


# -- chaos: the refcount-custody contract ----------------------------------


@pytest.mark.slow
@pytest.mark.chaos
def test_injected_transfer_failure_is_victim_only_and_leak_free(
    lm, prompts
):
    """FaultPlan.fail_page_transfer: the first handoff's stream fails
    with PageTransferError — its prefill-delivered first token
    readable in partials, its adopted decode pages unwound — while
    every other stream serves token-identical to the oracle and BOTH
    pools finish leak-free."""
    module, params, state, variables = lm
    sched, pre, dec, _, m = make_disagg(lm, metrics=True)
    with faults.injected(FaultPlan(fail_page_transfer=1)):
        streams = [
            sched.submit(p, max_new_tokens=8) for p in prompts[:5]
        ]
        sched.drain()
    failed = [s for s in streams if s._error is not None]
    assert len(failed) == 1
    victim = failed[0]
    with pytest.raises(PageTransferError, match="fail_page_transfer"):
        victim.result()
    # First token was delivered at prefill — partials readable.
    assert victim.tokens_so_far.shape[0] == 1
    for p, s in zip(prompts, streams):
        if s is victim:
            continue
        np.testing.assert_array_equal(
            s.result(), oracle(module, variables, p, 8)
        )
    assert leak_free(pre, dec)
    assert m.totals["transfer_handoffs_total"] == 4
    # The service keeps working after the injection drained.
    again = sched.submit(prompts[0], max_new_tokens=4)
    sched.drain()
    np.testing.assert_array_equal(
        again.result(), oracle(module, variables, prompts[0], 4)
    )
    assert leak_free(pre, dec)


@pytest.mark.slow
@pytest.mark.chaos
def test_prefill_role_crash_mid_handoff_decode_side_survives(lm):
    """FaultPlan.prefill_role_crash_at=N: the prefill role dies
    mid-handoff AFTER a stream already crossed into decode. The
    crossed stream keeps decoding to a token-identical finish (its
    slot uncorrupted), every prefill-side stream fails cleanly with
    partials readable, queued work serves on the recovered role, and
    BOTH pools finish leak-free."""
    module, params, state, variables = lm
    prompts = [
        np.array([1, 2, 3, 4, 5], np.int32),
        np.array([6, 7, 8], np.int32),
        np.array([9, 10, 11, 12], np.int32),
        np.array([13, 14], np.int32),
    ]
    sched, pre, dec, _, m = make_disagg(lm, metrics=True)
    with faults.injected(FaultPlan(prefill_role_crash_at=2)):
        streams = [sched.submit(p, max_new_tokens=8) for p in prompts]
        sched.drain()
    survivors = [s for s in streams if s._error is None]
    victims = [s for s in streams if s._error is not None]
    # Handoff 1 landed (the crossed stream); handoff 2 triggered the
    # crash, taking the in-flight stream and any stream still parked
    # or in a lane. Queued streams re-admit on the recovered role.
    assert victims
    assert len(survivors) == len(streams) - len(victims)
    for s in victims:
        with pytest.raises(WorkerCrashedError, match="prefill role"):
            s.result()
        assert s.tokens_so_far.shape[0] >= 1  # prefill token readable
    for p, s in zip(prompts, streams):
        if s in victims:
            continue
        np.testing.assert_array_equal(
            s.result(), oracle(module, variables, p, 8)
        )
    assert leak_free(pre, dec)
    assert m.totals["worker_restarts_total"] == 1
    # The recovered prefill role serves fresh traffic.
    again = sched.submit(prompts[0], max_new_tokens=4)
    sched.drain()
    np.testing.assert_array_equal(
        again.result(), oracle(module, variables, prompts[0], 4)
    )
    assert leak_free(pre, dec)


@pytest.mark.slow
@pytest.mark.chaos
def test_transfer_failure_after_warmup_stays_compile_free(lm):
    """The unwind paths allocate no new programs: an injected transfer
    failure plus recovery traffic leaves both engines at their warmup
    compile counts."""
    sched, pre, dec, _, _ = make_disagg(lm, warm=True)
    pre_c, dec_c = pre.compile_count, dec.compile_count
    with faults.injected(FaultPlan(fail_page_transfer=1)):
        streams = [
            sched.submit(np.array([1, 2, 3], np.int32), max_new_tokens=4)
            for _ in range(3)
        ]
        sched.drain()
    assert sum(1 for s in streams if s._error is not None) == 1
    assert pre.compile_count == pre_c
    assert dec.compile_count == dec_c
    assert leak_free(pre, dec)
