"""Bench regression gate: direction-aware classification, tolerance
gating, driver-wrapper loading, schema-mismatch downgrade, and the CLI
exit-code contract."""

import json

import pytest

from tools import bench_diff


# -- classification ------------------------------------------------------


@pytest.mark.parametrize(
    "name,expected",
    [
        ("value", "higher"),
        ("vs_baseline", "higher"),
        ("lm_tokens_per_sec_per_chip", "higher"),
        ("host_aug_images_per_sec_per_core", "higher"),
        ("serve_qps_per_chip", "higher"),
        ("mfu_vs_measured_peak", "higher"),
        ("ckpt_steps_overlapped_per_save", "higher"),
        ("serve_p50_ms", "lower"),
        ("serve_p99_ms", "lower"),
        ("recovery_restore_ms", "lower"),
        ("ckpt_async_save_stall_ms", "lower"),
        ("shed_rate", "lower"),
        # Decode-serving leg (ZK_BENCH_DECODE): the two gated keys the
        # acceptance criteria name, plus the ride-along latencies.
        ("serve_decode_tokens_per_sec_per_chip", "higher"),
        ("decode_ttft_p99_ms", "lower"),
        ("decode_ttft_p50_ms", "lower"),
        ("decode_token_p50_ms", "lower"),
        ("decode_prefill_p50_ms", "lower"),
    ],
)
def test_classify_metric_directions(name, expected):
    assert bench_diff.classify_metric(name) == expected


@pytest.mark.parametrize(
    "name",
    [
        "model", "metric", "unit", "n_chips", "batch_size", "unroll",
        "device_kind", "git_sha", "jax_version", "bench_schema_version",
        "peak_flops_source", "binary_compute", "obs_trace_overhead_frac",
        # Peak anchors and FLOP counts are measurement CONTEXT: a
        # re-measured peak (the BENCH_r04 237.9 pathology being fixed)
        # explains the gated numbers and must not gate itself.
        "measured_bf16_peak_tflops", "measured_int8_peak_tops",
        "model_step_tflops",
        # Decode-leg workload shape: config, not performance.
        "decode_requests", "decode_slots", "decode_new_tokens",
        "decode_refills", "decode_generated_tokens",
    ],
)
def test_identity_and_context_keys_never_gate(name):
    assert bench_diff.classify_metric(name) is None


# -- compare -------------------------------------------------------------


def _line(**kw):
    base = {
        "metric": "quicknet_train_images_per_sec_per_chip",
        "value": 1000.0,
        "unit": "images/sec/chip",
        "bench_schema_version": 1,
    }
    base.update(kw)
    return base


def test_no_gate_within_tolerance():
    diff = bench_diff.compare(_line(value=950.0), _line(value=1000.0))
    assert diff.ok
    assert not diff.regressions and not diff.improvements


def test_throughput_drop_beyond_tolerance_is_a_regression():
    diff = bench_diff.compare(_line(value=850.0), _line(value=1000.0))
    assert not diff.ok
    (row,) = diff.regressions
    assert row["name"] == "value"
    assert row["delta"] == pytest.approx(-0.15)
    assert "REGRESSION" in diff.report()


def test_latency_directions_invert():
    cur = _line(serve_p50_ms=12.0)
    prev = _line(serve_p50_ms=10.0)
    diff = bench_diff.compare(cur, prev)
    assert [r["name"] for r in diff.regressions] == ["serve_p50_ms"]
    # A latency DROP is an improvement, not a regression.
    diff2 = bench_diff.compare(prev, _line(serve_p50_ms=14.0))
    assert diff2.ok
    assert [r["name"] for r in diff2.improvements] == ["serve_p50_ms"]


def test_per_metric_tolerance_overrides_default():
    # serve_p99_ms carries a 30% override: +25% is weather, not a gate.
    diff = bench_diff.compare(
        _line(serve_p99_ms=12.5), _line(serve_p99_ms=10.0)
    )
    assert diff.ok
    diff2 = bench_diff.compare(
        _line(serve_p99_ms=14.0), _line(serve_p99_ms=10.0)
    )
    assert not diff2.ok


def test_added_removed_and_drift_never_gate():
    cur = _line(new_leg_tokens_per_sec=5.0, model="QuickNet")
    prev = _line(old_leg_qps=3.0, model="ResNet50")
    diff = bench_diff.compare(cur, prev)
    assert diff.ok
    assert "new_leg_tokens_per_sec" in diff.added
    assert "old_leg_qps" in diff.removed
    assert [d["name"] for d in diff.drift] == ["model"]


def test_schema_mismatch_downgrades_to_report_only():
    cur = _line(value=500.0, bench_schema_version=2)
    prev = _line(value=1000.0, bench_schema_version=1)
    diff = bench_diff.compare(cur, prev)
    assert diff.schema_mismatch
    assert diff.ok  # a 50% drop would gate, but renames would lie
    assert "REPORT-ONLY" in diff.report()


def test_zero_previous_reports_as_drift():
    diff = bench_diff.compare(
        _line(serve_p50_ms=5.0), _line(serve_p50_ms=0.0)
    )
    assert diff.ok
    assert any(d["name"] == "serve_p50_ms" for d in diff.drift)


def test_negative_unknown_sentinel_reports_as_drift():
    # -1.0 is the repo-wide "unknown" sentinel (MFU without cost
    # analysis, HBM without memory_stats): a measurement gap must not
    # gate as a fake regression in either direction.
    diff = bench_diff.compare(
        _line(vs_baseline=-1.0), _line(vs_baseline=0.34)
    )
    assert diff.ok
    assert any(d["name"] == "vs_baseline" for d in diff.drift)
    diff = bench_diff.compare(
        _line(vs_baseline=0.34), _line(vs_baseline=-1.0)
    )
    assert diff.ok
    assert not diff.improvements


def test_bools_and_strings_never_gate():
    diff = bench_diff.compare(
        _line(host_aug_native_available=True, peak_flops_source="measured"),
        _line(host_aug_native_available=False, peak_flops_source="env"),
    )
    assert diff.ok
    assert {d["name"] for d in diff.drift} == {
        "host_aug_native_available",
        "peak_flops_source",
    }


# -- loading -------------------------------------------------------------


def test_load_raw_line_and_driver_wrapper(tmp_path):
    raw = tmp_path / "raw.json"
    raw.write_text(json.dumps(_line()))
    assert bench_diff.load_bench_json(str(raw))["value"] == 1000.0
    # The committed BENCH_r*.json driver wrapper nests the line under
    # "parsed".
    wrapped = tmp_path / "wrapped.json"
    wrapped.write_text(
        json.dumps({"n": 5, "cmd": "bench", "rc": 0, "parsed": _line()})
    )
    assert bench_diff.load_bench_json(str(wrapped))["value"] == 1000.0


def test_load_rejects_non_bench_documents(tmp_path):
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"unrelated": 1}))
    with pytest.raises(ValueError):
        bench_diff.load_bench_json(str(bad))
    notdict = tmp_path / "notdict.json"
    notdict.write_text("[1, 2]")
    with pytest.raises(ValueError):
        bench_diff.load_bench_json(str(notdict))


def test_committed_artifacts_load():
    """The CI gate compares against the committed latest BENCH_r*.json:
    every committed artifact must stay loadable. (MULTICHIP_r*.json are
    pass/fail dryrun records with no metric line — out of scope.)"""
    import glob

    paths = sorted(glob.glob("BENCH_r*.json"))
    assert paths
    for p in paths:
        doc = bench_diff.load_bench_json(p)
        assert "metric" in doc or "value" in doc


# -- CLI contract --------------------------------------------------------


def _write(tmp_path, name, doc):
    p = tmp_path / name
    p.write_text(json.dumps(doc))
    return str(p)


def test_cli_exit_codes(tmp_path, capsys):
    cur = _write(tmp_path, "cur.json", _line(value=800.0))
    prev = _write(tmp_path, "prev.json", _line(value=1000.0))
    same = _write(tmp_path, "same.json", _line(value=1000.0))
    assert bench_diff.main([same, prev]) == 0
    assert bench_diff.main([cur, prev]) == 3
    assert bench_diff.main([cur, prev, "--allow-regression"]) == 0
    assert bench_diff.main([cur, str(tmp_path / "missing.json")]) == 2
    capsys.readouterr()


def test_cli_writes_diff_artifact(tmp_path, capsys):
    cur = _write(tmp_path, "cur.json", _line(value=800.0))
    prev = _write(tmp_path, "prev.json", _line(value=1000.0))
    out = tmp_path / "diff.json"
    assert bench_diff.main([cur, prev, "--json", str(out)]) == 3
    doc = json.loads(out.read_text())
    assert doc["ok"] is False
    assert doc["regressions"][0]["name"] == "value"
    capsys.readouterr()


def test_cli_custom_tolerance(tmp_path, capsys):
    cur = _write(tmp_path, "cur.json", _line(value=850.0))
    prev = _write(tmp_path, "prev.json", _line(value=1000.0))
    assert bench_diff.main([cur, prev]) == 3  # default 10%
    assert bench_diff.main([cur, prev, "--tol", "0.20"]) == 0
    capsys.readouterr()


def test_bench_main_wires_compare(tmp_path):
    """bench.py --compare parses and threads through to the gate (the
    full bench run needs a device; the arg contract is what CI relies
    on)."""
    import bench

    args = bench.parse_args(["--compare", "BENCH_r05.json"])
    assert args.compare == "BENCH_r05.json"
    assert args.compare_out is None
    args = bench.parse_args([])
    assert args.compare is None


def test_decode_kernel_era_keys_classify():
    """The paged-decode-kernel A/B + MBU keys (DESIGN.md §17) gate
    direction-aware; the flavor tag is config, not perf."""
    assert bench_diff.classify_metric("decode_mbu") == "higher"
    assert bench_diff.classify_metric("decode_kernel_speedup") == "higher"
    for key in (
        "decode_kernel_tokens_per_sec_per_chip",
        "decode_reference_tokens_per_sec_per_chip",
    ):
        assert bench_diff.classify_metric(key) == "higher"
    assert bench_diff.classify_metric("decode_attention_flavor") is None


def test_decode_kernel_keys_gate_with_registered_tolerances():
    from tools.bench_diff import TOLERANCES, compare

    for key in (
        "decode_mbu",
        "decode_kernel_speedup",
        "decode_kernel_tokens_per_sec_per_chip",
        "decode_reference_tokens_per_sec_per_chip",
    ):
        tol = TOLERANCES[key]
        prev = {"metric": "x", key: 1.0}
        # Just inside tolerance: no gate; past it: regression.
        ok = compare({"metric": "x", key: 1.0 - tol * 0.9}, prev)
        assert ok.ok, key
        bad = compare({"metric": "x", key: 1.0 - tol * 1.5}, prev)
        assert not bad.ok and bad.regressions[0]["name"] == key


def test_speculative_era_keys_classify():
    """The speculative-decode A/B keys (DESIGN.md §18) gate
    direction-aware: both throughputs and the speedup higher-better,
    and acceptance_rate is the one ``_rate$`` where UP is good (checked
    before the lower-better latency family); workload-shape keys are
    config, not perf."""
    for key in (
        "spec_tokens_per_sec_per_chip",
        "spec_plain_tokens_per_sec_per_chip",
        "spec_speedup",
        "spec_acceptance_rate",
    ):
        assert bench_diff.classify_metric(key) == "higher", key
    # The generic rate family stays lower-better.
    assert bench_diff.classify_metric("shed_rate") == "lower"
    for key in (
        "spec_k",
        "spec_teacher_layers",
        "spec_draft_layers",
        "spec_requests",
        "spec_slots",
        "spec_new_tokens",
    ):
        assert bench_diff.classify_metric(key) is None, key


def test_speculative_keys_gate_with_registered_tolerances():
    from tools.bench_diff import TOLERANCES, compare

    for key in (
        "spec_tokens_per_sec_per_chip",
        "spec_plain_tokens_per_sec_per_chip",
        "spec_speedup",
        "spec_acceptance_rate",
    ):
        tol = TOLERANCES[key]
        prev = {"metric": "x", key: 1.0}
        ok = compare({"metric": "x", key: 1.0 - tol * 0.9}, prev)
        assert ok.ok, key
        bad = compare({"metric": "x", key: 1.0 - tol * 1.5}, prev)
        assert not bad.ok and bad.regressions[0]["name"] == key


def test_binary_kernel_era_keys_classify():
    """The §21 binary-kernel A/B keys gate direction-aware: both
    throughputs, the speedup and the int8-anchored MFU higher-better;
    the workload shape and the flavor/source tags are config, not
    perf."""
    for key in (
        "binary_kernel_images_per_sec_per_chip",
        "binary_reference_images_per_sec_per_chip",
        "binary_kernel_speedup",
        "binary_mfu_vs_measured_int8_peak",
    ):
        assert bench_diff.classify_metric(key) == "higher", key
    for key in (
        "binary_model",
        "binary_batch",
        "binary_image",
        "binary_kernel_flavor",
        "binary_int8_peak_source",
    ):
        assert bench_diff.classify_metric(key) is None, key


def test_binary_kernel_keys_gate_with_registered_tolerances():
    from tools.bench_diff import TOLERANCES, compare

    for key in (
        "binary_kernel_images_per_sec_per_chip",
        "binary_reference_images_per_sec_per_chip",
        "binary_kernel_speedup",
        "binary_mfu_vs_measured_int8_peak",
    ):
        tol = TOLERANCES[key]
        prev = {"metric": "x", key: 1.0}
        ok = compare({"metric": "x", key: 1.0 - tol * 0.9}, prev)
        assert ok.ok, key
        bad = compare({"metric": "x", key: 1.0 - tol * 1.5}, prev)
        assert not bad.ok and bad.regressions[0]["name"] == key


def test_disagg_era_keys_classify():
    """The §22 disaggregated-serving A/B keys gate direction-aware:
    both topologies' throughputs higher-better, the TTFT tails and the
    per-handoff transfer median lower-better (``transfer_ms_p50``
    names its unit before the percentile — the explicit _LOWER entry);
    role sizes and transfer-volume tallies are config/workload, not
    perf."""
    for key in (
        "disagg_tokens_per_sec_per_chip",
        "disagg_baseline_tokens_per_sec_per_chip",
    ):
        assert bench_diff.classify_metric(key) == "higher", key
    for key in (
        "disagg_ttft_p50_ms",
        "disagg_ttft_p99_ms",
        "disagg_baseline_ttft_p50_ms",
        "disagg_baseline_ttft_p99_ms",
        "transfer_ms_p50",
    ):
        assert bench_diff.classify_metric(key) == "lower", key
    for key in (
        "disagg_requests",
        "disagg_slots",
        "disagg_lanes",
        "disagg_new_tokens",
        "disagg_transfer_handoffs",
        "disagg_transfer_pages",
        "disagg_transfer_bytes",
        "disagg_host_bounces",
        "disagg_generated_tokens",
    ):
        assert bench_diff.classify_metric(key) is None, key


def test_disagg_keys_gate_with_registered_tolerances():
    from tools.bench_diff import TOLERANCES, compare

    for key, direction in (
        ("disagg_tokens_per_sec_per_chip", "higher"),
        ("disagg_baseline_tokens_per_sec_per_chip", "higher"),
        ("disagg_ttft_p50_ms", "lower"),
        ("disagg_ttft_p99_ms", "lower"),
        ("disagg_baseline_ttft_p50_ms", "lower"),
        ("disagg_baseline_ttft_p99_ms", "lower"),
        ("transfer_ms_p50", "lower"),
    ):
        tol = TOLERANCES[key]
        sign = -1.0 if direction == "higher" else 1.0
        prev = {"metric": "x", key: 1.0}
        ok = compare({"metric": "x", key: 1.0 + sign * tol * 0.9}, prev)
        assert ok.ok, key
        bad = compare({"metric": "x", key: 1.0 + sign * tol * 1.5}, prev)
        assert not bad.ok and bad.regressions[0]["name"] == key


def test_fleet_era_keys_classify():
    """The §23 fleet-serving A/B keys gate direction-aware: both
    passes' aggregate tokens/s and the affinity speedup higher-better,
    the TTFT medians and the routing-decision latency lower-better
    (``fleet_route_ms_p50`` names its unit before the percentile —
    the explicit _LOWER entry, like ``transfer_ms_p50``); replica/
    session/turn counts, token budgets and the workload-determined
    hit rate are config, not perf (hit rate in particular ends in
    ``_rate`` — informational must win over the lower-better
    suffix)."""
    for key in (
        "fleet_tokens_per_sec",
        "fleet_rr_tokens_per_sec",
        "fleet_affinity_ttft_speedup",
    ):
        assert bench_diff.classify_metric(key) == "higher", key
    for key in (
        "fleet_warm_ttft_p50_ms",
        "fleet_rr_ttft_p50_ms",
        "fleet_cold_ttft_p50_ms",
        "fleet_route_ms_p50",
    ):
        assert bench_diff.classify_metric(key) == "lower", key
    for key in (
        "fleet_replicas",
        "fleet_sessions",
        "fleet_turns",
        "fleet_shared_tokens",
        "fleet_tail_tokens",
        "fleet_new_tokens",
        "fleet_affinity_hit_rate",
        "fleet_generated_tokens",
    ):
        assert bench_diff.classify_metric(key) is None, key


def test_fleet_keys_gate_with_registered_tolerances():
    from tools.bench_diff import TOLERANCES, compare

    for key, direction in (
        ("fleet_tokens_per_sec", "higher"),
        ("fleet_rr_tokens_per_sec", "higher"),
        ("fleet_affinity_ttft_speedup", "higher"),
        ("fleet_warm_ttft_p50_ms", "lower"),
        ("fleet_rr_ttft_p50_ms", "lower"),
        ("fleet_cold_ttft_p50_ms", "lower"),
        ("fleet_route_ms_p50", "lower"),
    ):
        tol = TOLERANCES[key]
        sign = -1.0 if direction == "higher" else 1.0
        prev = {"metric": "x", key: 1.0}
        ok = compare({"metric": "x", key: 1.0 + sign * tol * 0.9}, prev)
        assert ok.ok, key
        bad = compare({"metric": "x", key: 1.0 + sign * tol * 1.5}, prev)
        assert not bad.ok and bad.regressions[0]["name"] == key


def test_trace_slo_era_keys_classify():
    """The §24 guardrails A/B keys gate direction-aware: goodput and
    shed precision higher-better (precision has no suffix family —
    the explicit _HIGHER entry), the admitted p99 TTFT lower-better;
    the baseline pass exists to be WORSE under overload, so every
    ``trace_baseline_*`` key is informational along with the pinned
    workload shape and outcome tallies."""
    for key in (
        "trace_goodput_tokens_per_sec",
        "trace_shed_precision",
    ):
        assert bench_diff.classify_metric(key) == "higher", key
    assert bench_diff.classify_metric(
        "trace_admitted_ttft_p99_ms"
    ) == "lower"
    for key in (
        "trace_baseline_goodput_tokens_per_sec",
        "trace_baseline_admitted_ttft_p99_ms",
        "trace_baseline_deadline_expired",
        "trace_baseline_ok",
        "trace_requests",
        "trace_deadline_ms",
        "trace_shed_total",
        "trace_ok_total",
        "trace_deadline_expired",
    ):
        assert bench_diff.classify_metric(key) is None, key


def test_trace_slo_keys_gate_with_registered_tolerances():
    from tools.bench_diff import TOLERANCES, compare

    for key, direction in (
        ("trace_goodput_tokens_per_sec", "higher"),
        ("trace_shed_precision", "higher"),
        ("trace_admitted_ttft_p99_ms", "lower"),
    ):
        tol = TOLERANCES[key]
        sign = -1.0 if direction == "higher" else 1.0
        prev = {"metric": "x", key: 1.0}
        ok = compare({"metric": "x", key: 1.0 + sign * tol * 0.9}, prev)
        assert ok.ok, key
        # 1.2x tolerance keeps the bad value positive even for the
        # loose precision tolerance (a sign flip reads as drift).
        bad = compare({"metric": "x", key: 1.0 + sign * tol * 1.2}, prev)
        assert not bad.ok and bad.regressions[0]["name"] == key


def test_chunked_era_keys_classify():
    """The §25 chunked-prefill A/B keys gate direction-aware: the ITL
    improvement ratio and goodput higher-better (the ratio has no
    suffix family — the explicit _HIGHER entry), the chunked ITL/TTFT
    tails lower-better; the monolithic baseline pass exists to STALL,
    so every ``chunked_baseline_*`` key is informational along with
    the pinned workload shape (chunk size, long-prompt length/count,
    request and token tallies)."""
    for key in (
        "chunked_itl_improvement",
        "chunked_goodput_tokens_per_sec",
    ):
        assert bench_diff.classify_metric(key) == "higher", key
    for key in ("chunked_itl_p99_ms", "chunked_ttft_p99_ms"):
        assert bench_diff.classify_metric(key) == "lower", key
    for key in (
        "chunked_baseline_itl_p99_ms",
        "chunked_baseline_ttft_p99_ms",
        "chunked_baseline_goodput_tokens_per_sec",
        "chunked_chunk_tokens",
        "chunked_long_prompt_len",
        "chunked_long_arrivals",
        "chunked_requests",
        "chunked_generated_tokens",
    ):
        assert bench_diff.classify_metric(key) is None, key


def test_chunked_keys_gate_with_registered_tolerances():
    from tools.bench_diff import TOLERANCES, compare

    for key, direction in (
        ("chunked_itl_improvement", "higher"),
        ("chunked_goodput_tokens_per_sec", "higher"),
        ("chunked_itl_p99_ms", "lower"),
        ("chunked_ttft_p99_ms", "lower"),
    ):
        tol = TOLERANCES[key]
        sign = -1.0 if direction == "higher" else 1.0
        prev = {"metric": "x", key: 1.0}
        ok = compare({"metric": "x", key: 1.0 + sign * tol * 0.9}, prev)
        assert ok.ok, key
        bad = compare({"metric": "x", key: 1.0 + sign * tol * 1.2}, prev)
        assert not bad.ok and bad.regressions[0]["name"] == key
