"""Test configuration.

Forces JAX onto the host CPU platform with 8 virtual devices BEFORE jax is
first imported anywhere in the test session — the standard JAX fake-cluster
trick (SURVEY.md §4) — so mesh/pjit/collective tests run without TPU
hardware. Bench and real-TPU runs do not go through this file.
"""

import os

# Force (not setdefault): the environment pre-sets JAX_PLATFORMS to the
# real TPU platform, but tests must run on the virtual CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# Keep single-core CI boxes responsive — but stop at level 2 (INFO +
# WARNING suppressed, ERROR kept): GSPMD's "Involuntary full
# rematerialization" diagnostic is an E-level line that level 3 now
# SWALLOWS on this XLA version (the old "the warning bypasses level-3
# filtering" observation rotted), which silently blinded every
# SPMD-log-cleanliness assertion and its canary.
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "2")

# The machine's sitecustomize registers the real TPU backend
# programmatically (overriding JAX_PLATFORMS from the environment), so the
# platform must also be reset at the config level.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    # The serving-subsystem marker (select with `-m serving`). Serving
    # unit tests are CPU-safe and thread-free in tier 1; the threaded
    # batcher paths (async coalescing, QPS soak) additionally carry
    # `slow` and stay out of the tier-1 run.
    config.addinivalue_line(
        "markers",
        "serving: dynamic-batching inference subsystem tests",
    )
    # Deterministic fault-injection / recovery tests (select with
    # `-m chaos` — the CI chaos step runs exactly this subset on CPU).
    # Fast single-fault legs run in tier 1; multi-restart soaks
    # additionally carry `slow`.
    config.addinivalue_line(
        "markers",
        "chaos: deterministic fault-injection and recovery tests",
    )
