"""Unit tests for the shared VMEM-aware block policies (ops/blocks.py).

The flash / decode / resid policies moved here from attention.py and
binary_compute.py in docs/DESIGN.md §21 with behavior pinned by their
pre-existing tests (test_ring_attention.py, test_paged_decode_attention.py,
test_pack_residuals.py); this file covers the re-export identity (the
historical import sites must resolve to the SAME objects, not copies),
the pure-shape-arithmetic contract, and the new §21 binary policies.
"""

import pytest

from zookeeper_tpu.ops import blocks


# -- re-export identity ------------------------------------------------------


def test_attention_reexports_are_the_blocks_objects():
    """attention.py re-exports the moved policies unchanged: same
    function OBJECTS, so a policy fix lands everywhere at once and the
    historical import sites (bench.py, tests) cannot drift."""
    from zookeeper_tpu.ops import attention

    assert attention._default_flash_blocks is blocks._default_flash_blocks
    assert attention._flash_bwd_vmem_estimate is blocks._flash_bwd_vmem_estimate
    assert attention._default_decode_blocks is blocks._default_decode_blocks
    assert attention._decode_vmem_estimate is blocks._decode_vmem_estimate
    assert attention._FLASH_VMEM_BUDGET == blocks._FLASH_VMEM_BUDGET


def test_binary_compute_imports_are_the_blocks_objects():
    from zookeeper_tpu.ops import binary_compute

    assert binary_compute._resid_blocks is blocks._resid_blocks
    assert binary_compute._round_up is blocks._round_up
    assert binary_compute._divisor_at_most is blocks._divisor_at_most
    assert binary_compute._RESID_BLOCK_BYTES == blocks._RESID_BLOCK_BYTES


def test_blocks_module_is_jax_free():
    """The module contract: pure shape arithmetic, importable without a
    backend (tools and tests size grids without touching jax)."""
    import importlib
    import sys

    assert "jax" not in blocks.__dict__
    # Source-level check too: no lazy import hiding in a function body.
    import inspect

    src = inspect.getsource(blocks)
    assert "import jax" not in src
    # And it must be importable fresh without jax already loaded having
    # polluted sys.modules is not checkable here; the dict check above
    # plus the source check pin the intent.
    importlib.reload(sys.modules["zookeeper_tpu.ops.blocks"])


# -- shared helpers ----------------------------------------------------------


def test_round_up_and_divisor_at_most():
    assert blocks._round_up(1, 8) == 8
    assert blocks._round_up(8, 8) == 8
    assert blocks._round_up(9, 8) == 16
    assert blocks._divisor_at_most(48, 16) == 16
    assert blocks._divisor_at_most(48, 15) == 12
    assert blocks._divisor_at_most(7, 4) == 1  # prime: falls to 1


# -- flash / decode / resid (moved verbatim; spot-pin the headline cases) ----


def test_flash_policy_headline_cases():
    # Sweep winner at the LM leg's pinned config.
    assert blocks._default_flash_blocks(8192, None, None) == (1024, 1024)
    # Awkward length falls back (padding waste > 1/8 at big blocks).
    assert blocks._default_flash_blocks(1100, None, None)[0] <= 128
    # Explicit blocks pass through untouched.
    assert blocks._default_flash_blocks(4096, 256, 512) == (256, 512)


def test_decode_policy_headline_cases():
    assert blocks._default_decode_blocks(2048, 8, 128, page_size=16)[0] == 256
    with pytest.raises(ValueError):
        blocks._default_decode_blocks(64, 4, 64, block_kv=24)


def test_resid_blocks_divide_and_fit_budget():
    for h, w, c, itemsize in [(7, 9, 64, 1), (32, 32, 512, 4), (1, 1, 3, 2)]:
        bh, bw = blocks._resid_blocks(h, w, c, itemsize)
        assert h % bh == 0 and w % bw == 0
        assert 32 * c * itemsize * bh * bw <= max(
            blocks._RESID_BLOCK_BYTES, 32 * c * itemsize
        )


# -- §21 binary policies -----------------------------------------------------


def test_binary_gemm_blocks_legal_floor_and_budget():
    """Every auto selection is Mosaic-legal (output dims multiples of
    128 — lane floor; word axis 8 or 16) and inside the VMEM budget."""
    for m, n, kw in [
        (1, 1, 1), (130, 72, 3), (8192, 512, 144), (512, 4096, 16),
        (100000, 128, 8), (128, 100000, 8),
    ]:
        bm, bn, bkw = blocks._default_binary_gemm_blocks(m, n, kw)
        assert bm % 128 == 0 and bn % 128 == 0
        assert bkw in (8, 16)
        assert (
            blocks._binary_gemm_vmem_estimate(bm, bn, bkw)
            <= blocks._BINARY_GEMM_VMEM_BUDGET
        )


def test_binary_gemm_blocks_promote_only_on_big_divisible_axes():
    # Small problem: stays at the 128x128 floor.
    assert blocks._default_binary_gemm_blocks(130, 72, 16) == (128, 128, 16)
    # Large divisible axes promote (padding waste 0 < 1/8); m is
    # promoted first, and n follows as far as the budget allows (at the
    # 8-word depth both fit; at 16 the xor intermediate pins n to 128).
    assert blocks._default_binary_gemm_blocks(8192, 4096, 8) == (512, 256, 8)
    bm, bn, _ = blocks._default_binary_gemm_blocks(8192, 4096, 16)
    assert bm == 512 and bn == 128
    # Awkward axis just past a big block does NOT promote (waste > 1/8).
    bm, _, _ = blocks._default_binary_gemm_blocks(520, 128, 16)
    assert bm == 128


def test_binary_conv_block_n_floor_cap_and_budget():
    # Never below the 128-lane floor, never above 512 / padded co.
    assert blocks._default_binary_conv_block_n(16, 8, 64) == 128
    assert blocks._default_binary_conv_block_n(7, 1, 4096) == 512
    # A huge per-tap intermediate demotes by halving but stops at 128.
    bn = blocks._default_binary_conv_block_n(224, 144, 512)
    assert bn >= 128 and bn % 128 == 0
    assert (
        224 * 144 * bn * 4 <= blocks._BINARY_CONV_VMEM_BUDGET or bn == 128
    )


def test_pack_rows_block_aligned_and_bounded():
    for k, itemsize in [(32, 4), (4608, 4), (4608, 2), (10**6, 4), (32, 1)]:
        rows = blocks._default_pack_rows_block(k, itemsize)
        # 32-aligned: a multiple of every dtype's sublane tile.
        assert rows % 32 == 0
        assert 32 <= rows <= 256
    # Bigger K -> fewer rows (budget-bound), floored at 32.
    assert blocks._default_pack_rows_block(10**6) == 32
    assert blocks._default_pack_rows_block(32) == 256
