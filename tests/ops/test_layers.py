import jax
import jax.numpy as jnp
import numpy as np
import pytest

from zookeeper_tpu.ops import QuantConv, QuantDense


def test_quant_dense_binary_forward():
    layer = QuantDense(
        features=4, input_quantizer="ste_sign", kernel_quantizer="ste_sign",
        use_bias=False,
    )
    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 8)), jnp.float32)
    params = layer.init(jax.random.PRNGKey(0), x)
    y = layer.apply(params, x)
    # Output of +-1 inputs dot +-1 kernel over 8 terms: even ints in [-8, 8].
    vals = np.asarray(y)
    assert np.all(np.abs(vals) <= 8)
    assert np.allclose(vals, np.round(vals))
    assert np.all(np.mod(vals, 2) == np.mod(8, 2) % 2)


def test_quant_dense_latent_weights_fp32_and_trainable():
    layer = QuantDense(features=3, kernel_quantizer="ste_sign")
    x = jnp.ones((4, 5))
    params = layer.init(jax.random.PRNGKey(0), x)
    assert params["params"]["kernel"].dtype == jnp.float32

    def loss(p):
        return (layer.apply(p, x) ** 2).sum()

    grads = jax.grad(loss)(params)
    # STE: latent kernel receives nonzero gradient.
    assert float(jnp.abs(grads["params"]["kernel"]).sum()) > 0


def test_kernel_clip_projects_forward_only():
    layer = QuantDense(features=2, kernel_quantizer=None, kernel_clip=True,
                       use_bias=False)
    x = jnp.ones((1, 2))
    params = layer.init(jax.random.PRNGKey(0), x)
    # Unquantized kernels register as kernel_fp (excluded from the binary
    # param pattern).
    big = {"params": {"kernel_fp": jnp.array([[3.0, -3.0], [0.5, -0.5]])}}
    y = layer.apply(big, x)
    # Forward sees clipped kernel: 1 + .5 = 1.5 ; -1 + -.5 = -1.5.
    np.testing.assert_allclose(np.asarray(y)[0], [1.5, -1.5])
    g = jax.grad(lambda p: layer.apply(p, x).sum())(big)
    # Gradient passes straight through the clip.
    np.testing.assert_allclose(np.asarray(g["params"]["kernel_fp"]), 1.0)


def test_quant_conv_matches_manual_sign_conv():
    layer = QuantConv(
        features=2, kernel_size=(3, 3), kernel_quantizer="ste_sign",
        input_quantizer=None, padding="VALID",
    )
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(1, 5, 5, 1)), jnp.float32)
    params = layer.init(jax.random.PRNGKey(0), x)
    y = layer.apply(params, x)
    kernel = np.asarray(params["params"]["kernel"])
    signk = np.where(np.clip(kernel, -1, 1) >= 0, 1.0, -1.0)
    manual = jax.lax.conv_general_dilated(
        x, jnp.asarray(signk), (1, 1), "VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    np.testing.assert_allclose(np.asarray(y), np.asarray(manual), rtol=1e-5)
    assert y.shape == (1, 3, 3, 2)


def test_quant_conv_bf16_compute():
    layer = QuantConv(
        features=4, kernel_size=(3, 3), input_quantizer="ste_sign",
        kernel_quantizer="ste_sign", dtype=jnp.bfloat16,
    )
    x = jnp.ones((2, 8, 8, 3))
    params = layer.init(jax.random.PRNGKey(0), x)
    y = layer.apply(params, x)
    assert y.dtype == jnp.bfloat16
    assert params["params"]["kernel"].dtype == jnp.float32


def test_binary_layer_trains():
    import optax

    layer = QuantDense(
        features=2, input_quantizer="ste_sign", kernel_quantizer="ste_sign"
    )
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(64, 16)), jnp.float32)
    w_true = jnp.asarray(rng.normal(size=(16,)))
    y_true = (x @ w_true > 0).astype(jnp.int32)
    params = layer.init(jax.random.PRNGKey(0), x)
    tx = optax.adam(0.01)
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state):
        def loss_fn(p):
            logits = layer.apply(p, x)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, y_true
            ).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    losses = []
    for _ in range(60):
        params, opt_state, loss = step(params, opt_state)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.8


def test_quant_depthwise_conv_int8_matches_mxu():
    from zookeeper_tpu.ops import QuantDepthwiseConv

    rng = np.random.default_rng(31)
    x = jnp.asarray(rng.normal(size=(2, 8, 8, 16)), jnp.float32)
    kwargs = dict(
        channel_multiplier=2, kernel_size=(3, 3),
        input_quantizer="ste_sign", kernel_quantizer="ste_sign",
    )
    mxu = QuantDepthwiseConv(**kwargs, binary_compute="mxu")
    i8 = QuantDepthwiseConv(**kwargs, binary_compute="int8")
    params = mxu.init(jax.random.key(0), x)
    y_mxu = mxu.apply(params, x)
    y_i8 = i8.apply(params, x)
    assert y_mxu.shape == (2, 8, 8, 32)
    np.testing.assert_array_equal(np.asarray(y_mxu), np.asarray(y_i8))
    # Gradients agree too (custom_vjp path).
    g1 = jax.grad(lambda p: (mxu.apply(p, x) ** 2).sum())(params)
    g2 = jax.grad(lambda p: (i8.apply(p, x) ** 2).sum())(params)
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5)


def test_quant_depthwise_rejects_packed_modes():
    from zookeeper_tpu.ops import QuantDepthwiseConv

    x = jnp.zeros((1, 4, 4, 8), jnp.float32)
    conv = QuantDepthwiseConv(
        input_quantizer="ste_sign", kernel_quantizer="ste_sign",
        binary_compute="xnor",
    )
    with pytest.raises(ValueError, match="depthwise"):
        conv.init(jax.random.key(0), x)


def test_quant_separable_conv_larq_dataflow():
    """larq semantics: the depthwise output reaches the pointwise stage
    UNQUANTIZED (magnitudes preserved) unless intermediate_quantizer is
    set explicitly."""
    from zookeeper_tpu.ops import QuantSeparableConv

    rng = np.random.default_rng(33)
    x = jnp.asarray(rng.normal(size=(2, 8, 8, 32)), jnp.float32)
    sep = QuantSeparableConv(
        features=24, kernel_size=(3, 3), strides=(2, 2),
        input_quantizer="ste_sign", depthwise_quantizer="ste_sign",
        pointwise_quantizer="ste_sign",
    )
    params = sep.init(jax.random.key(0), x)
    y = sep.apply(params, x)
    assert y.shape == (2, 4, 4, 24)
    # With the intermediate re-binarized the result must differ (the
    # depthwise output carries non-unit magnitudes).
    sep_q = QuantSeparableConv(
        features=24, kernel_size=(3, 3), strides=(2, 2),
        input_quantizer="ste_sign", depthwise_quantizer="ste_sign",
        pointwise_quantizer="ste_sign", intermediate_quantizer="ste_sign",
    )
    y_q = sep_q.apply(params, x)
    assert not np.allclose(np.asarray(y), np.asarray(y_q))
    # A binarized intermediate enables the packed pointwise stage, which
    # must then match its mxu twin bit-for-bit.
    sep_x = QuantSeparableConv(
        features=24, kernel_size=(3, 3), strides=(2, 2),
        input_quantizer="ste_sign", depthwise_quantizer="ste_sign",
        pointwise_quantizer="ste_sign", intermediate_quantizer="ste_sign",
        pointwise_compute="xnor", pallas_interpret=True,
    )
    y_x = sep_x.apply(params, x)
    np.testing.assert_array_equal(np.asarray(y_q), np.asarray(y_x))
    # Unquantized intermediate + a binary pointwise path must raise, not
    # silently degrade.
    sep_bad = QuantSeparableConv(
        features=24, input_quantizer="ste_sign",
        depthwise_quantizer="ste_sign", pointwise_quantizer="ste_sign",
        pointwise_compute="int8",
    )
    with pytest.raises(ValueError, match="input_quantizer"):
        sep_bad.apply(params, x)


def test_int8_conv_exact_with_magnitude_aware_kernels():
    """The int8 path must carry per-channel kernel scales exactly
    (Bi-Real-Net's magnitude_aware_sign weights) instead of stripping
    them with a bare sign cast."""
    from zookeeper_tpu.ops import QuantConv

    rng = np.random.default_rng(35)
    x = jnp.asarray(rng.normal(size=(2, 8, 8, 16)), jnp.float32)
    kwargs = dict(
        features=8, kernel_size=(3, 3), input_quantizer="ste_sign",
        kernel_quantizer="magnitude_aware_sign",
    )
    mxu = QuantConv(**kwargs, binary_compute="mxu")
    i8 = QuantConv(**kwargs, binary_compute="int8")
    params = mxu.init(jax.random.key(0), x)
    y_mxu = np.asarray(mxu.apply(params, x))
    y_i8 = np.asarray(i8.apply(params, x))
    assert np.abs(y_mxu).max() > 0
    np.testing.assert_allclose(y_i8, y_mxu, rtol=1e-5, atol=1e-5)


def test_int8_rejects_fractional_input_quantizer():
    from zookeeper_tpu.ops import QuantConv

    x = jnp.zeros((1, 4, 4, 8), jnp.float32)
    conv = QuantConv(
        features=4, input_quantizer="dorefa", kernel_quantizer="ste_sign",
        binary_compute="int8",
    )
    with pytest.raises(ValueError, match="non-integer"):
        conv.init(jax.random.key(0), x)
