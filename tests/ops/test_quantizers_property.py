"""Property-based tests for the STE quantizer family over random
inputs (the hand-written suite pins exact values at chosen points; this
sweeps randomized tensors away from the surrogate boundaries and checks
transform consistency, which point tests can't).

Properties per quantizer:
- forward lands exactly on the documented level set;
- the custom_vjp gradient matches an independent numpy oracle of the
  published surrogate (indicator-family quantizers; inputs sampled away
  from the clip boundaries where the <=/< convention is pinned by the
  point tests instead);
- grad-under-jit == grad == grad-under-vmap (custom_vjp must be
  transform-transparent — the property that actually matters when the
  quantizer sits inside a pjit'd train step).
"""

import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from zookeeper_tpu.ops import (
    approx_sign,
    dorefa,
    ste_heaviside,
    ste_sign,
    ste_tern,
)


def rand_x(rng, shape, margin=0.05):
    """Uniform in [-2, 2], nudged away from the surrogate boundaries
    (|x| = 1 for the sign family, {0, 1} for dorefa, threshold for
    tern) so the oracle never straddles a <=/< convention."""
    x = rng.uniform(-2.0, 2.0, size=shape)
    for b in (-1.0, 0.0, 1.0):
        near = np.abs(x - b) < margin
        x = np.where(near, x + 2 * margin, x)
    return x.astype(np.float32)


CASES = [
    (
        "ste_sign",
        lambda x: ste_sign(x),
        lambda x: np.where(x >= 0, 1.0, -1.0),
        lambda x: (np.abs(x) <= 1.0).astype(np.float32),
    ),
    (
        "approx_sign",
        lambda x: approx_sign(x),
        lambda x: np.where(x >= 0, 1.0, -1.0),
        lambda x: np.where(np.abs(x) <= 1.0, 2.0 - 2.0 * np.abs(x), 0.0),
    ),
    (
        "ste_heaviside",
        lambda x: ste_heaviside(x),
        lambda x: (x > 0).astype(np.float32),
        lambda x: (np.abs(x) <= 1.0).astype(np.float32),
    ),
    (
        "ste_tern",
        lambda x: ste_tern(x, 0.3, False),
        lambda x: np.where(x > 0.3, 1.0, np.where(x < -0.3, -1.0, 0.0)),
        lambda x: (np.abs(x) <= 1.0).astype(np.float32),
    ),
    (
        "dorefa2",
        lambda x: dorefa(x, 2),
        # Half-UP like the implementation (floor(x*n + 0.5) — NOT
        # np.round, whose half-to-even convention differs at the level
        # midpoints), same float32 arithmetic on both sides.
        lambda x: np.floor(
            np.clip(x, 0.0, 1.0).astype(np.float32) * np.float32(3.0)
            + np.float32(0.5)
        )
        / np.float32(3.0),
        lambda x: ((x >= 0.0) & (x <= 1.0)).astype(np.float32),
    ),
]


@pytest.mark.parametrize("seed", range(8))
@pytest.mark.parametrize("name,fn,fwd_oracle,grad_oracle", CASES)
def test_quantizer_forward_and_grad_match_oracle(
    seed, name, fn, fwd_oracle, grad_oracle
):
    rng = np.random.default_rng(seed)
    shape = random.Random(seed).choice(((7,), (3, 5), (2, 3, 4)))
    x = rand_x(rng, shape)
    if name == "ste_tern":
        # Keep clear of this case's +-0.3 thresholds too.
        x = np.where(np.abs(np.abs(x) - 0.3) < 0.05, x + 0.1, x)

    xj = jnp.asarray(x)
    np.testing.assert_allclose(
        np.asarray(fn(xj)), fwd_oracle(x), atol=1e-6, err_msg=name
    )

    # Cotangent-weighted VJP against the oracle: grad of sum(fn * w)
    # must be w * surrogate'(x) elementwise (checks the vjp actually
    # scales the incoming cotangent, not just the mask).
    w = rng.uniform(-1.0, 1.0, size=shape).astype(np.float32)
    wj = jnp.asarray(w)
    g = jax.grad(lambda v: (fn(v) * wj).sum())(xj)
    np.testing.assert_allclose(
        np.asarray(g), w * grad_oracle(x), atol=1e-5, err_msg=name
    )

    # Transform transparency: identical under jit and vmap (leading
    # axis) — the composition a pjit'd train step relies on.
    g_jit = jax.jit(jax.grad(lambda v: (fn(v) * wj).sum()))(xj)
    np.testing.assert_allclose(np.asarray(g_jit), np.asarray(g), err_msg=name)
    if len(shape) > 1:
        g_vmap = jax.vmap(
            jax.grad(lambda v, ww: (fn(v) * ww).sum()), in_axes=(0, 0)
        )(xj, wj)
        np.testing.assert_allclose(
            np.asarray(g_vmap), np.asarray(g), atol=1e-6, err_msg=name
        )
