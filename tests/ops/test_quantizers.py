import jax
import jax.numpy as jnp
import numpy as np
import pytest

from zookeeper_tpu.ops import (
    approx_sign,
    dorefa,
    get_quantizer,
    magnitude_aware_sign,
    ste_heaviside,
    ste_sign,
    ste_tern,
    swish_sign,
)


def grad_at(fn, x):
    return jax.vmap(jax.grad(lambda v: fn(v).sum()))(x[:, None])[:, 0]


def test_ste_sign_forward():
    x = jnp.array([-2.0, -0.5, 0.0, 0.5, 2.0])
    np.testing.assert_array_equal(ste_sign(x), [-1, -1, 1, 1, 1])


def test_ste_sign_gradient_clipped_identity():
    x = jnp.array([-2.0, -0.99, 0.0, 0.99, 2.0])
    g = jax.grad(lambda v: ste_sign(v).sum())(x)
    np.testing.assert_array_equal(g, [0.0, 1.0, 1.0, 1.0, 0.0])


def test_approx_sign_gradient_triangular():
    x = jnp.array([-2.0, -0.5, 0.0, 0.5, 2.0])
    g = jax.grad(lambda v: approx_sign(v).sum())(x)
    np.testing.assert_allclose(g, [0.0, 1.0, 2.0, 1.0, 0.0])


def test_swish_sign_gradient_peak_at_zero():
    x = jnp.array([-3.0, 0.0, 3.0])
    g = jax.grad(lambda v: swish_sign(v).sum())(x)
    assert g[1] > g[0] and g[1] > g[2]
    # d/dx SignSwish at 0 is exactly beta (default 5).
    assert float(g[1]) == pytest.approx(5.0, rel=1e-3)


def test_magnitude_aware_sign_scale():
    w = jnp.array([[0.5, -1.0], [0.25, 2.0]])  # per-output-channel scale
    out = magnitude_aware_sign(w)
    # scale over all but last axis: col0 mean(|.5|,|.25|)=0.375, col1 1.5
    np.testing.assert_allclose(out, [[0.375, -1.5], [0.375, 1.5]])
    g = jax.grad(lambda v: magnitude_aware_sign(v).sum())(w)
    np.testing.assert_allclose(g, [[0.375, 1.5], [0.375, 0.0]])


def test_ste_tern_thresholds():
    x = jnp.array([-1.0, -0.01, 0.0, 0.01, 1.0])
    np.testing.assert_array_equal(
        ste_tern(x, 0.05, False), [-1.0, 0.0, 0.0, 0.0, 1.0]
    )
    # TWN mode: threshold = 0.7 * mean|x|.
    x2 = jnp.array([1.0, 1.0, 0.5, -1.0])  # mean=0.875, thr=0.6125
    np.testing.assert_array_equal(ste_tern(x2, 0.05, True), [1, 1, 0, -1])


def test_ste_heaviside():
    x = jnp.array([-0.5, 0.0, 0.5])
    np.testing.assert_array_equal(ste_heaviside(x), [0.0, 0.0, 1.0])
    g = jax.grad(lambda v: ste_heaviside(v).sum())(jnp.array([-2.0, 0.5, 2.0]))
    np.testing.assert_array_equal(g, [0.0, 1.0, 0.0])


def test_dorefa_levels():
    x = jnp.array([-0.5, 0.0, 0.3, 0.5, 1.0, 2.0])
    out = dorefa(x, 1)  # 1 bit: levels {0, 1}
    np.testing.assert_array_equal(out, [0, 0, 0, 1, 1, 1])
    out2 = dorefa(x, 2)  # 2 bits: levels {0, 1/3, 2/3, 1}
    np.testing.assert_allclose(out2, [0, 0, 1 / 3, 2 / 3, 1, 1], atol=1e-6)
    g = jax.grad(lambda v: dorefa(v, 2).sum())(x)
    np.testing.assert_array_equal(g, [0, 1, 1, 1, 1, 0])


def test_quantizers_preserve_dtype_bf16():
    x = jnp.array([-0.5, 0.5], jnp.bfloat16)
    for fn in (ste_sign, approx_sign, ste_heaviside):
        assert fn(x).dtype == jnp.bfloat16


def test_get_quantizer_resolution():
    assert get_quantizer("ste_sign") is ste_sign
    assert get_quantizer(None) is None
    assert get_quantizer(ste_sign) is ste_sign
    with pytest.raises(ValueError, match="Unknown quantizer"):
        get_quantizer("nope")


def test_ste_sign_shard_transparent():
    # Gradient parity: single-device vs 8-way sharded input.
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    x = jnp.asarray(np.random.default_rng(0).normal(size=(16, 64)), jnp.float32)
    f = lambda v: ste_sign(v).sum()
    g1 = jax.grad(f)(x)
    mesh = Mesh(np.array(jax.devices()).reshape(8), ("data",))
    xs = jax.device_put(x, NamedSharding(mesh, PartitionSpec("data")))
    g2 = jax.jit(jax.grad(f))(xs)
    np.testing.assert_array_equal(np.asarray(g1), np.asarray(g2))
