"""1-bit residual residency: bit-exactness of the packed fwd->bwd paths.

The lever (VERDICT r3 next #1) stores the +-1 conv-input residual and the
ste_sign pass-through mask BIT-PACKED between forward and backward. The
contract is that numerics are IDENTICAL — every test here pins bitwise
equality of outputs and gradients against the unpacked baseline.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from zookeeper_tpu.ops import (
    QuantConv,
    int8_conv,
    mask_mul_resid,
    pack_resid,
    ste_sign,
    ste_sign_packed,
    unpack_resid_pm1,
)


def random_signs(shape, seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.choice([-1.0, 1.0], size=shape), dtype)


# -- residual kernels (Pallas, interpret on CPU) ----------------------------


@pytest.mark.parametrize(
    "shape",
    [
        (2, 5, 33),  # rank 3, batch far below the 32-deep word (pads)
        (3, 4096),  # rank 2 (dense residuals)
        (2, 7, 7, 65),  # rank 4, odd channels
        (64, 3, 5, 8),  # two full 32-batch word groups
        (33, 2, 2, 3, 4),  # rank 5 + batch one past a word boundary
    ],
)
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.slow
def test_pack_resid_pm1_roundtrip(shape, dtype):
    x = random_signs(shape, seed=2, dtype=dtype)
    words = pack_resid(x)
    assert words.dtype == jnp.int32
    # Words pack along BATCH on the layout-normalized 4-D shape.
    assert words.shape[0] == -(-shape[0] // 32)
    out = unpack_resid_pm1(words, shape, dtype)
    assert out.dtype == dtype
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))


def test_pack_resid_rejects_unbatched():
    with pytest.raises(ValueError, match="batched"):
        pack_resid(random_signs((4096,)))


@pytest.mark.parametrize("shape", [(2, 5, 33), (3, 4096), (64, 3, 5, 8)])
def test_pack_resid_mask_mul(shape):
    # Mask mode packs |x| <= 1; mask_mul_resid fuses unpack * g.
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=shape) * 1.5, jnp.float32)
    g = jnp.asarray(rng.normal(size=shape), jnp.float32)
    words = pack_resid(x, mask_mode=True)
    got = mask_mul_resid(g, words)
    expected = g * (jnp.abs(x) <= 1.0).astype(g.dtype)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(expected))


# -- ste_sign_packed --------------------------------------------------------


def test_ste_sign_packed_forward_matches():
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(4, 37)) * 2.0, jnp.float32)
    np.testing.assert_array_equal(
        np.asarray(ste_sign_packed(x)), np.asarray(ste_sign(x))
    )


@pytest.mark.parametrize("c", [64, 37])
def test_ste_sign_packed_grad_matches(c):
    # Values straddling the |x| <= 1 boundary, including exactly +-1 (the
    # mask is inclusive there) and larger magnitudes (mask off).
    rng = np.random.default_rng(4)
    vals = rng.normal(size=(6, c)) * 1.5
    vals.flat[:4] = [1.0, -1.0, 1.0000001, -1.0000001]
    x = jnp.asarray(vals, jnp.float32)
    g = jnp.asarray(rng.normal(size=x.shape), jnp.float32)

    base = jax.vjp(ste_sign, x)[1](g)[0]
    packed = jax.vjp(ste_sign_packed, x)[1](g)[0]
    np.testing.assert_array_equal(np.asarray(packed), np.asarray(base))


# -- int8_conv packed residuals ---------------------------------------------


@pytest.mark.parametrize(
    "ci,strides,padding",
    [(32, (1, 1), "SAME"), (7, (2, 2), "SAME"), (64, (1, 1), "VALID")],
)
def test_int8_conv_pack_residuals_exact(ci, strides, padding):
    x = random_signs((2, 8, 8, ci), seed=5)
    rng = np.random.default_rng(6)
    k = jnp.asarray(
        rng.choice([-1.0, 1.0], size=(3, 3, ci, 5)), jnp.float32
    )

    def run(pack):
        def f(x, k):
            return int8_conv(x, k, strides, padding, 1, True, pack).sum()

        loss, grads = jax.value_and_grad(f, argnums=(0, 1))(x, k)
        return loss, *grads

    base = run(False)
    packed = run(True)
    for b, p in zip(base, packed):
        np.testing.assert_array_equal(np.asarray(p), np.asarray(b))


def test_int8_conv_pack_residuals_grouped_exact():
    # Depthwise-style grouping: ci recovered as k.shape[-2] * groups.
    ci, groups = 8, 8
    x = random_signs((2, 6, 6, ci), seed=7)
    rng = np.random.default_rng(8)
    k = jnp.asarray(
        rng.choice([-1.0, 1.0], size=(3, 3, ci // groups, ci)), jnp.float32
    )

    def run(pack):
        def f(x, k):
            return int8_conv(
                x, k, (1, 1), "SAME", groups, False, pack
            ).sum()

        return jax.grad(f, argnums=(0, 1))(x, k)

    for b, p in zip(run(False), run(True)):
        np.testing.assert_array_equal(np.asarray(p), np.asarray(b))


def test_int8_conv_pack_residuals_bf16_exact():
    # The north-star regime: bf16 compute dtype, fp32 cotangent.
    x = random_signs((2, 8, 8, 32), seed=9, dtype=jnp.bfloat16)
    k = random_signs((3, 3, 32, 16), seed=10)

    def run(pack):
        def f(x, k):
            return int8_conv(x, k, (1, 1), "SAME", 1, True, pack).sum()

        dx, dk = jax.grad(f, argnums=(0, 1))(x, k)
        assert dx.dtype == jnp.bfloat16
        return dx, dk

    for b, p in zip(run(False), run(True)):
        np.testing.assert_array_equal(np.asarray(p), np.asarray(b))


# -- QuantConv threading ----------------------------------------------------


def _quantconv_loss_and_grads(pack_residuals, dtype=jnp.bfloat16):
    layer = QuantConv(
        12,
        (3, 3),
        input_quantizer="ste_sign",
        kernel_quantizer="ste_sign",
        binary_compute="int8",
        pack_residuals=pack_residuals,
        dtype=dtype,
    )
    rng = np.random.default_rng(11)
    # Pre-quantizer inputs around the STE boundary, not pre-binarized:
    # this exercises BOTH packed residuals (mask + conv input) at once.
    x = jnp.asarray(rng.normal(size=(2, 8, 8, 20)) * 1.3, dtype)
    params = layer.init(jax.random.PRNGKey(0), x)

    def loss(params, x):
        return (layer.apply(params, x).astype(jnp.float32) ** 2).sum()

    l, grads = jax.value_and_grad(loss)(params, x)
    gx = jax.grad(lambda x: loss(params, x))(x)
    return l, grads, gx


@pytest.mark.slow
def test_quantconv_pack_residuals_end_to_end_exact():
    l0, g0, gx0 = _quantconv_loss_and_grads(False)
    l1, g1, gx1 = _quantconv_loss_and_grads(True)
    np.testing.assert_array_equal(np.asarray(l1), np.asarray(l0))
    np.testing.assert_array_equal(np.asarray(gx1), np.asarray(gx0))
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)
        ),
        g1,
        g0,
    )


def test_quantconv_pack_residuals_requires_int8():
    layer = QuantConv(
        4,
        (3, 3),
        input_quantizer="ste_sign",
        kernel_quantizer="ste_sign",
        binary_compute="mxu",
        pack_residuals=True,
    )
    x = jnp.zeros((1, 4, 4, 4))
    with pytest.raises(ValueError, match="pack_residuals"):
        layer.init(jax.random.PRNGKey(0), x)


def test_quantconv_pack_residuals_rejects_ternary_input():
    # ste_tern emits 0s, which 1-bit packing would corrupt — loud error.
    layer = QuantConv(
        4,
        (3, 3),
        input_quantizer="ste_tern",
        kernel_quantizer="ste_sign",
        binary_compute="int8",
        pack_residuals=True,
    )
    x = jnp.zeros((1, 4, 4, 4))
    with pytest.raises(ValueError, match="other than \\+-1"):
        layer.init(jax.random.PRNGKey(0), x)


def test_quantconv_pack_residuals_rejects_packed_weights():
    layer = QuantConv(
        4,
        (3, 3),
        input_quantizer="ste_sign",
        kernel_quantizer="ste_sign",
        binary_compute="xnor",
        packed_weights=True,
        pack_residuals=True,
        pallas_interpret=True,
    )
    x = jnp.zeros((1, 4, 4, 32))
    with pytest.raises(ValueError, match="inference-only"):
        layer.init(jax.random.PRNGKey(0), x)


def test_quicknet_pack_residuals_field_threads():
    from zookeeper_tpu.core import configure
    from zookeeper_tpu.models import QuickNet

    model = QuickNet()
    configure(
        model,
        {
            "binary_compute": "int8",
            "pack_residuals": True,
            "blocks_per_section": (1, 1),
            "section_features": (8, 16),
        },
        name="model",
    )
    module = model.build((32, 32, 3), num_classes=10)
    params, model_state = model.initialize(module, (32, 32, 3))
    rng = np.random.default_rng(12)
    x = jnp.asarray(rng.normal(size=(2, 32, 32, 3)), jnp.float32)
    out = module.apply(
        {"params": params, **model_state}, x, training=False
    )
    assert out.shape == (2, 10)
