import jax
import jax.numpy as jnp
import numpy as np
import pytest

from zookeeper_tpu.ops import (
    int8_conv,
    int8_matmul,
    pack_bits,
    unpack_bits,
    xnor_matmul,
)


def random_signs(shape, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.choice([-1.0, 1.0], size=shape), jnp.float32)


def test_pack_unpack_roundtrip():
    x = random_signs((4, 64))
    packed = pack_bits(x)
    assert packed.shape == (4, 2)
    assert packed.dtype == jnp.int32
    np.testing.assert_array_equal(np.asarray(unpack_bits(packed, 64)), np.asarray(x))


def test_pack_bits_axis():
    x = random_signs((32, 5))
    packed = pack_bits(x, axis=0)
    assert packed.shape == (1, 5)
    np.testing.assert_array_equal(
        np.asarray(unpack_bits(packed, 32, axis=0)), np.asarray(x)
    )


def test_pack_bits_requires_multiple_of_32():
    with pytest.raises(ValueError, match="multiple of 32"):
        pack_bits(random_signs((4, 33)))


def test_xnor_matmul_matches_float(interpret=True):
    a = random_signs((17, 96), seed=1)
    b = random_signs((96, 23), seed=2)
    expected = np.asarray(a @ b)
    got = np.asarray(xnor_matmul(a, b, interpret=True, block_m=8, block_n=8))
    np.testing.assert_array_equal(got, expected)


def test_xnor_matmul_k_padding():
    # K not a multiple of 32: symmetric padding must cancel exactly.
    a = random_signs((5, 40), seed=3)
    b = random_signs((40, 7), seed=4)
    expected = np.asarray(a @ b)
    got = np.asarray(xnor_matmul(a, b, interpret=True, block_m=8, block_n=8))
    np.testing.assert_array_equal(got, expected)


def test_int8_matmul_matches_float():
    a = random_signs((16, 64), seed=5)
    b = random_signs((64, 8), seed=6)
    np.testing.assert_array_equal(
        np.asarray(int8_matmul(a, b)), np.asarray(a @ b)
    )


def test_int8_matmul_preserves_zeros():
    # Same "exact on {-1, 0, +1}" contract as int8_conv: a literal 0
    # operand contributes 0, not sign(0)-mapped garbage.
    a = np.array(random_signs((8, 32), seed=9))
    b = np.array(random_signs((32, 4), seed=10))
    a[:, ::3] = 0.0
    b[::5, :] = 0.0
    np.testing.assert_array_equal(
        np.asarray(int8_matmul(jnp.asarray(a), jnp.asarray(b))), a @ b
    )


def test_int8_conv_matches_float_conv():
    x = random_signs((2, 8, 8, 16), seed=7)
    k = random_signs((3, 3, 16, 8), seed=8)
    expected = jax.lax.conv_general_dilated(
        x, k, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC")
    )
    got = int8_conv(x, k, (1, 1), "SAME")
    np.testing.assert_array_equal(np.asarray(got), np.asarray(expected))


def test_int8_conv_gradients_match_float_conv():
    x = random_signs((1, 6, 6, 4), seed=9)
    k = random_signs((3, 3, 4, 2), seed=10)

    def loss_int8(x, k):
        return (int8_conv(x, k, (1, 1), "SAME") ** 2).sum()

    def loss_float(x, k):
        y = jax.lax.conv_general_dilated(
            x, k, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC")
        )
        return (y**2).sum()

    gx1, gk1 = jax.grad(loss_int8, argnums=(0, 1))(x, k)
    gx2, gk2 = jax.grad(loss_float, argnums=(0, 1))(x, k)
    np.testing.assert_allclose(np.asarray(gx1), np.asarray(gx2), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gk1), np.asarray(gk2), rtol=1e-5)


def test_quant_conv_int8_path_matches_mxu_path():
    from zookeeper_tpu.ops import QuantConv

    x = jnp.asarray(
        np.random.default_rng(11).normal(size=(2, 8, 8, 8)), jnp.float32
    )
    kwargs = dict(
        features=4, kernel_size=(3, 3), input_quantizer="ste_sign",
        kernel_quantizer="ste_sign",
    )
    mxu = QuantConv(**kwargs, binary_compute="mxu")
    i8 = QuantConv(**kwargs, binary_compute="int8")
    params = mxu.init(jax.random.PRNGKey(0), x)
    y1 = mxu.apply(params, x)
    y2 = i8.apply(params, x)
    np.testing.assert_array_equal(np.asarray(y1), np.asarray(y2))
    # Gradients agree too (STE through both paths).
    g1 = jax.grad(lambda p: (mxu.apply(p, x) ** 2).sum())(params)
    g2 = jax.grad(lambda p: (i8.apply(p, x) ** 2).sum())(params)
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5)


def test_xnor_matmul_large_shapes_interpret():
    # Multi-block grid path (block 128 with 150x260 output).
    a = random_signs((150, 128), seed=12)
    b = random_signs((128, 260), seed=13)
    got = np.asarray(xnor_matmul(a, b, interpret=True))
    np.testing.assert_array_equal(got, np.asarray(a @ b))
