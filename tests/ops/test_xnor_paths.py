"""Pallas packed binary paths: K-tiled kernels, conv wiring, packed
inference, and the loud-fallback contract.

All Pallas calls run in interpreter mode (CPU test suite); the bench
exercises the compiled kernels on real TPU hardware.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from zookeeper_tpu.ops import (
    QuantConv,
    magnitude_aware_sign,
    pack_conv_kernel,
    pack_quantconv_params,
    packed_conv_infer,
    packed_weight_matmul,
    xnor_conv,
    xnor_matmul,
)


def random_signs(shape, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.choice([-1.0, 1.0], size=shape), jnp.float32)


def float_conv(x, k, strides=(1, 1), padding="SAME"):
    return jax.lax.conv_general_dilated(
        x, k, strides, padding, dimension_numbers=("NHWC", "HWIO", "NHWC")
    )


# -- kernels ----------------------------------------------------------------


def test_xnor_matmul_k_tiled_large_k():
    """QuickNet's largest contraction (K=4608) through the K-tiled kernel:
    the round-1 kernel kept full K per block and overflowed VMEM here."""
    a = random_signs((32, 4608), seed=1)
    b = random_signs((4608, 32), seed=2)
    got = np.asarray(xnor_matmul(a, b, interpret=True, block_kw=16))
    np.testing.assert_array_equal(got, np.asarray(a @ b))


def test_xnor_matmul_k_tiling_is_exact_across_block_sizes():
    a = random_signs((16, 256), seed=3)
    b = random_signs((256, 16), seed=4)
    expected = np.asarray(a @ b)
    for block_kw in (1, 2, 8):
        got = np.asarray(
            xnor_matmul(a, b, interpret=True, block_kw=block_kw)
        )
        np.testing.assert_array_equal(got, expected)


def test_packed_weight_matmul_matches_float_with_zeros():
    """The MXU-unpack kernel: A may contain zeros (conv padding), only B
    is packed — result exact vs the float GEMM."""
    rng = np.random.default_rng(5)
    a = jnp.asarray(rng.choice([-1.0, 0.0, 1.0], size=(48, 96)), jnp.float32)
    b = random_signs((96, 40), seed=6)
    from zookeeper_tpu.ops import pack_bits

    bp = pack_bits(b, axis=0)
    got = np.asarray(packed_weight_matmul(a, bp, interpret=True))
    np.testing.assert_array_equal(got, np.asarray(a @ b).astype(np.int32))


def test_packed_weight_matmul_k_tiled():
    a = random_signs((8, 2048), seed=7)
    b = random_signs((2048, 8), seed=8)
    from zookeeper_tpu.ops import pack_bits

    bp = pack_bits(b, axis=0)
    got = np.asarray(
        packed_weight_matmul(a, bp, interpret=True, block_kw=8)
    )
    np.testing.assert_array_equal(got, np.asarray(a @ b).astype(np.int32))


# -- conv paths -------------------------------------------------------------


@pytest.mark.parametrize("strides", [(1, 1), (2, 2)])
@pytest.mark.parametrize("padding", ["SAME", "VALID"])
def test_xnor_conv_bit_exact_vs_float(strides, padding):
    x = random_signs((2, 9, 9, 40), seed=9)
    k = random_signs((3, 3, 40, 8), seed=10)
    expected = np.asarray(float_conv(x, k, strides, padding))
    got = np.asarray(
        xnor_conv(x, k, strides, padding, False, True)
    )
    np.testing.assert_array_equal(got, expected)


def test_xnor_conv_popcount_valid_bit_exact():
    x = random_signs((2, 8, 8, 64), seed=11)
    k = random_signs((3, 3, 64, 8), seed=12)
    expected = np.asarray(float_conv(x, k, (1, 1), "VALID"))
    got = np.asarray(xnor_conv(x, k, (1, 1), "VALID", True, True))
    np.testing.assert_array_equal(got, expected)


def test_xnor_conv_popcount_same_uses_one_padding():
    """Documented deviation: the bit-serial kernel one-pads SAME. Check
    against a float conv on an explicitly +1-padded input."""
    x = random_signs((1, 6, 6, 32), seed=13)
    k = random_signs((3, 3, 32, 4), seed=14)
    x_padded = jnp.pad(
        x, ((0, 0), (1, 1), (1, 1), (0, 0)), constant_values=1.0
    )
    expected = np.asarray(float_conv(x_padded, k, (1, 1), "VALID"))
    got = np.asarray(xnor_conv(x, k, (1, 1), "SAME", True, True))
    np.testing.assert_array_equal(got, expected)


def test_xnor_conv_magnitude_aware_scale():
    """Kernel = sign x per-channel scale (Bi-Real-Net weight path) must be
    handled exactly by the pack/scale split."""
    rng = np.random.default_rng(15)
    latent = jnp.asarray(rng.normal(size=(3, 3, 32, 8)), jnp.float32)
    q = magnitude_aware_sign(latent)
    x = random_signs((2, 6, 6, 32), seed=16)
    expected = np.asarray(float_conv(x, q, (1, 1), "SAME"))
    got = np.asarray(xnor_conv(x, q, (1, 1), "SAME", False, True))
    # Not bit-identical to the float conv: the packed path computes the
    # EXACT integer sum then scales once, while the float conv rounds
    # per-element — the difference is float-associativity noise only.
    np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-5)


def test_xnor_conv_gradients_match_float_conv():
    x = random_signs((1, 6, 6, 32), seed=17)
    k = random_signs((3, 3, 32, 4), seed=18)

    def loss_xnor(x, k):
        return (xnor_conv(x, k, (1, 1), "SAME", False, True) ** 2).sum()

    def loss_float(x, k):
        return (float_conv(x, k, (1, 1), "SAME") ** 2).sum()

    gx1, gk1 = jax.grad(loss_xnor, argnums=(0, 1))(x, k)
    gx2, gk2 = jax.grad(loss_float, argnums=(0, 1))(x, k)
    np.testing.assert_allclose(np.asarray(gx1), np.asarray(gx2), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gk1), np.asarray(gk2), rtol=1e-5)


def test_packed_conv_infer_matches_training_forward():
    x = random_signs((2, 7, 7, 32), seed=19)
    k = random_signs((3, 3, 32, 8), seed=20)
    packed, scale = pack_conv_kernel(k)
    assert packed.shape == (3, 3, 1, 8)
    y_train = np.asarray(xnor_conv(x, k, (1, 1), "SAME", False, True))
    y_infer = np.asarray(
        packed_conv_infer(x, packed, scale, (1, 1), "SAME", interpret=True)
    )
    np.testing.assert_array_equal(y_infer, y_train)


# -- QuantConv wiring -------------------------------------------------------


def _quantconv_pair(binary_compute, **extra):
    kwargs = dict(
        features=8,
        kernel_size=(3, 3),
        input_quantizer="ste_sign",
        kernel_quantizer="ste_sign",
        pallas_interpret=True,
        **extra,
    )
    mxu = QuantConv(**kwargs, binary_compute="mxu")
    other = QuantConv(**kwargs, binary_compute=binary_compute)
    return mxu, other


def test_quantconv_xnor_matches_mxu_bit_exact():
    x = jnp.asarray(
        np.random.default_rng(21).normal(size=(2, 8, 8, 32)), jnp.float32
    )
    mxu, xnor = _quantconv_pair("xnor")
    params = mxu.init(jax.random.PRNGKey(0), x)
    np.testing.assert_array_equal(
        np.asarray(mxu.apply(params, x)), np.asarray(xnor.apply(params, x))
    )
    g1 = jax.grad(lambda p: (mxu.apply(p, x) ** 2).sum())(params)
    g2 = jax.grad(lambda p: (xnor.apply(p, x) ** 2).sum())(params)
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5)


def test_quantconv_loud_errors_no_silent_fallback():
    x = jnp.zeros((1, 4, 4, 32), jnp.float32)
    # Unusable int8: no quantizers.
    conv = QuantConv(features=4, binary_compute="int8")
    with pytest.raises(ValueError, match="never falls back silently"):
        conv.init(jax.random.PRNGKey(0), x)
    # Unusable int8: explicit pad tuples.
    conv = QuantConv(
        features=4, binary_compute="int8", input_quantizer="ste_sign",
        kernel_quantizer="ste_sign", padding=((1, 1), (1, 1)),
    )
    with pytest.raises(ValueError, match="padding"):
        conv.init(jax.random.PRNGKey(0), x)
    # Non-sign kernel quantizer on a packed path.
    conv = QuantConv(
        features=4, binary_compute="xnor", input_quantizer="ste_sign",
        kernel_quantizer="ste_tern",
    )
    with pytest.raises(ValueError, match="sign x per-channel"):
        conv.init(jax.random.PRNGKey(0), x)
    # Unknown mode.
    conv = QuantConv(features=4, binary_compute="warp")
    with pytest.raises(ValueError, match="unknown binary_compute"):
        conv.init(jax.random.PRNGKey(0), x)
    # packed_weights without a packed mode.
    conv = QuantConv(
        features=4, binary_compute="int8", input_quantizer="ste_sign",
        kernel_quantizer="ste_sign", packed_weights=True,
    )
    with pytest.raises(ValueError, match="packed_weights"):
        conv.init(jax.random.PRNGKey(0), x)


def test_xnor_conv_popcount_same_gradients_match_one_padded_forward():
    """The popcount backward must be the VJP of the function actually
    computed (one-padded SAME), not the zero-padded float conv."""
    x = random_signs((1, 5, 5, 32), seed=30)
    k = random_signs((3, 3, 32, 4), seed=31)

    def loss_pop(x, k):
        return (xnor_conv(x, k, (1, 1), "SAME", True, True) ** 2).sum()

    def loss_ref(x, k):
        xp = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)), constant_values=1.0)
        return (float_conv(xp, k, (1, 1), "VALID") ** 2).sum()

    gx1, gk1 = jax.grad(loss_pop, argnums=(0, 1))(x, k)
    gx2, gk2 = jax.grad(loss_ref, argnums=(0, 1))(x, k)
    np.testing.assert_allclose(np.asarray(gx1), np.asarray(gx2), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gk1), np.asarray(gk2), rtol=1e-5)


def test_quantconv_input_quantizer_validation_for_packed_paths():
    x = jnp.zeros((1, 4, 4, 32), jnp.float32)
    # dorefa emits fractions: int8 cast would truncate on the xnor path.
    conv = QuantConv(
        features=4, binary_compute="xnor", input_quantizer="dorefa",
        kernel_quantizer="ste_sign",
    )
    with pytest.raises(ValueError, match="non-integer"):
        conv.init(jax.random.PRNGKey(0), x)
    # ste_tern emits zeros: fine for xnor (0 * w = 0) ...
    conv = QuantConv(
        features=4, binary_compute="xnor", input_quantizer="ste_tern",
        kernel_quantizer="ste_sign", pallas_interpret=True,
    )
    conv.init(jax.random.PRNGKey(0), x)
    # ... but NOT for popcount (0 would pack as the +1 bit).
    conv = QuantConv(
        features=4, binary_compute="xnor_popcount", input_quantizer="ste_tern",
        kernel_quantizer="ste_sign",
    )
    with pytest.raises(ValueError, match="other than \\+-1"):
        conv.init(jax.random.PRNGKey(0), x)


def test_packed_conv_infer_raises_under_differentiation():
    from zookeeper_tpu.ops import pack_conv_kernel as pck

    x = random_signs((1, 4, 4, 32), seed=32)
    k = random_signs((3, 3, 32, 4), seed=33)
    packed, scale = pck(k)

    def loss(x):
        return (
            packed_conv_infer(x, packed, scale, (1, 1), "SAME", interpret=True)
            ** 2
        ).sum()

    with pytest.raises(ValueError, match="inference-only"):
        jax.grad(loss)(x)


def test_binarynet_first_conv_stays_fp_under_binary_modes():
    """BinaryNet's first conv takes fp input; requesting int8/xnor for the
    model must not make that layer's validation explode."""
    from zookeeper_tpu.core import configure
    from zookeeper_tpu.models import BinaryNet

    model = BinaryNet()
    configure(
        model,
        {
            "features": (16, 16),
            "dense_units": (32,),
            "binary_compute": "xnor",
            "pallas_interpret": True,
        },
        name="model",
    )
    module = model.build((8, 8, 1), num_classes=4)
    x = jnp.asarray(
        np.random.default_rng(34).normal(size=(2, 8, 8, 1)), jnp.float32
    )
    variables = module.init(jax.random.PRNGKey(0), x, training=False)
    y = module.apply(variables, x, training=False)
    assert y.shape == (2, 4)
    assert np.isfinite(np.asarray(y)).all()


def test_quantconv_packed_weights_params_are_32x_smaller():
    x = jnp.zeros((1, 8, 8, 64), jnp.float32)
    conv = QuantConv(
        features=16, binary_compute="xnor", input_quantizer="ste_sign",
        kernel_quantizer="ste_sign", packed_weights=True,
        pallas_interpret=True,
    )
    params = conv.init(jax.random.PRNGKey(0), x)["params"]
    assert set(params) == {"kernel_packed", "kernel_scale"}
    assert params["kernel_packed"].shape == (3, 3, 2, 16)  # 64/32 words
    assert params["kernel_packed"].dtype == jnp.int32
    float_bytes = 3 * 3 * 64 * 16 * 4
    packed_bytes = params["kernel_packed"].size * 4 + 16 * 4
    assert packed_bytes * 28 < float_bytes  # ~32x (scale overhead aside)


@pytest.mark.slow
def test_quicknet_large_inference_through_pallas_bit_exact():
    """The flagship criterion: QuickNet-Large (full depth, reduced input
    resolution for CPU runtime) runs inference through the Pallas packed
    path bit-exactly vs the mxu path."""
    from zookeeper_tpu.core import configure
    from zookeeper_tpu.models import QuickNetLarge

    def build(binary_compute):
        model = QuickNetLarge()
        configure(
            model,
            {"binary_compute": binary_compute, "pallas_interpret": True},
            name="model",
        )
        return model.build((32, 32, 3), num_classes=1000)

    x = jnp.asarray(
        np.random.default_rng(23).normal(size=(1, 32, 32, 3)), jnp.float32
    )
    mxu_module = build("mxu")
    variables = mxu_module.init(jax.random.PRNGKey(0), x, training=False)
    y_mxu = mxu_module.apply(variables, x, training=False)
    y_xnor = build("xnor").apply(variables, x, training=False)
    np.testing.assert_array_equal(np.asarray(y_mxu), np.asarray(y_xnor))


def test_pack_quantconv_params_round_trip_quicknet():
    """The LCE-converter contract on the flagship family: train-float
    params -> packed params, packed model output bit-exact vs float."""
    from zookeeper_tpu.core import configure
    from zookeeper_tpu.models import QuickNet

    def build(packed, bc="xnor", flavor="auto"):
        model = QuickNet()
        configure(
            model,
            {
                "blocks_per_section": (1, 1),
                "section_features": (32, 64),
                "binary_compute": bc,
                "packed_weights": packed,
                "pallas_interpret": True,
                "binary_flavor": flavor,
            },
            name="model",
        )
        return model.build((32, 32, 3), num_classes=10)

    x = jnp.asarray(
        np.random.default_rng(22).normal(size=(2, 32, 32, 3)), jnp.float32
    )
    float_module = build(False)
    variables = float_module.init(jax.random.PRNGKey(0), x, training=False)
    y_float = float_module.apply(variables, x, training=False)

    packed_module = build(True)
    packed_params = pack_quantconv_params(variables["params"])
    packed_vars = {**variables, "params": packed_params}
    y_packed = packed_module.apply(packed_vars, x, training=False)
    np.testing.assert_array_equal(np.asarray(y_float), np.asarray(y_packed))
    # §21 flavor seam on the popcount deployment: the fused Pallas
    # kernels (interpret mode as the numerics vehicle) must produce
    # IDENTICAL logits to the reference composition on the same packed
    # params — the zoo-level certification of the kernel bit-identity.
    y_pc_ref = build(True, bc="xnor_popcount", flavor="reference").apply(
        packed_vars, x, training=False
    )
    y_pc_pallas = build(True, bc="xnor_popcount", flavor="pallas").apply(
        packed_vars, x, training=False
    )
    np.testing.assert_array_equal(
        np.asarray(y_pc_ref), np.asarray(y_pc_pallas)
    )
    # Structure matches what the packed module would declare.
    ref = jax.eval_shape(
        lambda: packed_module.init(jax.random.PRNGKey(0), x, training=False)
    )
    assert jax.tree_util.tree_structure(
        ref["params"]
    ) == jax.tree_util.tree_structure(packed_params)


def test_fused_and_per_tap_schedules_bit_identical():
    """The auto-fused (one launch, tap-major K) and per-tap (streamed)
    schedules of the packed conv must agree bit-for-bit, for both kernels
    and both paddings."""
    import numpy as np

    from zookeeper_tpu.ops.binary_compute import (
        _packed_conv_forward,
        pack_conv_kernel,
    )

    rng = np.random.default_rng(11)
    x = jnp.asarray(
        np.sign(rng.normal(size=(2, 9, 9, 40))).astype(np.float32)
    )
    k = jnp.asarray(
        np.sign(rng.normal(size=(3, 3, 40, 8))).astype(np.float32)
    )
    packed, scale = pack_conv_kernel(k)
    for use_pc in (False, True):
        for padding in ("SAME", "VALID"):
            for strides in ((1, 1), (2, 2)):
                fused = _packed_conv_forward(
                    x, packed, scale, strides, padding, ci=40,
                    use_popcount=use_pc, interpret=True, fuse_taps=True,
                )
                per_tap = _packed_conv_forward(
                    x, packed, scale, strides, padding, ci=40,
                    use_popcount=use_pc, interpret=True, fuse_taps=False,
                )
                np.testing.assert_array_equal(
                    np.asarray(fused), np.asarray(per_tap),
                    err_msg=f"{use_pc=} {padding=} {strides=}",
                )


def test_mixed_per_section_deployment_matches_float():
    """Mixed deployment (BASELINE.md): deep sections packed, early
    sections on the plain path. Template-aware packing converts ONLY the
    layers the deployment model declares packed, and the mixed apply is
    bit-exact vs the all-mxu float model."""
    import jax
    import numpy as np

    from zookeeper_tpu.core import configure
    from zookeeper_tpu.models import QuickNet
    from zookeeper_tpu.ops.packed import pack_quantconv_params

    def build(bc, pw):
        m = QuickNet()
        configure(
            m,
            {"blocks_per_section": (1, 1), "section_features": (32, 64),
             "binary_compute": bc, "packed_weights": pw,
             "pallas_interpret": True},
            name="m",
        )
        module = m.build((16, 16, 3), num_classes=5)
        return m, module

    m_f, mod_f = build("mxu", False)
    params, model_state = m_f.initialize(mod_f, (16, 16, 3))

    _, mod_mixed = build(("mxu", "xnor"), (False, True))
    abstract = jax.eval_shape(
        lambda: mod_mixed.init(
            jax.random.key(0), jnp.zeros((1, 16, 16, 3)), training=False
        )
    )
    mixed_params = pack_quantconv_params(
        params, template=abstract["params"]
    )

    # Only section-2 convs converted.
    from flax import traverse_util

    flat = traverse_util.flatten_dict(mixed_params, sep="/")
    packed_keys = [k for k in flat if k.endswith("kernel_packed")]
    latent_keys = [
        k for k in flat if "QuantConv" in k and k.endswith("/kernel")
    ]
    assert len(packed_keys) == 1 and len(latent_keys) == 1

    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.normal(size=(2, 16, 16, 3)), jnp.float32)
    y_float = mod_f.apply({"params": params, **model_state}, x, training=False)
    y_mixed = mod_mixed.apply(
        {"params": mixed_params, **model_state}, x, training=False
    )
    np.testing.assert_array_equal(np.asarray(y_float), np.asarray(y_mixed))


def test_per_section_tuple_length_validated():
    from zookeeper_tpu.core import configure
    from zookeeper_tpu.models import QuickNet

    m = QuickNet()
    configure(m, {"binary_compute": ("mxu", "xnor")}, name="m")  # 4 sections
    with pytest.raises(ValueError, match="sections"):
        m.build((32, 32, 3), num_classes=10)


def test_pack_template_mismatch_raises():
    import jax

    from zookeeper_tpu.core import configure
    from zookeeper_tpu.models import QuickNet
    from zookeeper_tpu.ops.packed import pack_quantconv_params

    m = QuickNet()
    configure(
        m,
        {"blocks_per_section": (1, 1), "section_features": (32, 64),
         "binary_compute": "xnor", "packed_weights": True,
         "pallas_interpret": True},
        name="m",
    )
    module = m.build((16, 16, 3), num_classes=5)
    abstract = jax.eval_shape(
        lambda: module.init(
            jax.random.key(0), jnp.zeros((1, 16, 16, 3)), training=False
        )
    )
    m_f = QuickNet()
    configure(
        m_f,
        {"blocks_per_section": (1, 1), "section_features": (32, 64)},
        name="m_f",
    )
    mod_f = m_f.build((16, 16, 3), num_classes=5)
    params, _ = m_f.initialize(mod_f, (16, 16, 3))
    # Whole eval_shape result instead of its ["params"] subtree: nothing
    # matches, which must raise instead of silently packing nothing.
    with pytest.raises(ValueError, match="structurally match"):
        pack_quantconv_params(params, template=abstract)
