"""N-D quantized conv layers: QuantConv1D / QuantConv3D / QuantConvTranspose.

The rank-generic mxu/int8 paths must agree with each other bit-exactly on
quantized operands (same exactness argument as the 2-D paths), and the 1-D
layer must agree with the 2-D layer on a height-1 embedding of the same
problem (the cross-rank consistency oracle).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from zookeeper_tpu.ops import (
    QuantConv,
    QuantConv1D,
    QuantConv3D,
    QuantConvTranspose,
)
from zookeeper_tpu.ops.layers import BINARY_KERNEL_PATTERN


def _binary(layer_cls, **kw):
    return layer_cls(
        input_quantizer="ste_sign", kernel_quantizer="ste_sign", **kw
    )


def test_conv1d_matches_conv2d_height1_embedding():
    rng = np.random.default_rng(0)
    x1 = jnp.asarray(rng.normal(size=(2, 16, 8)), jnp.float32)
    l1 = _binary(QuantConv1D, features=4, kernel_size=(3,), padding="SAME")
    p1 = l1.init(jax.random.PRNGKey(0), x1)
    y1 = l1.apply(p1, x1)

    # Same kernel as [1, 3, ci, co] in the 2-D layer on [N, 1, W, C].
    l2 = _binary(QuantConv, features=4, kernel_size=(1, 3), padding="SAME")
    k1 = p1["params"]["kernel"]
    p2 = {"params": {"kernel": k1[None]}}
    y2 = l2.apply(p2, x1[:, None])
    np.testing.assert_array_equal(np.asarray(y1), np.asarray(y2)[:, 0])


@pytest.mark.parametrize("cls,shape,ks", [
    (QuantConv1D, (2, 16, 32), (3,)),
    (QuantConv3D, (2, 6, 6, 6, 32), (3, 3, 3)),
])
def test_nd_int8_bit_exact_vs_mxu(cls, shape, ks):
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=shape), jnp.float32)
    kw = dict(features=8, kernel_size=ks, padding="SAME")
    mxu = _binary(cls, binary_compute="mxu", **kw)
    i8 = _binary(cls, binary_compute="int8", **kw)
    params = mxu.init(jax.random.PRNGKey(1), x)
    y_mxu = mxu.apply(params, x)
    y_i8 = i8.apply(params, x)
    np.testing.assert_array_equal(np.asarray(y_mxu), np.asarray(y_i8))


def test_nd_int8_gradients_match_mxu():
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(2, 10, 16)), jnp.float32)
    kw = dict(features=4, kernel_size=(3,), padding="VALID")
    mxu = _binary(QuantConv1D, binary_compute="mxu", **kw)
    i8 = _binary(QuantConv1D, binary_compute="int8", **kw)
    params = mxu.init(jax.random.PRNGKey(2), x)

    def loss(layer, p):
        return (layer.apply(p, x) ** 2).sum()

    g_mxu = jax.grad(lambda p: loss(mxu, p))(params)
    g_i8 = jax.grad(lambda p: loss(i8, p))(params)
    np.testing.assert_allclose(
        np.asarray(g_mxu["params"]["kernel"]),
        np.asarray(g_i8["params"]["kernel"]),
        rtol=1e-5,
    )
    assert float(jnp.abs(g_i8["params"]["kernel"]).sum()) > 0


def test_conv3d_strided_output_shape_and_parity():
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(1, 8, 8, 8, 4)), jnp.float32)
    layer = _binary(
        QuantConv3D, features=6, kernel_size=(3, 3, 3), strides=(2, 2, 2),
        padding="SAME", binary_compute="int8",
    )
    params = layer.init(jax.random.PRNGKey(3), x)
    y = layer.apply(params, x)
    assert y.shape == (1, 4, 4, 4, 6)
    # Integer-valued output (exact binary accumulation).
    vals = np.asarray(y)
    np.testing.assert_allclose(vals, np.round(vals))


def test_conv_transpose_int8_bit_exact_vs_mxu():
    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.normal(size=(2, 7, 7, 16)), jnp.float32)
    kw = dict(features=8, kernel_size=(3, 3), strides=(2, 2), padding="SAME")
    mxu = _binary(QuantConvTranspose, binary_compute="mxu", **kw)
    i8 = _binary(QuantConvTranspose, binary_compute="int8", **kw)
    params = mxu.init(jax.random.PRNGKey(4), x)
    y_mxu = mxu.apply(params, x)
    y_i8 = i8.apply(params, x)
    assert y_mxu.shape == (2, 14, 14, 8)
    np.testing.assert_array_equal(np.asarray(y_mxu), np.asarray(y_i8))


def test_conv_transpose_ste_gradient_flows():
    x = jnp.asarray(
        np.random.default_rng(5).normal(size=(1, 5, 5, 4)), jnp.float32
    )
    layer = _binary(
        QuantConvTranspose, features=3, kernel_size=(2, 2), strides=(2, 2),
        binary_compute="int8",
    )
    params = layer.init(jax.random.PRNGKey(5), x)
    g = jax.grad(lambda p: (layer.apply(p, x) ** 2).sum())(params)
    assert float(jnp.abs(g["params"]["kernel"]).sum()) > 0


def test_nd_kernels_match_binary_param_pattern():
    """The latent kernels of the digit-bearing class names (QuantConv1D_0,
    QuantConv3D_0) must be classified binary — Bop/flip-ratio/summary all
    key off this single pattern."""
    import re

    pat = re.compile(BINARY_KERNEL_PATTERN)
    for path in (
        "QuantConv1D_0/kernel",
        "QuantConv3D_2/kernel",
        "QuantConvTranspose_1/kernel",
        "QuantConv_0/kernel",
    ):
        assert pat.search(path), path
    for path in (
        "QuantConv1D_0/kernel_fp",
        "QuantConv1D_0/bias",
        "Dense_0/kernel",
    ):
        assert not pat.search(path), path


def test_nd_rejects_packed_modes_and_bad_ranks():
    x1 = jnp.ones((1, 8, 4))
    with pytest.raises(ValueError, match="2-D"):
        _binary(QuantConv1D, features=2, binary_compute="xnor").init(
            jax.random.PRNGKey(0), x1
        )
    with pytest.raises(ValueError, match="spatial dim"):
        QuantConv1D(features=2, kernel_size=(3, 3)).init(
            jax.random.PRNGKey(0), jnp.ones((1, 8, 8, 4))
        )
    with pytest.raises(ValueError, match="input rank"):
        QuantConv3D(features=2).init(jax.random.PRNGKey(0), x1)
    with pytest.raises(ValueError, match="packed kernels"):
        _binary(
            QuantConvTranspose, features=2, binary_compute="xnor_popcount"
        ).init(jax.random.PRNGKey(0), jnp.ones((1, 4, 4, 4)))


def test_packed_converter_skips_transpose_scopes():
    """pack_quantconv_params must leave QuantConvTranspose kernels alone:
    they are 4-D like QuantConv's but have no packed deployment structure."""
    from zookeeper_tpu.ops import pack_quantconv_params

    params = {
        "QuantConv_0": {"kernel": jnp.ones((3, 3, 32, 8))},
        "QuantConvTranspose_0": {"kernel": jnp.ones((3, 3, 8, 4))},
    }
    out = pack_quantconv_params(params)
    assert "kernel_packed" in out["QuantConv_0"]
    assert "kernel" in out["QuantConvTranspose_0"]
    assert "kernel_packed" not in out["QuantConvTranspose_0"]


def test_separable_conv1d_matches_manual_composition():
    """QuantSeparableConv1D == depthwise (groups=ci) then 1x1 pointwise,
    with the larq data flow (intermediate unquantized by default)."""
    from zookeeper_tpu.ops import QuantConvND, QuantSeparableConv1D

    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.normal(size=(2, 12, 6)), jnp.float32)
    layer = QuantSeparableConv1D(
        features=5, kernel_size=(3,), input_quantizer="ste_sign",
        depthwise_quantizer="ste_sign", pointwise_quantizer="ste_sign",
    )
    params = layer.init(jax.random.PRNGKey(7), x)
    y = layer.apply(params, x)
    assert y.shape == (2, 12, 5)

    dw = QuantConvND(
        features=6, kernel_size=(3,), feature_group_count=6,
        input_quantizer="ste_sign", kernel_quantizer="ste_sign",
    )
    pw = QuantConvND(
        features=5, kernel_size=(1,), kernel_quantizer="ste_sign",
    )
    inner = params["params"]
    mid = dw.apply({"params": inner["QuantConvND_0"]}, x)
    y2 = pw.apply({"params": inner["QuantConvND_1"]}, mid)
    np.testing.assert_array_equal(np.asarray(y), np.asarray(y2))


def test_separable_conv1d_int8_with_intermediate_quantizer():
    """int8 pointwise requires a binarized intermediate; bit-exact vs the
    mxu path under the same params."""
    from zookeeper_tpu.ops import QuantSeparableConv1D

    rng = np.random.default_rng(8)
    x = jnp.asarray(rng.normal(size=(2, 10, 8)), jnp.float32)
    kw = dict(
        features=4, input_quantizer="ste_sign",
        depthwise_quantizer="ste_sign", pointwise_quantizer="ste_sign",
        intermediate_quantizer="ste_sign",
    )
    mxu = QuantSeparableConv1D(**kw)
    i8 = QuantSeparableConv1D(
        depthwise_compute="int8", pointwise_compute="int8", **kw
    )
    params = mxu.init(jax.random.PRNGKey(8), x)
    np.testing.assert_array_equal(
        np.asarray(mxu.apply(params, x)), np.asarray(i8.apply(params, x))
    )


def test_separable_conv1d_rejects_2d_kernel():
    from zookeeper_tpu.ops import QuantSeparableConv1D

    with pytest.raises(ValueError, match="must have 1 spatial dim"):
        QuantSeparableConv1D(features=2, kernel_size=(3, 3)).init(
            jax.random.PRNGKey(0), jnp.ones((1, 8, 8, 4))
        )
