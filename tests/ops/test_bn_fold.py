"""BN-folding at packed conversion (VERDICT r3 next #4, the last
declined LCE-converter parity row): eval-mode BatchNorm after a packed
binary layer is the affine ``a*y + b``, folded at convert time into
``kernel_scale`` and a conv ``bias`` — four fp32 vectors per conv erased
from the deployed tree at zero runtime cost."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from zookeeper_tpu.core import configure
from zookeeper_tpu.models import QuickNet
from zookeeper_tpu.ops.packed import pack_quantconv_params


def _build(model_cls=QuickNet, base_conf=None, **conf):
    model = model_cls()
    configure(
        model,
        {
            **(
                base_conf
                if base_conf is not None
                else {
                    "blocks_per_section": (1, 1),
                    "section_features": (32, 64),
                }
            ),
            "pallas_interpret": True,
            **conf,
        },
        name="model",
    )
    module = model.build((16, 16, 3), num_classes=8)
    return model, module


def _randomize_bns(params, model_state, rng):
    # Single-sourced with verify_onchip's jitter (zookeeper_tpu.testing)
    # so the test and the driver probe cannot drift.
    from zookeeper_tpu.testing import randomize_bn_variables

    return randomize_bn_variables(params, model_state["batch_stats"], rng)


def _trained_like_variables(model_cls=QuickNet, base_conf=None):
    model, module = _build(model_cls, base_conf)
    params, model_state = model.initialize(module, (16, 16, 3))
    return _randomize_bns(params, model_state, np.random.default_rng(0))


def test_fold_bn_matches_unfolded_eval():
    params, stats = _trained_like_variables()
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(2, 16, 16, 3)), jnp.float32)

    _, packed_module = _build(binary_compute="xnor", packed_weights=True)
    packed_params = pack_quantconv_params(params)
    ref = packed_module.apply(
        {"params": packed_params, "batch_stats": stats}, x, training=False
    )

    _, folded_module = _build(
        binary_compute="xnor", packed_weights=True, fold_bn=True
    )
    fparams, fstats = pack_quantconv_params(
        params, fold_bn=True, batch_stats=stats
    )
    out = folded_module.apply(
        {"params": fparams, "batch_stats": fstats}, x, training=False
    )
    # Same affine computed in a different association (a*y + b vs
    # normalize-then-scale): equal to float rounding, not bitwise.
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_fold_bn_erases_binary_conv_bns():
    params, stats = _trained_like_variables()
    packed_params = pack_quantconv_params(params)
    fparams, fstats = pack_quantconv_params(
        params, fold_bn=True, batch_stats=stats
    )
    # QuickNet (1,1): stem BNs 0-1, first binary conv's BN_2, transition
    # BN_3, second binary conv's BN_4.
    for gone in ("BatchNorm_2", "BatchNorm_4"):
        assert gone not in fparams
        assert gone not in fstats
    for kept in ("BatchNorm_0", "BatchNorm_1", "BatchNorm_3"):
        assert kept in fparams
        assert kept in fstats
    for conv in ("QuantConv_0", "QuantConv_1"):
        assert "bias" in fparams[conv]
        assert "kernel_packed" in fparams[conv]

    def nbytes(tree):
        return sum(np.asarray(leaf).nbytes for leaf in jax.tree.leaves(tree))

    unfolded = nbytes(packed_params) + nbytes(stats)
    folded = nbytes(fparams) + nbytes(fstats)
    # Per folded conv: -2 BN params, -2 running stats, +1 conv bias.
    saved = unfolded - folded
    assert saved == 3 * 4 * (32 + 64), (unfolded, folded)


def test_fold_bn_sorted_checkpoint_needs_fold_order():
    """Checkpoint round trips (and pytree round trips) sort params
    alphabetically, destroying the creation-order adjacency the fold
    pairing reads. ``fold_order`` restores it; without it the sorted
    tree fails LOUDLY instead of folding the wrong BN."""
    params, stats = _trained_like_variables()
    sorted_params = {k: params[k] for k in sorted(params)}
    with pytest.raises(ValueError, match="not followed by a BatchNorm"):
        pack_quantconv_params(sorted_params, fold_bn=True, batch_stats=stats)
    fparams, fstats = pack_quantconv_params(
        sorted_params, fold_bn=True, batch_stats=stats, fold_order=params
    )
    ref_p, ref_s = pack_quantconv_params(
        params, fold_bn=True, batch_stats=stats
    )
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)
        ),
        {"p": fparams, "s": fstats},
        {"p": ref_p, "s": ref_s},
    )


@pytest.mark.slow
@pytest.mark.parametrize(
    "model_cls,base_conf,kernel_quantizer",
    [
        (
            "BiRealNet",
            {"blocks_per_section": (1, 1), "section_features": (32, 64)},
            "magnitude_aware_sign",
        ),
        (
            "BinaryResNetE18",
            {"blocks_per_section": (1, 1), "section_features": (32, 64)},
            "ste_sign",
        ),
    ],
)
def test_fold_bn_other_families_match_unfolded_eval(
    model_cls, base_conf, kernel_quantizer
):
    """The fold generalizes to every conv->BN->(+shortcut) family —
    including NESTED block scopes (the fold pass recurses) and
    magnitude_aware_sign kernels (the per-channel MA scale multiplies
    into the fold's `a` exactly). The shortcut BNs (after fp convs)
    must survive unfolded."""
    import zookeeper_tpu.models as zoo

    cls = getattr(zoo, model_cls)
    params, stats = _trained_like_variables(cls, base_conf)
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(2, 16, 16, 3)), jnp.float32)

    _, packed_module = _build(
        cls, base_conf, binary_compute="xnor", packed_weights=True
    )
    packed_params = pack_quantconv_params(
        params, kernel_quantizer=kernel_quantizer
    )
    ref = packed_module.apply(
        {"params": packed_params, "batch_stats": stats}, x, training=False
    )

    _, folded_module = _build(
        cls, base_conf, binary_compute="xnor", packed_weights=True,
        fold_bn=True,
    )
    fparams, fstats = pack_quantconv_params(
        params,
        kernel_quantizer=kernel_quantizer,
        fold_bn=True,
        batch_stats=stats,
    )
    out = folded_module.apply(
        {"params": fparams, "batch_stats": fstats}, x, training=False
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


@pytest.mark.slow
def test_fold_bn_binaryalexnet_dense_stage():
    """BinaryAlexNet folds its DENSE stage only (dense holds ~80% of its
    params): the dense-only packed deployment's BNs fold; the conv BNs
    — two of which sit after a maxpool, where folding is invalid for
    negative BN scales — survive, and conv-packed + fold raises."""
    from zookeeper_tpu.models import BinaryAlexNet

    def build(conf):
        m = BinaryAlexNet()
        configure(m, {"pallas_interpret": True, **conf}, name="m")
        return m, m.build((67, 67, 3), num_classes=5)

    model, float_module = build({})
    rng_np = np.random.default_rng(5)
    x = jnp.asarray(rng_np.normal(size=(1, 67, 67, 3)), jnp.float32)
    variables = float_module.init(jax.random.PRNGKey(3), x, training=False)
    params, stats = _randomize_bns(
        variables["params"], variables, rng_np
    )

    mixed_conf = {"dense_binary_compute": "xnor", "dense_packed_weights": True}
    _, ref_module = build(mixed_conf)
    template = jax.eval_shape(
        lambda: ref_module.init(jax.random.PRNGKey(3), x, training=False)
    )["params"]
    ref = ref_module.apply(
        {"params": pack_quantconv_params(params, template=template),
         "batch_stats": stats},
        x, training=False,
    )

    _, folded_module = build({**mixed_conf, "fold_bn": True})
    ftemplate = jax.eval_shape(
        lambda: folded_module.init(jax.random.PRNGKey(3), x, training=False)
    )["params"]
    fparams, fstats = pack_quantconv_params(
        params, template=ftemplate, fold_bn=True, batch_stats=stats
    )
    # Dense-stage BNs (5, 6) folded away; conv-stage BNs (0-4) survive.
    for gone in ("BatchNorm_5", "BatchNorm_6"):
        assert gone not in fparams and gone not in fstats
    for kept in ("BatchNorm_0", "BatchNorm_4"):
        assert kept in fparams and kept in fstats
    out = folded_module.apply(
        {"params": fparams, "batch_stats": fstats}, x, training=False
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4
    )

    # Conv-packed + fold: loud refusal (maxpool between conv and BN).
    _, bad = build({"packed_weights": True, "binary_compute": "xnor",
                    "fold_bn": True})
    with pytest.raises(ValueError, match="DENSE stage only"):
        bad.init(jax.random.PRNGKey(0), x, training=False)


@pytest.mark.slow
def test_fold_bn_binarynet_dense_stage():
    """BinaryNet mirrors the BinaryAlexNet rule: dense-stage fold only
    (odd convs feed a maxpool before their BN); conv-packed + fold
    raises."""
    from zookeeper_tpu.models import BinaryNet

    def build(conf):
        m = BinaryNet()
        configure(
            m,
            {
                "features": (16, 16),
                "dense_units": (32,),
                "pallas_interpret": True,
                **conf,
            },
            name="m",
        )
        return m, m.build((16, 16, 1), num_classes=5)

    model, float_module = build({})
    rng_np = np.random.default_rng(6)
    x = jnp.asarray(rng_np.normal(size=(2, 16, 16, 1)), jnp.float32)
    variables = float_module.init(jax.random.PRNGKey(1), x, training=False)
    params, stats = _randomize_bns(variables["params"], variables, rng_np)

    mixed_conf = {"dense_binary_compute": "xnor", "dense_packed_weights": True}
    _, ref_module = build(mixed_conf)
    template = jax.eval_shape(
        lambda: ref_module.init(jax.random.PRNGKey(1), x, training=False)
    )["params"]
    ref = ref_module.apply(
        {"params": pack_quantconv_params(params, template=template),
         "batch_stats": stats},
        x, training=False,
    )

    _, folded_module = build({**mixed_conf, "fold_bn": True})
    ftemplate = jax.eval_shape(
        lambda: folded_module.init(jax.random.PRNGKey(1), x, training=False)
    )["params"]
    fparams, fstats = pack_quantconv_params(
        params, template=ftemplate, fold_bn=True, batch_stats=stats
    )
    # Conv-stage BNs (0, 1) survive; the dense BN (2) folds away.
    assert "BatchNorm_0" in fparams and "BatchNorm_1" in fparams
    assert "BatchNorm_2" not in fparams and "BatchNorm_2" not in fstats
    assert "bias" in fparams["QuantDense_0"]
    out = folded_module.apply(
        {"params": fparams, "batch_stats": fstats}, x, training=False
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4
    )

    _, bad = build({"packed_weights": True, "binary_compute": "xnor",
                    "fold_bn": True})
    with pytest.raises(ValueError, match="DENSE stage only"):
        bad.init(jax.random.PRNGKey(0), x, training=False)


@pytest.mark.slow
def test_fold_bn_xnornet_both_stages():
    """XNOR-Net is the one AlexNet-shaped family where BOTH stages fold:
    every binary layer (conv and dense) is directly BN-followed — its
    maxpools come AFTER the BN, so the pool hazard doesn't exist."""
    from zookeeper_tpu.models import XNORNet

    def build(conf):
        m = XNORNet()
        configure(m, {"pallas_interpret": True, **conf}, name="m")
        return m, m.build((67, 67, 1), num_classes=5)

    model, float_module = build({})
    rng_np = np.random.default_rng(8)
    x = jnp.asarray(rng_np.normal(size=(1, 67, 67, 1)), jnp.float32)
    variables = float_module.init(jax.random.PRNGKey(2), x, training=False)
    params, stats = _randomize_bns(variables["params"], variables, rng_np)
    # Sign-mixed BN scales: the conv-fold validity argument hinges on
    # negative scales being safe here (no pool between conv and BN), so
    # the test must actually EXECUTE a negative folded kernel_scale.
    for k in params:
        if k.startswith("BatchNorm"):
            signs = rng_np.choice([-1.0, 1.0], size=np.shape(params[k]["scale"]))
            params[k] = dict(params[k])
            params[k]["scale"] = params[k]["scale"] * jnp.asarray(
                signs, jnp.float32
            )

    packed_conf = {"binary_compute": "xnor", "packed_weights": True}
    _, ref_module = build(packed_conf)
    packed_params = pack_quantconv_params(
        params, kernel_quantizer="magnitude_aware_sign"
    )
    ref = ref_module.apply(
        {"params": packed_params, "batch_stats": stats}, x, training=False
    )

    _, folded_module = build({**packed_conf, "fold_bn": True})
    fparams, fstats = pack_quantconv_params(
        params,
        kernel_quantizer="magnitude_aware_sign",
        fold_bn=True,
        batch_stats=stats,
    )
    # Only the fp stem's BN survives; every binary layer's BN folds.
    assert "BatchNorm_0" in fparams
    assert all(
        not k.startswith("BatchNorm") or k == "BatchNorm_0"
        for k in fparams
    ), sorted(k for k in fparams if k.startswith("BatchNorm"))
    for scope in ("QuantConv_0", "QuantDense_0", "QuantDense_1"):
        assert "bias" in fparams[scope]
    out = folded_module.apply(
        {"params": fparams, "batch_stats": fstats}, x, training=False
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4
    )

    # Training apply of a folding build must raise (either stage packed).
    with pytest.raises(ValueError, match="DEPLOYMENT mode"):
        folded_module.init(jax.random.PRNGKey(0), x, training=True)
    _, dense_only_fold = build(
        {"packed_weights": False, "dense_packed_weights": True,
         "dense_binary_compute": "xnor", "fold_bn": True}
    )
    with pytest.raises(ValueError, match="DEPLOYMENT mode"):
        dense_only_fold.init(jax.random.PRNGKey(0), x, training=True)
    # Mixed config (dense-only packed + fold): conv BNs survive, dense
    # BNs fold — eval init builds the expected structure.
    v = dense_only_fold.init(jax.random.PRNGKey(2), x, training=False)
    assert "BatchNorm_1" in v["params"]  # conv-stage BN still applied
    assert "bias" in v["params"]["QuantDense_0"]
    n_bns = sum(1 for k in v["params"] if k.startswith("BatchNorm"))
    assert n_bns == 5  # stem + 4 conv BNs; the 2 dense BNs are skipped


def test_fold_bn_pre_activation_family_raises():
    """BinaryDenseNet is pre-activation (BN BEFORE the conv; outputs
    concatenate with no following BN) — folding is structurally
    impossible there and must fail loudly, not fold the wrong BN."""
    from zookeeper_tpu.models import BinaryDenseNet28

    model, module = _build(
        BinaryDenseNet28,
        {"layers_per_block": (2, 2), "reduction": (2.0,),
         "dilation": (1, 1), "growth_rate": 32, "initial_features": 32},
    )
    params, model_state = model.initialize(module, (16, 16, 3))
    params, stats = _randomize_bns(
        params, model_state, np.random.default_rng(4)
    )
    with pytest.raises(
        ValueError, match="does not normalize this conv's output"
    ):
        pack_quantconv_params(params, fold_bn=True, batch_stats=stats)


def test_fold_bn_rejects_training_apply():
    """fold_bn is deployment-only: a training=True apply would silently
    skip the binary-conv BNs — it must raise at the module instead."""
    import jax.numpy as jnp

    _, module = _build(
        binary_compute="xnor", packed_weights=True, fold_bn=True
    )
    with pytest.raises(ValueError, match="DEPLOYMENT mode"):
        module.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 16, 16, 3)), training=True
        )


def test_fold_bn_requires_batch_stats():
    params, _ = _trained_like_variables()
    with pytest.raises(ValueError, match="batch_stats"):
        pack_quantconv_params(params, fold_bn=True)


def test_fold_bn_mixed_sections_with_template():
    """Per-section mixed deployment: only the packed section folds; the
    unpacked section keeps its BN. Template-driven conversion."""
    params, stats = _trained_like_variables()
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(2, 16, 16, 3)), jnp.float32)

    _, ref_module = _build(
        binary_compute=("xnor", "xnor"), packed_weights=(False, True)
    )
    template = jax.eval_shape(
        lambda: ref_module.init(jax.random.PRNGKey(0), jnp.zeros((1, 16, 16, 3)))
    )["params"]
    mixed_params = pack_quantconv_params(params, template=template)
    ref = ref_module.apply(
        {"params": mixed_params, "batch_stats": stats}, x, training=False
    )

    _, folded_module = _build(
        binary_compute=("xnor", "xnor"),
        packed_weights=(False, True),
        fold_bn=True,
    )
    fparams, fstats = pack_quantconv_params(
        params, template=template, fold_bn=True, batch_stats=stats
    )
    # The unpacked section's conv + BN survive; the packed one folds.
    assert "BatchNorm_2" in fparams and "kernel" in fparams["QuantConv_0"]
    assert "BatchNorm_4" not in fparams and "bias" in fparams["QuantConv_1"]
    out = folded_module.apply(
        {"params": fparams, "batch_stats": fstats}, x, training=False
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )
