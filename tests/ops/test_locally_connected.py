"""QuantLocallyConnected1D/2D: unshared-weight convs (larq surface
parity, VERDICT round-2 missing #4). Oracle: per-position patch-matmul —
``conv_general_dilated_patches`` + einsum is an independent compute path
from ``conv_general_dilated_local``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from zookeeper_tpu.ops import (
    QuantLocallyConnected1D,
    QuantLocallyConnected2D,
)


def _patch_oracle(x, kernel, bias, kernel_size, strides, padding):
    """Reference: extract patches, per-position matmul, add bias."""
    rank = len(kernel_size)
    dims = ("NHWC", "HWIO", "NHWC") if rank == 2 else ("NWC", "WIO", "NWC")
    patches = jax.lax.conv_general_dilated_patches(
        x, kernel_size, strides, padding, dimension_numbers=dims
    )
    eq = "nhwk,hwko->nhwo" if rank == 2 else "nwk,wko->nwo"
    out = jnp.einsum(eq, patches, kernel)
    return out + bias if bias is not None else out


@pytest.mark.parametrize("padding,strides", [
    ("VALID", (1, 1)),
    ("SAME", (2, 2)),
    (((1, 0), (0, 2)), (1, 2)),
])
@pytest.mark.slow
def test_local2d_matches_patch_oracle(padding, strides):
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(2, 7, 6, 3)), jnp.float32)
    layer = QuantLocallyConnected2D(
        features=5, kernel_size=(3, 3), strides=strides, padding=padding
    )
    variables = layer.init(jax.random.PRNGKey(1), x)
    y = layer.apply(variables, x)
    params = variables["params"]
    ref = _patch_oracle(
        x, params["kernel_fp"], params["bias"], (3, 3), strides, padding
    )
    assert y.shape == ref.shape
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), atol=1e-5)
    # The kernel is genuinely per-position: (out_h, out_w, kh*kw*ci, co).
    assert params["kernel_fp"].shape[:2] == y.shape[1:3]
    assert params["bias"].shape == y.shape[1:]


def test_local1d_matches_patch_oracle():
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(3, 9, 4)), jnp.float32)
    layer = QuantLocallyConnected1D(
        features=6, kernel_size=(3,), strides=(2,), padding="SAME"
    )
    variables = layer.init(jax.random.PRNGKey(3), x)
    y = layer.apply(variables, x)
    params = variables["params"]
    ref = _patch_oracle(
        x, params["kernel_fp"], params["bias"], (3,), (2,), "SAME"
    )
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), atol=1e-5)


def test_local2d_quantized_forward_and_grad():
    """ste_sign input+kernel: forward equals the oracle on binarized
    operands; gradients flow to the latent kernel (STE), i.e. the layer
    trains like the other Quant* layers."""
    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.normal(size=(2, 5, 5, 2)), jnp.float32)
    layer = QuantLocallyConnected2D(
        features=3, kernel_size=(3, 3), padding="VALID",
        input_quantizer="ste_sign", kernel_quantizer="ste_sign",
        use_bias=False,
    )
    variables = layer.init(jax.random.PRNGKey(5), x)
    y = layer.apply(variables, x)
    k = variables["params"]["kernel"]
    ref = _patch_oracle(
        jnp.sign(x), jnp.sign(k), None, (3, 3), (1, 1), "VALID"
    )
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), atol=1e-5)

    def loss(params):
        return (layer.apply({"params": params}, x) ** 2).sum()

    grads = jax.grad(loss)(variables["params"])
    assert float(jnp.abs(grads["kernel"]).sum()) > 0.0


def test_local_rejects_binary_compute_modes():
    x = jnp.ones((1, 5, 5, 2))
    for mode in ("int8", "xnor", "xnor_popcount"):
        layer = QuantLocallyConnected2D(
            features=3, input_quantizer="ste_sign",
            kernel_quantizer="ste_sign", binary_compute=mode,
        )
        with pytest.raises(ValueError, match="only 'mxu'"):
            layer.init(jax.random.PRNGKey(0), x)


def test_local_rank_mismatch_is_loud():
    layer = QuantLocallyConnected1D(features=2)
    with pytest.raises(ValueError, match="rank-3"):
        layer.init(jax.random.PRNGKey(0), jnp.ones((1, 5, 5, 2)))
