"""Ring attention vs full attention on the virtual CPU mesh: exact
sequence-parallel attention (values AND gradients) — the working proof
that the mesh API's "SP could be added without redesign" claim holds.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from zookeeper_tpu.ops import (
    all_to_all_attention,
    attention_reference,
    flash_attention,
    ring_attention,
    ring_flash_attention,
)


def _mesh(n):
    if jax.device_count() < n:
        pytest.skip(f"needs {n} devices")
    return Mesh(np.array(jax.devices()[:n]), ("sp",))


def _qkv(seed, b=2, s=32, h=2, d=8, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(
        rng.normal(size=(b, s, h, d)).astype(np.float32), dtype
    )
    return mk(), mk(), mk()


@pytest.mark.parametrize("n", [1, 2, 8])
@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_full_attention(n, causal):
    mesh = _mesh(n)
    q, k, v = _qkv(seed=n * 10 + causal)
    ref = attention_reference(q, k, v, causal=causal)
    out = ring_attention(q, k, v, mesh=mesh, seq_axis="sp", causal=causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )


@pytest.mark.slow
@pytest.mark.parametrize("causal", [False, True])
def test_ring_gradients_match_full_attention(causal):
    mesh = _mesh(8)
    q, k, v = _qkv(seed=42 + causal)
    w = jnp.asarray(
        np.random.default_rng(3).normal(size=q.shape).astype(np.float32)
    )

    def loss_ref(q, k, v):
        return (attention_reference(q, k, v, causal=causal) * w).sum()

    def loss_ring(q, k, v):
        return (
            ring_attention(
                q, k, v, mesh=mesh, seq_axis="sp", causal=causal
            )
            * w
        ).sum()

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_ring, g_ref):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b_), atol=5e-5, rtol=5e-5
        )


def test_ring_bf16_close_to_fp32_reference():
    mesh = _mesh(8)
    q, k, v = _qkv(seed=7, dtype=jnp.bfloat16)
    ref = attention_reference(
        q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32)
    )
    out = ring_attention(q, k, v, mesh=mesh, seq_axis="sp")
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref), atol=3e-2
    )


def _overlap_vs_sequential(fn, kw, mesh, *, grads):
    """Run ``fn`` under both ring schedules, causal (the hard case:
    masking bookkeeping + the ring_flash lax.switch branches), and pin
    outputs (and gradients when ``grads``) to <= 5e-7 — the documented
    schedule-parity contract (identical dataflow; measured bit-exact
    on this backend)."""
    q, k, v = _qkv(seed=32)
    w = jnp.asarray(
        np.random.default_rng(17).normal(size=q.shape).astype(np.float32)
    )
    runs = {
        ov: fn(
            q, k, v, mesh=mesh, seq_axis="sp", causal=True,
            overlap=ov, **kw,
        )
        for ov in (True, False)
    }
    np.testing.assert_allclose(
        np.asarray(runs[True]), np.asarray(runs[False]), atol=5e-7,
        err_msg=f"{fn.__name__} fwd",
    )
    if not grads:
        return
    gs = {
        ov: jax.grad(
            lambda q, k, v, _ov=ov: (
                fn(
                    q, k, v, mesh=mesh, seq_axis="sp",
                    causal=True, overlap=_ov, **kw,
                )
                * w
            ).sum(),
            argnums=(0, 1, 2),
        )(q, k, v)
        for ov in (True, False)
    }
    for a, b_ in zip(gs[True], gs[False]):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b_), atol=5e-7,
            err_msg=f"{fn.__name__} bwd",
        )


def test_ring_overlap_schedule_matches_sequential():
    """The double-buffered (comm-overlapped) schedule vs the sequential
    one on a 4-device ring: dense-ring forward AND backward, ring_flash
    forward (its backward — four more flash custom_vjp traces — is the
    slow-tier sibling below; the schedules differ only inside the scan
    body, so mesh width adds compile time, not coverage)."""
    mesh = _mesh(4)
    _overlap_vs_sequential(ring_attention, {}, mesh, grads=True)
    _overlap_vs_sequential(
        ring_flash_attention, dict(block_q=8, block_k=8), mesh,
        grads=False,
    )


@pytest.mark.slow
def test_ring_flash_overlap_schedule_bwd_matches_sequential():
    """Certification tail of the schedule contract: ring_flash
    GRADIENTS under both schedules (the composed tier's custom_vjp +
    inverse-rotation backward), on the full 8-device ring."""
    mesh = _mesh(8)
    _overlap_vs_sequential(
        ring_flash_attention, dict(block_q=8, block_k=8), mesh,
        grads=True,
    )


def test_ring_rejects_indivisible_sequence():
    mesh = _mesh(8)
    q, k, v = _qkv(seed=0, s=30)
    with pytest.raises(ValueError, match="does not divide"):
        ring_attention(q, k, v, mesh=mesh, seq_axis="sp")


def test_ring_composes_under_jit():
    mesh = _mesh(8)
    q, k, v = _qkv(seed=11)
    f = jax.jit(
        lambda q, k, v: ring_attention(
            q, k, v, mesh=mesh, seq_axis="sp", causal=True
        )
    )
    np.testing.assert_allclose(
        np.asarray(f(q, k, v)),
        np.asarray(attention_reference(q, k, v, causal=True)),
        atol=2e-5,
        rtol=2e-5,
    )


def test_ring_composes_with_data_parallel_mesh():
    """The realistic pod layout: batch over 'data' x sequence over 'sp'
    on a (2, 4) mesh — each data-shard runs an independent ring."""
    if jax.device_count() < 8:
        pytest.skip("needs 8 devices")
    mesh = Mesh(
        np.array(jax.devices()[:8]).reshape(2, 4), ("data", "sp")
    )
    q, k, v = _qkv(seed=5, b=4, s=16)
    ref = attention_reference(q, k, v, causal=True)
    out = ring_attention(
        q, k, v, mesh=mesh, seq_axis="sp", batch_axis="data", causal=True
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )

    with pytest.raises(ValueError, match="Batch"):
        ring_attention(
            _qkv(seed=5, b=3, s=16)[0], k[:3], v[:3],
            mesh=mesh, seq_axis="sp", batch_axis="data",
        )



@pytest.mark.parametrize("n", [1, 2, 8])
@pytest.mark.parametrize("causal", [False, True])
def test_all_to_all_matches_full_attention(n, causal):
    """The Ulysses SP flavor: heads re-sharded via all_to_all, dense
    attention local, re-sharded back — exact vs the dense oracle."""
    mesh = _mesh(n)
    q, k, v = _qkv(seed=n * 100 + causal, h=8)  # h divisible by any n
    ref = attention_reference(q, k, v, causal=causal)
    out = all_to_all_attention(
        q, k, v, mesh=mesh, seq_axis="sp", causal=causal
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )


@pytest.mark.slow
def test_all_to_all_gradients_match_full_attention():
    mesh = _mesh(8)
    q, k, v = _qkv(seed=9, h=8)
    w = jnp.asarray(
        np.random.default_rng(4).normal(size=q.shape).astype(np.float32)
    )
    g_ref = jax.grad(
        lambda q, k, v: (attention_reference(q, k, v, causal=True) * w).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    g_u = jax.grad(
        lambda q, k, v: (
            all_to_all_attention(
                q, k, v, mesh=mesh, seq_axis="sp", causal=True
            )
            * w
        ).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b_ in zip(g_u, g_ref):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b_), atol=5e-5, rtol=5e-5
        )


def test_all_to_all_rejects_indivisible_heads():
    mesh = _mesh(8)
    q, k, v = _qkv(seed=0, h=2)  # 2 heads on an 8-way axis
    with pytest.raises(Exception, match="heads"):
        all_to_all_attention(q, k, v, mesh=mesh, seq_axis="sp")


def test_all_to_all_composes_with_data_parallel_mesh():
    """Ulysses under the dp x sp layout too (the PARITY claim for BOTH
    flavors): batch over 'data', sequence ring axis over 'sp'."""
    if jax.device_count() < 8:
        pytest.skip("needs 8 devices")
    mesh = Mesh(
        np.array(jax.devices()[:8]).reshape(2, 4), ("data", "sp")
    )
    q, k, v = _qkv(seed=6, b=4, s=16, h=8)
    ref = attention_reference(q, k, v, causal=True)
    out = all_to_all_attention(
        q, k, v, mesh=mesh, seq_axis="sp", batch_axis="data", causal=True
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize(
    "shape", [(2, 32, 2, 8), (1, 40, 1, 16), (2, 128, 2, 8)]
)
def test_flash_attention_matches_dense(shape, causal):
    """The Pallas flash forward (interpret mode on CPU) vs the dense
    oracle — including a sequence length (40) that exercises the
    internal padding/masking path."""
    b, s, h, d = shape
    rng = np.random.default_rng(s + causal)
    mk = lambda: jnp.asarray(
        rng.normal(size=(b, s, h, d)).astype(np.float32)
    )
    q, k, v = mk(), mk(), mk()
    out = flash_attention(
        q, k, v, causal=causal, block_q=16, block_k=16, interpret=True
    )
    ref = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )


def test_flash_attention_bf16():
    rng = np.random.default_rng(3)
    mk = lambda: jnp.asarray(
        rng.normal(size=(1, 32, 2, 8)).astype(np.float32), jnp.bfloat16
    )
    q, k, v = mk(), mk(), mk()
    out = flash_attention(q, k, v, block_q=16, block_k=16, interpret=True)
    assert out.dtype == jnp.bfloat16
    ref = attention_reference(
        q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32)
    )
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref), atol=3e-2
    )


def test_flash_attention_unequal_blocks_and_awkward_seq():
    """Unequal block_q/block_k with a sequence dividing neither: the
    lcm padding must keep every query row written and every key
    attended (regression: max-based padding dropped rows/keys)."""
    rng = np.random.default_rng(13)
    b, s, h, d = 1, 20, 1, 8
    mk = lambda: jnp.asarray(
        rng.normal(size=(b, s, h, d)).astype(np.float32)
    )
    q, k, v = mk(), mk(), mk()
    for bq, bk in ((16, 8), (8, 16), (16, 12)):
        for causal in (False, True):
            out = flash_attention(
                q, k, v, causal=causal, block_q=bq, block_k=bk,
                interpret=True,
            )
            ref = attention_reference(q, k, v, causal=causal)
            np.testing.assert_allclose(
                np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5,
                err_msg=f"bq={bq} bk={bk} causal={causal}",
            )


def test_sharded_attention_rejects_mismatched_qkv_shapes():
    """Cross-attention shapes must fail loudly at the boundary: with
    causal=True and per-shard sk > sq a non-first ring block can be
    fully masked while the running max still sits at the mask value,
    making p = exp(0) = 1 for masked entries — silently corrupt l/acc,
    wrong output, no error. Self-attention is the supported contract."""
    mesh = _mesh(8)
    q, k, v = _qkv(seed=3, s=16)
    q_short = q[:, :8]
    for fn in (ring_attention, all_to_all_attention):
        with pytest.raises(ValueError, match="identical shape"):
            fn(q_short, k, v, mesh=mesh, seq_axis="sp", causal=True)
        # Head/dim mismatches are the same class of boundary error.
        with pytest.raises(ValueError, match="identical shape"):
            fn(q[..., : q.shape[-1] // 2], k, v, mesh=mesh, seq_axis="sp")


def test_local_kernels_reject_mismatched_qkv_shapes():
    """The guard lives INSIDE the local programs too — they are public
    API for users' own shard_maps, and the corruption is in the local
    online-softmax math."""
    from zookeeper_tpu.ops import (
        all_to_all_attention_local,
        ring_attention_local,
    )

    mesh = _mesh(8)
    q, k, v = _qkv(seed=3, s=16)

    def call(fn):
        from functools import partial as _p

        from jax.sharding import PartitionSpec as P

        from jax import shard_map

        sm = shard_map(
            _p(fn, axis_name="sp", causal=True),
            mesh=mesh,
            in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
            out_specs=P(None, "sp"),
        )
        return sm(q[:, :8], k, v)

    for fn in (ring_attention_local, all_to_all_attention_local):
        with pytest.raises(ValueError, match="identical"):
            call(fn)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("shape", [(2, 32, 2, 8), (1, 40, 1, 16)])
def test_flash_attention_gradients_match_dense(shape, causal):
    """The recompute-based flash backward (custom_vjp, two Pallas
    kernels) vs the dense oracle's gradients — including a sequence
    length (40) that exercises the padding path, where padded q rows
    must contribute nothing and padded keys must receive no gradient."""
    b, s, h, d = shape
    rng = np.random.default_rng(s * 2 + causal)
    mk = lambda: jnp.asarray(rng.normal(size=(b, s, h, d)).astype(np.float32))
    q, k, v = mk(), mk(), mk()
    w = jnp.asarray(rng.normal(size=(b, s, h, d)).astype(np.float32))

    def loss_flash(q, k, v):
        return (
            flash_attention(
                q, k, v, causal=causal, block_q=16, block_k=16,
                interpret=True,
            )
            * w
        ).sum()

    def loss_ref(q, k, v):
        return (attention_reference(q, k, v, causal=causal) * w).sum()

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_flash, g_ref):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b_), atol=5e-5, rtol=5e-5
        )


def test_flash_attention_gradients_bf16():
    """bf16 operands keep the native MXU path in the backward too; the
    gradients stay within the bf16 tolerance class of the fp32 oracle."""
    rng = np.random.default_rng(11)
    mk = lambda: jnp.asarray(
        rng.normal(size=(1, 32, 2, 8)).astype(np.float32), jnp.bfloat16
    )
    q, k, v = mk(), mk(), mk()
    w = jnp.asarray(rng.normal(size=(1, 32, 2, 8)).astype(np.float32))

    g_flash = jax.grad(
        lambda q, k, v: (
            flash_attention(
                q, k, v, causal=True, block_q=16, block_k=16,
                interpret=True,
            ).astype(jnp.float32)
            * w
        ).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    g_ref = jax.grad(
        lambda q, k, v: (
            attention_reference(
                q.astype(jnp.float32),
                k.astype(jnp.float32),
                v.astype(jnp.float32),
                causal=True,
            )
            * w
        ).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b_ in zip(g_flash, g_ref):
        assert a.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b_), atol=5e-2, rtol=5e-2
        )


def test_flash_attention_grad_composes_under_jit_and_value():
    """custom_vjp composes with jit and value_and_grad (the training
    path shape)."""
    rng = np.random.default_rng(5)
    mk = lambda: jnp.asarray(rng.normal(size=(2, 32, 2, 8)).astype(np.float32))
    q, k, v = mk(), mk(), mk()

    @jax.jit
    def step(q, k, v):
        return jax.value_and_grad(
            lambda q: flash_attention(
                q, k, v, causal=True, block_q=16, block_k=16,
                interpret=True,
            ).sum()
        )(q)

    val, g = step(q, k, v)
    ref_val = attention_reference(q, k, v, causal=True).sum()
    np.testing.assert_allclose(float(val), float(ref_val), rtol=1e-5)
    ref_g = jax.grad(
        lambda q: attention_reference(q, k, v, causal=True).sum()
    )(q)
    np.testing.assert_allclose(
        np.asarray(g), np.asarray(ref_g), atol=5e-5, rtol=5e-5
    )


@pytest.mark.parametrize("n", [1, 2, 8])
@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_matches_full_attention(n, causal):
    """The COMPOSED tier — flash kernels as each device's block compute
    inside the ring (log-sum-exp block merge) — is exact vs the dense
    oracle on every mesh size, causal and not."""
    mesh = _mesh(n)
    q, k, v = _qkv(seed=n * 7 + causal, s=32)
    ref = attention_reference(q, k, v, causal=causal)
    out = ring_flash_attention(
        q, k, v, mesh=mesh, seq_axis="sp", causal=causal,
        block_q=8, block_k=8,
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )


@pytest.mark.slow
@pytest.mark.parametrize("n", [1, 2, 8])
@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_gradients_match_full_attention(n, causal):
    """End-to-end differentiability of the composition: the flash
    custom_vjp (including the lse cotangent the merge consumes), the
    jnp merge, and ppermute's inverse-rotation backward together
    reproduce the dense oracle's gradients — on every mesh size."""
    mesh = _mesh(n)
    q, k, v = _qkv(seed=13 + causal, s=32)
    w = jnp.asarray(
        np.random.default_rng(6).normal(size=q.shape).astype(np.float32)
    )

    def loss_ref(q, k, v):
        return (attention_reference(q, k, v, causal=causal) * w).sum()

    def loss_rf(q, k, v):
        return (
            ring_flash_attention(
                q, k, v, mesh=mesh, seq_axis="sp", causal=causal,
                block_q=8, block_k=8,
            )
            * w
        ).sum()

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_rf = jax.grad(loss_rf, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_rf, g_ref):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b_), atol=5e-5, rtol=5e-5
        )


@pytest.mark.slow
def test_ring_flash_composes_with_data_parallel_mesh():
    """dp x sp for the composed tier too — values AND gradients."""
    if jax.device_count() < 8:
        pytest.skip("needs 8 devices")
    mesh = Mesh(
        np.array(jax.devices()[:8]).reshape(2, 4), ("data", "sp")
    )
    q, k, v = _qkv(seed=8, b=4, s=16)
    ref = attention_reference(q, k, v, causal=True)
    out = ring_flash_attention(
        q, k, v, mesh=mesh, seq_axis="sp", batch_axis="data",
        causal=True, block_q=8, block_k=8,
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )

    w = jnp.asarray(
        np.random.default_rng(2).normal(size=q.shape).astype(np.float32)
    )
    g_ref = jax.grad(
        lambda q, k, v: (attention_reference(q, k, v, causal=True) * w).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    g_rf = jax.grad(
        lambda q, k, v: (
            ring_flash_attention(
                q, k, v, mesh=mesh, seq_axis="sp", batch_axis="data",
                causal=True, block_q=8, block_k=8,
            )
            * w
        ).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b_ in zip(g_rf, g_ref):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b_), atol=5e-5, rtol=5e-5
        )


def test_ring_flash_bf16():
    mesh = _mesh(8)
    q, k, v = _qkv(seed=9, s=32, dtype=jnp.bfloat16)
    ref = attention_reference(
        q.astype(jnp.float32), k.astype(jnp.float32),
        v.astype(jnp.float32), causal=True,
    )
    out = ring_flash_attention(
        q, k, v, mesh=mesh, seq_axis="sp", causal=True,
        block_q=8, block_k=8,
    )
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref), atol=3e-2
    )


def test_flash_auto_block_policy_aligned_and_bounded_waste():
    """The auto block defaults: largest aligned candidate with padding
    waste under 1/8 of the sequence; the short-sequence clamp stays
    16-aligned (a raw-s clamp would hand Mosaic a tile-unaligned block
    for awkward lengths like 999)."""
    from zookeeper_tpu.ops.attention import (
        _default_flash_blocks,
        _flash_dims,
    )

    for s, want_auto in [
        (2048, 1024), (4096, 1024), (8192, 1024),  # powers of two: max
        (999, 1024),   # single padded tile (clamped to 1008 below)
        (1100, 128),   # big blocks would pad to 2048 (+86%): fall back
        (1280, 256),   # exact multiple of 256, not of 512/1024
        (100, 128),
    ]:
        bq, bk = _default_flash_blocks(s, None, None)
        assert (bq, bk) == (want_auto, want_auto), s
        cq, ck, s_pad = _flash_dims(s, bq, bk)
        assert cq % 8 == 0 and ck % 8 == 0, s
        assert s_pad >= s and (s_pad - s) <= max(s // 8, 16), s
    # Explicit sizes pass through untouched (modulo the short-seq clamp).
    assert _default_flash_blocks(4096, 256, 512) == (256, 512)


def test_flash_auto_block_policy_vmem_head_dim_aware():
    """The auto policy folds head_dim + a VMEM budget into candidate
    filtering (ADVICE round-5): the backward holds three (bq, bk) fp32
    intermediates plus (block, d) tiles, so at head dims well above 64
    a 1024 block exceeds VMEM and must demote to the largest block
    that fits — never selecting an uncompilable default."""
    from zookeeper_tpu.ops.attention import (
        _FLASH_VMEM_BUDGET,
        _default_flash_blocks,
        _flash_bwd_vmem_estimate,
    )

    # The measured sweep winner (block 1024 at d=64 bf16) stays in.
    assert _default_flash_blocks(
        8192, None, None, head_dim=64, itemsize=2
    ) == (1024, 1024)
    # Blocks shrink monotonically with head_dim and every non-floor
    # choice fits the budget.
    prev = 2048
    for d in (64, 256, 1024, 4096):
        bq, bk = _default_flash_blocks(
            8192, None, None, head_dim=d, itemsize=4
        )
        assert bq == bk and bq <= prev, d
        prev = bq
        assert (
            bq == 128
            or _flash_bwd_vmem_estimate(bq, bk, d, 4) <= _FLASH_VMEM_BUDGET
        ), d
    # A giant head dim actually demotes below 1024...
    assert _default_flash_blocks(8192, None, None, head_dim=4096)[0] < 1024
    # ...but explicit sizes always bypass both filters.
    assert _default_flash_blocks(8192, 1024, 1024, head_dim=4096) == (
        1024,
        1024,
    )
    # head_dim=None keeps the padding-only policy (pinned above).
    assert _default_flash_blocks(8192, None, None) == (1024, 1024)


@pytest.mark.parametrize("s", [999, 1100])
def test_flash_attention_awkward_lengths_exact(s):
    """Values and gradients stay exact at tile-awkward sequence lengths
    under the auto block policy (padding + masking path)."""
    rng = np.random.default_rng(s)
    mk = lambda: jnp.asarray(rng.normal(size=(1, s, 2, 8)).astype(np.float32))
    q, k, v = mk(), mk(), mk()
    out = flash_attention(q, k, v, causal=True, interpret=True)
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )
    g = jax.grad(
        lambda q: flash_attention(q, k, v, causal=True, interpret=True).sum()
    )(q)
    gr = jax.grad(
        lambda q: attention_reference(q, k, v, causal=True).sum()
    )(q)
    np.testing.assert_allclose(
        np.asarray(g), np.asarray(gr), atol=5e-5, rtol=5e-5
    )


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.slow
def test_all_to_all_flash_local_matches_dense(causal):
    """Ulysses with the flash kernel as its local compute (the
    long-context variant): exact values AND gradients vs the dense
    oracle."""
    mesh = _mesh(8)
    q, k, v = _qkv(seed=21 + causal, s=32, h=8)
    ref = attention_reference(q, k, v, causal=causal)
    out = all_to_all_attention(
        q, k, v, mesh=mesh, seq_axis="sp", causal=causal,
        local_attention="flash",
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )

    w = jnp.asarray(
        np.random.default_rng(7).normal(size=q.shape).astype(np.float32)
    )
    g_ref = jax.grad(
        lambda q, k, v: (attention_reference(q, k, v, causal=causal) * w).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    g_f = jax.grad(
        lambda q, k, v: (
            all_to_all_attention(
                q, k, v, mesh=mesh, seq_axis="sp", causal=causal,
                local_attention="flash",
            )
            * w
        ).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b_ in zip(g_f, g_ref):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b_), atol=5e-5, rtol=5e-5
        )


def test_all_to_all_rejects_unknown_local_attention():
    mesh = _mesh(1)
    q, k, v = _qkv(seed=0, h=8)
    with pytest.raises(ValueError, match="local_attention"):
        all_to_all_attention(
            q, k, v, mesh=mesh, seq_axis="sp", local_attention="sparse"
        )
