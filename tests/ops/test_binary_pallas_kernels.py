"""§21 binary-kernel certification: the fused Pallas xnor-popcount
kernels (sign+pack producer, scaled GEMM, conv-as-gemm) are
BIT-IDENTICAL to the reference popcount composition — exact integers
plus one fp32 multiply, no ULP budget (docs/DESIGN.md §21).

Interpret mode is the numerics vehicle here (CPU tier-1): it executes
the same kernel program, so a bitwise mismatch in interpret mode is a
kernel bug, not a platform artifact. The sweep is adversarial on
purpose: ragged K via ``k_true``, block-edge shapes (axis == 1, just
past a block, non-multiples of every alignment), strides/padding grid,
poisoned unread input, extreme scales, bf16 inputs, ±0.0 and NaN sign
semantics.
"""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from zookeeper_tpu.ops.binary_compute import (
    _packed_conv_forward,
    pack_bits,
    pack_conv_kernel,
    pack_dense_kernel,
    pack_rows_packed,
    packed_dense_infer,
    resolve_binary_flavor,
    xnor_matmul_packed,
    xnor_matmul_packed_scaled,
)


# -- flavor seam -------------------------------------------------------------


def test_resolve_binary_flavor_seam():
    assert resolve_binary_flavor("reference") == "reference"
    assert resolve_binary_flavor("pallas") == "pallas"
    expected = "pallas" if jax.default_backend() == "tpu" else "reference"
    assert resolve_binary_flavor("auto") == expected
    with pytest.raises(ValueError, match="flavor"):
        resolve_binary_flavor("palas")  # typo must be loud, not silent


def test_explicit_pallas_on_mxu_path_warns_and_degrades():
    """The MXU (use_popcount=False) paths have no fused flavor: an
    explicit "pallas" warns (the caller named a flavor it cannot get)
    and degrades to the reference composition; "auto" stays silent."""
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(3, 64)), jnp.float32)
    kern = jnp.asarray(
        np.sign(rng.normal(size=(64, 8))).astype(np.float32)
    )
    packed, scale = pack_dense_kernel(kern)
    with pytest.warns(UserWarning, match="no fused"):
        y_warn = packed_dense_infer(
            x, packed, scale, 64, use_popcount=False, interpret=True,
            flavor="pallas",
        )
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        y_auto = packed_dense_infer(
            x, packed, scale, 64, use_popcount=False, interpret=True,
            flavor="auto",
        )
    np.testing.assert_array_equal(np.asarray(y_warn), np.asarray(y_auto))


# -- fused sign+pack producer ------------------------------------------------


@pytest.mark.parametrize("m", [1, 3, 37, 96])
@pytest.mark.parametrize("k", [32, 96, 416])
def test_pack_rows_matches_pack_bits(m, k):
    rng = np.random.default_rng(m * 1000 + k)
    x = jnp.asarray(rng.normal(size=(m, k)), jnp.float32)
    got = pack_rows_packed(x, interpret=True)
    want = pack_bits(x, axis=-1)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_pack_rows_sign_edge_semantics():
    """±0.0 and NaN must take the SAME bit as pack_bits (both lower to
    the identical ``>= 0`` compare): +0.0 and -0.0 -> bit 1, NaN -> 0."""
    x = jnp.asarray(
        [[0.0, -0.0, np.nan, -np.nan] * 8, [1.0, -1.0, np.inf, -np.inf] * 8],
        jnp.float32,
    )
    got = np.asarray(pack_rows_packed(x, interpret=True))
    want = np.asarray(pack_bits(x, axis=-1))
    np.testing.assert_array_equal(got, want)
    # Pin the absolute semantics too, not just agreement: row 0 packs
    # bits 1,1,0,0 repeating -> 0b...0011 pattern.
    assert got[0, 0] & 0xF == 0b0011


def test_pack_rows_bf16_and_ragged_rows():
    rng = np.random.default_rng(7)
    # 41 rows: not a multiple of any block; bf16: sublane tile 16 | 32.
    x = jnp.asarray(rng.normal(size=(41, 64)), jnp.bfloat16)
    got = pack_rows_packed(x, interpret=True)
    want = pack_bits(x, axis=-1)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_pack_rows_rejects_unaligned_k():
    with pytest.raises(ValueError, match="32"):
        pack_rows_packed(jnp.ones((4, 33), jnp.float32), interpret=True)


# -- fused-epilogue GEMM -----------------------------------------------------


def _signs(rng, shape):
    return np.where(rng.random(shape) < 0.5, -1.0, 1.0).astype(np.float32)


def _adversarial_scale(rng, n):
    # Spans 16 decades: any epilogue reassociation or double-rounding
    # difference from the reference one-multiply shows up bitwise.
    s = np.abs(rng.normal(size=n)).astype(np.float32)
    return (s * rng.choice([1e-8, 1.0, 1e8], size=n)).astype(np.float32)


@pytest.mark.parametrize(
    "m,n,k",
    [
        (1, 1, 32),  # degenerate axes
        (7, 33, 64),  # nothing aligned
        (130, 72, 96),  # just past one M block
        (64, 200, 512),  # multi-K-block accumulation
        (3, 129, 4608),  # QuickNet-section K depth, N just past a block
    ],
)
def test_scaled_gemm_bitwise_vs_reference(m, n, k):
    rng = np.random.default_rng(m * 7 + n * 3 + k)
    a = _signs(rng, (m, k))
    b = _signs(rng, (k, n))
    scale = _adversarial_scale(rng, n)
    ap = pack_bits(jnp.asarray(a), axis=-1)
    bp = pack_bits(jnp.asarray(b), axis=0)
    got = xnor_matmul_packed_scaled(
        ap, bp, jnp.asarray(scale), k_true=k, interpret=True
    )
    # The reference composition the zero-ULP argument is made against:
    # exact int32 counts -> exact fp32 cast -> ONE fp32 multiply.
    acc = np.asarray(
        xnor_matmul_packed(ap, bp, k_true=k, interpret=True)
    )
    np.testing.assert_array_equal(acc, a @ b)  # exact-integer contract
    want = acc.astype(np.float32) * scale[None, :]
    np.testing.assert_array_equal(np.asarray(got), want)


def test_scaled_gemm_ragged_k_true_correction():
    """K not a multiple of 32: both operands pad the tail with MATCHED
    +1 bits (zero mismatches) and ``k_true`` keeps the count exact —
    the kernel must reproduce the true-K product bitwise."""
    rng = np.random.default_rng(11)
    for k_true in (1, 31, 33, 100):
        k_pad = -(-k_true // 32) * 32
        a = _signs(rng, (5, k_true))
        b = _signs(rng, (k_true, 40))
        a_pad = np.pad(a, ((0, 0), (0, k_pad - k_true)), constant_values=1.0)
        b_pad = np.pad(b, ((0, k_pad - k_true), (0, 0)), constant_values=1.0)
        scale = _adversarial_scale(rng, 40)
        got = xnor_matmul_packed_scaled(
            pack_bits(jnp.asarray(a_pad), axis=-1),
            pack_bits(jnp.asarray(b_pad), axis=0),
            jnp.asarray(scale),
            k_true=k_true,
            interpret=True,
        )
        want = (a @ b).astype(np.float32) * scale[None, :]
        np.testing.assert_array_equal(np.asarray(got), want)


def test_scaled_gemm_validates_scale_shape():
    ap = pack_bits(jnp.ones((4, 32), jnp.float32), axis=-1)
    bp = pack_bits(jnp.ones((32, 8), jnp.float32), axis=0)
    with pytest.raises(ValueError, match="scale"):
        xnor_matmul_packed_scaled(
            ap, bp, jnp.ones((4,), jnp.float32), k_true=32, interpret=True
        )


# -- conv-as-gemm ------------------------------------------------------------


def _conv_pair(rng, b, h, w, ci, co, kh, kw):
    x = jnp.asarray(_signs(rng, (b, h, w, ci)))
    scale = np.abs(rng.normal(size=co)).astype(np.float32) + 0.1
    q_kernel = jnp.asarray(_signs(rng, (kh, kw, ci, co)) * scale)
    packed, pscale = pack_conv_kernel(q_kernel)
    return x, packed, pscale


def _conv_ab(x, packed, scale, strides, padding, ci):
    kw = {"ci": ci, "use_popcount": True, "interpret": True}
    ref = _packed_conv_forward(
        x, packed, scale, strides, padding, flavor="reference", **kw
    )
    fused = _packed_conv_forward(
        x, packed, scale, strides, padding, flavor="pallas", **kw
    )
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(fused))
    return np.asarray(fused)


@pytest.mark.parametrize("strides", [(1, 1), (2, 2), (2, 1)])
@pytest.mark.parametrize("padding", ["SAME", "VALID"])
def test_conv_gemm_strides_padding_grid(strides, padding):
    rng = np.random.default_rng(sum(strides) * 10 + len(padding))
    x, packed, scale = _conv_pair(rng, b=2, h=9, w=8, ci=17, co=33, kh=3, kw=3)
    _conv_ab(x, packed, scale, strides, padding, ci=17)


@pytest.mark.parametrize("ci,co,kh,kw", [(3, 8, 1, 1), (5, 33, 3, 3), (32, 130, 5, 3)])
def test_conv_gemm_ragged_channels_and_kernels(ci, co, kh, kw):
    """Ragged input channels exercise the +1 channel padding (k_true =
    kh*kw*ci stays the TRUE count); co past the 128-lane block
    exercises the output-channel padding slice."""
    rng = np.random.default_rng(ci * co)
    x, packed, scale = _conv_pair(rng, b=1, h=7, w=7, ci=ci, co=co, kh=kh, kw=kw)
    _conv_ab(x, packed, scale, (1, 1), "SAME", ci=ci)


def test_conv_gemm_poisoned_unread_input_rows():
    """VALID at stride 2 on an even height leaves the last input row
    unread by every window: garbage there (±1e30) must not leak into
    either flavor, and the two must still agree bitwise."""
    rng = np.random.default_rng(3)
    x, packed, scale = _conv_pair(rng, b=1, h=8, w=8, ci=16, co=16, kh=3, kw=3)
    xg = np.array(x)  # writable copy
    xg[:, -1, :, :] = 1e30 * np.where(rng.random(xg[:, -1].shape) < 0.5, -1, 1)
    xg[:, :, -1, :] = -1e30
    clean = _conv_ab(x, packed, scale, (2, 2), "VALID", ci=16)
    poisoned = _conv_ab(jnp.asarray(xg), packed, scale, (2, 2), "VALID", ci=16)
    # (8-3)//2+1 = 3 output rows read input rows 0..6 only; the
    # poisoned row 7 / col 7 are dead and the outputs match exactly.
    np.testing.assert_array_equal(clean, poisoned)


def test_conv_gemm_bf16_input_bitwise():
    """bf16 activations (the mixed-precision deployment dtype): the
    sign compare is exact in any float dtype, so the fused path stays
    bit-identical — the documented-ULP budget is for the fp32 epilogue
    multiply, which both flavors share as one op."""
    rng = np.random.default_rng(5)
    x, packed, scale = _conv_pair(rng, b=1, h=6, w=6, ci=32, co=16, kh=3, kw=3)
    _conv_ab(x.astype(jnp.bfloat16), packed, scale, (1, 1), "SAME", ci=32)


def test_grouped_and_depthwise_convs_excluded_upstream():
    """The §21 kernels never see grouped contractions: the layer seam
    rejects grouped/depthwise binary_compute before dispatch (grouping
    removes the K=ci compression the packed paths exist for)."""
    from zookeeper_tpu.ops.layers import QuantConv

    x = jnp.ones((1, 8, 8, 16), jnp.float32)
    for groups in (2, -1):  # grouped, depthwise
        layer = QuantConv(
            16, (3, 3), input_quantizer="ste_sign",
            kernel_quantizer="ste_sign", binary_compute="xnor_popcount",
            feature_group_count=groups, pallas_interpret=True,
        )
        with pytest.raises(ValueError, match="grouped conv"):
            layer.init(jax.random.PRNGKey(0), x)


# -- deployment walk ---------------------------------------------------------


def test_packed_deployment_walk_compile_free():
    """The packed QuickNet forward under the pallas flavor is ONE
    compilation: repeated batches re-enter the same executable
    (zero post-warmup recompiles — the serving contract §21 rides)."""
    from zookeeper_tpu.core import configure
    from zookeeper_tpu.models import QuickNet
    from zookeeper_tpu.ops.packed import pack_quantconv_params

    def build(packed):
        model = QuickNet()
        configure(
            model,
            {
                "blocks_per_section": (1, 1),
                "section_features": (32, 64),
                "binary_compute": "xnor_popcount",
                "packed_weights": packed,
                "pallas_interpret": True,
                "binary_flavor": "pallas",
            },
            name="model",
        )
        return model.build((16, 16, 3), num_classes=4)

    rng = np.random.default_rng(9)
    x = jnp.asarray(rng.normal(size=(2, 16, 16, 3)), jnp.float32)
    variables = build(False).init(jax.random.PRNGKey(0), x, training=False)
    packed_vars = {
        **variables,
        "params": pack_quantconv_params(variables["params"]),
    }
    module = build(True)
    fwd = jax.jit(lambda v, xb: module.apply(v, xb, training=False))
    y0 = np.asarray(fwd(packed_vars, x))
    for seed in (1, 2):
        xb = jnp.asarray(
            np.random.default_rng(seed).normal(size=x.shape), jnp.float32
        )
        fwd(packed_vars, xb)
    assert fwd._cache_size() == 1  # zero post-warmup recompiles
    np.testing.assert_array_equal(y0, np.asarray(fwd(packed_vars, x)))
