"""quantized_param_view: the larq ``quantized_scope`` capability as an
explicit tree transform (params are explicit in JAX, so "enter the scope"
becomes "map the tree")."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from zookeeper_tpu.ops import (
    QuantConv,
    QuantDense,
    quantized_param_view,
)


def test_view_quantizes_only_latent_sign_kernels():
    import flax.linen as nn

    class Net(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = QuantConv(
                features=4, kernel_size=(3, 3),
                input_quantizer="ste_sign", kernel_quantizer="ste_sign",
            )(x)
            x = x.mean(axis=(1, 2))
            x = QuantDense(features=3, kernel_quantizer="ste_sign")(x)
            return nn.Dense(2)(x)

    x = jnp.asarray(
        np.random.default_rng(0).normal(size=(1, 6, 6, 2)), jnp.float32
    )
    params = Net().init(jax.random.PRNGKey(0), x)["params"]
    view = quantized_param_view(params)

    qconv = view["QuantConv_0"]["kernel"]
    qdense = view["QuantDense_0"]["kernel"]
    # Sign-family read: exactly +-1 everywhere.
    np.testing.assert_array_equal(np.abs(np.asarray(qconv)), 1.0)
    np.testing.assert_array_equal(np.abs(np.asarray(qdense)), 1.0)
    # Signs agree with the latents.
    np.testing.assert_array_equal(
        np.sign(np.asarray(params["QuantConv_0"]["kernel"])),
        np.asarray(qconv),
    )
    # Non-quant layers pass through untouched (same objects / values).
    np.testing.assert_array_equal(
        np.asarray(view["Dense_0"]["kernel"]),
        np.asarray(params["Dense_0"]["kernel"]),
    )
    # Originals are not mutated.
    assert not np.all(np.abs(np.asarray(params["QuantConv_0"]["kernel"])) == 1)


def test_view_matches_layer_forward_read():
    """The view must equal the value the forward pass contracts with:
    applying the view's kernel through a no-quantizer layer reproduces
    the quantized layer's output."""
    layer = QuantConv(
        features=3, kernel_size=(3, 3), kernel_quantizer="ste_sign",
        padding="VALID",
    )
    plain = QuantConv(
        features=3, kernel_size=(3, 3), kernel_quantizer=None,
        kernel_clip=False, padding="VALID",
    )
    x = jnp.asarray(
        np.random.default_rng(1).normal(size=(2, 5, 5, 2)), jnp.float32
    )
    params = layer.init(jax.random.PRNGKey(1), x)
    y_q = layer.apply(params, x)
    # A top-level layer's params carry no module scope; present them the
    # way they appear inside a model tree.
    view = quantized_param_view({"QuantConv_0": params["params"]})
    # Unquantized kernels are stored under "kernel_fp".
    y_plain = plain.apply(
        {"params": {"kernel_fp": view["QuantConv_0"]["kernel"]}}, x
    )
    np.testing.assert_array_equal(np.asarray(y_q), np.asarray(y_plain))


def test_view_magnitude_aware_keeps_per_channel_scale():
    params = {
        "QuantConv_0": {
            "kernel": jnp.asarray(
                np.random.default_rng(2).normal(size=(3, 3, 4, 2)),
                jnp.float32,
            )
        }
    }
    view = quantized_param_view(
        params, kernel_quantizer="magnitude_aware_sign", kernel_clip=False
    )
    q = np.asarray(view["QuantConv_0"]["kernel"])
    # sign x per-output-channel scale: each channel has exactly one |value|.
    for co in range(q.shape[-1]):
        vals = np.unique(np.abs(q[..., co]))
        assert len(vals) == 1
    assert not np.allclose(np.abs(q), 1.0)


def test_view_requires_quantizer():
    with pytest.raises(ValueError, match="requires a kernel quantizer"):
        quantized_param_view({}, kernel_quantizer=None)
