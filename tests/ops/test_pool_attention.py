"""Op-level certification of the page-pool attention family
(docs/DESIGN.md §20): the gathered-pool reference must be BIT-identical
to the slot-contiguous ``cached_attention`` oracle on every live row
(the gather is pure indirection — same values, same einsums), the
page-table scalar-prefetch kernel rides the §17 tolerance contract
against that reference, and the int8 path's dequantize-inside-the-read
stays within the documented quantization bound with argmax stability.
All CPU (interpret-mode Pallas)."""

import numpy as np
import pytest

from zookeeper_tpu import ops

ATOL = 2e-6  # the §17 kernel's documented fp32 reassociation bound


def scattered_pool(kc, vc, page_size, num_pages, seed=0, poison=1e9):
    """Scatter slot-contiguous caches ``[b, cap, h, d]`` into a
    shuffled page pool whose UNUSED pages are poisoned at ±1e9 — every
    test therefore re-pins the free-page-garbage-harmless contract."""
    rng = np.random.default_rng(seed)
    b, cap, h, d = kc.shape
    m = cap // page_size
    assert num_pages >= b * m
    perm = rng.permutation(num_pages)[: b * m]
    table = perm.reshape(b, m).astype(np.int32)
    sign = rng.choice([-1.0, 1.0], size=(num_pages, page_size, h, d))
    k_pool = (sign * poison).astype(kc.dtype)
    v_pool = (-sign * poison).astype(vc.dtype)
    for s in range(b):
        for p in range(m):
            k_pool[table[s, p]] = kc[s, p * page_size:(p + 1) * page_size]
            v_pool[table[s, p]] = vc[s, p * page_size:(p + 1) * page_size]
    return k_pool, v_pool, table


@pytest.fixture(scope="module")
def operands():
    rng = np.random.default_rng(3)
    b, cap, h, d, ps = 4, 32, 4, 16, 8
    kc = rng.normal(size=(b, cap, h, d)).astype(np.float32)
    vc = rng.normal(size=(b, cap, h, d)).astype(np.float32)
    q = rng.normal(size=(b, 1, h, d)).astype(np.float32)
    # The adversarial length sweep: empty, mid-page, page boundary,
    # last row.
    lengths = np.array([0, 13, 16, 31], np.int32)
    k_pool, v_pool, table = scattered_pool(kc, vc, ps, 24)
    return q, kc, vc, k_pool, v_pool, table, lengths, ps


def test_pool_reference_bit_identical_to_cached_attention(operands):
    q, kc, vc, k_pool, v_pool, table, lengths, ps = operands
    ref = np.asarray(ops.cached_attention(q, kc, vc, lengths))
    pool = np.asarray(
        ops.pool_decode_attention(q, k_pool, v_pool, table, lengths)
    )
    # BIT-identical, with the unused pool pages poisoned at ±1e9: the
    # gather is indirection only, and masked rows (finite mask value,
    # softmax-underflow to exactly 0.0) cannot perturb one bit.
    np.testing.assert_array_equal(ref, pool)


def test_pool_verify_bit_identical_to_verify_cached(operands):
    q, kc, vc, k_pool, v_pool, table, lengths, ps = operands
    rng = np.random.default_rng(5)
    w = 5
    qv = rng.normal(size=(kc.shape[0], w, kc.shape[2], kc.shape[3]))
    qv = qv.astype(np.float32)
    lens = np.array([0, 7, 16, 27 - w], np.int32)
    ref = np.asarray(ops.verify_cached_attention(qv, kc, vc, lens))
    pool = np.asarray(
        ops.pool_verify_attention(qv, k_pool, v_pool, table, lens)
    )
    np.testing.assert_array_equal(ref, pool)


def test_pool_kernel_matches_reference_within_tolerance(operands):
    q, kc, vc, k_pool, v_pool, table, lengths, ps = operands
    ref = np.asarray(
        ops.pool_decode_attention(q, k_pool, v_pool, table, lengths)
    )
    kern = np.asarray(
        ops.pool_paged_decode_attention(q, k_pool, v_pool, table, lengths)
    )
    np.testing.assert_allclose(kern, ref, atol=ATOL, rtol=0)


def test_pool_kernel_dead_table_entries_harmless(operands):
    """Unallocated (-1) table entries past each slot's live pages must
    not perturb either path: the kernel's index map never selects them
    (dead logical pages clamp to the last live page) and the reference
    masks them."""
    q, kc, vc, k_pool, v_pool, table, lengths, ps = operands
    t2 = table.copy()
    # Kill every page strictly past the live region per slot.
    for s, n in enumerate(lengths):
        live = int(n) // ps + 1
        t2[s, live:] = -1
    ref = np.asarray(
        ops.pool_decode_attention(q, k_pool, v_pool, table, lengths)
    )
    got_ref = np.asarray(
        ops.pool_decode_attention(q, k_pool, v_pool, t2, lengths)
    )
    got_kern = np.asarray(
        ops.pool_paged_decode_attention(q, k_pool, v_pool, t2, lengths)
    )
    np.testing.assert_array_equal(ref, got_ref)
    np.testing.assert_allclose(got_kern, ref, atol=ATOL, rtol=0)


def test_int8_pool_attention_documented_ulp_and_argmax(operands):
    """int8 rows + per-(row, head) scales, dequantized inside the
    read: output within the quantization bound of the fp pool path,
    and the per-head argmax over a logits-like projection stays
    stable — the op-level half of the §20 numerics contract."""
    q, kc, vc, k_pool, v_pool, table, lengths, ps = operands
    kq, ks = ops.quantize_kv_rows(k_pool)
    vq, vs = ops.quantize_kv_rows(v_pool)
    fp = np.asarray(
        ops.pool_decode_attention(q, k_pool, v_pool, table, lengths)
    )
    q8 = np.asarray(
        ops.pool_decode_attention(
            q, np.asarray(kq), np.asarray(vq), table, lengths,
            k_scale=np.asarray(ks), v_scale=np.asarray(vs),
        )
    )
    # Symmetric int8 with per-row scales: relative step 1/254, and the
    # softmax-weighted sum keeps the error in the same class.
    np.testing.assert_allclose(q8, fp, atol=0.05, rtol=0)
    kern8 = np.asarray(
        ops.pool_paged_decode_attention(
            q, np.asarray(kq), np.asarray(vq), table, lengths,
            k_scale=np.asarray(ks), v_scale=np.asarray(vs),
        )
    )
    np.testing.assert_allclose(kern8, q8, atol=ATOL, rtol=0)


def test_quantize_kv_rows_roundtrip_bound():
    rng = np.random.default_rng(11)
    x = (rng.normal(size=(6, 4, 3, 16)) * rng.gamma(1, 4)).astype(
        np.float32
    )
    x[0, 0] = 0.0  # all-zero row: scale 1, exact round trip
    q, s = ops.quantize_kv_rows(x)
    back = np.asarray(ops.dequantize_kv_rows(np.asarray(q), np.asarray(s)))
    amax = np.abs(x).max(axis=-1, keepdims=True)
    # Half-step bound per element, relative to each row's own scale.
    bound = amax / ops.KV_INT8_QMAX * 0.5 + 1e-7
    assert np.all(np.abs(back - x) <= bound)
    np.testing.assert_array_equal(back[0, 0], 0.0)


def test_pool_kernel_bf16_matches_reference_argmax(operands):
    import jax.numpy as jnp

    q, kc, vc, k_pool, v_pool, table, lengths, ps = operands
    qb = jnp.asarray(q, jnp.bfloat16)
    kb = jnp.asarray(np.nan_to_num(k_pool, posinf=0, neginf=0), jnp.bfloat16)
    vb = jnp.asarray(np.nan_to_num(v_pool, posinf=0, neginf=0), jnp.bfloat16)
    ref = np.asarray(
        ops.pool_decode_attention(qb, kb, vb, table, lengths),
        np.float32,
    )
    kern = np.asarray(
        ops.pool_paged_decode_attention(qb, kb, vb, table, lengths),
        np.float32,
    )
    # bf16 output grid is coarse; the two paths must agree to the
    # output resolution and pick the same per-head max lane.
    np.testing.assert_allclose(kern, ref, atol=0.04, rtol=0)
    np.testing.assert_array_equal(
        kern.argmax(axis=-1), ref.argmax(axis=-1)
    )


def test_pool_attention_validation_errors(operands):
    q, kc, vc, k_pool, v_pool, table, lengths, ps = operands
    with pytest.raises(ValueError, match="slots, 1, heads"):
        ops.pool_paged_decode_attention(
            q[:, 0], k_pool, v_pool, table, lengths
        )
    with pytest.raises(ValueError, match="page_table"):
        ops.pool_paged_decode_attention(
            q, k_pool, v_pool, table[:2], lengths
        )
    with pytest.raises(ValueError, match="together"):
        ops.pool_paged_decode_attention(
            q, k_pool, v_pool, table, lengths,
            k_scale=np.ones(k_pool.shape[:3], np.float32),
        )


@pytest.mark.slow
def test_sharded_pool_kernel_on_mesh(operands):
    """The shard_map composition on the 8-virtual-device mesh: slots/
    table/lengths over the data axes, pool heads over the model axis,
    zero collectives — output equal to the single-device kernel."""
    import jax
    from jax.sharding import Mesh

    q, kc, vc, k_pool, v_pool, table, lengths, ps = operands
    devices = np.asarray(jax.devices()[:8]).reshape(4, 2)
    mesh = Mesh(devices, ("data", "model"))
    single = np.asarray(
        ops.pool_paged_decode_attention(q, k_pool, v_pool, table, lengths)
    )
    with mesh:
        sharded = np.asarray(
            ops.sharded_pool_paged_decode_attention(
                q, k_pool, v_pool, table, lengths,
                mesh=mesh, data_axes=("data",), model_axis="model",
            )
        )
        replicated = np.asarray(
            ops.sharded_pool_paged_decode_attention(
                q, k_pool, v_pool, table, lengths,
                mesh=mesh, replicated=True,
            )
        )
    np.testing.assert_allclose(sharded, single, atol=ATOL, rtol=0)
    np.testing.assert_allclose(replicated, single, atol=ATOL, rtol=0)
