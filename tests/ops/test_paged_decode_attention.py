"""Paged decode-attention kernel certification (docs/DESIGN.md §17).

The kernel's load-bearing claim is NUMERICS: it must agree with the
``ops.cached_attention`` reference einsum — the oracle the whole decode
parity chain (§15) is pinned against — to documented-ULP on logits and
token-exactly on argmax, over every cache state the scheduler can
produce. The property sweep therefore varies LENGTHS (runtime data: one
jitted kernel serves every case — length=1-row, length=capacity,
partial final page, ragged mixes, garbage rows beyond ``lengths``)
against a single compiled geometry, plus geometry-edge cases that each
pay one extra interpret-mode compile.

Tolerance contract (stated here, referenced by the kernel docstring):
fp32 outputs agree with the reference within ``2e-6`` absolute for
O(1)-scale inputs — online-softmax reassociation across kv blocks is
the ONLY divergence (observed max ~2e-7, one order of margin); bf16
outputs have the reassociation ULPs absorbed by the output rounding and
are asserted bit-identical. Argmax over the head_dim axis (the
token-selection proxy) is exact in both dtypes.

All CPU: ``interpret=None`` auto-selects interpret mode off-TPU.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from zookeeper_tpu.ops import (
    cached_attention,
    decode_attention_supported,
    paged_decode_attention,
    sharded_paged_decode_attention,
)
from zookeeper_tpu.ops.attention import _default_decode_blocks

F32_ATOL = 2e-6

# One geometry, jitted once, shared by the whole length sweep: 3 heads
# (non-power-of-two), head_dim 16, capacity 48 = 3 blocks of 16 — so a
# partial-final-page length (e.g. 33) exercises the masked last block
# and the ragged cases hit different per-slot live-block counts.
SLOTS, CAP, HEADS, DIM, BLOCK = 4, 48, 3, 16, 16


@pytest.fixture(scope="module")
def qkv():
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(SLOTS, 1, HEADS, DIM)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(SLOTS, CAP, HEADS, DIM)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(SLOTS, CAP, HEADS, DIM)), jnp.float32)
    return q, k, v


@pytest.fixture(scope="module")
def kernel():
    from functools import partial

    return jax.jit(
        partial(paged_decode_attention, page_size=8, block_kv=BLOCK)
    )


@pytest.mark.parametrize(
    "lengths",
    [
        # length=0: only row 0 (the just-written token) is attended —
        # the first decode step after a 1-token prefill.
        [0, 0, 0, 0],
        # length=capacity-1: every row live, the ring/capacity edge the
        # scheduler truncates at.
        [CAP - 1] * SLOTS,
        # Partial final page: 33 lands 2 rows into the third block.
        [33, 33, 33, 33],
        # Ragged: every slot bounds its own kv loop differently.
        [0, CAP - 1, 17, 5],
        # Block boundaries themselves (first row of a block / last row
        # of the previous one).
        [15, 16, 31, 32],
    ],
)
def test_length_sweep_matches_reference(qkv, kernel, lengths):
    q, k, v = qkv
    lens = jnp.asarray(lengths, jnp.int32)
    ref = cached_attention(q, k, v, lens)
    got = kernel(q, k, v, lens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=F32_ATOL, rtol=0)
    # Token-exactness proxy: per-(slot, head) argmax over head_dim.
    np.testing.assert_array_equal(
        np.argmax(np.asarray(got), axis=-1),
        np.argmax(np.asarray(ref), axis=-1),
    )


def test_garbage_rows_beyond_lengths_never_leak(qkv, kernel):
    """The slot-refill validity invariant: rows >= length+1 hold a
    PREVIOUS occupant's K/V (or prefill padding). The kernel on a
    garbage-poisoned cache must equal the reference on a ZEROED one —
    masked rows contribute exactly nothing, not merely approximately."""
    q, k, v = qkv
    lens = jnp.asarray([5, 20, 0, CAP - 1], jnp.int32)
    row = jnp.arange(CAP)[None, :, None, None]
    live = row <= lens[:, None, None, None]
    # Huge finite garbage: if any masked row leaked it would dominate.
    k_dirty = jnp.where(live, k, 1e9)
    v_dirty = jnp.where(live, v, -1e9)
    k_clean = jnp.where(live, k, 0.0)
    v_clean = jnp.where(live, v, 0.0)
    got = kernel(q, k_dirty, v_dirty, lens)
    ref = cached_attention(q, k_clean, v_clean, lens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=F32_ATOL, rtol=0)


def test_lengths_at_or_past_capacity_clamp_like_reference(qkv, kernel):
    """The reference mask ``ki <= lengths`` attends every row when
    lengths >= capacity; the kernel's clamp must agree (the scheduler
    never sends such lengths, but an idle slot's ride-along must not
    be able to produce NaN)."""
    q, k, v = qkv
    lens = jnp.asarray([CAP, CAP + 7, CAP - 1, 2 * CAP], jnp.int32)
    ref = cached_attention(q, k, v, lens)
    got = kernel(q, k, v, lens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=F32_ATOL, rtol=0)
    assert not np.isnan(np.asarray(got)).any()


def test_bf16_bit_identical_and_argmax_exact(qkv, kernel):
    q, k, v = (t.astype(jnp.bfloat16) for t in qkv)
    lens = jnp.asarray([7, CAP - 1, 0, 21], jnp.int32)
    ref = cached_attention(q, k, v, lens)
    got = kernel(q, k, v, lens)
    # Output rounding to bf16 absorbs the fp32 reassociation ULPs: the
    # observed contract is BIT-identical, and this pin is what turns
    # the observation into a commitment.
    np.testing.assert_array_equal(
        np.asarray(got.astype(jnp.float32)),
        np.asarray(ref.astype(jnp.float32)),
    )


def test_explicit_scale_and_head_blocking(qkv):
    q, k, v = qkv
    lens = jnp.asarray([3, 40, 11, 0], jnp.int32)
    ref = cached_attention(q, k, v, lens, scale=0.25)
    # block_h=1: the head-blocked grid (3 head steps) must reproduce
    # the all-heads-per-step default exactly.
    got = paged_decode_attention(
        q, k, v, lens, scale=0.25, block_kv=BLOCK, block_h=1
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=F32_ATOL, rtol=0)


def test_single_block_capacity(qkv):
    """block_kv == capacity (nk = 1): init, the only block, and the
    finalize all land on one grid step."""
    q, k, v = qkv
    lens = jnp.asarray([0, 13, CAP - 1, 29], jnp.int32)
    ref = cached_attention(q, k, v, lens)
    got = paged_decode_attention(q, k, v, lens, block_kv=CAP)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=F32_ATOL, rtol=0)


def test_sharded_wrapper_matches_reference():
    """The mesh composition (slots over 'data', heads over 'model') on
    the 8-virtual-device test mesh — the decode engine's sharded path
    without the engine around it."""
    from jax.sharding import Mesh

    mesh = Mesh(
        np.array(jax.devices()[:8]).reshape(4, 2), ("data", "model")
    )
    rng = np.random.default_rng(1)
    b, cap, h, d = 8, 32, 2, 16
    q = jnp.asarray(rng.normal(size=(b, 1, h, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, cap, h, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, cap, h, d)), jnp.float32)
    lens = jnp.asarray(rng.integers(0, cap, size=b), jnp.int32)
    ref = cached_attention(q, k, v, lens)
    got = sharded_paged_decode_attention(
        q, k, v, lens, mesh=mesh, data_axes=("data",), model_axis="model",
        block_kv=16,
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=F32_ATOL, rtol=0)


def test_shape_validation():
    q = jnp.zeros((2, 1, 2, 16), jnp.float32)
    k = jnp.zeros((2, 32, 2, 16), jnp.float32)
    lens = jnp.zeros((2,), jnp.int32)
    with pytest.raises(ValueError, match="expects q"):
        paged_decode_attention(q[:, 0], k, k, lens)
    with pytest.raises(ValueError, match="does not match q"):
        paged_decode_attention(q, k[:, :, :1], k[:, :, :1], lens)
    with pytest.raises(ValueError, match="does not divide"):
        paged_decode_attention(q, k, k, lens, block_kv=5)


def test_supported_predicate():
    # Lane-quantum head dims serve; off-quantum ones fall back (the
    # engine degrades to the reference einsum — see DecodeEngine).
    assert decode_attention_supported(4, 64)
    assert decode_attention_supported(1, 8)
    assert not decode_attention_supported(4, 20)
    assert not decode_attention_supported(4, 7)
    assert not decode_attention_supported(0, 64)


def test_default_decode_blocks_policy():
    # Largest candidate dividing capacity, nesting with the page size,
    # within VMEM.
    assert _default_decode_blocks(2048, 8, 128, page_size=16)[0] == 256
    assert _default_decode_blocks(128, 4, 64, page_size=16) == (128, 4)
    # Awkward capacity falls toward the page size...
    assert _default_decode_blocks(48, 4, 64, page_size=16)[0] == 16
    # ...a sub-page candidate that divides both capacity and the page
    # still nests (8 | 40)...
    assert _default_decode_blocks(40, 4, 64, page_size=40)[0] == 8
    # ...and a capacity NO candidate divides becomes a single block.
    assert _default_decode_blocks(44, 4, 64, page_size=44)[0] == 44
    # A page size off the candidate grid must still nest: block 32
    # divides capacity 96 but STRADDLES 48-row pages -> rejected; 16
    # divides the page and is taken instead.
    assert _default_decode_blocks(96, 4, 64, page_size=48)[0] == 16
    # Explicit blocks pass through with divisibility enforced.
    assert _default_decode_blocks(64, 4, 64, block_kv=32, block_h=2) == (32, 2)
    with pytest.raises(ValueError, match="does not divide"):
        _default_decode_blocks(64, 4, 64, block_kv=24)
    with pytest.raises(ValueError, match="does not divide num_heads"):
        _default_decode_blocks(64, 4, 64, block_h=3)
