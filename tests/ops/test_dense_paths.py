"""Binary DENSE compute paths: int8 MXU, packed-weight MXU, XNOR-popcount
VPU, and packed deployment — the dense counterpart of the conv path suite
(BinaryAlexNet's parameters are dominated by its binary dense layers, so
the 32x packed compression matters most here).

All paths run in Pallas interpret mode on CPU and must be bit-exact vs
the float matmul on the quantized domain.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from zookeeper_tpu.ops import (
    QuantDense,
    pack_dense_kernel,
    pack_quantconv_params,
)


def _binary_dense(**kw):
    return QuantDense(
        input_quantizer="ste_sign", kernel_quantizer="ste_sign",
        use_bias=False, **kw,
    )


def _params(features=8, ki=70, seed=0):
    layer = _binary_dense(features=features)
    x = jnp.asarray(
        np.random.default_rng(seed).normal(size=(4, ki)), jnp.float32
    )
    return layer.init(jax.random.PRNGKey(seed), x), x


@pytest.mark.parametrize("mode", ["int8", "xnor", "xnor_popcount"])
def test_dense_paths_bit_exact_vs_mxu(mode):
    params, x = _params()
    base = _binary_dense(features=8)
    alt = _binary_dense(
        features=8, binary_compute=mode, pallas_interpret=True
    )
    np.testing.assert_array_equal(
        np.asarray(base.apply(params, x)), np.asarray(alt.apply(params, x))
    )


@pytest.mark.parametrize("mode", ["int8", "xnor"])
def test_dense_gradients_match_mxu(mode):
    params, x = _params()
    base = _binary_dense(features=8)
    alt = _binary_dense(
        features=8, binary_compute=mode, pallas_interpret=True
    )

    def loss(layer, p):
        return (layer.apply(p, x) ** 2).sum()

    g_base = jax.grad(lambda p: loss(base, p))(params)
    g_alt = jax.grad(lambda p: loss(alt, p))(params)
    np.testing.assert_allclose(
        np.asarray(g_base["params"]["kernel"]),
        np.asarray(g_alt["params"]["kernel"]),
        rtol=1e-5,
    )


def test_dense_magnitude_aware_scale_exact():
    """Per-output-channel scaled kernels run exactly on the int8 path
    (descale to +-1, integer GEMM, one rescale)."""
    ki, n = 36, 6
    rng = np.random.default_rng(3)
    x = jnp.asarray(np.sign(rng.normal(size=(3, ki))), jnp.float32)
    layer = QuantDense(
        features=n, input_quantizer="ste_sign",
        kernel_quantizer="magnitude_aware_sign", use_bias=False,
        binary_compute="int8",
    )
    base = QuantDense(
        features=n, input_quantizer="ste_sign",
        kernel_quantizer="magnitude_aware_sign", use_bias=False,
    )
    params = layer.init(jax.random.PRNGKey(3), x)
    # atol covers the FLOAT oracle's reassociation noise near zero (the
    # int8 path is the exact one: integer sum, one scale multiply).
    np.testing.assert_allclose(
        np.asarray(base.apply(params, x)),
        np.asarray(layer.apply(params, x)),
        rtol=1e-6,
        atol=1e-6,
    )


def test_packed_dense_deployment_bit_exact_and_32x_smaller():
    """Float-trained params convert to the packed structure, load into a
    packed_weights=True layer, and produce bit-identical outputs."""
    features, ki = 8, 96
    params, x = _params(features=features, ki=ki, seed=4)
    float_layer = _binary_dense(features=features)
    y_float = float_layer.apply(params, x)

    packed_params = pack_quantconv_params(
        {"QuantDense_0": params["params"]}
    )["QuantDense_0"]
    assert set(packed_params) == {"kernel_packed", "kernel_scale"}
    assert packed_params["kernel_packed"].shape == (ki // 32, features)
    # 32x compression on the kernel itself (int32 words vs fp32 floats).
    assert (
        packed_params["kernel_packed"].size * 32
        == params["params"]["kernel"].size
    )

    for mode in ("xnor", "xnor_popcount"):
        deployed = _binary_dense(
            features=features, binary_compute=mode, packed_weights=True,
            pallas_interpret=True,
        )
        y_packed = deployed.apply({"params": packed_params}, x)
        np.testing.assert_array_equal(
            np.asarray(y_float), np.asarray(y_packed), err_msg=mode
        )


def test_packed_dense_k_not_multiple_of_32():
    """K padding: zeros on the MXU path, matching +1s on the popcount
    path — both exact for any K."""
    params, x = _params(features=4, ki=45, seed=5)
    base = _binary_dense(features=4)
    for mode in ("xnor", "xnor_popcount"):
        alt = _binary_dense(
            features=4, binary_compute=mode, pallas_interpret=True
        )
        np.testing.assert_array_equal(
            np.asarray(base.apply(params, x)),
            np.asarray(alt.apply(params, x)),
            err_msg=mode,
        )


def test_packed_dense_infer_is_inference_only():
    from zookeeper_tpu.ops import packed_dense_infer

    kernel = jnp.asarray(
        np.sign(np.random.default_rng(6).normal(size=(32, 4))), jnp.float32
    )
    packed, scale = pack_dense_kernel(kernel)
    x = jnp.ones((2, 32))
    with pytest.raises(ValueError, match="inference-only"):
        jax.grad(
            lambda xx: packed_dense_infer(
                xx, packed, scale, 32, interpret=True
            ).sum()
        )(x)


def test_dense_rejects_unusable_binary_path():
    layer = QuantDense(features=4, binary_compute="int8")  # no quantizers
    with pytest.raises(ValueError, match="never falls back silently"):
        layer.init(jax.random.PRNGKey(0), jnp.ones((2, 16)))


def test_higher_rank_dense_inputs():
    """QuantDense accepts [..., K] inputs on every path (flatten/restore
    inside the binary kernels)."""
    layer = _binary_dense(features=6)
    x = jnp.asarray(
        np.random.default_rng(7).normal(size=(2, 3, 40)), jnp.float32
    )
    params = layer.init(jax.random.PRNGKey(7), x)
    y_base = layer.apply(params, x)
    assert y_base.shape == (2, 3, 6)
    for mode in ("int8", "xnor"):
        alt = _binary_dense(
            features=6, binary_compute=mode, pallas_interpret=True
        )
        np.testing.assert_array_equal(
            np.asarray(y_base), np.asarray(alt.apply(params, x))
        )


def test_binarynet_whole_model_packed_deployment_with_dense():
    """BinaryNet float-trained params (convs + dense) convert to the
    packed structure and the packed model apply is bit-identical —
    the whole-model deployment path now covers the dense layers too."""
    from zookeeper_tpu.core import configure
    from zookeeper_tpu.models import BinaryNet

    def build(packed):
        model = BinaryNet()
        configure(
            model,
            {
                "features": (16, 16),
                "dense_units": (64,),
                "binary_compute": "xnor",
                "packed_weights": packed,
                "pallas_interpret": True,
            },
            name="model",
        )
        return model.build((8, 8, 1), num_classes=4)

    float_module = build(packed=False)
    x = jnp.asarray(
        np.random.default_rng(40).normal(size=(2, 8, 8, 1)), jnp.float32
    )
    variables = float_module.init(jax.random.PRNGKey(1), x, training=False)
    y_float = float_module.apply(variables, x, training=False)

    packed_module = build(packed=True)
    template = jax.eval_shape(
        lambda: packed_module.init(jax.random.PRNGKey(1), x, training=False)
    )["params"]
    packed_params = pack_quantconv_params(
        variables["params"], template=template
    )
    # Both a conv and the dense layer converted.
    flat = str(sorted(packed_params))
    assert "QuantDense_0" in flat
    y_packed = packed_module.apply(
        {**variables, "params": packed_params}, x, training=False
    )
    np.testing.assert_array_equal(np.asarray(y_float), np.asarray(y_packed))


@pytest.mark.slow
def test_xnornet_packed_deployment_includes_dense(tmp_path):
    """XNORNet (magnitude-aware kernels) converts template-less and the
    packed model loads — the regression the reviewer flagged: zoo models
    with binary dense layers must declare the packed structure their
    converted params produce."""
    from zookeeper_tpu.core import configure
    from zookeeper_tpu.models import XNORNet

    def build(packed):
        m = XNORNet()
        configure(
            m,
            {
                "binary_compute": "xnor",
                "packed_weights": packed,
                "pallas_interpret": True,
            },
            name="m",
        )
        return m.build((67, 67, 3), num_classes=5)

    x = jnp.asarray(
        np.random.default_rng(50).normal(size=(1, 67, 67, 3)), jnp.float32
    )
    float_module = build(packed=False)
    variables = float_module.init(jax.random.PRNGKey(2), x, training=False)
    y_float = float_module.apply(variables, x, training=False)

    packed_params = pack_quantconv_params(
        variables["params"], kernel_quantizer="magnitude_aware_sign"
    )
    packed_module = build(packed=True)
    y_packed = packed_module.apply(
        {**variables, "params": packed_params}, x, training=False
    )
    np.testing.assert_allclose(
        np.asarray(y_float), np.asarray(y_packed), rtol=1e-5, atol=1e-5
    )


@pytest.mark.slow
def test_binaryalexnet_dense_only_packed_deployment():
    """The measured deployment sweet spot: bf16 convs + packed dense
    (dense holds ~80% of BinaryAlexNet's params at M = batch). The
    mixed template converts only the dense kernels and the mixed model
    is bit-exact vs the float one."""
    from zookeeper_tpu.core import configure
    from zookeeper_tpu.models import BinaryAlexNet

    def build(conf):
        m = BinaryAlexNet()
        configure(m, conf, name="m")
        return m.build((67, 67, 3), num_classes=5)

    x = jnp.asarray(
        np.random.default_rng(60).normal(size=(1, 67, 67, 3)), jnp.float32
    )
    float_module = build({})
    variables = float_module.init(jax.random.PRNGKey(3), x, training=False)
    y_float = float_module.apply(variables, x, training=False)

    mixed_module = build(
        {
            "dense_binary_compute": "xnor",
            "dense_packed_weights": True,
            "pallas_interpret": True,
        }
    )
    template = jax.eval_shape(
        lambda: mixed_module.init(jax.random.PRNGKey(3), x, training=False)
    )["params"]
    packed = pack_quantconv_params(variables["params"], template=template)
    # Only the two dense layers converted; convs keep latent kernels.
    n_packed = sum(
        1 for scope in packed.values()
        if isinstance(scope, dict) and "kernel_packed" in scope
    )
    assert n_packed == 2
    y_mixed = mixed_module.apply(
        {**variables, "params": packed}, x, training=False
    )
    np.testing.assert_array_equal(np.asarray(y_float), np.asarray(y_mixed))


def test_model_summary_counts_packed_dense_weights():
    """models.summary accounts packed DENSE kernels as 1-bit deployment
    weights (32 true weights per stored int32 lane), same as convs."""
    import flax.linen as nn

    from zookeeper_tpu.models import model_summary

    class Net(nn.Module):
        @nn.compact
        def __call__(self, x, training=False):
            x = x.reshape((x.shape[0], -1))
            return QuantDense(
                8, input_quantizer="ste_sign", kernel_quantizer="ste_sign",
                use_bias=False, binary_compute="xnor", packed_weights=True,
                pallas_interpret=True,
            )(x)

    s = model_summary(Net(), (4, 8, 2))  # K = 64 -> 2 packed words
    packed_rows = [r for r in s.rows if r.packed]
    assert len(packed_rows) == 1
    row = packed_rows[0]
    # True weight count restored from the packed lanes: 64 * 8.
    assert row.weight_count == 64 * 8
    assert row.deploy_bits == 1
