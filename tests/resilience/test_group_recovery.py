"""Coordinated process-group recovery (docs/DESIGN.md §19), simulated
in one process: the experiment plays host 0 of a 2-host group over a
``FileCoordinator`` while a test-driven stub thread plays host 1 —
publishing drain flags, joining verdict exchanges. The protocol is
pure filesystem, so the simulation walks the real code; the genuinely
multi-process composition lives in test_multiprocess_chaos.py."""

import threading

import numpy as np
import pytest

from zookeeper_tpu.core import configure
from zookeeper_tpu.observability.registry import default_registry
from zookeeper_tpu.resilience import (
    FaultPlan,
    FileCoordinator,
    GroupPeerFailure,
    Preempted,
    faults,
    run_with_recovery,
)
from zookeeper_tpu.resilience import supervisor as _supervisor
from zookeeper_tpu.training import TrainingExperiment

pytestmark = pytest.mark.chaos


def make_experiment(extra_conf=None):
    exp = TrainingExperiment()
    conf = {
        "loader.dataset": "SyntheticMnist",
        "loader.dataset.num_train_examples": 128,
        "loader.dataset.num_validation_examples": 0,
        "loader.preprocessing": "ImageClassificationPreprocessing",
        "loader.preprocessing.height": 28,
        "loader.preprocessing.width": 28,
        "loader.preprocessing.channels": 1,
        "loader.host_index": 0,
        "loader.host_count": 1,
        "model": "Mlp",
        "model.hidden_units": (16,),
        "batch_size": 32,
        "epochs": 2,
        "validate": False,
        "verbose": False,
        **(extra_conf or {}),
    }
    configure(exp, conf, name="group_exp")
    return exp


def ckpt_conf(tmp_path):
    return {
        "checkpointer.directory": str(tmp_path / "ckpt"),
        "checkpointer.synchronous": True,
        "checkpointer.save_every_epochs": 0,
        "checkpointer.save_every_steps": 0,
    }


class PeerStub:
    """Host 1 of the group, driven on a thread: optionally originates a
    drain flag, then follows the supervisor verdict protocol —
    'recoverable' for the first ``restarts`` verdict rounds, 'ok'
    after — exactly what a real peer supervisor exchanges."""

    def __init__(self, root, restarts=1, originate_at_step=None):
        self.coord = FileCoordinator(str(root), 1, 2, timeout_s=60.0)
        self.restarts = restarts
        self.originate_at_step = originate_at_step
        self.verdicts = []
        self.error = None
        self._thread = threading.Thread(target=self._run, daemon=True)

    def start(self):
        self._thread.start()
        return self

    def join(self):
        self._thread.join(timeout=120)
        assert not self._thread.is_alive()
        if self.error is not None:
            raise self.error

    def _run(self):
        try:
            for attempt in range(self.restarts + 1):
                self.coord.generation = attempt
                if attempt == 0 and self.originate_at_step is not None:
                    self.coord.publish_flag(
                        "preempt",
                        {
                            "origin": 1,
                            "step": int(self.originate_at_step),
                            "signal": None,
                        },
                    )
                outcome = "recoverable" if attempt < self.restarts else "ok"
                self.verdicts.append(
                    self.coord.exchange(
                        "supervisor_verdict",
                        {"outcome": outcome, "cause": None, "origin": None},
                    )
                )
        except BaseException as e:  # surfaced by join()
            self.error = e


def final_params(exp):
    import jax

    return [
        np.asarray(leaf) for leaf in jax.tree.leaves(exp.final_state.params)
    ]


def test_peer_originated_drain_and_bit_identical_resume(tmp_path):
    """A PEER host's preemption flag drains THIS host at the agreed
    boundary (one synchronous save + Preempted), the group supervisor
    restarts in sync with the peer's verdicts, and the resumed run's
    final params are bit-identical to an uninterrupted run's."""
    oracle = make_experiment()
    oracle.run()
    want = final_params(oracle)

    exp = make_experiment(ckpt_conf(tmp_path))
    coord = FileCoordinator(str(tmp_path / "coord"), 0, 2, timeout_s=60.0)
    stub = PeerStub(
        tmp_path / "coord", restarts=1, originate_at_step=0
    ).start()
    result = run_with_recovery(
        exp,
        coordinator=coord,
        max_restarts=2,
        backoff_s=0.0,
        sleep=lambda s: None,
    )
    stub.join()
    assert result.restarts == 1
    # The drain exited at flag.step 0 + the margin (4 at unroll=1).
    assert isinstance(result.causes[0], Preempted)
    assert result.causes[0].step == 4
    assert result.causes[0].saved
    got = final_params(exp)
    for w, g in zip(want, got):
        np.testing.assert_array_equal(w, g)
    # The wiring is removed after the supervised run.
    assert exp.group_coordinator is None


def test_local_kill_publishes_flag_with_origin_and_metrics(tmp_path):
    """kill_process_at_step keyed to THIS host's process index: the
    flag carries origin 0, the guard records it, the group restart
    metric/gauge move, and the flight recorder is notified with the
    triggering host's identity."""
    exp = make_experiment(ckpt_conf(tmp_path))
    coord = FileCoordinator(str(tmp_path / "coord"), 0, 2, timeout_s=60.0)
    stub = PeerStub(tmp_path / "coord", restarts=1).start()
    notifications = []
    orig_notify = _supervisor._recorder.notify
    _supervisor._recorder.notify = lambda kind, **kw: notifications.append(
        (kind, kw)
    )
    counter = default_registry().counter(
        "zk_group_restarts_total",
        help="coordinated whole-process-group restarts",
    )
    before = counter.value
    try:
        with faults.injected(FaultPlan(kill_process_at_step={0: 2})):
            result = run_with_recovery(
                exp,
                coordinator=coord,
                max_restarts=2,
                backoff_s=0.0,
                sleep=lambda s: None,
            )
    finally:
        _supervisor._recorder.notify = orig_notify
    stub.join()
    assert result.restarts == 1
    # Flag at boundary 2 + margin 4 => agreed exit at step 6.
    assert result.causes[0].step == 6
    assert counter.value == before + 1
    assert (
        default_registry()
        .gauge("zk_group_restore_ms")
        .value
        > 0
    )
    group_events = [kw for kind, kw in notifications if kind == "group_restart"]
    assert group_events and group_events[0]["attrs"]["origin"] == 0
    assert group_events[0]["attrs"]["cause"] == "Preempted"


def test_kill_process_at_step_other_host_does_not_fire_locally():
    """The multi-host kill map is keyed on the process index: a plan
    naming host 1 must not preempt host 0 (no coordinator wired, so
    nothing relays it either)."""
    exp = make_experiment()
    with faults.injected(FaultPlan(kill_process_at_step={1: 1})):
        exp.run()  # completes: the fault targets another host


def test_peer_hard_failure_stops_group(tmp_path):
    """A peer whose verdict says 'stop' (unrecoverable exit) must stop
    THIS host's supervisor too — re-forming half a process group would
    wedge the survivors in a collective."""
    exp = make_experiment(ckpt_conf(tmp_path))
    coord = FileCoordinator(str(tmp_path / "coord"), 0, 2, timeout_s=60.0)

    class HardFailPeer(PeerStub):
        def _run(self):
            try:
                self.coord.generation = 0
                self.coord.publish_flag(
                    "preempt", {"origin": 1, "step": 0, "signal": None}
                )
                self.coord.exchange(
                    "supervisor_verdict",
                    {"outcome": "stop", "cause": "RuntimeError", "origin": 1},
                )
            except BaseException as e:
                self.error = e

    stub = HardFailPeer(tmp_path / "coord").start()
    with pytest.raises(Preempted):
        # This host's own exit was a (recoverable) Preempted; the peer's
        # stop verdict makes it propagate instead of restarting.
        run_with_recovery(
            exp,
            coordinator=coord,
            max_restarts=2,
            backoff_s=0.0,
            sleep=lambda s: None,
        )
    stub.join()


def test_verdict_coordinator_loss_raises_group_peer_failure(tmp_path):
    """Losing the coordinator during the restart verdict cannot be
    recovered locally: restarting without agreement could re-form a
    partial group."""
    exp = make_experiment(ckpt_conf(tmp_path))
    coord = FileCoordinator(str(tmp_path / "coord"), 0, 2, timeout_s=60.0)
    # The peer only ORIGINATES the drain; it never exchanges, so the
    # experiment's verdict exchange is the one (deterministic) consumer
    # of the injected one-shot loss — FaultPlan is process-local, and a
    # stub exchange on another thread would race it away.
    peer = FileCoordinator(str(tmp_path / "coord"), 1, 2)
    peer.publish_flag("preempt", {"origin": 1, "step": 0, "signal": None})
    # The loss fires inside the supervisor's verdict exchange (the
    # boundary drain polls flags without exchanging).
    with faults.injected(FaultPlan(coordinator_loss=1)):
        with pytest.raises(GroupPeerFailure):
            run_with_recovery(
                exp,
                coordinator=coord,
                max_restarts=1,
                backoff_s=0.0,
                sleep=lambda s: None,
                group_timeout_s=5.0,
            )


def test_single_process_coordinator_is_inert(tmp_path):
    """A coordinator spanning ONE process must leave the supervised run
    byte-identical to the plain path (the degrade contract)."""
    from zookeeper_tpu.resilience import NullCoordinator

    oracle = make_experiment()
    oracle.run()
    exp = make_experiment()
    result = run_with_recovery(exp, coordinator=NullCoordinator())
    assert result.restarts == 0
    for w, g in zip(final_params(oracle), final_params(exp)):
        np.testing.assert_array_equal(w, g)
