"""FaultPlan / PreemptionGuard mechanics: the deterministic injection
primitives every chaos test builds on. These are pure host-side units
(no JAX) — if they rot, every recovery-leg test downstream lies."""

import os
import signal

import pytest

from zookeeper_tpu.core import configure
from zookeeper_tpu.resilience import (
    FaultPlan,
    PreemptionGuard,
    corrupt_checkpoint_dir,
    faults,
)

pytestmark = pytest.mark.chaos


def test_no_active_plan_by_default():
    assert faults.active() is None


def test_injected_scopes_and_restores():
    outer = FaultPlan(fail_save_io=1)
    with faults.injected(outer) as p:
        assert faults.active() is p is outer
        with faults.injected(FaultPlan(nan_at_step=3)) as inner:
            assert faults.active() is inner
        assert faults.active() is outer
    assert faults.active() is None


def test_install_clear():
    plan = faults.install(FaultPlan(kill_at_step=1))
    try:
        assert faults.active() is plan
    finally:
        faults.clear()
    assert faults.active() is None


def test_kill_due_is_one_shot_and_threshold():
    plan = FaultPlan(kill_at_step=5)
    assert not plan.kill_due(4)
    assert plan.kill_due(6)  # first boundary at/after the step fires
    assert not plan.kill_due(7)  # one-shot: the recovery run survives
    assert not FaultPlan().kill_due(10**9)


def test_save_io_and_worker_crash_counters_consume():
    plan = FaultPlan(fail_save_io=2, serving_worker_crash=1)
    assert plan.take_save_io_failure()
    assert plan.take_save_io_failure()
    assert not plan.take_save_io_failure()
    assert plan.take_worker_crash()
    assert not plan.take_worker_crash()


def test_corrupt_due_fires_once_for_its_step_only():
    plan = FaultPlan(corrupt_checkpoint_step=3)
    assert not plan.corrupt_due(2)
    assert plan.corrupt_due(3)
    assert not plan.corrupt_due(3)


def test_corrupt_checkpoint_dir_tears_files(tmp_path):
    d = tmp_path / "step"
    (d / "sub").mkdir(parents=True)
    (d / "data.bin").write_bytes(os.urandom(256))
    (d / "sub" / "meta.json").write_text('{"ok": true}')
    n = corrupt_checkpoint_dir(str(d))
    assert n == 2
    assert (d / "data.bin").stat().st_size == 128
    assert b"\xde\xad\xbe\xef" in (d / "data.bin").read_bytes()
    # An empty target reports 0 damaged files (test-setup error signal).
    assert corrupt_checkpoint_dir(str(tmp_path / "nowhere")) == 0


def make_guard(**conf):
    g = PreemptionGuard()
    configure(g, dict(conf), name="guard")
    return g


def test_guard_flags_sigterm_without_dying():
    g = make_guard().install()
    try:
        assert not g.preempted
        os.kill(os.getpid(), signal.SIGTERM)
        # The handler runs synchronously in the main thread on the next
        # bytecode boundary; give the interpreter one.
        for _ in range(100):
            if g.preempted:
                break
        assert g.preempted
        assert g.received_signal == signal.SIGTERM
    finally:
        g.uninstall()


def test_guard_restores_previous_handlers():
    prev_term = signal.getsignal(signal.SIGTERM)
    prev_int = signal.getsignal(signal.SIGINT)
    g = make_guard().install()
    assert signal.getsignal(signal.SIGTERM) is not prev_term
    g.uninstall()
    assert signal.getsignal(signal.SIGTERM) is prev_term
    assert signal.getsignal(signal.SIGINT) is prev_int


def test_guard_reinstall_clears_stale_flag():
    g = make_guard()
    g.request_preemption()
    assert g.preempted
    g.install()  # a resumed run must not instantly re-exit
    try:
        assert not g.preempted
    finally:
        g.uninstall()


def test_guard_disabled_hooks_nothing():
    prev = signal.getsignal(signal.SIGTERM)
    g = make_guard(enabled=False).install()
    try:
        assert signal.getsignal(signal.SIGTERM) is prev
        # Programmatic preemption still works (the fault-injection path).
        g.request_preemption()
        assert g.preempted
    finally:
        g.uninstall()


def test_guard_sigint_opt_out():
    prev_int = signal.getsignal(signal.SIGINT)
    g = make_guard(handle_sigint=False).install()
    try:
        assert signal.getsignal(signal.SIGINT) is prev_int
        assert signal.getsignal(signal.SIGTERM) is not prev_int
    finally:
        g.uninstall()


def test_guard_install_off_main_thread_is_quiet():
    """Signals can't be hooked off the main thread; install must degrade
    to flag-only instead of raising (experiments do run in worker
    threads in some harnesses)."""
    import threading

    result = {}

    def run():
        g = make_guard()
        try:
            g.install()
            g.request_preemption()
            result["preempted"] = g.preempted
            g.uninstall()
        except Exception as e:  # pragma: no cover
            result["error"] = e

    t = threading.Thread(target=run)
    t.start()
    t.join(timeout=10)
    assert result.get("error") is None
    assert result.get("preempted") is True


def test_kill_process_at_step_keys_on_process_index():
    plan = FaultPlan(kill_process_at_step={1: 5})
    assert not plan.kill_due(10, process_index=0)  # other host
    assert not plan.kill_due(4, process_index=1)  # before the step
    assert plan.kill_due(6, process_index=1)  # first boundary at/after
    assert not plan.kill_due(7, process_index=1)  # one-shot
    # Default process_index is 0 (single-process callers unchanged).
    assert FaultPlan(kill_process_at_step={0: 2}).kill_due(2)


def test_kill_at_step_still_fires_for_any_process():
    plan = FaultPlan(kill_at_step=3)
    assert plan.kill_due(3, process_index=7)


def test_host_finalize_failure_targets_one_host_once():
    plan = FaultPlan(fail_host_finalize=1)
    assert not plan.take_host_finalize_failure(0)  # other host
    assert plan.take_host_finalize_failure(1)
    assert not plan.take_host_finalize_failure(1)  # one-shot
    # Host 0 is a valid target too (None is the off sentinel).
    assert FaultPlan(fail_host_finalize=0).take_host_finalize_failure(0)
    assert not FaultPlan().take_host_finalize_failure(0)


def test_coordinator_loss_consumes():
    plan = FaultPlan(coordinator_loss=2)
    assert plan.take_coordinator_loss()
    assert plan.take_coordinator_loss()
    assert not plan.take_coordinator_loss()
    assert not FaultPlan().take_coordinator_loss()


def test_kill_knobs_compose_earliest_fires():
    """Both kill knobs set: whichever applicable trigger comes FIRST
    fires (the host-keyed one must not be shadowed by kill_at_step)."""
    plan = FaultPlan(kill_at_step=10, kill_process_at_step={1: 3})
    assert not plan.kill_due(2, process_index=1)
    assert plan.kill_due(3, process_index=1)  # host knob, not step 10
    assert not plan.kill_due(10, process_index=1)  # one-shot plan-wide
    # On a host the map does not name, only kill_at_step applies.
    plan2 = FaultPlan(kill_at_step=10, kill_process_at_step={1: 3})
    assert not plan2.kill_due(3, process_index=0)
    assert plan2.kill_due(10, process_index=0)
