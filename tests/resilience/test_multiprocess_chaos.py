"""The multi-process chaos leg (docs/DESIGN.md §19): two REAL OS
processes form a jax CPU cluster (gloo collectives) and walk the
multi-host fault-tolerance contracts end to end —

- per-host sharded checkpointing: a committed step round-trips
  bit-exactly (a leaf genuinely sharded across the process boundary
  included), and a ``fail_host_finalize`` step is never restored by ANY
  process (commit record absent ⇒ invisible);
- coordinated group recovery: ``kill_process_at_step`` on host 1
  mid-epoch under ``unroll > 1`` drains and saves every host at one
  agreed boundary, both supervisors restart together, restore agrees on
  the step, and the final params are BIT-IDENTICAL to an uninterrupted
  run.

The cluster spins up once (module-scoped — it costs tens of seconds,
hence slow-marked; CI runs this file in its own step) via the same
``zookeeper_tpu.testing`` worker ``__graft_entry__.dryrun_multiprocess``
drives, so the test and the dryrun cannot drift.
"""

import pytest

from zookeeper_tpu.testing import spawn_group_chaos_cluster

pytestmark = [pytest.mark.slow, pytest.mark.chaos]

NUM_PROCESSES = 2


@pytest.fixture(scope="module")
def cluster_results(tmp_path_factory):
    workdir = str(tmp_path_factory.mktemp("group_chaos"))
    results = spawn_group_chaos_cluster(workdir, NUM_PROCESSES)
    assert len(results) == NUM_PROCESSES
    for r in results:
        assert r["ok"], r
    return results


def test_sharded_commit_round_trip(cluster_results):
    """A step every host finalized gets a commit record and restores
    exactly on both hosts — including the leaf sharded ACROSS the
    process boundary (each host wrote and read only its half)."""
    for r in cluster_results:
        assert r["sharded_latest_committed"] == 1
        assert r["restored_step"] == 1
        assert r["restored_shards_exact"]
        assert r["w_cross_process"]


def test_torn_host_finalize_invisible_to_every_process(cluster_results):
    """The acceptance-criteria leg: a step whose finalize died on ONE
    host has no commit record, so NO process ever restores it — both
    hosts walk back to the previous committed step."""
    for r in cluster_results:
        assert not r["torn_step_saved"]
        assert r["latest_after_torn"] == 1
        assert r["restored_step"] == 1


def test_group_recovery_bit_identical(cluster_results):
    """kill_process_at_step={1: 3} mid-epoch under unroll=2: the kill
    on host 1 propagates through the group drain, both hosts save the
    agreed boundary, restart together, restore the same step, and
    finish with params bit-identical to the uninterrupted oracle —
    on every host."""
    digests = set()
    for r in cluster_results:
        assert r["restarts"] == 1
        assert r["bit_identical"]
        digests.add(r["oracle_digest"])
        digests.add(r["chaos_digest"])
    # One byte stream across both runs AND both hosts.
    assert len(digests) == 1
    assert cluster_results[0]["group_restore_ms"] is not None
