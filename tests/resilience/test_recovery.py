"""Recovery legs, walked end to end under deterministic fault injection:
kill -> resume (bit-exact), corrupt checkpoint -> fallback restore,
failed save -> retry/drop without crashing, NaN step -> skip/halt, and
the supervisor's restart budget + restore-latency measurement."""

import logging

import numpy as np
import pytest

from zookeeper_tpu.core import configure
from zookeeper_tpu.resilience import (
    FaultPlan,
    NonFiniteLossError,
    Preempted,
    faults,
    measure_recovery_restore_ms,
    run_with_recovery,
)
from zookeeper_tpu.training import Checkpointer, TrainingExperiment

pytestmark = pytest.mark.chaos


def make_experiment(extra_conf=None):
    exp = TrainingExperiment()
    conf = {
        "loader.dataset": "SyntheticMnist",
        "loader.dataset.num_train_examples": 256,
        "loader.dataset.num_validation_examples": 64,
        "loader.preprocessing": "ImageClassificationPreprocessing",
        "loader.preprocessing.height": 28,
        "loader.preprocessing.width": 28,
        "loader.preprocessing.channels": 1,
        "loader.host_index": 0,
        "loader.host_count": 1,
        "model": "Mlp",
        "model.hidden_units": (32,),
        "batch_size": 32,
        "epochs": 2,
        "verbose": False,
        **(extra_conf or {}),
    }
    configure(exp, conf, name="experiment")
    return exp


def ckpt_conf(tmp_path, **extra):
    return {
        "checkpointer.directory": str(tmp_path / "ckpt"),
        "checkpointer.synchronous": True,
        "checkpointer.save_every_epochs": 0,
        "checkpointer.save_every_steps": 0,
        **extra,
    }


def assert_states_equal(a, b):
    import jax

    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for xa, xb in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(xa), np.asarray(xb))


def _tiny_state(value: float, step: int):
    import jax.numpy as jnp
    import optax

    from zookeeper_tpu.training import TrainState

    state = TrainState.create(
        apply_fn=lambda *a, **k: None,
        params={"w": jnp.full((2,), value)},
        model_state={},
        tx=optax.sgd(0.1),
    )
    return state.replace(step=jnp.asarray(step))


# -- preemption: kill -> save -> Preempted -> resume ---------------------


def test_injected_kill_saves_and_raises_preempted(tmp_path):
    exp = make_experiment({"epochs": 1, **ckpt_conf(tmp_path)})
    with faults.injected(FaultPlan(kill_at_step=2)):
        with pytest.raises(Preempted) as exc:
            exp.run()
    assert exc.value.step == 2 and exc.value.saved
    # The preemption save is the exact state at the boundary.
    assert exp.checkpointer.latest_step() == 2
    exp.checkpointer.close()


def test_kill_without_checkpointer_still_exits_cleanly():
    exp = make_experiment({"epochs": 1})
    with faults.injected(FaultPlan(kill_at_step=2)):
        with pytest.raises(Preempted) as exc:
            exp.run()
    assert exc.value.step == 2 and not exc.value.saved


def test_real_sigterm_exits_at_boundary_with_save(tmp_path):
    """The production path: an actual SIGTERM (not injection) trips the
    guard; the loop exits at the next step boundary with a synchronous
    save. Deterministic: the signal is raised from inside the loop via
    a one-time log hook... simpler — request_preemption() mid-run is
    covered by injection; here we assert the SIGNAL path end to end by
    sending SIGTERM before the first boundary check."""
    import os
    import signal

    exp = make_experiment({"epochs": 1, **ckpt_conf(tmp_path)})
    orig_install = type(exp.guard).install

    def install_and_sigterm(guard):
        orig_install(guard)
        os.kill(os.getpid(), signal.SIGTERM)
        return guard

    object.__setattr__(exp.guard, "install", lambda: install_and_sigterm(exp.guard))
    with pytest.raises(Preempted) as exc:
        exp.run()
    assert exc.value.step == 1  # first boundary after the signal
    assert exp.guard.received_signal == signal.SIGTERM
    assert exp.checkpointer.latest_step() == 1
    # Handlers restored: a later SIGTERM would again be fatal.
    assert signal.getsignal(signal.SIGTERM) not in (None,)
    exp.checkpointer.close()


def test_run_with_recovery_resumes_bit_exact_eager(tmp_path):
    ref = make_experiment()
    ref.run()

    exp = make_experiment(ckpt_conf(tmp_path))
    with faults.injected(FaultPlan(kill_at_step=5)):
        result = run_with_recovery(exp, backoff_s=0.0, sleep=lambda s: None)
    assert result.restarts == 1
    assert isinstance(result.causes[0], Preempted)
    assert len(result.restore_ms) == 1 and result.restore_ms[0] > 0
    assert_states_equal(ref.final_state.params, exp.final_state.params)
    assert_states_equal(ref.final_state.opt_state, exp.final_state.opt_state)
    exp.checkpointer.close()


def test_supervisor_budget_exhausted_propagates(tmp_path):
    """A kill on EVERY attempt exhausts max_restarts and the last
    Preempted propagates (the supervisor never spins forever)."""
    exp = make_experiment(ckpt_conf(tmp_path))
    sleeps = []
    # A fresh one-shot kill per attempt: re-arm via a plan whose
    # kill_at_step always lies ahead of the resumed step.
    attempts = {"n": 0}
    orig_run = exp.run

    def run_rearmed():
        attempts["n"] += 1
        with faults.injected(FaultPlan(kill_at_step=attempts["n"])):
            return orig_run()

    exp.run = run_rearmed
    with pytest.raises(Preempted):
        run_with_recovery(
            exp,
            max_restarts=2,
            backoff_s=1.0,
            backoff_factor=2.0,
            sleep=sleeps.append,
        )
    assert attempts["n"] == 3  # initial + 2 restarts
    assert sleeps == [1.0, 2.0]  # exponential backoff between restarts
    exp.checkpointer.close()


def test_supervisor_rejects_bad_config():
    with pytest.raises(ValueError, match="max_restarts"):
        run_with_recovery(object(), max_restarts=-1)
    with pytest.raises(ValueError, match="backoff"):
        run_with_recovery(object(), backoff_factor=0.5)


def test_supervisor_does_not_restart_operator_sigint(tmp_path):
    """Ctrl-C means STOP: a SIGINT-caused Preempted must propagate
    (clean save already happened), never be auto-restarted — otherwise
    a supervised run is effectively uninterruptible."""
    import signal

    exp = make_experiment({"epochs": 1, **ckpt_conf(tmp_path)})
    orig_check = exp._boundary_check
    tripped = {"done": False}

    def trip_sigint(state, global_step):
        if not tripped["done"]:
            tripped["done"] = True
            exp.guard.request_preemption(signal.SIGINT)
        return orig_check(state, global_step)

    object.__setattr__(exp, "_boundary_check", trip_sigint)
    with pytest.raises(Preempted) as exc:
        run_with_recovery(exp, max_restarts=5, backoff_s=0.0)
    assert exc.value.signum == signal.SIGINT
    assert exp.checkpointer.latest_step() == 1  # saved, then stopped
    exp.checkpointer.close()


def test_supervisor_unrecoverable_propagates_immediately():
    class Boom:
        calls = 0

        def run(self):
            type(self).calls += 1
            raise ValueError("config bug")

    exp = Boom()
    with pytest.raises(ValueError, match="config bug"):
        run_with_recovery(exp, max_restarts=5, backoff_s=0.0)
    assert Boom.calls == 1  # no retry of a non-recoverable exit


def test_measure_recovery_restore_ms(tmp_path):
    out = measure_recovery_restore_ms(
        lambda: make_experiment({"epochs": 1, **ckpt_conf(tmp_path)}),
        kill_at_step=2,
    )
    assert out["recovery_restarts"] == 1.0
    assert out["recovery_restore_ms"] > 0


# -- crash-consistent restore -------------------------------------------


def test_restore_falls_back_to_newest_valid_step(tmp_path, caplog):
    ckpt = Checkpointer()
    configure(
        ckpt,
        {"directory": str(tmp_path / "ck"), "synchronous": True},
        name="ckpt",
    )
    with faults.injected(FaultPlan(corrupt_checkpoint_step=3)):
        for s in (1, 2, 3):
            ckpt.save(_tiny_state(float(s), s), step=s)
    with caplog.at_level(logging.WARNING, "zookeeper_tpu.training.checkpoint"):
        restored = ckpt.restore_state(_tiny_state(0.0, 0))
    assert int(np.asarray(restored.step)) == 2
    np.testing.assert_allclose(np.asarray(restored.params["w"]), 2.0)
    assert any("falling back" in r.message for r in caplog.records)
    ckpt.close()


def test_restore_raises_when_no_step_is_valid(tmp_path):
    """Every retained step corrupt -> raise (silently restarting from
    scratch would be worse than the crash)."""
    from zookeeper_tpu.resilience import corrupt_checkpoint_dir

    ckpt = Checkpointer()
    configure(
        ckpt,
        {"directory": str(tmp_path / "ck"), "synchronous": True},
        name="ckpt",
    )
    for s in (1, 2):
        ckpt.save(_tiny_state(float(s), s), step=s)
    for s in (1, 2):
        assert corrupt_checkpoint_dir(str(tmp_path / "ck" / str(s))) > 0
    with pytest.raises(ValueError, match="None of the 2 retained"):
        ckpt.restore_state(_tiny_state(0.0, 0))
    ckpt.close()


def test_corrupt_latest_end_to_end_resume_continues(tmp_path):
    """The e2e leg: a training run whose newest step-cadence checkpoint
    is torn resumes from the previous one and completes."""
    conf = ckpt_conf(
        tmp_path,
        **{
            "checkpointer.save_every_steps": 3,
            "checkpointer.max_to_keep": 5,
        },
    )
    exp = make_experiment({"epochs": 1, **conf})
    with faults.injected(FaultPlan(corrupt_checkpoint_step=6)):
        exp.run()  # spe=8: saves at 3 and 6; 6 is torn on disk
    exp.checkpointer.close()

    exp2 = make_experiment({"epochs": 2, **conf})
    history = exp2.run()  # resumes at 3, retrains 3..8 then epoch 2
    import jax

    assert int(jax.device_get(exp2.final_state.step)) == 16
    assert len(history["train"]) == 2
    exp2.checkpointer.close()


# -- failed saves never crash the loop -----------------------------------


def test_failed_save_retries_then_succeeds(tmp_path):
    ckpt = Checkpointer()
    configure(
        ckpt,
        {
            "directory": str(tmp_path / "ck"),
            "synchronous": True,
            "save_retry_backoff_s": 0.0,
        },
        name="ckpt",
    )
    with faults.injected(FaultPlan(fail_save_io=1)):
        assert ckpt.save(_tiny_state(1.0, 1), step=1)
    assert ckpt.latest_step() == 1
    ckpt.close()


def test_save_failure_exhausted_drops_without_crashing(tmp_path, caplog):
    ckpt = Checkpointer()
    configure(
        ckpt,
        {
            "directory": str(tmp_path / "ck"),
            "synchronous": True,
            "save_retries": 1,
            "save_retry_backoff_s": 0.0,
        },
        name="ckpt",
    )
    with caplog.at_level(logging.WARNING, "zookeeper_tpu.training.checkpoint"):
        with faults.injected(FaultPlan(fail_save_io=10)):
            assert ckpt.save(_tiny_state(1.0, 1), step=1) is False
    assert ckpt.latest_step() is None
    # The final drop is LOUD: error level, step number, and the full
    # exception chain — a thinning save cadence must not be missable
    # in supervisor logs.
    dropped = [r for r in caplog.records if "DROPPED" in r.message]
    assert dropped and dropped[0].levelno == logging.ERROR
    assert "step 1" in dropped[0].getMessage()
    assert dropped[0].exc_info is not None
    ckpt.close()


def test_training_survives_injected_save_failures(tmp_path):
    """Mid-epoch step-cadence saves that fail (exhausted retries) must
    not abort the epoch — the run completes and later saves land."""
    conf = ckpt_conf(
        tmp_path,
        **{
            "checkpointer.save_every_steps": 3,
            "checkpointer.save_retries": 0,
        },
    )
    exp = make_experiment({"epochs": 1, **conf})
    with faults.injected(FaultPlan(fail_save_io=1)):
        history = exp.run()  # the step-3 save fails; step-6 save lands
    assert len(history["train"]) == 1
    assert sorted(exp.checkpointer._manager().all_steps()) == [6]
    exp.checkpointer.close()


# -- nan_policy -----------------------------------------------------------


def test_nan_skip_keeps_prestep_state_and_counts(tmp_path):
    """At the injected NaN step the params/opt state keep their pre-step
    values (bit-exact vs a run stopped just before it), the step counter
    still advances, and the epoch metrics count 1 skipped step."""
    import jax

    probe = make_experiment(
        {"epochs": 1, "steps_per_epoch": 2, "nan_policy": "skip"}
    )
    probe.run()  # 2 clean steps: the state a skipped step 3 must keep
    exp = make_experiment(
        {"epochs": 1, "steps_per_epoch": 3, "nan_policy": "skip"}
    )
    with faults.injected(FaultPlan(nan_at_step=2)):
        history = exp.run()  # step with counter==2 (the 3rd) blows up
    assert history["train"][0]["skipped_steps"] == 1.0
    assert int(jax.device_get(exp.final_state.step)) == 3
    assert_states_equal(probe.final_state.params, exp.final_state.params)
    # The whole optimizer state (moments AND count) kept its pre-step
    # values — the skipped step is invisible to Adam's bias correction.
    assert_states_equal(
        probe.final_state.opt_state, exp.final_state.opt_state
    )


def test_nan_skip_clean_run_counts_zero():
    exp = make_experiment(
        {"epochs": 1, "steps_per_epoch": 2, "nan_policy": "skip"}
    )
    history = exp.run()
    assert history["train"][0]["skipped_steps"] == 0.0
    assert np.isfinite(history["train"][0]["loss"])


def test_nan_halt_raises_and_recovers(tmp_path):
    """halt: the run raises NonFiniteLossError at the readback boundary;
    a supervised re-run (fault cleared — transient blow-up) restores
    from checkpoint and completes."""
    # log_every tightens the readback cadence: the blow-up at step 6 is
    # detected at the step-6 readback, BEFORE the step-8 save would have
    # written a post-skip state (detection latency IS the readback
    # cadence — the documented halt tradeoff).
    conf = ckpt_conf(
        tmp_path, **{"checkpointer.save_every_steps": 4, "log_every": 2}
    )
    exp = make_experiment({"epochs": 1, "nan_policy": "halt", **conf})
    with faults.injected(FaultPlan(nan_at_step=5)):
        with pytest.raises(NonFiniteLossError) as exc:
            exp.run()
    assert exc.value.skipped == 1
    assert exp.checkpointer.latest_step() == 4  # clean state on disk
    exp.checkpointer.close()

    # The supervisor view: transient fault, one restart completes.
    exp2 = make_experiment({"epochs": 1, "nan_policy": "halt", **conf})
    calls = {"n": 0}
    orig_run = exp2.run

    def run_once_faulted():
        calls["n"] += 1
        if calls["n"] == 1:
            with faults.injected(FaultPlan(nan_at_step=5)):
                return orig_run()
        return orig_run()

    exp2.run = run_once_faulted
    result = run_with_recovery(exp2, backoff_s=0.0, sleep=lambda s: None)
    assert result.restarts == 1
    assert isinstance(result.causes[0], NonFiniteLossError)
    import jax

    assert int(jax.device_get(exp2.final_state.step)) == 8
    exp2.checkpointer.close()


def test_nan_policy_invalid_rejected():
    exp = make_experiment({"nan_policy": "retry"})
    with pytest.raises(ValueError, match="nan_policy"):
        exp.run()
    from zookeeper_tpu.training import make_train_step

    with pytest.raises(ValueError, match="nan_policy"):
        make_train_step(nan_policy="explode")


def test_nan_skip_fused_matches_eager_bit_exact():
    """The scan-fused loop's nan guard is the SAME computation as the
    eager loop's (where-selects ride the scan like everything else)."""
    conf = {"epochs": 1, "nan_policy": "skip"}
    with faults.injected(FaultPlan(nan_at_step=3)):
        eager = make_experiment(conf)
        eager.run()
    with faults.injected(FaultPlan(nan_at_step=3)):
        fused = make_experiment({**conf, "unroll": 4})
        fused.run()
    assert_states_equal(eager.final_state.params, fused.final_state.params)


# -- teardown must not mask the real exception ---------------------------


def test_teardown_failure_does_not_mask_original_exception(tmp_path, caplog):
    """Checkpointer.wait() raising during the finally of a run that is
    ALREADY failing must not replace the original exception (the one
    naming the real bug)."""
    exp = make_experiment({"epochs": 1, "nan_policy": "halt", **ckpt_conf(tmp_path)})

    def broken_wait():
        raise OSError("disk vanished during teardown")

    object.__setattr__(exp.checkpointer, "wait", broken_wait)
    with caplog.at_level(logging.WARNING, "zookeeper_tpu.training.experiment"):
        with faults.injected(FaultPlan(nan_at_step=2)):
            with pytest.raises(NonFiniteLossError):
                exp.run()
    assert any("teardown" in r.message for r in caplog.records)


def test_teardown_failure_propagates_when_run_succeeded(tmp_path):
    """With no exception in flight, a teardown failure is a real
    failure and must propagate (it would otherwise hide a lost save)."""
    exp = make_experiment({"epochs": 1, **ckpt_conf(tmp_path)})

    def broken_wait():
        raise OSError("async save failed at finalize")

    object.__setattr__(exp.checkpointer, "wait", broken_wait)
    with pytest.raises(OSError, match="finalize"):
        exp.run()


def test_teardown_runs_all_steps_before_raising(tmp_path):
    """A checkpointer.wait failure must not prevent writer.flush from
    running (durable metrics > tidy tracebacks)."""
    exp = make_experiment({"epochs": 1, **ckpt_conf(tmp_path)})
    calls = []
    object.__setattr__(
        exp.checkpointer,
        "wait",
        lambda: (_ for _ in ()).throw(OSError("wait failed")),
    )
    orig_flush = exp.writer.flush
    object.__setattr__(
        exp.writer, "flush", lambda: calls.append("flush") or orig_flush()
    )
    with pytest.raises(OSError, match="wait failed"):
        exp.run()
    assert calls == ["flush"]


# -- multi-restart soak ---------------------------------------------------


@pytest.mark.slow
def test_multi_restart_soak_bit_exact(tmp_path):
    """Several kills across one training run, each resumed — the final
    state still matches the uninterrupted run bit-for-bit."""
    ref = make_experiment({"epochs": 3})
    ref.run()

    exp = make_experiment({"epochs": 3, **ckpt_conf(tmp_path)})
    kills = iter([3, 9, 17, None])
    orig_run = exp.run

    def run_rearmed():
        k = next(kills)
        if k is None:
            return orig_run()
        with faults.injected(FaultPlan(kill_at_step=k)):
            return orig_run()

    exp.run = run_rearmed
    result = run_with_recovery(
        exp, max_restarts=5, backoff_s=0.0, sleep=lambda s: None
    )
    assert result.restarts == 3
    # Every resumed run trained past its first step before the next
    # kill, so each contributes a restore-latency sample.
    assert len(result.restore_ms) == 3
    assert all(m > 0 for m in result.restore_ms)
    assert_states_equal(ref.final_state.params, exp.final_state.params)
    assert_states_equal(ref.final_state.opt_state, exp.final_state.opt_state)
    exp.checkpointer.close()
