"""Cross-host coordination primitives (resilience/coordination.py):
the shared-directory exchange/flag substrate the group-recovery and
per-host-checkpoint protocols ride. Driven with N coordinator
instances in one process — the primitive is pure filesystem, so the
simulation IS the real code path."""

import threading

import pytest

from zookeeper_tpu.resilience import (
    CoordinatorLostError,
    FaultPlan,
    FileCoordinator,
    NullCoordinator,
    faults,
)

pytestmark = pytest.mark.chaos


def make_pair(root, **kw):
    return [
        FileCoordinator(str(root), pid, 2, timeout_s=10.0, **kw)
        for pid in range(2)
    ]


def test_exchange_allgathers_ordered_payloads(tmp_path):
    a, b = make_pair(tmp_path)
    out = {}

    def run(coord, payload):
        out[coord.process_index] = coord.exchange("greet", payload)

    t = threading.Thread(target=run, args=(b, {"v": 1}))
    t.start()
    run(a, {"v": 0})
    t.join()
    # Ordered by process index on every host.
    assert out[0] == [{"v": 0}, {"v": 1}]
    assert out[1] == [{"v": 0}, {"v": 1}]


def test_exchange_rounds_do_not_bleed(tmp_path):
    """Round 2 of a key must never consume round 1's files."""
    a, b = make_pair(tmp_path)
    results = []

    def peer():
        results.append(b.exchange("k", "b1"))
        results.append(b.exchange("k", "b2"))

    t = threading.Thread(target=peer)
    t.start()
    assert a.exchange("k", "a1") == ["a1", "b1"]
    assert a.exchange("k", "a2") == ["a2", "b2"]
    t.join()
    assert results == [["a1", "b1"], ["a2", "b2"]]


def test_exchange_timeout_raises_lost(tmp_path):
    a, _ = make_pair(tmp_path)
    with pytest.raises(CoordinatorLostError, match="host\\(s\\) \\[1\\]"):
        a.exchange("alone", 1, timeout_s=0.2)


def test_generation_namespaces_rounds(tmp_path):
    """A restarted attempt (new generation) cannot see the previous
    attempt's files — same key, fresh namespace."""
    a, b = make_pair(tmp_path)
    t = threading.Thread(target=lambda: b.exchange("k", "old"))
    t.start()
    a.exchange("k", "old")
    t.join()
    a.generation = b.generation = 1
    with pytest.raises(CoordinatorLostError):
        a.exchange("k", "new", timeout_s=0.2)


def test_flags_publish_poll_and_generation(tmp_path):
    a, b = make_pair(tmp_path)
    assert a.poll_flags("preempt") == []
    b.publish_flag("preempt", {"origin": 1, "step": 4})
    assert a.poll_flags("preempt") == [{"origin": 1, "step": 4}]
    # Republish overwrites (idempotent per host).
    b.publish_flag("preempt", {"origin": 1, "step": 6})
    assert a.poll_flags("preempt") == [{"origin": 1, "step": 6}]
    a.publish_flag("preempt", {"origin": 0, "step": 6})
    assert len(b.poll_flags("preempt")) == 2
    # A new generation starts flag-free.
    a.generation = 1
    assert a.poll_flags("preempt") == []


def test_injected_coordinator_loss_is_deterministic(tmp_path):
    a, b = make_pair(tmp_path)
    with faults.injected(FaultPlan(coordinator_loss=1)):
        with pytest.raises(CoordinatorLostError, match="injected"):
            a.exchange("k", 1)
        # One-shot: the next round succeeds (peer in a thread).
        t = threading.Thread(target=lambda: b.exchange("k2", "b"))
        t.start()
        assert a.exchange("k2", "a") == ["a", "b"]
        t.join()


def test_bad_process_index_rejected(tmp_path):
    with pytest.raises(ValueError, match="process_index"):
        FileCoordinator(str(tmp_path), 2, 2)


def test_null_coordinator_degenerates():
    c = NullCoordinator()
    assert c.process_count == 1
    assert c.exchange("k", {"x": 1}) == [{"x": 1}]
    assert c.poll_flags("preempt") == []
    c.publish_flag("preempt", {"origin": 0})
    assert c.poll_flags("preempt") == [{"origin": 0}]


def test_new_incarnation_purges_own_stale_files(tmp_path):
    """A REAL restart (fresh coordinator objects over the same
    persistent root) must not consume the dead incarnation's flags or
    exchange rounds: construction purges this host's own files, so
    once both hosts re-construct, the root is clean."""
    a, b = make_pair(tmp_path)
    b.publish_flag("preempt", {"origin": 1, "step": 4})
    t = threading.Thread(target=lambda: b.exchange("verdict", "old-b"))
    t.start()
    a.exchange("verdict", "old-a")
    t.join()
    # The job dies; a new incarnation constructs fresh coordinators.
    a2, b2 = make_pair(tmp_path)
    assert a2.poll_flags("preempt") == []  # no spurious re-drain
    # The first exchange round must wait for FRESH files, not be
    # satisfied instantly by the dead incarnation's verdicts.
    t = threading.Thread(target=lambda: b2.exchange("verdict", "new-b"))
    t.start()
    assert a2.exchange("verdict", "new-a") == ["new-a", "new-b"]
    t.join()


def test_exchange_and_flags_carry_none_payloads(tmp_path):
    """A JSON-null payload is a VALUE, not a missing peer: the round
    completes and the flag polls back."""
    a, b = make_pair(tmp_path)
    t = threading.Thread(target=lambda: b.exchange("k", None))
    t.start()
    assert a.exchange("k", None) == [None, None]
    t.join()
    a.publish_flag("f", None)
    assert b.poll_flags("f") == [None]
