"""Property-based randomized tests of the multi-host batch pipeline's
determinism contract (SURVEY.md §7 "input pipeline at pod scale",
`data/pipeline.py` module docstring):

given (seed, epoch, global example count), every host computes the SAME
global permutation and reads ONLY its own contiguous slice of each
global batch. The hand-written tests pin single configs; this module
sweeps randomized (n, batch_size, host_count, seed, epoch,
drop_remainder) and checks, against an independently-computed expected
permutation:

- cross-host exactness: host h's batch b is exactly
  ``order[b*G + h*B : ...]`` (no duplicates, no gaps, no overlap);
- batch-count arithmetic for drop/keep-remainder (and that multi-host
  FORCES dropping);
- bitwise run-to-run and cross-"process" reproducibility (each host's
  iterator is built independently, as real processes would);
- epoch keying: different epochs permute differently (n > 2).
"""

import random

import numpy as np
import pytest

from zookeeper_tpu.data.pipeline import batch_iterator
from zookeeper_tpu.data.source import ArraySource


def expected_order(seed, epoch, n):
    return np.random.default_rng(
        np.random.SeedSequence([seed, epoch])
    ).permutation(n)


@pytest.mark.parametrize("case_seed", range(30))
def test_multihost_batches_match_permutation_slices(case_seed):
    rng = random.Random(case_seed)
    n = rng.randrange(1, 65)
    batch_size = rng.randrange(1, 9)
    host_count = rng.choice((1, 1, 2, 3, 4))
    seed = rng.randrange(10_000)
    epoch = rng.randrange(5)
    drop_remainder = rng.random() < 0.5
    shuffle = rng.random() < 0.8

    source = ArraySource({"x": np.arange(n, dtype=np.int64)})

    # A train split smaller than one global batch (with effective
    # remainder-dropping) is rejected loudly — the zero-step-epoch
    # guard. Sampled configs landing there pin the REJECTION contract
    # instead of the slicing one.
    g = batch_size * host_count
    effective_drop = drop_remainder or host_count > 1
    if effective_drop and n < g:
        with pytest.raises(ValueError, match="zero batches"):
            list(
                batch_iterator(
                    source,
                    None,
                    batch_size,
                    training=True,
                    shuffle=shuffle,
                    seed=seed,
                    epoch=epoch,
                    drop_remainder=drop_remainder,
                    host_index=0,
                    host_count=host_count,
                )
            )
        return

    per_host = []
    for h in range(host_count):
        batches = list(
            batch_iterator(
                source,
                None,
                batch_size,
                training=True,
                shuffle=shuffle,
                seed=seed,
                epoch=epoch,
                drop_remainder=drop_remainder,
                host_index=h,
                host_count=host_count,
            )
        )
        per_host.append(batches)

    order = (
        expected_order(seed, epoch, n) if shuffle else np.arange(n)
    )
    # Multi-host FORCES drop_remainder (desync safety).
    expected_batches = n // g if effective_drop else -(-n // g)

    # Every counted batch has a non-empty slice on every host: dropping
    # is forced multi-host, and single-host keep-remainder's final
    # partial batch still starts below n.
    for h, batches in enumerate(per_host):
        assert len(batches) == expected_batches, (
            f"case={case_seed} host={h}"
        )
        for b, batch in enumerate(batches):
            start = b * g + h * batch_size
            stop = min(start + batch_size, n, (b + 1) * g)
            np.testing.assert_array_equal(
                batch["x"], order[start:stop], err_msg=f"case={case_seed} "
                f"host={h} batch={b}"
            )

    # Within every global batch: the hosts' slices are disjoint and
    # (when dropping) cover the full global batch exactly.
    for b in range(expected_batches):
        seen = np.concatenate(
            [
                per_host[h][b]["x"]
                for h in range(host_count)
                if b < len(per_host[h])
            ]
        )
        assert len(np.unique(seen)) == len(seen)
        if effective_drop:
            np.testing.assert_array_equal(
                np.sort(seen), np.sort(order[b * g : (b + 1) * g])
            )

    # Bitwise reproducibility: an independently-built iterator (a fresh
    # "process") yields identical batches.
    for h in (0, host_count - 1):
        rerun = list(
            batch_iterator(
                source,
                None,
                batch_size,
                training=True,
                shuffle=shuffle,
                seed=seed,
                epoch=epoch,
                drop_remainder=drop_remainder,
                host_index=h,
                host_count=host_count,
            )
        )
        assert len(rerun) == len(per_host[h])
        for a, c in zip(rerun, per_host[h]):
            np.testing.assert_array_equal(a["x"], c["x"])

    # Mid-epoch resume: start_batch=k yields exactly the [k:] suffix of
    # the full epoch, bitwise (the exact-resume contract). k is a valid
    # resume point, i.e. strictly inside the epoch (start_batch ==
    # num_batches is rejected — an epoch-boundary resume rolls into the
    # next epoch at step 0).
    if per_host[0]:
        k = rng.randrange(len(per_host[0]))
        suffix = list(
            batch_iterator(
                source,
                None,
                batch_size,
                training=True,
                shuffle=shuffle,
                seed=seed,
                epoch=epoch,
                drop_remainder=drop_remainder,
                host_index=0,
                host_count=host_count,
                start_batch=k,
            )
        )
        assert len(suffix) == len(per_host[0]) - k
        for a, c in zip(suffix, per_host[0][k:]):
            np.testing.assert_array_equal(a["x"], c["x"])

    # Epoch keying of the PIPELINE itself: the next epoch's batches,
    # concatenated, must differ from this epoch's (almost surely for
    # n > 2; skip degenerate sizes and batchless cases).
    if shuffle and n > 2 and per_host[0]:
        next_epoch = list(
            batch_iterator(
                source,
                None,
                batch_size,
                training=True,
                shuffle=shuffle,
                seed=seed,
                epoch=epoch + 1,
                drop_remainder=drop_remainder,
                host_index=0,
                host_count=host_count,
            )
        )
        flat = np.concatenate([b["x"] for b in per_host[0]])
        flat_next = np.concatenate([b["x"] for b in next_epoch])
        if len(flat) > 2:
            assert not np.array_equal(flat, flat_next), f"case={case_seed}"
