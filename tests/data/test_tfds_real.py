"""Integration tests against the REAL tensorflow_datasets library.

tfds is not installed in the build environment (no network), so the
adapters are contract-tested against a mock (test_tfds_mock.py). These
tests importorskip the real library: the day the environment gains tfds,
they activate and catch any drift between the mock's API surface and the
real ``tfds.data_source`` / ``tfds.builder`` (VERDICT round-2 weak #2).
"""

import numpy as np
import pytest

tfds = pytest.importorskip("tensorflow_datasets")

from zookeeper_tpu.core import configure  # noqa: E402
from zookeeper_tpu.data import TFDSDataset  # noqa: E402


@pytest.fixture
def mnist_dir(tmp_path):
    """Generate a tiny on-disk dataset with tfds' own testing harness, so
    the test exercises the REAL data_source stack without network."""
    mock = pytest.importorskip("tensorflow_datasets.testing")
    with mock.mock_data(num_examples=8, data_dir=str(tmp_path)):
        yield str(tmp_path)


def test_real_tfds_data_source_streams(mnist_dir):
    ds = TFDSDataset()
    configure(
        ds,
        {"name": "mnist", "data_dir": mnist_dir, "validation_split": "test"},
        name="ds",
    )
    train = ds.train()
    # Random access protocol: len + integer indexing of dict examples.
    assert len(train) > 0
    ex = train[0]
    assert isinstance(ex, dict) and "image" in ex
    assert np.asarray(ex["image"]).ndim == 3
    # Builder-metadata class count (real FeaturesDict surface).
    assert ds.resolved_num_classes() == 10


def test_real_tfds_decoders_passthrough(mnist_dir):
    ds = TFDSDataset()
    configure(ds, {"name": "mnist", "data_dir": mnist_dir}, name="ds")
    # SkipDecoding must be accepted by the real tfds.data_source kwarg.
    src = ds.load("train", decoders={"image": tfds.decode.SkipDecoding()})
    assert len(src) > 0
