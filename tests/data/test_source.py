import numpy as np
import pytest

from zookeeper_tpu.data import ArraySource, ConcatSource


def make_source(n=10, offset=0):
    return ArraySource(
        {
            "x": np.arange(offset, offset + n, dtype=np.float32),
            "y": np.arange(offset, offset + n, dtype=np.int32) * 2,
        }
    )


def test_array_source_basics():
    s = make_source(10)
    assert len(s) == 10
    ex = s[3]
    assert ex["x"] == 3.0 and ex["y"] == 6
    assert s[-1]["x"] == 9.0
    with pytest.raises(IndexError):
        s[10]


def test_array_source_unequal_lengths():
    with pytest.raises(ValueError):
        ArraySource({"a": np.zeros(3), "b": np.zeros(4)})


def test_map_and_iter():
    s = make_source(5).map(lambda e: {"x": e["x"] + 1, "y": e["y"]})
    assert [e["x"] for e in s] == [1, 2, 3, 4, 5]


def test_slice_and_negative_index():
    s = make_source(10).slice(2, 6)
    assert len(s) == 4
    assert s[0]["x"] == 2.0
    assert s[-1]["x"] == 5.0
    with pytest.raises(IndexError):
        s[4]


def test_shard_partitions_exactly():
    s = make_source(10)
    shards = [s.shard(i, 3) for i in range(3)]
    seen = [e["x"] for sh in shards for e in sh]
    assert sorted(seen) == list(range(10))
    with pytest.raises(ValueError):
        s.shard(3, 3)


def test_concat_source():
    c = ConcatSource([make_source(3, 0), make_source(4, 100)])
    assert len(c) == 7
    assert c[0]["x"] == 0.0
    assert c[2]["x"] == 2.0
    assert c[3]["x"] == 100.0
    assert c[-1]["x"] == 103.0
    with pytest.raises(IndexError):
        c[7]
