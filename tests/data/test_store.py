"""Streaming disk-backed store: writer/reader round-trip, scale behavior,
dataset integration, and the grain-protocol adapter."""

import json
import os
import sys
import types

import numpy as np
import pytest

from zookeeper_tpu.core import configure
from zookeeper_tpu.data import (
    ArrayDataset,
    MemmapDataset,
    MemmapSource,
    MemmapWriter,
    TFDSDataset,
    WrappedSource,
    wrap_source,
    write_store,
)
from zookeeper_tpu.data.source import DataSource


def _write_split(directory, n, *, h=8, w=8, c=1, num_classes=5, chunk=64, seed=0):
    """Stream a synthetic split to disk chunk-by-chunk (never materializes
    the whole split in memory)."""
    rng = np.random.default_rng(seed)
    with MemmapWriter(directory) as writer:
        done = 0
        while done < n:
            m = min(chunk, n - done)
            writer.append(
                {
                    "image": rng.integers(0, 255, (m, h, w, c), dtype=np.uint8),
                    "label": rng.integers(0, num_classes, (m,), dtype=np.int32),
                }
            )
            done += m


def test_writer_reader_round_trip(tmp_path):
    d = str(tmp_path / "store")
    rng = np.random.default_rng(1)
    images = rng.integers(0, 255, (40, 4, 4, 3), dtype=np.uint8)
    labels = rng.integers(0, 10, (40,), dtype=np.int64)
    with MemmapWriter(d) as w:
        w.append({"image": images[:15], "label": labels[:15]})
        w.append({"image": images[15:], "label": labels[15:]})
    src = MemmapSource(d)
    assert len(src) == 40
    for i in (0, 14, 15, 39, -1):
        ex = src[i]
        np.testing.assert_array_equal(ex["image"], images[i])
        assert ex["label"] == labels[i]
    # Examples are copies, not memmap views.
    assert type(src[0]["image"]) is np.ndarray


def test_writer_rejects_inconsistent_chunks(tmp_path):
    w = MemmapWriter(str(tmp_path / "s"))
    w.append({"x": np.zeros((2, 3), np.float32)})
    with pytest.raises(ValueError, match="features"):
        w.append({"y": np.zeros((2, 3), np.float32)})
    with pytest.raises(ValueError, match="store is"):
        w.append({"x": np.zeros((2, 4), np.float32)})
    # unequal lengths across features
    w2 = MemmapWriter(str(tmp_path / "s2"))
    with pytest.raises(ValueError, match="unequal"):
        w2.append(
            {"a": np.zeros((2, 1), np.float32), "b": np.zeros((3,), np.int32)}
        )


def test_reader_requires_closed_store(tmp_path):
    d = str(tmp_path / "unclosed")
    w = MemmapWriter(d)
    w.append({"x": np.zeros((2, 3), np.float32)})
    with pytest.raises(FileNotFoundError, match="meta"):
        MemmapSource(d)  # no meta.json until close()
    w.close()
    assert len(MemmapSource(d)) == 2


def test_reader_detects_truncated_file(tmp_path):
    d = str(tmp_path / "trunc")
    write_store(d, {"x": np.arange(64, dtype=np.float32).reshape(8, 8)})
    with open(os.path.join(d, "x.bin"), "r+b") as f:
        f.truncate(100)
    with pytest.raises(ValueError, match="bytes"):
        MemmapSource(d)


def test_store_streams_without_full_materialization(tmp_path):
    """A store 10x bigger than any single chunk round-trips by random
    access; only touched pages are read."""
    d = str(tmp_path / "big")
    _write_split(d, 2560, chunk=128)  # 20 chunks
    src = MemmapSource(d)
    assert len(src) == 2560
    # Spot-check determinism against a fresh regeneration of chunk 0.
    rng = np.random.default_rng(0)
    first_images = rng.integers(0, 255, (128, 8, 8, 1), dtype=np.uint8)
    np.testing.assert_array_equal(src[17]["image"], first_images[17])


def test_memmap_dataset_trains_end_to_end(tmp_path):
    """The VERDICT round-1 acceptance: a disk-backed dataset with many
    batches drives the full TrainingExperiment loop (loss finite, steps
    taken), with num_classes inferred from the label file."""
    from zookeeper_tpu.training import TrainingExperiment

    root = str(tmp_path / "ds")
    _write_split(os.path.join(root, "train"), 640, num_classes=5, seed=0)
    _write_split(os.path.join(root, "validation"), 128, num_classes=5, seed=1)

    exp = TrainingExperiment()
    configure(
        exp,
        {
            "loader.dataset": "MemmapDataset",
            "loader.dataset.directory": root,
            "loader.preprocessing": "ImageClassificationPreprocessing",
            "loader.preprocessing.height": 8,
            "loader.preprocessing.width": 8,
            "loader.preprocessing.channels": 1,
            "loader.host_index": 0,
            "loader.host_count": 1,
            "model": "Mlp",
            "model.hidden_units": (16,),
            "batch_size": 64,
            "epochs": 1,
            "verbose": False,
        },
        name="experiment",
    )
    assert exp.num_classes == 5  # inferred by label scan
    history = exp.run()
    assert len(history["train"]) == 1
    assert np.isfinite(history["train"][0]["loss"])
    assert len(history["validation"]) == 1


def test_array_dataset_infers_num_classes():
    ds = ArrayDataset()
    configure(ds, {}, name="dataset")
    ds.with_data(
        {
            "image": np.zeros((10, 2, 2, 1), np.uint8),
            "label": np.array([0, 1, 2, 3, 3, 2, 1, 0, 3, 2], np.int64),
        }
    )
    assert ds.resolved_num_classes() == 4


def test_memmap_dataset_explicit_num_classes_wins(tmp_path):
    root = str(tmp_path / "ds")
    _write_split(os.path.join(root, "train"), 64, num_classes=3)
    ds = MemmapDataset()
    configure(ds, {"directory": root, "num_classes": 11}, name="dataset")
    assert ds.resolved_num_classes() == 11


def test_wrap_source_adapts_grain_protocol():
    """Anything with __len__/__getitem__ (grain's RandomAccessDataSource
    protocol) plugs into the pipeline."""

    class FakeGrainSource:  # deliberately NOT a DataSource subclass
        def __len__(self):
            return 4

        def __getitem__(self, i):
            return {"image": np.full((2, 2), i), "label": i % 2}

    src = wrap_source(FakeGrainSource())
    assert isinstance(src, WrappedSource)
    assert len(src) == 4
    np.testing.assert_array_equal(src[2]["image"], np.full((2, 2), 2))
    # Non-dict values land under feature_name.
    class Scalars:
        def __len__(self):
            return 3

        def __getitem__(self, i):
            return np.float32(i)

    s2 = wrap_source(Scalars(), feature_name="x")
    assert s2[1]["x"] == 1.0
    # Pass-through for existing DataSources.
    assert wrap_source(src) is src


# -- TFDS path (mocked: tfds is not installed in this environment) ----------


class _FakeTfdsArraySource:
    """Mimics tfds.data_source(): random access, decode on demand."""

    def __init__(self, n, num_classes):
        self.n = n
        self.num_classes = num_classes
        self.accesses = []

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        self.accesses.append(i)
        rng = np.random.default_rng(i)
        return {
            "image": rng.integers(0, 255, (8, 8, 1), dtype=np.uint8),
            "label": np.int64(i % self.num_classes),
        }


def _install_fake_tfds(monkeypatch, n=256, num_classes=5):
    sources = {}

    def data_source(name, split, data_dir=None):
        key = (name, split)
        if key not in sources:
            sources[key] = _FakeTfdsArraySource(n, num_classes)
        return sources[key]

    class _Label:
        pass

    label = _Label()
    label.num_classes = num_classes

    class _Info:
        features = {"label": label}
        splits = {
            "train": types.SimpleNamespace(num_examples=n),
            "validation": types.SimpleNamespace(num_examples=n // 4),
        }

    def builder(name, data_dir=None):
        return types.SimpleNamespace(info=_Info())

    fake = types.ModuleType("tensorflow_datasets")
    fake.data_source = data_source
    fake.builder = builder
    monkeypatch.setitem(sys.modules, "tensorflow_datasets", fake)
    return sources


def test_tfds_dataset_streams_and_reaches_train_loop(monkeypatch, tmp_path):
    """TFDSDataset configured end-to-end: never materializes the split
    (access pattern stays per-example) and drives the training loop."""
    from zookeeper_tpu.training import TrainingExperiment

    sources = _install_fake_tfds(monkeypatch, n=256, num_classes=5)
    exp = TrainingExperiment()
    configure(
        exp,
        {
            "loader.dataset": "TFDSDataset",
            "loader.dataset.name": "fake_ds",
            "loader.preprocessing": "ImageClassificationPreprocessing",
            "loader.preprocessing.height": 8,
            "loader.preprocessing.width": 8,
            "loader.preprocessing.channels": 1,
            "loader.host_index": 0,
            "loader.host_count": 1,
            "model": "Mlp",
            "model.hidden_units": (16,),
            "batch_size": 32,
            "epochs": 1,
            "validate": False,
            "verbose": False,
        },
        name="experiment",
    )
    assert exp.num_classes == 5  # from builder metadata, not a label scan
    history = exp.run()
    assert len(history["train"]) == 1
    assert np.isfinite(history["train"][0]["loss"])
    src = sources[("fake_ds", "train")]
    # Streaming contract: each example fetched on demand, exactly once.
    assert len(src.accesses) == 256
    assert sorted(src.accesses) == list(range(256))


def test_tfds_num_examples_from_builder(monkeypatch):
    _install_fake_tfds(monkeypatch, n=256)
    ds = TFDSDataset()
    configure(
        ds,
        {"name": "fake_ds", "validation_split": "validation"},
        name="dataset",
    )
    assert ds.num_examples("train") == 256
    assert ds.num_examples("validation") == 64


def test_tfds_missing_import_error_is_actionable(monkeypatch):
    monkeypatch.setitem(sys.modules, "tensorflow_datasets", None)
    ds = TFDSDataset()
    configure(ds, {"name": "mnist"}, name="dataset")
    with pytest.raises(ImportError, match="MemmapDataset"):
        ds.train()


def test_meta_json_is_human_readable(tmp_path):
    d = str(tmp_path / "s")
    write_store(d, {"x": np.zeros((3, 2), np.float32)})
    with open(os.path.join(d, "meta.json")) as f:
        meta = json.load(f)
    assert meta["num_examples"] == 3
    assert meta["features"]["x"] == {"dtype": "float32", "shape": [2]}
