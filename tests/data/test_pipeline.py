import numpy as np
import pytest

from zookeeper_tpu.core import configure
from zookeeper_tpu.data import (
    ArraySource,
    DataLoader,
    SyntheticMnist,
    ImageClassificationPreprocessing,
    PassThroughPreprocessing,
    TokenPreprocessing,
    batch_iterator,
    prefetch_to_device,
    slab_iterator,
)


def make_source(n=32):
    return ArraySource(
        {
            "image": np.arange(n, dtype=np.float32)[:, None, None, None]
            * np.ones((1, 4, 4, 1), np.float32),
            "label": np.arange(n, dtype=np.int32) % 10,
        }
    )


def collect_inputs(batches):
    return np.concatenate([b["input"][:, 0, 0, 0] for b in batches])


def test_batch_shapes_and_drop_remainder():
    pre = PassThroughPreprocessing()
    configure(pre, {"input_key": "image", "target_key": "label"}, name="pre")
    batches = list(
        batch_iterator(make_source(30), pre, 8, training=False, shuffle=False)
    )
    assert len(batches) == 3  # 30 // 8, remainder dropped
    assert batches[0]["input"].shape == (8, 4, 4, 1)
    assert batches[0]["target"].shape == (8,)
    batches = list(
        batch_iterator(
            make_source(30), pre, 8, training=False, shuffle=False,
            drop_remainder=False,
        )
    )
    assert len(batches) == 4
    assert batches[-1]["input"].shape[0] == 6


def test_shuffle_deterministic_per_epoch():
    pre = PassThroughPreprocessing()
    configure(pre, {}, name="pre")
    kw = dict(training=True, shuffle=True, seed=7)
    a = collect_inputs(batch_iterator(make_source(), pre, 8, epoch=0, **kw))
    b = collect_inputs(batch_iterator(make_source(), pre, 8, epoch=0, **kw))
    c = collect_inputs(batch_iterator(make_source(), pre, 8, epoch=1, **kw))
    np.testing.assert_array_equal(a, b)
    assert not np.array_equal(a, c)
    assert sorted(a) == sorted(c)  # same examples, different order


def test_host_sharding_partitions_global_batch():
    pre = PassThroughPreprocessing()
    configure(pre, {}, name="pre")
    kw = dict(training=True, shuffle=True, seed=3, epoch=0)
    # 2 hosts, per-host batch 4 => global batch 8 over 32 examples.
    h0 = list(batch_iterator(make_source(), pre, 4, host_index=0, host_count=2, **kw))
    h1 = list(batch_iterator(make_source(), pre, 4, host_index=1, host_count=2, **kw))
    assert len(h0) == len(h1) == 4
    merged = np.concatenate(
        [np.concatenate([a["input"], b["input"]]) for a, b in zip(h0, h1)]
    )[:, 0, 0, 0]
    single = collect_inputs(batch_iterator(make_source(), pre, 8, **kw))
    np.testing.assert_array_equal(np.sort(merged), np.sort(single))
    # Same global order: each global batch has the same example set.
    for a, b, idx in zip(h0, h1, range(4)):
        got = set(np.concatenate([a["input"], b["input"]])[:, 0, 0, 0])
        want = set(single[idx * 8 : (idx + 1) * 8])
        assert got == want


def test_num_workers_matches_serial():
    pre = PassThroughPreprocessing()
    configure(pre, {}, name="pre")
    kw = dict(training=True, shuffle=True, seed=5)
    serial = collect_inputs(batch_iterator(make_source(), pre, 8, **kw))
    threaded = collect_inputs(
        batch_iterator(make_source(), pre, 8, num_workers=4, **kw)
    )
    np.testing.assert_array_equal(serial, threaded)


def test_preprocessing_scaling_and_augment_determinism():
    pre = ImageClassificationPreprocessing()
    configure(
        pre,
        {"height": 4, "width": 4, "channels": 1, "augment": True, "pad_pixels": 1},
        name="pre",
    )
    src = ArraySource(
        {
            "image": (np.arange(16, dtype=np.uint8).reshape(1, 4, 4, 1))
            * np.ones((8, 1, 1, 1), np.uint8),
            "label": np.zeros(8, np.int64),
        }
    )
    out1 = list(batch_iterator(src, pre, 4, training=True, shuffle=False))
    out2 = list(batch_iterator(src, pre, 4, training=True, shuffle=False))
    np.testing.assert_array_equal(out1[0]["input"], out2[0]["input"])
    assert out1[0]["input"].min() >= -1.0 and out1[0]["input"].max() <= 1.0
    assert out1[0]["target"].dtype == np.int32


def test_augmentation_varies_per_epoch():
    """Same example must get a DIFFERENT (but deterministic) augmentation
    each epoch — seeding from index alone would repeat the identical crop
    every epoch and silently shrink augmentation diversity."""
    pre = ImageClassificationPreprocessing()
    configure(
        pre,
        {"height": 6, "width": 6, "channels": 1, "augment": True, "pad_pixels": 2},
        name="pre",
    )
    rng = np.random.default_rng(3)
    src = ArraySource(
        {
            "image": rng.integers(0, 255, (8, 6, 6, 1), dtype=np.uint8),
            "label": np.zeros(8, np.int64),
        }
    )

    def epoch_inputs(epoch):
        return np.concatenate(
            [
                b["input"]
                for b in batch_iterator(
                    src, pre, 4, training=True, shuffle=False, epoch=epoch
                )
            ]
        )

    e0, e0_again, e1 = epoch_inputs(0), epoch_inputs(0), epoch_inputs(1)
    np.testing.assert_array_equal(e0, e0_again)  # deterministic per epoch
    assert not np.array_equal(e0, e1)  # varies across epochs


def test_prefetch_to_device_yields_device_arrays():
    import jax

    pre = PassThroughPreprocessing()
    configure(pre, {}, name="pre")
    it = batch_iterator(make_source(16), pre, 4, training=False, shuffle=False)
    out = list(prefetch_to_device(it, size=2))
    assert len(out) == 4
    assert isinstance(out[0]["input"], jax.Array)
    np.testing.assert_allclose(
        np.asarray(out[0]["input"])[:, 0, 0, 0], [0, 1, 2, 3]
    )


def test_prefetch_propagates_errors():
    def bad_iter():
        yield {"x": np.zeros(1)}
        raise RuntimeError("boom")

    with pytest.raises(RuntimeError, match="boom"):
        list(prefetch_to_device(bad_iter(), size=1))


def test_dataloader_end_to_end():
    loader = DataLoader()
    configure(
        loader,
        {
            "dataset": "SyntheticMnist",
            "dataset.num_train_examples": 64,
            "preprocessing": "ImageClassificationPreprocessing",
            "preprocessing.height": 28,
            "preprocessing.width": 28,
            "preprocessing.channels": 1,
            "batch_size": 16,
            "host_index": 0,
            "host_count": 1,
            "prefetch": 0,
        },
        name="loader",
    )
    assert isinstance(loader.dataset, SyntheticMnist)
    assert loader.steps_per_epoch("train") == 4
    batches = list(loader.batches("train", epoch=0))
    assert len(batches) == 4
    assert batches[0]["input"].shape == (16, 28, 28, 1)
    assert batches[0]["target"].shape == (16,)


def test_dataloader_batch_size_divisibility():
    loader = DataLoader()
    configure(
        loader,
        {
            "dataset": "SyntheticMnist",
            "preprocessing": "PassThroughPreprocessing",
            "batch_size": 5,
            "host_index": 0,
            "host_count": 2,
        },
        name="loader",
    )
    with pytest.raises(ValueError, match="not divisible"):
        loader.per_host_batch_size


def test_prefetch_early_stop_terminates_producer():
    import threading
    import time

    pre = PassThroughPreprocessing()
    configure(pre, {}, name="pre")

    def run_once():
        it = batch_iterator(
            make_source(32), pre, 4, training=False, shuffle=False
        )
        gen = prefetch_to_device(it, size=1)
        next(gen)
        gen.close()  # Early stop: consumer abandons mid-iteration.

    before = threading.active_count()
    for _ in range(5):
        run_once()
    deadline = time.time() + 5.0
    while threading.active_count() > before and time.time() < deadline:
        time.sleep(0.05)
    # Producer threads must terminate, not accumulate.
    assert threading.active_count() <= before + 1


def test_multihost_forces_drop_remainder():
    pre = PassThroughPreprocessing()
    configure(pre, {}, name="pre")
    # 10 examples, global batch 8, drop_remainder=False requested: both
    # hosts must still agree on the batch count (partial batch dropped).
    kw = dict(
        training=False, shuffle=False, drop_remainder=False, host_count=2
    )
    src = make_source(10)
    h0 = list(batch_iterator(src, pre, 4, host_index=0, **kw))
    h1 = list(batch_iterator(src, pre, 4, host_index=1, **kw))
    assert len(h0) == len(h1) == 1
    assert h0[0]["input"].shape[0] == h1[0]["input"].shape[0] == 4


def test_native_fast_path_matches_per_example_path():
    rng = np.random.default_rng(9)
    src = ArraySource(
        {
            "image": rng.integers(0, 256, size=(32, 8, 8, 3), dtype=np.uint8),
            "label": rng.integers(0, 10, size=(32,)).astype(np.int64),
        }
    )
    pre = ImageClassificationPreprocessing()
    configure(pre, {"height": 8, "width": 8, "channels": 3}, name="pre")
    assert pre.native_batch_spec(training=False) is not None
    kw = dict(training=False, shuffle=True, seed=11)
    fast = list(batch_iterator(src, pre, 8, **kw))
    # Force the per-example path by hiding the spec.
    slow_pre = ImageClassificationPreprocessing()
    configure(slow_pre, {"height": 8, "width": 8, "channels": 3}, name="p2")
    object.__setattr__(slow_pre, "native_batch_spec", lambda training: None)
    slow = list(batch_iterator(src, slow_pre, 8, **kw))
    assert len(fast) == len(slow) == 4
    for a, b in zip(fast, slow):
        # Affine order differs ((x/255)*2-1 vs x*(2/255)-1): fp32 rounding.
        np.testing.assert_allclose(a["input"], b["input"], atol=1e-4)
        np.testing.assert_array_equal(a["target"], b["target"])
        assert a["input"].dtype == np.float32
        assert a["target"].dtype == np.int32


def test_native_batch_spec_modes():
    """Training-with-augmentation now has its OWN fused-kernel mode (the
    path every real ImageNet-recipe run takes — previously a silent
    fallback to per-example Python); eval stays on the plain
    gather+normalize spec."""
    pre = ImageClassificationPreprocessing()
    configure(pre, {"augment": True, "pad_pixels": 4}, name="pre")
    train_spec = pre.native_batch_spec(training=True)
    assert train_spec["mode"] == "augment"
    assert train_spec["pad_pixels"] == 4
    assert not train_spec["random_resized_crop"]
    eval_spec = pre.native_batch_spec(training=False)
    assert eval_spec["mode"] == "normalize"
    # RRC recipe carries its (validated) ranges, log-space aspect.
    import math

    pre2 = ImageClassificationPreprocessing()
    configure(
        pre2,
        {"augment": True, "random_resized_crop": True,
         "crop_aspect_range": (0.5, 2.0)},
        name="pre2",
    )
    spec2 = pre2.native_batch_spec(training=True)
    assert spec2["random_resized_crop"]
    assert spec2["log_aspect_range"] == (math.log(0.5), math.log(2.0))
    # Invalid ranges fail fast at spec time (the native path never runs
    # the per-example Python validation).
    pre3 = ImageClassificationPreprocessing()
    configure(
        pre3,
        {"augment": True, "random_resized_crop": True,
         "crop_scale_range": (0.0, 1.0)},
        name="pre3",
    )
    with pytest.raises(ValueError, match="RandomResizedCrop ranges"):
        pre3.native_batch_spec(training=True)


def test_preprocessing_resize_nearest():
    import numpy as np

    from zookeeper_tpu.core import configure
    from zookeeper_tpu.data import ImageClassificationPreprocessing

    p = ImageClassificationPreprocessing()
    configure(
        p,
        {"height": 16, "width": 16, "channels": 1, "resize": True,
         "zero_center": False},
        name="p",
    )
    src = np.arange(64, dtype=np.uint8).reshape(8, 8)
    out = p.input({"image": src}, training=False)
    assert out.shape == (16, 16, 1)
    # Exact 2x upsample: each source pixel appears as a 2x2 block.
    expected = np.repeat(np.repeat(src, 2, axis=0), 2, axis=1) / 255.0
    np.testing.assert_allclose(out[..., 0], expected, rtol=1e-6)

    # Downsample path too (16 -> 8 picks every other pixel).
    p2 = ImageClassificationPreprocessing()
    configure(
        p2,
        {"height": 4, "width": 4, "channels": 1, "resize": True,
         "zero_center": False},
        name="p2",
    )
    out2 = p2.input({"image": src}, training=False)
    np.testing.assert_allclose(out2[..., 0], src[::2, ::2] / 255.0, rtol=1e-6)


def test_random_resized_crop_shape_determinism_and_epoch_variation():
    """Inception-style RandomResizedCrop: output is always (height, width),
    the same (index, epoch) seed reproduces the same crop (resumability),
    and different epochs produce different crops (augmentation variety)."""
    from zookeeper_tpu.core import configure
    from zookeeper_tpu.data import ImageClassificationPreprocessing

    pp = ImageClassificationPreprocessing()
    configure(
        pp,
        {
            "height": 16,
            "width": 16,
            "channels": 3,
            "augment": True,
            "random_resized_crop": True,
            "random_flip": False,
        },
        name="pp",
    )
    rng = np.random.default_rng(0)
    image = rng.integers(0, 255, (48, 64, 3)).astype(np.uint8)

    def run(index, epoch):
        ex = {
            "image": image,
            "label": np.int32(0),
            "_index": np.int64(index),
            "_epoch": np.int64(epoch),
        }
        return pp(ex, training=True)["input"]

    a = run(3, 0)
    assert a.shape == (16, 16, 3)
    np.testing.assert_array_equal(a, run(3, 0))  # deterministic
    assert not np.array_equal(a, run(3, 1))  # varies per epoch
    assert not np.array_equal(a, run(4, 0))  # varies per example
    # Bilinear taps are convex combinations of source pixels: output
    # stays inside the source's value range after the affine rescale.
    src = (image.astype(np.float32) / 255.0) * 2 - 1
    assert a.min() >= src.min() - 1e-6 and a.max() <= src.max() + 1e-6


def test_random_resized_crop_eval_path_unaffected():
    from zookeeper_tpu.core import configure
    from zookeeper_tpu.data import ImageClassificationPreprocessing

    pp = ImageClassificationPreprocessing()
    configure(
        pp,
        {
            "height": 8,
            "width": 8,
            "channels": 1,
            "augment": True,
            "random_resized_crop": True,
        },
        name="pp",
    )
    img = np.zeros((12, 12, 1), np.uint8)
    out = pp({"image": img, "label": np.int32(1)}, training=False)
    # Eval ignores augmentation entirely: center crop to (8, 8).
    assert out["input"].shape == (8, 8, 1)


def test_random_resized_crop_invalid_ranges_fail_fast():
    from zookeeper_tpu.core import configure
    from zookeeper_tpu.data import ImageClassificationPreprocessing

    pp = ImageClassificationPreprocessing()
    configure(
        pp,
        {
            "height": 8, "width": 8, "augment": True,
            "random_resized_crop": True,
            "crop_aspect_range": (0.0, 1.33),
        },
        name="pp",
    )
    ex = {"image": np.zeros((16, 16, 3), np.uint8), "label": np.int32(0)}
    with pytest.raises(ValueError, match="RandomResizedCrop ranges"):
        pp(ex, training=True)


def test_random_resized_crop_skips_pre_resize():
    """resize=True + RRC must crop from the FULL-res source, not a
    pre-shrunk one: a crop from a 64x64 source with scale pinned to a
    quarter of the area can only contain pixels from a 32x32 region —
    impossible if the source had first been resized to 16x16."""
    from zookeeper_tpu.core import configure
    from zookeeper_tpu.data import ImageClassificationPreprocessing

    pp = ImageClassificationPreprocessing()
    configure(
        pp,
        {
            "height": 16, "width": 16, "channels": 1, "resize": True,
            "augment": True, "random_resized_crop": True,
            "random_flip": False, "zero_center": False,
            "crop_scale_range": (0.25, 0.25),
            "crop_aspect_range": (1.0, 1.0),
        },
        name="pp",
    )
    # Source: a 64x64 gradient with 64 distinct row values. A 32x32 crop
    # resized to 16 rows keeps ADJACENT-ROW spacing of 2 (nearest,
    # stride 2); a pre-resize to 16 rows first would sample rows 4 apart.
    img = np.tile(np.arange(64, dtype=np.uint8)[:, None, None], (1, 64, 1))
    ex = {
        "image": img, "label": np.int32(0),
        "_index": np.int64(0), "_epoch": np.int64(0),
    }
    out = pp(ex, training=True)["input"]
    rows = np.unique((out * 255.0).round().astype(np.int64)[..., 0], axis=1)
    row_vals = rows[:, 0]
    steps = np.diff(row_vals)
    assert out.shape == (16, 16, 1)
    # Full-res 32-row crop -> stride-2 row sampling.
    assert set(np.unique(steps)) == {2}


def test_native_fast_path_hits_memmap_store(tmp_path, monkeypatch):
    """The disk-backed (>= RAM) store rides the SAME fused C++ batch
    assembly as the in-RAM source (VERDICT round-2 #3: the native path
    used to be gated on ArraySource, leaving MemmapSource — the path
    ImageNet-scale training actually uses — on per-example Python)."""
    from zookeeper_tpu import native
    from zookeeper_tpu.data.store import MemmapSource, MemmapWriter

    rng = np.random.default_rng(21)
    images = rng.integers(0, 256, size=(48, 8, 8, 3), dtype=np.uint8)
    labels = rng.integers(0, 10, size=(48,)).astype(np.int64)
    with MemmapWriter(str(tmp_path / "store")) as w:
        w.append({"image": images[:30], "label": labels[:30]})
        w.append({"image": images[30:], "label": labels[30:]})
    src = MemmapSource(str(tmp_path / "store"))

    pre = ImageClassificationPreprocessing()
    configure(pre, {"height": 8, "width": 8, "channels": 3}, name="pre")

    calls = []
    real = native.gather_normalize
    monkeypatch.setattr(
        native, "gather_normalize",
        lambda *a, **k: (calls.append(1), real(*a, **k))[1],
    )
    kw = dict(training=False, shuffle=True, seed=5)
    fast = list(batch_iterator(src, pre, 16, **kw))
    assert len(calls) == 3, "native fused assembly was not hit for Memmap"

    # Bit-identical to the in-RAM ArraySource native path (same kernel,
    # same order): the store IS the arrays, just memory-mapped.
    ram = list(
        batch_iterator(
            ArraySource({"image": images, "label": labels}), pre, 16, **kw
        )
    )
    assert len(fast) == len(ram) == 3
    for a, b in zip(fast, ram):
        np.testing.assert_array_equal(a["input"], b["input"])
        np.testing.assert_array_equal(a["target"], b["target"])


def test_slab_iterator_preserves_order_partial_and_cap():
    """Slabs are consecutive batches stacked on a new leading axis:
    order unchanged, final slab partial when the epoch length is not a
    multiple of unroll, and max_batches truncates mid-slab."""
    pre = PassThroughPreprocessing()
    configure(pre, {"input_key": "image", "target_key": "label"}, name="pre")

    def batches():
        return batch_iterator(
            make_source(32), pre, 4, training=False, shuffle=False
        )

    flat = collect_inputs(batches())
    slabs = list(slab_iterator(batches(), 3))
    # 8 batches at unroll 3 -> slabs of 3, 3, 2.
    assert [s["input"].shape[0] for s in slabs] == [3, 3, 2]
    assert slabs[0]["input"].shape == (3, 4, 4, 4, 1)
    restacked = np.concatenate(
        [s["input"].reshape(-1, 4, 4, 1) for s in slabs]
    )[:, 0, 0, 0]
    np.testing.assert_array_equal(restacked, flat)

    # max_batches mid-slab: 5 batches at unroll 4 -> 4 + 1.
    capped = list(slab_iterator(batches(), 4, max_batches=5))
    assert [s["input"].shape[0] for s in capped] == [4, 1]
    np.testing.assert_array_equal(
        np.concatenate([s["input"].reshape(-1, 4, 4, 1) for s in capped])[
            :, 0, 0, 0
        ],
        flat[:20],
    )

    # unroll=1 slabs are [1, batch, ...] (degenerate but well-formed).
    ones = list(slab_iterator(batches(), 1, max_batches=2))
    assert [s["input"].shape[:2] for s in ones] == [(1, 4), (1, 4)]

    # max_batches=0 yields NOTHING (matching islice semantics on the
    # unroll=1 loader surface), not a one-batch slab.
    assert list(slab_iterator(batches(), 4, max_batches=0)) == []

    with pytest.raises(ValueError, match="unroll"):
        list(slab_iterator(batches(), 0))


def test_slab_iterator_rejects_shape_changing_batches():
    """A partial FINAL BATCH (drop_remainder=False) cannot be stacked
    into a slab — fail loudly instead of mis-stacking, INCLUDING when
    slab alignment puts the partial batch alone in the last slab
    (where a per-slab check would see uniform shapes and silently
    emit a shape-changing slab)."""
    pre = PassThroughPreprocessing()
    configure(pre, {"input_key": "image", "target_key": "label"}, name="pre")

    def batches(n):
        return batch_iterator(
            make_source(n), pre, 8, training=False, shuffle=False,
            drop_remainder=False,
        )

    # 30 examples: batches 8,8,8,6 — partial shares slab 1 of 4.
    with pytest.raises(ValueError, match="slab"):
        list(slab_iterator(batches(30), 4))
    # 36 examples: batches 8,8,8,8,4 — partial is ALONE in slab 2.
    with pytest.raises(ValueError, match="slab"):
        list(slab_iterator(batches(36), 4))


def test_dataloader_unroll_yields_device_slabs():
    """DataLoader.batches(unroll=k) stages [k, batch, ...] device slabs
    equal to the same call's consecutive single batches stacked."""
    import jax

    conf = {
        "dataset": "SyntheticMnist",
        "dataset.num_train_examples": 64,
        "preprocessing": "ImageClassificationPreprocessing",
        "preprocessing.height": 28,
        "preprocessing.width": 28,
        "preprocessing.channels": 1,
        "batch_size": 16,
        "host_index": 0,
        "host_count": 1,
    }
    loader = DataLoader()
    configure(loader, conf, name="loader")
    singles = list(loader.batches("train", epoch=0))
    loader2 = DataLoader()
    configure(loader2, conf, name="loader2")
    slabs = list(loader2.batches("train", epoch=0, unroll=2))
    assert len(singles) == 4 and len(slabs) == 2
    assert isinstance(slabs[0]["input"], jax.Array)
    assert slabs[0]["input"].shape == (2, 16, 28, 28, 1)
    for i, slab in enumerate(slabs):
        for j in range(2):
            np.testing.assert_array_equal(
                np.asarray(slab["input"][j]),
                np.asarray(singles[2 * i + j]["input"]),
            )
            np.testing.assert_array_equal(
                np.asarray(slab["target"][j]),
                np.asarray(singles[2 * i + j]["target"]),
            )

    # max_batches caps the eager (unroll=1) surface too.
    loader3 = DataLoader()
    configure(loader3, conf, name="loader3")
    assert len(list(loader3.batches("train", epoch=0, max_batches=3))) == 3


def test_preprocessing_input_dtype_hints():
    """The data layer's dtype hint for dummy-input consumers
    (models.summary): tokens are int32, pixels float32, passthrough
    unknown."""
    assert TokenPreprocessing().input_dtype == "int32"
    img = ImageClassificationPreprocessing()
    assert img.input_dtype == "float32"
    assert PassThroughPreprocessing().input_dtype is None


def test_start_batch_out_of_range_fails_loudly():
    """A miscomputed resume point must raise, not silently train zero
    steps: negative start_batch, start_batch at the epoch end, and
    start_batch beyond it are all rejected (a legitimate epoch-boundary
    resume rolls into the next epoch at step 0). Validation happens at
    first iteration (batch_iterator is a generator)."""
    src = make_source(32)  # 4 batches of 8
    kw = dict(training=True, shuffle=True, seed=0)

    # Valid interior resume points still work.
    assert len(list(batch_iterator(src, None, 8, **kw, start_batch=3))) == 1

    for bad in (-1, 4, 5):
        with pytest.raises(ValueError, match="start_batch"):
            list(batch_iterator(src, None, 8, **kw, start_batch=bad))

    # Through the DataLoader surface too (the path Experiment uses).
    loader = DataLoader()
    configure(
        loader,
        {
            "dataset": "SyntheticMnist",
            "dataset.num_train_examples": 32,
            "preprocessing": "PassThroughPreprocessing",
            "batch_size": 8,
        },
        name="loader",
    )
    with pytest.raises(ValueError, match="start_batch"):
        list(loader.batches("train", epoch=0, start_batch=-2))


def test_start_batch_validated_even_on_empty_source():
    """The validation must not be bypassed by the empty-source early
    exit: a zero-example source with a stale resume point fails loudly
    instead of silently yielding nothing forever."""
    empty = ArraySource(
        {
            "image": np.zeros((0, 4, 4, 1), np.float32),
            "label": np.zeros((0,), np.int32),
        }
    )
    # start_batch=0 on an empty source is a legitimate empty iteration.
    assert list(batch_iterator(empty, None, 8, training=True)) == []
    for bad in (-1, 3):
        with pytest.raises(ValueError, match="start_batch"):
            list(
                batch_iterator(
                    empty, None, 8, training=True, start_batch=bad
                )
            )


def test_train_split_smaller_than_global_batch_fails_loudly():
    """A train split that cannot fill one global batch (remainder
    dropped) would otherwise 'train' zero steps per epoch forever; eval
    iteration of the same source stays permissive (callers handle
    produced-no-batches explicitly)."""
    src = make_source(6)  # 6 examples < batch 8
    with pytest.raises(ValueError, match="zero batches"):
        list(batch_iterator(src, None, 8, training=True))
    # Eval mode without remainder dropping still yields the partial batch.
    got = list(
        batch_iterator(
            src, None, 8, training=False, shuffle=False,
            drop_remainder=False,
        )
    )
    assert len(got) == 1 and got[0]["image"].shape[0] == 6
    # Eval mode WITH remainder dropping: empty, silently (callers own it).
    assert (
        list(batch_iterator(src, None, 8, training=False, shuffle=False))
        == []
    )
