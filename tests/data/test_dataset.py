import numpy as np
import pytest

from zookeeper_tpu.core import configure
from zookeeper_tpu.data import (
    ArrayDataset,
    SyntheticCifar10,
    SyntheticImageClassification,
    SyntheticMnist,
)


def test_array_dataset():
    ds = ArrayDataset().with_data(
        train={"image": np.zeros((8, 4, 4, 1)), "label": np.zeros(8, np.int32)},
        validation={"image": np.zeros((2, 4, 4, 1)), "label": np.zeros(2, np.int32)},
    )
    assert ds.num_examples("train") == 8
    assert ds.num_examples("validation") == 2
    assert ds.train()[0]["image"].shape == (4, 4, 1)


def test_array_dataset_without_validation():
    ds = ArrayDataset().with_data(
        train={"image": np.zeros((8, 4, 4, 1)), "label": np.zeros(8, np.int32)}
    )
    assert ds.validation() is None
    with pytest.raises(ValueError):
        ds.num_examples("validation")


def test_synthetic_shapes_and_determinism():
    ds = SyntheticImageClassification()
    configure(ds, {"num_train_examples": 64, "num_classes": 7}, name="ds")
    train = ds.train()
    assert len(train) == 64
    ex = train[0]
    assert ex["image"].shape == (32, 32, 3)
    assert ex["image"].dtype == np.uint8
    assert 0 <= ex["label"] < 7
    # Deterministic across constructions.
    ds2 = SyntheticImageClassification()
    configure(ds2, {"num_train_examples": 64, "num_classes": 7}, name="ds2")
    np.testing.assert_array_equal(ds.train()[5]["image"], ds2.train()[5]["image"])
    # Validation split differs from train split.
    assert not np.array_equal(ds.train()[0]["image"], ds.validation()[0]["image"])


def test_synthetic_mnist_cifar_shapes():
    m = SyntheticMnist()
    configure(m, {}, name="m")
    assert m.train()[0]["image"].shape == (28, 28, 1)
    c = SyntheticCifar10()
    configure(c, {}, name="c")
    assert c.train()[0]["image"].shape == (32, 32, 3)


def test_synthetic_is_learnable_signal():
    # Images of the same class are more similar than across classes
    # (sanity check that the synthetic data has class-dependent signal).
    ds = SyntheticImageClassification()
    configure(ds, {"num_train_examples": 256, "num_classes": 2}, name="ds")
    src = ds.train()
    by_class = {0: [], 1: []}
    for i in range(len(src)):
        ex = src[i]
        by_class[int(ex["label"])].append(ex["image"].astype(np.float32).ravel())
    m0 = np.mean(by_class[0], axis=0)
    m1 = np.mean(by_class[1], axis=0)
    # Class means should differ noticeably more than sampling noise.
    assert np.abs(m0 - m1).mean() > 1.0


def _grain_examples(n, seed):
    """Example schema shared by the grain-backed tests: 8x8x1 uint8
    image, label i % 4."""
    r = np.random.default_rng(seed)
    return [
        {
            "image": r.integers(0, 255, (8, 8, 1)).astype(np.uint8),
            "label": np.int32(i % 4),
        }
        for i in range(n)
    ]


def _grain_experiment_conf(**overrides):
    """The configure dict for a GrainDataset-driven Mlp experiment."""
    conf = {
        "loader.preprocessing": "ImageClassificationPreprocessing",
        "loader.preprocessing.height": 8,
        "loader.preprocessing.width": 8,
        "loader.preprocessing.channels": 1,
        "loader.dataset": "GrainDataset",
        "loader.host_index": 0,
        "loader.host_count": 1,
        "model": "Mlp",
        "model.hidden_units": (8,),
        "batch_size": 16,
        "epochs": 1,
        "verbose": False,
    }
    conf.update(overrides)
    return conf


class TestGrainDataset:
    def _sources(self):
        import grain.python as pg

        train = pg.MapDataset.source(_grain_examples(64, 1))
        val = pg.MapDataset.source(_grain_examples(16, 2))
        return train, val

    def test_grain_pipeline_trains_end_to_end(self):
        """A grain.MapDataset (with a .map stage) drives the full
        training loop — the SURVEY §7 'grain as host pipeline' story."""
        from zookeeper_tpu.core import configure
        from zookeeper_tpu.data import GrainDataset
        from zookeeper_tpu.training import TrainingExperiment

        train, val = self._sources()
        train = train.map(lambda ex: ex)  # A real grain transform stage.

        exp = TrainingExperiment()
        configure(exp, _grain_experiment_conf(), name="experiment")
        exp.loader.dataset.with_sources(train, val)
        history = exp.run()
        import numpy as np

        assert np.isfinite(history["train"][0]["loss"])
        assert history["validation"]

    def test_infer_num_classes_scans_labels(self):
        from zookeeper_tpu.core import configure
        from zookeeper_tpu.data import GrainDataset

        train, _ = self._sources()
        ds = GrainDataset()
        configure(ds, {}, name="ds")
        ds.with_sources(train)
        assert ds.resolved_num_classes() == 4

    def test_rejects_non_random_access_source(self):
        import pytest

        from zookeeper_tpu.core import configure
        from zookeeper_tpu.data import GrainDataset

        ds = GrainDataset()
        configure(ds, {}, name="ds")
        with pytest.raises(TypeError, match="random-access"):
            ds.with_sources(iter(range(10)))

    def test_infer_rejects_empty_and_float_labels(self):
        import grain.python as pg
        import numpy as np
        import pytest

        from zookeeper_tpu.core import configure
        from zookeeper_tpu.data import GrainDataset

        ds = GrainDataset()
        configure(ds, {}, name="ds")
        ds.with_sources(pg.MapDataset.source([]))
        with pytest.raises(ValueError, match="num_classes"):
            ds.resolved_num_classes()

        ds2 = GrainDataset()
        configure(ds2, {}, name="ds2")
        ds2.with_sources(
            pg.MapDataset.source(
                [{"image": np.zeros((2, 2)), "label": np.float32(0.9)}]
            )
        )
        with pytest.raises(ValueError):
            ds2.resolved_num_classes()  # Float labels must not truncate.


class TestArrayRecordGrain:
    """Disk-backed ArrayRecord files through grain into the training loop
    — the full production data path (write once, stream random-access;
    nothing materializes beyond the touched records)."""

    @staticmethod
    def _write_records(path, n, seed):
        import pickle

        try:
            from array_record.python.array_record_module import (
                ArrayRecordWriter,
            )
        except ImportError:
            import pytest

            pytest.skip("array_record not installed")
        writer = ArrayRecordWriter(str(path), "group_size:1")
        for example in _grain_examples(n, seed):
            writer.write(pickle.dumps(example))
        writer.close()

    def test_array_record_streams_and_trains(self, tmp_path):
        import pickle

        import grain.python as pg

        from zookeeper_tpu.core import configure
        from zookeeper_tpu.data import GrainDataset
        from zookeeper_tpu.training import TrainingExperiment

        train_file = tmp_path / "train.array_record"
        val_file = tmp_path / "val.array_record"
        self._write_records(train_file, 64, 1)
        self._write_records(val_file, 16, 2)

        def decode(raw):
            return pickle.loads(raw)

        train = pg.MapDataset.source(
            pg.ArrayRecordDataSource([str(train_file)])
        ).map(decode)
        val = pg.MapDataset.source(
            pg.ArrayRecordDataSource([str(val_file)])
        ).map(decode)
        assert len(train) == 64 and len(val) == 16

        exp = TrainingExperiment()
        configure(
            exp,
            _grain_experiment_conf(**{"model.hidden_units": (16,), "epochs": 2}),
            name="experiment",
        )
        exp.loader.dataset.with_sources(train, validation=val)
        history = exp.run()
        assert len(history["train"]) == 2
        losses = [m["loss"] for m in history["train"]]
        assert losses[-1] < losses[0]
