import numpy as np
import pytest

from zookeeper_tpu.core import configure
from zookeeper_tpu.data import (
    ArrayDataset,
    SyntheticCifar10,
    SyntheticImageClassification,
    SyntheticMnist,
)


def test_array_dataset():
    ds = ArrayDataset().with_data(
        train={"image": np.zeros((8, 4, 4, 1)), "label": np.zeros(8, np.int32)},
        validation={"image": np.zeros((2, 4, 4, 1)), "label": np.zeros(2, np.int32)},
    )
    assert ds.num_examples("train") == 8
    assert ds.num_examples("validation") == 2
    assert ds.train()[0]["image"].shape == (4, 4, 1)


def test_array_dataset_without_validation():
    ds = ArrayDataset().with_data(
        train={"image": np.zeros((8, 4, 4, 1)), "label": np.zeros(8, np.int32)}
    )
    assert ds.validation() is None
    with pytest.raises(ValueError):
        ds.num_examples("validation")


def test_synthetic_shapes_and_determinism():
    ds = SyntheticImageClassification()
    configure(ds, {"num_train_examples": 64, "num_classes": 7}, name="ds")
    train = ds.train()
    assert len(train) == 64
    ex = train[0]
    assert ex["image"].shape == (32, 32, 3)
    assert ex["image"].dtype == np.uint8
    assert 0 <= ex["label"] < 7
    # Deterministic across constructions.
    ds2 = SyntheticImageClassification()
    configure(ds2, {"num_train_examples": 64, "num_classes": 7}, name="ds2")
    np.testing.assert_array_equal(ds.train()[5]["image"], ds2.train()[5]["image"])
    # Validation split differs from train split.
    assert not np.array_equal(ds.train()[0]["image"], ds.validation()[0]["image"])


def test_synthetic_mnist_cifar_shapes():
    m = SyntheticMnist()
    configure(m, {}, name="m")
    assert m.train()[0]["image"].shape == (28, 28, 1)
    c = SyntheticCifar10()
    configure(c, {}, name="c")
    assert c.train()[0]["image"].shape == (32, 32, 3)


def test_synthetic_is_learnable_signal():
    # Images of the same class are more similar than across classes
    # (sanity check that the synthetic data has class-dependent signal).
    ds = SyntheticImageClassification()
    configure(ds, {"num_train_examples": 256, "num_classes": 2}, name="ds")
    src = ds.train()
    by_class = {0: [], 1: []}
    for i in range(len(src)):
        ex = src[i]
        by_class[int(ex["label"])].append(ex["image"].astype(np.float32).ravel())
    m0 = np.mean(by_class[0], axis=0)
    m1 = np.mean(by_class[1], axis=0)
    # Class means should differ noticeably more than sampling noise.
    assert np.abs(m0 - m1).mean() > 1.0
