"""Per-host slicing determinism (docs/DESIGN.md §19 satellite): the
multi-host input contract — every host computes the same (seed, epoch)
permutation and reads a DISJOINT, EXHAUSTIVE slice of each global
batch, bit-stable across mid-epoch resume, with the augmentation RNG
keyed on (seed, index, epoch) so bytes are host-placement-invariant.
Driven entirely through the ``host_index``/``host_count`` injection —
no cluster needed."""

import numpy as np
import pytest

from zookeeper_tpu.core import configure
from zookeeper_tpu.data import (
    ArraySource,
    ImageClassificationPreprocessing,
    batch_iterator,
)


def make_source(n=48):
    rng = np.random.default_rng(0)
    return ArraySource(
        {
            "image": rng.integers(0, 255, size=(n, 8, 8, 1)).astype(
                np.uint8
            ),
            "label": (np.arange(n) % 10).astype(np.int32),
        }
    )


def make_pre(augment=False):
    pre = ImageClassificationPreprocessing()
    configure(
        pre,
        {
            "height": 8,
            "width": 8,
            "channels": 1,
            "pad_pixels": 2 if augment else 0,
            "random_flip": augment,
        },
        name="pre_hosts",
    )
    return pre


def host_batches(host_index, host_count, *, seed=3, epoch=1, start_batch=0,
                 training=False, pre=None, batch_size=8):
    return list(
        batch_iterator(
            make_source(),
            pre,
            batch_size,
            training=training,
            shuffle=True,
            seed=seed,
            epoch=epoch,
            host_index=host_index,
            host_count=host_count,
            start_batch=start_batch,
        )
    )


def test_two_hosts_disjoint_and_exhaustive():
    """The two hosts' index spaces partition every global batch: no
    example seen twice, none dropped (within the drop_remainder
    boundary), and together they equal the single-host global run."""
    pre = None
    h0 = host_batches(0, 2, pre=pre)
    h1 = host_batches(1, 2, pre=pre)
    full = host_batches(0, 1, pre=pre, batch_size=16)
    assert len(h0) == len(h1) == len(full) == 3  # 48 // 16
    for b0, b1, bf in zip(h0, h1, full):
        i0 = set(np.asarray(b0["_index"]).tolist())
        i1 = set(np.asarray(b1["_index"]).tolist())
        assert not (i0 & i1)  # disjoint
        assert i0 | i1 == set(np.asarray(bf["_index"]).tolist())
        # Contiguous slices of the SAME global permutation, in order.
        np.testing.assert_array_equal(
            np.concatenate([b0["_index"], b1["_index"]]), bf["_index"]
        )


def test_host_slices_bitwise_match_global_run_under_augmentation():
    """The counter-RNG contract: augmented bytes depend on (seed,
    index, epoch) only, so host h's rows ARE the global run's rows
    h*b..(h+1)*b — bit-for-bit, not just statistically."""
    pre = make_pre(augment=True)
    full = host_batches(0, 1, pre=pre, batch_size=16, training=True)
    for h in (0, 1):
        part = host_batches(h, 2, pre=pre, training=True)
        for bp, bf in zip(part, full):
            np.testing.assert_array_equal(
                bp["input"], bf["input"][h * 8 : (h + 1) * 8]
            )
            np.testing.assert_array_equal(
                bp["target"], bf["target"][h * 8 : (h + 1) * 8]
            )


def test_resume_is_bit_stable_per_host():
    """start_batch=k on each host reproduces batches k.. of that host's
    uninterrupted epoch bit-for-bit — the exact-mid-epoch-resume
    contract, per host."""
    pre = make_pre(augment=True)
    for h in (0, 1):
        uninterrupted = host_batches(h, 2, pre=pre, training=True)
        resumed = host_batches(h, 2, pre=pre, training=True, start_batch=1)
        assert len(resumed) == len(uninterrupted) - 1
        for br, bu in zip(resumed, uninterrupted[1:]):
            np.testing.assert_array_equal(br["input"], bu["input"])
            np.testing.assert_array_equal(br["target"], bu["target"])


def test_epoch_changes_the_shared_permutation():
    """Both hosts see the SAME new permutation when the epoch advances
    (the shared (seed, epoch) key) — and it differs from epoch 1's."""
    a0 = host_batches(0, 2, epoch=1)
    b0 = host_batches(0, 2, epoch=2)
    b1 = host_batches(1, 2, epoch=2)
    assert not np.array_equal(a0[0]["_index"], b0[0]["_index"])
    full = host_batches(0, 1, batch_size=16, epoch=2)
    np.testing.assert_array_equal(
        np.concatenate([b0[0]["_index"], b1[0]["_index"]]),
        full[0]["_index"],
    )


def test_bad_host_identity_rejected():
    with pytest.raises(ValueError, match="host_index"):
        host_batches(2, 2)
    with pytest.raises(ValueError, match="host_index"):
        host_batches(-1, 2)
    with pytest.raises(ValueError, match="host_index"):
        list(
            batch_iterator(
                make_source(), None, 8, training=False, host_count=0
            )
        )
