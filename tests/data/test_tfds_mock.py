"""TFDSDataset / MultiTFDSDataset exercised against a mock tfds module.

tensorflow_datasets is not installed here (no network), so these tests
inject a minimal fake implementing the exact API surface the adapters
consume (``tfds.data_source`` random access + ``tfds.builder().info``) —
turning the previously env-gated code paths into tested contract:
streaming (no split materialization), split routing, metadata-derived
class counts, multi-dataset concat, and end-to-end training.
"""

import sys
import types

import numpy as np
import pytest

from zookeeper_tpu.core import configure


class _FakeSource:
    """Random-access split that COUNTS decodes: materialization bugs
    (iterating the whole split on open) become assertion failures."""

    def __init__(self, n, num_classes, offset=0):
        self.n = n
        self.num_classes = num_classes
        self.offset = offset
        self.decode_calls = 0

    def __len__(self):
        return self.n

    def __getitem__(self, index):
        self.decode_calls += 1
        rng = np.random.default_rng(self.offset + index)
        return {
            "image": rng.integers(0, 255, (8, 8, 1)).astype(np.uint8),
            "label": np.int64((self.offset + index) % self.num_classes),
        }


@pytest.fixture
def fake_tfds(monkeypatch):
    sources = {}

    seen_decoders = {}

    def data_source(name, split=None, data_dir=None, **kwargs):
        # Sentinel distinguishes "kwarg omitted" (older-tfds compat) from
        # an explicit decoders=None.
        seen_decoders[(name, split)] = kwargs.get("decoders", "<omitted>")
        key = (name, split)
        if key not in sources:
            n = {"train": 64, "validation": 16}.get(split, 8)
            # Offset NOT divisible by num_classes, so cross-dataset label
            # streams genuinely differ (routing bugs show in labels).
            offset = 1001 if name.endswith("2") else 0
            sources[key] = _FakeSource(n, num_classes=4, offset=offset)
        return sources[key]

    class _Label:
        num_classes = 4

    class _Split:
        def __init__(self, n):
            self.num_examples = n

    class _Info:
        features = {"label": _Label()}
        splits = {"train": _Split(64), "validation": _Split(16)}

    class _Builder:
        info = _Info()

    module = types.ModuleType("tensorflow_datasets")
    module.data_source = data_source
    module.builder = lambda name, data_dir=None: _Builder()
    monkeypatch.setitem(sys.modules, "tensorflow_datasets", module)
    sources["_decoders"] = seen_decoders
    return sources


def test_tfds_dataset_streams_without_materializing(fake_tfds):
    from zookeeper_tpu.data import TFDSDataset

    ds = TFDSDataset()
    configure(
        ds, {"name": "fakeset", "validation_split": "validation"}, name="ds"
    )
    train = ds.train()
    assert len(train) == 64
    # Opening the split must decode NOTHING (the round-1 failure mode was
    # list(tfds.as_numpy(ds)) — full materialization on open).
    src = fake_tfds[("fakeset", "train")]
    assert src.decode_calls == 0
    ex = train[5]
    assert ex["image"].shape == (8, 8, 1) and src.decode_calls == 1

    val = ds.validation()
    assert len(val) == 16
    assert ds.num_examples("train") == 64
    # 'validation' remaps to validation_split before the builder lookup.
    assert ds.num_examples("validation") == 16
    # Class count from the builder's feature metadata, no field needed.
    assert ds.resolved_num_classes() == 4


def test_tfds_dataset_trains_end_to_end(fake_tfds):
    from zookeeper_tpu.training import TrainingExperiment

    exp = TrainingExperiment()
    configure(
        exp,
        {
            "loader.dataset": "TFDSDataset",
            "loader.dataset.name": "fakeset",
            "loader.dataset.validation_split": "validation",
            "loader.preprocessing": "ImageClassificationPreprocessing",
            "loader.preprocessing.height": 8,
            "loader.preprocessing.width": 8,
            "loader.preprocessing.channels": 1,
            "loader.host_index": 0,
            "loader.host_count": 1,
            "model": "Mlp",
            "model.hidden_units": (8,),
            "batch_size": 16,
            "epochs": 1,
            "verbose": False,
        },
        name="experiment",
    )
    history = exp.run()
    assert np.isfinite(history["train"][0]["loss"])
    assert history["validation"]


def test_multi_tfds_concat_routes_to_sources(fake_tfds):
    from zookeeper_tpu.data import MultiTFDSDataset

    ds = MultiTFDSDataset()
    configure(ds, {"names": ["set1", "set2"], "num_classes": 4}, name="ds")
    train = ds.train()
    assert len(train) == 128  # 64 + 64.
    a, b = train[0], train[64]
    # Second half routes to the second dataset (distinct offset stream):
    # 1001 % 4 == 1 differs from set1's label 0 at the same local index.
    assert int(a["label"]) == 0
    assert int(b["label"]) == 1001 % 4 == 1
    assert fake_tfds[("set1", "train")].decode_calls == 1
    assert fake_tfds[("set2", "train")].decode_calls == 1


def test_tfds_missing_dependency_error_is_actionable(monkeypatch):
    from zookeeper_tpu.data import TFDSDataset

    # Force the import to fail regardless of environment (a sys.modules
    # entry of None makes `import tensorflow_datasets` raise ImportError).
    monkeypatch.setitem(sys.modules, "tensorflow_datasets", None)
    ds = TFDSDataset()
    configure(ds, {"name": "whatever"}, name="ds")
    with pytest.raises(ImportError, match="MemmapDataset"):
        ds.train()


def test_tfds_load_passes_decoders_through(fake_tfds):
    """The reference ``load(split, decoders)`` capability: decoders reach
    tfds.data_source (e.g. SkipDecoding to defer JPEG decode), and are
    omitted entirely when not given (older-tfds compatibility)."""
    from zookeeper_tpu.core import configure
    from zookeeper_tpu.data import TFDSDataset

    ds = TFDSDataset()
    configure(ds, {"name": "fake1"}, name="ds")
    ds.load("train")
    assert fake_tfds["_decoders"][("fake1", "train")] == "<omitted>"

    marker = {"image": "skip-decoding-marker"}
    ds.load("train", decoders=marker)
    assert fake_tfds["_decoders"][("fake1", "train")] == marker


def test_multi_tfds_surface_parity_with_tfds_dataset(fake_tfds):
    """MultiTFDSDataset exposes the same load(split, decoders) /
    num_examples / metadata-derived class count surface as TFDSDataset
    (round-2 gap: _load_all silently dropped the decoders passthrough)."""
    from zookeeper_tpu.data import MultiTFDSDataset

    ds = MultiTFDSDataset()
    configure(ds, {"names": ["set1", "set2"]}, name="ds")

    marker = {"image": "skip-decoding-marker"}
    ds.load("train", decoders=marker)
    # Decoders reach EVERY underlying dataset, not just the first.
    assert fake_tfds["_decoders"][("set1", "train")] == marker
    assert fake_tfds["_decoders"][("set2", "train")] == marker
    # Omitted stays omitted (older-tfds kwarg compatibility).
    ds.load("train")
    assert fake_tfds["_decoders"][("set2", "train")] == "<omitted>"

    # Counts sum across datasets; class count from builder metadata (max
    # over the merged label spaces) with no num_classes field set.
    assert ds.num_examples("train") == 128
    assert ds.resolved_num_classes() == 4
