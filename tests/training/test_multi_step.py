"""Fused multi-step engine (training.step.build_multi_step + the
unroll>1 experiment loop): bit-exactness vs the eager per-step path,
mid-slab resume, the partial-final-slab edge, and the deferred-readback
logging contract."""

import json

import numpy as np
import pytest

from zookeeper_tpu.core import configure
from zookeeper_tpu.training import TrainingExperiment


def make_experiment(extra_conf=None):
    exp = TrainingExperiment()
    conf = {
        "loader.dataset": "SyntheticMnist",
        "loader.dataset.num_train_examples": 256,
        "loader.dataset.num_validation_examples": 64,
        "loader.preprocessing": "ImageClassificationPreprocessing",
        "loader.preprocessing.height": 28,
        "loader.preprocessing.width": 28,
        "loader.preprocessing.channels": 1,
        "loader.host_index": 0,
        "loader.host_count": 1,
        "model": "Mlp",
        "model.hidden_units": (32,),
        "batch_size": 32,
        "epochs": 2,
        "verbose": False,
        **(extra_conf or {}),
    }
    configure(exp, conf, name="experiment")
    return exp


def assert_states_equal(a, b):
    import jax

    for xa, xb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(xa), np.asarray(xb))


def test_build_multi_step_matches_sequential_steps():
    """The scan-fused multi-step is the SAME computation as N eager
    steps: params, opt state, step counter, and per-step metrics all
    bit-equal."""
    import jax
    import jax.numpy as jnp
    import optax

    from zookeeper_tpu.models import Mlp
    from zookeeper_tpu.training import (
        TrainState,
        build_multi_step,
        make_train_step,
    )

    m = Mlp()
    configure(m, {"hidden_units": (8,)}, name="m")
    module = m.build((4, 4, 1), num_classes=3)
    params, model_state = m.initialize(module, (4, 4, 1))

    def fresh_state():
        return TrainState.create(
            apply_fn=module.apply,
            params=params,
            model_state=model_state,
            tx=optax.adam(1e-3),
        )

    rng = np.random.default_rng(0)
    batches = [
        {
            "input": jnp.asarray(
                rng.normal(size=(8, 4, 4, 1)), jnp.float32
            ),
            "target": jnp.asarray(rng.integers(0, 3, 8)),
        }
        for _ in range(5)
    ]
    step = jax.jit(make_train_step())
    s_eager = fresh_state()
    eager_metrics = []
    for b in batches:
        s_eager, mtr = step(s_eager, b)
        eager_metrics.append(mtr)

    slab = {
        k: jnp.stack([b[k] for b in batches]) for k in batches[0]
    }
    multi = jax.jit(build_multi_step(make_train_step()))
    s_fused, stacked = multi(fresh_state(), slab)

    assert int(s_fused.step) == 5
    assert_states_equal(s_eager.params, s_fused.params)
    assert_states_equal(s_eager.opt_state, s_fused.opt_state)
    for i, mtr in enumerate(eager_metrics):
        for k, v in mtr.items():
            np.testing.assert_array_equal(
                np.asarray(v), np.asarray(stacked[k][i]), err_msg=f"{k}@{i}"
            )


@pytest.mark.parametrize("unroll", [4, 3])
def test_unroll_bit_exact_with_eager_loop(unroll):
    """unroll>1 must be bit-exact with unroll=1 over full training:
    per-epoch train metrics, validation, and the final state (params +
    opt state). unroll=3 over 8 steps/epoch also exercises the
    partial-final-slab edge (slabs of 3, 3, 2)."""
    ref = make_experiment()
    h_ref = ref.run()
    fused = make_experiment({"unroll": unroll})
    h_fused = fused.run()

    for split in ("train", "validation"):
        assert len(h_ref[split]) == len(h_fused[split])
        for e_ref, e_fused in zip(h_ref[split], h_fused[split]):
            for k, v in e_ref.items():
                if k == "examples_per_sec":
                    continue
                assert v == e_fused[k], (split, k)
    assert_states_equal(ref.final_state.params, fused.final_state.params)
    assert_states_equal(
        ref.final_state.opt_state, fused.final_state.opt_state
    )
    assert int(np.asarray(fused.final_state.step)) == int(
        np.asarray(ref.final_state.step)
    )


@pytest.mark.slow
def test_unroll_mid_slab_resume_bit_exact(tmp_path):
    """A step-granular checkpoint at a step that is NOT a multiple of
    unroll resumes mid-slab: the fused run picks up at start_batch=5
    (slabs of 3 over the remaining 3 steps of epoch 0, then full
    epochs) and lands bit-identical to an uninterrupted eager run."""
    ckpt = {
        "checkpointer.directory": str(tmp_path / "ckpt"),
        "checkpointer.save_every_steps": 5,
        "checkpointer.save_every_epochs": 0,
        "checkpointer.synchronous": True,
    }
    # Phase 1: eager, first epoch only; leaves a checkpoint at step 5
    # (8 steps/epoch — step 5 is mid-slab for any unroll > 1).
    first = make_experiment({"epochs": 1, **ckpt})
    first.run()
    first.checkpointer.close()

    # Phase 2: resume FUSED (unroll=4 -> first slab covers steps 5-7,
    # a partial slab of 3) and finish both epochs.
    resumed = make_experiment({"epochs": 2, "unroll": 4, **ckpt})
    h_resumed = resumed.run()
    resumed.checkpointer.close()

    # Reference: uninterrupted eager run, no checkpointing.
    ref = make_experiment()
    h_ref = ref.run()

    assert_states_equal(ref.final_state.params, resumed.final_state.params)
    assert_states_equal(
        ref.final_state.opt_state, resumed.final_state.opt_state
    )
    # Epoch 1 (the fully-post-resume epoch) aggregates match exactly;
    # epoch 0's are partial by design (resumed at step 5).
    for k, v in h_ref["train"][1].items():
        if k == "examples_per_sec":
            continue
        assert v == h_resumed["train"][1][k], k


def test_unroll_step_cadence_checkpoints_quantize_to_slab_end(tmp_path):
    """Step-cadence saves in fused mode fire at the end of the slab
    containing the due step (state mid-scan is not addressable), so
    saved step ids are slab multiples — and each is a valid exact
    resume point."""
    exp = make_experiment(
        {
            "epochs": 1,
            "unroll": 4,
            "checkpointer.directory": str(tmp_path / "ckpt"),
            "checkpointer.save_every_steps": 3,
            "checkpointer.save_every_epochs": 0,
            "checkpointer.synchronous": True,
        }
    )
    exp.run()
    # 8 steps, slabs [0-4), [4-8); due steps 3 and 6 -> saves at 4, 8.
    assert sorted(exp.checkpointer._manager().all_steps()) == [4, 8]
    exp.checkpointer.close()


@pytest.mark.slow
def test_deferred_readback_logs_same_metrics_as_eager(tmp_path):
    """CI smoke for the fused loop: Experiment.run() over a few slabs
    on CPU, asserting the deferred-readback path emits EXACTLY the
    per-step scalars the eager path logs (same steps, same values), so
    the fused loop cannot silently rot. log_every=2 with unroll=3
    exercises readback boundaries that straddle slab boundaries."""
    logs = {}
    for name, unroll in (("eager", 1), ("fused", 3)):
        path = str(tmp_path / f"{name}.jsonl")
        exp = make_experiment(
            {
                "epochs": 1,
                "unroll": unroll,
                "log_every": 2,
                "writer.jsonl.path": path,
            }
        )
        exp.run()
        with open(path) as f:
            logs[name] = [json.loads(line) for line in f]
    # Drop the epoch-aggregate record (train_epoch/ + val/ tags); the
    # per-step train/ records must agree row for row.
    step_rows = {
        name: [r for r in rows if any(k.startswith("train/") for k in r)]
        for name, rows in logs.items()
    }
    assert step_rows["eager"], "eager path logged no per-step scalars"
    assert step_rows["eager"] == step_rows["fused"]


@pytest.mark.slow
def test_unroll_respects_steps_per_epoch_cap():
    """A steps_per_epoch cap that falls mid-slab truncates the final
    slab instead of over-training (5 steps at unroll=4 -> slabs of
    4 + 1)."""
    ref = make_experiment({"epochs": 1, "steps_per_epoch": 5})
    h_ref = ref.run()
    fused = make_experiment(
        {"epochs": 1, "steps_per_epoch": 5, "unroll": 4}
    )
    h_fused = fused.run()
    assert int(np.asarray(fused.final_state.step)) == 5
    for k, v in h_ref["train"][0].items():
        if k == "examples_per_sec":
            continue
        assert v == h_fused["train"][0][k], k
    assert_states_equal(ref.final_state.params, fused.final_state.params)


def test_unroll_data_parallel_mesh():
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs multi-device (conftest forces 8 CPU devices)")
    exp = make_experiment(
        {
            "partitioner": "DataParallelPartitioner",
            "epochs": 1,
            "unroll": 4,
        }
    )
    history = exp.run()
    assert history["validation"][-1]["accuracy"] > 0.2
    # The slab sharding replicates the scan axis and shards batch on
    # the data axes.
    sh = exp.partitioner.slab_sharding()
    assert sh.spec[0] is None and sh.spec[1] == ("data",)


def test_unroll_invalid_rejected():
    exp = make_experiment({"unroll": 0})
    with pytest.raises(ValueError, match="unroll"):
        exp.run()


@pytest.mark.slow
def test_unroll_conv_forward_exact_backward_within_ulp_drift():
    """The documented conv caveat (build_multi_step docstring): the
    FORWARD is bit-identical under scan (step-0 loss/metrics agree
    exactly — the batch slicing and RNG are right), while conv wgrad
    reductions may differ at the fp32 ULP level between the scanned
    and flat programs (XLA reduction ordering), Adam-amplified over
    steps. Pin both halves: exact forward, bounded drift."""
    import jax
    import jax.numpy as jnp
    import optax

    from zookeeper_tpu.core import configure as _cfg
    from zookeeper_tpu.models import SimpleCnn
    from zookeeper_tpu.training import (
        TrainState,
        build_multi_step,
        make_train_step,
    )

    m = SimpleCnn()
    _cfg(m, {}, name="m")
    module = m.build((28, 28, 1), num_classes=10)
    params, model_state = m.initialize(module, (28, 28, 1))

    def fresh():
        return TrainState.create(
            apply_fn=module.apply,
            params=params,
            model_state=model_state,
            tx=optax.adam(1e-3),
        )

    rng = np.random.default_rng(0)
    batches = [
        {
            "input": jnp.asarray(
                rng.normal(size=(8, 28, 28, 1)), jnp.float32
            ),
            "target": jnp.asarray(rng.integers(0, 10, 8)),
        }
        for _ in range(4)
    ]
    step = jax.jit(make_train_step())
    s_eager = fresh()
    eager_losses = []
    for b in batches:
        s_eager, mtr = step(s_eager, b)
        eager_losses.append(float(mtr["loss"]))
    slab = {k: jnp.stack([b[k] for b in batches]) for k in batches[0]}
    s_fused, stacked = jax.jit(build_multi_step(make_train_step()))(
        fresh(), slab
    )
    # Forward bit-exact: the first step sees identical params + batch.
    assert float(stacked["loss"][0]) == eager_losses[0]
    # Later steps track within the documented Adam-amplified ULP drift.
    np.testing.assert_allclose(
        np.asarray(stacked["loss"]), eager_losses, rtol=1e-4
    )
    for a, b in zip(
        jax.tree.leaves(s_eager.params), jax.tree.leaves(s_fused.params)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-2
        )


@pytest.mark.chaos
def test_unroll_kill_midepoch_recovery_bit_exact(tmp_path):
    """The resilience acceptance pin for the fused loop: an injected
    kill (FaultPlan.kill_at_step) mid-epoch under unroll>1 exits with
    Preempted at the next SLAB boundary after one synchronous save, and
    run_with_recovery resumes to a final state — params, opt_state, AND
    per-epoch metrics — bit-identical to an uninterrupted eager run."""
    from zookeeper_tpu.resilience import (
        FaultPlan,
        Preempted,
        faults,
        run_with_recovery,
    )

    ref = make_experiment()  # uninterrupted eager reference, 2 epochs
    h_ref = ref.run()

    ckpt = {
        "checkpointer.directory": str(tmp_path / "ckpt"),
        "checkpointer.synchronous": True,
        "checkpointer.save_every_epochs": 0,
        "checkpointer.save_every_steps": 0,  # ONLY the preemption save
    }
    exp = make_experiment({"unroll": 3, **ckpt})
    # Step 5 is mid-epoch (spe=8) and mid-slab for unroll=3: the kill
    # must quantize to the slab boundary at step 6, like step-cadence
    # checkpoints do.
    with faults.injected(FaultPlan(kill_at_step=5)) as plan:
        result = run_with_recovery(exp, backoff_s=0.0, sleep=lambda s: None)
    assert result.restarts == 1
    assert isinstance(result.causes[0], Preempted)
    assert result.causes[0].step == 6 and result.causes[0].saved
    assert result.restore_ms and result.restore_ms[0] > 0

    assert_states_equal(ref.final_state.params, exp.final_state.params)
    assert_states_equal(
        ref.final_state.opt_state, exp.final_state.opt_state
    )
    assert int(np.asarray(exp.final_state.step)) == int(
        np.asarray(ref.final_state.step)
    )
    # Epoch 1 (fully post-recovery) metrics match the reference exactly;
    # epoch 0's aggregates are split across the kill (partial by design).
    h_res = result.history
    for k, v in h_ref["train"][1].items():
        if k == "examples_per_sec":
            continue
        assert v == h_res["train"][1][k], k
    exp.checkpointer.close()


@pytest.mark.chaos
def test_unroll_async_ckpt_kill_recovery_bit_exact(tmp_path):
    """The SAME contract as above under checkpointer.mode="async": the
    step-cadence saves ride the background writer (slab-boundary
    snapshots overlapping the next slab), the kill drains the in-flight
    write before the final synchronous save, and the recovered run is
    STILL bit-identical to the uninterrupted eager reference — the
    async path changes where the write runs, never what resumes."""
    from zookeeper_tpu.resilience import (
        FaultPlan,
        Preempted,
        faults,
        run_with_recovery,
    )

    ref = make_experiment()  # uninterrupted eager reference, 2 epochs
    h_ref = ref.run()

    ckpt = {
        "checkpointer.directory": str(tmp_path / "ckpt"),
        "checkpointer.mode": "async",
        "checkpointer.save_every_epochs": 0,
        # Step-cadence saves flow through the writer while training
        # continues; the preemption save is still synchronous.
        "checkpointer.save_every_steps": 3,
    }
    exp = make_experiment({"unroll": 3, **ckpt})
    with faults.injected(FaultPlan(kill_at_step=5)):
        result = run_with_recovery(exp, backoff_s=0.0, sleep=lambda s: None)
    assert result.restarts == 1
    assert isinstance(result.causes[0], Preempted)
    assert result.causes[0].step == 6 and result.causes[0].saved
    # The async addition to the preemption budget is observable.
    assert len(result.save_wait_ms) == 1 and result.save_wait_ms[0] >= 0.0

    assert_states_equal(ref.final_state.params, exp.final_state.params)
    assert_states_equal(
        ref.final_state.opt_state, exp.final_state.opt_state
    )
    h_res = result.history
    for k, v in h_ref["train"][1].items():
        if k == "examples_per_sec":
            continue
        assert v == h_res["train"][1][k], k
    exp.checkpointer.close()


def test_unroll_with_ema_and_flip_free_extras_bit_exact():
    """Optional step extras (EMA, label smoothing) ride the scan
    unchanged."""
    import jax

    conf = {"epochs": 1, "ema_decay": 0.9, "label_smoothing": 0.1}
    ref = make_experiment(conf)
    ref.run()
    fused = make_experiment({**conf, "unroll": 4})
    fused.run()
    for a, b in zip(
        jax.tree.leaves(ref.final_state.ema_params),
        jax.tree.leaves(fused.final_state.ema_params),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
