"""Knowledge distillation: kd loss, model export/restore, the staged
teacher->student recipe end-to-end (Real-to-Binary capability)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from zookeeper_tpu.core import configure
from zookeeper_tpu.training import (
    DistillationExperiment,
    TrainingExperiment,
    load_model,
    save_model,
)
from zookeeper_tpu.training.step import kd_divergence


def test_kd_divergence_zero_iff_logits_match():
    a = jnp.asarray(np.random.default_rng(0).normal(size=(4, 10)), jnp.float32)
    assert float(kd_divergence(a, a, 2.0)) == pytest.approx(0.0, abs=1e-6)
    b = a + 1.0  # Uniform logit shift: softmax-invariant, still zero KL.
    assert float(kd_divergence(b, a, 2.0)) == pytest.approx(0.0, abs=1e-5)
    c = a.at[:, 0].add(3.0)
    assert float(kd_divergence(c, a, 2.0)) > 0.01


def test_save_load_model_roundtrip(tmp_path):
    params = {"dense": {"kernel": jnp.arange(6.0).reshape(2, 3)}}
    model_state = {"batch_stats": {"bn": {"mean": jnp.ones((3,))}}}
    save_model(str(tmp_path / "m"), params, model_state)
    p2, s2 = load_model(str(tmp_path / "m"), params, model_state)
    np.testing.assert_array_equal(
        np.asarray(p2["dense"]["kernel"]), np.arange(6.0).reshape(2, 3)
    )
    np.testing.assert_array_equal(
        np.asarray(s2["batch_stats"]["bn"]["mean"]), np.ones((3,))
    )


def _base_conf(extra=None):
    return {
        "loader.dataset": "SyntheticMnist",
        "loader.dataset.num_train_examples": 128,
        "loader.dataset.num_validation_examples": 32,
        "loader.preprocessing": "ImageClassificationPreprocessing",
        "loader.preprocessing.height": 28,
        "loader.preprocessing.width": 28,
        "loader.preprocessing.channels": 1,
        "loader.host_index": 0,
        "loader.host_count": 1,
        "model": "Mlp",
        "model.hidden_units": (16,),
        "batch_size": 32,
        "epochs": 1,
        "verbose": False,
        **(extra or {}),
    }


@pytest.mark.slow
def test_distillation_end_to_end(tmp_path):
    """Stage 1 trains+exports a teacher; stage 2 distills a student from
    it. The student's step reports kd_loss and the loop runs to the end."""
    teacher_path = str(tmp_path / "teacher")
    t_exp = TrainingExperiment()
    configure(
        t_exp,
        _base_conf({"epochs": 2, "export_model_to": teacher_path}),
        name="teacher_exp",
    )
    t_exp.run()

    s_conf = _base_conf()
    del s_conf["model.hidden_units"]
    s_exp = DistillationExperiment()
    configure(
        s_exp,
        {
            **s_conf,
            **{
                "model": "BinaryNet",
                "model.features": (8, 8),
                "model.dense_units": (16,),
                "teacher": "Mlp",
                "teacher.hidden_units": (16,),
                "teacher_checkpoint": teacher_path,
                "alpha": 0.5,
                "temperature": 2.0,
                "metrics_file": str(tmp_path / "m.jsonl"),
            },
        },
        name="student_exp",
    )
    history = s_exp.run()
    epoch = history["train"][-1]
    assert "kd_loss" in epoch and np.isfinite(epoch["kd_loss"])
    assert np.isfinite(epoch["loss"])


def test_distillation_requires_teacher_checkpoint():
    s_exp = DistillationExperiment()
    configure(
        s_exp,
        _base_conf({"teacher": "Mlp", "teacher.hidden_units": (8,)}),
        name="student_exp",
    )
    with pytest.raises(ValueError, match="teacher_checkpoint"):
        s_exp.run()


def test_distillation_pulls_student_toward_teacher(tmp_path):
    """With alpha=0 (pure KD) the student's KD loss to the teacher drops
    over training — the gradient really flows from the teacher term."""
    teacher_path = str(tmp_path / "teacher")
    t_exp = TrainingExperiment()
    configure(
        t_exp,
        _base_conf({"epochs": 2, "export_model_to": teacher_path}),
        name="teacher_exp",
    )
    t_exp.run()

    s_exp = DistillationExperiment()
    configure(
        s_exp,
        _base_conf(
            {
                "epochs": 4,
                "teacher": "Mlp",
                "teacher.hidden_units": (16,),
                "teacher_checkpoint": teacher_path,
                "alpha": 0.0,
            }
        ),
        name="student_exp",
    )
    history = s_exp.run()
    kd_first = history["train"][0]["kd_loss"]
    kd_last = history["train"][-1]["kd_loss"]
    assert kd_last < kd_first
