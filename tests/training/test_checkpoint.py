import numpy as np
import pytest

from zookeeper_tpu.core import configure
from zookeeper_tpu.training import Checkpointer, TrainingExperiment


def make_experiment(tmp_path, extra=None):
    exp = TrainingExperiment()
    conf = {
        "loader.dataset": "SyntheticMnist",
        "loader.dataset.num_train_examples": 128,
        "loader.dataset.num_validation_examples": 32,
        "loader.preprocessing": "ImageClassificationPreprocessing",
        "loader.preprocessing.height": 28,
        "loader.preprocessing.width": 28,
        "loader.preprocessing.channels": 1,
        "loader.host_index": 0,
        "loader.host_count": 1,
        "model": "Mlp",
        "model.hidden_units": (16,),
        "batch_size": 32,
        "epochs": 2,
        "verbose": False,
        "checkpointer.directory": str(tmp_path / "ckpt"),
        "checkpointer.synchronous": True,
        **(extra or {}),
    }
    configure(exp, conf, name="experiment")
    return exp


def test_checkpointer_disabled_by_default():
    ckpt = Checkpointer()
    configure(ckpt, {}, name="ckpt")
    assert not ckpt.enabled
    assert ckpt.save(None) is False
    assert ckpt.restore_state("anything") == "anything"


@pytest.mark.slow
def test_save_and_restore_roundtrip(tmp_path):
    exp = make_experiment(tmp_path)
    exp.run()
    ckpt = exp.checkpointer
    assert ckpt.latest_step() == 8  # 2 epochs * 4 steps.

    # A fresh experiment with the same directory resumes: epochs already
    # done, so run() trains zero additional epochs and state matches.
    exp2 = make_experiment(tmp_path)
    history2 = exp2.run()
    assert history2["train"] == []
    import jax

    assert int(jax.device_get(exp2.final_state.step)) == 8
    for a, b in zip(
        jax.tree.leaves(exp.final_state.params),
        jax.tree.leaves(exp2.final_state.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    exp.checkpointer.close()
    exp2.checkpointer.close()


@pytest.mark.slow
def test_resume_continues_training(tmp_path):
    # Train 1 epoch, then "crash"; resume with epochs=3 trains 2 more.
    exp = make_experiment(tmp_path, {"epochs": 1})
    exp.run()
    assert exp.checkpointer.latest_step() == 4
    exp.checkpointer.close()

    exp2 = make_experiment(tmp_path, {"epochs": 3})
    history = exp2.run()
    assert len(history["train"]) == 2  # Epochs 1 and 2 only.
    import jax

    assert int(jax.device_get(exp2.final_state.step)) == 12
    exp2.checkpointer.close()


def test_restore_disabled_starts_fresh(tmp_path):
    exp = make_experiment(tmp_path, {"epochs": 1})
    exp.run()
    exp.checkpointer.close()
    exp2 = make_experiment(
        tmp_path, {"epochs": 1, "checkpointer.restore": False}
    )
    history = exp2.run()
    assert len(history["train"]) == 1  # Trained from scratch.
    exp2.checkpointer.close()


def test_metrics_file_written(tmp_path):
    import json

    path = tmp_path / "metrics.jsonl"
    exp = make_experiment(
        tmp_path,
        {"metrics_file": str(path), "checkpointer.directory": None},
    )
    exp.run()
    lines = [json.loads(l) for l in path.read_text().splitlines()]
    assert len(lines) == 2
    assert {"epoch", "loss", "accuracy", "examples_per_sec"} <= set(lines[0])
    assert "val_accuracy" in lines[0]


def _tiny_state(value: float, step: int):
    """A minimal TrainState-shaped object for direct Checkpointer tests."""
    import jax.numpy as jnp
    import optax

    from zookeeper_tpu.training import TrainState

    state = TrainState.create(
        apply_fn=lambda *a, **k: None,
        params={"w": jnp.full((2,), value)},
        model_state={},
        tx=optax.sgd(0.1),
    )
    return state.replace(step=jnp.asarray(step))


def test_keep_best_retention_and_best_step(tmp_path):
    """keep_best_metric ranks checkpoints (Keras save_best_only parity):
    max_to_keep=1 keeps the best-accuracy save, not the latest."""
    ckpt = Checkpointer()
    configure(
        ckpt,
        {
            "directory": str(tmp_path / "best"),
            "max_to_keep": 1,
            "synchronous": True,
            "keep_best_metric": "accuracy",
        },
        name="ckpt",
    )
    for step, acc in ((1, 0.2), (2, 0.9), (3, 0.5)):
        ckpt.save(_tiny_state(float(step), step), metrics={"accuracy": acc})
    ckpt.wait()
    assert ckpt.best_step() == 2
    # The best save survives retention and restores with its params.
    restored = ckpt.restore_state(_tiny_state(0.0, 0))
    assert int(np.asarray(restored.step)) == 2
    np.testing.assert_allclose(np.asarray(restored.params["w"]), 2.0)
    ckpt.close()


def test_keep_best_requires_metrics(tmp_path):
    ckpt = Checkpointer()
    configure(
        ckpt,
        {
            "directory": str(tmp_path / "best2"),
            "synchronous": True,
            "keep_best_metric": "accuracy",
        },
        name="ckpt",
    )
    with pytest.raises(ValueError, match="carries no such metric"):
        ckpt.save(_tiny_state(1.0, 1))
    with pytest.raises(ValueError, match="carries no such metric"):
        ckpt.save(_tiny_state(1.0, 1), metrics={"loss": 0.5})
    ckpt.close()


def test_experiment_passes_metrics_to_best_checkpointing(tmp_path):
    """End-to-end: a TrainingExperiment with keep_best_metric ranks epoch
    saves by validation accuracy without erroring."""
    exp = make_experiment(
        tmp_path,
        {"checkpointer.keep_best_metric": "accuracy"},
    )
    exp.run()
    assert exp.checkpointer.best_step() is not None
    exp.checkpointer.close()


def test_keep_best_rank_saves_only_on_validated_epochs(tmp_path):
    """With keep_best_metric + validate_every=2, non-validation epochs
    must not rank-save (train metrics are not comparable to val metrics
    on one scale): only validated epochs appear in the manager."""
    exp = make_experiment(
        tmp_path,
        {
            "epochs": 4,
            "steps_per_epoch": 2,
            "validate_every": 2,
            "checkpointer.keep_best_metric": "accuracy",
            "checkpointer.max_to_keep": 10,
        },
    )
    exp.run()
    mgr = exp.checkpointer._manager()
    steps = sorted(mgr.all_steps())
    # Saves at the end of epochs 2 and 4 only (2 steps/epoch -> 4, 8).
    assert steps == [4, 8]
    exp.checkpointer.close()


def test_step_granular_save_and_exact_midepoch_resume(tmp_path):
    """save_every_steps checkpoints INSIDE the epoch, and resuming from
    a mid-epoch step replays exactly the remaining batches of that
    epoch: the resumed run's final params are bit-identical to an
    uninterrupted run's (the whole-pipeline determinism contract)."""
    import jax

    # Uninterrupted reference: 2 epochs x 4 steps.
    ref = make_experiment(tmp_path / "ref", {"epochs": 2})
    ref.run()
    ref_params = jax.device_get(ref.final_state.params)
    ref_step = int(jax.device_get(ref.final_state.step))
    ref.checkpointer.close()

    # Interrupted run: step saves only (epoch saves pushed out of
    # reach), so after the "crash" the LATEST checkpoint is the
    # mid-epoch step 3 of 4.
    conf = {
        "checkpointer.save_every_steps": 3,
        "checkpointer.save_every_epochs": 0,
    }
    exp = make_experiment(tmp_path, {"epochs": 1, **conf})
    exp.run()
    assert exp.checkpointer.latest_step() == 3
    exp.checkpointer.close()

    exp2 = make_experiment(tmp_path, {"epochs": 2, **conf})
    history = exp2.run()
    assert int(jax.device_get(exp2.final_state.step)) == ref_step == 8
    # Epoch 0 resumed mid-way (1 remaining step) + full epoch 1.
    assert len(history["train"]) == 2
    got = jax.device_get(exp2.final_state.params)
    ref_leaves = jax.tree.leaves(ref_params)
    got_leaves = jax.tree.leaves(got)
    assert len(ref_leaves) == len(got_leaves)
    for a, b in zip(ref_leaves, got_leaves):
        np.testing.assert_array_equal(a, b)
    # The resumed run's own step saves continued on the global-step
    # grid (6; step 3 already existed, epoch boundaries excluded).
    assert sorted(exp2.checkpointer._manager().all_steps()) == [3, 6]
    exp2.checkpointer.close()


def test_save_every_steps_rejects_best_ranking(tmp_path):
    """Mid-epoch saves carry no fresh rankable metrics: combining
    save_every_steps with keep_best_metric must fail loudly at run
    start, not pin a metric-less save later."""
    exp = make_experiment(
        tmp_path,
        {
            "checkpointer.save_every_steps": 2,
            "checkpointer.keep_best_metric": "accuracy",
        },
    )
    with pytest.raises(ValueError, match="save_every_steps"):
        exp.run()


def test_step_saves_cover_epoch_boundaries_when_epoch_path_idle(tmp_path):
    """A step-cadence save landing on an epoch boundary must still
    happen when the save_every_epochs path won't fire that epoch — the
    'loss bounded to N steps' promise has no epoch-shaped holes."""
    exp = make_experiment(
        tmp_path,
        {
            "epochs": 2,
            "checkpointer.save_every_steps": 4,
            "checkpointer.save_every_epochs": 0,
        },
    )
    exp.run()  # spe=4: steps 4 and 8 are both boundaries.
    assert sorted(exp.checkpointer._manager().all_steps()) == [4, 8]
    exp.checkpointer.close()


def test_step_save_defers_to_epoch_save_on_shared_step(tmp_path):
    """When both cadences land on one step, exactly one save happens
    (the epoch path's); a double save of one step would collide."""
    exp = make_experiment(
        tmp_path,
        {
            "epochs": 2,
            "checkpointer.save_every_steps": 4,
            "checkpointer.save_every_epochs": 1,
        },
    )
    exp.run()
    assert sorted(exp.checkpointer._manager().all_steps()) == [4, 8]
    exp.checkpointer.close()


@pytest.mark.slow
def test_midepoch_resume_bit_exact_under_dp_sharding(tmp_path):
    """The sharded interaction: restore_state() of a step-granular
    checkpoint onto a DataParallel mesh + the pipeline's start_batch
    skip must still be bit-identical to an uninterrupted DP run (the
    single-device variant above doesn't cover sharded restore)."""
    import jax

    if jax.device_count() < 2:
        pytest.skip("needs the multi-device CPU mesh")
    dp = {"partitioner": "DataParallelPartitioner", "batch_size": 32}

    ref = make_experiment(tmp_path / "ref", {"epochs": 2, **dp})
    ref.run()
    ref_params = jax.device_get(ref.final_state.params)
    ref.checkpointer.close()

    conf = {
        "checkpointer.save_every_steps": 3,
        "checkpointer.save_every_epochs": 0,
        **dp,
    }
    exp = make_experiment(tmp_path, {"epochs": 1, **conf})
    exp.run()
    assert exp.checkpointer.latest_step() == 3  # mid-epoch (spe=4)
    exp.checkpointer.close()

    exp2 = make_experiment(tmp_path, {"epochs": 2, **conf})
    exp2.run()
    assert int(jax.device_get(exp2.final_state.step)) == 8
    for a, b in zip(
        jax.tree.leaves(ref_params),
        jax.tree.leaves(jax.device_get(exp2.final_state.params)),
    ):
        np.testing.assert_array_equal(a, b)
    exp2.checkpointer.close()


@pytest.mark.slow
def test_midepoch_resume_tags_partial_epoch(tmp_path):
    """The resumed epoch's train aggregates cover only the replayed
    suffix of the epoch — its metrics_file record is tagged
    partial_epoch and it is excluded from early-stop scoring when no
    validation split exists (a partial epoch's train metrics are not
    comparable to full epochs'). Full epochs carry no tag."""
    import json as _json

    conf = {
        "checkpointer.save_every_steps": 3,
        "checkpointer.save_every_epochs": 0,
        # No validation split: the early-stop/scoring path under test
        # is the one that would otherwise score partial train metrics.
        "loader.dataset.num_validation_examples": 0,
        "validate": False,
    }
    exp = make_experiment(tmp_path, {"epochs": 1, **conf})
    exp.run()
    assert exp.checkpointer.latest_step() == 3  # mid-epoch (spe=4)
    exp.checkpointer.close()

    metrics_file = tmp_path / "metrics.jsonl"
    exp2 = make_experiment(
        tmp_path,
        {
            "epochs": 2,
            "metrics_file": str(metrics_file),
            # Early stop on train loss: the partial epoch must not be
            # scored (it would compare a 1-step mean vs 4-step means).
            "early_stop_metric": "loss",
            "early_stop_patience": 1,
            **conf,
        },
    )
    exp2.run()
    exp2.checkpointer.close()
    records = [
        _json.loads(line)
        for line in metrics_file.read_text().splitlines()
    ]
    assert [r["epoch"] for r in records] == [0, 1]
    assert records[0].get("partial_epoch") is True
    assert "partial_epoch" not in records[1]


# -- model-only round trips: the serving load path --------------------------
# save_model / load_model / load_exported_model are what the serving
# engine and EvalExperiment consume; their contract (exact values, dtype
# preservation, loud structure mismatch) is pinned here BEFORE the engine
# builds on it.


def _tiny_model(hidden=(16,), features=6, classes=4, seed=0):
    from zookeeper_tpu.core import configure as _configure
    from zookeeper_tpu.models.simple import Mlp

    model = Mlp()
    _configure(model, {"hidden_units": tuple(hidden)}, name="model")
    module = model.build((features,), classes)
    params, model_state = model.initialize(module, (features,), seed=seed)
    return model, module, params, model_state


def test_save_load_model_roundtrip_exact_and_dtypes(tmp_path):
    """params + model_state round-trip bit-exactly, preserving dtypes —
    including a non-float32 leaf (the bf16 deployment case)."""
    import jax
    import jax.numpy as jnp

    from zookeeper_tpu.training.checkpoint import load_model, save_model

    _, _, params, model_state = _tiny_model()
    # Mixed dtypes: cast one kernel to bfloat16 before saving.
    params = dict(params)
    first = sorted(params)[0]
    params[first] = jax.tree.map(
        lambda a: a.astype(jnp.bfloat16), params[first]
    )
    model_state = {"aux": {"counter": jnp.asarray(3, jnp.int32)}}
    path = str(tmp_path / "model")
    save_model(path, params, model_state)

    abstract = jax.eval_shape(lambda: (params, model_state))
    got_params, got_state = load_model(path, abstract[0], abstract[1])
    for want, got in zip(
        jax.tree.leaves(params), jax.tree.leaves(got_params)
    ):
        assert want.dtype == got.dtype
        assert np.array_equal(jax.device_get(want), jax.device_get(got))
    assert got_state["aux"]["counter"].dtype == jnp.int32
    assert int(got_state["aux"]["counter"]) == 3


def test_save_model_overwrite_is_allowed(tmp_path):
    import jax

    from zookeeper_tpu.training.checkpoint import load_model, save_model

    _, _, params, model_state = _tiny_model()
    path = str(tmp_path / "model")
    save_model(path, params, model_state)
    _, _, params2, _ = _tiny_model(seed=1)
    save_model(path, params2, model_state)  # re-export must not crash
    abstract = jax.eval_shape(lambda: (params2, model_state))
    got, _ = load_model(path, abstract[0], abstract[1])
    assert np.array_equal(
        jax.device_get(jax.tree.leaves(params2)[0]),
        jax.device_get(jax.tree.leaves(got)[0]),
    )


def test_load_exported_model_roundtrip(tmp_path):
    """The abstract-init consumer flow (eval / teacher / serving):
    zero-allocation target structure, exact restored values."""
    import jax

    from zookeeper_tpu.training.checkpoint import (
        load_exported_model,
        save_model,
    )

    model, module, params, model_state = _tiny_model()
    path = str(tmp_path / "model")
    save_model(path, params, model_state)
    got_params, got_state = load_exported_model(path, model, module, (6,))
    for want, got in zip(
        jax.tree.leaves(params), jax.tree.leaves(got_params)
    ):
        assert want.dtype == got.dtype
        assert np.array_equal(jax.device_get(want), jax.device_get(got))


def test_load_model_structure_mismatch_is_clear(tmp_path):
    """Restoring into a differently-shaped model must raise the
    actionable structure-mismatch error, not a raw orbax traceback."""
    import jax

    from zookeeper_tpu.training.checkpoint import (
        load_exported_model,
        save_model,
    )

    model, module, params, model_state = _tiny_model(hidden=(16,))
    path = str(tmp_path / "model")
    save_model(path, params, model_state)
    other_model, other_module, _, _ = _tiny_model(hidden=(16, 16))
    with pytest.raises(ValueError, match="does not match the target model"):
        load_exported_model(path, other_model, other_module, (6,))


def test_select_inference_weights_policy():
    from zookeeper_tpu.training.checkpoint import select_inference_weights

    raw, ema = {"w": 1}, {"w": 2}
    assert select_inference_weights(raw, ema, "raw") is raw
    assert select_inference_weights(raw, ema, "ema") is ema
    assert select_inference_weights(raw, ema, "auto") is ema
    assert select_inference_weights(raw, None, "auto") is raw
    assert select_inference_weights(raw, None, "raw") is raw
    with pytest.raises(ValueError, match="no ema_params"):
        select_inference_weights(raw, None, "ema")
    with pytest.raises(ValueError, match="unknown"):
        select_inference_weights(raw, ema, "fastest")


def test_load_inference_model_export_and_manager_dir(tmp_path):
    """ONE loader serves both deployment artifacts: a save_model export
    and a full Checkpointer directory (latest step), with EMA-vs-raw
    selection and structure validation."""
    import jax

    from zookeeper_tpu.training.checkpoint import load_inference_model

    exp = make_experiment(
        tmp_path,
        {
            "epochs": 1,
            "ema_decay": 0.9,
            "validate": False,
            "loader.dataset.num_validation_examples": 0,
            "export_model_to": str(tmp_path / "export"),
        },
    )
    exp.run()
    state = exp.final_state
    raw = jax.device_get(state.params)
    ema = jax.device_get(state.ema_params)

    def same(a, b):
        return all(
            np.array_equal(x, y)
            for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b))
        )

    # Model-only export ships the EMA (the "ship weights" artifact).
    p_exp, _ = load_inference_model(str(tmp_path / "export"))
    assert same(p_exp, ema)
    # Full manager dir: explicit raw / ema / auto selection.
    ckpt = str(tmp_path / "ckpt")
    p_raw, _ = load_inference_model(ckpt, weights="raw")
    p_ema, _ = load_inference_model(ckpt, weights="ema")
    p_auto, ms = load_inference_model(ckpt, weights="auto")
    assert same(p_raw, raw) and same(p_ema, ema) and same(p_auto, ema)
    # Structure validation against a *_like tree.
    with pytest.raises(ValueError, match="does not match the target model"):
        load_inference_model(
            ckpt, params_like={"not": {"this": np.zeros(1)}}
        )
    # Clear error on a path with no checkpoint at all.
    with pytest.raises(ValueError, match="No restorable checkpoint"):
        load_inference_model(str(tmp_path / "nowhere"))


@pytest.mark.slow
def test_eval_experiment_scores_selected_weights(tmp_path):
    """The EvalExperiment fix: it can now score the EMA (or raw) weights
    straight from a full training checkpoint directory, matching the
    export-based score exactly."""
    from zookeeper_tpu.core import configure as _configure
    from zookeeper_tpu.training import EvalExperiment

    exp = make_experiment(
        tmp_path,
        {
            "epochs": 1,
            "ema_decay": 0.9,
            "export_model_to": str(tmp_path / "export"),
        },
    )
    exp.run()

    def evaluate(checkpoint, weights):
        ev = EvalExperiment()
        _configure(
            ev,
            {
                "loader.dataset": "SyntheticMnist",
                "loader.dataset.num_train_examples": 128,
                "loader.dataset.num_validation_examples": 32,
                "loader.preprocessing": "ImageClassificationPreprocessing",
                "loader.preprocessing.height": 28,
                "loader.preprocessing.width": 28,
                "loader.preprocessing.channels": 1,
                "loader.host_index": 0,
                "loader.host_count": 1,
                "model": "Mlp",
                "model.hidden_units": (16,),
                "batch_size": 32,
                "verbose": False,
                "checkpoint": checkpoint,
                "weights": weights,
            },
            name="eval",
        )
        return ev.run()

    ema_from_ckpt = evaluate(str(tmp_path / "ckpt"), "ema")
    ema_from_export = evaluate(str(tmp_path / "export"), "auto")
    raw_from_ckpt = evaluate(str(tmp_path / "ckpt"), "raw")
    assert ema_from_ckpt == ema_from_export
    assert raw_from_ckpt["loss"] != ema_from_ckpt["loss"]
    with pytest.raises(ValueError, match="unknown"):
        evaluate(str(tmp_path / "ckpt"), "fastest")


def test_load_inference_model_same_structure_wrong_widths_is_clear(tmp_path):
    """A checkpoint with the SAME tree structure but different layer
    widths must fail the like-validation with the clear error, not
    surface later as an opaque XLA shape error inside apply."""
    import jax

    from zookeeper_tpu.training.checkpoint import (
        load_inference_model,
        save_model,
    )

    model16, module16, params16, state16 = _tiny_model(hidden=(16,))
    path = str(tmp_path / "model16")
    save_model(path, params16, state16)
    model32, module32, _, _ = _tiny_model(hidden=(32,))
    abstract = jax.eval_shape(
        lambda: model32.initialize(module32, (6,))
    )
    with pytest.raises(ValueError, match="leaf shape mismatch"):
        load_inference_model(
            path, params_like=abstract[0], model_state_like=abstract[1]
        )
