import numpy as np
import pytest

from zookeeper_tpu.core import configure
from zookeeper_tpu.training import Checkpointer, TrainingExperiment


def make_experiment(tmp_path, extra=None):
    exp = TrainingExperiment()
    conf = {
        "loader.dataset": "SyntheticMnist",
        "loader.dataset.num_train_examples": 128,
        "loader.dataset.num_validation_examples": 32,
        "loader.preprocessing": "ImageClassificationPreprocessing",
        "loader.preprocessing.height": 28,
        "loader.preprocessing.width": 28,
        "loader.preprocessing.channels": 1,
        "loader.host_index": 0,
        "loader.host_count": 1,
        "model": "Mlp",
        "model.hidden_units": (16,),
        "batch_size": 32,
        "epochs": 2,
        "verbose": False,
        "checkpointer.directory": str(tmp_path / "ckpt"),
        "checkpointer.synchronous": True,
        **(extra or {}),
    }
    configure(exp, conf, name="experiment")
    return exp


def test_checkpointer_disabled_by_default():
    ckpt = Checkpointer()
    configure(ckpt, {}, name="ckpt")
    assert not ckpt.enabled
    assert ckpt.save(None) is False
    assert ckpt.restore_state("anything") == "anything"


def test_save_and_restore_roundtrip(tmp_path):
    exp = make_experiment(tmp_path)
    exp.run()
    ckpt = exp.checkpointer
    assert ckpt.latest_step() == 8  # 2 epochs * 4 steps.

    # A fresh experiment with the same directory resumes: epochs already
    # done, so run() trains zero additional epochs and state matches.
    exp2 = make_experiment(tmp_path)
    history2 = exp2.run()
    assert history2["train"] == []
    import jax

    assert int(jax.device_get(exp2.final_state.step)) == 8
    for a, b in zip(
        jax.tree.leaves(exp.final_state.params),
        jax.tree.leaves(exp2.final_state.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    exp.checkpointer.close()
    exp2.checkpointer.close()


def test_resume_continues_training(tmp_path):
    # Train 1 epoch, then "crash"; resume with epochs=3 trains 2 more.
    exp = make_experiment(tmp_path, {"epochs": 1})
    exp.run()
    assert exp.checkpointer.latest_step() == 4
    exp.checkpointer.close()

    exp2 = make_experiment(tmp_path, {"epochs": 3})
    history = exp2.run()
    assert len(history["train"]) == 2  # Epochs 1 and 2 only.
    import jax

    assert int(jax.device_get(exp2.final_state.step)) == 12
    exp2.checkpointer.close()


def test_restore_disabled_starts_fresh(tmp_path):
    exp = make_experiment(tmp_path, {"epochs": 1})
    exp.run()
    exp.checkpointer.close()
    exp2 = make_experiment(
        tmp_path, {"epochs": 1, "checkpointer.restore": False}
    )
    history = exp2.run()
    assert len(history["train"]) == 1  # Trained from scratch.
    exp2.checkpointer.close()


def test_metrics_file_written(tmp_path):
    import json

    path = tmp_path / "metrics.jsonl"
    exp = make_experiment(
        tmp_path,
        {"metrics_file": str(path), "checkpointer.directory": None},
    )
    exp.run()
    lines = [json.loads(l) for l in path.read_text().splitlines()]
    assert len(lines) == 2
    assert {"epoch", "loss", "accuracy", "examples_per_sec"} <= set(lines[0])
    assert "val_accuracy" in lines[0]


def _tiny_state(value: float, step: int):
    """A minimal TrainState-shaped object for direct Checkpointer tests."""
    import jax.numpy as jnp
    import optax

    from zookeeper_tpu.training import TrainState

    state = TrainState.create(
        apply_fn=lambda *a, **k: None,
        params={"w": jnp.full((2,), value)},
        model_state={},
        tx=optax.sgd(0.1),
    )
    return state.replace(step=jnp.asarray(step))


def test_keep_best_retention_and_best_step(tmp_path):
    """keep_best_metric ranks checkpoints (Keras save_best_only parity):
    max_to_keep=1 keeps the best-accuracy save, not the latest."""
    ckpt = Checkpointer()
    configure(
        ckpt,
        {
            "directory": str(tmp_path / "best"),
            "max_to_keep": 1,
            "synchronous": True,
            "keep_best_metric": "accuracy",
        },
        name="ckpt",
    )
    for step, acc in ((1, 0.2), (2, 0.9), (3, 0.5)):
        ckpt.save(_tiny_state(float(step), step), metrics={"accuracy": acc})
    ckpt.wait()
    assert ckpt.best_step() == 2
    # The best save survives retention and restores with its params.
    restored = ckpt.restore_state(_tiny_state(0.0, 0))
    assert int(np.asarray(restored.step)) == 2
    np.testing.assert_allclose(np.asarray(restored.params["w"]), 2.0)
    ckpt.close()


def test_keep_best_requires_metrics(tmp_path):
    ckpt = Checkpointer()
    configure(
        ckpt,
        {
            "directory": str(tmp_path / "best2"),
            "synchronous": True,
            "keep_best_metric": "accuracy",
        },
        name="ckpt",
    )
    with pytest.raises(ValueError, match="carries no such metric"):
        ckpt.save(_tiny_state(1.0, 1))
    with pytest.raises(ValueError, match="carries no such metric"):
        ckpt.save(_tiny_state(1.0, 1), metrics={"loss": 0.5})
    ckpt.close()


def test_experiment_passes_metrics_to_best_checkpointing(tmp_path):
    """End-to-end: a TrainingExperiment with keep_best_metric ranks epoch
    saves by validation accuracy without erroring."""
    exp = make_experiment(
        tmp_path,
        {"checkpointer.keep_best_metric": "accuracy"},
    )
    exp.run()
    assert exp.checkpointer.best_step() is not None
    exp.checkpointer.close()


def test_keep_best_rank_saves_only_on_validated_epochs(tmp_path):
    """With keep_best_metric + validate_every=2, non-validation epochs
    must not rank-save (train metrics are not comparable to val metrics
    on one scale): only validated epochs appear in the manager."""
    exp = make_experiment(
        tmp_path,
        {
            "epochs": 4,
            "steps_per_epoch": 2,
            "validate_every": 2,
            "checkpointer.keep_best_metric": "accuracy",
            "checkpointer.max_to_keep": 10,
        },
    )
    exp.run()
    mgr = exp.checkpointer._manager()
    steps = sorted(mgr.all_steps())
    # Saves at the end of epochs 2 and 4 only (2 steps/epoch -> 4, 8).
    assert steps == [4, 8]
    exp.checkpointer.close()
