"""Training-loop observability: host-span capture across a real run
(the acceptance artifact — data_wait/dispatch/readback/checkpoint spans
covering full slabs, exported as Chrome trace-event JSON), the live
/metrics endpoint, and the profiling-window try/finally fix."""

import json
import urllib.request

import pytest

from zookeeper_tpu.core import configure
from zookeeper_tpu.observability import trace
from zookeeper_tpu.training import TrainingExperiment


@pytest.fixture(autouse=True)
def _clean_tracer():
    trace.disable()
    yield
    trace.disable()


def make_experiment(tmp_path, extra=None):
    exp = TrainingExperiment()
    conf = {
        "loader.dataset": "SyntheticMnist",
        "loader.dataset.num_train_examples": 256,
        "loader.dataset.num_validation_examples": 0,
        "loader.preprocessing": "ImageClassificationPreprocessing",
        "loader.preprocessing.height": 28,
        "loader.preprocessing.width": 28,
        "loader.preprocessing.channels": 1,
        "loader.host_index": 0,
        "loader.host_count": 1,
        "model": "Mlp",
        "model.hidden_units": (32,),
        "batch_size": 32,
        "epochs": 1,
        "validate": False,
        "verbose": False,
        "checkpointer.directory": str(tmp_path / "ckpt"),
        "checkpointer.synchronous": True,
        **(extra or {}),
    }
    configure(exp, conf, name="obs_experiment")
    return exp


def _spans(doc, name):
    return [
        e
        for e in doc["traceEvents"]
        if e["ph"] == "X" and e["name"] == name
    ]


def test_fused_run_exports_full_slab_phase_trace(tmp_path):
    """The acceptance artifact: a fused (unroll>1) run's host trace is
    valid Chrome trace-event JSON covering >= one full slab with
    data_wait / dispatch / readback / checkpoint spans, each carrying
    step/slab attribution."""
    trace_path = tmp_path / "host_trace.json"
    exp = make_experiment(
        tmp_path,
        {
            "unroll": 2,
            "log_every": 2,
            "checkpointer.save_every_steps": 4,
            "trace_export": str(trace_path),
        },
    )
    exp.run()
    doc = json.loads(trace_path.read_text())
    # 256 examples / 32 batch = 8 steps = 4 slabs of 2.
    dispatch = _spans(doc, "dispatch")
    assert len(dispatch) == 4
    assert [e["args"]["slab"] for e in dispatch] == [0, 1, 2, 3]
    assert all("step" in e["args"] for e in dispatch)
    data_wait = _spans(doc, "data_wait")
    assert len(data_wait) >= 4  # one per slab pull (+ exhaustion probe)
    assert _spans(doc, "readback")  # log_every + epoch-end readbacks
    ckpt = _spans(doc, "checkpoint")
    assert len(ckpt) == 2  # save_every_steps=4 over 8 steps
    # The nested checkpointer-internal span rides the same timeline.
    assert _spans(doc, "ckpt_sync_save")
    # Every complete event is well-formed for the trace viewers.
    for e in doc["traceEvents"]:
        if e["ph"] == "X":
            assert e["ts"] >= 0 and e["dur"] >= 0
    # Run-scoped enablement: teardown restored the disabled state.
    assert not trace.enabled()


def test_eager_run_exports_phase_trace(tmp_path):
    trace_path = tmp_path / "host_trace.json"
    exp = make_experiment(
        tmp_path, {"log_every": 4, "trace_export": str(trace_path)}
    )
    exp.run()
    doc = json.loads(trace_path.read_text())
    assert len(_spans(doc, "dispatch")) == 8  # one per eager step
    assert _spans(doc, "data_wait")
    assert _spans(doc, "readback")


def test_trace_export_written_even_when_run_raises(tmp_path):
    """Teardown exports the trace on the failure path too — the trace
    of a crashed run is the one you actually want to look at."""
    from zookeeper_tpu.resilience import faults

    trace_path = tmp_path / "host_trace.json"
    exp = make_experiment(tmp_path, {"trace_export": str(trace_path)})
    with faults.injected(faults.FaultPlan(kill_at_step=3)):
        with pytest.raises(faults.Preempted):
            exp.run()
    doc = json.loads(trace_path.read_text())
    assert _spans(doc, "dispatch")
    # The injected kill is a self-explaining instant on the timeline.
    injected = [
        e
        for e in doc["traceEvents"]
        if e["ph"] == "i" and e["name"] == "fault_injected"
    ]
    assert injected and injected[0]["args"]["kind"] == "kill_at_step"
    assert not trace.enabled()


def test_metrics_endpoint_live_during_run(tmp_path):
    """metrics_port=0 brings up /metrics for the run's lifetime: a
    scrape from inside the run (hooked off the epoch writer call) sees
    the process-global gauges and the experiment's published epoch
    rates; the server is gone after teardown."""
    exp = make_experiment(tmp_path, {"epochs": 2, "metrics_port": 0})
    spe = 8  # 256 / 32
    scraped = {}
    orig_write = exp.writer.write_scalars

    def spy(step, values):
        server = getattr(exp, "obs_server", None)
        if (
            "body" not in scraped
            and server is not None
            and any(k.startswith("train_epoch/") for k in values)
            and step >= 2 * spe
        ):
            base = f"http://127.0.0.1:{server.port}"
            scraped["body"] = (
                urllib.request.urlopen(base + "/metrics").read().decode()
            )
            scraped["statusz"] = json.loads(
                urllib.request.urlopen(base + "/statusz").read()
            )
        return orig_write(step, values)

    exp.writer.write_scalars = spy
    exp.run()
    assert "body" in scraped, "epoch-boundary scrape never fired"
    body = scraped["body"]
    # Epoch-derived rates (published at the END of epoch 1, scraped at
    # epoch 2's writer call) and the process-global prefetch gauge.
    assert "zk_train_loss" in body
    assert "zk_train_examples_per_sec" in body
    assert "zk_train_epoch 1" in body
    assert "zk_prefetch_occupancy" in body
    status = scraped["statusz"]
    assert status["training"]["model"] == "Mlp"
    assert status["training"]["epochs"] == 2
    # Teardown stopped the server and cleared the handle.
    assert getattr(exp, "obs_server", None) is None


def test_prefetch_thread_is_named(tmp_path):
    """Satellite: the device-prefetch producer runs under a zk- name so
    py-spy / host-trace attribution reads as a subsystem, not
    Thread-N."""
    import threading
    import time

    from zookeeper_tpu.data.pipeline import prefetch_to_device

    seen = {}
    release = threading.Event()

    def slow_source():
        for i in range(4):
            yield {"x": i}
            release.wait(1.0)  # keep the producer alive to be observed

    it = prefetch_to_device(slow_source(), size=1)
    first = next(it)
    deadline = time.perf_counter() + 2.0
    while time.perf_counter() < deadline and "name" not in seen:
        names = [t.name for t in threading.enumerate()]
        hits = [n for n in names if n.startswith("zk-prefetch")]
        if hits:
            seen["name"] = hits[0]
        else:
            time.sleep(0.01)
    release.set()
    for _ in it:
        pass
    assert seen.get("name") == "zk-prefetch"
    assert first["x"] == 0


def test_profiling_window_closed_on_mid_capture_exception(
    tmp_path, monkeypatch
):
    """Satellite fix: an exception raised while the jax.profiler
    capture window is open (here: an injected preemption between
    p_start and p_stop) must still stop the trace in teardown —
    previously the window leaked and poisoned the next start_trace."""
    import jax

    from zookeeper_tpu.resilience import faults

    calls = {"start": 0, "stop": 0}
    real_start = jax.profiler.start_trace
    real_stop = jax.profiler.stop_trace

    def start(*a, **k):
        calls["start"] += 1
        return real_start(*a, **k)

    def stop(*a, **k):
        calls["stop"] += 1
        return real_stop(*a, **k)

    monkeypatch.setattr(jax.profiler, "start_trace", start)
    monkeypatch.setattr(jax.profiler, "stop_trace", stop)

    exp = make_experiment(
        tmp_path, {"profile_dir": str(tmp_path / "prof")}
    )
    # Eager window is steps p_start=4..p_stop=7 (spe=8): kill at global
    # step 6, strictly inside the open capture.
    with faults.injected(faults.FaultPlan(kill_at_step=6)):
        with pytest.raises(faults.Preempted):
            exp.run()
    assert calls["start"] == 1
    assert calls["stop"] == 1, (
        "teardown must close the dangling capture window"
    )
    assert not getattr(exp, "_jax_trace_active", False)
    # And the next capture starts cleanly in the same process.
    real_start(str(tmp_path / "prof2"))
    real_stop()


def test_profiling_window_still_closed_on_clean_run(tmp_path, monkeypatch):
    """The happy path stops the trace exactly once (in the loop, not
    again in teardown)."""
    import jax

    calls = {"start": 0, "stop": 0}
    real_start = jax.profiler.start_trace
    real_stop = jax.profiler.stop_trace
    monkeypatch.setattr(
        jax.profiler,
        "start_trace",
        lambda *a, **k: (calls.__setitem__("start", calls["start"] + 1),
                         real_start(*a, **k))[1],
    )
    monkeypatch.setattr(
        jax.profiler,
        "stop_trace",
        lambda *a, **k: (calls.__setitem__("stop", calls["stop"] + 1),
                         real_stop(*a, **k))[1],
    )
    exp = make_experiment(
        tmp_path, {"profile_dir": str(tmp_path / "prof")}
    )
    exp.run()
    assert calls["start"] == 1
    assert calls["stop"] == 1


# -- device-side ledger / step-time watchdog / live MFU (docs §14) -------


def test_live_run_publishes_step_time_and_mfu_gauges(tmp_path):
    """The acceptance artifact: a real (eager, log_every-synced)
    training run publishes zk_train_step_time_ms and zk_train_mfu from
    ledger FLOPs / measured step time / the shared reference peak —
    and the gauge agrees with the hand computation from its own
    inputs."""
    from zookeeper_tpu.observability.ledger import default_ledger, mfu
    from zookeeper_tpu.observability.peaks import reference_peak_flops

    exp = make_experiment(tmp_path, {"log_every": 2})
    exp.run()
    reg = exp.obs_registry
    step_ms = reg.gauge("zk_train_step_time_ms").value
    assert step_ms > 0
    mfu_value = reg.gauge("zk_train_mfu").value
    rec = default_ledger().latest("train_step")
    assert rec is not None and rec.dispatches > 0
    if rec.flops:
        expected = mfu(rec.flops, step_ms / 1e3, reference_peak_flops()[0])
        assert mfu_value == pytest.approx(expected, rel=1e-6)
        assert 0 < mfu_value < 1
    else:
        assert mfu_value == -1  # unknown renders as the sentinel


def test_fused_run_ledgers_multi_step_and_divides_flops_by_unroll(
    tmp_path,
):
    """The fused (unroll>1) loop's MFU divides the slab executable's
    FLOPs by the unroll factor — per-STEP utilization, same definition
    as the eager loop."""
    from zookeeper_tpu.observability.ledger import default_ledger, mfu
    from zookeeper_tpu.observability.peaks import reference_peak_flops

    exp = make_experiment(tmp_path, {"unroll": 2, "log_every": 2})
    exp.run()
    rec = default_ledger().latest("multi_step")
    assert rec is not None
    assert rec.compile_ms is not None
    reg = exp.obs_registry
    step_ms = reg.gauge("zk_train_step_time_ms").value
    assert step_ms > 0
    if rec.flops:
        expected = mfu(
            rec.flops / 2, step_ms / 1e3, reference_peak_flops()[0]
        )
        assert reg.gauge("zk_train_mfu").value == pytest.approx(
            expected, rel=1e-6
        )


def test_mfu_divides_by_recorded_slab_size_not_configured_unroll(
    tmp_path,
):
    """A partial first slab (mid-epoch resume, spe < unroll) compiles
    the recorded multi_step program for k < unroll steps; the MFU
    divisor must be the program's actual slab size, not the config."""
    from zookeeper_tpu.observability.ledger import ProgramRecord, mfu
    from zookeeper_tpu.observability.peaks import reference_peak_flops

    exp = make_experiment(tmp_path, {"unroll": 8})

    class FakeProgram:
        ledger_entry = ProgramRecord(
            kind="multi_step", key="k", flops=9e9, attrs={"steps": 3}
        )

    exp._publish_mfu(0.5, FakeProgram())
    expected = mfu(9e9 / 3, 0.5, reference_peak_flops()[0])
    assert exp.obs_registry.gauge("zk_train_mfu").value == pytest.approx(
        expected, rel=1e-6
    )


def test_steady_run_fires_no_step_anomalies(tmp_path):
    """False-positive half of the watchdog contract at integration
    level: a short steady run's sync-stream observations sit inside
    the warmup window, so the anomaly counter is exactly zero."""
    exp = make_experiment(tmp_path, {"log_every": 2})
    exp.run()
    reg = exp.obs_registry
    assert reg.counter(
        "zk_step_time_anomalies_total", labels={"stream": "train_step"}
    ).value == 0
    # The dispatch stream baselined (its EWMA gauge moved off zero).
    assert reg.gauge(
        "zk_step_time_ewma_ms", labels={"stream": "train_dispatch"}
    ).value > 0


def test_metrics_endpoint_serves_mfu_and_hbm_series(tmp_path):
    """CI-smoke contract: with metrics_port on, the new gauges render
    as valid exposition text and the zk-device-probe's zk_hbm_* series
    exist from the first scrape (-1 sentinel on statless backends)."""
    import re
    import urllib.request

    seen = {}
    exp = make_experiment(tmp_path, {"log_every": 2, "metrics_port": 0})

    # Scrape DURING the run via the checkpointer save hook (the
    # endpoint tears down at run end).
    orig_save = exp.checkpointer.save

    def save_and_scrape(*a, **k):
        if "body" not in seen and getattr(exp, "obs_server", None):
            url = f"http://127.0.0.1:{exp.obs_server.port}/metrics"
            seen["body"] = urllib.request.urlopen(url).read().decode()
        return orig_save(*a, **k)

    exp.checkpointer.save = save_and_scrape
    exp.run()
    body = seen["body"]
    assert "zk_hbm_bytes_in_use" in body
    line_re = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? [^ ]+$")
    samples = [
        l for l in body.splitlines() if l and not l.startswith("#")
    ]
    assert samples and all(line_re.match(l) for l in samples)
    assert getattr(exp, "obs_probe", None) is None  # torn down


def test_trace_export_with_profile_dir_logs_paired_artifacts(
    tmp_path, capsys
):
    """Satellite: the docs §13 Perfetto merge recipe is automated —
    one teardown writes the host spans AND closes the device capture,
    logging both artifact locations as a pair."""
    prof = tmp_path / "prof"
    out = tmp_path / "host_trace.json"
    exp = make_experiment(
        tmp_path,
        {
            "trace_export": str(out),
            "profile_dir": str(prof),
            "verbose": True,
        },
    )
    exp.run()
    assert out.exists()
    doc = json.loads(out.read_text())
    assert doc["traceEvents"]
    text = capsys.readouterr().out
    assert "paired trace artifacts" in text
    assert str(out) in text and str(prof) in text
    assert not getattr(exp, "_jax_trace_active", False)


# -- flight recorder (docs/DESIGN.md §16) ---------------------------------


@pytest.mark.chaos
def test_nan_halt_and_recovery_each_write_a_bundle(tmp_path):
    """flight_recorder_dir= arms the recorder for the run: the NaN
    halt bundles its evidence at the readback boundary, and the
    supervisor writes one more bundle per recovery — with the recorder
    still installed across the restart (run() teardown leaves it in
    place deliberately)."""
    import os

    from zookeeper_tpu.observability import recorder as recorder_mod
    from zookeeper_tpu.resilience import faults, run_with_recovery

    bundles_dir = tmp_path / "bundles"
    exp = make_experiment(
        tmp_path,
        {
            "nan_policy": "halt",
            "log_every": 1,
            "checkpointer.save_every_steps": 1,
            "flight_recorder_dir": str(bundles_dir),
            "flight_recorder_interval_s": 0.0,
        },
    )
    prior = recorder_mod.get_recorder()
    try:
        with faults.injected(faults.FaultPlan(nan_at_step=3)):
            result = run_with_recovery(
                exp, max_restarts=1, backoff_s=0.0, sleep=lambda s: None
            )
        assert result.restarts == 1
        rec = exp.flight_recorder
        kinds = [
            json.load(open(os.path.join(b, "manifest.json")))["trigger"][
                "kind"
            ]
            for b in rec.bundles()
        ]
        assert "nan_halt" in kinds, kinds
        assert "supervisor_restart" in kinds, kinds
        nan_bundle = rec.bundles()[kinds.index("nan_halt")]
        manifest = json.load(
            open(os.path.join(nan_bundle, "manifest.json"))
        )
        assert manifest["trigger"]["attrs"]["skipped_steps"] >= 1
        # The bundle carries the run's /statusz section + metrics text.
        statusz = json.load(
            open(os.path.join(nan_bundle, "statusz.json"))
        )
        assert statusz["training"]["model"] == "Mlp"
        assert os.path.getsize(os.path.join(nan_bundle, "metrics.prom")) >= 0
    finally:
        (
            recorder_mod.install(prior)
            if prior is not None
            else recorder_mod.uninstall()
        )
