"""Training-loop observability: host-span capture across a real run
(the acceptance artifact — data_wait/dispatch/readback/checkpoint spans
covering full slabs, exported as Chrome trace-event JSON), the live
/metrics endpoint, and the profiling-window try/finally fix."""

import json
import urllib.request

import pytest

from zookeeper_tpu.core import configure
from zookeeper_tpu.observability import trace
from zookeeper_tpu.training import TrainingExperiment


@pytest.fixture(autouse=True)
def _clean_tracer():
    trace.disable()
    yield
    trace.disable()


def make_experiment(tmp_path, extra=None):
    exp = TrainingExperiment()
    conf = {
        "loader.dataset": "SyntheticMnist",
        "loader.dataset.num_train_examples": 256,
        "loader.dataset.num_validation_examples": 0,
        "loader.preprocessing": "ImageClassificationPreprocessing",
        "loader.preprocessing.height": 28,
        "loader.preprocessing.width": 28,
        "loader.preprocessing.channels": 1,
        "loader.host_index": 0,
        "loader.host_count": 1,
        "model": "Mlp",
        "model.hidden_units": (32,),
        "batch_size": 32,
        "epochs": 1,
        "validate": False,
        "verbose": False,
        "checkpointer.directory": str(tmp_path / "ckpt"),
        "checkpointer.synchronous": True,
        **(extra or {}),
    }
    configure(exp, conf, name="obs_experiment")
    return exp


def _spans(doc, name):
    return [
        e
        for e in doc["traceEvents"]
        if e["ph"] == "X" and e["name"] == name
    ]


def test_fused_run_exports_full_slab_phase_trace(tmp_path):
    """The acceptance artifact: a fused (unroll>1) run's host trace is
    valid Chrome trace-event JSON covering >= one full slab with
    data_wait / dispatch / readback / checkpoint spans, each carrying
    step/slab attribution."""
    trace_path = tmp_path / "host_trace.json"
    exp = make_experiment(
        tmp_path,
        {
            "unroll": 2,
            "log_every": 2,
            "checkpointer.save_every_steps": 4,
            "trace_export": str(trace_path),
        },
    )
    exp.run()
    doc = json.loads(trace_path.read_text())
    # 256 examples / 32 batch = 8 steps = 4 slabs of 2.
    dispatch = _spans(doc, "dispatch")
    assert len(dispatch) == 4
    assert [e["args"]["slab"] for e in dispatch] == [0, 1, 2, 3]
    assert all("step" in e["args"] for e in dispatch)
    data_wait = _spans(doc, "data_wait")
    assert len(data_wait) >= 4  # one per slab pull (+ exhaustion probe)
    assert _spans(doc, "readback")  # log_every + epoch-end readbacks
    ckpt = _spans(doc, "checkpoint")
    assert len(ckpt) == 2  # save_every_steps=4 over 8 steps
    # The nested checkpointer-internal span rides the same timeline.
    assert _spans(doc, "ckpt_sync_save")
    # Every complete event is well-formed for the trace viewers.
    for e in doc["traceEvents"]:
        if e["ph"] == "X":
            assert e["ts"] >= 0 and e["dur"] >= 0
    # Run-scoped enablement: teardown restored the disabled state.
    assert not trace.enabled()


def test_eager_run_exports_phase_trace(tmp_path):
    trace_path = tmp_path / "host_trace.json"
    exp = make_experiment(
        tmp_path, {"log_every": 4, "trace_export": str(trace_path)}
    )
    exp.run()
    doc = json.loads(trace_path.read_text())
    assert len(_spans(doc, "dispatch")) == 8  # one per eager step
    assert _spans(doc, "data_wait")
    assert _spans(doc, "readback")


def test_trace_export_written_even_when_run_raises(tmp_path):
    """Teardown exports the trace on the failure path too — the trace
    of a crashed run is the one you actually want to look at."""
    from zookeeper_tpu.resilience import faults

    trace_path = tmp_path / "host_trace.json"
    exp = make_experiment(tmp_path, {"trace_export": str(trace_path)})
    with faults.injected(faults.FaultPlan(kill_at_step=3)):
        with pytest.raises(faults.Preempted):
            exp.run()
    doc = json.loads(trace_path.read_text())
    assert _spans(doc, "dispatch")
    # The injected kill is a self-explaining instant on the timeline.
    injected = [
        e
        for e in doc["traceEvents"]
        if e["ph"] == "i" and e["name"] == "fault_injected"
    ]
    assert injected and injected[0]["args"]["kind"] == "kill_at_step"
    assert not trace.enabled()


def test_metrics_endpoint_live_during_run(tmp_path):
    """metrics_port=0 brings up /metrics for the run's lifetime: a
    scrape from inside the run (hooked off the epoch writer call) sees
    the process-global gauges and the experiment's published epoch
    rates; the server is gone after teardown."""
    exp = make_experiment(tmp_path, {"epochs": 2, "metrics_port": 0})
    spe = 8  # 256 / 32
    scraped = {}
    orig_write = exp.writer.write_scalars

    def spy(step, values):
        server = getattr(exp, "obs_server", None)
        if (
            "body" not in scraped
            and server is not None
            and any(k.startswith("train_epoch/") for k in values)
            and step >= 2 * spe
        ):
            base = f"http://127.0.0.1:{server.port}"
            scraped["body"] = (
                urllib.request.urlopen(base + "/metrics").read().decode()
            )
            scraped["statusz"] = json.loads(
                urllib.request.urlopen(base + "/statusz").read()
            )
        return orig_write(step, values)

    exp.writer.write_scalars = spy
    exp.run()
    assert "body" in scraped, "epoch-boundary scrape never fired"
    body = scraped["body"]
    # Epoch-derived rates (published at the END of epoch 1, scraped at
    # epoch 2's writer call) and the process-global prefetch gauge.
    assert "zk_train_loss" in body
    assert "zk_train_examples_per_sec" in body
    assert "zk_train_epoch 1" in body
    assert "zk_prefetch_occupancy" in body
    status = scraped["statusz"]
    assert status["training"]["model"] == "Mlp"
    assert status["training"]["epochs"] == 2
    # Teardown stopped the server and cleared the handle.
    assert getattr(exp, "obs_server", None) is None


def test_prefetch_thread_is_named(tmp_path):
    """Satellite: the device-prefetch producer runs under a zk- name so
    py-spy / host-trace attribution reads as a subsystem, not
    Thread-N."""
    import threading
    import time

    from zookeeper_tpu.data.pipeline import prefetch_to_device

    seen = {}
    release = threading.Event()

    def slow_source():
        for i in range(4):
            yield {"x": i}
            release.wait(1.0)  # keep the producer alive to be observed

    it = prefetch_to_device(slow_source(), size=1)
    first = next(it)
    deadline = time.perf_counter() + 2.0
    while time.perf_counter() < deadline and "name" not in seen:
        names = [t.name for t in threading.enumerate()]
        hits = [n for n in names if n.startswith("zk-prefetch")]
        if hits:
            seen["name"] = hits[0]
        else:
            time.sleep(0.01)
    release.set()
    for _ in it:
        pass
    assert seen.get("name") == "zk-prefetch"
    assert first["x"] == 0


def test_profiling_window_closed_on_mid_capture_exception(
    tmp_path, monkeypatch
):
    """Satellite fix: an exception raised while the jax.profiler
    capture window is open (here: an injected preemption between
    p_start and p_stop) must still stop the trace in teardown —
    previously the window leaked and poisoned the next start_trace."""
    import jax

    from zookeeper_tpu.resilience import faults

    calls = {"start": 0, "stop": 0}
    real_start = jax.profiler.start_trace
    real_stop = jax.profiler.stop_trace

    def start(*a, **k):
        calls["start"] += 1
        return real_start(*a, **k)

    def stop(*a, **k):
        calls["stop"] += 1
        return real_stop(*a, **k)

    monkeypatch.setattr(jax.profiler, "start_trace", start)
    monkeypatch.setattr(jax.profiler, "stop_trace", stop)

    exp = make_experiment(
        tmp_path, {"profile_dir": str(tmp_path / "prof")}
    )
    # Eager window is steps p_start=4..p_stop=7 (spe=8): kill at global
    # step 6, strictly inside the open capture.
    with faults.injected(faults.FaultPlan(kill_at_step=6)):
        with pytest.raises(faults.Preempted):
            exp.run()
    assert calls["start"] == 1
    assert calls["stop"] == 1, (
        "teardown must close the dangling capture window"
    )
    assert not getattr(exp, "_jax_trace_active", False)
    # And the next capture starts cleanly in the same process.
    real_start(str(tmp_path / "prof2"))
    real_stop()


def test_profiling_window_still_closed_on_clean_run(tmp_path, monkeypatch):
    """The happy path stops the trace exactly once (in the loop, not
    again in teardown)."""
    import jax

    calls = {"start": 0, "stop": 0}
    real_start = jax.profiler.start_trace
    real_stop = jax.profiler.stop_trace
    monkeypatch.setattr(
        jax.profiler,
        "start_trace",
        lambda *a, **k: (calls.__setitem__("start", calls["start"] + 1),
                         real_start(*a, **k))[1],
    )
    monkeypatch.setattr(
        jax.profiler,
        "stop_trace",
        lambda *a, **k: (calls.__setitem__("stop", calls["stop"] + 1),
                         real_stop(*a, **k))[1],
    )
    exp = make_experiment(
        tmp_path, {"profile_dir": str(tmp_path / "prof")}
    )
    exp.run()
    assert calls["start"] == 1
    assert calls["stop"] == 1
