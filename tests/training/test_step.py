import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from zookeeper_tpu.core import configure
from zookeeper_tpu.models import Mlp, SimpleCnn
from zookeeper_tpu.training import (
    TrainState,
    make_eval_step,
    make_train_step,
)


def make_state(model_cls=Mlp, conf=None, input_shape=(6, 6, 1), num_classes=4):
    m = model_cls()
    configure(m, conf or {}, name="m")
    module = m.build(input_shape, num_classes=num_classes)
    params, model_state = m.initialize(module, input_shape)
    return TrainState.create(
        apply_fn=module.apply,
        params=params,
        model_state=model_state,
        tx=optax.adam(1e-2),
    )


def toy_batch(n=16, input_shape=(6, 6, 1), num_classes=4, seed=0):
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, num_classes, n)
    x = rng.normal(size=(n, *input_shape)).astype(np.float32)
    # Make inputs label-dependent so the model can learn.
    x += labels[:, None, None, None] * 0.5
    return {"input": jnp.asarray(x), "target": jnp.asarray(labels)}


def test_train_step_reduces_loss():
    state = make_state()
    step = jax.jit(make_train_step())
    batch = toy_batch()
    losses = []
    for _ in range(30):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] * 0.5
    assert int(state.step) == 30


def test_train_step_updates_batch_stats():
    state = make_state(SimpleCnn, {"features": (4,), "dense_units": ()})
    step = jax.jit(make_train_step())
    batch = toy_batch()
    new_state, _ = step(state, batch)
    before = jax.tree.leaves(state.model_state["batch_stats"])
    after = jax.tree.leaves(new_state.model_state["batch_stats"])
    assert any(not np.allclose(a, b) for a, b in zip(before, after))


def test_train_step_deterministic():
    batch = toy_batch()
    outs = []
    for _ in range(2):
        state = make_state()
        step = jax.jit(make_train_step(rng_seed=3))
        state, metrics = step(state, batch)
        outs.append(float(metrics["loss"]))
    assert outs[0] == outs[1]


def test_eval_step_metrics():
    state = make_state()
    eval_step = jax.jit(make_eval_step())
    metrics = eval_step(state, toy_batch())
    assert set(metrics) == {"loss", "accuracy"}
    assert 0.0 <= float(metrics["accuracy"]) <= 1.0


def test_metrics_contents():
    state = make_state()
    step = jax.jit(make_train_step())
    _, metrics = step(state, toy_batch())
    assert set(metrics) == {"loss", "accuracy", "grad_norm"}
    assert float(metrics["grad_norm"]) > 0


def test_weight_decay_applies_to_all_optimizers():
    from zookeeper_tpu.core import configure as _configure
    from zookeeper_tpu.training import Momentum, Sgd

    for cls in (Sgd, Momentum):
        opt = cls()
        _configure(opt, {"weight_decay": 0.1, "schedule.base_lr": 1.0}, name="o")
        tx = opt.build(total_steps=10)
        params = {"w": jnp.ones((3,))}
        state = tx.init(params)
        zero_grads = {"w": jnp.zeros((3,))}
        updates, _ = tx.update(zero_grads, state, params)
        new = optax.apply_updates(params, updates)
        # With zero gradients, weight decay alone must shrink the params.
        assert float(new["w"][0]) < 1.0, cls.__name__


@pytest.mark.slow
def test_remat_policies_match_no_remat_exactly():
    """Remat changes WHEN activations exist, never WHAT is computed:
    loss, metrics, and updated params must match the no-remat step
    bit-for-bit-close for both policies, through BN mutation and the
    custom_vjp quantizers."""
    import numpy as np
    import optax

    from zookeeper_tpu.core import configure
    from zookeeper_tpu.models import QuickNet
    from zookeeper_tpu.training import TrainState, make_train_step

    m = QuickNet()
    configure(
        m, {"blocks_per_section": (1, 1), "section_features": (16, 32)},
        name="m",
    )
    input_shape = (16, 16, 3)
    module = m.build(input_shape, num_classes=4)
    params, model_state = m.initialize(module, input_shape)

    def fresh_state():
        return TrainState.create(
            apply_fn=module.apply, params=params, model_state=model_state,
            tx=optax.sgd(0.1),
        )

    rng = np.random.default_rng(0)
    batch = {
        "input": jnp.asarray(rng.normal(size=(4, *input_shape)), jnp.float32),
        "target": jnp.asarray(rng.integers(0, 4, 4)),
    }
    base_state, base_metrics = jax.jit(make_train_step())(fresh_state(), batch)
    for policy in ("dots", "full", "quant"):
        st, mt = jax.jit(make_train_step(remat=policy))(fresh_state(), batch)
        np.testing.assert_allclose(
            float(mt["loss"]), float(base_metrics["loss"]), rtol=1e-6
        )
        for a, b in zip(
            jax.tree.leaves(base_state.params), jax.tree.leaves(st.params)
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6,
                err_msg=f"remat={policy}",
            )
        for a, b in zip(
            jax.tree.leaves(base_state.model_state),
            jax.tree.leaves(st.model_state),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
            )


def test_remat_unknown_policy_rejected():
    import pytest

    from zookeeper_tpu.training import make_train_step

    with pytest.raises(ValueError, match="remat"):
        make_train_step(remat="bogus")


def test_polynomial_and_linear_warmup_schedules():
    import numpy as np

    from zookeeper_tpu.core import configure
    from zookeeper_tpu.training import LinearWarmup, PolynomialDecay

    s = PolynomialDecay()
    configure(s, {"base_lr": 1.0, "end_lr": 0.0, "power": 1.0}, name="s")
    sched = s.build(total_steps=10)
    np.testing.assert_allclose(float(sched(0)), 1.0)
    np.testing.assert_allclose(float(sched(5)), 0.5)
    np.testing.assert_allclose(float(sched(10)), 0.0)

    w = LinearWarmup()
    configure(w, {"base_lr": 2.0, "warmup_steps": 4}, name="w")
    sched = w.build(total_steps=20)
    np.testing.assert_allclose(float(sched(0)), 0.0)
    np.testing.assert_allclose(float(sched(2)), 1.0)
    assert float(sched(4)) == 2.0 and float(sched(19)) == 2.0


def test_smoothed_loss_matches_manual_and_zero_is_plain():
    import numpy as np

    from zookeeper_tpu.training.step import (
        smoothed_softmax_cross_entropy,
        softmax_cross_entropy,
    )

    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(8, 10)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 10, 8))

    assert smoothed_softmax_cross_entropy(0.0) is softmax_cross_entropy

    s = 0.1
    loss = float(smoothed_softmax_cross_entropy(s)(logits, labels))
    # Manual: CE against smoothed one-hots.
    logp = jax.nn.log_softmax(logits)
    onehot = jax.nn.one_hot(labels, 10)
    targets = onehot * (1 - s) + s / 10
    manual = float(-(targets * logp).sum(-1).mean())
    np.testing.assert_allclose(loss, manual, rtol=1e-6)

    import pytest

    with pytest.raises(ValueError, match="smoothing"):
        smoothed_softmax_cross_entropy(1.0)


def test_top_k_accuracy_exact():
    import numpy as np

    from zookeeper_tpu.training.step import top_k_accuracy

    logits = jnp.asarray(
        [
            [9.0, 5.0, 4.0, 3.0, 2.0, 1.0],  # label 1: in top-5, not top-1
            [0.0, 1.0, 2.0, 3.0, 4.0, 5.0],  # label 0: not in top-5
            [5.0, 4.0, 3.0, 2.0, 1.0, 0.0],  # label 0: top-1
        ]
    )
    labels = jnp.asarray([1, 0, 0])
    np.testing.assert_allclose(
        float(top_k_accuracy(logits, labels, 5)), 2 / 3
    )
    np.testing.assert_allclose(
        float(top_k_accuracy(logits, labels, 1)), 1 / 3
    )


def test_eval_step_top5_metric_present():
    import numpy as np
    import optax

    from zookeeper_tpu.models import Mlp
    from zookeeper_tpu.core import configure
    from zookeeper_tpu.training import TrainState, make_eval_step

    m = Mlp()
    configure(m, {"hidden_units": (8,)}, name="m")
    module = m.build((4, 4, 1), num_classes=6)
    params, model_state = m.initialize(module, (4, 4, 1))
    state = TrainState.create(
        apply_fn=module.apply, params=params, model_state=model_state,
        tx=optax.sgd(0.1),
    )
    batch = {
        "input": jnp.zeros((4, 4, 4, 1)),
        "target": jnp.asarray([0, 1, 2, 3]),
    }
    metrics = jax.jit(make_eval_step(top5=True))(state, batch)
    assert "top5_accuracy" in metrics
    assert 0.0 <= float(metrics["top5_accuracy"]) <= 1.0
