"""Recipe EFFICACY A/B tests (VERDICT round-2 #1): the advanced recipes
exist to buy accuracy, so each one must demonstrably beat (or at least
not lose to) its baseline on REAL offline data — not merely execute.

All runs are seed-deterministic (dataset permutation, init, and the
(epoch, index)-keyed pipeline are all derived from fixed seeds), so the
pinned margins are reproducible, with headroom for minor numeric drift.
Measured deltas are recorded in BASELINE.md ("Recipe efficacy" section).

Regimes are chosen where each recipe's mechanism has something to do:
- KD: noisy-label training (the clean-label teacher regularizes away the
  corrupted hard labels — with plentiful clean labels KD has nothing to
  transfer and measures as a wash; that null result is in BASELINE.md).
- Bop vs Adam-latent: the flagship binary question, plain digits.
- EMA: a deliberately high learning rate so raw binary-net weights are
  still oscillating when training stops.
- Label smoothing: plain recipe, must not hurt.
"""

import jax
import numpy as np
import pytest

from zookeeper_tpu.core import configure
from zookeeper_tpu.training import DistillationExperiment, TrainingExperiment

pytest.importorskip("sklearn")


def _digits_conf(extra=None):
    return {
        "loader.dataset": "SklearnDigits",
        "loader.preprocessing": "ImageClassificationPreprocessing",
        "loader.preprocessing.height": 8,
        "loader.preprocessing.width": 8,
        "loader.preprocessing.channels": 1,
        "loader.host_index": 0,
        "loader.host_count": 1,
        "batch_size": 32,
        "verbose": False,
        **(extra or {}),
    }


def _tail_mean(history, k=3):
    accs = [v["accuracy"] for v in history["validation"]]
    return float(np.mean(accs[-k:]))


@pytest.mark.slow
def test_kd_beats_no_kd_under_label_noise(tmp_path):
    """A clean-label teacher lifts a student trained on 40%-corrupted
    hard labels: KD val accuracy (last-3 mean) beats the same student
    without KD by a pinned margin.

    Measured (calibration run, this box): alone 0.924, KD(alpha=0.3,
    T=2) 0.951 — a +2.6pt lift; margin pinned at 1pt."""
    teacher_path = str(tmp_path / "teacher")
    teacher = TrainingExperiment()
    configure(
        teacher,
        _digits_conf({
            "model": "SimpleCnn",
            "model.features": (16, 32),
            "model.dense_units": (64,),
            "epochs": 6,
            "export_model_to": teacher_path,
        }),
        name="teacher",
    )
    t_hist = teacher.run()
    assert t_hist["validation"][-1]["accuracy"] >= 0.95

    student = {
        "loader.dataset.label_noise_fraction": 0.4,
        "model": "Mlp",
        "model.hidden_units": (32,),
        "epochs": 14,
    }
    alone = TrainingExperiment()
    configure(alone, _digits_conf(dict(student)), name="alone")
    alone_hist = alone.run()

    kd = DistillationExperiment()
    configure(
        kd,
        _digits_conf({
            **student,
            "teacher": "SimpleCnn",
            "teacher.features": (16, 32),
            "teacher.dense_units": (64,),
            "teacher_checkpoint": teacher_path,
            "alpha": 0.3,
            "temperature": 2.0,
        }),
        name="kd",
    )
    kd_hist = kd.run()

    alone_acc, kd_acc = _tail_mean(alone_hist), _tail_mean(kd_hist)
    assert alone_acc >= 0.88, f"noisy-label baseline collapsed: {alone_acc}"
    assert kd_acc >= alone_acc + 0.01, (
        f"KD did not beat the no-KD student: kd={kd_acc:.4f} "
        f"alone={alone_acc:.4f}"
    )


@pytest.mark.slow
def test_bop_matches_adam_latent_recipe():
    """Bop (the binary-native optimizer) trains BinaryNet to within a few
    points of the Adam-on-latent-weights recipe on real digits.

    Measured (calibration): Adam best 0.984, Bop best 0.997 — Bop
    actually WINS here; pinned as within-3pts + an absolute floor."""
    base = {
        "model": "BinaryNet",
        "model.features": (32, 32),
        "model.dense_units": (64,),
        "epochs": 8,
        "batch_size": 64,
    }
    adam = TrainingExperiment()
    configure(
        adam,
        _digits_conf({**base, "optimizer.schedule.base_lr": 5e-3}),
        name="adam",
    )
    adam_hist = adam.run()
    bop = TrainingExperiment()
    configure(bop, _digits_conf({**base, "optimizer": "Bop"}), name="bop")
    bop_hist = bop.run()

    adam_best = max(v["accuracy"] for v in adam_hist["validation"])
    bop_best = max(v["accuracy"] for v in bop_hist["validation"])
    assert bop_best >= 0.93, f"Bop absolute floor: {bop_best:.4f}"
    assert bop_best >= adam_best - 0.03, (
        f"Bop lost to Adam-latent by more than 3pts: bop={bop_best:.4f} "
        f"adam={adam_best:.4f}"
    )


@pytest.mark.slow
def test_ema_eval_beats_raw_eval_late_in_run():
    """With a high LR the raw binary-net weights are still oscillating at
    the end of training; the EMA weights (what ships) must evaluate
    better than the raw ones on the SAME final state.

    Measured (calibration): raw 0.944, EMA 0.984 — +4pts; margin pinned
    at 1pt (plus an EMA-loss <= raw-loss check)."""
    from zookeeper_tpu.training.experiment import run_weighted_eval
    from zookeeper_tpu.training.step import make_eval_step

    exp = TrainingExperiment()
    configure(
        exp,
        _digits_conf({
            "model": "BinaryNet",
            "model.features": (32, 32),
            "model.dense_units": (64,),
            "epochs": 8,
            "batch_size": 64,
            "optimizer.schedule.base_lr": 1e-2,
            "ema_decay": 0.95,
        }),
        name="ema_exp",
    )
    exp.run()
    state = exp.final_state
    raw = run_weighted_eval(
        exp.loader, "validation", jax.jit(make_eval_step(use_ema=False)),
        state, None, epoch=0,
    )
    ema = run_weighted_eval(
        exp.loader, "validation", jax.jit(make_eval_step(use_ema=True)),
        state, None, epoch=0,
    )
    assert ema["accuracy"] >= 0.95, f"EMA floor: {ema['accuracy']:.4f}"
    assert ema["accuracy"] >= raw["accuracy"] + 0.01, (
        f"EMA eval did not beat raw eval: ema={ema['accuracy']:.4f} "
        f"raw={raw['accuracy']:.4f}"
    )
    assert ema["loss"] <= raw["loss"], (
        f"EMA loss worse than raw: {ema['loss']:.4f} vs {raw['loss']:.4f}"
    )


@pytest.mark.slow
def test_label_smoothing_does_not_hurt():
    """Label smoothing 0.1 (the ImageNet-recipe default) must not cost
    accuracy on the fp baseline.

    Measured (calibration): plain 0.969, smoothed 0.972 final (best
    0.969 vs 0.975) — pinned as within-1pt, i.e. 'not hurting'."""
    base = {
        "model": "SimpleCnn",
        "model.features": (16, 32),
        "model.dense_units": (64,),
        "epochs": 5,
        "batch_size": 64,
    }
    plain = TrainingExperiment()
    configure(plain, _digits_conf(dict(base)), name="plain")
    plain_hist = plain.run()
    smooth = TrainingExperiment()
    configure(
        smooth, _digits_conf({**base, "label_smoothing": 0.1}), name="smooth"
    )
    smooth_hist = smooth.run()

    p_final = plain_hist["validation"][-1]["accuracy"]
    s_final = smooth_hist["validation"][-1]["accuracy"]
    assert s_final >= 0.94, f"smoothed floor: {s_final:.4f}"
    assert s_final >= p_final - 0.01, (
        f"label smoothing hurt: smooth={s_final:.4f} plain={p_final:.4f}"
    )


def test_digits_label_noise_is_deterministic_and_scoped():
    """The noise knob: deterministic in seed, train-only, ~the requested
    fraction actually corrupted, validation untouched."""
    from zookeeper_tpu.data import SklearnDigits

    clean = SklearnDigits()
    configure(clean, {"seed": 3}, name="clean")
    noisy = SklearnDigits()
    configure(noisy, {"seed": 3, "label_noise_fraction": 0.4}, name="noisy")
    noisy2 = SklearnDigits()
    configure(noisy2, {"seed": 3, "label_noise_fraction": 0.4}, name="noisy2")

    def labels(src):
        return np.asarray([src[i]["label"] for i in range(len(src))])

    lc, ln = labels(clean.train()), labels(noisy.train())
    frac = float(np.mean(lc != ln))
    assert 0.35 <= frac <= 0.45, frac  # every corrupted label is wrong
    np.testing.assert_array_equal(ln, labels(noisy2.train()))
    np.testing.assert_array_equal(
        labels(clean.validation()), labels(noisy.validation())
    )
    # Images are untouched in both splits.
    np.testing.assert_array_equal(
        np.asarray(clean.train()[0]["image"]),
        np.asarray(noisy.train()[0]["image"]),
    )


def test_digits_train_fraction_scopes_train_only():
    from zookeeper_tpu.data import SklearnDigits

    full = SklearnDigits()
    configure(full, {"seed": 3}, name="full")
    frac = SklearnDigits()
    configure(frac, {"seed": 3, "train_fraction": 0.1}, name="frac")
    assert len(frac.train()) == int(round(len(full.train()) * 0.1))
    assert len(frac.validation()) == len(full.validation())
    # The kept slice is a PREFIX of the full (seed-shuffled) train split.
    np.testing.assert_array_equal(
        np.asarray(frac.train()[0]["image"]),
        np.asarray(full.train()[0]["image"]),
    )
    with pytest.raises(ValueError, match="train_fraction"):
        bad = SklearnDigits()
        configure(bad, {"train_fraction": 0.0}, name="bad")
        bad.train()
