"""Latency-measurement utility (training.benchmark)."""

import jax.numpy as jnp
import numpy as np
import pytest

from zookeeper_tpu.training.benchmark import scan_chain_latency


@pytest.mark.slow
def test_scan_chain_latency_heavy_apply_measurable_and_ordered():
    """A work-heavy apply (20 chained 256x256 matmuls, ~ms per call on
    CPU — far above dispatch/timer jitter) must measure strictly positive
    and slower than a single-matmul apply."""
    x = jnp.asarray(
        np.random.default_rng(0).normal(size=(256, 256)), jnp.float32
    )

    def heavy(v):
        for _ in range(20):
            v = v @ x
        return v

    t_heavy = scan_chain_latency(heavy, x, length=8, rounds=3)
    t_light = scan_chain_latency(lambda v: v @ x, x, length=8, rounds=3)
    assert t_heavy > 1e-6  # genuinely measured, not the noise floor
    assert t_heavy > t_light


def test_scan_chain_latency_never_negative_or_zero():
    """Noise-dominated measurements floor at a tiny positive value (the
    'unmeasurably fast, raise length' signal), never negative/zero."""
    x = jnp.ones((4,))
    t = scan_chain_latency(lambda v: v + 1.0, x, length=2, rounds=1)
    assert t > 0.0


def test_measure_serving_latency_on_engine():
    """The bench's serve_* anchor path (ZK_BENCH_SERVE): measures a
    warmed InferenceEngine with the shared chain protocols — finite
    mean, ordered percentiles, zero compiles inside the timed window."""
    from zookeeper_tpu.core import configure
    from zookeeper_tpu.models.simple import Mlp
    from zookeeper_tpu.serving import InferenceEngine
    from zookeeper_tpu.training.benchmark import measure_serving_latency

    model = Mlp()
    configure(model, {"hidden_units": (16,)}, name="model")
    module = model.build((6,), 4)
    params, model_state = model.initialize(module, (6,))
    engine = InferenceEngine()
    configure(engine, {"batch_buckets": (4,)}, name="engine")
    engine.bind(module.apply, params, model_state, (6,))
    engine.warmup()
    x = np.random.default_rng(0).normal(size=(4, 6)).astype(np.float32)
    before = engine.compile_count
    mean_s, p50_s, p99_s = measure_serving_latency(
        engine, x, n1=2, n2=6, rounds=2, percentile_samples=6, chain_len=2
    )
    assert engine.compile_count == before  # warmed: no timed compiles
    assert np.isfinite(mean_s)
    assert 0.0 <= p50_s <= p99_s
