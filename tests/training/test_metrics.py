"""Metrics-writer components (SURVEY §5 metrics/observability row).

The reference's metrics story is Keras callbacks (TensorBoard); here the
sink is a configurable component. These tests pin: jsonl format, the
no-op-when-unconfigured contract, real TensorBoard event files on disk,
and the experiment wiring (per-epoch always, per-step under log_every).
"""

import glob
import json

import pytest

from zookeeper_tpu.core import configure
from zookeeper_tpu.training import (
    CompositeMetricsWriter,
    JsonlMetricsWriter,
    MetricsWriter,
    TensorBoardMetricsWriter,
    TrainingExperiment,
)


def make_experiment(tmp_path, extra=None):
    exp = TrainingExperiment()
    conf = {
        "loader.dataset": "SyntheticMnist",
        "loader.dataset.num_train_examples": 128,
        "loader.dataset.num_validation_examples": 32,
        "loader.preprocessing": "ImageClassificationPreprocessing",
        "loader.preprocessing.height": 28,
        "loader.preprocessing.width": 28,
        "loader.preprocessing.channels": 1,
        "loader.host_index": 0,
        "loader.host_count": 1,
        "model": "Mlp",
        "model.hidden_units": (16,),
        "batch_size": 32,
        "epochs": 2,
        "verbose": False,
        **(extra or {}),
    }
    configure(exp, conf, name="experiment")
    return exp


def test_null_writer_is_noop():
    w = MetricsWriter()
    configure(w, {}, name="writer")
    w.write_scalars(0, {"loss": 1.0})
    w.flush()
    w.close()


def test_jsonl_writer(tmp_path):
    path = tmp_path / "m.jsonl"
    w = JsonlMetricsWriter()
    configure(w, {"path": str(path)}, name="writer")
    w.write_scalars(1, {"loss": 0.5, "acc": 0.9})
    w.write_scalars(2, {"loss": 0.25})
    w.close()
    lines = [json.loads(l) for l in path.read_text().splitlines()]
    assert lines == [
        {"step": 1, "loss": 0.5, "acc": 0.9},
        {"step": 2, "loss": 0.25},
    ]


def test_jsonl_writer_unconfigured_is_noop(tmp_path):
    w = JsonlMetricsWriter()
    configure(w, {}, name="writer")
    w.write_scalars(1, {"loss": 0.5})  # Must not raise or write anywhere.
    w.close()


def _read_tb_scalars(log_dir):
    """Parse scalar summaries back out of TensorBoard event files."""
    import tensorflow as tf

    out = {}
    for path in glob.glob(f"{log_dir}/**/events.out.tfevents*", recursive=True):
        for raw in tf.data.TFRecordDataset(path):
            event = tf.compat.v1.Event.FromString(raw.numpy())
            for value in event.summary.value:
                if value.HasField("simple_value"):
                    out[(event.step, value.tag)] = value.simple_value
                elif value.HasField("tensor"):
                    out[(event.step, value.tag)] = float(
                        tf.make_ndarray(value.tensor)
                    )
    return out


def test_tensorboard_writer_round_trip(tmp_path):
    log_dir = str(tmp_path / "tb")
    w = TensorBoardMetricsWriter()
    configure(w, {"log_dir": log_dir}, name="writer")
    w.write_scalars(3, {"train/loss": 0.125})
    w.close()
    w.write_scalars(4, {"train/loss": 0.5})  # Post-close: contract says no-op.
    scalars = _read_tb_scalars(log_dir)
    assert scalars[(3, "train/loss")] == pytest.approx(0.125)
    assert (4, "train/loss") not in scalars


def test_composite_writer_fans_out(tmp_path):
    w = CompositeMetricsWriter()
    configure(
        w,
        {
            "jsonl.path": str(tmp_path / "m.jsonl"),
            "tensorboard.log_dir": str(tmp_path / "tb"),
        },
        name="writer",
    )
    w.write_scalars(7, {"loss": 2.0})
    w.close()
    assert json.loads((tmp_path / "m.jsonl").read_text()) == {
        "step": 7,
        "loss": 2.0,
    }
    assert _read_tb_scalars(str(tmp_path / "tb"))[(7, "loss")] == 2.0


def test_experiment_writes_metrics(tmp_path):
    """End-to-end: the training loop feeds the writer per epoch and (with
    log_every) per step, with train/ and val/ prefixes."""
    exp = make_experiment(
        tmp_path,
        {
            "log_every": 2,
            "writer.jsonl.path": str(tmp_path / "m.jsonl"),
            "writer.tensorboard.log_dir": str(tmp_path / "tb"),
        },
    )
    exp.run()
    lines = [json.loads(l) for l in (tmp_path / "m.jsonl").read_text().splitlines()]
    # 2 epochs x 4 steps with log_every=2 -> 2 step-records + 1 epoch-record
    # per epoch = 6 lines total.
    assert len(lines) == 6
    epoch_records = [l for l in lines if "val/accuracy" in l]
    assert len(epoch_records) == 2
    assert {
        "train_epoch/loss",
        "train_epoch/accuracy",
        "train_epoch/examples_per_sec",
    } <= set(epoch_records[0])
    assert epoch_records[0]["step"] == 4  # Steps-per-epoch granularity.
    step_records = [l for l in lines if "val/accuracy" not in l]
    assert [r["step"] for r in step_records] == [2, 4, 6, 8]

    scalars = _read_tb_scalars(str(tmp_path / "tb"))
    assert (4, "train/loss") in scalars
    assert (4, "train_epoch/loss") in scalars
    assert (8, "val/accuracy") in scalars


def test_top_k_accuracy_rank_general():
    """top_k is rank-general like loss/accuracy: [b, s, V] logits with
    [b, s] labels (per-position LM scoring) — labels[:, None] used to
    break rank-3 broadcasting."""
    import jax.numpy as jnp
    import numpy as np

    from zookeeper_tpu.training.step import top_k_accuracy

    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(2, 8, 11)).astype(np.float32))
    labels = jnp.asarray(rng.integers(0, 11, (2, 8)))
    v = float(top_k_accuracy(logits, labels, 5))
    assert 0.0 <= v <= 1.0
    # Oracle: per-position membership of the label in the top-5 set.
    top5 = np.argsort(-np.asarray(logits), axis=-1)[..., :5]
    want = float(
        (top5 == np.asarray(labels)[..., None]).any(-1).mean()
    )
    assert abs(v - want) < 1e-6
    # Rank-2 (image classification) path unchanged.
    l2 = jnp.asarray(rng.normal(size=(16, 11)).astype(np.float32))
    y2 = jnp.asarray(rng.integers(0, 11, (16,)))
    t2 = np.argsort(-np.asarray(l2), axis=-1)[:, :5]
    want2 = float((t2 == np.asarray(y2)[:, None]).any(-1).mean())
    assert abs(float(top_k_accuracy(l2, y2, 5)) - want2) < 1e-6
