"""Schedule components: trajectory endpoints and shapes.

Each schedule builds an optax step->lr callable; these tests pin the
contract points (initial value, peak, boundaries, final value) that the
experiment's applied-units accounting depends on.
"""

import numpy as np
import pytest

from zookeeper_tpu.core import configure
from zookeeper_tpu.training.schedule import (
    ConstantSchedule,
    CosineDecay,
    LinearWarmup,
    PolynomialDecay,
    StepDecay,
    WarmupCosine,
)


def build(cls, conf, total_steps=100):
    s = cls()
    configure(s, conf, name="s")
    return s.build(total_steps)


def test_constant():
    fn = build(ConstantSchedule, {"base_lr": 0.25})
    assert float(fn(0)) == 0.25
    assert float(fn(99)) == 0.25


def test_cosine_decay_endpoints():
    fn = build(CosineDecay, {"base_lr": 1.0, "alpha": 0.1}, total_steps=100)
    assert float(fn(0)) == pytest.approx(1.0)
    # Cosine reaches alpha * base at the end of the decay.
    assert float(fn(100)) == pytest.approx(0.1, rel=1e-5)
    # Monotone decreasing on the decay interval.
    vals = [float(fn(t)) for t in range(0, 101, 10)]
    assert all(a >= b for a, b in zip(vals, vals[1:]))


def test_warmup_cosine_ramps_then_decays():
    fn = build(
        WarmupCosine,
        {"base_lr": 1.0, "warmup_steps": 10, "alpha": 0.0},
        total_steps=100,
    )
    assert float(fn(0)) == pytest.approx(0.0, abs=1e-6)
    peak = max(float(fn(t)) for t in range(101))
    assert peak == pytest.approx(1.0, rel=1e-3)
    assert float(fn(100)) < 0.01


def test_step_decay_boundaries():
    fn = build(
        StepDecay,
        {"base_lr": 1.0, "boundaries": [0.5, 0.75], "factor": 0.1},
        total_steps=100,
    )
    assert float(fn(49)) == pytest.approx(1.0)
    assert float(fn(60)) == pytest.approx(0.1, rel=1e-5)
    assert float(fn(80)) == pytest.approx(0.01, rel=1e-5)


def test_step_decay_collapsed_boundaries_compound():
    """Short runs can collapse two boundaries onto one step: the factors
    must compound, not overwrite."""
    fn = build(
        StepDecay,
        {"base_lr": 1.0, "boundaries": [0.5, 0.6], "factor": 0.1},
        total_steps=2,  # both boundaries -> step 1
    )
    assert float(fn(1)) == pytest.approx(0.01, rel=1e-5)


def test_polynomial_decay_linear():
    fn = build(
        PolynomialDecay,
        {"base_lr": 1.0, "end_lr": 0.0, "power": 1.0},
        total_steps=100,
    )
    assert float(fn(0)) == pytest.approx(1.0)
    assert float(fn(50)) == pytest.approx(0.5, rel=1e-5)
    assert float(fn(100)) == pytest.approx(0.0, abs=1e-7)


def test_linear_warmup_reaches_and_holds_peak():
    fn = build(
        LinearWarmup,
        {"base_lr": 0.4, "warmup_steps": 20},
        total_steps=100,
    )
    assert float(fn(0)) < 0.4
    assert float(fn(20)) == pytest.approx(0.4, rel=1e-5)
    assert float(fn(99)) == pytest.approx(0.4, rel=1e-5)
    ramp = [float(fn(t)) for t in range(21)]
    assert all(b >= a for a, b in zip(ramp, ramp[1:]))


def test_warmup_fraction_fallback():
    fn = build(
        LinearWarmup,
        {"base_lr": 1.0, "warmup_fraction": 0.1},
        total_steps=50,
    )
    # warmup = 5 steps; before it, lr < peak.
    assert float(fn(2)) < 1.0
    assert float(fn(5)) == pytest.approx(1.0, rel=1e-5)


def test_constant_schedule_after_configure_is_frozen():
    s = ConstantSchedule()
    configure(s, {"base_lr": 0.1}, name="s")
    with pytest.raises(Exception):
        s.base_lr = 0.2  # Components freeze after configure.
