"""Bop optimizer, flip-ratio metric, and model summary (larq parity:
``Bop``/``CaseOptimizer``, ``metrics.FlipRatio``, ``models.summary``)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from zookeeper_tpu.core import configure
from zookeeper_tpu.training import Bop, scale_by_bop
from zookeeper_tpu.training.optimizer import BINARY_KERNEL_PATTERN


def test_scale_by_bop_flip_rule():
    """The exact Bop rule: flip iff |m| > tau and sign(m) == sign(w)."""
    tx = scale_by_bop(threshold=0.1, gamma=1.0)  # gamma=1: m == grad.
    w = jnp.array([1.0, 1.0, -1.0, -1.0, 1.0])
    #            same-sign big | opp-sign big | same-sign big | small | tiny
    g = jnp.array([0.5, -0.5, -0.5, -0.05, 0.01])
    state = tx.init(w)
    updates, state = tx.update(g, state, w)
    new_w = optax.apply_updates(w, updates)
    # w[0]: m=0.5 same sign as w=1, |m|>0.1 -> flipped to -1.
    # w[1]: m=-0.5 opposite sign -> kept.
    # w[2]: m=-0.5 same sign as w=-1 -> flipped to +1.
    # w[3]: |m|=0.05 < 0.1 -> kept.  w[4]: tiny -> kept.
    np.testing.assert_array_equal(
        np.asarray(new_w), np.array([-1.0, 1.0, 1.0, -1.0, 1.0])
    )


def test_scale_by_bop_gradient_memory_accumulates():
    """Below-threshold gradients accumulate in m until they trip a flip —
    the 'consistency detector' that distinguishes Bop from naive sign-SGD."""
    tx = scale_by_bop(threshold=0.5, gamma=0.5)
    w = jnp.array([1.0])
    g = jnp.array([1.0])  # Same sign as w every step.
    state = tx.init(w)
    # m after steps: 0.5, 0.75 -> crosses 0.5 only on step 2.
    updates, state = tx.update(g, state, w)
    w1 = optax.apply_updates(w, updates)
    assert float(w1[0]) == 1.0  # m == 0.5, not > threshold yet.
    updates, state = tx.update(g, state, w1)
    w2 = optax.apply_updates(w1, updates)
    assert float(w2[0]) == -1.0  # m == 0.75 > 0.5: flip.


def _quicknet_tiny_state(optimizer):
    from zookeeper_tpu.models import QuickNet
    from zookeeper_tpu.training import TrainState

    m = QuickNet()
    configure(
        m, {"blocks_per_section": (1, 1), "section_features": (16, 32)},
        name="m",
    )
    input_shape = (32, 32, 3)
    module = m.build(input_shape, num_classes=4)
    params, model_state = m.initialize(module, input_shape)
    tx = optimizer.build(total_steps=10)
    return (
        TrainState.create(
            apply_fn=module.apply, params=params, model_state=model_state,
            tx=tx,
        ),
        input_shape,
    )


def test_bop_component_splits_binary_and_fp():
    """Bop moves binary kernels ONLY by sign flips (magnitudes frozen)
    while fp params (stem conv, BN, head) move continuously."""
    from zookeeper_tpu.training import make_train_step

    opt = Bop()
    configure(opt, {"threshold": 0.0, "gamma": 0.1}, name="opt")
    state, input_shape = _quicknet_tiny_state(opt)

    step = jax.jit(make_train_step())
    rng = np.random.default_rng(0)
    batch = {
        "input": jnp.asarray(rng.normal(size=(8, *input_shape)), jnp.float32),
        "target": jnp.asarray(rng.integers(0, 4, 8)),
    }
    new_state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))

    import re

    from flax import traverse_util

    pat = re.compile(BINARY_KERNEL_PATTERN)
    old = traverse_util.flatten_dict(state.params, sep="/")
    new = traverse_util.flatten_dict(new_state.params, sep="/")
    binary_paths = [p for p in old if pat.search(p)]
    fp_paths = [p for p in old if not pat.search(p)]
    assert binary_paths and fp_paths

    flipped_any = False
    for p in binary_paths:
        a, b = np.asarray(old[p]), np.asarray(new[p])
        # Bop preserves magnitude exactly: |w| unchanged everywhere.
        np.testing.assert_allclose(np.abs(a), np.abs(b), rtol=0, atol=0)
        flipped_any = flipped_any or np.any(np.sign(a) != np.sign(b))
    assert flipped_any  # threshold=0 guarantees flips on step 1.

    fp_moved = any(
        not np.allclose(np.asarray(old[p]), np.asarray(new[p]))
        for p in fp_paths
    )
    assert fp_moved


def test_flip_ratio_metric_reports_fraction():
    from zookeeper_tpu.training import make_train_step

    opt = Bop()
    configure(opt, {"threshold": 0.0, "gamma": 0.1}, name="opt")
    state, input_shape = _quicknet_tiny_state(opt)
    step = jax.jit(
        make_train_step(flip_ratio_pattern=BINARY_KERNEL_PATTERN)
    )
    rng = np.random.default_rng(0)
    batch = {
        "input": jnp.asarray(rng.normal(size=(8, *input_shape)), jnp.float32),
        "target": jnp.asarray(rng.integers(0, 4, 8)),
    }
    _, metrics = step(state, batch)
    fr = float(metrics["flip_ratio"])
    # threshold=0 Bop flips every weight whose EMA-gradient sign matches
    # its own — statistically about half: definitely in (0, 1).
    assert 0.0 < fr < 1.0


def test_flip_ratio_zero_for_pure_fp_small_lr():
    """With a tiny-LR fp optimizer no kernel crosses zero in one step."""
    from zookeeper_tpu.training import Adam, make_train_step

    opt = Adam()
    configure(opt, {"schedule.base_lr": 1e-12}, name="opt")
    state, input_shape = _quicknet_tiny_state(opt)
    step = jax.jit(
        make_train_step(flip_ratio_pattern=BINARY_KERNEL_PATTERN)
    )
    rng = np.random.default_rng(0)
    batch = {
        "input": jnp.asarray(rng.normal(size=(8, *input_shape)), jnp.float32),
        "target": jnp.asarray(rng.integers(0, 4, 8)),
    }
    _, metrics = step(state, batch)
    assert float(metrics["flip_ratio"]) == 0.0


def test_model_summary_binary_accounting():
    from zookeeper_tpu.core import configure
    from zookeeper_tpu.models import QuickNet, model_summary

    m = QuickNet()
    configure(
        m, {"blocks_per_section": (1, 1), "section_features": (16, 32)},
        name="m",
    )
    module = m.build((32, 32, 3), num_classes=10)
    s = model_summary(module, (32, 32, 3))
    assert s.total_params == s.binary_params + s.fp_params
    assert s.binary_params > 0
    # Binary kernels deploy at 1 bit: deployment is much smaller than
    # fp32 training memory, and exactly train_bytes - binary*4 + binary/8.
    expected = s.train_bytes - s.binary_params * 4 + s.binary_params / 8
    assert s.deploy_bytes == pytest.approx(expected)
    text = str(s)
    assert "binary" in text and "MiB" in text
    # All QuantConv kernels are marked binary (1 bit).
    for r in s.rows:
        if "QuantConv" in r.path and r.path.endswith("/kernel"):
            assert r.binary and r.deploy_bits == 1


def test_model_summary_flops():
    from zookeeper_tpu.core import configure
    from zookeeper_tpu.models import Mlp, model_summary

    m = Mlp()
    configure(m, {"hidden_units": (16,)}, name="m")
    module = m.build((8, 8, 1), num_classes=10)
    s = model_summary(module, (8, 8, 1), compute_flops=True)
    if s.flops is not None:  # Cost analysis availability is backend-dependent.
        # Dense 64->16->10: ~2*(64*16 + 16*10) = ~2368 FLOPs minimum.
        assert s.flops > 1000


def test_bop_rejects_dead_base_fields():
    opt = Bop()
    configure(opt, {"weight_decay": 1e-4}, name="opt")
    with pytest.raises(ValueError, match="fp_optimizer"):
        opt.build(total_steps=10)


@pytest.mark.slow
def test_flip_ratio_raises_when_pattern_matches_nothing():
    from zookeeper_tpu.training import Adam, make_train_step

    opt = Adam()
    configure(opt, {}, name="opt")
    from zookeeper_tpu.core import configure as _cfg
    from zookeeper_tpu.models import Mlp
    from zookeeper_tpu.training import TrainState

    m = Mlp()
    _cfg(m, {"hidden_units": (8,)}, name="m")
    module = m.build((4, 4, 1), num_classes=2)
    params, model_state = m.initialize(module, (4, 4, 1))
    state = TrainState.create(
        apply_fn=module.apply, params=params, model_state=model_state,
        tx=opt.build(10),
    )
    step = make_train_step(flip_ratio_pattern=BINARY_KERNEL_PATTERN)
    batch = {
        "input": jnp.zeros((2, 4, 4, 1), jnp.float32),
        "target": jnp.zeros((2,), jnp.int32),
    }
    with pytest.raises(ValueError, match="matched no"):
        step(state, batch)  # Mlp has no Quant* layers.


def test_bop_rejects_configured_schedule():
    opt = Bop()
    configure(opt, {"schedule.base_lr": 0.1}, name="opt")
    with pytest.raises(ValueError, match="fp_optimizer.schedule"):
        opt.build(total_steps=10)


def test_unquantized_quant_kernel_named_fp_and_skipped_by_bop():
    """A Quant layer with kernel_quantizer=None (activation-only
    quantization) registers its kernel as kernel_fp, so the binary
    pattern never routes it to Bop / flip-ratio / 1-bit accounting."""
    import re

    from zookeeper_tpu.ops.layers import QuantDense

    from flax import traverse_util

    layer = QuantDense(4, input_quantizer="ste_sign", kernel_quantizer=None)
    params = layer.init(jax.random.key(0), jnp.zeros((2, 8)))
    flat = traverse_util.flatten_dict(params["params"], sep="/")
    assert "kernel_fp" in flat and "kernel" not in flat
    assert not any(re.search(BINARY_KERNEL_PATTERN, f"QuantDense_0/{p}") for p in flat)

    # Multi-level kernel quantizers are not sign-family either.
    layer2 = QuantDense(4, input_quantizer="ste_sign", kernel_quantizer="ste_tern")
    params2 = layer2.init(jax.random.key(0), jnp.zeros((2, 8)))
    flat2 = traverse_util.flatten_dict(params2["params"], sep="/")
    assert "kernel_fp" in flat2 and "kernel" not in flat2


def test_model_summary_packed_counts_true_weights():
    """Packed deployment stores int32 lanes; the summary must report the
    LOGICAL weight count (32x the lanes) so train and packed forms of the
    same model agree on 'params'."""
    from zookeeper_tpu.core import configure
    from zookeeper_tpu.models import QuickNet, model_summary

    def build(extra):
        m = QuickNet()
        configure(
            m,
            {"blocks_per_section": (1, 1), "section_features": (32, 64),
             **extra},
            name="m",
        )
        return m.build((32, 32, 3), num_classes=10)

    s_train = model_summary(build({}), (32, 32, 3))
    s_packed = model_summary(
        build({"binary_compute": "xnor", "packed_weights": True,
               "pallas_interpret": True}),
        (32, 32, 3),
    )
    assert s_packed.binary_params == s_train.binary_params
    # The packed form additionally stores per-channel scales; totals match
    # once those fp scales are accounted.
    scales = sum(
        r.count for r in s_packed.rows if r.path.endswith("kernel_scale")
    )
    assert s_packed.total_params == s_train.total_params + scales
    # Deployment bytes for the binary kernels agree between forms (1 bit).
    packed_dep = sum(r.deploy_bytes for r in s_packed.rows if r.binary)
    train_dep = sum(r.deploy_bytes for r in s_train.rows if r.binary)
    assert packed_dep == train_dep


def test_gradient_accumulation_semantics():
    """accumulate_steps=k: params move only on every k-th micro step, by
    the update computed from the MEAN of the k microbatch gradients."""
    from zookeeper_tpu.training import Sgd

    opt = Sgd()
    configure(
        opt, {"schedule.base_lr": 0.5, "accumulate_steps": 2}, name="opt"
    )
    tx = opt.build(total_steps=10)
    params = jnp.array([1.0, 2.0])
    state = tx.init(params)
    g1 = jnp.array([0.2, -0.4])
    g2 = jnp.array([0.6, 0.0])
    up1, state = tx.update(g1, state, params)
    p1 = optax.apply_updates(params, up1)
    np.testing.assert_array_equal(np.asarray(p1), np.asarray(params))
    up2, state = tx.update(g2, state, p1)
    p2 = optax.apply_updates(p1, up2)
    expected = params - 0.5 * (g1 + g2) / 2.0
    np.testing.assert_allclose(np.asarray(p2), np.asarray(expected), rtol=1e-6)


@pytest.mark.slow
def test_bop_with_accumulation_flips_on_boundary():
    from zookeeper_tpu.training import make_train_step

    opt = Bop()
    configure(
        opt, {"threshold": 0.0, "gamma": 0.1, "accumulate_steps": 2},
        name="opt",
    )
    state, input_shape = _quicknet_tiny_state(opt)
    step = jax.jit(make_train_step())
    rng = np.random.default_rng(0)
    batch = {
        "input": jnp.asarray(rng.normal(size=(8, *input_shape)), jnp.float32),
        "target": jnp.asarray(rng.integers(0, 4, 8)),
    }
    mid_state, _ = step(state, batch)
    # Micro step 1: nothing applied yet.
    for a, b in zip(
        jax.tree.leaves(state.params), jax.tree.leaves(mid_state.params)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    end_state, metrics = step(mid_state, batch)
    assert np.isfinite(float(metrics["loss"]))
    moved = any(
        not np.allclose(np.asarray(a), np.asarray(b))
        for a, b in zip(
            jax.tree.leaves(state.params), jax.tree.leaves(end_state.params)
        )
    )
    assert moved  # Boundary step applies the accumulated update.


@pytest.mark.slow
@pytest.mark.parametrize("cls_name", ["Lamb", "Lars"])
def test_large_batch_optimizers_step(cls_name):
    import zookeeper_tpu.training as tr
    from zookeeper_tpu.training import make_train_step

    opt = getattr(tr, cls_name)()
    configure(opt, {"weight_decay": 1e-4}, name="opt")
    state, input_shape = _quicknet_tiny_state(opt)
    step = jax.jit(make_train_step())
    rng = np.random.default_rng(0)
    batch = {
        "input": jnp.asarray(rng.normal(size=(8, *input_shape)), jnp.float32),
        "target": jnp.asarray(rng.integers(0, 4, 8)),
    }
    new_state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))
    moved = any(
        not np.allclose(np.asarray(a), np.asarray(b))
        for a, b in zip(
            jax.tree.leaves(state.params), jax.tree.leaves(new_state.params)
        )
    )
    assert moved


def test_accumulated_schedule_equals_reference_trajectory():
    """accumulate_steps=k must be EQUIVALENT to an unaccumulated run on
    the mean gradients with a schedule over the applied steps — pinning
    both the mean semantics and the applied-units schedule (a
    micro-step-built schedule would stretch the decay by k)."""
    from zookeeper_tpu.training import Sgd

    grads = [jnp.array([g]) for g in (0.3, -0.5, 0.2, 0.8, -0.1, 0.4, 0.6, -0.2)]

    opt_acc = Sgd()
    configure(
        opt_acc,
        {"schedule": "CosineDecay", "schedule.base_lr": 0.5,
         "accumulate_steps": 2},
        name="opt_acc",
    )
    tx = opt_acc.build(total_steps=8)  # 8 micro steps.
    p = jnp.array([1.0])
    st = tx.init(p)
    for g in grads:
        up, st = tx.update(g, st, p)
        p = optax.apply_updates(p, up)

    opt_ref = Sgd()
    configure(
        opt_ref,
        {"schedule": "CosineDecay", "schedule.base_lr": 0.5},
        name="opt_ref",
    )
    tx_ref = opt_ref.build(total_steps=4)  # 4 applied steps.
    p_ref = jnp.array([1.0])
    st_ref = tx_ref.init(p_ref)
    for g1, g2 in zip(grads[::2], grads[1::2]):
        up, st_ref = tx_ref.update((g1 + g2) / 2.0, st_ref, p_ref)
        p_ref = optax.apply_updates(p_ref, up)

    np.testing.assert_allclose(np.asarray(p), np.asarray(p_ref), rtol=1e-6)


@pytest.mark.slow
def test_bop_accumulation_fp_side_single_wrapped():
    """The unscoped accumulate_steps key scope-inherits onto
    fp_optimizer; Bop must still apply accumulation ONCE — fp params
    move on micro step k, not k^2."""
    from zookeeper_tpu.training import make_train_step

    opt = Bop()
    configure(
        opt, {"threshold": 0.0, "gamma": 0.1, "accumulate_steps": 2},
        name="opt",
    )
    assert opt.fp_optimizer.accumulate_steps == 2  # Inherited, by design.
    state, input_shape = _quicknet_tiny_state(opt)
    step = jax.jit(make_train_step())
    rng = np.random.default_rng(0)
    batch = {
        "input": jnp.asarray(rng.normal(size=(8, *input_shape)), jnp.float32),
        "target": jnp.asarray(rng.integers(0, 4, 8)),
    }
    s1, _ = step(state, batch)
    s2, _ = step(s1, batch)

    import re

    from flax import traverse_util

    pat = re.compile(BINARY_KERNEL_PATTERN)
    old = traverse_util.flatten_dict(state.params, sep="/")
    new = traverse_util.flatten_dict(s2.params, sep="/")
    fp_moved = any(
        not np.allclose(np.asarray(old[p]), np.asarray(new[p]))
        for p in old
        if not pat.search(p)
    )
    assert fp_moved  # At micro step 2 (the boundary), not step 4.


def test_scale_by_bop_scheduled_threshold_stops_flips():
    """threshold/gamma accept optax-style schedules evaluated from the
    state's own counter (larq HyperparameterScheduler capability): a
    threshold that jumps high after step 0 blocks the step-1 flip that a
    constant threshold would have made."""
    sched = optax.piecewise_constant_schedule(0.1, {1: 1e6})
    tx = scale_by_bop(threshold=sched, gamma=1.0)
    w = jnp.array([1.0])
    g = jnp.array([0.5])  # Same sign, |m| > 0.1 every step.
    state = tx.init(w)
    updates, state = tx.update(g, state, w)
    w1 = optax.apply_updates(w, updates)
    assert float(w1[0]) == -1.0  # Step 0: threshold 0.1 -> flip.
    g2 = jnp.array([-0.5])  # Same sign as w1 now.
    updates, state = tx.update(g2, state, w1)
    w2 = optax.apply_updates(w1, updates)
    assert float(w2[0]) == -1.0  # Step 1: threshold 1e6 -> no flip.


def test_scale_by_bop_state_structure_stable_under_scheduling():
    """Scheduled and constant Bop share one state structure, so
    checkpoints are interchangeable between the two."""
    w = {"k": jnp.ones((2,))}
    s_const = scale_by_bop(threshold=0.1, gamma=0.5).init(w)
    s_sched = scale_by_bop(
        threshold=optax.constant_schedule(0.1), gamma=0.5
    ).init(w)
    assert jax.tree.structure(s_const) == jax.tree.structure(s_sched)


@pytest.mark.slow
def test_bop_component_gamma_schedule_runs():
    """gamma_schedule configured by subclass name drives the binary side;
    the step still trains end-to-end."""
    opt = Bop()
    configure(
        opt,
        {
            "gamma_schedule": "PolynomialDecay",
            "gamma_schedule.base_lr": 1e-2,
            "gamma_schedule.end_lr": 1e-4,
        },
        name="opt",
    )
    from zookeeper_tpu.training import make_train_step

    state, input_shape = _quicknet_tiny_state(opt)
    step = jax.jit(make_train_step())
    rng = np.random.default_rng(0)
    batch = {
        "input": jnp.asarray(rng.normal(size=(4, *input_shape)), jnp.float32),
        "target": jnp.asarray(rng.integers(0, 4, 4)),
    }
    _, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))


def test_bop_rejects_flat_knob_plus_schedule():
    opt = Bop()
    configure(
        opt,
        {"gamma": 1e-3, "gamma_schedule.base_lr": 1e-3},
        name="opt",
    )
    with pytest.raises(ValueError, match="two sources of truth"):
        opt.build(total_steps=10)
