"""Trace analysis (training/profiling.py): per-op device-time
attribution. Device planes exist only in real accelerator traces, so the
parsing contract is tested against a synthetically built xplane proto —
the same schema the profiler writes (verified against real TPU dumps;
the BASELINE.md round-5 attributions use exactly this reader).

The load-bearing design point pinned here: attribution comes from XLA's
per-op stats (hlo_category / flops / bytes_accessed), NEVER from op-name
substrings — ``%convert_reduce_fusion`` (a BN reduction) contains
"conv", and real convolutions lower to plain ``%fusion.N`` names, so
name bucketing misattributes in both directions.
"""

import os

import pytest

tsl_xplane = pytest.importorskip("tensorflow.tsl.profiler.protobuf.xplane_pb2")

from zookeeper_tpu.training.profiling import (  # noqa: E402
    device_op_stats,
    format_breakdown,
    op_time_breakdown,
)

# (name, category, duration_ms per event, events, flops_each, bytes_each)
_OPS = (
    # A real conv fusion: compute-bound (ideal compute >> ideal memory).
    ("%fusion.7 = bf16[128,28,28,256] fusion(...)", "convolution fusion",
     3.0, 2, 5.0e9, 1.0e6),
    # The name trap: contains "conv", IS a bandwidth-bound BN reduction.
    ("%convert_reduce_fusion.1 = (f32[64], f32[64]) fusion(...)",
     "loop fusion", 2.0, 1, 1.0e6, 500.0e6),
    # Layout traffic with no flops/bytes stats: unattributed in roofline.
    ("%copy.3 = bf16[8,8] copy(...)", "copy-done", 1.0, 1, 0, 0),
)


def _add_device_plane(space, plane_name):
    plane = space.planes.add()
    plane.name = plane_name
    # Stat metadata ids shared by plane + event stats.
    stat_ids = {}
    for i, key in enumerate(
        ("hlo_category", "flops", "bytes_accessed",
         "peak_teraflops_per_second", "peak_hbm_bw_gigabytes_per_second"),
        start=1,
    ):
        plane.stat_metadata[i].id = i
        plane.stat_metadata[i].name = key
        stat_ids[key] = i
    for key, value in (
        ("peak_teraflops_per_second", 200.0),
        ("peak_hbm_bw_gigabytes_per_second", 800.0),
    ):
        s = plane.stats.add()
        s.metadata_id = stat_ids[key]
        s.double_value = value
    line = plane.lines.add()
    line.name = "XLA Ops"
    for op_id, (name, category, dur_ms, n_events, flops, nbytes) in enumerate(
        _OPS, start=1
    ):
        meta = plane.event_metadata[op_id]
        meta.id = op_id
        meta.name = name
        s = meta.stats.add()
        s.metadata_id = stat_ids["hlo_category"]
        s.str_value = category
        if flops:
            s = meta.stats.add()
            s.metadata_id = stat_ids["flops"]
            s.double_value = flops
        if nbytes:
            s = meta.stats.add()
            s.metadata_id = stat_ids["bytes_accessed"]
            s.double_value = nbytes
        for _ in range(n_events):
            ev = line.events.add()
            ev.metadata_id = op_id
            ev.duration_ps = int(dur_ms * 1e9)
    # A decoy line that must be ignored.
    plane.lines.add().name = "Steps"


def _write_fake_trace(tmp_path, n_device_planes=1):
    space = tsl_xplane.XSpace()
    for i in range(n_device_planes):
        _add_device_plane(space, f"/device:TPU:{i}")
    # A host plane that must be ignored.
    space.planes.add().name = "/host:CPU"
    nested = tmp_path / "plugins" / "profile" / "run1"
    os.makedirs(nested)
    (nested / "host0.xplane.pb").write_bytes(space.SerializeToString())
    return str(tmp_path)


def test_device_op_stats(tmp_path):
    data = device_op_stats(_write_fake_trace(tmp_path))
    assert data["peak_flops_per_sec"] == pytest.approx(200e12)
    assert data["peak_bytes_per_sec"] == pytest.approx(800e9)
    by_name = {op["name"]: op for op in data["ops"]}
    conv = by_name[_OPS[0][0]]
    assert conv["category"] == "convolution fusion"
    assert conv["seconds"] == pytest.approx(6e-3)  # 2 events x 3 ms
    assert conv["count"] == 2
    assert conv["flops"] == pytest.approx(1.0e10)  # per-event x count


def test_breakdown_categories_and_roofline(tmp_path):
    trace_dir = _write_fake_trace(tmp_path)
    b = op_time_breakdown(trace_dir, steps=2)
    assert b["total_ms_per_step"] == pytest.approx(4.5)  # 9 ms / 2
    cats = b["by_category"]
    assert cats["convolution fusion"]["ms_per_step"] == pytest.approx(3.0)
    # The "conv"-substring BN reduction lands in ITS category, not conv.
    assert cats["loop fusion"]["share"] == pytest.approx(2 / 9)

    roof = b["roofline"]
    # conv fusion: 5e9/200e12 = 25 us compute vs 1e6/800e9 ~ 1.3 us mem
    # -> compute-bound; BN reduce: 5 ns compute vs 625 us mem ->
    # bandwidth-bound; copy: no stats -> unattributed.
    assert roof["compute_bound_ms_per_step"] == pytest.approx(3.0)
    assert roof["bandwidth_bound_ms_per_step"] == pytest.approx(1.0)
    assert roof["unattributed_ms_per_step"] == pytest.approx(0.5)
    assert roof["compute_bound_share"] == pytest.approx(6 / 9)

    # Per-op achieved-bandwidth columns: (ms, category, name, bytes/s,
    # fraction of HBM peak); ops without bytes stats carry None.
    top = {row[2]: row for row in b["top_ops"]}
    bn = top[_OPS[1][0]]
    assert bn[3] == pytest.approx(500e6 / 2e-3)  # 250 GB/s achieved
    assert bn[4] == pytest.approx(250e9 / 800e9)  # 31% of peak
    copy = top[_OPS[2][0]]
    assert copy[3] is None and copy[4] is None

    text = format_breakdown(b)
    assert "4.50 ms/step" in text
    assert "convolution fusion" in text
    assert "compute-bound ops 3.00 ms (67%)" in text
    assert "250 GB/s   31%" in text
    # The no-overlap roofline lower bounds (sums of per-op ideals).
    assert "roofline lower bounds" in text


def test_peak_overrides_change_classification(tmp_path):
    trace_dir = _write_fake_trace(tmp_path)
    # With an absurdly slow compute peak EVERY attributed op (incl. the
    # BN reduction) flips compute-bound — classification must follow the
    # OVERRIDDEN peaks, not the plane's.
    b = op_time_breakdown(
        trace_dir, steps=2, peak_flops_per_sec=1e9,
        peak_bytes_per_sec=800e9,
    )
    assert b["roofline"]["compute_bound_ms_per_step"] == pytest.approx(4.0)
    b2 = op_time_breakdown(
        trace_dir, steps=2, peak_flops_per_sec=1e20,
        peak_bytes_per_sec=1.0,
    )
    assert b2["roofline"]["bandwidth_bound_ms_per_step"] == pytest.approx(
        4.0
    )


def test_single_plane_semantics(tmp_path):
    """Multi-chip dumps (one plane per local device, SPMD-identical
    programs) must report PER-DEVICE numbers, not a sum over planes —
    and the substring filter selects a specific plane."""
    trace_dir = _write_fake_trace(tmp_path, n_device_planes=4)
    b = op_time_breakdown(trace_dir, steps=2)
    assert b["total_ms_per_step"] == pytest.approx(4.5)  # not 4x
    times = device_op_stats(trace_dir, device_substring="TPU:3")
    assert sum(op["seconds"] for op in times["ops"]) == pytest.approx(9e-3)


def test_device_filter_and_errors(tmp_path):
    trace_dir = _write_fake_trace(tmp_path)
    with pytest.raises(ValueError, match="XLA Ops"):
        device_op_stats(trace_dir, device_substring="TPU:7")
    with pytest.raises(FileNotFoundError, match="xplane"):
        device_op_stats(str(tmp_path / "empty"))


def test_cli_category_filter(tmp_path, capsys):
    """The analyzer CLI's --category/--min-ms flags narrow the top-op
    list (the relayout-copy hunting workflow) without touching the
    per-category totals."""
    from zookeeper_tpu.training.profiling import _main

    trace_dir = _write_fake_trace(tmp_path)
    _main([trace_dir, "--steps", "2", "--category", "copy-done"])
    out = capsys.readouterr().out
    assert "4.50 ms/step" in out  # totals still cover everything
    # Top-op rows: only the copy (data formatting) survives the filter.
    top_lines = out.split("top ops")[1]
    assert "copy" in top_lines
    assert "%fusion.7" not in top_lines

    _main([trace_dir, "--steps", "2", "--min-ms", "10.0"])
    out = capsys.readouterr().out
    assert "4.50 ms/step" in out
    assert out.split("top ops")[1].strip().count("\n") == 0  # all filtered
