"""Real-data accuracy anchors (VERDICT round-1 missing #4; SURVEY §6).

SklearnDigits is genuine handwritten-digit data (offline, bundled with
scikit-learn). Training to high validation accuracy on it is evidence no
loss/gradient/pipeline bug survives — for BOTH the fp stack and the
binary (STE quantizer) stack.
"""

import numpy as np
import pytest

from zookeeper_tpu.core import configure
from zookeeper_tpu.training import TrainingExperiment

pytest.importorskip("sklearn")


def _digits_conf(extra=None):
    return {
        "loader.dataset": "SklearnDigits",
        "loader.preprocessing": "ImageClassificationPreprocessing",
        "loader.preprocessing.height": 8,
        "loader.preprocessing.width": 8,
        "loader.preprocessing.channels": 1,
        "loader.host_index": 0,
        "loader.host_count": 1,
        "batch_size": 64,
        "verbose": False,
        **(extra or {}),
    }


@pytest.mark.slow
def test_fp_model_learns_real_digits():
    """SimpleCnn reaches >=90% validation accuracy on real handwritten
    digits in a few epochs — far above the 10% chance floor."""
    exp = TrainingExperiment()
    configure(
        exp,
        _digits_conf({
            "model": "SimpleCnn",
            "model.features": (16, 32),
            "model.dense_units": (64,),
            "epochs": 5,
        }),
        name="experiment",
    )
    history = exp.run()
    val_acc = history["validation"][-1]["accuracy"]
    assert val_acc >= 0.90, f"val accuracy {val_acc:.3f} < 0.90"


@pytest.mark.slow
def test_binary_model_learns_real_digits():
    """BinaryNet (ste_sign activations AND weights, latent training)
    reaches >=80% validation accuracy on real digits — the full STE
    quantizer stack learns on actual data, not just synthetic."""
    exp = TrainingExperiment()
    configure(
        exp,
        _digits_conf({
            "model": "BinaryNet",
            "model.features": (32, 32),
            "model.dense_units": (64,),
            "epochs": 8,
            "optimizer.schedule.base_lr": 5e-3,
        }),
        name="experiment",
    )
    history = exp.run()
    val_acc = history["validation"][-1]["accuracy"]
    assert val_acc >= 0.80, f"val accuracy {val_acc:.3f} < 0.80"


def test_digits_split_is_deterministic_and_disjoint():
    from zookeeper_tpu.data import SklearnDigits

    ds = SklearnDigits()
    configure(ds, {"seed": 3}, name="ds")
    train, val = ds.train(), ds.validation()
    assert len(train) + len(val) == 1797
    assert ds.resolved_num_classes() == 10

    def stack(src):
        return np.stack([np.asarray(src[i]["image"]) for i in range(len(src))])

    train_imgs, val_imgs = stack(train), stack(val)
    # Disjoint: no validation image appears in the train split (images
    # are 8x8 uint8 — compare raw bytes).
    train_set = {img.tobytes() for img in train_imgs}
    overlap = sum(img.tobytes() in train_set for img in val_imgs)
    # The digits corpus contains a handful of duplicate scans; a leaked
    # SPLIT would overlap in the hundreds.
    assert overlap <= 20, f"{overlap} validation images found in train"

    # Deterministic: a second instance with the same seed yields the
    # SAME full ordering, not just the first element.
    ds2 = SklearnDigits()
    configure(ds2, {"seed": 3}, name="ds2")
    np.testing.assert_array_equal(train_imgs, stack(ds2.train()))
    np.testing.assert_array_equal(
        np.asarray([train[i]["label"] for i in range(len(train))]),
        np.asarray([ds2.train()[i]["label"] for i in range(len(train))]),
    )


@pytest.mark.slow
def test_quicknet_flagship_learns_real_digits():
    """The flagship family (QuickNet: residual binary convs, blurpool
    transitions, synced BN) reaches >=85% validation accuracy on real
    digits through the resize path — the full north-star training stack
    learns on actual data."""
    exp = TrainingExperiment()
    configure(
        exp,
        _digits_conf({
            "loader.preprocessing.height": 32,
            "loader.preprocessing.width": 32,
            "loader.preprocessing.resize": True,
            "model": "QuickNet",
            "model.blocks_per_section": (1, 1),
            "model.section_features": (16, 32),
            "epochs": 8,
            "optimizer.schedule.base_lr": 3e-3,
        }),
        name="experiment",
    )
    history = exp.run()
    best = max(v["accuracy"] for v in history["validation"])
    assert best >= 0.85, f"best val accuracy {best:.3f} < 0.85"


@pytest.mark.slow
def test_birealnet_family_learns_real_digits():
    """Bi-Real-Net (magnitude_aware_sign kernels, per-conv real-valued
    residual shortcuts — a different quantizer family and block
    structure than QuickNet) reaches >=80% validation accuracy on real
    digits through the resize path."""
    exp = TrainingExperiment()
    configure(
        exp,
        _digits_conf({
            "loader.preprocessing.height": 32,
            "loader.preprocessing.width": 32,
            "loader.preprocessing.resize": True,
            "model": "BiRealNet",
            "model.blocks_per_section": (1, 1),
            "model.section_features": (16, 32),
            "epochs": 8,
            "optimizer.schedule.base_lr": 3e-3,
        }),
        name="experiment",
    )
    history = exp.run()
    best = max(v["accuracy"] for v in history["validation"])
    assert best >= 0.80, f"best val accuracy {best:.3f} < 0.80"


@pytest.mark.slow
def test_reactnet_family_learns_real_digits():
    """ReActNet (learnable RSign thresholds + RPReLU activations — the
    only family whose BINARIZATION is itself trained) reaches >=80%
    validation accuracy on real digits: evidence the learnable-shift
    gradients flow end-to-end, not just per-layer."""
    exp = TrainingExperiment()
    configure(
        exp,
        _digits_conf({
            "loader.preprocessing.height": 32,
            "loader.preprocessing.width": 32,
            "loader.preprocessing.resize": True,
            "model": "ReActNet",
            # Calibrated: (16,32,32)x8ep plateaus at ~64% — the
            # sign-threshold/RPReLU machinery needs real width to pay
            # off; this config measures 93% (margin over the 80% gate).
            "model.features": (32, 64, 64, 128),
            "model.strides": (1, 2, 1),
            "epochs": 12,
            "optimizer.schedule.base_lr": 5e-3,
        }),
        name="experiment",
    )
    history = exp.run()
    best = max(v["accuracy"] for v in history["validation"])
    assert best >= 0.80, f"best val accuracy {best:.3f} < 0.80"


@pytest.mark.slow
def test_binary_densenet_family_learns_real_digits():
    """BinaryDenseNet (concat growth instead of residual addition — the
    structurally-different capacity mechanism) reaches >=80% validation
    accuracy on real digits."""
    exp = TrainingExperiment()
    configure(
        exp,
        _digits_conf({
            "loader.preprocessing.height": 32,
            "loader.preprocessing.width": 32,
            "loader.preprocessing.resize": True,
            "model": "BinaryDenseNet28",
            "model.layers_per_block": (3, 3),
            "model.reduction": (2.0,),
            "model.growth_rate": 16,
            "model.initial_features": 16,
            "epochs": 8,
            "optimizer.schedule.base_lr": 3e-3,
        }),
        name="experiment",
    )
    history = exp.run()
    best = max(v["accuracy"] for v in history["validation"])
    assert best >= 0.80, f"best val accuracy {best:.3f} < 0.80"


@pytest.mark.slow
def test_meliusnet_family_learns_real_digits():
    """MeliusNet (dense-then-improve dual blocks: concat growth refined
    by residual improvement convs) reaches >=80% validation accuracy on
    real digits."""
    exp = TrainingExperiment()
    configure(
        exp,
        _digits_conf({
            "loader.preprocessing.height": 32,
            "loader.preprocessing.width": 32,
            "loader.preprocessing.resize": True,
            "model": "MeliusNet22",
            "model.blocks_per_section": (2, 2),
            "model.transition_features": (32,),
            "model.growth": 16,
            "model.stem_features": 16,
            "epochs": 8,
            "optimizer.schedule.base_lr": 3e-3,
        }),
        name="experiment",
    )
    history = exp.run()
    best = max(v["accuracy"] for v in history["validation"])
    assert best >= 0.80, f"best val accuracy {best:.3f} < 0.80"
