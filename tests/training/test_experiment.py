import pytest

from zookeeper_tpu.core import configure
from zookeeper_tpu.training import TrainingExperiment


def make_experiment(extra_conf=None):
    exp = TrainingExperiment()
    conf = {
        "loader.dataset": "SyntheticMnist",
        "loader.dataset.num_train_examples": 256,
        "loader.dataset.num_validation_examples": 64,
        "loader.preprocessing": "ImageClassificationPreprocessing",
        "loader.preprocessing.height": 28,
        "loader.preprocessing.width": 28,
        "loader.preprocessing.channels": 1,
        "loader.host_index": 0,
        "loader.host_count": 1,
        "model": "Mlp",
        "model.hidden_units": (32,),
        "batch_size": 32,
        "epochs": 2,
        "verbose": False,
        **(extra_conf or {}),
    }
    configure(exp, conf, name="experiment")
    return exp


def test_experiment_end_to_end_learns():
    exp = make_experiment()
    history = exp.run()
    assert len(history["train"]) == 2
    assert len(history["validation"]) == 2
    # Synthetic data has real signal: accuracy should clearly beat chance.
    assert history["validation"][-1]["accuracy"] > 0.3
    assert history["train"][1]["loss"] < history["train"][0]["loss"]
    assert history["train"][0]["examples_per_sec"] > 0


def test_experiment_batch_size_inherited_by_loader():
    exp = make_experiment()
    assert exp.loader.batch_size == 32
    assert exp.loader.per_host_batch_size == 32


def test_experiment_steps_per_epoch_cap():
    exp = make_experiment({"steps_per_epoch": 2, "epochs": 1})
    history = exp.run()
    assert len(history["train"]) == 1
    # 2 steps * 32 per batch.
    assert exp._steps_per_epoch() == 2


def test_experiment_data_parallel_on_cpu_mesh():
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs multi-device (conftest forces 8 CPU devices)")
    exp = make_experiment(
        {"partitioner": "DataParallelPartitioner", "epochs": 1}
    )
    history = exp.run()
    assert history["validation"][-1]["accuracy"] > 0.2


def test_experiment_num_classes_derived_from_dataset():
    exp = make_experiment({"loader.dataset.num_classes": 7})
    assert exp.num_classes == 7


def test_ema_tracked_evaluated_and_exported(tmp_path):
    """ema_decay wires EMA through state, train step, validation, export,
    and checkpoint resume."""
    import jax
    import numpy as np

    from zookeeper_tpu.core import configure
    from zookeeper_tpu.training import TrainingExperiment, load_model

    export = str(tmp_path / "ema_export")
    exp = TrainingExperiment()
    configure(
        exp,
        {
            "loader.dataset": "SyntheticMnist",
            "loader.dataset.num_train_examples": 64,
            "loader.dataset.num_validation_examples": 32,
            "loader.preprocessing": "ImageClassificationPreprocessing",
            "loader.preprocessing.height": 28,
            "loader.preprocessing.width": 28,
            "loader.preprocessing.channels": 1,
            "loader.host_index": 0,
            "loader.host_count": 1,
            "model": "Mlp",
            "model.hidden_units": (16,),
            "batch_size": 32,
            "epochs": 2,
            "verbose": False,
            "ema_decay": 0.9,
            "export_model_to": export,
            "checkpointer.directory": str(tmp_path / "ckpt"),
            "checkpointer.synchronous": True,
        },
        name="experiment",
    )
    history = exp.run()
    state = exp.final_state
    assert state.ema_params is not None
    # EMA lags the raw params (decay 0.9 over 4 steps — must differ).
    diffs = [
        float(np.abs(np.asarray(e) - np.asarray(p)).max())
        for e, p in zip(
            jax.tree.leaves(state.ema_params), jax.tree.leaves(state.params)
        )
    ]
    assert max(diffs) > 0
    # Export holds the EMA params, not the raw ones.
    exported, _ = load_model(export, state.params, state.model_state)
    for a, b in zip(jax.tree.leaves(exported), jax.tree.leaves(state.ema_params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert history["validation"]  # Validation ran (on EMA weights).

    # Resume restores the EMA buffer exactly.
    exp2 = TrainingExperiment()
    configure(
        exp2,
        {
            "loader.dataset": "SyntheticMnist",
            "loader.dataset.num_train_examples": 64,
            "loader.dataset.num_validation_examples": 32,
            "loader.preprocessing": "ImageClassificationPreprocessing",
            "loader.preprocessing.height": 28,
            "loader.preprocessing.width": 28,
            "loader.preprocessing.channels": 1,
            "loader.host_index": 0,
            "loader.host_count": 1,
            "model": "Mlp",
            "model.hidden_units": (16,),
            "batch_size": 32,
            "epochs": 2,
            "verbose": False,
            "ema_decay": 0.9,
            "checkpointer.directory": str(tmp_path / "ckpt"),
            "checkpointer.synchronous": True,
        },
        name="experiment",
    )
    exp2.run()  # 0 additional epochs; restores state.
    for a, b in zip(
        jax.tree.leaves(exp2.final_state.ema_params),
        jax.tree.leaves(state.ema_params),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    exp.checkpointer.close()
    exp2.checkpointer.close()


def test_ema_math_single_step():
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from zookeeper_tpu.core import configure
    from zookeeper_tpu.models import Mlp
    from zookeeper_tpu.training import TrainState, make_train_step

    m = Mlp()
    configure(m, {"hidden_units": (8,)}, name="m")
    module = m.build((4, 4, 1), num_classes=2)
    params, model_state = m.initialize(module, (4, 4, 1))
    state = TrainState.create(
        apply_fn=module.apply, params=params, model_state=model_state,
        tx=optax.sgd(0.1), ema=True,
    )
    step = jax.jit(make_train_step(ema_decay=0.5))
    batch = {
        "input": jnp.ones((4, 4, 4, 1), jnp.float32),
        "target": jnp.zeros((4,), jnp.int32),
    }
    new_state, _ = step(state, batch)
    # ema_1 = 0.5 * params_0 + 0.5 * params_1 (ema_0 == params_0).
    for e, p0, p1 in zip(
        jax.tree.leaves(new_state.ema_params),
        jax.tree.leaves(state.params),
        jax.tree.leaves(new_state.params),
    ):
        np.testing.assert_allclose(
            np.asarray(e), 0.5 * np.asarray(p0) + 0.5 * np.asarray(p1),
            rtol=1e-6,
        )


def _ema_toggle_conf(tmp_path, ema_decay):
    return {
        "loader.dataset": "SyntheticMnist",
        "loader.dataset.num_train_examples": 64,
        "loader.dataset.num_validation_examples": 32,
        "loader.preprocessing": "ImageClassificationPreprocessing",
        "loader.preprocessing.height": 28,
        "loader.preprocessing.width": 28,
        "loader.preprocessing.channels": 1,
        "loader.host_index": 0,
        "loader.host_count": 1,
        "model": "Mlp",
        "model.hidden_units": (16,),
        "batch_size": 32,
        "epochs": 2,
        "verbose": False,
        "ema_decay": ema_decay,
        "checkpointer.directory": str(tmp_path / "ckpt"),
        "checkpointer.synchronous": True,
    }


@pytest.mark.parametrize("first,second", [(0.0, 0.9), (0.9, 0.0)])
def test_ema_toggle_across_resume(tmp_path, first, second):
    """Toggling ema_decay between runs sharing a checkpoint directory
    must restore gracefully (on->off drops the buffer; off->on seeds the
    EMA from the restored params)."""
    import jax
    import numpy as np

    from zookeeper_tpu.core import configure
    from zookeeper_tpu.training import TrainingExperiment

    exp = TrainingExperiment()
    configure(exp, _ema_toggle_conf(tmp_path, first), name="experiment")
    exp.run()
    exp.checkpointer.close()

    exp2 = TrainingExperiment()
    conf = _ema_toggle_conf(tmp_path, second)
    conf["epochs"] = 3  # One more epoch so the resumed run trains.
    configure(exp2, conf, name="experiment")
    history = exp2.run()
    assert len(history["train"]) == 1
    if second > 0:
        assert exp2.final_state.ema_params is not None
        for leaf in jax.tree.leaves(exp2.final_state.ema_params):
            assert np.all(np.isfinite(np.asarray(leaf)))
    else:
        assert exp2.final_state.ema_params is None
    exp2.checkpointer.close()


def test_ema_decay_out_of_range_rejected(tmp_path):
    from zookeeper_tpu.core import configure
    from zookeeper_tpu.training import TrainingExperiment

    exp = TrainingExperiment()
    configure(exp, _ema_toggle_conf(tmp_path, 1.0), name="experiment")
    with pytest.raises(ValueError, match="ema_decay"):
        exp.run()


def test_eval_experiment_scores_exported_model(tmp_path):
    """Train -> export -> EvalExperiment reproduces the final validation
    accuracy from the exported model-only checkpoint."""
    import numpy as np

    from zookeeper_tpu.core import configure as _cfg
    from zookeeper_tpu.training import EvalExperiment, TrainingExperiment

    export = str(tmp_path / "model")
    exp = TrainingExperiment()
    _cfg(
        exp,
        {
            "loader.dataset": "SklearnDigits",
            "loader.preprocessing": "ImageClassificationPreprocessing",
            "loader.preprocessing.height": 8,
            "loader.preprocessing.width": 8,
            "loader.preprocessing.channels": 1,
            "loader.host_index": 0,
            "loader.host_count": 1,
            "model": "Mlp",
            "model.hidden_units": (32,),
            "batch_size": 64,
            "epochs": 2,
            "verbose": False,
            "export_model_to": export,
        },
        name="experiment",
    )
    history = exp.run()
    trained_acc = history["validation"][-1]["accuracy"]

    ev = EvalExperiment()
    _cfg(
        ev,
        {
            "loader.dataset": "SklearnDigits",
            "loader.preprocessing": "ImageClassificationPreprocessing",
            "loader.preprocessing.height": 8,
            "loader.preprocessing.width": 8,
            "loader.preprocessing.channels": 1,
            "loader.host_index": 0,
            "loader.host_count": 1,
            # Mirror the training loop's validation batching exactly
            # (drop_remainder) so the scores must agree to the bit; the
            # full-coverage default is pinned by the test below.
            "loader.drop_remainder": True,
            "model": "Mlp",
            "model.hidden_units": (32,),
            "batch_size": 64,
            "verbose": False,
            "checkpoint": export,
        },
        name="eval",
    )
    metrics = ev.run()
    assert metrics["accuracy"] == pytest.approx(trained_acc, abs=1e-6)
    assert np.isfinite(metrics["loss"])


def test_eval_experiment_full_coverage_and_train_split(tmp_path):
    """EvalExperiment scores EVERY example (partial tail batch included)
    and can score the train split in eval mode; unknown splits raise."""
    import numpy as np

    from zookeeper_tpu.core import configure as _cfg
    from zookeeper_tpu.training import EvalExperiment, TrainingExperiment

    export = str(tmp_path / "model")
    conf = {
        "loader.dataset": "SklearnDigits",
        "loader.preprocessing": "ImageClassificationPreprocessing",
        "loader.preprocessing.height": 8,
        "loader.preprocessing.width": 8,
        "loader.preprocessing.channels": 1,
        "loader.host_index": 0,
        "loader.host_count": 1,
        "model": "Mlp",
        "model.hidden_units": (32,),
        "batch_size": 64,
        "verbose": False,
    }
    exp = TrainingExperiment()
    _cfg(exp, {**conf, "epochs": 1, "export_model_to": export}, name="e")
    exp.run()

    # 359 validation examples, batch 64: 5 full + 1 partial batch. The
    # eval must consume all 359 (drop_remainder=False default).
    ev = EvalExperiment()
    _cfg(ev, {**conf, "checkpoint": export}, name="ev")
    seen = 0
    for batch in ev.loader.batches("validation", training=False):
        seen += batch["target"].shape[0]
    assert seen == ev.loader.dataset.num_examples("validation")
    metrics = ev.run()
    assert np.isfinite(metrics["loss"])

    # Train split in eval mode works and is deterministic.
    ev_train = EvalExperiment()
    _cfg(ev_train, {**conf, "checkpoint": export, "split": "train"}, name="evt")
    m1 = ev_train.run()
    assert np.isfinite(m1["accuracy"])

    ev_bad = EvalExperiment()
    _cfg(ev_bad, {**conf, "checkpoint": export, "split": "test"}, name="evb")
    import pytest as _pytest

    with _pytest.raises(ValueError, match="split"):
        ev_bad.run()


def test_early_stopping_halts_on_plateau():
    """Keras EarlyStopping capability: an impossible min_delta means no
    epoch ever 'improves', so training stops after exactly
    1 (baseline) + patience epochs instead of running all 10."""
    exp = make_experiment(
        {
            "epochs": 10,
            "steps_per_epoch": 2,
            "early_stop_metric": "loss",
            "early_stop_patience": 2,
            "early_stop_min_delta": 1e9,
        }
    )
    history = exp.run()
    assert len(history["train"]) == 3  # baseline epoch + 2 stale epochs


def test_early_stopping_runs_to_completion_when_improving():
    exp = make_experiment(
        {
            "epochs": 3,
            "steps_per_epoch": 4,
            "early_stop_metric": "accuracy",
            "early_stop_patience": 3,
        }
    )
    history = exp.run()
    assert len(history["train"]) == 3


def test_early_stopping_unknown_metric_raises():
    exp = make_experiment(
        {"epochs": 2, "steps_per_epoch": 1, "early_stop_metric": "f1"}
    )
    with pytest.raises(ValueError, match="not in epoch metrics"):
        exp.run()


def test_early_stopping_bad_mode_rejected():
    exp = make_experiment({"early_stop_mode": "upwards"})
    with pytest.raises(ValueError, match="early_stop_mode"):
        exp.run()


def test_print_model_summary_runs(capsys):
    exp = make_experiment(
        {
            "epochs": 1,
            "steps_per_epoch": 1,
            "verbose": True,
            "print_model_summary": True,
        }
    )
    exp.run()
    out = capsys.readouterr().out
    assert "params" in out and "Dense_0/kernel" in out


def test_validate_every_cadence():
    """Keras validation_freq capability: validation runs every N epochs;
    best-checkpoint/early-stop scoring uses the latest (possibly stale)
    validation metrics."""
    exp = make_experiment(
        {"epochs": 4, "steps_per_epoch": 2, "validate_every": 2}
    )
    history = exp.run()
    assert len(history["train"]) == 4
    assert len(history["validation"]) == 2


def test_validate_every_does_not_burn_early_stop_patience():
    """Skipped-validation epochs must not tick early-stop patience: with
    validate_every=5 and patience=3 over 10 epochs, only epochs 5 and 10
    are scored, so a never-improving metric still cannot stop before
    epoch 10 (two scored epochs < patience 3)."""
    exp = make_experiment(
        {
            "epochs": 10,
            "steps_per_epoch": 1,
            "validate_every": 5,
            "early_stop_metric": "loss",
            "early_stop_patience": 3,
            "early_stop_min_delta": 1e9,
        }
    )
    history = exp.run()
    assert len(history["train"]) == 10
    assert len(history["validation"]) == 2


def test_validate_every_zero_rejected():
    exp = make_experiment({"validate_every": 0})
    with pytest.raises(ValueError, match="validate_every"):
        exp.run()


def test_profile_dir_captures_trace(tmp_path):
    """SURVEY §5 tracing row: profile_dir captures a jax.profiler trace
    of steady-state steps (works on CPU; produces a perfetto/xplane
    artifact under plugins/profile)."""
    import os

    profile_dir = str(tmp_path / "trace")
    exp = make_experiment(
        {
            "epochs": 1,
            "steps_per_epoch": 6,
            "profile_dir": profile_dir,
        }
    )
    exp.run()
    found = []
    for root, _dirs, files in os.walk(profile_dir):
        found.extend(os.path.join(root, f) for f in files)
    assert found, f"no profiler artifacts under {profile_dir}"


def test_label_smoothing_and_top5_in_loop():
    exp = make_experiment(
        {
            "epochs": 1,
            "steps_per_epoch": 3,
            "label_smoothing": 0.1,
            "track_top5": True,
        }
    )
    history = exp.run()
    assert "top5_accuracy" in history["validation"][0]
    v = history["validation"][0]
    assert v["top5_accuracy"] >= v["accuracy"] - 1e-6


def test_track_top5_rejected_for_few_classes():
    exp = make_experiment(
        {
            "loader.dataset.num_classes": 3,
            "track_top5": True,
        }
    )
    with pytest.raises(ValueError, match="track_top5"):
        exp.run()


def test_label_smoothing_out_of_range_rejected():
    exp = make_experiment({"label_smoothing": 1.5})
    with pytest.raises(ValueError, match="label_smoothing"):
        exp.run()


def test_runtime_initialize_invoked_and_single_process_unchanged():
    """The DistributedRuntime component is actually WIRED: run() calls
    runtime.initialize() before mesh construction — and on a single
    process the call changes nothing (params bit-identical to a run
    with the runtime disabled)."""
    import numpy as np

    calls = []
    exp = make_experiment({"epochs": 1, "validate": False})
    orig = exp.runtime.initialize
    exp.runtime.initialize = lambda: (calls.append(1), orig())[1]
    exp.run()
    assert calls == [1]

    disabled = make_experiment(
        {"epochs": 1, "validate": False, "runtime.enabled": False}
    )
    disabled.run()
    import jax

    for a, b in zip(
        jax.tree.leaves(exp.final_state.params),
        jax.tree.leaves(disabled.final_state.params),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
