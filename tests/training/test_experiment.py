import pytest

from zookeeper_tpu.core import configure
from zookeeper_tpu.training import TrainingExperiment


def make_experiment(extra_conf=None):
    exp = TrainingExperiment()
    conf = {
        "loader.dataset": "SyntheticMnist",
        "loader.dataset.num_train_examples": 256,
        "loader.dataset.num_validation_examples": 64,
        "loader.preprocessing": "ImageClassificationPreprocessing",
        "loader.preprocessing.height": 28,
        "loader.preprocessing.width": 28,
        "loader.preprocessing.channels": 1,
        "loader.host_index": 0,
        "loader.host_count": 1,
        "model": "Mlp",
        "model.hidden_units": (32,),
        "batch_size": 32,
        "epochs": 2,
        "verbose": False,
        **(extra_conf or {}),
    }
    configure(exp, conf, name="experiment")
    return exp


def test_experiment_end_to_end_learns():
    exp = make_experiment()
    history = exp.run()
    assert len(history["train"]) == 2
    assert len(history["validation"]) == 2
    # Synthetic data has real signal: accuracy should clearly beat chance.
    assert history["validation"][-1]["accuracy"] > 0.3
    assert history["train"][1]["loss"] < history["train"][0]["loss"]
    assert history["train"][0]["examples_per_sec"] > 0


def test_experiment_batch_size_inherited_by_loader():
    exp = make_experiment()
    assert exp.loader.batch_size == 32
    assert exp.loader.per_host_batch_size == 32


def test_experiment_steps_per_epoch_cap():
    exp = make_experiment({"steps_per_epoch": 2, "epochs": 1})
    history = exp.run()
    assert len(history["train"]) == 1
    # 2 steps * 32 per batch.
    assert exp._steps_per_epoch() == 2


def test_experiment_data_parallel_on_cpu_mesh():
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs multi-device (conftest forces 8 CPU devices)")
    exp = make_experiment(
        {"partitioner": "DataParallelPartitioner", "epochs": 1}
    )
    history = exp.run()
    assert history["validation"][-1]["accuracy"] > 0.2


def test_experiment_num_classes_derived_from_dataset():
    exp = make_experiment({"loader.dataset.num_classes": 7})
    assert exp.num_classes == 7
