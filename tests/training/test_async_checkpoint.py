"""Async tiered checkpointing (training.async_checkpoint + the
Checkpointer's mode="async"): crash-consistent finalize under injected
kills/finalize failures, writer-thread IO-failure isolation, queue
policies, retention tiers, and restore-vs-GC races — every leg walked
deterministically (docs/DESIGN.md §12)."""

import logging
import os
import shutil
import threading

import numpy as np
import pytest

from zookeeper_tpu.core import configure
from zookeeper_tpu.resilience import FaultPlan, faults
from zookeeper_tpu.training import Checkpointer, TrainingExperiment

pytestmark = pytest.mark.chaos


def make_experiment(extra_conf=None):
    exp = TrainingExperiment()
    conf = {
        "loader.dataset": "SyntheticMnist",
        "loader.dataset.num_train_examples": 256,
        "loader.dataset.num_validation_examples": 0,
        "loader.preprocessing": "ImageClassificationPreprocessing",
        "loader.preprocessing.height": 28,
        "loader.preprocessing.width": 28,
        "loader.preprocessing.channels": 1,
        "loader.host_index": 0,
        "loader.host_count": 1,
        "model": "Mlp",
        "model.hidden_units": (32,),
        "batch_size": 32,
        "epochs": 1,
        "validate": False,
        "verbose": False,
        **(extra_conf or {}),
    }
    configure(exp, conf, name="experiment")
    return exp


def async_conf(tmp_path, **extra):
    return {
        "checkpointer.directory": str(tmp_path / "ckpt"),
        "checkpointer.mode": "async",
        "checkpointer.save_every_epochs": 0,
        "checkpointer.save_retry_backoff_s": 0.0,
        **extra,
    }


def assert_states_equal(a, b):
    import jax

    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for xa, xb in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(xa), np.asarray(xb))


def _tiny_state(value: float, step: int):
    import jax.numpy as jnp
    import optax

    from zookeeper_tpu.training import TrainState

    state = TrainState.create(
        apply_fn=lambda *a, **k: None,
        params={"w": jnp.full((2,), value)},
        model_state={},
        tx=optax.sgd(0.1),
    )
    return state.replace(step=jnp.asarray(step))


def make_ckpt(tmp_path, **conf):
    ckpt = Checkpointer()
    configure(
        ckpt,
        {
            "directory": str(tmp_path / "ck"),
            "save_retry_backoff_s": 0.0,
            **conf,
        },
        name="ckpt",
    )
    return ckpt


# -- the async mode is the same checkpoint, written off-thread -----------


def test_async_saves_restore_bit_identical_to_sync(tmp_path):
    """An async-mode save of a state restores bit-identically to a
    sync-mode save of the same state: one write protocol, two threads."""
    for mode, sub in (("sync", "a"), ("async", "b")):
        ckpt = make_ckpt(tmp_path / sub, mode=mode)
        ckpt.save(_tiny_state(3.5, 7), step=7)
        ckpt.wait()
        restored = ckpt.restore_state(_tiny_state(0.0, 0))
        assert int(np.asarray(restored.step)) == 7
        np.testing.assert_array_equal(np.asarray(restored.params["w"]), 3.5)
        ckpt.close()


def test_async_mode_training_run_resumes_like_sync(tmp_path):
    """End to end: async step-cadence checkpoints from a real training
    run restore into an exact mid-epoch resume (the same contract the
    sync mode pins in test_checkpoint.py)."""
    import jax

    ref = make_experiment({"epochs": 2})
    ref.run()

    conf = async_conf(tmp_path, **{"checkpointer.save_every_steps": 3})
    exp = make_experiment({"epochs": 1, **conf})
    exp.run()
    assert exp.checkpointer.latest_step() == 6  # spe=8: saves at 3, 6
    exp.checkpointer.close()

    exp2 = make_experiment({"epochs": 2, **conf})
    exp2.run()
    assert int(jax.device_get(exp2.final_state.step)) == 16
    assert_states_equal(ref.final_state.params, exp2.final_state.params)
    assert_states_equal(
        ref.final_state.opt_state, exp2.final_state.opt_state
    )
    exp2.checkpointer.close()


def test_invalid_mode_and_policy_rejected(tmp_path):
    for bad in (
        {"checkpointer.mode": "background"},
        {"checkpointer.queue_policy": "drop"},
        {"checkpointer.durable_every_steps": -1},
        # supersede may drop a better-ranked queued snapshot for a
        # worse one: incompatible with best-ranking, by construction.
        {
            "checkpointer.queue_policy": "supersede",
            "checkpointer.keep_best_metric": "accuracy",
        },
    ):
        exp = make_experiment({**async_conf(tmp_path), **bad})
        with pytest.raises(ValueError):
            exp.run()


# -- chaos: kill mid-async-write -----------------------------------------


def test_kill_mid_async_write_restores_previous_finalized_step(tmp_path):
    """THE crash-consistency pin: an async write that dies mid-write
    (before its atomic finalize) leaves only an unfinalized remnant —
    restore lands on the PREVIOUS finalized step, bit-exactly."""
    import jax

    # Reference: the state after exactly 3 steps (the surviving save).
    ref = make_experiment({"steps_per_epoch": 3})
    ref.run()

    conf = async_conf(tmp_path, **{"checkpointer.save_every_steps": 3})
    exp = make_experiment(conf)
    with faults.injected(FaultPlan(kill_during_async_write=6)):
        exp.run()  # spe=8: step-3 save lands, step-6 write is torn
    exp.checkpointer.close()

    # The torn write is invisible to discovery (unfinalized name), and
    # its remnant is really on disk.
    root = str(tmp_path / "ckpt")
    names = os.listdir(root)
    assert any(n.startswith("6.") for n in names), names
    assert "6" not in names

    ckpt = Checkpointer()
    configure(ckpt, {"directory": root}, name="restore_ckpt")
    restored = ckpt.restore_state(
        exp.build_state()
    )  # fresh structurally-matching state
    assert int(jax.device_get(restored.step)) == 3
    assert_states_equal(ref.final_state.params, restored.params)
    assert_states_equal(ref.final_state.opt_state, restored.opt_state)
    ckpt.close()


def test_fail_async_finalize_retries_then_succeeds(tmp_path):
    """A finalize failure (data written, rename didn't happen) is torn
    on disk but retried by the writer: the retry lands the step and the
    remnant never becomes restorable."""
    ckpt = make_ckpt(tmp_path, mode="async")
    with faults.injected(FaultPlan(fail_async_finalize=1)):
        ckpt.save(_tiny_state(1.0, 4), step=4)
        ckpt.wait()
    assert ckpt.latest_step() == 4
    writer = ckpt._writer()
    assert writer.stats["finalized"] == 1
    restored = ckpt.restore_state(_tiny_state(0.0, 0))
    assert int(np.asarray(restored.step)) == 4
    ckpt.close()


def test_fail_async_finalize_exhausted_drops_and_earlier_step_restores(
    tmp_path, caplog
):
    """Every finalize attempt failing drops the save LOUDLY (error log
    with the step + exception chain) and restore falls back to the
    previous step — the training thread never hears about any of it."""
    ckpt = make_ckpt(tmp_path, mode="async", save_retries=0)
    ckpt.save(_tiny_state(1.0, 2), step=2)
    ckpt.wait()
    with caplog.at_level(logging.ERROR, "zookeeper_tpu.training.checkpoint"):
        with faults.injected(FaultPlan(fail_async_finalize=5)):
            ckpt.save(_tiny_state(9.0, 4), step=4)
            ckpt.wait()
    dropped = [r for r in caplog.records if "DROPPED" in r.message]
    assert dropped and dropped[0].exc_info is not None  # chain logged
    assert ckpt.latest_step() == 2
    restored = ckpt.restore_state(_tiny_state(0.0, 0))
    assert int(np.asarray(restored.step)) == 2
    ckpt.close()


def test_writer_thread_save_io_failure_never_touches_training(tmp_path):
    """fail_save_io consumed ON THE WRITER THREAD: the training loop
    completes every epoch with zero exceptions and a final state
    bit-identical to a run that never checkpointed; the failed save is
    retried/dropped entirely in the background."""
    ref = make_experiment()
    ref.run()

    conf = async_conf(
        tmp_path,
        **{
            "checkpointer.save_every_steps": 3,
            "checkpointer.save_retries": 0,
        },
    )
    exp = make_experiment(conf)
    with faults.injected(FaultPlan(fail_save_io=1)):
        history = exp.run()  # the step-3 write fails+drops; step 6 lands
    assert len(history["train"]) == 1
    assert_states_equal(ref.final_state.params, exp.final_state.params)
    assert sorted(
        s for s, _ in exp.checkpointer._tier_entries()
    ) == [6]
    exp.checkpointer.close()


# -- queue policies -------------------------------------------------------


def _gated_writer_ckpt(tmp_path, policy):
    """A checkpointer whose async writes block on a test-held gate, so
    queue-policy behavior is exercised without any timing."""
    ckpt = make_ckpt(tmp_path, mode="async", queue_policy=policy)
    gate = threading.Event()
    orig = ckpt._attempt_async_write

    def gated(step, tree, metrics):
        gate.wait(timeout=30)
        return orig(step, tree, metrics)

    object.__setattr__(ckpt, "_attempt_async_write", gated)
    return ckpt, gate


def test_supersede_policy_replaces_queued_snapshot(tmp_path):
    """supersede: while one write is in flight, the QUEUED snapshot is
    replaced by a newer one — the in-flight write still lands, the
    superseded step never does, and the newest state wins."""
    import time

    ckpt, gate = _gated_writer_ckpt(tmp_path, "supersede")
    ckpt.save(_tiny_state(1.0, 1), step=1)  # taken by the writer, gated
    writer = ckpt._writer()
    for _ in range(2000):
        if writer._writing_step is not None:
            break
        time.sleep(0.001)
    assert writer._writing_step == 1
    ckpt.save(_tiny_state(2.0, 2), step=2)  # queued
    ckpt.save(_tiny_state(3.0, 3), step=3)  # supersedes 2
    gate.set()
    ckpt.wait()
    assert sorted(s for s, _ in ckpt._tier_entries()) == [1, 3]
    assert writer.stats["superseded"] == 1
    restored = ckpt.restore_state(_tiny_state(0.0, 0))
    assert int(np.asarray(restored.step)) == 3
    ckpt.close()


def test_wait_policy_backpressures_and_writes_every_step(tmp_path):
    """wait (default): the depth-1 queue blocks the submitter instead
    of dropping — every submitted step lands, in order."""
    ckpt, gate = _gated_writer_ckpt(tmp_path, "wait")
    done = []

    def submit_all():
        for s in (1, 2, 3):
            ckpt.save(_tiny_state(float(s), s), step=s)
            done.append(s)

    t = threading.Thread(target=submit_all)
    t.start()
    gate.set()
    t.join(timeout=30)
    assert not t.is_alive()
    ckpt.wait()
    assert sorted(s for s, _ in ckpt._tier_entries()) == [1, 2, 3]
    assert ckpt._writer().stats["superseded"] == 0
    ckpt.close()


def test_preemption_drains_inflight_write_and_records_wait(tmp_path):
    """The PreemptionGuard path under async mode: the in-flight write
    lands before the final synchronous save, SIGTERM semantics are
    unchanged (newest state on disk), and save_wait_ms is surfaced per
    attempt by run_with_recovery."""
    import jax

    from zookeeper_tpu.resilience import run_with_recovery

    ref = make_experiment({"epochs": 2})
    ref.run()

    conf = async_conf(tmp_path, **{"checkpointer.save_every_steps": 2})
    exp = make_experiment({"epochs": 2, **conf})
    with faults.injected(FaultPlan(kill_at_step=5)):
        result = run_with_recovery(exp, backoff_s=0.0, sleep=lambda s: None)
    assert result.restarts == 1
    assert len(result.save_wait_ms) == 1
    assert result.save_wait_ms[0] >= 0.0
    assert len(result.restore_ms) == 1 and result.restore_ms[0] > 0
    assert int(jax.device_get(exp.final_state.step)) == 16
    assert_states_equal(ref.final_state.params, exp.final_state.params)
    assert_states_equal(
        ref.final_state.opt_state, exp.final_state.opt_state
    )
    exp.checkpointer.close()


# -- retention tiers ------------------------------------------------------


def test_durable_tier_promotes_and_restores_after_local_loss(tmp_path):
    """Every-N local with GC + progress-based durable promotion (first
    save, then every >= M steps of progress — cadence alignment can
    never starve the tier): when the whole local tier is lost, restore
    falls back to the newest durable step; when that one is torn too,
    to the one before it."""
    ckpt = make_ckpt(
        tmp_path,
        mode="async",
        max_to_keep=2,
        durable_every_steps=4,
    )
    for s in (2, 4, 6, 8):
        ckpt.save(_tiny_state(float(s), s), step=s)
    ckpt.wait()
    # Local GC kept the newest 2; durable promoted the FIRST save, then
    # the first save >= 4 steps later (2 -> 6; 4 and 8 are closer).
    entries = ckpt._tier_entries()
    assert [e for e in entries if e[1] == "local"] == [
        (8, "local"), (6, "local"),
    ]
    assert [e for e in entries if e[1] == "durable"] == [
        (6, "durable"), (2, "durable"),
    ]
    # Lose the ENTIRE local tier (the machine died; only the durable
    # store survived).
    for name in os.listdir(str(tmp_path / "ck")):
        if name.isdigit():
            shutil.rmtree(str(tmp_path / "ck" / name))
    restored = ckpt.restore_state(_tiny_state(0.0, 0))
    assert int(np.asarray(restored.step)) == 6
    np.testing.assert_array_equal(np.asarray(restored.params["w"]), 6.0)
    # Tear durable step 6 as well: the walk lands on durable 2.
    from zookeeper_tpu.resilience import corrupt_checkpoint_dir

    assert corrupt_checkpoint_dir(str(tmp_path / "ck" / "durable" / "6")) > 0
    restored = ckpt.restore_state(_tiny_state(0.0, 0))
    assert int(np.asarray(restored.step)) == 2
    ckpt.close()


def test_durable_tier_cannot_be_starved_by_cadence_misalignment(tmp_path):
    """The promotion rule is progress-based, NOT step-number
    divisibility: a save cadence whose step numbers never hit the
    durable grid (saves at 64,128,... with durable_every_steps=100)
    still fills the archival tier."""
    ckpt = make_ckpt(tmp_path, durable_every_steps=100)
    for s in (64, 128, 192, 256):
        ckpt.save(_tiny_state(float(s), s), step=s)
    ckpt.wait()
    durable = [s for s, t in ckpt._tier_entries() if t == "durable"]
    # 64 (first), then 192 (>= 100 past 64); 128 and 256 are closer.
    assert sorted(durable) == [64, 192]
    ckpt.close()


def test_restore_survives_retention_gc_race(tmp_path, caplog):
    """A step directory deleted between the walk's listing and its open
    (the retention GC racing a restore) must fall through to the
    next-newest step, not raise."""
    ckpt = make_ckpt(tmp_path)
    for s in (1, 2):
        ckpt.save(_tiny_state(float(s), s), step=s)
    ckpt.wait()
    # The manager has listed steps [1, 2]; delete 2 from disk UNDER it,
    # exactly what a concurrent GC (or operator rm) does mid-walk.
    assert sorted(ckpt._manager().all_steps()) == [1, 2]
    shutil.rmtree(str(tmp_path / "ck" / "2"))
    with caplog.at_level(
        logging.WARNING, "zookeeper_tpu.training.checkpoint"
    ):
        restored = ckpt.restore_state(_tiny_state(0.0, 0))
    assert int(np.asarray(restored.step)) == 1
    assert any("falling back" in r.message for r in caplog.records)
    ckpt.close()


# -- save retry backoff (satellite): jittered, loud on final drop --------


def test_save_retry_backoff_rerandomized_per_attempt(tmp_path, monkeypatch):
    """The retry backoff draws FRESH jitter every attempt (±50% around
    the doubling base) — a fleet must decorrelate, not stampede —
    and the final drop logs at error level with the exception chain."""
    delays = []
    monkeypatch.setattr(
        "zookeeper_tpu.training.checkpoint.time.sleep", delays.append
    )
    ckpt = make_ckpt(
        tmp_path, save_retries=4, save_retry_backoff_s=1.0
    )
    with faults.injected(FaultPlan(fail_save_io=10)):
        assert ckpt.save(_tiny_state(1.0, 1), step=1) is False
    assert len(delays) == 4
    for attempt, d in enumerate(delays):
        base = 1.0 * 2**attempt
        assert 0.5 * base <= d <= 1.5 * base, (attempt, d)
    # Re-randomized: the exact deterministic doubling (the old bug) is
    # a measure-zero draw across four attempts.
    assert delays != [1.0, 2.0, 4.0, 8.0]
    ckpt.close()
