"""Deployment round trip on REAL data: train -> export -> convert to the
bit-packed deployment -> evaluate the packed model. The converter's
forward-diff check is already pinned on synthetic inputs; this test pins
the full workflow at the metric a user ships on — validation ACCURACY on
genuine handwritten digits — and the bit-exactness contract predicts the
packed score equals the float score exactly.
"""

import sys

import numpy as np
import pytest

from zookeeper_tpu.core import configure
from zookeeper_tpu.training import EvalExperiment, TrainingExperiment

pytest.importorskip("sklearn")


def _digits_conf(extra=None):
    return {
        "loader.dataset": "SklearnDigits",
        "loader.preprocessing": "ImageClassificationPreprocessing",
        "loader.preprocessing.height": 8,
        "loader.preprocessing.width": 8,
        "loader.preprocessing.channels": 1,
        "loader.host_index": 0,
        "loader.host_count": 1,
        "batch_size": 64,
        "verbose": False,
        **(extra or {}),
    }


_MODEL = {
    "model": "BinaryNet",
    "model.features": (32, 32),
    "model.dense_units": (64,),
}


@pytest.mark.slow
def test_train_convert_packed_eval_accuracy_roundtrip(tmp_path):
    export = str(tmp_path / "float_model")
    packed = str(tmp_path / "packed_model")

    exp = TrainingExperiment()
    configure(
        exp,
        _digits_conf({
            **_MODEL,
            "epochs": 8,
            "optimizer.schedule.base_lr": 5e-3,
            "export_model_to": export,
        }),
        name="train",
    )
    history = exp.run()
    trained_acc = history["validation"][-1]["accuracy"]
    assert trained_acc >= 0.80, f"training anchor failed: {trained_acc:.3f}"

    # Convert with the example CLI task (the real user workflow), driving
    # its component directly in-process.
    sys.path.insert(0, str(__import__("pathlib").Path(__file__).parents[2] / "examples"))
    try:
        from convert_packed import ConvertPacked
    finally:
        sys.path.pop(0)
    conv = ConvertPacked()
    configure(
        conv,
        {
            **_MODEL,
            "checkpoint": export,
            "output": packed,
            "height": 8,
            "width": 8,
            "channels": 1,
            "num_classes": 10,
        },
        name="convert",
    )
    conv.run()

    def score(model_extra, checkpoint):
        ev = EvalExperiment()
        configure(
            ev,
            _digits_conf({
                **_MODEL,
                **model_extra,
                "checkpoint": checkpoint,
            }),
            name="eval",
        )
        return ev.run()

    float_metrics = score({}, export)
    packed_metrics = score(
        {
            "model.binary_compute": "xnor",
            "model.packed_weights": True,
            "model.pallas_interpret": True,
        },
        packed,
    )
    # Bit-exact deployment: the packed model scores IDENTICALLY on every
    # validation example, not merely similarly.
    assert packed_metrics["accuracy"] == float_metrics["accuracy"], (
        f"packed deployment changed accuracy: "
        f"{packed_metrics['accuracy']:.4f} vs {float_metrics['accuracy']:.4f}"
    )
    assert float_metrics["accuracy"] >= 0.80
