"""Numerics parity vs a Keras oracle (SURVEY.md §4).

The reference stack trains through Keras; TF 2.x is installed here
host-side only. We build the SAME small CNN in Keras and in our flax
stack, copy the flax initialization into Keras, feed identical data, and
assert the per-step loss trajectories agree — a test that would have
caught any loss/gradient/update bug anywhere in our train step.

SGD (not Adam) keeps the oracle sharp: optimizer-epsilon conventions
differ across frameworks, plain SGD is convention-free. BatchNorm is off
for the same reason (momentum/eps conventions); BN semantics are pinned
separately by the DP parity test.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

tf = pytest.importorskip("tensorflow")

from zookeeper_tpu.core import configure  # noqa: E402
from zookeeper_tpu.models import SimpleCnn  # noqa: E402
from zookeeper_tpu.training import TrainState, make_train_step  # noqa: E402

FEATURES = (8, 16)
DENSE = (32,)
NUM_CLASSES = 10
INPUT_SHAPE = (8, 8, 1)
LR = 0.1
STEPS = 5


def _flax_state():
    model = SimpleCnn()
    configure(
        model,
        {
            "features": FEATURES,
            "dense_units": DENSE,
            "use_batch_norm": False,
        },
        name="model",
    )
    module = model.build(INPUT_SHAPE, num_classes=NUM_CLASSES)
    params, model_state = model.initialize(module, INPUT_SHAPE, seed=0)
    state = TrainState.create(
        apply_fn=module.apply,
        params=params,
        model_state=model_state,
        tx=optax.sgd(LR),
    )
    return state


def _keras_model_from_flax(params):
    """Mirror SimpleCnn(use_batch_norm=False) in Keras and load the flax
    init (flax HWIO conv kernels and [in, out] dense kernels match Keras
    channels_last conventions directly — no transposes)."""
    tf.keras.backend.clear_session()
    layers = [tf.keras.layers.Input(INPUT_SHAPE)]
    for i, f in enumerate(FEATURES):
        layers.append(
            tf.keras.layers.Conv2D(f, 3, padding="same", activation="relu")
        )
        if i % 2 == 1:
            layers.append(tf.keras.layers.MaxPool2D(2, 2))
    layers.append(tf.keras.layers.Flatten())
    for u in DENSE:
        layers.append(tf.keras.layers.Dense(u, activation="relu"))
    layers.append(tf.keras.layers.Dense(NUM_CLASSES))
    model = tf.keras.Sequential(layers)

    weights = []
    for i in range(len(FEATURES)):
        conv = params[f"Conv_{i}"]
        weights += [np.asarray(conv["kernel"]), np.asarray(conv["bias"])]
    for i in range(len(DENSE) + 1):
        dense = params[f"Dense_{i}"]
        weights += [np.asarray(dense["kernel"]), np.asarray(dense["bias"])]
    model.set_weights(weights)
    return model


def _batches():
    rng = np.random.default_rng(42)
    for i in range(STEPS):
        x = rng.normal(size=(16, *INPUT_SHAPE)).astype(np.float32)
        y = rng.integers(0, NUM_CLASSES, 16).astype(np.int32)
        yield x, y


@pytest.mark.slow
def test_per_step_loss_matches_keras_oracle():
    state = _flax_state()
    keras_model = _keras_model_from_flax(state.params)
    opt = tf.keras.optimizers.SGD(learning_rate=LR)
    loss_fn = tf.keras.losses.SparseCategoricalCrossentropy(from_logits=True)

    step = jax.jit(make_train_step())

    flax_losses, keras_losses = [], []
    for x, y in _batches():
        state, metrics = step(state, {"input": jnp.asarray(x), "target": jnp.asarray(y)})
        flax_losses.append(float(metrics["loss"]))

        with tf.GradientTape() as tape:
            logits = keras_model(x, training=True)
            loss = loss_fn(y, logits)
        grads = tape.gradient(loss, keras_model.trainable_variables)
        opt.apply_gradients(zip(grads, keras_model.trainable_variables))
        keras_losses.append(float(loss))

    # Same math end to end: losses track step by step. Tolerance covers
    # fp32 reduction-order differences only — a gradient or update bug
    # diverges by >1e-2 within 5 steps at lr=0.1.
    np.testing.assert_allclose(flax_losses, keras_losses, rtol=2e-4, atol=2e-4)
    # And training actually moved (the oracle isn't comparing constants).
    assert flax_losses[-1] != flax_losses[0]


def test_forward_logits_match_keras_oracle():
    state = _flax_state()
    keras_model = _keras_model_from_flax(state.params)
    x = np.random.default_rng(7).normal(size=(4, *INPUT_SHAPE)).astype(np.float32)
    flax_logits = np.asarray(
        state.apply_fn({"params": state.params}, jnp.asarray(x), training=False)
    )
    keras_logits = keras_model(x, training=False).numpy()
    np.testing.assert_allclose(flax_logits, keras_logits, rtol=1e-4, atol=1e-5)
