"""Per-host sharded checkpointing (docs/DESIGN.md §19), driven by TWO
Checkpointer instances with injected ``process_index``/``process_count``
sharing one directory — the protocol (finalize markers, commit record,
restore agreement, retention) is pure filesystem + numpy, so the
simulated pair walks the real code byte-for-byte; the genuinely
cross-process leg lives in tests/resilience/test_multiprocess_chaos.py.
"""

import json
import os
import shutil
import threading

import numpy as np
import pytest

from zookeeper_tpu.core import configure
from zookeeper_tpu.resilience import FaultPlan, faults
from zookeeper_tpu.training import Checkpointer, TrainState

pytestmark = pytest.mark.chaos


def tiny_state(value: float, step: int):
    import jax.numpy as jnp
    import optax

    state = TrainState.create(
        apply_fn=lambda *a, **k: None,
        params={
            "w": jnp.full((4, 2), value, jnp.float32),
            "b": jnp.asarray(value, jnp.bfloat16),
        },
        model_state={},
        tx=optax.sgd(0.1),
    )
    return state.replace(step=jnp.asarray(step))


def host_pair(tmp_path, **extra):
    """Two Checkpointers impersonating hosts 0/1 of one group."""
    cks = []
    for pid in range(2):
        ck = Checkpointer()
        configure(
            ck,
            {
                "directory": str(tmp_path / "ckpt"),
                "sharded_per_host": True,
                "synchronous": True,
                "save_every_epochs": 0,
                "process_index": pid,
                "process_count": 2,
                "host_commit_timeout_s": 2.0,
                **extra,
            },
            name=f"ck_host{pid}",
        )
        cks.append(ck)
    return cks


def group_save(cks, state, step):
    """Save on both hosts: host 1 first so host 0's commit wait finds
    the marker immediately (the real group saves concurrently)."""
    ok1 = cks[1].save(state, step=step)
    ok0 = cks[0].save(state, step=step)
    return ok0, ok1


def group_restore(cks, target_factory):
    """Concurrent restore on both hosts (the agreement exchanges
    rendezvous); returns {pid: restored_state}."""
    out = {}

    def run(pid):
        out[pid] = cks[pid].restore_state(target_factory())

    t = threading.Thread(target=run, args=(1,))
    t.start()
    run(0)
    t.join()
    return out


def assert_state(restored, value, step):
    import jax.numpy as jnp

    assert int(np.asarray(restored.step)) == step
    np.testing.assert_array_equal(
        np.asarray(restored.params["w"]),
        np.full((4, 2), value, np.float32),
    )
    b = np.asarray(restored.params["b"])
    assert b.dtype == jnp.bfloat16  # raw-bytes storage: dtype survives
    assert float(b) == value


# -- commit protocol ------------------------------------------------------


def test_commit_and_round_trip_both_hosts(tmp_path):
    cks = host_pair(tmp_path)
    ok0, ok1 = group_save(cks, tiny_state(3.0, 7), 7)
    assert ok0 and ok1
    root = tmp_path / "ckpt" / "7.zkhost"
    assert (root / "host_00000" / "data.npz").is_file()
    assert (root / "host_00001" / "data.npz").is_file()
    commit = json.loads((root / "COMMIT.json").read_text())
    assert commit["step"] == 7 and commit["process_count"] == 2
    assert cks[0].latest_step() == 7 and cks[1].latest_step() == 7
    out = group_restore(cks, lambda: tiny_state(0.0, 0))
    for pid in (0, 1):
        assert_state(out[pid], 3.0, 7)


def test_torn_host_finalize_is_invisible_to_every_host(tmp_path):
    """fail_host_finalize: host 1 dies between shard write and rename —
    no marker, no commit record, the step never existed; both hosts
    restore the previous committed step (the acceptance-criteria
    invariant)."""
    cks = host_pair(tmp_path)
    assert all(group_save(cks, tiny_state(1.0, 1), 1))
    with faults.injected(FaultPlan(fail_host_finalize=1)):
        assert not cks[1].save(tiny_state(2.0, 2), step=2)
        assert not cks[0].save(tiny_state(2.0, 2), step=2)  # commit wait
    step_root = tmp_path / "ckpt" / "2.zkhost"
    assert not (step_root / "COMMIT.json").exists()
    assert not (step_root / "host_00001").exists()  # torn tmp only
    assert cks[0].latest_step() == 1 and cks[1].latest_step() == 1
    out = group_restore(cks, lambda: tiny_state(0.0, 0))
    for pid in (0, 1):
        assert_state(out[pid], 1.0, 1)


def test_gc_race_per_host_walk_falls_through(tmp_path, caplog):
    """The PR 6 GC-race leg, per-host flavor: a step whose commit
    record exists but whose host data was GC'd between listing and
    open falls through with a warning on BOTH hosts and the earlier
    committed step restores."""
    import logging

    cks = host_pair(tmp_path)
    assert all(group_save(cks, tiny_state(1.0, 1), 1))
    assert all(group_save(cks, tiny_state(2.0, 2), 2))
    # GC tears step 2's host data AFTER commit (the commit record
    # survives the race — exactly the torn-after-commit shape).
    shutil.rmtree(tmp_path / "ckpt" / "2.zkhost" / "host_00001")
    with caplog.at_level(logging.WARNING):
        out = group_restore(cks, lambda: tiny_state(0.0, 0))
    for pid in (0, 1):
        assert_state(out[pid], 1.0, 1)
    assert any(
        "falling back to an earlier step" in r.getMessage()
        or "torn on a peer host" in r.getMessage()
        for r in caplog.records
    )


def test_peer_torn_step_skipped_by_healthy_host(tmp_path, caplog):
    """A step restorable HERE but torn on the peer is skipped on every
    host — the group must agree on one step."""
    import logging

    cks = host_pair(tmp_path)
    assert all(group_save(cks, tiny_state(1.0, 1), 1))
    assert all(group_save(cks, tiny_state(2.0, 2), 2))
    # Tear ONLY host 1's half of step 2; host 0's half stays valid —
    # but validation covers every recorded host dir, so both skip.
    os.unlink(tmp_path / "ckpt" / "2.zkhost" / "host_00001" / "data.npz")
    with caplog.at_level(logging.WARNING):
        out = group_restore(cks, lambda: tiny_state(0.0, 0))
    for pid in (0, 1):
        assert_state(out[pid], 1.0, 1)


def test_retention_prunes_committed_steps(tmp_path):
    cks = host_pair(tmp_path, max_to_keep=2)
    for step in (1, 2, 3):
        assert all(group_save(cks, tiny_state(float(step), step), step))
    names = sorted(
        n for n in os.listdir(tmp_path / "ckpt") if n.endswith(".zkhost")
    )
    assert names == ["2.zkhost", "3.zkhost"]


def test_durable_tier_promotion_and_fallback(tmp_path):
    """Committed steps promote (whole step dir, commit included) on the
    progress cadence; a host that lost the ENTIRE local tier still
    restores from the durable copy — and the group agrees on it."""
    cks = host_pair(tmp_path, durable_every_steps=2)
    assert all(group_save(cks, tiny_state(1.0, 1), 1))  # first promotes
    assert all(group_save(cks, tiny_state(2.0, 2), 2))  # < 2 steps: no
    assert all(group_save(cks, tiny_state(3.0, 3), 3))  # promotes
    droot = tmp_path / "ckpt" / "durable"
    assert sorted(
        n for n in os.listdir(droot) if n.endswith(".zkhost")
    ) == ["1.zkhost", "3.zkhost"]
    assert json.loads(
        (droot / "3.zkhost" / "COMMIT.json").read_text()
    )["step"] == 3
    # Lose the whole local tier (both sharded steps).
    for name in ("1.zkhost", "2.zkhost", "3.zkhost"):
        shutil.rmtree(tmp_path / "ckpt" / name)
    out = group_restore(cks, lambda: tiny_state(0.0, 0))
    for pid in (0, 1):
        assert_state(out[pid], 3.0, 3)


def test_async_mode_sharded_save_lands_commit(tmp_path):
    cks = host_pair(tmp_path, mode="async")
    state = tiny_state(5.0, 4)
    assert cks[1].save(state, step=4)  # accepted by the writer
    cks[1].wait()
    assert cks[0].save(state, step=4)
    cks[0].wait()
    assert (tmp_path / "ckpt" / "4.zkhost" / "COMMIT.json").is_file()
    out = group_restore(cks, lambda: tiny_state(0.0, 0))
    for pid in (0, 1):
        assert_state(out[pid], 5.0, 4)
    for ck in cks:
        ck.close()


def test_coordinator_loss_degrades_to_local_walk(tmp_path, caplog):
    """A coordinator lost mid-agreement degrades the walk to a loud
    local decision instead of hanging or crashing."""
    import logging

    cks = host_pair(tmp_path)
    assert all(group_save(cks, tiny_state(1.0, 9), 9))
    with caplog.at_level(logging.WARNING):
        with faults.injected(FaultPlan(coordinator_loss=1)):
            restored = cks[0].restore_state(tiny_state(0.0, 0))
    assert_state(restored, 1.0, 9)
    assert any(
        "restore agreement" in r.message for r in caplog.records
    )


# -- degrade + compatibility ---------------------------------------------


def test_process_count_one_degrades_to_orbax_layout(tmp_path):
    """sharded_per_host at process_count==1 keeps the EXISTING on-disk
    layout byte-for-byte: bare orbax step dirs, no .zkhost anywhere,
    and restore_state reads it unchanged."""
    ck = Checkpointer()
    configure(
        ck,
        {
            "directory": str(tmp_path / "ckpt"),
            "sharded_per_host": True,
            "synchronous": True,
            "save_every_epochs": 0,
            "process_index": 0,
            "process_count": 1,
        },
        name="ck_single",
    )
    assert ck.save(tiny_state(2.0, 3), step=3)
    names = os.listdir(tmp_path / "ckpt")
    assert "3" in names
    assert not any(n.endswith(".zkhost") for n in names)
    assert_state(ck.restore_state(tiny_state(0.0, 0)), 2.0, 3)
    ck.close()


def test_old_orbax_checkpoints_walked_alongside_sharded(tmp_path):
    """A directory holding BOTH layouts (a run that enabled the mode
    mid-history) restores the newest step regardless of layout."""
    single = Checkpointer()
    configure(
        single,
        {
            "directory": str(tmp_path / "ckpt"),
            "synchronous": True,
            "save_every_epochs": 0,
        },
        name="ck_old",
    )
    assert single.save(tiny_state(1.0, 1), step=1)
    single.close()
    cks = host_pair(tmp_path)
    assert all(group_save(cks, tiny_state(2.0, 2), 2))
    assert cks[0].latest_step() == 2
    out = group_restore(cks, lambda: tiny_state(0.0, 0))
    for pid in (0, 1):
        assert_state(out[pid], 2.0, 2)
    # Tear the sharded step entirely: the walk falls back to the OLD
    # orbax checkpoint (still readable through the same Checkpointer).
    shutil.rmtree(tmp_path / "ckpt" / "2.zkhost")
    out = group_restore(cks, lambda: tiny_state(0.0, 0))
    for pid in (0, 1):
        assert_state(out[pid], 1.0, 1)


def test_single_process_can_read_group_checkpoint(tmp_path):
    """Post-mortem inspection: one process (count==1) restores a
    2-host group's checkpoint by reading every host's shard files."""
    cks = host_pair(tmp_path)
    assert all(group_save(cks, tiny_state(6.0, 5), 5))
    reader = Checkpointer()
    configure(
        reader,
        {
            "directory": str(tmp_path / "ckpt"),
            "sharded_per_host": True,
            "synchronous": True,
            "save_every_epochs": 0,
            "process_index": 0,
            "process_count": 1,
        },
        name="ck_reader",
    )
    assert_state(reader.restore_state(tiny_state(0.0, 0)), 6.0, 5)


def test_sharded_rejects_keep_best_metric(tmp_path):
    ck = Checkpointer()
    configure(
        ck,
        {
            "directory": str(tmp_path / "ckpt"),
            "sharded_per_host": True,
            "keep_best_metric": "accuracy",
        },
        name="ck_bad",
    )
    with pytest.raises(ValueError, match="sharded_per_host is incompat"):
        ck._validate_mode()


def test_structure_mismatch_raises_clear_error(tmp_path):
    """A differently-shaped target fails the walk with the structure
    message, not a silent partial restore."""
    import jax.numpy as jnp
    import optax

    cks = host_pair(tmp_path)
    assert all(group_save(cks, tiny_state(1.0, 1), 1))

    def wrong_target():
        state = TrainState.create(
            apply_fn=lambda *a, **k: None,
            params={"w": jnp.zeros((8, 2), jnp.float32)},
            model_state={},
            tx=optax.sgd(0.1),
        )
        return state.replace(step=jnp.asarray(0))

    errors = {}

    def run(pid):
        try:
            cks[pid].restore_state(wrong_target())
        except ValueError as e:
            errors[pid] = str(e)

    t = threading.Thread(target=run, args=(1,))
    t.start()
    run(0)
    t.join()
    assert "None of the 1 retained" in errors[0]
    assert "None of the 1 retained" in errors[1]


def test_stale_uncommitted_host_dir_rewritten_not_sealed(tmp_path):
    """A host dir left by a previous incarnation's UNCOMMITTED save of
    the same step must be rewritten, not sealed under a fresh commit —
    mixing shard bytes from two runs would be a silent frankenstate."""
    cks = host_pair(tmp_path)
    with faults.injected(FaultPlan(fail_host_finalize=1)):
        # Old incarnation: host 0 finalized step 2, host 1 died, no
        # commit — step 2 is (correctly) invisible.
        assert not cks[1].save(tiny_state(1.0, 2), step=2)
        assert not cks[0].save(tiny_state(1.0, 2), step=2)
    # New incarnation reaches step 2 again with DIFFERENT bytes.
    cks2 = host_pair(tmp_path)
    assert all(group_save(cks2, tiny_state(9.0, 2), 2))
    out = group_restore(cks2, lambda: tiny_state(0.0, 0))
    for pid in (0, 1):
        assert_state(out[pid], 9.0, 2)  # host 0's half rewritten too


# -- serving-side discovery + restore of committed sharded steps -----------
# (docs/DESIGN.md §20 satellite: the CheckpointWatcher's primitives —
# finalized_steps + load_inference_model — must see .zkhost steps, or
# a server tracking a multi-host run silently never swaps.)


def test_finalized_steps_lists_committed_sharded_steps(tmp_path):
    from zookeeper_tpu.training.checkpoint import finalized_steps

    cks = host_pair(tmp_path)
    root = str(tmp_path / "ckpt")
    assert finalized_steps(root) == []
    assert all(group_save(cks, tiny_state(1.0, 3), 3))
    assert finalized_steps(root) == [3]
    # A torn group save (host 1's finalize dropped => no commit
    # record) must stay invisible.
    with faults.injected(FaultPlan(fail_host_finalize=1)):
        assert not cks[1].save(tiny_state(2.0, 4), step=4)
        assert not cks[0].save(tiny_state(2.0, 4), step=4)
    assert finalized_steps(root) == [3]
    assert all(group_save(cks, tiny_state(3.0, 5), 5))
    assert finalized_steps(root) == [3, 5]


def test_load_inference_model_reads_sharded_step(tmp_path, caplog):
    import logging

    from zookeeper_tpu.training.checkpoint import load_inference_model

    cks = host_pair(tmp_path)
    assert all(group_save(cks, tiny_state(4.0, 7), 7))
    with caplog.at_level(logging.WARNING):
        params, model_state = load_inference_model(str(tmp_path / "ckpt"))
    # The multi-host layout warns LOUDLY (whole state on one host).
    assert any("MULTI-HOST" in r.message for r in caplog.records)
    np.testing.assert_allclose(np.asarray(params["w"]), 4.0)
    # bf16 leaves round-trip bit-exactly through the raw-bytes shards.
    assert str(params["b"].dtype) == "bfloat16"
    assert float(np.asarray(params["b"], np.float32)) == 4.0
    # Explicit step addressing (the hot-swap watcher's mode).
    p2, _ = load_inference_model(str(tmp_path / "ckpt"), step=7)
    np.testing.assert_allclose(np.asarray(p2["w"]), 4.0)


def test_load_inference_model_prefers_newest_across_layouts(tmp_path):
    """Orbax bare-step and .zkhost steps coexisting in one directory:
    the loader serves the NEWEST step regardless of layout."""
    from zookeeper_tpu.training.checkpoint import (
        finalized_steps,
        load_inference_model,
    )

    single = Checkpointer()
    configure(
        single,
        {
            "directory": str(tmp_path / "ckpt"),
            "synchronous": True,
            "save_every_epochs": 0,
        },
        name="ck_single_layout",
    )
    assert single.save(tiny_state(1.0, 1), step=1)
    cks = host_pair(tmp_path)
    assert all(group_save(cks, tiny_state(2.0, 2), 2))
    assert finalized_steps(str(tmp_path / "ckpt")) == [1, 2]
    params, _ = load_inference_model(str(tmp_path / "ckpt"))
    np.testing.assert_allclose(np.asarray(params["w"]), 2.0)  # step 2
    # And the older orbax step stays addressable.
    p1, _ = load_inference_model(str(tmp_path / "ckpt"), step=1)
    np.testing.assert_allclose(np.asarray(p1["w"]), 1.0)


def test_checkpoint_watcher_swaps_from_sharded_step(tmp_path):
    """End to end: a CheckpointWatcher polling a directory where a
    multi-host training run lands .zkhost steps must discover and
    apply them — the SERVING gap this satellite closes."""
    from zookeeper_tpu.serving.engine import CheckpointWatcher

    cks = host_pair(tmp_path)
    assert all(group_save(cks, tiny_state(5.0, 11), 11))
    seen = {}

    class FakeEngine:
        def swap_weights(self, params, model_state):
            seen["w"] = np.asarray(params["w"]).copy()

    watcher = CheckpointWatcher(
        FakeEngine(),
        str(tmp_path / "ckpt"),
        weights="raw",
        poll_interval_s=60.0,
    )
    step = watcher.poll_once()
    assert step == 11
    np.testing.assert_allclose(seen["w"], 5.0)


def test_load_inference_model_skips_stateful_opt_state(tmp_path):
    """A sharded step saved under a STATEFUL optimizer (adam: opt_state
    keystr paths carry tuple/attr segments like "['opt_state'][0]
    .count") must still serve: the loader filters non-inference
    subtrees BEFORE enforcing nested-dict path purity."""
    import jax.numpy as jnp
    import optax

    from zookeeper_tpu.training import TrainState
    from zookeeper_tpu.training.checkpoint import load_inference_model

    state = TrainState.create(
        apply_fn=lambda *a, **k: None,
        params={"w": jnp.full((4, 2), 8.0, jnp.float32)},
        model_state={},
        tx=optax.adam(1e-3),
    ).replace(step=jnp.asarray(2))
    cks = host_pair(tmp_path)
    assert cks[1].save(state, step=2)
    assert cks[0].save(state, step=2)
    params, _ = load_inference_model(str(tmp_path / "ckpt"))
    np.testing.assert_allclose(np.asarray(params["w"]), 8.0)
