"""Repo-root script contracts (bench.py): pure-logic checks that the
driver-facing entry points resolve their configuration correctly without
needing TPU hardware."""

import os
import sys

import pytest

REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _bench_attr(name):
    sys.path.insert(0, REPO_ROOT)
    try:
        import bench

        return getattr(bench, name)
    finally:
        sys.path.pop(0)


def _resolve_bench_config():
    return _bench_attr("resolve_bench_config")


def test_bench_config_resolution():
    """bench.py's env-override resolution: the driver's default is the
    north-star config; overrides select other acceptance-config models,
    with binary_compute applied only where the model has the field."""
    resolve_bench_config = _resolve_bench_config()

    model, name, batch, bc, packres = resolve_bench_config(env={})
    assert (name, batch, bc) == ("QuickNetLarge", 128, "int8")
    assert model.compute_dtype == "bfloat16"
    assert packres is False

    model, name, batch, bc, packres = resolve_bench_config(
        env={
            "ZK_BENCH_MODEL": "ResNet50",
            "ZK_BENCH_BATCH": "256",
            # Requested but unsupported by the fp model: recorded as
            # NOT applied, so the bench output cannot claim a lever
            # that never ran.
            "ZK_BENCH_PACK_RESIDUALS": "1",
        }
    )
    assert (name, batch) == ("ResNet50", 256)
    assert bc is None  # fp model: no binary path field
    assert packres is False

    model, name, batch, bc, packres = resolve_bench_config(
        env={
            "ZK_BENCH_MODEL": "BinaryAlexNet",
            "ZK_BENCH_BINARY_COMPUTE": "mxu",
        }
    )
    assert (name, bc) == ("BinaryAlexNet", "mxu")

    # QuickNet supports the lever: requested -> applied and recorded.
    model, name, batch, bc, packres = resolve_bench_config(
        env={"ZK_BENCH_PACK_RESIDUALS": "1"}
    )
    assert packres is True
    assert model.pack_residuals is True

    with pytest.raises(ValueError, match="not in the zoo"):
        resolve_bench_config(env={"ZK_BENCH_MODEL": "NoSuchNet"})

    # Non-model module attributes (helper functions, the abstract base)
    # fail loudly at resolution, not with a confusing configure error.
    with pytest.raises(ValueError, match="not in the zoo"):
        resolve_bench_config(env={"ZK_BENCH_MODEL": "model_summary"})
    with pytest.raises(ValueError, match="abstract base"):
        resolve_bench_config(env={"ZK_BENCH_MODEL": "Model"})


def test_bench_reachability_probe_cpu_noop():
    """Under an explicitly-requested cpu backend (the test env), the
    reachability probe is an instant no-op — it must neither run a
    device op nor trip the silent-fallback detector."""
    check = _bench_attr("check_device_reachable")
    check(timeout_s=30)  # Raises/exits on failure; returning is the pass.


def test_bench_peak_resolution():
    """The MFU anchor: env override wins; off-TPU the recorded v5e
    fallback applies (measurement needs the real MXU)."""
    resolve_peak_flops = _bench_attr("resolve_peak_flops")

    peak, source = resolve_peak_flops(env={"ZK_BENCH_PEAK_FLOPS": "9e13"})
    assert (peak, source) == (9e13, "env")

    peak, source = resolve_peak_flops(env={})
    # Tests force JAX_PLATFORMS=cpu, so the TPU measurement is skipped.
    assert (peak, source) == (184e12, "fallback_v5e")


def test_bench_compiler_options_resolution():
    """ZK_BENCH_COMPILER_OPTIONS: unset -> None (default compile path);
    a JSON object passes through; non-object JSON is rejected loudly."""
    resolve = _bench_attr("resolve_compiler_options")

    assert resolve(env={}) is None
    assert resolve(env={"ZK_BENCH_COMPILER_OPTIONS": "  "}) is None

    opts = resolve(
        env={
            "ZK_BENCH_COMPILER_OPTIONS": (
                '{"xla_tpu_scoped_vmem_limit_kib": "65536"}'
            )
        }
    )
    assert opts == {"xla_tpu_scoped_vmem_limit_kib": "65536"}

    with pytest.raises(ValueError, match="JSON object"):
        resolve(env={"ZK_BENCH_COMPILER_OPTIONS": '["not", "a", "dict"]'})

    # Flag-syntax (non-JSON) input fails loudly, NAMING the env var —
    # not with a bare JSONDecodeError.
    with pytest.raises(
        ValueError, match="ZK_BENCH_COMPILER_OPTIONS is not valid JSON"
    ):
        resolve(
            env={
                "ZK_BENCH_COMPILER_OPTIONS": (
                    "xla_tpu_scoped_vmem_limit_kib=65536"
                )
            }
        )


def test_bench_peak_aggregation():
    """Agreement-gated median over independent peak attempts — the
    aggregator that replaced max-over-attempts after three fast-side
    failures (268 / 270 / 237.9 TF/s "measured" on a 197 TF/s v5e).
    Pinned off-chip with the observed failure shapes."""
    agg = _bench_attr("aggregate_peak_attempts")

    # Clean session: all attempts agree; median of the cluster.
    assert agg([190e12, 192e12, 189e12, 191e12]) == pytest.approx(
        190.5e12
    )

    # Cache-hit spike (the BENCH_r04 pathology): one above-physics fast
    # outlier must be EXCLUDED, not returned as the max.
    clean = agg([237.9e12, 191e12, 190e12, 192e12])
    assert clean == pytest.approx(191e12)

    # Jitter spike (slow-side outlier, the round-2 ~154 TF/s shape):
    # excluded the same way.
    assert agg([154e12, 190e12, 192e12, 191e12]) == pytest.approx(191e12)

    # Both failure shapes in one session.
    assert agg([154e12, 238e12, 190e12, 192e12]) == pytest.approx(191e12)

    # No two attempts agree: refuse to anchor rather than guess.
    with pytest.raises(ValueError, match="agree"):
        agg([100e12, 150e12, 238e12])

    # Fewer than two positive attempts: refuse.
    with pytest.raises(ValueError, match=">=2"):
        agg([190e12])
    with pytest.raises(ValueError, match=">=2"):
        agg([-1.0, 190e12])

    # Equal-size disjoint clusters (bimodal session): REFUSE — anchoring
    # on the slow cluster inflates MFU (the round-2 114 TF/s lesson),
    # the fast one risks the cache pathology. Neither is trustworthy.
    with pytest.raises(ValueError, match="ambiguous"):
        agg([150e12, 151e12, 237e12, 238e12])
    with pytest.raises(ValueError, match="ambiguous"):
        agg([154e12, 156e12, 190e12, 192e12])

    # But a mild outlier that merely OVERLAPS the clean cluster's band
    # (within tol of its max, not its min) is the same cluster shifted,
    # not a second mode — it must not veto three agreeing attempts.
    assert agg([190e12, 191e12, 192e12, 199.6e12]) == pytest.approx(
        191e12
    )


def test_bench_peak_datasheet_clamp():
    """Generation-specific clamp: a measured peak above ~1.05x the
    datasheet number for the detected device_kind is a measurement
    failure; unknown generations must pass (stale table vs future
    chip)."""
    sheet = _bench_attr("datasheet_bf16_peak")
    check = _bench_attr("check_peak_against_datasheet")

    assert sheet("TPU v5 lite") == pytest.approx(197e12)
    assert sheet("TPU v5e") == pytest.approx(197e12)
    assert sheet("TPU v5p") == pytest.approx(459e12)  # "v5 lite" must not
    assert sheet("TPU v4") == pytest.approx(275e12)
    assert sheet("TPU v6 lite") == pytest.approx(918e12)
    assert sheet("some future chip") is None
    assert sheet(None) is None

    # The exact BENCH_r04 defect: 237.9 TF/s on a v5e must raise.
    with pytest.raises(ValueError, match="datasheet"):
        check(237.9e12, "TPU v5 lite")
    # In-band measurements pass, including slightly above datasheet
    # (within headroom) and legitimately degraded ones.
    check(192.5e12, "TPU v5 lite")
    check(200e12, "TPU v5 lite")
    check(154e12, "TPU v5 lite")
    # Unknown generation: no clamp.
    check(2e15, "TPU v9 hyperlite")


def test_bench_int8_peak_resolution():
    """The second MFU anchor (int8 MXU): env override wins; off-TPU the
    recorded v5e measurement applies."""
    resolve = _bench_attr("resolve_int8_peak")

    peak, source = resolve(env={"ZK_BENCH_INT8_PEAK_FLOPS": "3.9e14"})
    assert (peak, source) == (3.9e14, "env")

    peak, source = resolve(env={})
    # Tests force JAX_PLATFORMS=cpu, so the TPU measurement is skipped.
    assert (peak, source) == (369e12, "fallback_v5e")
    # The recorded fallback sits below the physical 2x-bf16 ceiling.
    assert peak < 2.0 * 197e12


def test_lm_bench_records_flash_blocks_and_sp_degree():
    """The LM leg's bench JSON carries the auto-selected flash block
    sizes (so a flash-policy regression moves a driver-visible number,
    not just the step time) — computed by the same head_dim/VMEM-aware
    policy the compiled step uses, at the leg's bf16 operands."""
    lm_bench_flash_blocks = _bench_attr("lm_bench_flash_blocks")

    # Pinned config (d512/h8 -> head_dim 64, bf16): the measured sweep
    # winner at every power-of-two length.
    assert lm_bench_flash_blocks(8192) == (1024, 1024)
    assert lm_bench_flash_blocks(2048) == (1024, 1024)
    # Awkward lengths fall back exactly like the kernel's policy...
    assert lm_bench_flash_blocks(1100) == (128, 128)
    # ...and extreme head dims demote via the VMEM filter.
    bq, bk = lm_bench_flash_blocks(8192, d_model=4096, num_heads=1,
                                   itemsize=4)
    assert bq == bk and bq < 1024


def test_sp_bench_env_knobs_validate():
    """The SP A/B leg fails fast on an invalid flavor (before any
    multi-device compile)."""
    import pytest

    measure = _bench_attr("measure_sp_ring_throughput")
    with pytest.raises(ValueError, match="ZK_BENCH_SP_FLAVOR"):
        measure(env={"ZK_BENCH_SP_FLAVOR": "dense"})
