"""Repo-root script contracts (bench.py): pure-logic checks that the
driver-facing entry points resolve their configuration correctly without
needing TPU hardware."""

import os
import sys

import pytest

REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _resolve_bench_config():
    sys.path.insert(0, REPO_ROOT)
    try:
        from bench import resolve_bench_config

        return resolve_bench_config
    finally:
        sys.path.pop(0)


def test_bench_config_resolution():
    """bench.py's env-override resolution: the driver's default is the
    north-star config; overrides select other acceptance-config models,
    with binary_compute applied only where the model has the field."""
    resolve_bench_config = _resolve_bench_config()

    model, name, batch, bc = resolve_bench_config(env={})
    assert (name, batch, bc) == ("QuickNetLarge", 128, "int8")
    assert model.compute_dtype == "bfloat16"

    model, name, batch, bc = resolve_bench_config(
        env={"ZK_BENCH_MODEL": "ResNet50", "ZK_BENCH_BATCH": "256"}
    )
    assert (name, batch) == ("ResNet50", 256)
    assert bc is None  # fp model: no binary path field

    model, name, batch, bc = resolve_bench_config(
        env={
            "ZK_BENCH_MODEL": "BinaryAlexNet",
            "ZK_BENCH_BINARY_COMPUTE": "mxu",
        }
    )
    assert (name, bc) == ("BinaryAlexNet", "mxu")

    with pytest.raises(ValueError, match="not in the zoo"):
        resolve_bench_config(env={"ZK_BENCH_MODEL": "NoSuchNet"})
