"""The example scripts ARE the reference's canonical capability demo
(SURVEY §2.3): pin that each drives end-to-end from its CLI, in a real
subprocess (fresh interpreter, arg parsing, task registry, exit code)."""

import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.dirname(__file__)))


def run_example(script, *args, timeout=240):
    env = dict(os.environ)
    # Strip the TPU-relay activation vars: this machine's sitecustomize
    # would otherwise call jax.config.update("jax_platforms", ...) at
    # import, which BEATS the JAX_PLATFORMS env var below and would point
    # these "CPU smoke" subprocesses at the real chip (same guard as the
    # in-child config reset in tests/parallel/multiproc_worker.py).
    for key in [k for k in env if k.startswith("PALLAS_AXON")]:
        env.pop(key)
    pythonpath = REPO
    if env.get("PYTHONPATH"):
        pythonpath = f"{REPO}{os.pathsep}{env['PYTHONPATH']}"
    env.update({
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": pythonpath,
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
    })
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", script), *args],
        capture_output=True, text=True, timeout=timeout, env=env,
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    return proc.stdout


def test_mnist_train_export_eval_convert(tmp_path):
    export = str(tmp_path / "model")
    packed = str(tmp_path / "packed")
    out = run_example(
        "mnist_experiment.py", "TrainMnist",
        "model=BinaryNet", "model.features=(8,8)", "model.dense_units=(16,)",
        "epochs=1", "steps_per_epoch=2", "batch_size=16",
        "loader.dataset.num_train_examples=32",
        "loader.dataset.num_validation_examples=16",
        f"export_model_to='{export}'",
    )
    assert "epoch 1/1" in out

    out = run_example(
        "mnist_experiment.py", "EvaluateMnist",
        "model=BinaryNet", "model.features=(8,8)", "model.dense_units=(16,)",
        "batch_size=16",
        "loader.dataset.num_train_examples=32",
        "loader.dataset.num_validation_examples=16",
        f"checkpoint='{export}'",
    )
    assert "eval[validation]" in out

    out = run_example(
        "convert_packed.py", "ConvertPacked",
        "model=BinaryNet", "model.features=(8,8)", "model.dense_units=(16,)",
        f"checkpoint='{export}'", f"output='{packed}'",
    )
    assert "verified max |forward diff| = 0.0" in out


def test_imagenet_task_compiles_tiny():
    out = run_example(
        "imagenet_experiment.py", "TrainImageNet",
        "epochs=1", "steps_per_epoch=1", "batch_size=4", "validate=False",
        "loader.dataset.num_train_examples=8",
        "loader.dataset.num_validation_examples=4",
        "loader.preprocessing.height=32", "loader.preprocessing.width=32",
        "loader.num_workers=0",
        "model.blocks_per_section=(1,1)", "model.section_features=(8,16)",
        timeout=400,
    )
    assert "epoch 1/1" in out


def test_cifar_binarynet_task():
    out = run_example(
        "cifar_experiment.py", "TrainCifar",
        "epochs=1", "steps_per_epoch=2", "batch_size=16",
        "model.features=(8,8)", "model.dense_units=(16,)",
        "loader.dataset.num_train_examples=32",
        "loader.dataset.num_validation_examples=16",
        "track_flip_ratio=True",
    )
    assert "epoch 1/1" in out


def test_latency_bench_task():
    out = run_example(
        "latency_bench.py", "LatencyBench",
        "model=Mlp", "model.hidden_units=(16,)",
        "height=8", "width=8", "channels=1", "num_classes=4",
        "chain_length=4", "rounds=2", "batch_size=2",
    )
    import json

    result = json.loads(out.strip().splitlines()[-1])
    assert result["model"] == "Mlp"
    assert result["ms_per_inference"] >= 0.0
    assert result["params_mib"] >= 0.0


def test_digits_real_data_task():
    """The offline REAL-data example: genuine handwritten digits, no
    synthetic fallback, >=85% val accuracy in two epochs through the
    subprocess CLI."""
    pytest.importorskip("sklearn")
    out = run_example(
        "digits_experiment.py", "TrainDigits",
        "epochs=2", "model.features=(16,32)", "model.dense_units=(64,)",
    )
    assert "epoch 2/2" in out
    import re

    accs = re.findall(r"val_acc=([0-9.]+)", out)
    assert accs and float(accs[-1]) >= 0.85, out[-500:]


def test_lm_long_context_example():
    """The long-context LM demo drives the TransformerLM family end to
    end (build -> DP partitioner -> flash-attention train steps) and
    reports falling loss + a throughput line."""
    # 25 steps -> loss lines at steps 10, 20, 24: enough to OBSERVE the
    # fall, not just parse a line.
    out = run_example(
        "lm_long_context.py",
        "--steps", "25", "--seq", "64", "--vocab", "53", "--layers", "2",
        "--d-model", "64", "--heads", "2", "--batch", "4",
    )
    assert "TransformerLM: 2L d64 h2 s64" in out
    assert "tokens/s" in out
    losses = [
        float(line.split("loss=")[1].split()[0])
        for line in out.splitlines()
        if "loss=" in line
    ]
    assert len(losses) >= 2, out
    assert losses[-1] < losses[0], losses


def test_lm_task_cli():
    """The config-system-native LM flow: TrainLM from the task CLI with
    scoped seq_len inheritance wiring dataset windows, preprocessing
    input_shape, and (via the -1 default) the model's positional table
    from ONE knob."""
    out = run_example(
        "lm_experiment.py", "TrainLM",
        "epochs=3", "seq_len=32", "batch_size=16",
        "loader.dataset.num_train_examples=128",
        "loader.dataset.vocab_size=31",
        "model.num_layers=2", "model.d_model=64", "model.num_heads=2",
    )
    assert "TrainLM" in out
    accs = [
        float(line.split("val_acc=")[1].split()[0])
        for line in out.splitlines()
        if "val_acc=" in line
    ]
    assert len(accs) == 3
    assert accs[-1] > accs[0], accs
    assert accs[-1] > 0.5, accs  # memorizable corpus, chance ~1/31


def test_lm_task_cli_sequence_parallel():
    """The dp x sp recipe straight from the CLI (the last code-not-
    config seam, closed): partitioner=SequenceParallelPartitioner
    partitioner.sp=2 trains the LM on the subprocess's 2 virtual
    devices — partitioner-owned mesh, injected ring-flash attention,
    loss falling like the single-device run's."""
    out = run_example(
        "lm_experiment.py", "TrainLM",
        "partitioner=SequenceParallelPartitioner", "partitioner.sp=2",
        "epochs=2", "seq_len=32", "batch_size=16",
        "loader.dataset.num_train_examples=64",
        "loader.dataset.vocab_size=31",
        "model.num_layers=2", "model.d_model=32", "model.num_heads=2",
    )
    assert "SequenceParallelPartitioner" in out
    losses = [
        float(line.split("loss=")[1].split()[0])
        for line in out.splitlines()
        if line.startswith("epoch ")
    ]
    assert len(losses) == 2, out
    assert losses[-1] < losses[0], losses


def test_serve_classifier_end_to_end(tmp_path):
    """The full inference half of the north star from the CLI: train +
    export the digits model, then serve the validation split through the
    dynamic-batching engine — batched serving must score what training
    shipped, with zero recompiles after warmup."""
    pytest.importorskip("sklearn")
    export = str(tmp_path / "digits_model")
    out = run_example(
        "digits_experiment.py", "TrainDigits",
        "epochs=2", "model.features=(16,32)", "model.dense_units=(64,)",
        f"export_model_to='{export}'",
    )
    assert "epoch 2/2" in out
    import json
    import re

    accs = re.findall(r"val_acc=([0-9.]+)", out)
    assert accs, out[-500:]
    trained_acc = float(accs[-1])

    out = run_example(
        "serve_classifier.py", "ServeDigits",
        f"checkpoint='{export}'",
        "model.features=(16,32)", "model.dense_units=(64,)",
        "engine.batch_buckets=(1,8,32)",
    )
    result = json.loads(out.strip().splitlines()[-1])
    assert result["recompiles_after_warmup"] == 0
    assert result["compiles"] == 3
    # Serving the exported weights through the batcher reproduces the
    # trained model's quality (row-exact batching; the small tolerance
    # covers the training-side eval dropping the remainder batch while
    # serving scores every example).
    assert result["accuracy"] >= 0.85, result
    assert abs(result["accuracy"] - trained_acc) < 0.05, (result, trained_acc)
    assert result["examples"] == 359  # full validation split coverage
    assert result["latency_p50_ms"] > 0.0


def test_serve_lm_fresh_init_smoke():
    """The decode subsystem from its CLI: fresh-init weights, a real
    continuous-batching serve (requests > slots => slot refills), one
    JSON result line with the decode metrics family, zero recompiles
    after warmup."""
    import json

    out = run_example(
        "serve_lm.py", "ServeLM",
        "model.num_layers=2", "model.d_model=32", "model.num_heads=4",
        "model.attention=dense", "seq_len=64", "vocab_size=50",
        "engine.slots=2", "engine.seq_buckets=(8,)",
        "requests=5", "max_prompt=8", "new_tokens=4",
    )
    result = json.loads(out.strip().splitlines()[-1])
    assert result["recompiles_after_warmup"] == 0
    assert result["compiles"] == 2  # one prefill bucket pair + decode
    assert result["requests"] == 5
    assert result["generated_tokens"] == 5 * 4
    assert result["tokens_per_sec"] > 0
    assert result["ttft_p99_ms"] > 0
    assert result["token_p50_ms"] > 0


def test_train_then_serve_lm_end_to_end(tmp_path):
    """The token-streaming north-star loop from the CLI: TrainLM into a
    checkpointer directory, then ServeLM streams generations from the
    shipped weights through the paged-KV decode engine."""
    import json

    ckpt = str(tmp_path / "lm_ckpt")
    out = run_example(
        "lm_experiment.py", "TrainLM",
        "epochs=2", "seq_len=32", "batch_size=16",
        "loader.dataset.num_train_examples=128",
        "loader.dataset.vocab_size=31",
        "model.num_layers=2", "model.d_model=64", "model.num_heads=2",
        "model.attention=dense",
        f"checkpointer.directory='{ckpt}'",
    )
    assert "epoch 2/2" in out
    out = run_example(
        "serve_lm.py", "ServeLM",
        f"checkpoint='{ckpt}'",
        "model.num_layers=2", "model.d_model=64", "model.num_heads=2",
        "model.attention=dense", "seq_len=32", "vocab_size=31",
        "engine.slots=2", "engine.seq_buckets=(8,16)",
        "requests=4", "max_prompt=8", "new_tokens=6",
    )
    result = json.loads(out.strip().splitlines()[-1])
    assert result["recompiles_after_warmup"] == 0
    assert result["requests"] == 4
    assert result["generated_tokens"] == 4 * 6
    assert result["weights"] == "auto"


def test_serve_fleet_cli_smoke():
    """The fleet topology from its CLI (docs/DESIGN.md §23): ServeFleet
    spawns a real worker process, pins the session, and the turn-2
    request reports worker-side warm ``shared_tokens`` — the
    prefix-affinity contract visible from one JSON line."""
    import json

    out = run_example(
        "serve_fleet.py", "ServeFleet",
        "replicas=1", "sessions=1", "turns=2",
        "num_layers=1", "d_model=32", "num_heads=4",
        "shared_tokens=24", "tail_tokens=8", "new_tokens=4",
        "page_size=8", "slots=2", "verbose=False",
        timeout=420,
    )
    result = json.loads(out.strip().splitlines()[-1])
    assert result["policy"] == "affinity"
    assert result["requests"] == 2
    assert result["routed_total"] == 2
    # Turn 2 re-entered the pinned replica's radix cache: an affinity
    # hit with every turn-1 full page warm on the worker side.
    assert result["affinity_hits"] == 1
    assert result["warm_shared_tokens"] == [24]
    assert result["healthy_replicas"] == 1
    assert result["rerouted"] == 0
    assert result["generated_tokens"] == 2 * 4
    assert result["tokens_per_sec"] > 0
