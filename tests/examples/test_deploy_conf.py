"""Deployment-twin config resolution (examples/convert_packed.py
``resolve_deploy_conf``): precedence and packing-default rules, pure
logic — no checkpoints or conversion runs needed."""

import os

import pytest

from zookeeper_tpu.core import configure
from zookeeper_tpu.models import BinaryAlexNet, Mlp, QuickNet

_SCRIPT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "examples",
    "convert_packed.py",
)


def _resolve():
    # Import under the script's CANONICAL module name so this shares one
    # sys.modules entry (and one registered @task) with any other test
    # that imports convert_packed — a second execution under a different
    # name would trip the task registry's duplicate check.
    import importlib
    import sys

    examples_dir = os.path.dirname(_SCRIPT)
    if examples_dir not in sys.path:
        sys.path.insert(0, examples_dir)
    return importlib.import_module("convert_packed").resolve_deploy_conf


def _model(cls, conf):
    m = cls()
    configure(m, conf, name="m")
    return m


def test_defaults_pack_everything():
    resolve = _resolve()
    conf, fold = resolve(_model(QuickNet, {}), False, {}, True)
    assert conf["packed_weights"] is True
    assert conf["binary_compute"] == "xnor"
    assert fold is False and "fold_bn" not in conf


def test_explicit_training_mode_still_flips_to_packable():
    """A user who trained with an explicit int8/mxu path must still get
    a runnable packed twin — the mode flips to xnor rather than
    producing the invalid int8+packed combo."""
    resolve = _resolve()
    conf, _ = resolve(
        _model(QuickNet, {"binary_compute": "int8"}), False, {}, True
    )
    assert conf["packed_weights"] is True
    assert conf["binary_compute"] == "xnor"


def test_explicit_unpacked_config_survives():
    """packed_weights=False set on the model expresses a partial
    deployment and must survive; with nothing packed, the trained
    binary_compute stays."""
    resolve = _resolve()
    conf, _ = resolve(
        _model(
            BinaryAlexNet,
            {"packed_weights": False, "binary_compute": "mxu",
             "dense_packed_weights": True, "dense_binary_compute": "xnor"},
        ),
        False, {}, True,
    )
    assert conf["packed_weights"] is False
    assert conf["binary_compute"] == "mxu"
    assert conf["dense_packed_weights"] is True


def test_deploy_overrides_win_over_everything():
    resolve = _resolve()
    # Overrides beat the user's model config AND the task fold_bn.
    conf, fold = resolve(
        _model(QuickNet, {"binary_compute": "int8"}),
        True,
        {"binary_compute": "int8", "fold_bn": False},
        True,
    )
    assert fold is False and "fold_bn" not in conf
    # Explicitly-overridden binary_compute is never second-guessed,
    # even though the twin is packed (the layer raises loudly instead).
    assert conf["binary_compute"] == "int8"

    conf, fold = resolve(_model(QuickNet, {}), False, {"fold_bn": True}, True)
    assert fold is True and conf["fold_bn"] is True


def test_per_section_tuples_left_alone():
    resolve = _resolve()
    conf, _ = resolve(
        _model(
            QuickNet,
            {"binary_compute": ("int8", "xnor"),
             "packed_weights": (False, True),
             "blocks_per_section": (1, 1),
             "section_features": (8, 16)},
        ),
        False, {}, True,
    )
    assert conf["binary_compute"] == ("int8", "xnor")
    assert conf["packed_weights"] == (False, True)


def test_fold_requires_the_model_mode():
    resolve = _resolve()
    with pytest.raises(ValueError, match="no fold_bn deployment mode"):
        resolve(_model(Mlp, {}), True, {}, True)
