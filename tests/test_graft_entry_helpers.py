"""Unit tests for __graft_entry__'s SPMD-log certification machinery —
the fd-level capture and the raise-on-warning contract — without paying
the multi-minute dryrun that exercises them end-to-end."""

import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import __graft_entry__ as ge  # noqa: E402


def test_capture_sees_fd_level_writes():
    """os.write to fd 2 bypasses sys.stderr — exactly how the C++ SPMD
    partitioner logs — and must land in the capture buffer."""
    buf = {}
    with ge._capture_fd_stderr(buf, replay=False):
        os.write(2, b"raw c++ style line\n")
    assert "raw c++ style line" in buf["text"]
    # (sys.stderr-level writes are not asserted here: under pytest,
    # sys.stderr is the capture plugin's object, not fd 2.)


def test_capture_replay_reemits(capfd):
    buf = {}
    with ge._capture_fd_stderr(buf, replay=True):
        os.write(2, b"replayed\n")
    # After the context, the captured text is back on the REAL stderr.
    assert "replayed" in capfd.readouterr().err


def test_certify_raises_on_warning():
    with pytest.raises(RuntimeError, match="full-tensor replication"):
        with ge._certify_clean_spmd_log("unit"):
            os.write(2, (ge._SPMD_REMAT_WARNING + "\n").encode())


def test_certify_passes_clean_log():
    with ge._certify_clean_spmd_log("unit"):
        os.write(2, b"benign compiler chatter\n")


def test_certify_propagates_inner_exception():
    """An exception inside the certified block must surface as ITSELF,
    not be masked by the certification logic, and stderr must be
    restored afterwards."""
    with pytest.raises(ZeroDivisionError):
        with ge._certify_clean_spmd_log("unit"):
            1 / 0
    # fd 2 is usable again (would raise if left dup2'd to a closed tmp).
    print("restored", file=sys.stderr, flush=True)
