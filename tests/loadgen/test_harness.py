"""Replay harness contracts (docs/DESIGN.md §24): outcome
classification over the serving exception taxonomy, report
aggregation, SLO violations firing the flight recorder, FaultPlan
install/clear, retry parsing from a target RequestLog."""

import numpy as np
import pytest

from zookeeper_tpu.loadgen import poisson_burst, replay, session_mix
from zookeeper_tpu.loadgen.harness import _classify
from zookeeper_tpu.resilience import FaultPlan, faults
from zookeeper_tpu.serving import (
    DeadlineExpiredError,
    FleetUnavailableError,
    PredictedMissError,
    RejectedError,
    WorkerCrashedError,
)


def tiny_trace(**kw):
    kw.setdefault("base_rate_rps", 200)
    kw.setdefault("burst_rate_rps", 400)
    kw.setdefault("base_s", 0.1)
    kw.setdefault("burst_s", 0.1)
    kw.setdefault("cooldown_s", 0.1)
    return poisson_burst(1, **kw)


def test_classification_covers_the_taxonomy():
    assert _classify(None) == "ok"
    assert _classify(RejectedError("q full")) == "shed"
    assert _classify(PredictedMissError("miss")) == "shed"
    assert _classify(DeadlineExpiredError("late")) == "deadline_expired"
    assert _classify(WorkerCrashedError("gone")) == "crashed"
    assert _classify(FleetUnavailableError("none")) == "unavailable"
    assert _classify(RuntimeError("?")) == "error"


def test_replay_callable_all_ok_report_shape():
    trace = tiny_trace()

    def target(req):
        return req.max_new_tokens, 1.5

    report = replay(trace, target, concurrency=4)
    assert report.total == len(trace.requests)
    assert report.outcomes == {"ok": len(trace.requests)}
    assert report.ok_tokens == sum(
        r.max_new_tokens for r in trace.requests
    )
    assert report.goodput_tokens_per_sec > 0
    assert set(report.per_phase) == {"base", "burst", "cooldown"}
    for phase, stats in report.per_phase.items():
        assert stats["ok"] == stats["requests"] > 0
        assert {"p50", "p95", "p99"} <= set(stats["latency_ms"])
        assert stats["ttft_ms"]["p50"] == 1.5
    d = report.as_dict()
    assert d["requests"] == report.total
    assert d["violations"] == 0
    # Every result is terminal and in trace order.
    assert [o.index for o in report.results] == [
        r.index for r in trace.requests
    ]


def test_replay_classifies_errors_per_request():
    trace = tiny_trace()
    errors = {
        0: RejectedError("shed"),
        1: DeadlineExpiredError("late"),
        2: WorkerCrashedError("crash"),
        3: RuntimeError("other"),
    }

    def target(req):
        if req.index in errors:
            raise errors[req.index]
        return 4, None

    report = replay(trace, target, concurrency=2)
    n = len(trace.requests)
    assert report.outcomes == {
        "ok": n - 4,
        "shed": 1,
        "deadline_expired": 1,
        "crashed": 1,
        "error": 1,
    }
    by_index = {o.index: o for o in report.results}
    assert by_index[0].outcome == "shed"
    assert by_index[0].error == "RejectedError"
    assert by_index[0].tokens == 0
    assert by_index[2].outcome == "crashed"
    # Shed/failed requests never contribute to goodput.
    assert report.ok_tokens == 4 * (n - 4)


def test_slo_violations_fire_the_flight_recorder(monkeypatch):
    from zookeeper_tpu.observability import recorder as _recorder

    seen = []
    monkeypatch.setattr(
        _recorder,
        "notify",
        lambda kind, step=None, attrs=None: seen.append((kind, attrs)),
    )
    trace = tiny_trace()
    slow = {trace.requests[0].index, trace.requests[1].index}

    def target(req):
        return 4, 500.0 if req.index in slow else 0.5

    report = replay(trace, target, slo_ttft_ms=100.0)
    assert len(report.violations) == 2
    assert {v["index"] for v in report.violations} == slow
    assert all(kind == "slo_violation" for kind, _ in seen)
    assert len(seen) == 2
    assert all("ttft_ms=500.0" in a["breached"][0] for _, a in seen)


def test_fault_plan_installed_for_replay_and_always_cleared():
    plan = FaultPlan(delay_forward_ms={"w9": 1})
    observed = []

    def target(req):
        observed.append(faults.active() is plan)
        return 1, None

    replay(tiny_trace(base_s=0.02, burst_s=0.0, cooldown_s=0.0),
           target, fault_plan=plan)
    assert observed and all(observed)
    assert faults.active() is None

    def boom(req):
        raise KeyboardInterrupt  # even a hard per-request abort is
        # contained as a terminal outcome, and the plan still clears

    report = replay(
        tiny_trace(base_s=0.02, burst_s=0.0, cooldown_s=0.0),
        boom,
        fault_plan=plan,
    )
    assert set(report.outcomes) == {"error"}
    assert faults.active() is None


class FakePending:
    def __init__(self, rid, rows):
        self.rid = rid
        self._rows = rows

    def result(self, timeout=None):
        return np.zeros((self._rows, 1), np.float32)


class FakeLog:
    def __init__(self):
        self.details = {}

    def find(self, rid):
        if rid not in self.details:
            return None
        return {"rid": rid, "detail": self.details[rid]}


class FakeBatcherTarget:
    """submit+flush duck type (open-loop path) whose RequestLog
    carries router-style ``retried=N`` details."""

    def __init__(self):
        self.request_log = FakeLog()
        self._next_rid = 100

    def submit(self, x, deadline_ms=None):
        rid = self._next_rid
        self._next_rid += 1
        if rid % 2 == 0:
            self.request_log.details[rid] = (
                f"ok replica=w1 retried={rid % 3}"
            )
        return FakePending(rid, int(np.asarray(x).shape[0]))

    def flush(self):
        pass


def test_retried_parsed_from_target_request_log():
    trace = tiny_trace(base_s=0.05, burst_s=0.0, cooldown_s=0.0)
    target = FakeBatcherTarget()
    report = replay(trace, target)  # auto -> open_loop via submit+flush
    assert report.outcomes == {"ok": len(trace.requests)}
    want = sum(
        rid % 3
        for rid in range(100, 100 + len(trace.requests))
        if rid % 2 == 0
    )
    assert report.retried_total == want
    by_rid = {o.rid: o for o in report.results}
    assert by_rid[102].retried == 102 % 3
    assert by_rid[101].retried == 0  # no log entry: parsed as 0


def test_open_loop_admission_error_is_terminal_at_submit():
    class SheddingTarget(FakeBatcherTarget):
        def submit(self, x, deadline_ms=None):
            if self._next_rid >= 103:
                raise RejectedError("queue full")
            return super().submit(x, deadline_ms=deadline_ms)

    trace = tiny_trace(base_s=0.05, burst_s=0.0, cooldown_s=0.0)
    assert len(trace.requests) > 4
    report = replay(trace, SheddingTarget())
    assert report.outcomes["ok"] == 3
    assert report.outcomes["shed"] == len(trace.requests) - 3
    shed = [o for o in report.results if o.outcome == "shed"]
    assert all(o.rid is None and o.tokens == 0 for o in shed)


def test_mode_and_concurrency_validation():
    with pytest.raises(ValueError, match="mode"):
        replay(tiny_trace(), lambda r: (1, None), mode="bogus")
    with pytest.raises(ValueError, match="concurrency"):
        replay(tiny_trace(), lambda r: (1, None), concurrency=0)


def test_time_scale_paces_arrivals():
    import time

    trace = session_mix(3, sessions=2, turns=2, rate_rps=40.0)
    t0 = time.perf_counter()
    replay(trace, lambda r: (1, None), time_scale=1.0, concurrency=8)
    elapsed_ms = (time.perf_counter() - t0) * 1e3
    # Paced replay takes at least the trace's span (minus the first
    # arrival); unpaced (default) is near-instant in comparison.
    assert elapsed_ms >= trace.duration_ms * 0.5
    t0 = time.perf_counter()
    replay(trace, lambda r: (1, None), concurrency=8)
    assert (time.perf_counter() - t0) * 1e3 < trace.duration_ms * 0.5
