"""Trace generator contracts (docs/DESIGN.md §24): seed determinism,
phase structure, heavy-tail bounds, session prefix growth, round-trip
serialization, RequestLog replay."""

import dataclasses

import pytest

from zookeeper_tpu.loadgen import (
    Trace,
    diurnal_ramp,
    from_request_log,
    poisson_burst,
    session_mix,
)


def as_dicts(trace):
    return [dataclasses.asdict(r) for r in trace.requests]


# -- determinism ------------------------------------------------------------


@pytest.mark.parametrize(
    "gen",
    [
        lambda seed: poisson_burst(seed),
        lambda seed: diurnal_ramp(seed),
        lambda seed: session_mix(seed),
    ],
    ids=["poisson_burst", "diurnal_ramp", "session_mix"],
)
def test_same_seed_same_trace(gen):
    """The §24 determinism contract: same seed, byte-identical trace."""
    assert as_dicts(gen(7)) == as_dicts(gen(7))
    assert as_dicts(gen(7)) != as_dicts(gen(8))


def test_knob_independence_of_field_streams():
    """Changing the OUTPUT-length knob must not perturb arrival times
    or prompt content — each field draws its own counter stream."""
    a = poisson_burst(3, new_tokens=2, max_new_tokens=8)
    b = poisson_burst(3, new_tokens=4, max_new_tokens=32)
    assert [r.at_ms for r in a.requests] == [r.at_ms for r in b.requests]
    assert [r.prompt for r in a.requests] == [r.prompt for r in b.requests]
    assert [r.max_new_tokens for r in a.requests] != [
        r.max_new_tokens for r in b.requests
    ]


# -- structure --------------------------------------------------------------


def test_poisson_burst_phases_and_rates():
    t = poisson_burst(
        11, base_rate_rps=20, burst_rate_rps=400, base_s=1, burst_s=1,
        cooldown_s=1,
    )
    assert t.phases() == ["base", "burst", "cooldown"]
    counts = t.stats()["phases"]
    # A 20x rate step must show up as a hugely denser burst phase.
    assert counts["burst"] > 5 * counts["base"]
    assert counts["burst"] > 5 * counts["cooldown"]
    # Arrivals are sorted, non-negative, and inside the 3s window.
    at = [r.at_ms for r in t.requests]
    assert at == sorted(at)
    assert all(0 <= x < 3_000 for x in at)
    # Indices are dense and stable.
    assert [r.index for r in t.requests] == list(range(len(t.requests)))


def test_heavy_tail_bounds_and_token_range():
    t = poisson_burst(
        5,
        prompt_len=3,
        max_prompt_len=10,
        new_tokens=2,
        max_new_tokens=9,
        vocab=17,
        burst_rate_rps=500,
    )
    lens = [len(r.prompt) for r in t.requests]
    outs = [r.max_new_tokens for r in t.requests]
    assert all(3 <= n <= 10 for n in lens)
    assert all(2 <= n <= 9 for n in outs)
    # Heavy tail: mostly at the floor, but the tail is actually drawn.
    assert min(lens) == 3 and max(lens) > 3
    # Token 0 is reserved (pad/eos): generated prompts never use it.
    assert all(
        1 <= tok < 17 for r in t.requests for tok in r.prompt
    )


def test_deadline_propagates():
    t = poisson_burst(1, deadline_ms=250.0)
    assert all(r.deadline_ms == 250.0 for r in t.requests)
    assert all(
        r.deadline_ms is None for r in poisson_burst(1).requests
    )


def test_diurnal_ramp_phases_and_thinning():
    t = diurnal_ramp(9, peak_rate_rps=200, trough_frac=0.05, duration_s=2)
    assert set(t.phases()) == {"ramp_up", "ramp_down"}
    # Thinning really thins: far fewer accepted than peak-rate draws.
    assert 0 < len(t.requests) < 200 * 2
    at = [r.at_ms for r in t.requests]
    assert at == sorted(at)


def test_session_mix_prefix_growth():
    """Turn k's prompt EXTENDS turn k-1's for every session (the radix
    cache shape), all sessions share the common prefix, and turns
    interleave round-robin rather than session-at-a-time."""
    t = session_mix(
        13, sessions=3, turns=3, shared_prefix_len=6, turn_tokens=2
    )
    by_session = {}
    for r in t.requests:
        by_session.setdefault(r.session, []).append(r)
    assert set(by_session) == {"s0", "s1", "s2"}
    shared = t.requests[0].prompt[:6]
    for sid, reqs in by_session.items():
        assert [r.phase for r in reqs] == ["turn0", "turn1", "turn2"]
        for prev, cur in zip(reqs, reqs[1:]):
            assert cur.prompt[: len(prev.prompt)] == prev.prompt
            assert len(cur.prompt) == len(prev.prompt) + 2
        assert reqs[0].prompt[:6] == shared
    # Interleaved: the first `sessions` arrivals are all DIFFERENT
    # sessions (turn 0 round-robin), not one session's whole history.
    assert len({r.session for r in t.requests[:3]}) == 3


def test_stats_shape():
    t = session_mix(2, sessions=4, turns=2)
    st = t.stats()
    assert st["requests"] == 8
    assert st["sessions"] == 4
    assert st["phases"] == {"turn0": 4, "turn1": 4}
    assert st["mean_prompt_tokens"] > 0
    assert Trace(name="empty", seed=0, requests=[]).stats() == {
        "requests": 0
    }


def test_generator_validation():
    with pytest.raises(ValueError, match="rates"):
        poisson_burst(0, base_rate_rps=0)
    with pytest.raises(ValueError, match="trough_frac"):
        diurnal_ramp(0, trough_frac=1.5)
    with pytest.raises(ValueError, match="sessions"):
        session_mix(0, sessions=0)


# -- serialization ----------------------------------------------------------


def test_save_load_round_trip(tmp_path):
    t = session_mix(21, sessions=2, turns=2, deadline_ms=100.0)
    path = str(tmp_path / "trace.json")
    t.save(path)
    back = Trace.load(path)
    assert back.name == t.name and back.seed == t.seed
    assert as_dicts(back) == as_dicts(t)


def test_from_request_log_offsets_and_sizes():
    base = 5_000_000_000
    records = [
        {"rid": 1, "enqueue_ns": base, "rows": 4, "tokens": 6},
        {"rid": 2, "enqueue_ns": base + 250_000_000, "rows": 8,
         "tokens": 3},
        {"rid": 3, "enqueue_ns": None},  # never enqueued: dropped
        {"rid": 4, "enqueue_ns": base + 100_000_000, "rows": 0},
    ]
    t = from_request_log(records, seed=5, vocab=32)
    assert len(t.requests) == 3
    # Sorted by enqueue time, offsets relative to the FIRST record.
    assert [r.at_ms for r in t.requests] == [0.0, 100.0, 250.0]
    assert len(t.requests[0].prompt) == 4
    assert len(t.requests[2].prompt) == 8
    assert t.requests[0].max_new_tokens == 6
    assert len(t.requests[1].prompt) >= 2  # rows missing: synthesized
    assert all(r.phase == "replay" for r in t.requests)
    # Deterministic like every generator.
    assert as_dicts(from_request_log(records, seed=5, vocab=32)) == (
        as_dicts(t)
    )
    assert from_request_log([], seed=1).requests == []
