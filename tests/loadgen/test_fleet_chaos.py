"""Chaos-composed trace replay over a REAL multi-process fleet
(docs/DESIGN.md §24 acceptance): a session-mix trace drives a
2-replica fleet while a FaultPlan SIGKILLs one replica mid-trace —
every request reaches a terminal outcome, retried requests are
token-identical to the single-replica oracle, the killed replica's
breaker opens, and no worker leaks a single KV page. A second leg
injects a GRAY failure (delay_forward_ms: alive, healthy, slow) and
certifies the breaker's open → half-open probe → closed cycle over
live HTTP routing."""

import json
import time
import urllib.request

import numpy as np
import pytest

from zookeeper_tpu.loadgen import replay, session_mix
from zookeeper_tpu.resilience import FaultPlan
from zookeeper_tpu.serving import CircuitBreaker, FleetRouter
from zookeeper_tpu.serving.fleet import ReplicaHandle

from tests.serving.test_fleet import FLEET_CONF, NEW_TOKENS

pytestmark = [pytest.mark.serving, pytest.mark.slow, pytest.mark.chaos]


def fleet_trace():
    """2 sessions x 2 growing turns, sized for FLEET_CONF geometry
    (vocab 61, prompts <= 16 tokens, fixed NEW_TOKENS budget so the
    oracle comparison is exact)."""
    return session_mix(
        17,
        sessions=2,
        turns=2,
        shared_prefix_len=8,
        turn_tokens=4,
        vocab=FLEET_CONF["vocab_size"],
        new_tokens=NEW_TOKENS,
        max_new_tokens=NEW_TOKENS,
    )


def oracle_for(trace):
    """Single-replica in-process oracle: every trace prompt through one
    paged-KV service — what the fleet must reproduce wherever (and
    however many times) each request lands."""
    from zookeeper_tpu.core import configure
    from zookeeper_tpu.serving import LMServingConfig

    svc = LMServingConfig()
    conf = dict(FLEET_CONF)
    conf["metrics_port"] = -1
    configure(svc, conf, name="trace_oracle")
    _, scheduler = svc.build_service()
    try:
        return {
            r.index: scheduler.submit(
                np.asarray(r.prompt, np.int32),
                max_new_tokens=r.max_new_tokens,
            ).result(timeout=300.0).tolist()
            for r in trace.requests
        }
    finally:
        svc._teardown_service(suppress=True)


def spawn(tmp_path, config, n=2):
    from zookeeper_tpu.testing import spawn_fleet_workers

    return spawn_fleet_workers(str(tmp_path), num_workers=n, config=config)


def statusz(worker):
    url = "http://127.0.0.1:%d/statusz" % worker["metrics_port"]
    with urllib.request.urlopen(url, timeout=10) as r:
        return json.loads(r.read().decode())


def leaked_values(doc):
    """Every ``leaked`` count anywhere in a /statusz document — the
    PagePool status exposes one per pool (KV + draft)."""
    found = []

    def walk(node):
        if isinstance(node, dict):
            for k, v in node.items():
                if k == "leaked":
                    found.append(v)
                else:
                    walk(v)
        elif isinstance(node, list):
            for v in node:
                walk(v)

    walk(doc)
    return found


class RouterTarget:
    """Callable replay target wrapping the router so the test can keep
    each response's exact tokens (the report itself only keeps
    counts)."""

    def __init__(self, router):
        self.router = router
        self.tokens = {}
        self.rids = {}

    def __call__(self, req):
        resp = self.router.submit(
            np.asarray(req.prompt, np.int32),
            session=req.session,
            max_new_tokens=req.max_new_tokens,
        )
        self.tokens[req.index] = resp.tokens.tolist()
        self.rids[req.index] = resp.rid
        return int(resp.tokens.shape[0]), resp.ttft_ms


def test_trace_replay_replica_kill_retries_token_identical(tmp_path):
    """The §24 pinned certification: mid-trace SIGKILL of a replica,
    rid-preserving retries land every request on the survivor with
    oracle-identical tokens, the dead replica's breaker opens, and
    both workers' page pools stay leak-free."""
    from zookeeper_tpu.testing import stop_fleet_workers

    trace = fleet_trace()
    want = oracle_for(trace)
    workers = spawn(tmp_path, FLEET_CONF)
    router = None
    try:
        router = FleetRouter(
            [ReplicaHandle.from_worker(w) for w in workers],
            page_size=FLEET_CONF["engine.page_size"],
            max_retries=2,
            retry_backoff_s=0.05,
            breaker_failures=1,
            breaker_cooldown_s=30.0,  # stays open for the whole replay
        )
        target = RouterTarget(router)
        report = replay(
            trace,
            target,
            fault_plan=FaultPlan(fleet_replica_kill_at=3),
            concurrency=2,
        )
        # Every request reached a terminal outcome — and with retries
        # on, that outcome is ok for ALL of them despite the kill.
        assert report.total == len(trace.requests)
        assert report.outcomes == {"ok": len(trace.requests)}
        # Token identity, including the retried requests: the retry
        # re-ran the SAME rid cold on the survivor and greedy decode
        # reproduced the oracle exactly.
        assert target.tokens == want
        assert router.retries_total >= 1
        assert (
            router.metrics.snapshot()["fleet_retries_total"]
            == router.retries_total
        )
        # The retried rids are traceable in the router's RequestLog.
        retried_rids = [
            rid
            for rid in target.rids.values()
            if "retried=" in (
                (router.request_log.find(rid) or {}).get("detail") or ""
            )
        ]
        assert len(retried_rids) >= 1
        # Exactly one replica died; its breaker tripped open and the
        # survivor's stayed closed.
        dead = [r for r in router.replicas if not r.healthy]
        live = [r for r in router.replicas if r.healthy]
        assert len(dead) == 1 and len(live) == 1
        assert dead[0].breaker.state == CircuitBreaker.OPEN
        assert live[0].breaker.state == CircuitBreaker.CLOSED
        # Zero page leaks on the survivor (the dead worker is gone —
        # its pages died with the process, which is the point of
        # process-level isolation).
        survivor = next(
            w
            for w in workers
            if w["worker_id"] == live[0].worker_id
        )
        leaks = leaked_values(statusz(survivor))
        assert leaks, "no PagePool leak counters found in /statusz"
        assert all(v == 0 for v in leaks)
    finally:
        if router is not None:
            router.close()
        stop_fleet_workers(workers)


def test_gray_failure_breaker_cycle_over_live_fleet(tmp_path):
    """delay_forward_ms chaos: w0 stalls ONE generate by 600ms while
    staying alive and healthy — only the latency-watching breaker can
    see it. The breaker opens, routing avoids w0, the cooldown's
    half-open probe (the gray stall is one-shot, so the probe is fast)
    closes it, and every response is token-identical throughout."""
    from zookeeper_tpu.testing import stop_fleet_workers

    config = dict(FLEET_CONF)
    config["faults"] = {"delay_forward_ms": {"w0": 600}}
    workers = spawn(tmp_path, config)
    router = None
    try:
        router = FleetRouter(
            [ReplicaHandle.from_worker(w) for w in workers],
            page_size=FLEET_CONF["engine.page_size"],
            policy="round_robin",
            breaker_latency_ms=400.0,
            breaker_latency_window=1,
            breaker_cooldown_s=0.5,
            breaker_jitter_frac=0.0,
        )
        prompt = np.arange(1, 11, dtype=np.int32)

        def submit():
            return router.submit(prompt, max_new_tokens=NEW_TOKENS)

        reference = None
        # Route until w0 has served its (stalled) first request.
        for _ in range(4):
            resp = submit()
            if reference is None:
                reference = resp.tokens.tolist()
            assert resp.tokens.tolist() == reference
            if router._by_id["w0"].breaker.state == CircuitBreaker.OPEN:
                break
        b0 = router._by_id["w0"].breaker
        assert b0.state == CircuitBreaker.OPEN
        assert b0.opened_total == 1
        # THE gray-failure point: liveness still says w0 is fine.
        assert router._by_id["w0"].healthy
        router.check_health()
        assert router._by_id["w0"].healthy
        # While open, everything routes to w1.
        for _ in range(2):
            resp = submit()
            assert resp.worker_id == "w1"
            assert resp.tokens.tolist() == reference
        # Cooldown elapses; the next submit claims the half-open probe
        # on w0, which is fast now (the stall was one-shot) → CLOSED.
        deadline = time.monotonic() + 10.0
        probed = None
        while time.monotonic() < deadline:
            resp = submit()
            assert resp.tokens.tolist() == reference
            if resp.worker_id == "w0":
                probed = resp
                break
        assert probed is not None, "w0 never probed after cooldown"
        assert b0.state == CircuitBreaker.CLOSED
        assert b0.probes_total == 1
        assert router.status()["replicas"][0]["breaker"]["state"] == (
            "closed"
        )
    finally:
        if router is not None:
            router.close()
        stop_fleet_workers(workers)
