"""Property-based randomized tests of the configure() precedence
contract (SURVEY.md §3.2).

The hand-written suites pin known precedence subtleties; this module
generates RANDOM component trees (seeded, reproducible) with colliding
field names across depths, random suffix-scoped confs, and random
pre-bound ComponentField overrides, then checks every resolved field
against an INDEPENDENT oracle that re-implements the documented
precedence:

    conf longest-suffix match
      > value set at construction (pre-bound ComponentField overrides)
      > nearest ancestor's *set* same-named field
      > own Field default
      > nearest ancestor's same-named field default

plus the unused-key error contract: randomized typo'd keys (outside
the field pool) and a DETERMINISTIC true-shadowing construction (a
scoped key out-matched by a longer key at every node it could apply
to), both of which must raise ConfigurationError naming the key.
"""

import itertools
import random

import pytest

from zookeeper_tpu.core import (
    ComponentField,
    ConfigurationError,
    Field,
    component,
    configure,
)
from zookeeper_tpu.core.component import configured_field_names

# Small pools on purpose: collisions across depths are the interesting
# cases (same field name declared at several levels, scoped keys that
# shadow each other).
FIELD_POOL = ("alpha", "beta", "gamma")
CHILD_SLOTS = ("first", "second")
NO_DEFAULT = object()

_class_counter = itertools.count()


class SpecNode:
    """Oracle-side tree description, independent of the component API."""

    def __init__(self):
        self.fields = {}  # name -> default int | NO_DEFAULT
        self.overrides = {}  # slot -> {field name -> int} (pre-bound)
        self.children = {}  # slot -> SpecNode


def gen_spec(rng: random.Random, depth: int = 0) -> SpecNode:
    node = SpecNode()
    for f in FIELD_POOL:
        if depth == 0:
            # Root declares every pool field WITH a default so every
            # generated tree resolves (ancestor-default backstop).
            node.fields[f] = rng.randrange(1000)
        else:
            r = rng.random()
            if r < 0.45:
                node.fields[f] = rng.randrange(1000)
            elif r < 0.70:
                node.fields[f] = NO_DEFAULT
            # else: the field is absent on this node entirely.
    if depth < 3:
        for slot in CHILD_SLOTS:
            if rng.random() < 0.65:
                child = gen_spec(rng, depth + 1)
                node.children[slot] = child
                node.overrides[slot] = {
                    f: rng.randrange(1000, 2000)
                    for f in child.fields
                    if rng.random() < 0.2
                }
    return node


def build_component_class(spec: SpecNode) -> type:
    attrs, ann = {}, {}
    for f, default in spec.fields.items():
        attrs[f] = Field() if default is NO_DEFAULT else Field(default)
        ann[f] = int
    for slot, child in spec.children.items():
        child_cls = build_component_class(child)
        attrs[slot] = ComponentField(child_cls, **spec.overrides[slot])
        ann[slot] = child_cls
    attrs["__annotations__"] = ann
    return component(
        type(f"PropNode{next(_class_counter)}", (), attrs)
    )


def walk(spec: SpecNode, path=()):
    yield path, spec
    for slot, child in spec.children.items():
        yield from walk(child, path + (slot,))


def gen_conf(rng: random.Random, spec: SpecNode) -> dict:
    """Random conf keys, each a VALID suffix scoping of some (node,
    field) pair. Because gen_spec gives the root every pool field, the
    bare and full-path keys generated here are always consumable —
    true SHADOWING cannot occur randomly and is covered by the
    deterministic test below; the random unused-key cases come from
    the out-of-pool typo key."""
    conf = {}
    pairs = [
        (path, f) for path, node in walk(spec) for f in node.fields
    ]
    for path, f in rng.sample(pairs, k=min(len(pairs), rng.randrange(1, 7))):
        start = rng.randrange(len(path) + 1)
        key = ".".join(list(path[start:]) + [f])
        conf[key] = rng.randrange(2000, 3000)
    if rng.random() < 0.25:
        # A key no node can consume (field outside the pool): the
        # typo'd-override case, must raise.
        conf["delta"] = 1
    return conf


def oracle(spec: SpecNode, conf: dict):
    """Expected per-node field values + the set of conf keys consumed."""
    used = set()
    results = {}  # path -> {field -> value}
    set_values = {}  # path -> {field -> value} (conf- or construction-set)
    nodes = dict(walk(spec))

    def conf_match(path, name):
        for start in range(len(path) + 1):
            key = ".".join(list(path[start:]) + [name])
            if key in conf:
                return key
        return None

    for path, node in nodes.items():
        sv = set_values[path] = {}
        parent_overrides = {}
        if path:
            parent_overrides = nodes[path[:-1]].overrides.get(path[-1], {})
        for f in node.fields:
            key = conf_match(path, f)
            if key is not None:
                used.add(key)
                sv[f] = conf[key]
            elif f in parent_overrides:
                sv[f] = parent_overrides[f]

    for path, node in nodes.items():
        res = results[path] = {}
        for f, default in node.fields.items():
            if f in set_values[path]:
                res[f] = set_values[path][f]
                continue
            for i in range(len(path) - 1, -1, -1):  # nearest ancestor set
                anc = path[:i]
                if f in set_values[anc]:
                    res[f] = set_values[anc][f]
                    break
            else:
                if default is not NO_DEFAULT:  # own default
                    res[f] = default
                else:  # nearest ancestor WITH a default
                    for i in range(len(path) - 1, -1, -1):
                        anc_default = nodes[path[:i]].fields.get(
                            f, NO_DEFAULT
                        )
                        if anc_default is not NO_DEFAULT:
                            res[f] = anc_default
                            break
                    else:
                        raise AssertionError(
                            "generator invariant broken: no resolvable "
                            f"value for {path}.{f}"
                        )
    return results, used, set_values


def get_node(root_instance, path):
    node = root_instance
    for slot in path:
        node = getattr(node, slot)
    return node


@pytest.mark.parametrize("seed", range(40))
def test_random_tree_matches_precedence_oracle(seed):
    rng = random.Random(seed)
    spec = gen_spec(rng)
    conf = gen_conf(rng, spec)
    expected, used, set_values = oracle(spec, conf)

    cls = build_component_class(spec)
    root = cls()
    if set(conf) - used:
        # Every conf key the oracle says no node consumes (shadowed by
        # longer matches at every applicable node) must be reported.
        with pytest.raises(ConfigurationError, match="did not match"):
            configure(root, conf, name="root")
        return
    configure(root, conf, name="root")
    for path, node_spec in walk(spec):
        inst = get_node(root, path)
        for f in node_spec.fields:
            assert getattr(inst, f) == expected[path][f], (
                f"seed={seed} path={'.'.join(path) or '<root>'} field={f} "
                f"conf={conf}"
            )
        # configured_field_names reports exactly the explicitly-set
        # fields (conf matches + pre-bound overrides) — not inherited
        # or defaulted ones, and not default-instantiated child slots
        # (those live in the lazy-default cache, not the values dict).
        assert configured_field_names(inst) == set(set_values[path]), (
            f"seed={seed} path={'.'.join(path) or '<root>'}"
        )


def _hand_built_spec():
    """root{beta=1} -> first{alpha=2, beta=NO_DEFAULT}
    -> first.second{alpha=3} — built without gen_spec so the root does
    NOT declare alpha (gen_spec's root-declares-everything invariant is
    exactly what makes true shadowing impossible in the random cases).
    """
    grand = SpecNode()
    grand.fields["alpha"] = 3
    child = SpecNode()
    child.fields["alpha"] = 2
    child.fields["beta"] = NO_DEFAULT
    child.children["second"] = grand
    child.overrides["second"] = {}
    root = SpecNode()
    root.fields["beta"] = 1
    root.children["first"] = child
    root.overrides["first"] = {}
    return root


def test_truly_shadowed_scoped_key_raises():
    """TRUE shadowing, not a typo: "second.alpha" names a real field of
    a real node — but its only matching node (first.second) finds its
    longer full-path key first, so the short key is consumed nowhere.
    configure must raise naming it; the oracle must predict exactly
    that key."""
    spec = _hand_built_spec()
    conf = {
        "first.alpha": 10,
        "first.second.alpha": 11,
        "second.alpha": 12,  # shadowed by first.second.alpha
    }
    _, used, _ = oracle(spec, conf)
    assert set(conf) - used == {"second.alpha"}
    with pytest.raises(ConfigurationError, match="second.alpha"):
        configure(build_component_class(spec)(), conf, name="root")


def test_oracle_matches_hand_computed_tree():
    """Known-answer test: the oracle (and the implementation) against
    values computed BY HAND for a fixed tree+conf — the guard against
    an oracle that drifted into mirroring the implementation's bugs."""
    spec = _hand_built_spec()
    conf = {"first.alpha": 10, "beta": 20}
    expected_by_hand = {
        (): {"beta": 20},  # bare key matches the root directly
        ("first",): {
            "alpha": 10,  # its scoped key
            "beta": 20,  # bare key matches here too (suffix "")
        },
        ("first", "second"): {
            # No key matches this path; nearest ancestor SET alpha=10
            # beats the own default 3 (explicit beats implicit).
            "alpha": 10,
        },
    }
    results, used, _ = oracle(spec, conf)
    assert used == set(conf)
    assert results == expected_by_hand

    root = build_component_class(spec)()
    configure(root, conf, name="root")
    for path, fields in expected_by_hand.items():
        for f, v in fields.items():
            assert getattr(get_node(root, path), f) == v, (path, f)
