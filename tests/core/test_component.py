"""Tests for the @component/configure contract (SURVEY.md §3.2).

Covers the reference test surface (SURVEY.md §4 'component_test.py is by far
the largest'): configure precedence, scope inheritance, subclass lookup,
immutability, type-check failures, tree printing.
"""

import pytest

from zookeeper_tpu import (
    ComponentField,
    ConfigurationError,
    Field,
    component,
    configure,
    pretty_print,
)


@component
class Child:
    a: int = Field()
    b: str = Field("child_default_b")


@component
class GrandParent:
    pass


@component
class Parent:
    a: int = Field(10)
    child: Child = ComponentField(Child)


def test_simple_configure_and_defaults():
    @component
    class C:
        x: int = Field(3)
        y: str = Field()

    c = C()
    configure(c, {"y": "hello"})
    assert c.x == 3
    assert c.y == "hello"


def test_conf_overrides_default():
    @component
    class C:
        x: int = Field(3)

    c = C()
    configure(c, {"x": 7})
    assert c.x == 7


def test_missing_value_raises():
    @component
    class C:
        x: int = Field()

    with pytest.raises(ConfigurationError, match="x"):
        configure(C(), {})


def test_allow_missing():
    @component
    class C:
        x: int = Field(allow_missing=True)

    c = C()
    configure(c, {})
    with pytest.raises(AttributeError):
        _ = c.x


def test_type_check_failure():
    @component
    class C:
        x: int = Field()

    with pytest.raises(TypeError, match="x"):
        configure(C(), {"x": "not an int"})


def test_type_check_on_assignment():
    @component
    class C:
        x: int = Field()

    c = C()
    with pytest.raises(TypeError):
        c.x = "nope"


def test_scope_inheritance_parent_value_reaches_child():
    p = Parent()
    configure(p, {"a": 5})
    assert p.a == 5
    assert p.child.a == 5  # Child has no own value: inherits parent's set a.


def test_scope_inheritance_parent_default_reaches_child():
    p = Parent()
    configure(p, {})
    # Parent's default a=10 flows to the child, which has no default.
    assert p.child.a == 10


def test_scoped_key_beats_unscoped():
    p = Parent()
    configure(p, {"a": 5, "child.a": 99})
    assert p.a == 5
    assert p.child.a == 99


def test_child_own_default_beats_parent_default():
    @component
    class Kid:
        b: str = Field("kid_b")

    @component
    class Pa:
        b: str = Field("pa_b")
        kid: Kid = ComponentField(Kid)

    p = Pa()
    configure(p, {})
    assert p.b == "pa_b"
    assert p.kid.b == "kid_b"  # Own default wins over ancestor default.


def test_parent_set_value_beats_child_default():
    @component
    class Kid:
        b: str = Field("kid_b")

    @component
    class Pa:
        b: str = Field("pa_b")
        kid: Kid = ComponentField(Kid)

    p = Pa()
    configure(p, {"b": "explicit"})
    # Explicit beats implicit: configured ancestor value overrides the
    # child's default (SURVEY.md §3.2 precedence).
    assert p.kid.b == "explicit"


def test_deep_inheritance_through_chain():
    @component
    class Leaf:
        size: int = Field()

    @component
    class Mid:
        leaf: Leaf = ComponentField(Leaf)

    @component
    class Root:
        size: int = Field(128)
        mid: Mid = ComponentField(Mid)

    r = Root()
    configure(r, {})
    assert r.mid.leaf.size == 128


def test_subclass_by_name_lookup():
    @component
    class Base:
        tag: str = Field("base")

    @component
    class Special(Base):
        tag: str = Field("special")

    @component
    class Host:
        item: Base = ComponentField(Base)

    h = Host()
    configure(h, {"item": "Special"})
    assert type(h.item).__name__ == "Special"
    assert h.item.tag == "special"


def test_subclass_by_snake_case_name():
    @component
    class Vehicle:
        pass

    @component
    class FastCar(Vehicle):
        pass

    @component
    class Garage:
        v: Vehicle = ComponentField(Vehicle)

    g = Garage()
    configure(g, {"v": "fast_car"})
    assert type(g.v).__name__ == "FastCar"


def test_unknown_subclass_name_raises():
    @component
    class AnimalZ:
        pass

    @component
    class FarmZ:
        a: AnimalZ = ComponentField(AnimalZ)

    with pytest.raises(ConfigurationError, match="Nope"):
        configure(FarmZ(), {"a": "Nope"})


def test_component_field_no_default_raises():
    @component
    class Thing:
        pass

    @component
    class Holder:
        t: Thing = ComponentField()

    with pytest.raises(ConfigurationError, match="t"):
        configure(Holder(), {})


def test_immutability_after_configure():
    @component
    class C:
        x: int = Field(1)

    c = C()
    configure(c, {})
    with pytest.raises(AttributeError, match="immutable"):
        c.x = 5


def test_cannot_reconfigure():
    @component
    class C:
        x: int = Field(1)

    c = C()
    configure(c, {})
    with pytest.raises(ConfigurationError, match="already configured"):
        configure(c, {})


def test_preassigned_value_used_when_not_in_conf():
    @component
    class C:
        x: int = Field()

    c = C(x=9)
    configure(c, {})
    assert c.x == 9


def test_conf_overrides_preassigned():
    @component
    class C:
        x: int = Field()

    c = C(x=9)
    configure(c, {"x": 2})
    assert c.x == 2


def test_lazy_default_with_self():
    @component
    class C:
        base: int = Field(4)
        derived: int = Field(lambda self: self.base * 3)

    c = C()
    configure(c, {})
    assert c.derived == 12


def test_field_decorator_form():
    @component
    class C:
        n: int = Field(2)

        @Field
        def doubled(self) -> int:
            return self.n * 2

    c = C()
    configure(c, {})
    assert c.doubled == 4


def test_lazy_default_cached():
    calls = []

    @component
    class C:
        @Field
        def v(self) -> int:
            calls.append(1)
            return 42

    c = C()
    configure(c, {})
    assert c.v == 42
    assert c.v == 42
    assert len(calls) == 1


def test_unused_conf_key_raises():
    @component
    class C:
        x: int = Field(1)

    with pytest.raises(ConfigurationError, match="typo_key"):
        configure(C(), {"typo_key": 5})


def test_field_inheritance_from_base_class():
    @component
    class BaseC:
        x: int = Field(5)

    @component
    class DerivedC(BaseC):
        y: int = Field(6)

    d = DerivedC()
    configure(d, {})
    assert d.x == 5 and d.y == 6


def test_field_override_in_subclass():
    @component
    class BaseD:
        x: int = Field(5)

    @component
    class DerivedD(BaseD):
        x: int = Field(7)

    d = DerivedD()
    configure(d, {})
    assert d.x == 7


def test_component_may_not_define_init():
    with pytest.raises(TypeError, match="__init__"):

        @component
        class Bad:
            def __init__(self):
                pass


def test_nested_component_instance_in_conf():
    @component
    class Inner:
        x: int = Field(1)

    @component
    class Outer:
        inner: Inner = ComponentField()

    inst = Inner()
    o = Outer()
    configure(o, {"inner": inst})
    assert o.inner is inst
    assert o.inner.x == 1


def test_component_field_kwarg_overrides():
    @component
    class Opt:
        lr: float = Field(0.1)

    @component
    class Exp:
        opt: Opt = ComponentField(Opt, lr=0.5)

    e = Exp()
    configure(e, {})
    assert e.opt.lr == 0.5

    e2 = Exp()
    configure(e2, {"opt.lr": 0.9})
    assert e2.opt.lr == 0.9  # Explicit conf still beats the pre-bound value.


def test_pretty_print_renders_tree():
    p = Parent()
    configure(p, {"child.a": 2})
    text = pretty_print(p, color=False)
    assert "Parent(" in text
    assert "Child(" in text
    assert "a=2" in text
    assert "child_default_b" in text


def test_str_of_component_is_tree():
    p = Parent()
    configure(p, {})
    assert "Parent(" in str(p)


def test_wrong_component_type_raises():
    @component
    class NotADataset:
        pass

    @component
    class NeedsChild:
        child: Child = ComponentField()

    with pytest.raises((TypeError, ConfigurationError)):
        configure(NeedsChild(), {"child": NotADataset()})


def test_generated_init_rejects_unknown_kwargs():
    @component
    class C:
        x: int = Field(1)

    with pytest.raises(TypeError, match="unexpected keyword"):
        C(zzz=1)


# --- Regression tests from round-1 code review -----------------------------


def test_scoped_key_propagation_order_independent():
    """A key scoped to an ancestor must reach grandchildren regardless of
    the intermediate component's field declaration order."""

    @component
    class Prep1:
        size: int = Field()

    @component
    class Data1:
        prep: Prep1 = ComponentField(Prep1)  # ComponentField declared first
        size: int = Field()

    @component
    class Exp1:
        dataset: Data1 = ComponentField(Data1)

    e = Exp1()
    configure(e, {"dataset.size": 4})
    assert e.dataset.size == 4
    assert e.dataset.prep.size == 4


def test_run_can_set_plain_attributes_after_configure():
    @component
    class T:
        x: int = Field(1)

    t = T()
    configure(t, {})
    t.result = 99  # Non-Field attribute: allowed post-configure.
    assert t.result == 99
    with pytest.raises(AttributeError):
        t.x = 2  # Declared Field: still immutable.


def test_overrides_not_forced_onto_sibling_subclass():
    @component
    class OptR:
        pass

    @component
    class AdamR(OptR):
        lr: float = Field(1e-3)

    @component
    class SgdR(OptR):
        pass

    @component
    class ExpR:
        opt: OptR = ComponentField(AdamR, lr=1e-2)

    e = ExpR()
    configure(e, {"opt": "SgdR"})  # Must not crash on unknown 'lr'.
    assert type(e.opt).__name__ == "SgdR"

    e2 = ExpR()
    configure(e2, {})
    assert e2.opt.lr == 1e-2


def test_mutable_default_not_shared_between_instances():
    @component
    class M:
        layers: list = Field([1])

    a, b = M(), M()
    configure(a, {})
    configure(b, {})
    a.layers.append(99)
    assert b.layers == [1]


def test_bad_concrete_default_rejected_at_declaration():
    with pytest.raises(TypeError, match="Default"):

        @component
        class BadDefault:
            x: int = Field("oops")


def test_partial_component_conf_value_merges_field_overrides():
    from zookeeper_tpu import PartialComponent

    @component
    class AdamP:
        lr: float = Field(1e-3)

    @component
    class ExpP:
        opt: AdamP = ComponentField(AdamP, lr=5.0)

    e = ExpP()
    configure(e, {"opt": PartialComponent(AdamP)})
    assert e.opt.lr == 5.0
