"""Regression tests for configure() precedence subtleties (code-review
findings): ancestor explicit values vs own defaults for ComponentFields,
declaration-order independence, inherited-value type checking, override
typo detection, and PEP 563 string annotations."""

import pytest

from zookeeper_tpu.core import (
    ComponentField,
    ConfigurationError,
    Field,
    component,
    configure,
)


@component
class Optimizer:
    lr: float = Field(0.1)


@component
class Sgd(Optimizer):
    momentum: float = Field(0.9)


@component
class Adam(Optimizer):
    b1: float = Field(0.9)


def test_ancestor_explicit_component_beats_child_default():
    @component
    class Inner:
        optimizer: Optimizer = ComponentField(Sgd)

    @component
    class Root:
        optimizer: Optimizer = ComponentField()
        inner: Inner = ComponentField(Inner)

    root = Root(optimizer=Adam())
    configure(root, {}, name="root")
    # The parent's explicitly-set Adam wins over Inner's own Sgd default.
    assert isinstance(root.inner.optimizer, Adam)
    assert root.inner.optimizer is root.optimizer


def test_child_default_beats_ancestor_default():
    @component
    class Inner2:
        optimizer: Optimizer = ComponentField(Sgd)

    @component
    class Root2:
        optimizer: Optimizer = ComponentField(Adam)
        inner: Inner2 = ComponentField(Inner2)

    root = Root2()
    configure(root, {}, name="root")
    # Both defaults: each component gets its own default (explicit beats
    # implicit; a mere ancestor default does not override).
    assert isinstance(root.inner.optimizer, Sgd)
    assert isinstance(root.optimizer, Adam)


def test_component_inheritance_independent_of_declaration_order():
    @component
    class Inner3:
        optimizer: Optimizer = ComponentField()

    @component
    class Root3:
        # Child declared BEFORE the sibling it must inherit from.
        inner: Inner3 = ComponentField(Inner3)
        optimizer: Optimizer = ComponentField(Adam)

    root = Root3()
    configure(root, {}, name="root")
    assert isinstance(root.inner.optimizer, Adam)


def test_plain_field_inheritance_order_independent():
    @component
    class Leaf:
        batch_size: int = Field()

    @component
    class Root4:
        leaf: Leaf = ComponentField(Leaf)
        batch_size: int = Field(64)

    root = Root4()
    configure(root, {"batch_size": 32}, name="root")
    assert root.leaf.batch_size == 32


def test_inherited_value_type_checked_at_configure():
    @component
    class Leaf2:
        n: int = Field()

    @component
    class Mid2:
        leaf: Leaf2 = ComponentField(Leaf2)
        n: str = Field()

    @component
    class Root6:
        n: str = Field()
        mid: Mid2 = ComponentField(Mid2)

    # Pre-assign at the root only: mid.n inherits "hello" fine (str), but
    # leaf.n declares int and must fail AT CONFIGURE TIME, not at access.
    root = Root6(n="hello")
    with pytest.raises(TypeError, match="inherits"):
        configure(root, {}, name="root")


def test_inherited_component_type_checked_at_configure():
    @component
    class NotAnOptimizer:
        x: int = Field(1)

    @component
    class Inner4:
        optimizer: Optimizer = ComponentField()

    @component
    class Root7:
        optimizer: NotAnOptimizer = ComponentField()
        inner: Inner4 = ComponentField(Inner4)

    root = Root7(optimizer=NotAnOptimizer())
    with pytest.raises(TypeError, match="inherits"):
        configure(root, {}, name="root")


def test_override_typo_raises_at_declaration():
    with pytest.raises(TypeError, match="learning_rte"):

        @component
        class Root8:
            optimizer: Optimizer = ComponentField(Adam, learning_rte=1e-2)


def test_override_soft_default_filtered_for_selected_subclass():
    @component
    class Root9:
        optimizer: Optimizer = ComponentField(Sgd, momentum=0.5)

    root = Root9()
    # Adam has no 'momentum'; the override is a soft default and is dropped.
    configure(root, {"optimizer": "Adam"}, name="root")
    assert isinstance(root.optimizer, Adam)
    root2 = Root9()
    configure(root2, {}, name="root")
    assert root2.optimizer.momentum == 0.5


def test_pep563_string_annotations_resolve():
    # Simulate `from __future__ import annotations` with explicit strings.
    @component
    class Root10:
        optimizer: "Optimizer" = ComponentField(Sgd)
        lr: "float" = Field(0.2)

    root = Root10()
    configure(root, {"optimizer": "Adam"}, name="root")
    assert isinstance(root.optimizer, Adam)
    with pytest.raises(TypeError):
        configure(Root10(), {"lr": "high"}, name="root")


def test_factory_unresolvable_return_annotation_does_not_crash():
    from zookeeper_tpu import factory

    @factory
    class MakesMystery:
        def build(self) -> "SomeUndefinedType":  # noqa: F821
            return 42

    @component
    class Root11:
        n: int = Field()

    root = Root11()
    configure(root, {"n": "MakesMystery"}, name="root")
    assert root.n == 42


def test_preassigned_partial_component_keeps_field_overrides():
    from zookeeper_tpu import PartialComponent

    @component
    class Child:
        a: int = Field(1)
        b: int = Field(2)

    @component
    class Root12:
        child: Child = ComponentField(Child, a=99)

    # Same PartialComponent via pre-assignment and via conf must configure
    # identically (field overrides act as soft defaults in both).
    r1 = Root12()
    r1.child = PartialComponent(Child, b=5)
    configure(r1, {}, name="r1")
    r2 = Root12()
    configure(r2, {"child": PartialComponent(Child, b=5)}, name="r2")
    assert (r1.child.a, r1.child.b) == (r2.child.a, r2.child.b) == (99, 5)


def test_init_subclass_cooperative_chaining():
    registry = []

    class RegistryMixin:
        def __init_subclass__(cls, **kwargs):
            super().__init_subclass__(**kwargs)
            registry.append(cls.__name__)

    @component
    class Base13(RegistryMixin):
        a: int = Field(1)

    class Sub13(Base13):
        b: int = Field(2)

    # The mixin's registration hook must still run for component subclasses.
    assert "Sub13" in registry
    # And the subclass's own fields are collected.
    assert set(Sub13.__component_fields__) == {"a", "b"}
