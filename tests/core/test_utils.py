"""Tests for core utils (SURVEY.md §2.1 'Utilities')."""

from typing import Dict, List, Optional, Union

import pytest

from zookeeper_tpu.core import utils


def test_missing_singleton():
    assert utils.missing is utils._Missing()
    assert not utils.missing
    assert repr(utils.missing) == "<missing>"


@pytest.mark.parametrize(
    "value,annotation,ok",
    [
        (1, int, True),
        ("a", int, False),
        (1.5, float, True),
        ([1, 2], List[int], True),
        (["a"], List[int], False),
        ({"a": 1}, Dict[str, int], True),
        (None, Optional[int], True),
        (3, Optional[int], True),
        ("x", Union[int, str], True),
        (1.0, Union[int, str], False),
    ],
)
def test_type_check(value, annotation, ok):
    assert utils.type_check(value, annotation) is ok


@pytest.mark.parametrize(
    "camel,snake",
    [
        ("QuickNet", "quick_net"),
        ("QuickNetLarge", "quick_net_large"),
        ("BinaryAlexNet", "binary_alex_net"),
        ("Mnist", "mnist"),
        ("TFDSDataset", "tfds_dataset"),
    ],
)
def test_snake_case(camel, snake):
    assert utils.convert_to_snake_case(camel) == snake


def test_generate_subclasses():
    class A:
        pass

    class B(A):
        pass

    class C(B):
        pass

    subs = set(utils.generate_subclasses(A))
    assert subs == {A, B, C}


def test_find_subclass_by_name():
    class Base2:
        pass

    class Leaf2(Base2):
        pass

    assert utils.find_subclass_by_name(Base2, "Leaf2") is Leaf2
    assert utils.find_subclass_by_name(Base2, "leaf2") is Leaf2
    with pytest.raises(utils.ConfigurationError):
        utils.find_subclass_by_name(Base2, "Nope")


@pytest.mark.parametrize(
    "raw,expected",
    [
        ("10", 10),
        ("1e-3", 1e-3),
        ("True", True),
        ("None", None),
        ("(1, 2)", (1, 2)),
        ("[1, 'a']", [1, "a"]),
        ("mnist", "mnist"),
        ("'quoted'", "quoted"),
    ],
)
def test_parse_value(raw, expected):
    assert utils.parse_value(raw) == expected


def test_parse_value_round_trips_random_literals():
    """Property: any python literal survives repr -> parse_value (the
    CLI's key=value grammar is exactly ast.literal_eval + string
    fallback), across randomized nesting."""
    import random

    from zookeeper_tpu.core.utils import parse_value

    rng = random.Random(7)

    def gen_literal(depth=0):
        kinds = ["int", "float", "str", "bool", "none"]
        if depth < 2:
            kinds += ["tuple", "list", "dict"]
        kind = rng.choice(kinds)
        if kind == "int":
            return rng.randrange(-(10**9), 10**9)
        if kind == "float":
            # round() keeps repr exact; NaN/inf are not literals.
            return round(rng.uniform(-1e6, 1e6), 6)
        if kind == "str":
            return "".join(
                rng.choice("abz_ 0-.'\"\\") for _ in range(rng.randrange(8))
            )
        if kind == "bool":
            return rng.random() < 0.5
        if kind == "none":
            return None
        if kind == "tuple":
            return tuple(
                gen_literal(depth + 1) for _ in range(rng.randrange(4))
            )
        if kind == "list":
            return [gen_literal(depth + 1) for _ in range(rng.randrange(4))]
        return {
            f"k{i}": gen_literal(depth + 1) for i in range(rng.randrange(3))
        }

    for _ in range(300):
        value = gen_literal()
        assert parse_value(repr(value)) == value, repr(value)

    # The string fallback: bare words (not valid literals) come back
    # verbatim, which is what makes `dataset=Mnist` work unquoted.
    for word in ("Mnist", "quicknet_large", "path/to/dir", "3x3", "a=b"):
        assert parse_value(word) == word
