"""Tests for core utils (SURVEY.md §2.1 'Utilities')."""

from typing import Dict, List, Optional, Union

import pytest

from zookeeper_tpu.core import utils


def test_missing_singleton():
    assert utils.missing is utils._Missing()
    assert not utils.missing
    assert repr(utils.missing) == "<missing>"


@pytest.mark.parametrize(
    "value,annotation,ok",
    [
        (1, int, True),
        ("a", int, False),
        (1.5, float, True),
        ([1, 2], List[int], True),
        (["a"], List[int], False),
        ({"a": 1}, Dict[str, int], True),
        (None, Optional[int], True),
        (3, Optional[int], True),
        ("x", Union[int, str], True),
        (1.0, Union[int, str], False),
    ],
)
def test_type_check(value, annotation, ok):
    assert utils.type_check(value, annotation) is ok


@pytest.mark.parametrize(
    "camel,snake",
    [
        ("QuickNet", "quick_net"),
        ("QuickNetLarge", "quick_net_large"),
        ("BinaryAlexNet", "binary_alex_net"),
        ("Mnist", "mnist"),
        ("TFDSDataset", "tfds_dataset"),
    ],
)
def test_snake_case(camel, snake):
    assert utils.convert_to_snake_case(camel) == snake


def test_generate_subclasses():
    class A:
        pass

    class B(A):
        pass

    class C(B):
        pass

    subs = set(utils.generate_subclasses(A))
    assert subs == {A, B, C}


def test_find_subclass_by_name():
    class Base2:
        pass

    class Leaf2(Base2):
        pass

    assert utils.find_subclass_by_name(Base2, "Leaf2") is Leaf2
    assert utils.find_subclass_by_name(Base2, "leaf2") is Leaf2
    with pytest.raises(utils.ConfigurationError):
        utils.find_subclass_by_name(Base2, "Nope")


@pytest.mark.parametrize(
    "raw,expected",
    [
        ("10", 10),
        ("1e-3", 1e-3),
        ("True", True),
        ("None", None),
        ("(1, 2)", (1, 2)),
        ("[1, 'a']", [1, "a"]),
        ("mnist", "mnist"),
        ("'quoted'", "quoted"),
    ],
)
def test_parse_value(raw, expected):
    assert utils.parse_value(raw) == expected
