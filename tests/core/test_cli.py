"""CLI tests with click.testing.CliRunner (SURVEY.md §4 'CLI tests')."""

from click.testing import CliRunner

from zookeeper_tpu import Field, task
from zookeeper_tpu.core.cli import cli

RESULTS = {}


@task
class GreetTask:
    """Greets someone."""

    name: str = Field("world")
    times: int = Field(1)

    def run(self):
        RESULTS["greeting"] = " ".join([f"hello {self.name}"] * self.times)


@task
class NeedsValueTask:
    x: int = Field()

    def run(self):
        RESULTS["x"] = self.x


def test_task_runs_with_defaults():
    runner = CliRunner()
    result = runner.invoke(cli, ["GreetTask"])
    assert result.exit_code == 0, result.output
    assert RESULTS["greeting"] == "hello world"
    # The resolved config tree is printed before running.
    assert "GreetTask(" in result.output


def test_key_value_args_parsed_and_applied():
    runner = CliRunner()
    result = runner.invoke(cli, ["GreetTask", "name=tpu", "times=2"])
    assert result.exit_code == 0, result.output
    assert RESULTS["greeting"] == "hello tpu hello tpu"


def test_missing_value_fails_without_interactive():
    runner = CliRunner()
    result = runner.invoke(cli, ["NeedsValueTask"])
    assert result.exit_code != 0


def test_interactive_prompts_for_missing(monkeypatch):
    runner = CliRunner()
    result = runner.invoke(cli, ["NeedsValueTask", "-i"], input="42\n")
    assert result.exit_code == 0, result.output
    assert RESULTS["x"] == 42


def test_bad_config_arg_reports_error():
    runner = CliRunner()
    result = runner.invoke(cli, ["GreetTask", "notakeyvalue"])
    assert result.exit_code != 0
    assert "key=value" in result.output


def test_unknown_task_fails():
    runner = CliRunner()
    result = runner.invoke(cli, ["NoSuchTask"])
    assert result.exit_code != 0


def test_typo_key_fails_with_helpful_error():
    runner = CliRunner()
    result = runner.invoke(cli, ["GreetTask", "nmae=x"])
    assert result.exit_code != 0
