"""Tests for @factory (SURVEY.md §2.1 'Factory system', §3.5)."""

import pytest

from zookeeper_tpu import ConfigurationError, Field, component, configure, factory


class Schedule:
    def __init__(self, values):
        self.values = values


@factory
class ConstantSchedule:
    value: float = Field(1.0)

    def build(self) -> Schedule:
        return Schedule([self.value])


@factory
class RampSchedule:
    steps: int = Field()

    def build(self) -> Schedule:
        return Schedule(list(range(self.steps)))


def test_factory_by_name():
    @component
    class Exp:
        schedule: Schedule = Field()

    e = Exp()
    configure(e, {"schedule": "ConstantSchedule", "schedule.value": 2.5})
    assert isinstance(e.schedule, Schedule)
    assert e.schedule.values == [2.5]


def test_factory_fields_configured_from_scoped_keys():
    @component
    class Exp:
        schedule: Schedule = Field()

    e = Exp()
    configure(e, {"schedule": "RampSchedule", "schedule.steps": 3})
    assert e.schedule.values == [0, 1, 2]


def test_factory_scope_inheritance_from_host():
    @component
    class Exp:
        steps: int = Field(4)
        schedule: Schedule = Field()

    e = Exp()
    # RampSchedule.steps has no value of its own: inherits Exp.steps.
    configure(e, {"schedule": "RampSchedule", "steps": 4})
    assert e.schedule.values == [0, 1, 2, 3]


def test_factory_missing_field_raises():
    @component
    class Exp:
        schedule: Schedule = Field()

    with pytest.raises(ConfigurationError, match="steps"):
        configure(Exp(), {"schedule": "RampSchedule"})


def test_unknown_factory_name_raises():
    @component
    class Exp:
        schedule: Schedule = Field()

    with pytest.raises((TypeError, ConfigurationError)):
        configure(Exp(), {"schedule": "NoSuchFactory"})


def test_factory_requires_build():
    with pytest.raises(TypeError, match="build"):

        @factory
        class Bad:
            pass
