"""Tests for PartialComponent (SURVEY.md §2.1)."""

import pytest

from zookeeper_tpu import (
    ComponentField,
    Field,
    PartialComponent,
    component,
    configure,
)


@component
class Opt:
    lr: float = Field(0.1)
    momentum: float = Field(0.9)


def test_partial_binds_fields():
    p = PartialComponent(Opt, lr=0.5)
    inst = p()
    configure(inst, {})
    assert inst.lr == 0.5
    assert inst.momentum == 0.9


def test_partial_as_component_field_default():
    @component
    class Exp:
        opt: Opt = ComponentField(PartialComponent(Opt, lr=0.25))

    e = Exp()
    configure(e, {})
    assert e.opt.lr == 0.25


def test_conf_overrides_partial_binding():
    @component
    class Exp:
        opt: Opt = ComponentField(PartialComponent(Opt, lr=0.25))

    e = Exp()
    configure(e, {"opt.lr": 0.75})
    assert e.opt.lr == 0.75


def test_nested_partial_merging():
    p1 = PartialComponent(Opt, lr=0.5)
    p2 = PartialComponent(p1, momentum=0.99)
    inst = p2()
    configure(inst, {})
    assert inst.lr == 0.5 and inst.momentum == 0.99


def test_partial_rejects_unknown_field():
    with pytest.raises(TypeError, match="zzz"):
        PartialComponent(Opt, zzz=1)


def test_partial_rejects_non_component():
    class Plain:
        pass

    with pytest.raises(TypeError):
        PartialComponent(Plain, x=1)
