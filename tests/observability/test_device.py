"""Device memory probe: stats enumeration never raises, gauges always
exist (the -1 sentinel where the backend has no allocator stats), and
the zk-device-probe thread lifecycle is clean."""

import threading

import pytest

from zookeeper_tpu.observability.device import (
    DeviceProbe,
    device_memory_stats,
)
from zookeeper_tpu.observability.registry import MetricsRegistry


def test_device_memory_stats_enumerates_local_devices():
    stats = device_memory_stats()
    assert stats  # jax always exposes >= 1 local device
    for i, row in enumerate(stats):
        assert row["device"] == i
        assert "kind" in row


def test_poll_once_publishes_every_gauge_with_sentinel():
    """Every device gets all three zk_hbm_* gauges on every poll; a
    backend without memory_stats (CPU) publishes the documented -1
    sentinel rather than dropping the series."""
    reg = MetricsRegistry()
    probe = DeviceProbe(registry=reg)
    stats = probe.poll_once()
    for row in stats:
        labels = {"device": str(row["device"])}
        for name in (
            "zk_hbm_bytes_in_use",
            "zk_hbm_peak_bytes_in_use",
            "zk_hbm_bytes_limit",
        ):
            value = reg.gauge(name, labels=labels).value
            if row.get("bytes_in_use") is None:
                assert value == -1
            else:
                assert value >= 0


def test_poll_once_reflects_real_stats_when_backend_exposes_them(
    monkeypatch,
):
    """Numbers from memory_stats land verbatim in the gauges (pinned
    via a faked stats payload so the test runs on any backend)."""
    from zookeeper_tpu.observability import device as device_mod

    monkeypatch.setattr(
        device_mod,
        "device_memory_stats",
        lambda: [
            {
                "device": 0,
                "kind": "fake-tpu",
                "bytes_in_use": 123.0,
                "peak_bytes_in_use": 456.0,
                "bytes_limit": 789.0,
            }
        ],
    )
    reg = MetricsRegistry()
    DeviceProbe(registry=reg).poll_once()
    labels = {"device": "0"}
    assert reg.gauge("zk_hbm_bytes_in_use", labels=labels).value == 123.0
    assert (
        reg.gauge("zk_hbm_peak_bytes_in_use", labels=labels).value == 456.0
    )
    assert reg.gauge("zk_hbm_bytes_limit", labels=labels).value == 789.0


def test_probe_thread_lifecycle_and_naming():
    probe = DeviceProbe(interval_s=60.0, registry=MetricsRegistry())
    assert not probe.alive
    probe.start()
    try:
        assert probe.alive
        names = [t.name for t in threading.enumerate()]
        assert "zk-device-probe" in names
        probe.start()  # idempotent — no second thread
        assert (
            sum(t.name == "zk-device-probe" for t in threading.enumerate())
            == 1
        )
    finally:
        probe.stop()
    assert not probe.alive
    probe.stop()  # idempotent


def test_interval_validation():
    with pytest.raises(ValueError):
        DeviceProbe(interval_s=0.0)
