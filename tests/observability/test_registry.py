"""Typed metrics registry: instrument semantics, get-or-create /
conflict rules, thread safety, and Prometheus text rendering."""

import re
import threading

import pytest

from zookeeper_tpu.observability.export import render_prometheus
from zookeeper_tpu.observability.registry import (
    Histogram,
    MetricsRegistry,
)


def test_counter_monotone():
    r = MetricsRegistry()
    c = r.counter("reqs")
    c.inc()
    c.inc(4)
    assert c.value == 5
    with pytest.raises(ValueError):
        c.inc(-1)


def test_gauge_set_inc_initial():
    r = MetricsRegistry()
    g = r.gauge("step", initial=-1)
    assert g.value == -1
    g.set(7)
    g.inc(2)
    assert g.value == 9


def test_histogram_buckets_cumulative():
    r = MetricsRegistry()
    h = r.histogram("lat", buckets=(1.0, 5.0, 10.0))
    for v in (0.5, 1.0, 3.0, 7.0, 100.0):
        h.observe(v)
    # le semantics: a sample equal to a bound lands IN that bucket.
    assert h.cumulative_counts() == [2, 3, 4]
    assert h.count == 5
    assert h.sum == pytest.approx(111.5)


def test_histogram_rejects_bad_buckets():
    r = MetricsRegistry()
    with pytest.raises(ValueError):
        r.histogram("bad", buckets=())
    with pytest.raises(ValueError):
        r.histogram("bad2", buckets=(5.0, 1.0))
    with pytest.raises(ValueError):
        r.histogram("bad3", buckets=(1.0, 1.0))
    # An inf bound would render an explicit le="+Inf" bucket line next
    # to the implicit one — a duplicate sample Prometheus rejects.
    with pytest.raises(ValueError):
        r.histogram("bad4", buckets=(1.0, float("inf")))
    with pytest.raises(ValueError):
        r.histogram("bad5", buckets=(float("nan"),))


def test_instrument_reset_in_place():
    r = MetricsRegistry()
    c = r.counter("c")
    g = r.gauge("g", initial=-1.0)
    h = r.histogram("h", buckets=(1.0, 10.0))
    c.inc(3)
    g.set(42.0)
    h.observe(5.0)
    for inst in (c, g, h):
        inst.reset()
    assert c.value == 0.0
    assert g.value == -1.0  # registration-time initial, not 0
    assert h.count == 0 and h.sum == 0.0
    assert h.cumulative_counts() == [0, 0]
    # Identity preserved: the registry still hands out the same objects.
    assert r.counter("c") is c and r.gauge("g") is g


def test_get_or_create_shares_and_conflicts():
    r = MetricsRegistry()
    assert r.counter("x") is r.counter("x")
    with pytest.raises(ValueError):
        r.gauge("x")  # same name, different type
    h = r.histogram("h", buckets=(1.0, 2.0))
    assert r.histogram("h", buckets=(1.0, 2.0)) is h
    with pytest.raises(ValueError):
        r.histogram("h", buckets=(1.0, 3.0))  # same name, other bounds


def test_labels_distinguish_series():
    r = MetricsRegistry()
    a = r.counter("req", labels={"tier": "a"})
    b = r.counter("req", labels={"tier": "b"})
    assert a is not b
    a.inc()
    assert (a.value, b.value) == (1, 0)


def test_concurrent_counter_increments_are_exact():
    r = MetricsRegistry()
    c = r.counter("hits")
    h = r.histogram("obs", buckets=(10.0, 100.0))
    n_threads, per_thread = 8, 2000

    def work():
        for i in range(per_thread):
            c.inc()
            h.observe(float(i % 150))

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == n_threads * per_thread
    assert h.count == n_threads * per_thread
    assert h.cumulative_counts()[-1] + (h.count - h.cumulative_counts()[-1]) == h.count


PROM_SAMPLE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? [^ ]+$"
)


def test_prometheus_rendering_format():
    r = MetricsRegistry()
    r.counter("zk_requests", help="total requests").inc(3)
    r.gauge("zk_step", initial=-1)
    h = r.histogram("zk_lat_ms", buckets=(1.0, 10.0), help="latency")
    h.observe(0.3)
    h.observe(4.0)
    h.observe(40.0)
    labeled = r.counter("zk_tenant_reqs", labels={"tenant": "a b"})
    labeled.inc()
    text = render_prometheus([r])
    lines = text.splitlines()
    samples = [l for l in lines if l and not l.startswith("#")]
    assert all(PROM_SAMPLE.match(l) for l in samples), samples
    assert "# TYPE zk_requests counter" in lines
    assert "# HELP zk_requests total requests" in lines
    assert "zk_requests 3" in lines
    assert "zk_step -1" in lines
    assert "# TYPE zk_lat_ms histogram" in lines
    assert 'zk_lat_ms_bucket{le="1"} 1' in lines
    assert 'zk_lat_ms_bucket{le="10"} 2' in lines
    assert 'zk_lat_ms_bucket{le="+Inf"} 3' in lines
    assert "zk_lat_ms_sum 44.3" in lines
    assert "zk_lat_ms_count 3" in lines
    assert 'zk_tenant_reqs{tenant="a b"} 1' in lines


def test_prometheus_groups_label_variants_under_one_header():
    """Two label variants of one metric (a per-split gauge, or the same
    name across two registries) must render ONE # HELP/# TYPE header
    with contiguous samples — the exposition parser rejects a second
    TYPE line for a name, failing the whole scrape."""
    r = MetricsRegistry()
    r.gauge("zk_occ", help="fill", labels={"split": "train"}).set(2)
    r.gauge("zk_occ", help="fill", labels={"split": "validation"}).set(1)
    r2 = MetricsRegistry()
    r2.gauge("zk_occ", help="fill", labels={"split": "test"}).set(0)
    text = render_prometheus([r, r2])
    lines = text.splitlines()
    assert lines.count("# TYPE zk_occ gauge") == 1
    assert lines.count("# HELP zk_occ fill") == 1
    assert 'zk_occ{split="train"} 2' in lines
    assert 'zk_occ{split="validation"} 1' in lines
    assert 'zk_occ{split="test"} 0' in lines


def test_prometheus_sanitizes_names():
    r = MetricsRegistry()
    r.counter("serve/latency p99").inc()
    text = render_prometheus([r])
    assert "serve_latency_p99 1" in text


def test_flat_dict_view():
    r = MetricsRegistry()
    r.counter("c").inc(2)
    r.gauge("g").set(1.5)
    h = r.histogram("h", buckets=(1.0,))
    h.observe(4.0)
    flat = r.as_flat_dict()
    assert flat["c"] == 2
    assert flat["g"] == 1.5
    assert flat["h_count"] == 1
    assert flat["h_sum"] == 4.0
    assert flat["h_mean"] == 4.0


def test_render_multiple_registries():
    a, b = MetricsRegistry(), MetricsRegistry()
    a.counter("from_a").inc()
    b.counter("from_b").inc()
    text = render_prometheus([a, b])
    assert "from_a 1" in text and "from_b 1" in text


def test_histogram_isinstance_check():
    r = MetricsRegistry()
    h = r.histogram("h", buckets=(1.0,))
    assert isinstance(h, Histogram)
    assert h.kind == "histogram"


# -- label-cardinality guard (docs/DESIGN.md §16 satellite) ---------------


def test_label_variants_capped_with_dropped_counter(caplog):
    import logging

    r = MetricsRegistry(max_label_variants=3)
    live = [
        r.counter("zk_capped", labels={"tenant": f"t{i}"}) for i in range(3)
    ]
    with caplog.at_level(logging.WARNING):
        dropped = r.counter("zk_capped", labels={"tenant": "t99"})
    # The detached instrument is fully usable...
    dropped.inc(5)
    assert dropped.value == 5
    # ...but never collected: /metrics stays bounded at the cap.
    rendered = [
        inst for inst in r.collect() if inst.name == "zk_capped"
    ]
    assert len(rendered) == 3
    assert all(inst is not dropped for inst in rendered)
    # The drop is accounted and warned once.
    assert (
        r.counter(_dropped_labels()[0], labels=_dropped_labels()[1]).value
        == 1
    )
    assert sum(
        "label-cardinality cap" in rec.message for rec in caplog.records
    ) == 1


def _dropped_labels():
    return "zk_labels_dropped_total", {"metric": "zk_capped"}


def test_cap_warns_once_and_counts_every_drop():
    r = MetricsRegistry(max_label_variants=2)
    for i in range(2):
        r.gauge("zk_g", labels={"k": str(i)})
    for i in range(4):
        r.gauge("zk_g", labels={"k": f"over{i}"})
    assert (
        r.counter(
            "zk_labels_dropped_total", labels={"metric": "zk_g"}
        ).value
        == 4
    )


def test_existing_variants_survive_the_cap():
    """Re-requesting an ALREADY-registered variant returns the shared
    instrument even when the name is at the cap — only NEW variants
    drop."""
    r = MetricsRegistry(max_label_variants=2)
    a = r.counter("zk_c", labels={"k": "a"})
    b = r.counter("zk_c", labels={"k": "b"})
    assert r.counter("zk_c", labels={"k": "a"}) is a
    assert r.counter("zk_c", labels={"k": "b"}) is b
    assert (
        r.counter("zk_labels_dropped_total", labels={"metric": "zk_c"}).value
        == 0
    )


def test_dropped_series_renders_in_exposition():
    r = MetricsRegistry(max_label_variants=1)
    r.counter("zk_c", labels={"k": "a"})
    r.counter("zk_c", labels={"k": "b"})  # dropped
    text = render_prometheus([r])
    assert 'zk_labels_dropped_total{metric="zk_c"} 1' in text
    assert 'zk_c{k="a"}' in text
    assert 'zk_c{k="b"}' not in text


def test_dropped_counter_itself_is_exempt_from_the_cap():
    r = MetricsRegistry(max_label_variants=1)
    for name in ("zk_a", "zk_b", "zk_c"):
        r.counter(name, labels={"k": "x"})
        r.counter(name, labels={"k": "y"})  # each name's drop
    for name in ("zk_a", "zk_b", "zk_c"):
        assert (
            r.counter(
                "zk_labels_dropped_total", labels={"metric": name}
            ).value
            == 1
        )
