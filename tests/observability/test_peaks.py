"""Peak anchors: live-gauge peak resolution (env > datasheet-scaled >
recorded v5e) and its agreement-by-construction with bench.py's
offline anchors (docs/DESIGN.md §14)."""

import pytest

import bench
from zookeeper_tpu.observability import peaks


def test_bench_reexports_the_shared_tables():
    """bench.py and the live gauges must divide by the SAME anchors —
    identity, not equality, so a future edit cannot fork them."""
    assert bench.aggregate_peak_attempts is peaks.aggregate_peak_attempts
    assert (
        bench.check_peak_against_datasheet
        is peaks.check_peak_against_datasheet
    )
    assert bench.datasheet_bf16_peak is peaks.datasheet_bf16_peak
    assert (
        bench.TPU_DATASHEET_BF16_TFLOPS is peaks.TPU_DATASHEET_BF16_TFLOPS
    )
    assert bench.TPU_INT8_FACTOR is peaks.TPU_INT8_FACTOR
    assert bench.BF16_PEAK_FALLBACK == peaks.BF16_PEAK_FALLBACK
    assert bench.INT8_PEAK_FALLBACK == peaks.INT8_PEAK_FALLBACK


def test_reference_peak_env_override_wins():
    value, source = peaks.reference_peak_flops(
        "TPU v5 lite", env={"ZK_BENCH_PEAK_FLOPS": "123e12"}
    )
    assert value == 123e12
    assert source == "env"


def test_reference_peak_bad_env_override_is_ignored():
    # The override resolves inside hot-path gauge updates: a typo'd
    # export must fall through to the device anchor, never raise or
    # poison the gauge with nan/inf.
    for bad in ("garbage", "-1", "0", "nan", "inf", "-inf"):
        value, source = peaks.reference_peak_flops(
            "TPU v5 lite", env={"ZK_BENCH_PEAK_FLOPS": bad}
        )
        assert source == "v5e_measured", bad
        assert value == peaks.BF16_PEAK_FALLBACK, bad
        value, source = peaks.reference_int8_peak_flops(
            "TPU v5 lite", env={"ZK_BENCH_INT8_PEAK_FLOPS": bad}
        )
        assert source == "v5e_measured", bad
        assert value == peaks.INT8_PEAK_FALLBACK, bad


def test_reference_peak_v5e_uses_recorded_measurement():
    value, source = peaks.reference_peak_flops("TPU v5 lite", env={})
    assert value == peaks.BF16_PEAK_FALLBACK
    assert source == "v5e_measured"


def test_reference_peak_other_generations_scale_datasheet():
    value, source = peaks.reference_peak_flops("TPU v4", env={})
    assert value == pytest.approx(peaks.ACHIEVABLE_FRACTION * 275e12)
    assert source == "datasheet_scaled"


def test_reference_peak_unknown_generation_falls_back():
    value, source = peaks.reference_peak_flops("TPU v99", env={})
    assert value == peaks.BF16_PEAK_FALLBACK
    assert source == "fallback_v5e"


def test_reference_peak_total_without_jax_device(monkeypatch):
    """Resolution must stay total when device_kind is unknown AND jax
    is unavailable: a live gauge update can never raise."""
    value, source = peaks.reference_peak_flops(None, env={})
    assert value > 0 and isinstance(source, str)


def test_reference_int8_peak_factors_by_generation():
    # v4 has no int8 MXU doubling: the int8 anchor IS the bf16 one.
    v4, src4 = peaks.reference_int8_peak_flops("TPU v4", env={})
    assert v4 == pytest.approx(peaks.ACHIEVABLE_FRACTION * 1.0 * 275e12)
    assert src4 == "datasheet_scaled"
    # v5e: the recorded on-chip int8 measurement.
    v5e, src5 = peaks.reference_int8_peak_flops("TPU v5e", env={})
    assert v5e == peaks.INT8_PEAK_FALLBACK
    assert src5 == "v5e_measured"
    # env override wins here too.
    v, s = peaks.reference_int8_peak_flops(
        "TPU v4", env={"ZK_BENCH_INT8_PEAK_FLOPS": "9e12"}
    )
    assert (v, s) == (9e12, "env")


def test_live_anchor_agrees_with_bench_fallback_path():
    """The 10% live-vs-offline agreement contract's anchor half: on a
    v5e, the live reference equals bench's measured-peak fallback
    EXACTLY; on other generations both sides apply the same 0.93x
    datasheet prior, so the anchors are identical by construction."""
    for kind in ("TPU v5 lite", "TPU v4", "TPU v5p", "TPU v6e"):
        live, _ = peaks.reference_peak_flops(kind, env={})
        sheet = peaks.datasheet_bf16_peak(kind)
        offline = (
            peaks.BF16_PEAK_FALLBACK
            if peaks.datasheet_match(kind)[0] in peaks.V5E_KEYS
            else peaks.ACHIEVABLE_FRACTION * sheet
        )
        assert live == pytest.approx(offline)


# -- HBM bandwidth anchors (the decode MBU roofline, DESIGN.md §17) -------


def test_reference_hbm_bandwidth_env_override_wins():
    value, source = peaks.reference_hbm_bandwidth(
        "TPU v5e", env={"ZK_BENCH_HBM_BANDWIDTH": "1.0e12"}
    )
    assert (value, source) == (1.0e12, "env")


def test_reference_hbm_bandwidth_datasheet_by_generation():
    for kind, gbps in (
        ("TPU v5 lite", 819.0),
        ("TPU v4", 1228.0),
        ("TPU v5p", 2765.0),
        ("TPU v6e", 1640.0),
    ):
        value, source = peaks.reference_hbm_bandwidth(kind, env={})
        assert value == pytest.approx(gbps * 1e9)
        assert source == "datasheet"


def test_reference_hbm_bandwidth_unknown_falls_back_v5e():
    value, source = peaks.reference_hbm_bandwidth("FutureChip 9", env={})
    assert value == peaks.HBM_BANDWIDTH_FALLBACK
    assert source == "fallback_v5e"
    # Total without jax/device_kind too (gauge updates never raise).
    value, source = peaks.reference_hbm_bandwidth(None, env={})
    assert value > 0


def test_reference_hbm_bandwidth_malformed_env_ignored(caplog):
    import logging

    with caplog.at_level(logging.WARNING):
        value, source = peaks.reference_hbm_bandwidth(
            "TPU v5e", env={"ZK_BENCH_HBM_BANDWIDTH": "fast"}
        )
    assert source == "datasheet"  # the override was warn-and-ignored
    assert any("ZK_BENCH_HBM_BANDWIDTH" in r.message for r in caplog.records)


def test_mbu_totality_and_value():
    from zookeeper_tpu.observability.ledger import mbu

    assert mbu(819e9, 1.0, 819e9) == pytest.approx(1.0)
    assert mbu(40.95e9, 0.1, 819e9) == pytest.approx(0.5)
    # Unknown bytes / zero time / missing bandwidth -> None (the gauge
    # publishes -1), never a raise.
    assert mbu(None, 0.01, 819e9) is None
    assert mbu(1e9, 0.0, 819e9) is None
    assert mbu(1e9, 0.01, None) is None
    assert mbu(-5.0, 0.01, 819e9) is None
