"""FlightRecorder: bundle contents, rate limiting, bounded retention,
the manual /debugz trigger, and the snapshot-not-drain audit
(docs/DESIGN.md §16)."""

import json
import os
import threading
import urllib.error
import urllib.request

import pytest

from zookeeper_tpu.observability import trace
from zookeeper_tpu.observability import recorder as recorder_mod
from zookeeper_tpu.observability.export import ObservabilityServer
from zookeeper_tpu.observability.recorder import FlightRecorder
from zookeeper_tpu.observability.registry import MetricsRegistry
from zookeeper_tpu.observability.requests import RequestLog


@pytest.fixture
def fresh_tracer():
    prior = trace.get_tracer()
    trace.install(trace.Tracer(1024))
    yield trace.get_tracer()
    trace.install(prior)


@pytest.fixture
def no_global_recorder():
    prior = recorder_mod.get_recorder()
    recorder_mod.uninstall()
    yield
    recorder_mod.install(prior) if prior is not None else recorder_mod.uninstall()


def make_recorder(tmp_path, **kw):
    reg = MetricsRegistry()
    reg.counter("zk_test_total", help="t").inc(3)
    log = RequestLog("svc")
    kw.setdefault("synchronous", True)
    kw.setdefault("min_interval_s", 0.0)
    rec = FlightRecorder(
        str(tmp_path / "bundles"),
        registries=[reg],
        status_providers={"svc": lambda: {"alive": True}},
        request_logs={"svc": log},
        **kw,
    )
    return rec, reg, log


def test_bundle_contents_join_every_layer(tmp_path, fresh_tracer):
    """The acceptance shape: one bundle carries trace JSON, exposition
    text, the ledger table, statusz sections, the RequestLog tail and
    a manifest naming the trigger."""
    rec, reg, log = make_recorder(tmp_path)
    with trace.span("request_submit", rid=11):
        pass
    trace.event("request_complete", rid=11)
    log.append(11, "crashed", rows=2, detail="WorkerCrashedError")
    path = rec.trigger("worker_crash", step=5, attrs={"error": "boom"})
    assert path is not None and os.path.isdir(path)
    names = sorted(os.listdir(path))
    assert names == [
        "manifest.json", "metrics.prom", "programs.json",
        "requestlog.json", "statusz.json", "trace.json",
    ]
    doc = json.load(open(os.path.join(path, "trace.json")))
    flow_ids = {
        e["id"] for e in doc["traceEvents"] if e.get("cat") == "rid"
    }
    assert flow_ids == {11}
    prom = open(os.path.join(path, "metrics.prom")).read()
    assert "zk_test_total 3" in prom
    statusz = json.load(open(os.path.join(path, "statusz.json")))
    assert statusz["svc"] == {"alive": True}
    assert statusz["metrics"]["zk_test_total"] == 3.0
    requestlog = json.load(open(os.path.join(path, "requestlog.json")))
    assert requestlog["svc"]["tail"][0]["rid"] == 11
    assert requestlog["svc"]["tail"][0]["outcome"] == "crashed"
    manifest = json.load(open(os.path.join(path, "manifest.json")))
    assert manifest["trigger"] == {
        "kind": "worker_crash", "step": 5, "attrs": {"error": "boom"},
    }
    assert isinstance(manifest["time_unix"], float)
    # Provenance via bench_metadata (git sha present on this checkout).
    assert "git_sha" in manifest["metadata"]
    programs = json.load(open(os.path.join(path, "programs.json")))
    assert "programs" in programs


def test_rate_limit_suppresses_and_force_bypasses(tmp_path):
    rec, _, _ = make_recorder(tmp_path, min_interval_s=3600.0)
    first = rec.trigger("step_time_anomaly")
    assert first is not None
    assert rec.trigger("step_time_anomaly") is None  # inside the window
    assert rec.bundles_suppressed == 1
    forced = rec.trigger("manual", force=True)  # /debugz semantics
    assert forced is not None and forced != first
    assert rec.bundles_written == 2


def test_retention_keeps_last_k(tmp_path):
    rec, _, _ = make_recorder(tmp_path, keep=2)
    paths = [rec.trigger(f"kind{i}") for i in range(5)]
    remaining = rec.bundles()
    assert len(remaining) == 2
    assert remaining == paths[-2:]


def test_injected_clock_is_the_manifest_timestamp(tmp_path):
    rec, _, _ = make_recorder(tmp_path, clock=lambda: 1234.5)
    path = rec.trigger("manual")
    manifest = json.load(open(os.path.join(path, "manifest.json")))
    assert manifest["time_unix"] == 1234.5


def test_async_mode_writes_on_worker_thread(tmp_path):
    rec, _, _ = make_recorder(tmp_path, synchronous=False)
    assert rec.trigger("worker_crash") is None  # queued, not written
    assert rec.flush(timeout=10)
    assert rec.bundles_written == 1
    assert rec.last_bundle is not None
    rec.close()


def test_trigger_never_raises(tmp_path, monkeypatch):
    """The call sites are crash handlers: a broken provider or an
    unwritable directory must produce a warning, not an exception."""
    rec = FlightRecorder(
        str(tmp_path / "bundles"),
        status_providers={"bad": lambda: (_ for _ in ()).throw(OSError())},
        synchronous=True,
        min_interval_s=0.0,
    )
    path = rec.trigger("manual")  # provider error -> section error
    statusz = json.load(open(os.path.join(path, "statusz.json")))
    assert "error" in statusz["bad"]
    # Unwritable directory: trigger returns None instead of raising.
    rec2 = FlightRecorder(
        "/proc/definitely/not/writable",
        synchronous=True,
        min_interval_s=0.0,
    )
    assert rec2.trigger("manual") is None


def test_notify_is_noop_without_recorder(no_global_recorder):
    recorder_mod.notify("worker_crash")  # must not raise


def test_notify_routes_to_installed_recorder(tmp_path, no_global_recorder):
    rec, _, _ = make_recorder(tmp_path)
    recorder_mod.install(rec)
    recorder_mod.notify("fault_injected", step=3, attrs={"kind": "x"})
    assert rec.bundles_written == 1
    manifest = json.load(
        open(os.path.join(rec.last_bundle, "manifest.json"))
    )
    assert manifest["trigger"]["kind"] == "fault_injected"
    recorder_mod.uninstall(rec)


def test_uninstall_only_evicts_own_recorder(tmp_path, no_global_recorder):
    rec_a, _, _ = make_recorder(tmp_path / "a")
    rec_b, _, _ = make_recorder(tmp_path / "b")
    recorder_mod.install(rec_a)
    recorder_mod.install(rec_b)  # replacement
    recorder_mod.uninstall(rec_a)  # stale teardown: must be a no-op
    assert recorder_mod.get_recorder() is rec_b
    recorder_mod.uninstall(rec_b)
    assert recorder_mod.get_recorder() is None


def test_debugz_post_writes_bundle_inline(
    tmp_path, fresh_tracer, no_global_recorder
):
    rec, reg, _ = make_recorder(tmp_path, min_interval_s=3600.0)
    recorder_mod.install(rec)
    server = ObservabilityServer([reg], port=0).start()
    try:
        # Rate limiter already consumed by a prior trigger: the manual
        # POST must still land (force semantics).
        rec.trigger("step_time_anomaly")
        req = urllib.request.Request(
            f"{server.url}/debugz", data=b"", method="POST"
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            body = json.loads(resp.read().decode())
        assert body["bundle"] is not None
        assert os.path.isdir(body["bundle"])
        manifest = json.load(
            open(os.path.join(body["bundle"], "manifest.json"))
        )
        assert manifest["trigger"]["kind"] == "manual"
        # /statusz reports the armed recorder.
        with urllib.request.urlopen(
            f"{server.url}/statusz", timeout=10
        ) as resp:
            statusz = json.loads(resp.read().decode())
        assert statusz["flight_recorder"]["installed"] is True
        assert statusz["flight_recorder"]["bundles_written"] == 2
    finally:
        server.stop()
        recorder_mod.uninstall(rec)


def test_debugz_post_without_recorder_is_503(no_global_recorder):
    reg = MetricsRegistry()
    server = ObservabilityServer([reg], port=0).start()
    try:
        req = urllib.request.Request(
            f"{server.url}/debugz", data=b"", method="POST"
        )
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(req, timeout=10)
        assert err.value.code == 503
    finally:
        server.stop()


def test_concurrent_trace_scrapes_and_bundle_see_same_ring(
    tmp_path, fresh_tracer, no_global_recorder
):
    """The destructive-read audit pin: every LIVE read path goes
    through Tracer.snapshot(), so two concurrent /trace scrapes plus a
    recorder bundle all see the SAME ring contents — none of them
    drains records out from under the others."""
    rec, reg, _ = make_recorder(tmp_path)
    recorder_mod.install(rec)
    server = ObservabilityServer([reg], port=0).start()
    try:
        for i in range(25):
            trace.event("marker", attrs={"i": i})
        results = {}
        errors = []

        def scrape(name):
            try:
                with urllib.request.urlopen(
                    f"{server.url}/trace", timeout=10
                ) as resp:
                    results[name] = json.loads(resp.read().decode())
            except Exception as e:  # pragma: no cover - failure detail
                errors.append(e)

        threads = [
            threading.Thread(target=scrape, args=(f"scrape{i}",))
            for i in range(2)
        ]
        for t in threads:
            t.start()
        bundle = rec.trigger("manual")
        for t in threads:
            t.join()
        assert not errors
        bundle_doc = json.load(
            open(os.path.join(bundle, "trace.json"))
        )

        def markers(doc):
            return [
                e["args"]["i"]
                for e in doc["traceEvents"]
                if e.get("name") == "marker"
            ]

        expected = list(range(25))
        assert markers(bundle_doc) == expected
        assert markers(results["scrape0"]) == expected
        assert markers(results["scrape1"]) == expected
        # And the ring still holds every record afterwards: nothing
        # drained (drain() is reserved for the final teardown export).
        assert len(trace.get_tracer()) == 25
    finally:
        server.stop()
        recorder_mod.uninstall(rec)


def test_seq_resumes_from_disk_across_recorder_restarts(tmp_path):
    """A restarted process over the same directory (the crash-loop
    case) extends the bundle series — it must never overwrite
    bundle-000001 or have retention GC its own fresh write."""
    rec, _, _ = make_recorder(tmp_path)
    first = rec.trigger("worker_crash")
    # Fresh recorder over the same directory (same construction path).
    rec2 = FlightRecorder(
        rec.directory, synchronous=True, min_interval_s=0.0
    )
    second = rec2.trigger("worker_crash")
    assert second != first
    assert os.path.isdir(first) and os.path.isdir(second)
    assert os.path.basename(second) > os.path.basename(first)  # seq grew


def test_forced_trigger_does_not_arm_the_rate_limiter(tmp_path):
    """A /debugz poke right before a crash must not suppress the
    crash's automatic bundle: force bypasses the limiter WITHOUT
    stamping it."""
    rec, _, _ = make_recorder(tmp_path, min_interval_s=3600.0)
    assert rec.trigger("manual", force=True) is not None
    assert rec.trigger("worker_crash") is not None  # NOT suppressed
    assert rec.bundles_suppressed == 0


def test_request_log_tail_zero_is_empty():
    from zookeeper_tpu.observability.requests import RequestLog

    log = RequestLog("svc")
    log.append(1, "ok")
    assert log.tail(0) == []
    assert log.as_status(tail=0)["tail"] == []
