"""Step-time anomaly watchdog: fires on an injected straggler, stays
silent on steady cadence, and follows the docs/DESIGN.md §14
false-positive policy (warmup, min_ratio floor, min_excess_s floor,
bounded-burst EWMA absorption)."""

import threading

import pytest

from zookeeper_tpu.observability import trace
from zookeeper_tpu.observability.registry import MetricsRegistry
from zookeeper_tpu.observability.watchdog import StepTimeWatchdog


@pytest.fixture(autouse=True)
def _clean_tracer():
    trace.disable()
    yield
    trace.disable()


def _dog(**kw):
    kw.setdefault("registry", MetricsRegistry())
    return StepTimeWatchdog("test_stream", **kw)


def test_silent_on_steady_cadence():
    """A realistic steady stream (small jitter around 100ms) must never
    fire — the acceptance contract's false-positive half."""
    reg = MetricsRegistry()
    dog = StepTimeWatchdog("steady", registry=reg)
    jitter = [1.0, -0.7, 0.3, -0.2, 0.9, -0.5, 0.1, -0.9]
    for i in range(200):
        flagged = dog.observe(0.100 + jitter[i % len(jitter)] * 1e-3, step=i)
        assert not flagged
    assert dog.anomalies == 0
    assert reg.counter(
        "zk_step_time_anomalies_total", labels={"stream": "steady"}
    ).value == 0
    assert dog.ewma_seconds == pytest.approx(0.100, rel=0.02)


def test_fires_on_injected_straggler_and_traces_it():
    """The acceptance contract's true-positive half: one injected 3x
    straggler in a steady stream is flagged, counted, and emits a
    step_time_anomaly trace event with attribution."""
    tracer = trace.enable()
    reg = MetricsRegistry()
    dog = StepTimeWatchdog("train_step", registry=reg)
    jitter = [0.4, -0.3, 0.2, -0.5, 0.1]
    for i in range(50):
        assert not dog.observe(0.100 + jitter[i % 5] * 1e-3, step=i)
    assert dog.observe(0.300, step=50)  # the straggler
    assert dog.anomalies == 1
    assert reg.counter(
        "zk_step_time_anomalies_total", labels={"stream": "train_step"}
    ).value == 1
    records = tracer.drain()
    events = [r for r in records if r.get("name") == "step_time_anomaly"]
    assert len(events) == 1
    attrs = events[0]["attrs"]
    assert attrs["stream"] == "train_step"
    assert attrs["observed_ms"] == pytest.approx(300.0)
    assert attrs["baseline_ms"] == pytest.approx(100.0, rel=0.05)
    assert events[0]["step"] == 50


def test_warmup_suppresses_early_observations():
    dog = _dog(warmup=5)
    # A wild first few samples (compile, first-touch) never fire.
    for v in (5.0, 0.1, 0.1, 0.1, 0.1):
        assert not dog.observe(v)


def test_min_ratio_floor_on_near_zero_spread():
    """A microsecond-perfect cadence collapses MAD to ~0; without the
    ratio floor ANY jitter would be 'threshold sigmas'. A +20% blip
    must stay silent, a 2x one may fire."""
    dog = _dog(threshold=6.0, min_ratio=1.5)
    for _ in range(64):
        dog.observe(0.010)
    assert not dog.observe(0.012)  # +20% — under the ratio floor
    assert dog.observe(0.020)  # 2x — a real straggler


def test_min_excess_floor_guards_fast_streams():
    """With min_excess_s=5ms (the training default), a 2x blip on a
    1ms-step CPU stream is sub-floor noise; on a 100ms stream the same
    ratio fires."""
    fast = _dog(min_excess_s=0.005)
    for _ in range(64):
        fast.observe(0.001)
    assert not fast.observe(0.003)  # 3x, but only +2ms — under floor
    slow = _dog(min_excess_s=0.005)
    for _ in range(64):
        slow.observe(0.100)
    assert slow.observe(0.300)


def test_persistent_regression_becomes_new_baseline():
    """Bounded-burst policy: a step-function regression fires while it
    is news, then the EWMA absorbs it and the alerts stop."""
    dog = _dog(alpha=0.2, min_excess_s=0.0)
    for _ in range(64):
        dog.observe(0.050)
    flags = [dog.observe(0.200) for _ in range(60)]
    assert flags[0] is True
    burst = sum(flags)
    assert 1 <= burst <= 30  # news for ~1/alpha observations, not forever
    assert not flags[-1]
    assert dog.ewma_seconds == pytest.approx(0.200, rel=0.05)


def test_ewma_gauge_mirrors_baseline():
    reg = MetricsRegistry()
    dog = StepTimeWatchdog("g", registry=reg)
    dog.observe(0.080)
    assert reg.gauge(
        "zk_step_time_ewma_ms", labels={"stream": "g"}
    ).value == pytest.approx(80.0)


def test_negative_durations_ignored():
    dog = _dog()
    assert not dog.observe(-1.0)
    assert dog.ewma_seconds is None


def test_constructor_validation():
    with pytest.raises(ValueError):
        _dog(alpha=0.0)
    with pytest.raises(ValueError):
        _dog(alpha=1.5)
    with pytest.raises(ValueError):
        _dog(window=2)
    with pytest.raises(ValueError):
        _dog(warmup=0)
    with pytest.raises(ValueError):
        _dog(min_ratio=0.5)


def test_thread_safe_under_concurrent_observers():
    """The serving dispatcher's worker thread and test assertions may
    race; N threads x M observes must count exactly and never raise."""
    dog = _dog(window=32)
    errors = []

    def feed():
        try:
            for _ in range(500):
                dog.observe(0.010)
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=feed) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    assert dog.anomalies == 0


def test_on_anomaly_seam_and_recorder_trigger(tmp_path):
    """The flight-recorder subscription seam (docs/DESIGN.md §16): a
    flagged straggler fires the on_anomaly callback AND triggers the
    installed recorder; a broken callback is logged, never raised."""
    from zookeeper_tpu.observability import recorder as recorder_mod
    from zookeeper_tpu.observability.recorder import FlightRecorder

    fired = []
    dog = _dog(on_anomaly=lambda stream, s, step: fired.append((stream, step)))
    rec = FlightRecorder(
        str(tmp_path / "bundles"), synchronous=True, min_interval_s=0.0
    )
    prior = recorder_mod.get_recorder()
    recorder_mod.install(rec)
    try:
        for i in range(50):
            dog.observe(0.100, step=i)
        assert dog.observe(0.400, step=50)
        assert fired == [("test_stream", 50)]
        assert rec.bundles_written == 1
        import json
        import os

        manifest = json.load(
            open(os.path.join(rec.last_bundle, "manifest.json"))
        )
        assert manifest["trigger"]["kind"] == "step_time_anomaly"
        assert manifest["trigger"]["step"] == 50
        assert manifest["trigger"]["attrs"]["stream"] == "test_stream"
    finally:
        (
            recorder_mod.install(prior)
            if prior is not None
            else recorder_mod.uninstall()
        )

    # A raising callback must not break observe().
    bad = _dog(on_anomaly=lambda *a: (_ for _ in ()).throw(RuntimeError()))
    for i in range(50):
        bad.observe(0.100, step=i)
    assert bad.observe(0.400, step=50)  # no raise
