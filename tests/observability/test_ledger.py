"""Program ledger: the shared cost_analysis wrapper's backend
tolerance, ledger recording/eviction, the LedgeredExecutable compile
seam, and the MFU gauge math pinned against hand-computed fixtures
(docs/DESIGN.md §14)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from zookeeper_tpu.observability.ledger import (
    LedgeredExecutable,
    ProgramLedger,
    cost_analysis_dict,
    cost_bytes,
    cost_flops,
    default_ledger,
    memory_analysis_dict,
    mfu,
)
from zookeeper_tpu.observability.registry import MetricsRegistry


# -- the shared cost_analysis wrapper ------------------------------------


class _Prog:
    """Stand-in for a jax Lowered/Compiled with a controllable
    cost_analysis payload."""

    def __init__(self, payload):
        self._payload = payload

    def cost_analysis(self):
        if isinstance(self._payload, Exception):
            raise self._payload
        return self._payload


@pytest.mark.parametrize(
    "payload",
    [
        None,  # CPU backend on some jax versions
        [],  # empty legacy list
        "not a dict",  # junk payload
        RuntimeError("unsupported backend"),  # cost_analysis raises
    ],
)
def test_cost_analysis_dict_tolerates_backend_quirks(payload):
    assert cost_analysis_dict(_Prog(payload)) == {}
    assert cost_flops(_Prog(payload)) is None
    assert cost_bytes(_Prog(payload)) is None


def test_cost_analysis_dict_unwraps_legacy_list_convention():
    prog = _Prog([{"flops": 12.0, "bytes accessed": 34.0}])
    assert cost_flops(prog) == 12.0
    assert cost_bytes(prog) == 34.0


def test_cost_scalars_reject_nan_negative_and_non_numeric():
    assert cost_flops(_Prog({"flops": float("nan")})) is None
    assert cost_flops(_Prog({"flops": -1.0})) is None
    assert cost_flops(_Prog({"flops": "garbage"})) is None
    assert cost_flops(_Prog({})) is None
    assert cost_flops(_Prog({"flops": 7})) == 7.0


def test_memory_analysis_dict_tolerates_missing_backend_support():
    class NoMem:
        def memory_analysis(self):
            raise NotImplementedError

    assert memory_analysis_dict(NoMem()) == {}

    class Mem:
        def memory_analysis(self):
            class A:
                argument_size_in_bytes = 128
                output_size_in_bytes = 64
                temp_size_in_bytes = 32

            return A()

    out = memory_analysis_dict(Mem())
    assert out["argument_size_in_bytes"] == 128.0
    assert out["temp_size_in_bytes"] == 32.0


def test_summary_and_ledger_share_one_wrapper():
    """The dedup contract: models.summary takes its FLOPs straight off
    the ledger record (record() runs the ONE shared cost_analysis pass
    per program) — no second divergent call site, no re-run."""
    import inspect

    from zookeeper_tpu.models import summary as summary_mod

    src = inspect.getsource(summary_mod)
    assert ").flops" in src  # record(...).flops — the shared pass
    assert ".cost_analysis()" not in src
    assert "cost_flops" not in src


# -- ProgramLedger -------------------------------------------------------


def test_ledger_records_and_renders_status():
    reg = MetricsRegistry()
    ledger = ProgramLedger(registry=reg)
    rec = ledger.record(
        "train_step",
        "TestPartitioner/mesh=1",
        lowered=_Prog({"flops": 1e9, "bytes accessed": 2e6}),
        lower_ms=1.5,
        compile_ms=20.0,
        attrs={"partitioner": "TestPartitioner"},
    )
    assert rec.flops == 1e9
    assert rec.bytes_accessed == 2e6
    assert rec.ordinal == 1
    assert ledger.latest("train_step") is rec
    assert ledger.latest("serve_forward") is None
    status = ledger.as_status()
    assert status["count"] == 1
    assert status["total_compile_ms"] == 20.0
    assert status["programs"][0]["kind"] == "train_step"
    assert reg.counter(
        "zk_compiles_total", labels={"kind": "train_step"}
    ).value == 1
    assert reg.counter(
        "zk_compile_ms_total", labels={"kind": "train_step"}
    ).value == 20.0


def test_ledger_survives_unavailable_cost_analysis():
    """The satellite contract: programs whose cost analysis is
    unavailable still get a row (identity + compile time), with None
    FLOPs rather than a crash."""
    ledger = ProgramLedger(registry=MetricsRegistry())
    rec = ledger.record(
        "serve_forward",
        "b4/float32",
        lowered=_Prog(RuntimeError("no cost analysis")),
        compiled=None,
        compile_ms=3.0,
    )
    assert rec.flops is None
    assert rec.bytes_accessed is None
    assert rec.memory == {}
    row = ledger.as_status()["programs"][0]
    assert "flops" not in row
    assert row["compile_ms"] == 3.0


def test_ledger_bounds_records_and_keeps_newest():
    ledger = ProgramLedger(max_records=4, registry=MetricsRegistry())
    for i in range(10):
        ledger.record("train_step", f"key{i}")
    entries = ledger.entries()
    assert len(entries) == 4
    assert [e.key for e in entries] == ["key6", "key7", "key8", "key9"]
    # Ordinals keep counting across eviction (process-lifetime order).
    assert entries[-1].ordinal == 10


def test_default_ledger_is_process_global():
    assert default_ledger() is default_ledger()


# -- MFU math (hand-computed fixture) ------------------------------------


def test_mfu_pinned_against_hand_computed_fixture():
    """18.4 TFLOP program at 0.25 s/step on a 184 TF/s peak is exactly
    40% MFU — the gauge math must reproduce the hand computation."""
    assert mfu(18.4e12, 0.25, 184e12) == pytest.approx(0.4)
    # bench.py's offline convention: mfu = flops / time / peak. A
    # half-speed step halves MFU.
    assert mfu(18.4e12, 0.5, 184e12) == pytest.approx(0.2)


@pytest.mark.parametrize(
    "flops,seconds,peak",
    [
        (None, 0.1, 184e12),  # cost analysis unavailable
        (1e12, 0.0, 184e12),  # zero time (no sync yet)
        (1e12, -0.1, 184e12),  # clock skew
        (1e12, 0.1, None),  # no peak anchor
        (0.0, 0.1, 184e12),  # empty program
        ("x", 0.1, 184e12),  # junk
        (float("nan"), 0.1, 184e12),
    ],
)
def test_mfu_returns_none_on_any_degenerate_input(flops, seconds, peak):
    assert mfu(flops, seconds, peak) is None


# -- LedgeredExecutable --------------------------------------------------


def _jitted_add():
    return jax.jit(lambda a, b: a + b)


def test_ledgered_executable_records_on_first_call_only():
    ledger = ProgramLedger(registry=MetricsRegistry())
    fn = LedgeredExecutable(
        _jitted_add(), kind="train_step", key="test/mesh=1", ledger=ledger
    )
    a = jnp.ones((4, 4))
    out = fn(a, a)
    np.testing.assert_allclose(np.asarray(out), 2.0)
    assert len(ledger.entries()) == 1
    rec = ledger.entries()[0]
    assert rec.kind == "train_step"
    assert rec.key.startswith("test/mesh=1/args")
    assert rec.compile_ms is not None and rec.compile_ms >= 0
    assert rec.dispatches == 1
    # Steady state: same signature dispatches the compiled program, no
    # new ledger rows.
    for _ in range(3):
        fn(a, a)
    assert len(ledger.entries()) == 1
    assert ledger.entries()[0].dispatches == 4
    assert fn.ledger_entry is rec


def test_ledgered_executable_matches_plain_jit_output():
    fn = LedgeredExecutable(
        jax.jit(lambda x: jnp.sin(x) * 2),
        kind="eval_step",
        key="k",
        ledger=ProgramLedger(registry=MetricsRegistry()),
    )
    x = jnp.linspace(0, 1, 17)
    np.testing.assert_array_equal(
        np.asarray(fn(x)), np.asarray(jax.jit(lambda x: jnp.sin(x) * 2)(x))
    )


def test_ledgered_executable_falls_back_on_shape_change():
    """A partial final batch (new shapes) must dispatch through the
    wrapped jit — same numbers as the uninstrumented seam — without
    growing the ledger."""
    ledger = ProgramLedger(registry=MetricsRegistry())
    fn = LedgeredExecutable(
        _jitted_add(), kind="eval_step", key="k", ledger=ledger
    )
    fn(jnp.ones((8,)), jnp.ones((8,)))
    out = fn(jnp.ones((3,)), jnp.ones((3,)))  # odd final batch
    assert out.shape == (3,)
    np.testing.assert_allclose(np.asarray(out), 2.0)
    assert len(ledger.entries()) == 1
    # And the original shape still dispatches the compiled program.
    assert fn(jnp.ones((8,)), jnp.ones((8,))).shape == (8,)


def test_ledgered_executable_real_error_still_raises():
    """An error that is NOT a shape change (same signature) must not be
    swallowed by the fallback path."""

    def bad(a, b):
        return jnp.reshape(a, (5,)) + b  # invalid for (4,) inputs

    fn = LedgeredExecutable(
        jax.jit(bad), kind="eval_step", key="k",
        ledger=ProgramLedger(registry=MetricsRegistry()),
    )
    with pytest.raises(TypeError):
        fn(jnp.ones((4,)), jnp.ones((4,)))


def test_ledgered_executable_delegates_lower_and_attrs():
    jitted = _jitted_add()
    fn = LedgeredExecutable(
        jitted, kind="train_step", key="k",
        ledger=ProgramLedger(registry=MetricsRegistry()),
    )
    lowered = fn.lower(jnp.ones((2,)), jnp.ones((2,)))
    assert hasattr(lowered, "compile")


def test_partitioner_seams_return_ledgered_executables():
    """The tentpole wiring: SingleDevicePartitioner's compile seams
    hand back ledger-instrumented callables whose records land in the
    process-global ledger with the partitioner identity key."""
    from zookeeper_tpu.parallel import SingleDevicePartitioner

    before = len(default_ledger().entries())
    part = SingleDevicePartitioner()
    part.setup()
    step = part.compile_step(
        lambda state, batch: (state, {"loss": jnp.mean(batch)}),
        {"w": jnp.ones(())},
        donate_state=False,
    )
    assert isinstance(step, LedgeredExecutable)
    state, metrics = step({"w": jnp.ones(())}, jnp.ones((4,)))
    rec = default_ledger().entries()[-1]
    assert len(default_ledger().entries()) == before + 1
    assert rec.kind == "train_step"
    assert "SingleDevicePartitioner" in rec.key
    # On the CPU backend cost analysis exists: the row carries FLOPs
    # the MFU gauge can divide.
    assert rec.flops is None or rec.flops >= 0
