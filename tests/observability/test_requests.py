"""Request-scoped tracing primitives: rid minting, the RequestLog
ring, and rid-tagged trace records rendering as Chrome flow events
(docs/DESIGN.md §16)."""

import threading

import pytest

from zookeeper_tpu.observability import trace
from zookeeper_tpu.observability.requests import OUTCOMES, RequestLog, next_rid


def test_rids_are_monotone_and_unique_across_threads():
    seen = []
    lock = threading.Lock()

    def mint(n):
        local = [next_rid() for _ in range(n)]
        with lock:
            seen.extend(local)

    threads = [
        threading.Thread(target=mint, args=(200,)) for _ in range(8)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(seen) == len(set(seen)) == 1600
    # Monotone within any single thread's minting order is implied by
    # process-global monotone: the full set is gap-free ascending.
    assert sorted(seen) == list(range(min(seen), min(seen) + 1600))


def test_request_log_bounds_and_counts():
    log = RequestLog("svc", capacity=4)
    for i in range(10):
        log.append(i, "ok", rows=1)
    assert len(log) == 4
    assert log.total == 10
    assert [r["rid"] for r in log.tail(2)] == [8, 9]
    assert log.find(9)["rid"] == 9
    assert log.find(0) is None  # evicted
    status = log.as_status(tail=3)
    assert status["service"] == "svc"
    assert status["recorded_total"] == 10
    assert status["by_outcome"] == {"ok": 10}
    assert [r["rid"] for r in status["tail"]] == [7, 8, 9]


def test_request_log_outcome_taxonomy_and_fields():
    log = RequestLog("svc")
    rec = log.append(
        7,
        "crashed",
        enqueue_ns=100,
        dispatch_ns=200,
        complete_ns=300,
        rows=3,
        bucket=8,
        weights_step=42,
        detail="WorkerCrashedError",
    )
    assert rec["outcome"] in OUTCOMES
    got = log.find(7)
    assert got["enqueue_ns"] == 100
    assert got["dispatch_ns"] == 200
    assert got["complete_ns"] == 300
    assert got["rows"] == 3
    assert got["bucket"] == 8
    assert got["weights_step"] == 42
    assert got["detail"] == "WorkerCrashedError"


def test_request_log_rejects_bad_capacity():
    with pytest.raises(ValueError):
        RequestLog("svc", capacity=0)


@pytest.fixture
def fresh_tracer():
    prior = trace.get_tracer()
    trace.install(trace.Tracer(1024))
    yield trace.get_tracer()
    trace.install(prior)


def test_rid_tagged_records_render_as_flow_chain(fresh_tracer):
    """The flow-event encoding contract: a rid's timeline-ordered
    records become one s -> t -> f chain with the rid as the flow id,
    each point INSIDE its record so Perfetto binds the arrow to the
    enclosing slice."""
    rid = next_rid()
    with trace.span("request_submit", rid=rid):
        pass
    trace.event("request_dispatch", rid=rid)
    trace.event("request_complete", rid=rid)
    # An untagged span must not join anyone's flow.
    with trace.span("dispatch"):
        pass
    doc = trace.to_chrome_trace()
    flows = sorted(
        (e for e in doc["traceEvents"] if e.get("cat") == "rid"),
        key=lambda e: e["ts"],
    )
    assert [f["ph"] for f in flows] == ["s", "t", "f"]
    assert all(f["id"] == rid for f in flows)
    # Binding: non-start points bind to the enclosing slice.
    assert "bp" not in flows[0] and flows[1]["bp"] == "e"
    # rid also lands in args of the underlying records.
    tagged = [
        e
        for e in doc["traceEvents"]
        if e.get("args", {}).get("rid") == rid
    ]
    assert {e["name"] for e in tagged} == {
        "request_submit", "request_dispatch", "request_complete",
    }


def test_single_record_rid_emits_no_flow(fresh_tracer):
    trace.event("request_enqueue", rid=next_rid())
    doc = trace.to_chrome_trace()
    assert not [e for e in doc["traceEvents"] if e.get("cat") == "rid"]


def test_flow_chains_are_per_rid(fresh_tracer):
    a, b = next_rid(), next_rid()
    for rid in (a, b):
        trace.event("request_enqueue", rid=rid)
        trace.event("request_complete", rid=rid)
    doc = trace.to_chrome_trace()
    for rid in (a, b):
        chain = [
            e
            for e in doc["traceEvents"]
            if e.get("cat") == "rid" and e["id"] == rid
        ]
        assert sorted(e["ph"] for e in chain) == ["f", "s"]
