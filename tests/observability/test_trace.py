"""Host-side span tracing: recording semantics, the disabled-path
zero-cost contract, ring bounding, thread attribution, and Chrome
trace-event export validity."""

import json
import threading

import pytest

from zookeeper_tpu.observability import trace


@pytest.fixture(autouse=True)
def _clean_tracer():
    """Every test starts and ends with tracing disabled (the module
    global is process-wide)."""
    trace.disable()
    yield
    trace.disable()


def test_disabled_span_is_shared_noop_no_allocation():
    # The zero-cost contract: the SAME object comes back from every
    # disabled span() call — one flag check, no per-call allocation.
    a = trace.span("x", step=1, slab=2)
    b = trace.span("y")
    assert a is b
    with a:
        pass  # entering/exiting the noop is safe and records nothing
    assert not trace.enabled()
    assert trace.get_tracer() is None


def test_disabled_event_records_nothing():
    trace.event("whatever", step=3, attrs={"k": 1})
    assert trace.get_tracer() is None


def test_span_records_interval_with_attribution():
    tracer = trace.enable(128)
    with trace.span("data_wait", step=7, slab=2, attrs={"rows": 32}):
        pass
    (rec,) = tracer.snapshot()
    assert rec["phase"] == "X"
    assert rec["name"] == "data_wait"
    assert rec["step"] == 7
    assert rec["slab"] == 2
    assert rec["attrs"] == {"rows": 32}
    assert rec["dur_ns"] >= 0
    assert rec["thread_name"] == threading.current_thread().name
    assert rec["thread_id"] == threading.get_ident()


def test_event_records_instant():
    tracer = trace.enable(128)
    trace.event("fault_injected", step=5, attrs={"kind": "kill_at_step"})
    (rec,) = tracer.snapshot()
    assert rec["phase"] == "i"
    assert rec["name"] == "fault_injected"
    assert rec["step"] == 5


def test_ring_is_bounded_and_evicts_oldest():
    tracer = trace.enable(capacity=8)
    for i in range(20):
        trace.event("e", step=i)
    records = tracer.snapshot()
    assert len(records) == 8
    assert [r["step"] for r in records] == list(range(12, 20))


def test_reenable_keeps_existing_ring_first_enable_wins():
    tracer = trace.enable(64)
    trace.event("kept")
    assert trace.enable(64) is tracer
    # A nested enabler with a different capacity must NOT drop the
    # live ring (the outer session's records and tracer reference
    # survive); capacity changes require an explicit disable().
    assert trace.enable(32) is tracer
    assert len(tracer) == 1
    trace.disable()
    fresh = trace.enable(32)
    assert fresh is not tracer and fresh.capacity == 32


def test_drain_clears_snapshotted_records():
    tracer = trace.enable(64)
    trace.event("a")
    trace.event("b")
    drained = tracer.drain()
    assert [r["name"] for r in drained] == ["a", "b"]
    assert len(tracer) == 0


def test_concurrent_recording_is_lossless_under_capacity():
    tracer = trace.enable(capacity=100_000)
    n_threads, per_thread = 8, 500

    def record(tid):
        for i in range(per_thread):
            with trace.span("work", step=i, attrs=None):
                pass
            trace.event("mark", step=i)

    threads = [
        threading.Thread(target=record, args=(t,), name=f"rec-{t}")
        for t in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(tracer) == n_threads * per_thread * 2


def test_chrome_export_is_valid_trace_event_json(tmp_path):
    trace.enable(256)
    with trace.span("dispatch", step=3, slab=1):
        with trace.span("inner"):
            pass
    trace.event("fault_injected", attrs={"kind": "fail_save_io"})

    def other():
        with trace.span("ckpt_write", step=3):
            pass

    t = threading.Thread(target=other, name="zk-async-ckpt")
    t.start()
    t.join()

    path = tmp_path / "trace.json"
    n = trace.export_chrome_trace(str(path))
    doc = json.loads(path.read_text())
    assert isinstance(doc["traceEvents"], list)
    assert n == len(doc["traceEvents"])
    by_phase = {}
    for e in doc["traceEvents"]:
        by_phase.setdefault(e["ph"], []).append(e)
        # The trace-event contract every viewer relies on.
        assert isinstance(e["name"], str)
        assert isinstance(e["pid"], int)
        assert isinstance(e["tid"], int)
        if e["ph"] == "X":
            assert e["ts"] >= 0 and e["dur"] >= 0
    # Complete spans, instants, and per-thread name metadata all present.
    assert {e["name"] for e in by_phase["X"]} == {
        "dispatch", "inner", "ckpt_write",
    }
    assert by_phase["i"][0]["args"]["kind"] == "fail_save_io"
    thread_names = {e["args"]["name"] for e in by_phase["M"]}
    assert "zk-async-ckpt" in thread_names
    # step/slab attribution lands in args.
    dispatch = next(e for e in by_phase["X"] if e["name"] == "dispatch")
    assert dispatch["args"] == {"step": 3, "slab": 1}


def test_span_is_exception_safe():
    tracer = trace.enable(64)
    with pytest.raises(ValueError):
        with trace.span("failing"):
            raise ValueError("boom")
    (rec,) = tracer.snapshot()
    assert rec["name"] == "failing"  # recorded despite the raise
