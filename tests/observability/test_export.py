"""The live HTTP endpoint: /metrics scrape validity, /statusz content,
/trace export — over a real socket, the way a scraper sees it."""

import json
import re
import urllib.request

import pytest

from zookeeper_tpu.observability import trace
from zookeeper_tpu.observability.export import ObservabilityServer
from zookeeper_tpu.observability.registry import MetricsRegistry

PROM_SAMPLE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? [^ ]+$"
)


@pytest.fixture
def server_and_registry():
    r = MetricsRegistry()
    r.counter("zk_test_requests", help="reqs").inc(2)
    r.gauge("zk_test_step", initial=-1)
    h = r.histogram("zk_test_lat_ms", buckets=(1.0, 10.0))
    h.observe(0.5)
    server = ObservabilityServer([r], port=0).start()
    yield server, r
    server.stop()


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return resp.status, resp.headers, resp.read().decode()


def test_metrics_endpoint_serves_all_series(server_and_registry):
    server, registry = server_and_registry
    assert server.port not in (None, 0)  # ephemeral port got bound
    status, headers, body = _get(f"{server.url}/metrics")
    assert status == 200
    assert headers["Content-Type"].startswith("text/plain")
    samples = [
        line
        for line in body.splitlines()
        if line and not line.startswith("#")
    ]
    assert all(PROM_SAMPLE.match(line) for line in samples), samples
    for inst in registry.collect():
        assert inst.name in body
    assert "zk_test_requests 2" in body
    assert 'zk_test_lat_ms_bucket{le="+Inf"} 1' in body


def test_metrics_scrape_sees_live_updates(server_and_registry):
    server, registry = server_and_registry
    registry.counter("zk_test_requests").inc(5)
    _, _, body = _get(f"{server.url}/metrics")
    assert "zk_test_requests 7" in body


def test_statusz_endpoint(server_and_registry):
    server, _ = server_and_registry
    server.add_status_provider("custom", lambda: {"answer": 42})
    status, headers, body = _get(f"{server.url}/statusz")
    assert status == 200
    doc = json.loads(body)
    assert doc["pid"] > 0
    assert doc["uptime_s"] >= 0
    assert "zk-obs-http" in doc["threads"]
    assert doc["metrics"]["zk_test_requests"] == 2
    assert doc["custom"] == {"answer": 42}


def test_statusz_survives_broken_provider(server_and_registry):
    server, _ = server_and_registry
    server.add_status_provider(
        "broken", lambda: (_ for _ in ()).throw(RuntimeError("nope"))
    )
    status, _, body = _get(f"{server.url}/statusz")
    assert status == 200
    assert "error" in json.loads(body)["broken"]


def test_trace_endpoint_serves_chrome_json(server_and_registry):
    server, _ = server_and_registry
    trace.disable()
    try:
        trace.enable(64)
        with trace.span("probe", step=1):
            pass
        _, _, body = _get(f"{server.url}/trace")
        doc = json.loads(body)
        assert any(
            e["ph"] == "X" and e["name"] == "probe"
            for e in doc["traceEvents"]
        )
    finally:
        trace.disable()


def test_unknown_path_404s(server_and_registry):
    server, _ = server_and_registry
    with pytest.raises(urllib.error.HTTPError) as err:
        _get(f"{server.url}/nope")
    assert err.value.code == 404


def test_healthz(server_and_registry):
    server, _ = server_and_registry
    status, _, body = _get(f"{server.url}/healthz")
    assert status == 200 and body == "ok\n"


def test_healthz_is_constant_and_lock_free(server_and_registry):
    """The liveness contract the fleet router's health probes rely on
    (docs/DESIGN.md §23): ``/healthz`` answers with the SAME constant
    body even while the metrics registry lock is held by a stalled
    writer — a probe must distinguish "process dead" from "registry
    busy", so it must never touch the lock that ``/metrics`` rendering
    takes."""
    server, registry = server_and_registry
    with registry._lock:  # a stalled registry writer
        status, _, body = _get(f"{server.url}/healthz")
        assert status == 200 and body == "ok\n"
    # Constant across scrapes; "/" is the same endpoint.
    assert _get(f"{server.url}/healthz")[2] == body
    assert _get(f"{server.url}/")[2] == body


def test_stop_is_idempotent():
    server = ObservabilityServer([MetricsRegistry()], port=0).start()
    url = server.url
    server.stop()
    server.stop()
    with pytest.raises(Exception):
        _get(f"{url}/metrics")
