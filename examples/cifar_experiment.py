"""CIFAR-scale binary-network experiment — the reference example's
canonical workload (SURVEY.md §2.3: ``examples/larq_experiment.py``
trains BinaryNet on CIFAR-10/MNIST; BASELINE config #1).

Synthetic CIFAR-shaped data by default (no network); the full larq-style
recipe is one CLI line::

    python examples/cifar_experiment.py TrainCifar epochs=100 \\
        optimizer=Bop track_flip_ratio=True ema_decay=0.999 \\
        loader.preprocessing.augment=True

Swap ``loader.dataset=TFDSDataset loader.dataset.name=cifar10`` where
TFDS data exists, or ``optimizer=Adam`` for the latent-weight recipe.
"""

from zookeeper_tpu import ComponentField, Field, PartialComponent, cli, task
from zookeeper_tpu.data import (
    DataLoader,
    ImageClassificationPreprocessing,
    SyntheticCifar10,
)
from zookeeper_tpu.models import BinaryNet, Model
from zookeeper_tpu.training import Adam, Optimizer, TrainingExperiment

CifarPreprocessing = PartialComponent(
    ImageClassificationPreprocessing,
    height=32, width=32, channels=3, augment=True, pad_pixels=4,
)


@task
class TrainCifar(TrainingExperiment):
    loader: DataLoader = ComponentField(
        DataLoader,
        dataset=SyntheticCifar10,
        preprocessing=CifarPreprocessing,
    )
    model: Model = ComponentField(
        BinaryNet, features=(128, 128, 256, 256), dense_units=(512,)
    )
    optimizer: Optimizer = ComponentField(Adam)
    epochs: int = Field(100)
    batch_size: int = Field(128)


if __name__ == "__main__":
    cli()
