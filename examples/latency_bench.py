"""Batch-1 (or any batch) inference-latency benchmark as a CLI task.

Measures a model's forward latency with the tunnel-safe on-device
scan-chain methodology (``zookeeper_tpu.training.benchmark``), optionally
loading an exported checkpoint — so deployment-mode comparisons (bf16 vs
int8 vs packed, BASELINE.md's tables) are one command each::

    # Fresh-init QuickNet, bf16, batch-1:
    python examples/latency_bench.py LatencyBench model=QuickNet \\
        model.compute_dtype=bfloat16

    # Packed deployment from a converted checkpoint:
    python examples/latency_bench.py LatencyBench model=QuickNet \\
        model.binary_compute=xnor model.packed_weights=True \\
        checkpoint=/tmp/packed_model

Prints one JSON line: {"model", "batch_size", "ms_per_inference",
"params_mib"}.
"""

import json
from typing import Optional

from zookeeper_tpu import ComponentField, Field, cli, task
from zookeeper_tpu.models import Model
from zookeeper_tpu.training import Experiment


@task
class LatencyBench(Experiment):
    """Measure forward latency of a model (optionally from a checkpoint)."""

    model: Model = ComponentField()
    #: Optional model-only checkpoint (save_model / ConvertPacked output);
    #: fresh-initialized params otherwise.
    checkpoint: Optional[str] = Field(None)
    batch_size: int = Field(1)
    height: int = Field(224)
    width: int = Field(224)
    channels: int = Field(3)
    num_classes: int = Field(1000)
    chain_length: int = Field(50)
    rounds: int = Field(4)

    def run(self) -> dict:
        import jax

        from zookeeper_tpu.training.benchmark import (
            measure_inference_latency,
        )

        input_shape = (self.height, self.width, self.channels)
        module = self.model.build(input_shape, self.num_classes)
        if self.checkpoint:
            from zookeeper_tpu.training.checkpoint import (
                load_exported_model,
            )

            params, model_state = load_exported_model(
                self.checkpoint, self.model, module, input_shape
            )
        else:
            params, model_state = self.model.initialize(module, input_shape)
        variables = {"params": params, **model_state}
        seconds = measure_inference_latency(
            module,
            variables,
            input_shape,
            batch_size=self.batch_size,
            dtype=self.model.dtype(),
            length=self.chain_length,
            rounds=self.rounds,
        )
        params_bytes = sum(
            p.size * p.dtype.itemsize for p in jax.tree.leaves(params)
        )
        result = {
            "model": type(self.model).__name__,
            "batch_size": self.batch_size,
            "ms_per_inference": round(seconds * 1e3, 4),
            "params_mib": round(params_bytes / 2**20, 2),
        }
        print(json.dumps(result))
        return result


if __name__ == "__main__":
    cli()
