"""Inference-latency benchmark as a CLI task — ON the serving engine.

Measures the steady-state per-dispatch latency of the REAL serving path
(``zookeeper_tpu.serving.InferenceEngine``: bucketed, pre-compiled,
padded forward — not a bespoke timing loop), optionally loading a
deployment artifact, so deployment-mode comparisons (bf16 vs int8 vs
packed, EMA vs raw weights, BASELINE.md's tables) are one command each::

    # Fresh-init QuickNet, bf16, batch-1:
    python examples/latency_bench.py LatencyBench model=QuickNet \\
        model.compute_dtype=bfloat16

    # Packed deployment from a converted checkpoint:
    python examples/latency_bench.py LatencyBench model=QuickNet \\
        model.binary_compute=xnor model.packed_weights=True \\
        checkpoint=/tmp/packed_model

Timing uses the repo's shared two-chain-length marginal protocol
(``training.benchmark.time_marginal``): chains of back-to-back engine
dispatches ended by one host readback, so the fixed dispatch + sync
overhead of the chain END cancels while the per-dispatch cost — engine
Python + padding + compiled forward — stays in. That is the number a
request actually pays once the MicroBatcher hands the engine a bucket.

Prints one JSON line: {"model", "batch_size", "ms_per_inference",
"params_mib", "compiles"}.
"""

import json
from typing import Optional

from zookeeper_tpu import ComponentField, Field, cli, task
from zookeeper_tpu.models import Model
from zookeeper_tpu.training import Experiment


@task
class LatencyBench(Experiment):
    """Measure serving-engine forward latency of a model (optionally
    from a checkpoint)."""

    model: Model = ComponentField()
    #: Optional deployment artifact: save_model / ConvertPacked output,
    #: or a full Checkpointer directory; fresh-initialized otherwise.
    checkpoint: Optional[str] = Field(None)
    #: EMA-vs-raw selection when the checkpoint carries both.
    weights: str = Field("auto")
    batch_size: int = Field(1)
    height: int = Field(224)
    width: int = Field(224)
    channels: int = Field(3)
    num_classes: int = Field(1000)
    #: Long-chain length for the marginal (the short chain is a third).
    chain_length: int = Field(48)
    rounds: int = Field(4)

    def run(self) -> dict:
        import jax
        import numpy as np

        from zookeeper_tpu.serving import InferenceEngine
        from zookeeper_tpu.training.benchmark import measure_serving_latency

        input_shape = (self.height, self.width, self.channels)
        module = self.model.build(input_shape, self.num_classes)
        if self.checkpoint:
            from zookeeper_tpu.training.checkpoint import load_inference_model

            abstract = jax.eval_shape(
                lambda: self.model.initialize(module, input_shape)
            )
            params, model_state = load_inference_model(
                self.checkpoint,
                weights=self.weights,
                params_like=abstract[0],
                model_state_like=abstract[1],
            )
        else:
            params, model_state = self.model.initialize(module, input_shape)

        engine = InferenceEngine()
        from zookeeper_tpu.core import configure

        configure(
            engine, {"batch_buckets": (self.batch_size,)}, name="engine"
        )
        engine.bind(
            module.apply,
            params,
            model_state,
            input_shape,
            dtype=self.model.dtype(),
        )
        engine.warmup()  # compile outside the timed window

        rng = np.random.default_rng(0)
        x = rng.normal(size=(self.batch_size, *input_shape)).astype(
            self.model.dtype()
        )

        n2 = max(2, self.chain_length)
        n1 = max(1, n2 // 3)
        mean_s, p50_s, p99_s = measure_serving_latency(
            engine, x, n1=n1, n2=n2, rounds=self.rounds,
            percentile_samples=max(4, self.rounds * 2),
        )
        # Pathological jitter can invert the marginal; clamp like
        # scan_chain_latency does rather than report a negative time.
        seconds = max(mean_s, 1e-9)
        params_bytes = sum(
            p.size * np.dtype(p.dtype).itemsize
            for p in jax.tree.leaves(params)
        )
        result = {
            "model": type(self.model).__name__,
            "batch_size": self.batch_size,
            "ms_per_inference": round(seconds * 1e3, 4),
            "p50_ms": round(p50_s * 1e3, 4),
            "p99_ms": round(p99_s * 1e3, 4),
            "params_mib": round(params_bytes / 2**20, 2),
            "compiles": engine.compile_count,
        }
        print(json.dumps(result))
        return result


if __name__ == "__main__":
    cli()
