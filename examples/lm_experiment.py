"""Config-system-native language-model training: the TransformerLM
family driven exactly like every other example — task CLI, components
by name, scoped field inheritance.

``seq_len`` is declared ONCE at the task level and inherited by BOTH
the dataset (window length) and the preprocessing (``input_shape``) —
the reference's signature config-reuse mechanism doing real work::

    # Zero-config smoke (synthetic periodic corpus, memorizable):
    python examples/lm_experiment.py TrainLM epochs=3

    # Long context on a real chip, everything from the CLI:
    python examples/lm_experiment.py TrainLM seq_len=8192 \\
        model.d_model=512 model.num_heads=8 batch_size=4 \\
        model.compute_dtype=bfloat16 loader.dataset.vocab_size=1024

    # Sequence parallelism (the dp x sp ring-flash recipe) — the
    # partitioner owns the ("data", "sp") mesh and injects the ring
    # attention; checkpoints/EMA/metrics/unroll/resume ride unchanged:
    python examples/lm_experiment.py TrainLM seq_len=8192 \\
        partitioner=SequenceParallelPartitioner partitioner.sp=4 \\
        model.d_model=512 model.num_heads=8 batch_size=4

    # Dense-attention oracle run, or any other field:
    python examples/lm_experiment.py TrainLM model.attention=dense
"""

from zookeeper_tpu import ComponentField, Field, PartialComponent, cli, task
from zookeeper_tpu.data import DataLoader, SyntheticTokens, TokenPreprocessing
from zookeeper_tpu.models import Model, TransformerLM
from zookeeper_tpu.parallel import DataParallelPartitioner, Partitioner
from zookeeper_tpu.training import TrainingExperiment


@task
class TrainLM(TrainingExperiment):
    loader: DataLoader = ComponentField(
        DataLoader,
        dataset=SyntheticTokens,
        preprocessing=PartialComponent(TokenPreprocessing),
    )
    model: Model = ComponentField(TransformerLM)
    partitioner: Partitioner = ComponentField(DataParallelPartitioner)
    #: Inherited by loader.dataset.seq_len AND loader.preprocessing.seq_len
    #: (scoped field inheritance) — and caps the model's positional table.
    seq_len: int = Field(64)
    batch_size: int = Field(32)
    epochs: int = Field(3)


if __name__ == "__main__":
    cli()
