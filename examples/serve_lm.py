"""Token-streaming LM serving: the continuous-batching decode engine
behind a CLI task.

The deployment pairing for ``lm_experiment.py``: train there, stream
tokens here — the interactive half of the north star (train -> ship
weights -> paged-KV continuous-batching decode) in two commands::

    # 1) train a small LM and export/checkpoint it:
    python examples/lm_experiment.py TrainLM epochs=3 \\
        checkpointer.directory=/tmp/lm_ckpt

    # 2) stream generations through the decode engine and report
    #    tokens/s + TTFT percentiles (one JSON line):
    python examples/serve_lm.py ServeLM checkpoint=/tmp/lm_ckpt \\
        seq_len=64 vocab_size=61

    # Fresh-init smoke (no training run needed — compile/latency only):
    python examples/serve_lm.py ServeLM requests=16

    # More slots / longer generations / a live /metrics + /statusz
    # endpoint:
    python examples/serve_lm.py ServeLM engine.slots=16 new_tokens=64 \\
        metrics_port=8080

    # Decode-attention flavor (docs/DESIGN.md §17): auto = the
    # length-aware Pallas paged decode kernel on TPU, the reference
    # einsum elsewhere; force either for an A/B:
    python examples/serve_lm.py ServeLM engine.decode_attention=pallas

    # Speculative decoding (docs/DESIGN.md §18): a distilled-student
    # draft proposes k tokens per slot, one teacher verify dispatch
    # scores the whole window — token-identical to plain greedy, up
    # to k+1 tokens per teacher dispatch:
    python examples/serve_lm.py ServeLM checkpoint=/tmp/lm_ckpt \\
        speculative.enabled=True speculative.k=4 \\
        speculative.draft_checkpoint=/tmp/lm_student_ckpt \\
        speculative.draft_model.num_layers=1

    # True paged KV (docs/DESIGN.md §20): shared page pool + per-slot
    # page tables — pooled capacity, warm-prefix reuse through the
    # radix prefix cache (CoW at the divergence point), optional int8
    # rows; the result line gains kv_layout / kv_pool_fill /
    # prefix_cache_hit_rate:
    python examples/serve_lm.py ServeLM engine.kv_layout=paged \\
        engine.kv_quant=int8   # int8 optional; fp stays token-exact

Every request rides the REAL serving path — bucketed prefill into a
KV slot, slot-refill continuous batching, per-token streaming — so the
reported numbers are the decode subsystem's, not a synthetic loop's
(docs/DESIGN.md §15).
"""

from zookeeper_tpu import cli, task
from zookeeper_tpu.serving import DisaggServingConfig, LMServingConfig


@task
class ServeLM(LMServingConfig):
    """Serve a causal LM through the continuous-batching decode engine
    (synthetic deterministic prompt stream; see LMServingConfig)."""


@task
class ServeLMDisagg(DisaggServingConfig):
    """Disaggregated prefill/decode serving (docs/DESIGN.md §22): the
    same request stream through a prefill role and a decode role on
    separate mesh slices, KV pages streamed between them. Also
    reachable as ``ServeLM --disagg``."""


if __name__ == "__main__":
    import sys

    if "--disagg" in sys.argv:
        # ``ServeLM --disagg`` serves the disaggregated topology: swap
        # the task in place so every other key=value applies unchanged
        # (engine.* stays the decode role; prefill_engine.* /
        # transfer.* / partitioner.*_devices are the disagg knobs).
        sys.argv.remove("--disagg")
        if "ServeLM" in sys.argv:
            sys.argv[sys.argv.index("ServeLM")] = "ServeLMDisagg"
        elif "ServeLMDisagg" not in sys.argv:
            sys.argv.insert(1, "ServeLMDisagg")
    cli()
