"""Fleet serving: a prefix-affinity router over real worker processes.

The multi-replica half of the serving story (docs/DESIGN.md §23): a
:class:`~zookeeper_tpu.serving.FleetRouter` fronts N OS processes, each
running the full paged-KV ``LMServingConfig`` decode engine behind
``POST /generate`` with live ``/metrics`` + ``/statusz`` + ``/healthz``.
The router mirrors every replica's radix prefix cache in a process-local
``PrefixIndex`` (the SAME chunk keying, via
``zookeeper_tpu.serving.decode.prefix_key``) and sends each request to
the replica whose cache already holds the longest prefix — so a
session's turn-2 history re-enters the warm §20 prefill path instead of
re-prefilling cold on whichever box round-robin picked.

This task drives a deterministic multi-turn stream (S sessions x T
turns, each turn extending the last) through a freshly spawned fleet
and reports routing + warm-path outcomes as one JSON line::

    # 2 replicas, 3 sessions x 2 turns (defaults):
    python examples/serve_fleet.py ServeFleet

    # Tiny smoke geometry (what the CLI test runs):
    python examples/serve_fleet.py ServeFleet replicas=1 sessions=1 \\
        num_layers=1 d_model=32 shared_tokens=24 new_tokens=4

    # The no-affinity baseline for an A/B (expect affinity_hits=0 and
    # cold turn-2 warm_shared_tokens):
    python examples/serve_fleet.py ServeFleet policy=round_robin

    # A live router /metrics + /statusz endpoint (zk_fleet_* series,
    # "fleet" statusz section) while the stream runs:
    python examples/serve_fleet.py ServeFleet metrics_port=8080

The result line's contract: ``affinity_hits > 0`` and every
``warm_shared_tokens`` entry positive under ``policy=affinity`` with
``turns >= 2`` — the router kept sessions on their warm replica; the
same stream is token-deterministic regardless of policy (routing is a
latency policy, never a correctness input — the §23 identity the fleet
test suite and ``ZK_BENCH_FLEET=1`` bench leg assert end to end).
"""

import json
import shutil
import tempfile
import time

from zookeeper_tpu import cli, task
from zookeeper_tpu.core import Field
from zookeeper_tpu.serving import FleetRouter, ReplicaHandle
from zookeeper_tpu.testing import spawn_fleet_workers, stop_fleet_workers
from zookeeper_tpu.training.experiment import Experiment


@task
class ServeFleet(Experiment):
    """Route a deterministic multi-turn session stream through a
    freshly spawned multi-process fleet (docs/DESIGN.md §23)."""

    # Fleet topology + routing policy.
    replicas: int = Field(2)
    policy: str = Field("affinity")  # or "round_robin"
    # Workload shape: sessions x turns, turn t+1 = turn t + tail.
    sessions: int = Field(3)
    turns: int = Field(2)
    shared_tokens: int = Field(48)  # turn-1 prompt length
    tail_tokens: int = Field(8)  # appended per later turn
    new_tokens: int = Field(8)  # generation budget per turn
    # Worker model geometry (every replica runs this config).
    num_layers: int = Field(2)
    d_model: int = Field(64)
    num_heads: int = Field(4)
    vocab_size: int = Field(61)
    page_size: int = Field(16)
    slots: int = Field(4)
    seed: int = Field(0)
    # Router observability: -1 = off, 0 = ephemeral, >0 = fixed port.
    metrics_port: int = Field(-1)
    verbose: bool = Field(True)

    def run(self):
        import numpy as np

        if self.turns < 1 or self.sessions < 1 or self.replicas < 1:
            raise ValueError(
                "ServeFleet needs replicas/sessions/turns >= 1 "
                f"(got {self.replicas}/{self.sessions}/{self.turns})."
            )
        max_prompt = (
            self.shared_tokens + (self.turns - 1) * self.tail_tokens
        )
        seq_len = max(64, 2 * (max_prompt + self.new_tokens))
        conf = {
            "model.num_layers": self.num_layers,
            "model.d_model": self.d_model,
            "model.num_heads": self.num_heads,
            "model.max_seq_len": seq_len,
            "model.attention": "dense",
            "seq_len": seq_len,
            "vocab_size": self.vocab_size,
            "seed": self.seed,
            "engine.kv_layout": "paged",
            "engine.page_size": self.page_size,
            "engine.slots": self.slots,
            "engine.seq_buckets": (16, max_prompt),
            "engine.prefill_buckets": (1,),
            "requests": 0,
            "verbose": False,
        }
        # The deterministic stream: seeded, so reruns (and the
        # round-robin A/B) see token-identical prompts.
        rng = np.random.default_rng(self.seed + 11)
        session_ids = [f"s{i}" for i in range(self.sessions)]
        prompts = {}
        for sid in session_ids:
            base = rng.integers(
                1, self.vocab_size, size=self.shared_tokens
            ).tolist()
            turn_prompts = [list(base)]
            for _ in range(self.turns - 1):
                base = base + rng.integers(
                    1, self.vocab_size, size=self.tail_tokens
                ).tolist()
                turn_prompts.append(list(base))
            prompts[sid] = turn_prompts

        workdir = tempfile.mkdtemp(prefix="zk_serve_fleet_")
        workers = spawn_fleet_workers(
            workdir, num_workers=self.replicas, config=conf
        )
        router = None
        obs = None
        try:
            router = FleetRouter(
                [ReplicaHandle.from_worker(w) for w in workers],
                page_size=self.page_size,
                policy=self.policy,
            )
            if self.metrics_port >= 0:
                obs = router.start_observability(port=self.metrics_port)
                if self.verbose:
                    print(f"router observability: {obs.url}/metrics")
            warm_shared = []
            ttft_by_turn = {t: [] for t in range(self.turns)}
            generated = 0
            t0 = time.perf_counter()
            # Turn-major: every session's turn t lands before any
            # turn t+1 — the arrival order a live fleet would see.
            for turn in range(self.turns):
                for sid in session_ids:
                    resp = router.submit(
                        prompts[sid][turn],
                        session=(
                            sid if self.policy == "affinity" else None
                        ),
                        max_new_tokens=self.new_tokens,
                    )
                    ttft_by_turn[turn].append(float(resp.ttft_ms))
                    generated += int(resp.tokens.shape[0])
                    if turn > 0:
                        warm_shared.append(int(resp.shared_tokens))
                    if self.verbose:
                        print(
                            f"  {resp.rid} session={sid} turn={turn} "
                            f"-> {resp.worker_id} "
                            f"shared={resp.shared_tokens} "
                            f"ttft={resp.ttft_ms:.2f}ms"
                        )
            dt = time.perf_counter() - t0
            snap = router.metrics.snapshot()
            status = router.status()
            result = {
                "policy": self.policy,
                "replicas": self.replicas,
                "sessions": self.sessions,
                "turns": self.turns,
                "requests": self.sessions * self.turns,
                "generated_tokens": generated,
                "tokens_per_sec": round(generated / dt, 1),
                "routed_total": status["routed_total"],
                "affinity_hits": status["affinity_hits_total"],
                "rerouted": status["rerouted_total"],
                "healthy_replicas": status["healthy_replicas"],
                "warm_shared_tokens": warm_shared,
                "turn1_ttft_p50_ms": round(
                    float(np.percentile(ttft_by_turn[0], 50)), 3
                ),
                "route_ms_p50": snap.get("fleet_route_ms_p50"),
            }
            if self.turns > 1:
                warm = [
                    x
                    for t in range(1, self.turns)
                    for x in ttft_by_turn[t]
                ]
                result["warm_ttft_p50_ms"] = round(
                    float(np.percentile(warm, 50)), 3
                )
            print(json.dumps(result))
            return result
        finally:
            # router.close() stops the obs endpoint it started.
            if router is not None:
                router.close()
            stop_fleet_workers(workers)
            shutil.rmtree(workdir, ignore_errors=True)


if __name__ == "__main__":
    cli()
