"""End-to-end serving demo on REAL data: the digits classifier behind
the dynamic-batching engine.

The deployment pairing for ``digits_experiment.py``: train + export
there, serve here — the full north-star loop (train -> ship weights ->
compiled bucketed inference) in two commands::

    # 1) train and export the ship artifact (EMA when ema_decay is on):
    python examples/digits_experiment.py TrainDigits epochs=5 \\
        export_model_to=/tmp/digits_model

    # 2) serve the validation split through the MicroBatcher and report
    #    accuracy + serving metrics (one JSON line):
    python examples/serve_classifier.py ServeDigits \\
        checkpoint=/tmp/digits_model

    # raw-vs-EMA A/B from a full training checkpoint directory:
    python examples/serve_classifier.py ServeDigits \\
        checkpoint=/tmp/digits_ckpt weights=raw

Every real example image rides the actual serving path — variable-size
requests, bucket padding, per-request slice-back — so the reported
accuracy doubles as a correctness check of the batching machinery
(batched serving must score exactly what per-example eval scores).
"""

import time

from zookeeper_tpu import ComponentField, Field, PartialComponent, cli, task
from zookeeper_tpu.core import pretty_print
from zookeeper_tpu.data import (
    DataLoader,
    ImageClassificationPreprocessing,
    SklearnDigits,
)
from zookeeper_tpu.models import Model, SimpleCnn
from zookeeper_tpu.serving import ServingConfig

DigitsPreprocessing = PartialComponent(
    ImageClassificationPreprocessing, height=8, width=8, channels=1
)


@task
class ServeDigits(ServingConfig):
    """Serve the digits validation split through the inference engine."""

    loader: DataLoader = ComponentField(
        DataLoader,
        dataset=SklearnDigits,
        preprocessing=DigitsPreprocessing,
        drop_remainder=False,
    )
    model: Model = ComponentField(SimpleCnn)
    #: Feeds the loader by scoped inheritance; also the largest request
    #: size submitted (oversized vs the engine's buckets is fine — the
    #: batcher splits).
    batch_size: int = Field(64)
    height: int = Field(8)
    width: int = Field(8)
    channels: int = Field(1)
    num_classes: int = Field(10)

    def run(self):
        import numpy as np

        if self.verbose:
            print(pretty_print(self), flush=True)
        engine, batcher = self.build_service()
        warm_compiles = engine.compile_count

        rng = np.random.default_rng(self.seed)
        handles = []
        t0 = time.perf_counter()
        n_requests = 0
        for batch in self.loader.batches(
            "validation", training=False, sharding=None
        ):
            x = np.asarray(batch["input"])
            y = np.asarray(batch["target"])
            # Carve the batch into variable-size requests (1..batch
            # rows) — the realistic traffic shape the batcher coalesces.
            lo = 0
            while lo < x.shape[0]:
                take = int(rng.integers(1, max(2, x.shape[0] - lo + 1)))
                hi = min(lo + take, x.shape[0])
                handles.append((y[lo:hi], batcher.submit(x[lo:hi])))
                n_requests += 1
                lo = hi
        batcher.flush()
        dt = time.perf_counter() - t0

        correct = total = 0
        for y, handle in handles:
            logits = np.asarray(handle.result())
            correct += int((logits.argmax(-1) == y).sum())
            total += int(y.shape[0])
        accuracy = correct / max(1, total)

        return self.finish_report(
            warm_compiles=warm_compiles,
            n_requests=n_requests,
            dt=dt,
            writer_extra={"accuracy": accuracy},
            result_extra={
                "accuracy": round(accuracy, 4),
                "examples": total,
            },
        )


if __name__ == "__main__":
    cli()
