"""Packed-deployment converter (the larq-compute-engine converter
capability, SURVEY.md §2.4, as a CLI task).

Converts a trained float checkpoint (``TrainingExperiment
export_model_to=...``) into the bit-packed deployment form: binary conv
AND dense kernels stored as int32 lanes (32x smaller) + per-channel
scales, loadable into the same model built with
``packed_weights=True``::

    # 1. Train and export the float model:
    python examples/mnist_experiment.py TrainMnist model=BinaryNet \\
        export_model_to=/tmp/float_model

    # 2. Convert (optionally per-section mixed for the QuickNet family):
    python examples/convert_packed.py ConvertPacked model=BinaryNet \\
        checkpoint=/tmp/float_model output=/tmp/packed_model

The task prints before/after summaries (param counts, deployment MiB)
and verifies the packed model's forward agrees with the float one on a
probe batch before writing anything.
"""

from typing import Optional

import numpy as np

from zookeeper_tpu import ComponentField, Field, cli, task
from zookeeper_tpu.core import component
from zookeeper_tpu.models import Model, model_summary
from zookeeper_tpu.training import Experiment, load_model, save_model


def resolve_deploy_conf(model, fold_bn, deploy_overrides, pallas_interpret):
    """Resolve the deployment twin's config from the trained model's
    explicit config + the task knobs (pure function, unit-tested).

    Precedence: user's explicitly-set model fields < task knobs
    (pallas_interpret, fold_bn) < ``deploy_overrides`` (twin-only, wins
    over everything). THEN the packing defaults apply to whatever
    survived — to the CONV-LEVEL pair only: ``packed_weights`` defaults
    True unless something set it, and when it ends up truthy,
    ``binary_compute`` flips to "xnor" unless an override pinned the
    mode or it is a per-section tuple (a trained-path 'int8'/'mxu'
    cloned from the user's config cannot run packed and would raise at
    init). Stage-specific knobs like BinaryAlexNet's
    ``dense_binary_compute`` are never second-guessed — pin them in
    ``deploy_overrides`` (the field docstring shows the recipe).

    Returns ``(conf, fold_bn_resolved)``.
    """
    from zookeeper_tpu.core import configured_field_names

    user_set = configured_field_names(model)
    conf = {name: getattr(model, name) for name in user_set}
    conf["pallas_interpret"] = pallas_interpret
    conf["fold_bn"] = fold_bn
    conf.update(dict(deploy_overrides))  # Twin-only knobs win.
    fold_resolved = bool(conf.get("fold_bn", False))
    if fold_resolved and not hasattr(type(model), "fold_bn"):
        raise ValueError(
            f"{type(model).__name__} has no fold_bn deployment mode."
        )
    if not fold_resolved:
        del conf["fold_bn"]  # Some families lack the field entirely.
    if "packed_weights" not in conf:
        conf["packed_weights"] = True
    pw = conf["packed_weights"]
    twin_packed = any(pw) if isinstance(pw, (tuple, list)) else bool(pw)
    bc = conf.get("binary_compute")
    if (
        twin_packed
        and "binary_compute" not in deploy_overrides
        and not isinstance(bc, (tuple, list))
        and bc not in ("xnor", "xnor_popcount")
    ):
        conf["binary_compute"] = "xnor"
    return conf, fold_resolved


@task
class ConvertPacked(Experiment):
    """Float checkpoint -> packed deployment checkpoint."""

    model: Model = ComponentField()
    #: Model-only checkpoint of the trained float model (save_model form).
    checkpoint: str = Field()
    #: Where the packed checkpoint is written.
    output: str = Field()
    #: Input shape the model was trained at.
    height: int = Field(28)
    width: int = Field(28)
    channels: int = Field(1)
    num_classes: int = Field(10)
    #: Kernel quantizer the model trained with (per zoo family).
    kernel_quantizer: str = Field("ste_sign")
    #: Max |forward difference| tolerated in verification (binary conv
    #: sums are integers — 0.0 is achievable and the default for pure
    #: sign models; allow small slack for scaled kernels).
    verify_atol: float = Field(0.0)
    #: Fold each packed layer's eval-mode BatchNorm into the conv
    #: epilogue at conversion (LCE-style; erases 4 fp32 vectors per conv
    #: from the deployed tree). The affine re-association is equal to
    #: float rounding, not bitwise — set verify_atol accordingly
    #: (~1e-4 covers typical stacks).
    fold_bn: bool = Field(False)
    #: Extra config overrides applied ONLY to the deployment twin (a
    #: dict literal on the CLI). For partial deployments where the
    #: trained model's own config must stay float while the twin packs a
    #: subset — e.g. the measured BinaryAlexNet sweet spot:
    #: "deploy_overrides={'packed_weights': False, 'binary_compute':
    #: 'mxu', 'dense_packed_weights': True, 'dense_binary_compute':
    #: 'xnor'}" — or a per-section QuickNet tuple.
    deploy_overrides: dict = Field({})
    #: Run Pallas kernels interpreted (CPU verification).
    pallas_interpret: bool = Field(True)

    def run(self) -> Optional[str]:
        import jax
        import jax.numpy as jnp

        from zookeeper_tpu.ops.packed import pack_quantconv_params

        input_shape = (self.height, self.width, self.channels)

        module_f = self.model.build(input_shape, self.num_classes)
        params_init, model_state = self.model.initialize(module_f, input_shape)
        params_f, model_state = load_model(
            self.checkpoint, params_init, model_state
        )

        # Deployment twin: same architecture, packed weights. Uses the
        # model component's own packed knobs when it has them.
        for field_name in (
            "packed_weights", "binary_compute", "pallas_interpret"
        ):
            if not hasattr(type(self.model), field_name):
                raise ValueError(
                    f"{type(self.model).__name__} has no {field_name} "
                    "field — not a packable model family."
                )
        deploy_model = type(self.model)()
        from zookeeper_tpu.core import configure as _configure

        conf, fold_bn = resolve_deploy_conf(
            self.model, self.fold_bn, self.deploy_overrides,
            self.pallas_interpret,
        )
        _configure(deploy_model, conf, name="deploy_model")
        module_p = deploy_model.build(input_shape, self.num_classes)
        abstract = jax.eval_shape(
            lambda: module_p.init(
                jax.random.key(0),
                jnp.zeros((1, *input_shape)),
                training=False,
            )
        )
        if fold_bn:
            # Creation-order tree: checkpoint loads (and anything that
            # round-trips a dict through JAX pytrees, like eval_shape)
            # sort params alphabetically, which breaks the
            # conv->following-BN adjacency the fold pairing reads. The
            # pre-load initialize result still has module creation order.
            order = params_init
            packed_params, folded_stats = pack_quantconv_params(
                params_f,
                kernel_quantizer=self.kernel_quantizer,
                template=abstract["params"],
                fold_bn=True,
                batch_stats=model_state["batch_stats"],
                fold_order=order,
            )
            deploy_state = dict(model_state)
            deploy_state["batch_stats"] = folded_stats
        else:
            packed_params = pack_quantconv_params(
                params_f,
                kernel_quantizer=self.kernel_quantizer,
                template=abstract["params"],
            )
            deploy_state = model_state

        # Verify on a probe batch BEFORE writing.
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(4, *input_shape)), jnp.float32)
        y_f = module_f.apply(
            {"params": params_f, **model_state}, x, training=False
        )
        y_p = module_p.apply(
            {"params": packed_params, **deploy_state}, x, training=False
        )
        max_diff = float(jnp.max(jnp.abs(y_f - y_p)))
        if max_diff > self.verify_atol:
            raise RuntimeError(
                f"Packed model diverges from float model: max |diff| "
                f"{max_diff} > verify_atol={self.verify_atol}. Wrong "
                "kernel_quantizer for this family?"
            )

        save_model(self.output, packed_params, deploy_state)

        s_f = model_summary(module_f, input_shape)
        s_p = model_summary(module_p, input_shape)
        # Symmetric accounting over the BINARY kernels (conv + dense):
        # numerator = their float train bytes; denominator = the same
        # logical kernels in the deployment model — packed rows (binary-
        # flagged), still-unpacked binary kernels (mixed deployments,
        # the never-packed stem), and the per-channel scales.
        binary_f = sum(r.train_bytes for r in s_f.rows if r.binary)
        binary_p = sum(
            r.train_bytes
            for r in s_p.rows
            if r.binary or "kernel_scale" in r.path
        )
        print(
            f"converted {self.checkpoint} -> {self.output}\n"
            f"  whole model: {s_f.train_bytes / 2**20:.2f} MiB -> "
            f"{s_p.train_bytes / 2**20:.2f} MiB\n"
            f"  binary kernels (conv + dense): {binary_f / 2**10:.1f} KiB -> "
            f"{binary_p / 2**10:.1f} KiB "
            f"({binary_f / max(binary_p, 1):.1f}x)\n"
            f"  verified max |forward diff| = {max_diff}"
        )
        return self.output


if __name__ == "__main__":
    cli()
