"""End-to-end MNIST-scale experiment (BASELINE config #1).

The TPU-native counterpart of the reference's ``examples/larq_experiment.py``
(SURVEY.md §2.3 [unverified]): dataset + preprocessing + model + experiment
wired purely through components, runnable from the CLI::

    python examples/mnist_experiment.py TrainMnist epochs=2 batch_size=64
    python examples/mnist_experiment.py TrainMnist model=Mlp "model.hidden_units=(256,)"
    python examples/mnist_experiment.py TrainMnist optimizer=Sgd optimizer.schedule.base_lr=0.01

Uses the synthetic MNIST-shaped dataset so it runs without network/TFDS;
swap ``dataset=TFDSDataset dataset.name=mnist`` on a machine with TFDS.
"""

from zookeeper_tpu import ComponentField, Field, PartialComponent, cli, task
from zookeeper_tpu.data import (
    DataLoader,
    ImageClassificationPreprocessing,
    SyntheticMnist,
)
from zookeeper_tpu.models import BinaryNet, Model, SimpleCnn
from zookeeper_tpu.training import (
    DistillationExperiment,
    EvalExperiment,
    TrainingExperiment,
)

MnistPreprocessing = PartialComponent(
    ImageClassificationPreprocessing, height=28, width=28, channels=1
)


@task
class TrainMnist(TrainingExperiment):
    loader: DataLoader = ComponentField(
        DataLoader,
        dataset=SyntheticMnist,
        preprocessing=MnistPreprocessing,
    )
    model: Model = ComponentField(SimpleCnn)
    epochs: int = Field(2)
    batch_size: int = Field(64)


@task
class DistillMnist(DistillationExperiment):
    """Stage-2 of the KD recipe: distill a binary student from an
    exported teacher (train the teacher first with
    ``TrainMnist export_model_to=/tmp/teacher``)::

        python examples/mnist_experiment.py DistillMnist \\
            teacher_checkpoint=/tmp/teacher alpha=0.4
    """

    loader: DataLoader = ComponentField(
        DataLoader,
        dataset=SyntheticMnist,
        preprocessing=MnistPreprocessing,
    )
    model: Model = ComponentField(BinaryNet)
    teacher: Model = ComponentField(SimpleCnn)
    epochs: int = Field(2)
    batch_size: int = Field(64)


@task
class EvaluateMnist(EvalExperiment):
    """Score an exported checkpoint (``TrainMnist export_model_to=...``)::

        python examples/mnist_experiment.py EvaluateMnist \\
            checkpoint=/tmp/model model=SimpleCnn
    """

    loader: DataLoader = ComponentField(
        DataLoader,
        dataset=SyntheticMnist,
        preprocessing=MnistPreprocessing,
    )
    model: Model = ComponentField(SimpleCnn)


if __name__ == "__main__":
    cli()
