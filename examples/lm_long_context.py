"""Long-context causal-LM training demo: the TransformerLM family on
synthetic token streams, data-parallel over every visible device, flash
attention inside each chip.

Runs anywhere (CPU mesh for a smoke, real TPU for speed)::

    # 8-virtual-device CPU smoke:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/lm_long_context.py --steps 20 --seq 256

    # Real chip, long context:
    python examples/lm_long_context.py --steps 50 --seq 8192 --d-model 512

Scope note: this example drives the model + partitioner + train step
directly (the token pipeline is synthetic in-process); wiring a real
text corpus through the Dataset/DataLoader components is a data-source
exercise, not a model one — see ``data/dataset.py``'s ArrayDataset for
the pattern.
"""

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from zookeeper_tpu.core import configure
from zookeeper_tpu.models import TransformerLM
from zookeeper_tpu.parallel import DataParallelPartitioner
from zookeeper_tpu.training import TrainState, make_train_step


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--seq", type=int, default=1024)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--vocab", type=int, default=1024)
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--d-model", type=int, default=256)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument(
        "--sp", type=int, default=0,
        help="sequence-parallel degree (0 = pure data parallelism); "
        "uses SequenceParallelPartitioner's dp x sp ring-flash recipe",
    )
    args = ap.parse_args()

    model = TransformerLM()
    configure(
        model,
        {
            "num_layers": args.layers,
            "d_model": args.d_model,
            "num_heads": args.heads,
            "max_seq_len": args.seq,
            "compute_dtype": (
                "bfloat16" if jax.default_backend() == "tpu" else "float32"
            ),
        },
        name="model",
    )
    if args.sp > 0:
        # dp x sp: the partitioner owns the mesh and injects the ring
        # attention callable — same seam the TrainLM CLI recipe uses.
        from zookeeper_tpu.parallel import SequenceParallelPartitioner

        part = SequenceParallelPartitioner()
        configure(part, {"sp": args.sp}, name="partitioner")
        part.setup()
        part.prepare_model(model)
    else:
        part = DataParallelPartitioner()
        configure(part, {}, name="partitioner")
        part.setup()
    module = model.build((args.seq,), num_classes=args.vocab)
    params, mstate = model.initialize(module, (args.seq,))
    n_params = sum(p.size for p in jax.tree.leaves(params))
    mesh_desc = (
        f"mesh={dict(part.mesh.shape)}" if part.mesh is not None
        else f"{jax.device_count()} device(s)"
    )
    print(
        f"TransformerLM: {args.layers}L d{args.d_model} h{args.heads} "
        f"s{args.seq} vocab{args.vocab} = {n_params / 1e6:.1f}M params "
        f"on {mesh_desc}"
    )

    ts = TrainState.create(
        apply_fn=module.apply,
        params=params,
        model_state=mstate,
        tx=optax.adam(args.lr),
    )
    ts = part.shard_state(ts)
    step = part.compile_step(make_train_step(), ts)
    sharding = part.batch_sharding()

    # Fixed periodic corpus: memorizable, so the loss visibly falls.
    base = np.random.default_rng(0).integers(0, args.vocab, 97)
    stream = np.tile(base, -(-args.seq * 4 // len(base)) + 1)
    rng = np.random.default_rng(1)

    def batch():
        starts = rng.integers(0, len(stream) - args.seq - 1, args.batch)
        toks = np.stack([stream[s : s + args.seq] for s in starts])
        nxt = np.stack([stream[s + 1 : s + args.seq + 1] for s in starts])
        return jax.device_put(
            {
                "input": jnp.asarray(toks, jnp.int32),
                "target": jnp.asarray(nxt, jnp.int32),
            },
            sharding,
        )

    t0 = time.perf_counter()
    for i in range(args.steps):
        ts, metrics = step(ts, batch())
        if i == 0:
            jax.block_until_ready(metrics["loss"])
            print(f"first step (compile) {time.perf_counter() - t0:.1f}s")
            t0 = time.perf_counter()
        elif i % 10 == 0 or i == args.steps - 1:
            m = {k: float(v) for k, v in jax.device_get(metrics).items()}
            print(
                f"step {i}: loss={m['loss']:.4f} acc={m['accuracy']:.4f}"
            )
    dt = time.perf_counter() - t0
    tok_s = (args.steps - 1) * args.batch * args.seq / dt if dt > 0 else 0
    print(f"{tok_s / 1e3:.1f}k tokens/s over {args.steps - 1} steps")


if __name__ == "__main__":
    main()
