"""ImageNet-scale binary-network experiments (BASELINE configs #2-#4).

Train the larq-zoo-equivalent binary families data-parallel over a TPU
mesh. With no real ImageNet on this machine the default dataset is
synthetic at ImageNet shapes (swap ``loader.dataset=TFDSDataset
loader.dataset.name=imagenet2012`` where TFDS data is available)::

    # QuickNet, pure data parallel over all chips:
    python examples/imagenet_experiment.py TrainImageNet model=QuickNet

    # Bi-Real-Net-18, 90-epoch cosine recipe:
    python examples/imagenet_experiment.py TrainImageNet model=BiRealNet \\
        epochs=90 optimizer.schedule=WarmupCosine \\
        optimizer.schedule.base_lr=2.5e-3 optimizer.schedule.warmup_steps=1000

    # Multi-host pod (per host):
    python examples/imagenet_experiment.py TrainImageNet \\
        runtime.coordinator_address=<host0>:8476 runtime.num_processes=16 \\
        runtime.process_id=$WORKER_ID batch_size=8192
"""

from zookeeper_tpu import ComponentField, Field, PartialComponent, cli, task
from zookeeper_tpu.data import (
    DataLoader,
    ImageClassificationPreprocessing,
    SyntheticImageNet,
)
from zookeeper_tpu.models import Model, QuickNet, RealToBinaryNet, ResNet50
from zookeeper_tpu.parallel import DataParallelPartitioner, Partitioner
from zookeeper_tpu.training import (
    Adam,
    DistillationExperiment,
    Optimizer,
    TrainingExperiment,
    WarmupCosine,
)

ImageNetPreprocessing = PartialComponent(
    ImageClassificationPreprocessing,
    height=224, width=224, channels=3, augment=True,
    random_resized_crop=True,
)


@task
class TrainImageNet(TrainingExperiment):
    loader: DataLoader = ComponentField(
        DataLoader,
        dataset=SyntheticImageNet,
        preprocessing=ImageNetPreprocessing,
        num_workers=8,
    )
    model: Model = ComponentField(QuickNet, compute_dtype="bfloat16")
    optimizer: Optimizer = ComponentField(
        Adam, schedule=PartialComponent(WarmupCosine, base_lr=1e-2)
    )
    partitioner: Partitioner = ComponentField(DataParallelPartitioner)
    epochs: int = Field(120)
    batch_size: int = Field(256)
    # ImageNet-recipe defaults: smoothed loss, top-1 + top-5 reporting.
    label_smoothing: float = Field(0.1)
    track_top5: bool = Field(True)


@task
class DistillImageNet(DistillationExperiment):
    """The Real-to-Binary staged recipe (Martinez et al. 2020) at
    ImageNet scale: first train (or restore) a full-precision teacher
    with ``TrainImageNet model=ResNet50 export_model_to=...``, then::

        python examples/imagenet_experiment.py DistillImageNet \\
            teacher_checkpoint=<path> alpha=0.4 temperature=2.0
    """

    loader: DataLoader = ComponentField(
        DataLoader,
        dataset=SyntheticImageNet,
        preprocessing=ImageNetPreprocessing,
        num_workers=8,
    )
    model: Model = ComponentField(RealToBinaryNet, compute_dtype="bfloat16")
    teacher: Model = ComponentField(ResNet50, compute_dtype="bfloat16")
    optimizer: Optimizer = ComponentField(
        Adam, schedule=PartialComponent(WarmupCosine, base_lr=2.5e-3)
    )
    partitioner: Partitioner = ComponentField(DataParallelPartitioner)
    epochs: int = Field(75)
    batch_size: int = Field(256)
    track_top5: bool = Field(True)


if __name__ == "__main__":
    cli()
