"""REAL-data experiment, fully offline: scikit-learn's bundled
handwritten-digits corpus (1,797 genuine 8x8 pen-stroke scans).

Every other example falls back to synthetic data without network/TFDS;
this one trains on actual images out of the box — the same data the
repo's accuracy anchors and recipe-efficacy A/Bs use::

    # fp baseline (~95%+ validation accuracy in a few epochs):
    python examples/digits_experiment.py TrainDigits

    # fully binary (ste_sign weights AND activations, Bop optimizer):
    python examples/digits_experiment.py TrainDigits model=BinaryNet \\
        "model.features=(32,32)" "model.dense_units=(64,)" optimizer=Bop

    # the flagship family, upscaled through the resize path:
    python examples/digits_experiment.py TrainDigits model=QuickNet \\
        "model.blocks_per_section=(1,1)" "model.section_features=(16,32)" \\
        loader.preprocessing.height=32 loader.preprocessing.width=32 \\
        loader.preprocessing.resize=True epochs=8

    # few-label / noisy-label research regimes (recipe-efficacy setups):
    python examples/digits_experiment.py TrainDigits \\
        loader.dataset.train_fraction=0.1 \\
        loader.dataset.label_noise_fraction=0.3
"""

from zookeeper_tpu import ComponentField, Field, PartialComponent, cli, task
from zookeeper_tpu.data import (
    DataLoader,
    ImageClassificationPreprocessing,
    SklearnDigits,
)
from zookeeper_tpu.models import Model, SimpleCnn
from zookeeper_tpu.training import TrainingExperiment

DigitsPreprocessing = PartialComponent(
    ImageClassificationPreprocessing, height=8, width=8, channels=1
)


@task
class TrainDigits(TrainingExperiment):
    loader: DataLoader = ComponentField(
        DataLoader,
        dataset=SklearnDigits,
        preprocessing=DigitsPreprocessing,
    )
    model: Model = ComponentField(SimpleCnn)
    epochs: int = Field(5)
    batch_size: int = Field(64)


if __name__ == "__main__":
    cli()
