"""Bench regression gate: diff two BENCH_r*.json artifacts.

The BENCH_r*.json trajectory is the repo's perf memory, but nothing
machine-checked it: a regression only surfaced if a human re-read two
JSON lines side by side. This tool is the gate — ``bench.py --compare
BENCH_rNN.json`` (and the standalone CLI below) diffs the current run
against a previous artifact with per-metric tolerances and exits
nonzero on regression, so a perf loss fails the run that introduced it
instead of being archaeology five rounds later.

Direction-aware comparison: metric names are classified HIGHER-better
(throughputs, MFU, speedups) or LOWER-better (latencies, step/stall
times) by suffix pattern; identity/config/provenance keys (model,
buckets, shas, sources) are compared for drift but never gate. A
metric present on only one side is reported as added/removed — also
non-gating, since bench legs are env-gated and runs legitimately
differ in coverage. Schema-version mismatch downgrades the whole diff
to report-only: renamed keys would read as removed+regressed.

Tolerances: ``DEFAULT_REL_TOL`` (10%) unless the metric has an entry
in ``TOLERANCES`` — deliberately loose for legs measured through
shared-host jitter (recovery walltimes, percentile tails) and absent
for the informational ``obs_*`` fractions whose gate lives in CI.

CLI:

    python tools/bench_diff.py CURRENT.json PREVIOUS.json \\
        [--tol 0.10] [--json OUT.json] [--allow-regression]

Accepts either a raw bench line object or the committed driver wrapper
(``{"parsed": {...}, ...}``); MULTICHIP_r*.json dryrun records carry no
metric line and are out of scope. Exit codes: 0 ok, 3 regression
(unless ``--allow-regression``), 2 unusable input.
"""

import argparse
import json
import re
import sys
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

__all__ = ["BenchDiff", "classify_metric", "compare", "load_bench_json"]

DEFAULT_REL_TOL = 0.10

#: Per-metric relative tolerance overrides (fraction of the PREVIOUS
#: value the metric may move in the BAD direction before gating).
TOLERANCES = {
    # Percentile tails and thread-scheduling-bound legs are noisy on
    # shared hosts; the gate is for real regressions, not weather.
    "serve_p99_ms": 0.30,
    "shed_p99_ms": 0.50,
    "shed_p50_ms": 0.50,
    "recovery_restore_ms": 0.60,
    "recovery_save_wait_ms": 0.60,
    "ckpt_sync_save_stall_ms": 0.50,
    "ckpt_async_save_stall_ms": 1.00,  # ~1ms quantities, scheduler-bound
    "host_aug_python_images_per_sec_per_core": 0.25,
    "host_aug_images_per_sec_per_core": 0.25,
    "host_aug_native_speedup_per_core": 0.25,
    # Decode serving leg (ZK_BENCH_DECODE): tokens/s is a wall-clock
    # ratio over a scheduler loop with host-side bookkeeping — steadier
    # than percentile tails but still thread/GC-exposed; TTFT p99 is a
    # tail of a handful of prefill cohorts and scatters accordingly.
    "serve_decode_tokens_per_sec_per_chip": 0.25,
    "decode_ttft_p99_ms": 0.50,
    "decode_ttft_p50_ms": 0.40,
    "decode_token_p50_ms": 0.40,
    "decode_prefill_p50_ms": 0.40,
    # Paged-decode-kernel era (docs/DESIGN.md §17): the A/B throughputs
    # gate like the headline (same wall-clock jitter class); the
    # speedup is a RATIO of two jittery numbers and scatters more; MBU
    # divides a millisecond-scale dispatch time into cost-analysis
    # bytes, so shared-host scheduling noise passes straight through.
    "decode_kernel_tokens_per_sec_per_chip": 0.30,
    "decode_reference_tokens_per_sec_per_chip": 0.25,
    "decode_kernel_speedup": 0.35,
    "decode_mbu": 0.35,
    # Speculative-decode era (docs/DESIGN.md §18): both throughputs are
    # the decode leg's jitter class; the speedup is a ratio of two
    # jittery wall-clock numbers; acceptance at the pinned zero-tail
    # workload is ~1.0 by construction — a real drop there means the
    # draft/teacher agreement broke, so it gates tightly.
    "spec_tokens_per_sec_per_chip": 0.25,
    "spec_plain_tokens_per_sec_per_chip": 0.25,
    "spec_speedup": 0.35,
    "spec_acceptance_rate": 0.10,
    # Paged-KV prefix-reuse era (docs/DESIGN.md §20): both TTFT medians
    # are single-dispatch prefill wall times on a shared host (the
    # decode TTFT jitter class); the speedup is their ratio and
    # scatters accordingly.
    "prefix_cold_ttft_p50_ms": 0.40,
    "prefix_warm_ttft_p50_ms": 0.40,
    "prefix_ttft_speedup": 0.35,
    # Binary-kernel era (docs/DESIGN.md §21): the A/B throughputs are
    # single-device forward wall clocks (decode-leg jitter class); the
    # speedup is a ratio of two jittery numbers; the int8-anchored MFU
    # divides a per-iter wall time into cost-analysis FLOPs, so host
    # scheduling noise passes straight through.
    "binary_kernel_images_per_sec_per_chip": 0.25,
    "binary_reference_images_per_sec_per_chip": 0.25,
    "binary_kernel_speedup": 0.35,
    "binary_mfu_vs_measured_int8_peak": 0.30,
    # Disaggregated-serving era (docs/DESIGN.md §22): both topologies'
    # throughputs are the decode leg's wall-clock jitter class; the
    # TTFT tails scatter like the single-mesh ones; the per-handoff
    # transfer median is a sub-millisecond device-put + two dispatches
    # on the CPU reference box, so host scheduling noise dominates.
    "disagg_tokens_per_sec_per_chip": 0.25,
    "disagg_baseline_tokens_per_sec_per_chip": 0.25,
    "disagg_ttft_p50_ms": 0.40,
    "disagg_ttft_p99_ms": 0.50,
    "disagg_baseline_ttft_p50_ms": 0.40,
    "disagg_baseline_ttft_p99_ms": 0.50,
    "transfer_ms_p50": 0.50,
    # Fleet-serving era (docs/DESIGN.md §23): both passes' aggregate
    # tokens/s ride worker HTTP round-trips on top of the decode leg's
    # wall-clock jitter; the TTFT medians are worker-side prefill wall
    # times (the §20 jitter class) and the speedup is their ratio; the
    # routing decision is a sub-millisecond host-side walk, so shared-
    # host scheduling noise passes straight through.
    "fleet_tokens_per_sec": 0.30,
    "fleet_rr_tokens_per_sec": 0.30,
    "fleet_warm_ttft_p50_ms": 0.40,
    "fleet_rr_ttft_p50_ms": 0.40,
    "fleet_cold_ttft_p50_ms": 0.40,
    "fleet_affinity_ttft_speedup": 0.35,
    "fleet_route_ms_p50": 0.50,
    # Trace-SLO guardrails era (docs/DESIGN.md §24): goodput is an
    # open-loop wall-clock ratio over a threaded replay (the decode
    # leg's jitter class, plus scheduler-thread scatter); the admitted
    # p99 TTFT is a tail over a burst cohort whose membership itself
    # shifts with admission timing; shed precision divides two small
    # timing-dependent counts, so it scatters the most.
    "trace_goodput_tokens_per_sec": 0.35,
    "trace_admitted_ttft_p99_ms": 0.60,
    "trace_shed_precision": 0.75,
    # Chunked-prefill era (docs/DESIGN.md §25): the ITL p99 is a tail
    # over client-side token-emission gaps under an open-loop replay
    # (the trace era's jitter class); the improvement ratio divides
    # two such tails, so it scatters doubly; TTFT p99 rides the same
    # replay; goodput is a wall-clock ratio over identical token work.
    "chunked_itl_p99_ms": 0.60,
    "chunked_itl_improvement": 0.50,
    "chunked_ttft_p99_ms": 0.60,
    "chunked_goodput_tokens_per_sec": 0.35,
}

#: HIGHER-better metric name patterns (throughput family). MBU joins
#: MFU: both are utilization-of-roofline ratios where down = regressed.
_HIGHER = re.compile(
    r"(_per_sec|_per_sec_per_chip|_per_sec_per_core|_qps|qps_per_chip"
    r"|^value$|^vs_baseline$|^mfu_|^binary_mfu_|_mfu$|_mbu$|_speedup"
    # Acceptance is the one _rate$ where UP is good (the generic _rate$
    # family — shed rate etc. — is lower-better); checked before _LOWER.
    r"|^spec_acceptance_rate$"
    # §24 shed precision: UP means sheds hit the doomed, not the
    # viable — no suffix family matches it, so it is named explicitly.
    r"|^trace_shed_precision$"
    # §25 ITL improvement: baseline-over-chunked tail ratio — UP means
    # chunking relieves more of the long-prefill stall; no suffix
    # family matches it, so it is named explicitly.
    r"|^chunked_itl_improvement$"
    r"|tokens_per_sec|images_per_sec|steps_overlapped)"
)

#: LOWER-better metric name patterns (latency/stall family). The §22
#: per-handoff transfer median spells its unit before the percentile
#: (it is also the serving result line's key), so it is named
#: explicitly rather than widening the suffix family.
_LOWER = re.compile(
    r"(_ms$|_time_ms$|_p50_ms$|_p95_ms$|_p99_ms$|_stall_ms$|_us$"
    r"|_frac$|_rate$|_wait_ms$|^transfer_ms_p50$"
    # §23 routing-decision latency spells its unit before the
    # percentile like the transfer median; named explicitly too.
    r"|^fleet_route_ms_p50$)"
)

#: Never-gating keys: identity, config, provenance. Drift is REPORTED
#: (a changed model or peak source explains a moved number) but a
#: config difference is not a perf regression.
_INFORMATIONAL = re.compile(
    r"(^model$|^metric$|^unit$|_source$|^binary_compute$|^n_chips$"
    r"|^batch_size$|^unroll$|^serve_bucket$|^seq|_seq_len$|_degree$"
    r"|_flavor$|^pack_residuals$|^git_|^jax_version$|^device_kind$"
    r"|^bench_schema_version$|^compiler_options$|^lm_model$"
    r"|^lm_attention$|^lm_batch_size$|^lm_flash_block_|^lm_sp_degree$"
    r"|^host_cores$|^host_aug_native_available$|^shed_requests$"
    r"|^shed_queue_rows$|^sp_batch_size$|^obs_|^ckpt_state_mb$"
    r"|^recovery_restarts$|^sp_seq_len$"
    # Decode-leg workload shape: request count, slot count, budgets and
    # the refill/token tallies they determine are config, not perf.
    r"|^decode_requests$|^decode_slots$|^decode_new_tokens$"
    r"|^decode_refills$|^decode_generated_tokens$"
    # Speculative-leg workload shape (k, model depths, traffic counts).
    r"|^spec_k$|^spec_teacher_layers$|^spec_draft_layers$"
    r"|^spec_requests$|^spec_slots$|^spec_new_tokens$"
    # Prefix-reuse-leg workload shape + cache-effectiveness context:
    # hit rate and CoW count are DETERMINED by the synthetic workload
    # (every request shares one prefix), and pool fill is a capacity
    # statement, not a speed — none of them is a perf direction.
    r"|^prefix_requests$|^prefix_shared_tokens$|^prefix_tail_tokens$"
    r"|^prefix_hit_rate$|^prefix_cow_pages$|^kv_pool_fill$"
    # Binary-kernel-leg workload shape (model, batch, image side).
    r"|^binary_model$|^binary_batch$|^binary_image$"
    # Disaggregated-serving-leg workload shape + transfer volume: role
    # sizes and budgets are config; handoff/page/byte/bounce tallies
    # are DETERMINED by the workload (requests x pages-per-prompt),
    # not a speed.
    r"|^disagg_requests$|^disagg_slots$|^disagg_lanes$"
    r"|^disagg_new_tokens$|^disagg_transfer_handoffs$"
    r"|^disagg_transfer_pages$|^disagg_transfer_bytes$"
    r"|^disagg_host_bounces$|^disagg_generated_tokens$"
    # Fleet-serving-leg workload shape + affinity context: replica/
    # session/turn counts and token budgets are config; the hit rate
    # is DETERMINED by the synthetic workload (the bench RAISES when
    # any turn-2+ request lands cold, so 1.0 by construction) — none
    # of them is a perf direction.
    r"|^fleet_replicas$|^fleet_sessions$|^fleet_turns$"
    r"|^fleet_shared_tokens$|^fleet_tail_tokens$|^fleet_new_tokens$"
    r"|^fleet_affinity_hit_rate$|^fleet_generated_tokens$"
    # Trace-SLO-leg baseline + workload shape: the guardrails-OFF pass
    # exists to contextualize the gated guardrails-on numbers (its
    # whole point is to be worse under overload), and request/outcome
    # tallies are determined by the pinned trace — none is a perf
    # direction of the code under test.
    r"|^trace_baseline_|^trace_requests$|^trace_deadline_ms$"
    r"|^trace_shed_total$|^trace_ok_total$|^trace_deadline_expired$"
    # Chunked-prefill-leg baseline + workload shape: the monolithic
    # pass exists to contextualize the gated chunked numbers (its
    # whole point is to stall), and chunk/prompt/request tallies are
    # pinned workload config — none is a perf direction of the code
    # under test.
    r"|^chunked_baseline_|^chunked_chunk_tokens$|^chunked_long_"
    r"|^chunked_requests$|^chunked_generated_tokens$"
    # Peak ANCHORS and model FLOP counts are measurement context, not
    # code performance: an anchor that moved (re-measured peak, fixed
    # cache pathology — BENCH_r04's 237.9 TF/s) or a FLOPs change (a
    # model edit) EXPLAINS the gated numbers and must not gate itself.
    r"|_peak_tflops$|_peak_tops$|_step_tflops$)"
)


def classify_metric(name: str) -> Optional[str]:
    """"higher" / "lower" / None (non-gating). Informational wins:
    config ints often end in suffixes the direction patterns match."""
    if _INFORMATIONAL.search(name):
        return None
    if _HIGHER.search(name):
        return "higher"
    if _LOWER.search(name):
        return "lower"
    return None


@dataclass
class BenchDiff:
    rows: List[Dict[str, Any]] = field(default_factory=list)
    regressions: List[Dict[str, Any]] = field(default_factory=list)
    improvements: List[Dict[str, Any]] = field(default_factory=list)
    drift: List[Dict[str, Any]] = field(default_factory=list)
    added: List[str] = field(default_factory=list)
    removed: List[str] = field(default_factory=list)
    schema_mismatch: bool = False

    @property
    def ok(self) -> bool:
        return not self.regressions

    def as_dict(self) -> Dict[str, Any]:
        return {
            "ok": self.ok,
            "schema_mismatch": self.schema_mismatch,
            "regressions": self.regressions,
            "improvements": self.improvements,
            "drift": self.drift,
            "added": self.added,
            "removed": self.removed,
            "rows": self.rows,
        }

    def report(self) -> str:
        lines = []
        if self.schema_mismatch:
            lines.append(
                "! bench_schema_version differs: diff is REPORT-ONLY "
                "(renamed keys would read as regressions)"
            )
        for row in self.regressions:
            lines.append(
                "REGRESSION {name}: {prev:g} -> {cur:g} "
                "({delta:+.1%}, tol {tol:.0%}, {direction}-is-better)".format(
                    **row
                )
            )
        for row in self.improvements:
            lines.append(
                "improved   {name}: {prev:g} -> {cur:g} ({delta:+.1%})".format(
                    **row
                )
            )
        for row in self.drift:
            lines.append(
                f"drift      {row['name']}: {row['prev']!r} -> "
                f"{row['cur']!r} (informational)"
            )
        if self.added:
            lines.append(f"added      {', '.join(sorted(self.added))}")
        if self.removed:
            lines.append(f"removed    {', '.join(sorted(self.removed))}")
        if not lines:
            lines.append("no differences beyond tolerance")
        return "\n".join(lines)


def load_bench_json(path: str) -> Dict[str, Any]:
    """Load a bench artifact: a raw ``{"metric": ...}`` line object or
    the committed driver wrapper (``{"parsed": {...}}``)."""
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: expected a JSON object, got {type(doc)}")
    if "parsed" in doc and isinstance(doc["parsed"], dict):
        doc = doc["parsed"]
    if "metric" not in doc and "value" not in doc:
        raise ValueError(
            f"{path}: neither a bench line (metric/value keys) nor a "
            "driver wrapper with one under 'parsed'"
        )
    return doc


def compare(
    current: Dict[str, Any],
    previous: Dict[str, Any],
    *,
    rel_tol: float = DEFAULT_REL_TOL,
    tolerances: Optional[Dict[str, float]] = None,
) -> BenchDiff:
    """Diff two bench line objects. Gating only applies to metrics
    present on BOTH sides with a known direction; see module docstring
    for the classification and schema rules."""
    tol_table = dict(TOLERANCES)
    tol_table.update(tolerances or {})
    diff = BenchDiff()
    diff.schema_mismatch = current.get("bench_schema_version") != previous.get(
        "bench_schema_version"
    )
    cur_keys, prev_keys = set(current), set(previous)
    diff.added = sorted(cur_keys - prev_keys)
    diff.removed = sorted(prev_keys - cur_keys)
    for name in sorted(cur_keys & prev_keys):
        cur, prev = current[name], previous[name]
        direction = (
            classify_metric(name)
            if isinstance(cur, (int, float))
            and isinstance(prev, (int, float))
            and not isinstance(cur, bool)
            and not isinstance(prev, bool)
            else None
        )
        if direction is None:
            if cur != prev:
                diff.drift.append({"name": name, "prev": prev, "cur": cur})
            continue
        if prev == 0 or cur < 0 or prev < 0:
            # prev == 0: no relative scale. Negative: the repo-wide -1
            # "unknown" sentinel (MFU without cost analysis, HBM
            # without memory_stats) — a measurement gap on either
            # side, not a perf move. Both report as drift only.
            if cur != prev:
                diff.drift.append({"name": name, "prev": prev, "cur": cur})
            continue
        delta = (cur - prev) / abs(prev)
        tol = tol_table.get(name, rel_tol)
        row = {
            "name": name,
            "prev": prev,
            "cur": cur,
            "delta": delta,
            "tol": tol,
            "direction": direction,
        }
        diff.rows.append(row)
        bad = delta < -tol if direction == "higher" else delta > tol
        good = delta > tol if direction == "higher" else delta < -tol
        if bad and not diff.schema_mismatch:
            diff.regressions.append(row)
        elif good:
            diff.improvements.append(row)
    return diff


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("current", help="current bench JSON")
    parser.add_argument("previous", help="previous bench JSON to gate on")
    parser.add_argument(
        "--tol", type=float, default=DEFAULT_REL_TOL,
        help="default relative tolerance (fraction, e.g. 0.10)",
    )
    parser.add_argument(
        "--json", dest="json_out", default=None,
        help="also write the full diff as JSON here (CI artifact)",
    )
    parser.add_argument(
        "--allow-regression", action="store_true",
        help="report regressions but exit 0 (trajectory-report mode)",
    )
    args = parser.parse_args(argv)
    try:
        current = load_bench_json(args.current)
        previous = load_bench_json(args.previous)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"bench_diff: {e}", file=sys.stderr)
        return 2
    diff = compare(current, previous, rel_tol=args.tol)
    print(diff.report())
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(diff.as_dict(), f, indent=1)
    if not diff.ok and not args.allow_regression:
        return 3
    return 0


if __name__ == "__main__":
    sys.exit(main())
