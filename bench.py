"""Benchmark: training throughput on the flagship model, real hardware.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no numbers (BASELINE.md); ``vs_baseline`` is the
ratio of measured images/sec/chip to BASELINE.md's working target for this
stage (see TARGET below), so >1.0 means ahead of target.
"""

import json
import time


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from zookeeper_tpu.core import configure
    from zookeeper_tpu.models import SimpleCnn
    from zookeeper_tpu.training import TrainState, make_train_step

    # CIFAR-shape training step on the end-to-end slice model. Will move to
    # QuickNet ImageNet shapes once the binary zoo + Pallas kernels land.
    input_shape = (32, 32, 3)
    batch_size = 512
    num_classes = 10
    TARGET = 20_000.0  # images/sec/chip working target for this stage.

    model = SimpleCnn()
    configure(
        model,
        {
            "features": (64, 128, 256),
            "dense_units": (256,),
            "compute_dtype": "bfloat16",
        },
        name="model",
    )
    module = model.build(input_shape, num_classes=num_classes)
    params, model_state = model.initialize(module, input_shape)
    state = TrainState.create(
        apply_fn=module.apply,
        params=params,
        model_state=model_state,
        tx=optax.adam(1e-3),
    )

    # Use every local chip (data-parallel): throughput/chip is then honest
    # on multi-chip hosts instead of dividing one chip's work by N.
    from zookeeper_tpu.parallel import DataParallelPartitioner

    partitioner = DataParallelPartitioner()
    configure(partitioner, {}, name="partitioner")
    partitioner.setup()
    state = partitioner.shard_state(state)
    step = partitioner.compile_step(make_train_step(), state)
    batch_sharding = partitioner.batch_sharding()

    rng = np.random.default_rng(0)
    batch = jax.device_put(
        {
            "input": jnp.asarray(
                rng.normal(size=(batch_size, *input_shape)), jnp.bfloat16
            ),
            "target": jnp.asarray(rng.integers(0, num_classes, batch_size)),
        },
        batch_sharding,
    )

    def run_chain(n, st):
        """n chained steps ended by a scalar host readback (device_get is
        the only reliable completion barrier through the remote-TPU
        tunnel; block_until_ready returns early there)."""
        t0 = time.perf_counter()
        for _ in range(n):
            st, metrics = step(st, batch)
        float(jax.device_get(metrics["loss"]))
        return time.perf_counter() - t0, st

    # Compile + warmup.
    _, state = run_chain(2, state)

    # The tunnel adds ~100ms fixed sync latency per readback; measure
    # marginal step time with two chain lengths and subtract.
    n1, n2 = 10, 60
    t1, state = run_chain(n1, state)
    t2, state = run_chain(n2, state)
    dt = max(t2 - t1, 1e-9)

    n_chips = jax.device_count()
    images_per_sec_per_chip = (n2 - n1) * batch_size / dt / max(1, n_chips)
    print(
        json.dumps(
            {
                "metric": "train_images_per_sec_per_chip",
                "value": round(images_per_sec_per_chip, 1),
                "unit": "images/sec/chip",
                "vs_baseline": round(images_per_sec_per_chip / TARGET, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
